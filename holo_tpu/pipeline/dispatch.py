"""Double-buffered async dispatch pipeline (ISSUE 9 tentpole, part a).

Protocol actors used to block synchronously on every SPF/FRR marshal →
device-execute → readback round trip.  This module puts a bounded
dispatch queue and one pipeline worker between the actors and the
device, in the spirit of DeltaPath's dataflow pipelining
(arXiv:1808.06893):

- actors **enqueue** work (:meth:`DispatchPipeline.submit`) and get a
  ticket back immediately; :class:`LazySpfResult` defers the block to
  the first *use* of the result, so the host work between the dispatch
  call and the first consumption (LSDB walks, route bookkeeping)
  overlaps the device execution for free;
- the worker runs the split-phase backend API
  (``TpuSpfBackend.launch_* / finish_*``): while dispatch *i* executes
  on the device, dispatch *i+1*'s host marshal proceeds — depth-bounded
  double buffering (``depth=2`` default), with the finish (device sync
  + readback) of the oldest in-flight entry interleaved;
- **ordering** is strict per ``(instance topology uid, root)`` key:
  results complete in submission order for a key, and at most ONE entry
  per key is ever in flight — the *ownership handoff* the DeltaPath
  donation contract requires (an in-flight dispatch's donated previous
  tensors / resident graph buffers must never be consumed by a queued
  delta for the same chain; the next entry launches only after the
  previous one's ``finish`` has re-deposited the retained tensors);
- superseded **what-if batches coalesce**: a queued advisory batch for
  the same key is dropped (ticket marked superseded) when a batch for a
  newer topology generation arrives, and a resubmission of the same
  generation shares the queued ticket instead of duplicating work;
- **breaker awareness**: while a dispatch breaker is OPEN, advisory
  what-if batches are skipped at the submit seam — previously each one
  paid the full scalar re-run just to produce advisory output nobody
  was owed.

Chaos seam: the async dispatch closures run
``faults.crashpoint("pipeline.dispatch")`` inside the breaker guard, so
a seeded plan can fail pipelined dispatches mid-storm and the scalar
fallback must keep FIBs bit-identical (tests/test_pipeline.py).

Everything lands in the ``holo_pipeline_*`` metric family: queue depth,
in-flight count, per-kind dispatch counters, coalesced/skipped tallies,
caller wait time, and the measured overlap ratio (device-in-flight
seconds that ran while the worker was free to do other host work).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from contextlib import nullcontext

from holo_tpu import telemetry
from holo_tpu.analysis.runtime import consumes_donated
from holo_tpu.telemetry import convergence, critpath

log = logging.getLogger("holo_tpu.pipeline")

_QUEUE_DEPTH = telemetry.gauge(
    "holo_pipeline_queue_depth",
    "Entries waiting in the dispatch pipeline queue",
)
_INFLIGHT = telemetry.gauge(
    "holo_pipeline_inflight",
    "Launched-but-unfinished pipeline entries (device in flight)",
)
_DISPATCHES = telemetry.counter(
    "holo_pipeline_dispatch_total",
    "Pipeline entries completed, by dispatch kind",
    ("kind",),
)
_COALESCED = telemetry.counter(
    "holo_pipeline_coalesced_total",
    "Queued what-if batches coalesced (shared or superseded)",
    ("reason",),
)
_BREAKER_SKIPS = telemetry.counter(
    "holo_pipeline_breaker_skip_total",
    "Advisory batches skipped at submit because the circuit was open",
)
_WAIT_SECONDS = telemetry.histogram(
    "holo_pipeline_wait_seconds",
    "Caller-side wait from result force to completion",
    ("kind",),
)
_OVERLAP_RATIO = telemetry.gauge(
    "holo_pipeline_overlap_ratio",
    "Fraction of device-in-flight time overlapped with other host work",
)


class PipelineClosed(RuntimeError):
    """Submit against a closed pipeline."""


class PipelineTicket:
    """Completion handle for one submitted dispatch."""

    __slots__ = (
        "key", "kind", "generation", "_event", "_value", "_exc",
        "skipped", "superseded", "_pipeline", "_cbs", "_cb_lock", "eids",
    )

    def __init__(self, pipeline, key, kind: str, generation: int):
        self.key = key
        self.kind = kind
        self.generation = generation
        self._pipeline = pipeline
        self._event = threading.Event()
        self._value = None
        self._exc: BaseException | None = None
        self.skipped = False  # breaker-open skip: never executed
        self.superseded = False  # coalesced away by a newer generation
        self._cbs: list = []
        self._cb_lock = threading.Lock()
        # Causal convergence ids captured at submit (the critical-path
        # ledger's cross-thread join key for the force-wait stamps).
        self.eids: tuple = ()

    def add_done_callback(self, fn) -> None:
        """Run ``fn(ticket)`` at completion (immediately when already
        done).  Callbacks fire on the COMPLETING thread — the pipeline
        worker for queued work — so receivers must hop back onto their
        own actor loop before touching instance state (the deferred
        FRR-attach seam posts itself a loop message).  Callback
        exceptions are swallowed: a consumer bug must not poison the
        worker or the other callbacks."""
        with self._cb_lock:
            if not self._event.is_set():
                self._cbs.append(fn)
                return
        self._run_cb(fn)

    def _run_cb(self, fn) -> None:
        try:
            fn(self)
        except Exception:  # noqa: BLE001 — see add_done_callback
            log.exception("pipeline ticket done-callback failed")

    def _fire_cbs(self) -> None:
        with self._cb_lock:
            cbs, self._cbs = self._cbs, []
        for fn in cbs:
            self._run_cb(fn)

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None):
        """Block until completion; re-raises a passthrough exception on
        the caller's thread (same contract as the synchronous dispatch).
        Skipped/superseded tickets return None."""
        if not self._event.is_set():
            critpath.note_force(self.eids, "b")
            t0 = time.perf_counter()
            if not self._event.wait(timeout):
                raise TimeoutError(
                    f"pipeline result for {self.key}/{self.kind} not ready"
                )
            # Span exemplar (ISSUE 17 satellite): a p99 force-wait is
            # joinable back to its flight-recorder timeline exactly like
            # holo_profile_stage_seconds buckets — the caller's active
            # span when one exists, the causal event id otherwise.
            sid = telemetry.current_span_id()
            exemplar = (
                {"span_id": sid}
                if sid is not None
                else ({"event_id": self.eids[0]} if self.eids else None)
            )
            _WAIT_SECONDS.labels(kind=self.kind).observe(
                time.perf_counter() - t0, exemplar=exemplar
            )
            critpath.note_force(self.eids, "e")
        if self._exc is not None:
            raise self._exc
        return self._value

    # pipeline-side completion
    def _complete(self, value) -> None:
        self._value = value
        self._event.set()
        self._fire_cbs()

    def _fail(self, exc: BaseException) -> None:
        self._exc = exc
        self._event.set()
        self._fire_cbs()

    def _skip(self, superseded: bool = False) -> None:
        if superseded:
            self.superseded = True
        else:
            self.skipped = True
        self._event.set()
        self._fire_cbs()


class _Item:
    """One queued dispatch."""

    __slots__ = (
        "key", "kind", "generation", "ticket", "run", "launch", "finish",
        "coalesce", "eids", "handle", "t_launch_end", "stalled",
    )

    def __init__(
        self, ticket, run=None, launch=None, finish=None,
        coalesce=False, eids=(),
    ):
        self.ticket = ticket
        self.key = ticket.key
        self.kind = ticket.kind
        self.generation = ticket.generation
        self.run = run
        self.launch = launch
        self.finish = finish
        self.coalesce = coalesce
        self.eids = tuple(eids)
        self.handle = None
        self.t_launch_end = 0.0
        # Per-key ordering-stall latch: stamped into the critical-path
        # waterfall on the FIRST skip only (worker rescans are routine).
        self.stalled = False


class DispatchPipeline:
    """Bounded dispatch queue + one pipeline worker thread.

    ``depth`` bounds the launched-but-unfinished entries (2 = classic
    double buffering); ``capacity`` bounds the queue — a full queue
    backpressures the submitting actor (bounded means bounded).
    ``guard`` is an optional zero-arg callable returning a context
    manager entered around every worker-side phase: tests pass
    ``holo_tpu.testing.no_implicit_transfers`` so the pipelined path
    runs under the same transfer sanitizer as the synchronous suites.
    """

    def __init__(
        self,
        depth: int = 2,
        capacity: int = 32,
        name: str = "pipeline",
        guard=None,
    ):
        self.depth = max(int(depth), 1)
        self.capacity = max(int(capacity), 1)
        self.name = name
        self.guard = guard
        self._cv = threading.Condition()
        self._queue: deque[_Item] = deque()
        self._inflight: list[_Item] = []
        self._inflight_keys: set = set()
        # Items the worker popped but has not yet parked in _inflight /
        # finalized — without this, drain() would report empty while a
        # launch (or a whole single-phase run) is still executing.
        self._working = 0
        self._closed = False
        self._thread: threading.Thread | None = None
        # stats (mutated under _cv or worker-only)
        self._submitted = 0
        self._completed = 0
        self._coalesced = 0
        self._skipped = 0
        self._launch_seconds = 0.0
        self._finish_seconds = 0.0
        self._overlap_seconds = 0.0
        self._max_inflight_per_key = 0  # invariant probe (tests): <= 1
        _QUEUE_DEPTH.set_fn(lambda: float(len(self._queue)))
        _INFLIGHT.set_fn(lambda: float(len(self._inflight)))

    # -- submit side ----------------------------------------------------

    def submit(
        self,
        key,
        kind: str,
        run=None,
        launch=None,
        finish=None,
        generation: int = 0,
        coalesce: bool = False,
        skip_when_open=None,
    ) -> PipelineTicket:
        """Enqueue one dispatch and return its ticket.

        Exactly one of ``run`` (single-phase: the worker executes it
        whole) or the ``launch``/``finish`` pair (split-phase: overlap
        eligible) must be given.  ``coalesce=True`` marks an advisory
        what-if batch: same-(key, generation) resubmissions share the
        queued ticket, a newer generation supersedes a queued older
        one, and ``skip_when_open`` (a CircuitBreaker) short-circuits
        the submit entirely while the circuit is open."""
        if (run is None) == (launch is None or finish is None):
            raise ValueError("pass run=... OR launch=.../finish=...")
        ticket = PipelineTicket(self, key, kind, int(generation))
        if skip_when_open is not None and skip_when_open.state == "open":
            # The breaker is already serving FIB-feeding dispatches from
            # the oracle; an advisory batch is not owed a scalar re-run.
            ticket._skip()
            self._skipped += 1
            _BREAKER_SKIPS.inc()
            return ticket
        item = _Item(
            ticket, run=run, launch=launch, finish=finish,
            coalesce=coalesce, eids=convergence.current(),
        )
        ticket.eids = item.eids
        with self._cv:
            if self._closed:
                raise PipelineClosed(self.name)
            if coalesce:
                for old in list(self._queue):
                    if not (
                        old.coalesce
                        and old.key == key
                        and old.kind == kind
                    ):
                        continue
                    if old.generation == item.generation:
                        # Identical work already queued: share it — the
                        # new submit's causal events ride the queued
                        # item from here on (their queue-wait started
                        # now, at THIS admission).
                        if item.eids:
                            old.eids = tuple(
                                dict.fromkeys(old.eids + item.eids)
                            )
                            old.ticket.eids = old.eids
                            critpath.note_enqueue(item.eids)
                        self._coalesced += 1
                        _COALESCED.labels(reason="shared").inc()
                        return old.ticket
                    if old.generation < item.generation:
                        # Stale batch nobody needs anymore.
                        self._queue.remove(old)
                        old.ticket._skip(superseded=True)
                        self._coalesced += 1
                        _COALESCED.labels(reason="superseded").inc()
            while len(self._queue) >= self.capacity and not self._closed:
                self._cv.wait(0.5)
            if self._closed:
                raise PipelineClosed(self.name)
            self._queue.append(item)
            self._submitted += 1
            self._ensure_worker_locked()
            self._cv.notify_all()
        critpath.note_enqueue(item.eids)
        return ticket

    def _ensure_worker_locked(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._worker, name=f"holo-pipeline-{self.name}",
                daemon=True,
            )
            self._thread.start()

    # -- worker side ----------------------------------------------------

    def _next_launchable_locked(self, stalled: list) -> _Item | None:
        """Oldest queued item whose key is not in flight (per-key
        ownership handoff: never two launches for one key).  Items
        skipped because their key IS in flight are collected into
        ``stalled`` on their first skip only (``_Item.stalled`` latch)
        — the per-key ordering-stall stamp of the critical-path ledger."""
        for item in self._queue:
            if item.key not in self._inflight_keys:
                self._queue.remove(item)
                return item
            if not item.stalled:
                item.stalled = True
                stalled.append(item)
        return None

    def _worker(self) -> None:
        while True:
            launch_item = None
            finish_item = None
            stalled: list = []
            with self._cv:
                if (
                    self._closed
                    and not self._queue
                    and not self._inflight
                ):
                    self._cv.notify_all()
                    return
                launch_item = (
                    self._next_launchable_locked(stalled)
                    if len(self._inflight) < self.depth
                    else None
                )
                if launch_item is None:
                    if self._inflight:
                        finish_item = self._inflight.pop(0)
                        self._working += 1
                    else:
                        self._cv.wait(0.5)
                        continue
                else:
                    self._working += 1
            # Stall stamps run OUTSIDE the cv lock (ISSUE 17 contract:
            # no new work under the queue lock on the dispatch thread).
            for it in stalled:
                critpath.note_stall(it.eids)
            if launch_item is not None:
                self._do_launch(launch_item)
                continue
            self._do_finish(finish_item)

    def _ctx(self, item: _Item):
        g = self.guard() if self.guard is not None else nullcontext()
        return g, convergence.activation(item.eids)

    def _do_launch(self, item: _Item) -> None:
        critpath.note_launch(item.eids, "b")
        t0 = time.perf_counter()
        try:
            guard, act = self._ctx(item)
            with guard, act:
                if item.run is not None:
                    item.ticket._complete(item.run())
                    critpath.note_finish(item.eids, "e")
                    self._finalize(item, finished=True)
                    return
                item.handle = item.launch()
        except BaseException as exc:  # noqa: BLE001 — marshaled to the
            # caller's thread by ticket.result(); the worker survives.
            item.ticket._fail(exc)
            self._finalize(item, finished=True)
            return
        finally:
            self._launch_seconds += time.perf_counter() - t0
        critpath.note_launch(item.eids, "e")
        item.t_launch_end = time.perf_counter()
        with self._cv:
            self._inflight.append(item)
            self._inflight_keys.add(item.key)
            self._working -= 1
            per_key = sum(
                1 for i in self._inflight if i.key == item.key
            )
            self._max_inflight_per_key = max(
                self._max_inflight_per_key, per_key
            )
            self._cv.notify_all()

    def _do_finish(self, item: _Item) -> None:
        critpath.note_finish(item.eids, "b")
        t_fs = time.perf_counter()
        # Device time that elapsed while the worker was busy elsewhere
        # (launching the next entry / idle-waiting): the overlap the
        # double buffer exists to create.
        self._overlap_seconds += max(t_fs - item.t_launch_end, 0.0)
        try:
            guard, act = self._ctx(item)
            # The pipeline's per-key ownership handoff: finish()
            # re-deposits the fresh tensors that replace the donated
            # previous set, and only then may a queued delta of the
            # same chain launch (submit() serializes on the key).
            # consumes_donated is the HL109 seam vocabulary — the
            # runtime guard counts the window so tests can pin that
            # the handoff actually ran under the async path.
            with guard, act, consumes_donated("pipeline.key.handoff"):
                item.ticket._complete(item.finish(item.handle))
            critpath.note_finish(item.eids, "e")
        except BaseException as exc:  # noqa: BLE001 — see _do_launch
            item.ticket._fail(exc)
        finally:
            self._finish_seconds += time.perf_counter() - t_fs
            self._finalize(item, finished=False)

    def _finalize(self, item: _Item, finished: bool) -> None:
        with self._cv:
            self._inflight_keys.discard(item.key)
            self._working -= 1
            self._completed += 1
            self._cv.notify_all()
        _DISPATCHES.labels(kind=item.kind).inc()
        denom = self._overlap_seconds + self._finish_seconds
        if denom > 0:
            _OVERLAP_RATIO.set(self._overlap_seconds / denom)

    # -- lifecycle ------------------------------------------------------

    def drain(self, timeout: float | None = None) -> bool:
        """Block until queue + in-flight are empty (True on success)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._queue or self._inflight_keys or self._working:
                wait = 0.5
                if deadline is not None:
                    wait = deadline - time.monotonic()
                    if wait <= 0:
                        return False
                self._cv.wait(min(wait, 0.5))
        return True

    def close(self, timeout: float = 10.0) -> None:
        """Refuse new submits, drain, stop the worker."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)
        # Detach the sampled gauges: a set_fn closure over self would
        # otherwise pin this closed pipeline forever and keep scraping
        # its dead queue.  Safe ordering with configure_process_pipeline
        # (old closed BEFORE the replacement's __init__ re-points them).
        _QUEUE_DEPTH.set_fn(None)
        _QUEUE_DEPTH.set(0.0)
        _INFLIGHT.set_fn(None)
        _INFLIGHT.set(0.0)

    @property
    def closed(self) -> bool:
        return self._closed

    def stats(self) -> dict:
        with self._cv:
            denom = self._overlap_seconds + self._finish_seconds
            return {
                "depth": self.depth,
                "capacity": self.capacity,
                "queued": len(self._queue),
                "inflight": len(self._inflight),
                "submitted": self._submitted,
                "completed": self._completed,
                "coalesced": self._coalesced,
                "breaker-skipped": self._skipped,
                "launch-seconds": round(self._launch_seconds, 6),
                "finish-seconds": round(self._finish_seconds, 6),
                "overlap-seconds": round(self._overlap_seconds, 6),
                "overlap-ratio": round(
                    self._overlap_seconds / denom, 4
                ) if denom > 0 else 0.0,
                "max-inflight-per-key": self._max_inflight_per_key,
            }


# -- lazy results -------------------------------------------------------


class LazySpfResult:
    """Duck-typed :class:`holo_tpu.spf.backend.SpfResult`: attribute
    access forces the pipeline ticket.  The protocol layer reads
    ``dist``/``parent``/``hops``/``nexthop_words`` — each blocks until
    the worker completed the dispatch, which by then has usually
    overlapped the caller's own host work."""

    __slots__ = ("_ticket",)

    _FIELDS = (
        "dist", "parent", "hops", "nexthop_words",
        "parents", "pdist", "pweight", "npaths", "nh_weights",
    )

    def __init__(self, ticket: PipelineTicket):
        self._ticket = ticket

    def _force(self):
        res = self._ticket.result()
        if res is None:
            raise RuntimeError(
                f"pipelined SPF dispatch for {self._ticket.key} was "
                f"{'skipped' if self._ticket.skipped else 'superseded'}"
            )
        return res

    def __getattr__(self, name):
        if name in self._FIELDS:
            return getattr(self._force(), name)
        raise AttributeError(name)

    def wait(self):
        """Explicit force (returns the real SpfResult)."""
        return self._force()


class LazyBackupTable:
    """Duck-typed :class:`holo_tpu.frr.kernel.BackupTable`: any
    attribute access forces the FRR pipeline ticket — the protocol
    layer stores the table at SPF time but only consumes it when a
    repair is resolved (BFD/carrier flip), so the FRR dispatch rides
    the pipeline for free."""

    __slots__ = ("_ticket",)

    def __init__(self, ticket: PipelineTicket):
        self._ticket = ticket

    def _force(self):
        res = self._ticket.result()
        if res is None:
            raise RuntimeError(
                f"pipelined FRR dispatch for {self._ticket.key} skipped"
            )
        return res

    def pending(self) -> bool:
        """True while the dispatch is still in flight — the protocol's
        defer-the-force probe (ISSUE 10: the SPF path must not pay the
        FRR force; it re-attaches from a worker done-callback)."""
        return not self._ticket.done()

    def on_done(self, fn) -> None:
        """Completion hook (fires on the pipeline worker thread)."""
        self._ticket.add_done_callback(fn)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._force(), name)

    def wait(self):
        return self._force()


# -- async backend facades ---------------------------------------------

#: exception types the breaker never masks (bugs, not device failures);
#: mirrored from resilience.breaker so the split-phase closures agree.
def _passthrough():
    from holo_tpu.resilience.breaker import _PASSTHROUGH

    return _PASSTHROUGH


def _guarded_launch(breaker, context: str, launch_fn) -> tuple:
    """Phase 1 of a split breaker-guarded dispatch — ONE implementation
    shared by the SPF and FRR facades so the breaker contract (admit →
    chaos seam → passthrough abort → failure) cannot drift between
    them.  Returns the ``(verdict, guard, handle)`` state
    :func:`_guarded_finish` completes."""
    from holo_tpu.resilience import faults

    guard = breaker.split(context)
    if not guard.admitted:
        return ("fallback", guard, None)
    try:
        faults.crashpoint("pipeline.dispatch")
        return ("ok", guard, launch_fn())
    except _passthrough():
        guard.abort()
        raise
    except Exception as exc:  # noqa: BLE001 — breaker contract
        guard.failure(exc)
        return ("fallback", guard, None)


def _guarded_finish(state: tuple, finish_fn, fallback_fn):
    """Phase 2: complete the device dispatch or serve the bit-identical
    fallback; success records the whole launch→finish deadline span."""
    verdict, guard, handle = state
    if verdict == "fallback":
        return fallback_fn()
    try:
        res = finish_fn(handle)
    except _passthrough():
        guard.abort()
        raise
    except Exception as exc:  # noqa: BLE001 — breaker contract
        guard.failure(exc)
        return fallback_fn()
    guard.success()
    return res


class AsyncSpfBackend:
    """``SpfBackend`` facade routing dispatches through a pipeline.

    ``compute`` enqueues a split-phase (launch/finish) dispatch and
    returns a :class:`LazySpfResult`; the synchronous breaker contract
    is preserved phase by phase via ``CircuitBreaker.split`` — an XLA
    failure in either phase re-runs on the scalar oracle
    (bit-identical), repeated failures open the circuit, and
    passthrough exceptions surface on the caller's thread at force
    time.  ``compute_whatif_async`` adds the advisory-batch semantics
    (coalescing + breaker-open skip); the plain ``compute_whatif`` /
    ``compute_multiroot`` stay synchronous delegates — their callers
    (CLI, bench) want blocking results.
    """

    #: retained chain-root entries (one live dispatch chain per entry)
    CHAIN_CAPACITY = 512

    def __init__(self, inner, pipeline: DispatchPipeline):
        self.inner = inner
        self.pipeline = pipeline
        # Topology uid -> chain-root uid.  Every SPF run marshals a
        # FRESH Topology object (new uid), so the ordering/ownership
        # unit is the DELTA CHAIN: a topology carrying ``delta_base``
        # lineage joins its base's chain, everything else roots a new
        # one.  This is what makes "(instance, root)" concrete at the
        # backend layer — one instance area advances one chain.
        self._chains: dict = {}

    @property
    def name(self) -> str:
        return f"{self.inner.name}-async"

    def __getattr__(self, attr):
        # breaker / incremental / engine / prepare / oracle ... all
        # delegate: the facade adds scheduling, not behavior.
        return getattr(self.inner, attr)

    # -- keys ----------------------------------------------------------

    def _key(self, topo) -> tuple:
        """The strict-ordering / ownership-handoff unit: (delta-chain
        root uid, root vertex).  Consecutive generations of one
        instance area MUST serialize — an in-flight dispatch's donated
        previous tensors / resident graph buffers must never be
        consumed by a queued delta of the same chain — while unrelated
        areas/instances overlap freely."""
        uid = topo.cache_key[0]
        delta = getattr(topo, "delta_base", None)
        if delta is not None:
            base_uid = delta.base_key[0]
            chain = self._chains.get(base_uid, base_uid)
        else:
            chain = self._chains.get(uid, uid)
        self._chains[uid] = chain
        while len(self._chains) > self.CHAIN_CAPACITY:
            self._chains.pop(next(iter(self._chains)))
        return (chain, int(topo.root))

    # -- SpfBackend interface ------------------------------------------

    def compute(self, topo, edge_mask=None, multipath_k: int = 1):
        inner = self.inner
        pipe = self.pipeline
        if pipe is None or pipe.closed:
            return inner.compute(topo, edge_mask, multipath_k=multipath_k)
        if inner.breaker.state == "open":
            # Degraded mode runs on the CALLER's thread, exactly like
            # the unpipelined breaker: N threaded instances' scalar
            # fallbacks must not serialize behind the one pipeline
            # worker while the device is down.  Safe w.r.t. the
            # per-key contract: the scalar path touches no device
            # residents or retained tensors.
            return inner.compute(topo, edge_mask, multipath_k=multipath_k)
        if getattr(inner, "engine", None) == "blocked" and multipath_k <= 1:
            # The blocked-Pallas experiment has no split-phase path;
            # run it whole on the worker (actors still don't block).
            ticket = pipe.submit(
                self._key(topo), "one",
                run=lambda: inner.compute(topo, edge_mask),
            )
            return LazySpfResult(ticket)
        use_part = getattr(inner, "_use_partitioned", None)
        if use_part is not None and use_part(topo):
            # Partitioned SPF (ISSUE 15) is a host-orchestrated
            # multi-dispatch (boundary solve -> skeleton stitch ->
            # halo-exchange rounds) with no single launch/finish seam:
            # run it whole on the worker.  Ordering still holds — the
            # per-key serialization covers the resident's donated
            # plane handoff exactly like the split-phase chains.
            ticket = pipe.submit(
                self._key(topo), "one",
                run=lambda: inner.compute(
                    topo, edge_mask, multipath_k=multipath_k
                ),
            )
            return LazySpfResult(ticket)
        fallback = lambda: inner._noted_fallback(  # noqa: E731
            lambda: inner._oracle.compute(
                topo, edge_mask, multipath_k=multipath_k
            )
        )
        ticket = pipe.submit(
            self._key(topo), "one",
            launch=lambda: _guarded_launch(
                inner.breaker, "spf.one",
                lambda: inner.launch_one(
                    topo, edge_mask, multipath_k=multipath_k
                ),
            ),
            finish=lambda st: _guarded_finish(
                st, inner.finish_one, fallback
            ),
        )
        return LazySpfResult(ticket)

    def compute_whatif(self, topo, edge_masks, multipath_k: int = 1):
        return self.inner.compute_whatif(
            topo, edge_masks, multipath_k=multipath_k
        )

    def compute_multiroot(self, topo, roots):
        return self.inner.compute_multiroot(topo, roots)

    # -- advisory what-if (the coalescing + breaker-skip seam) ----------

    def compute_whatif_async(
        self, topo, edge_masks, generation: int | None = None
    ) -> PipelineTicket:
        """Enqueue an advisory what-if batch.  Returns the ticket;
        ``result()`` yields the usual list of SpfResults — or None when
        the batch was skipped (circuit open) or superseded by a newer
        generation's batch for the same (uid, root).

        ``generation`` defaults to the topology's own generation, but
        protocol actors pass a monotonic per-instance stamp (their SPF
        run counter): every SPF marshals a FRESH topology whose local
        generation restarts, and without the stamp a queued batch from
        run N would be "shared" with run N+1 instead of superseded."""
        inner = self.inner
        pipe = self.pipeline
        gen = int(
            topo.cache_key[1] if generation is None else generation
        )
        if pipe is None or pipe.closed:
            t = PipelineTicket(None, self._key(topo), "whatif", gen)
            t._complete(inner.compute_whatif(topo, edge_masks))
            return t
        return pipe.submit(
            self._key(topo), "whatif",
            run=lambda: inner.compute_whatif(topo, edge_masks),
            generation=gen,
            coalesce=True,
            skip_when_open=inner.breaker,
        )


class AsyncFrrEngine:
    """``FrrEngine`` facade: ``compute`` enqueues the batched
    backup-table dispatch (split-phase on the tpu engine) and returns a
    :class:`LazyBackupTable` — SPF and FRR dispatches for one topology
    then overlap, since the FRR planes derive from the topology, not
    the SPF result."""

    def __init__(self, inner, pipeline: DispatchPipeline):
        self.inner = inner
        self.pipeline = pipeline

    def __getattr__(self, attr):
        return getattr(self.inner, attr)

    @property
    def name(self) -> str:
        return f"{getattr(self.inner, 'engine', 'frr')}-async"

    def compute(self, topo):
        inner = self.inner
        pipe = self.pipeline
        if (
            pipe is None
            or pipe.closed
            or getattr(inner, "engine", "scalar") != "tpu"
            or inner.breaker.state == "open"  # see AsyncSpfBackend
        ):
            return inner.compute(topo)
        # Distinct ordering domain from the SPF dispatches of the same
        # topology: FRR reads the resident graph but donates nothing,
        # and the shared DeviceGraphCache serializes its own mutation
        # under its lock — so SPF(topo) and FRR(topo) may overlap.
        # Plane marshal (occupancy gauges included) rides the worker;
        # the failure path re-marshals for the oracle — paying the
        # host marshal twice on the RARE failed dispatch beats paying
        # it on the actor for every healthy one.
        key = ("frr", topo.cache_key[0], int(topo.root))
        ticket = pipe.submit(
            key, "frr",
            launch=lambda: _guarded_launch(
                inner.breaker, "frr.batch",
                lambda: inner._launch_tpu(
                    topo, inner.marshal_inputs(topo)
                ),
            ),
            finish=lambda st: _guarded_finish(
                st, inner._finish_tpu,
                lambda: inner._scalar_fallback(
                    topo, inner.marshal_inputs(topo)
                ),
            ),
        )
        return LazyBackupTable(ticket)


# -- process-wide singleton --------------------------------------------

_PIPELINE: DispatchPipeline | None = None
_PIPELINE_LOCK = threading.Lock()


def configure_process_pipeline(
    depth: int = 2, capacity: int = 32, guard=None
) -> DispatchPipeline:
    """Install the process-wide dispatch pipeline (daemon boot from
    ``[pipeline]``; bench/tests call directly).  Closes any previous
    pipeline first so its worker cannot race the replacement."""
    global _PIPELINE
    with _PIPELINE_LOCK:
        if _PIPELINE is not None:
            _PIPELINE.close()
        _PIPELINE = DispatchPipeline(
            depth=depth, capacity=capacity, name="process", guard=guard
        )
        return _PIPELINE


def process_pipeline() -> DispatchPipeline | None:
    return _PIPELINE


def reset_process_pipeline() -> None:
    """Close + uninstall (tests / bench teardown)."""
    global _PIPELINE
    with _PIPELINE_LOCK:
        if _PIPELINE is not None:
            _PIPELINE.close()
        _PIPELINE = None


def wrap_spf_backend(backend):
    """Route a TpuSpfBackend through the process pipeline when one is
    armed; scalar backends and unarmed processes pass through unchanged
    (the ``[pipeline] enabled=false`` default costs nothing)."""
    pipe = _PIPELINE
    if pipe is None or pipe.closed:
        return backend
    if backend is None or getattr(backend, "name", "") != "tpu":
        return backend
    return AsyncSpfBackend(backend, pipe)


def wrap_frr_engine(engine):
    """FRR analog of :func:`wrap_spf_backend`."""
    pipe = _PIPELINE
    if pipe is None or pipe.closed:
        return engine
    if engine is None or getattr(engine, "engine", "scalar") != "tpu":
        return engine
    return AsyncFrrEngine(engine, pipe)

"""Double-buffered async dispatch pipeline (ISSUE 9 tentpole, part a).

Protocol actors used to block synchronously on every SPF/FRR marshal →
device-execute → readback round trip.  This module puts a bounded
dispatch queue and one pipeline worker between the actors and the
device, in the spirit of DeltaPath's dataflow pipelining
(arXiv:1808.06893):

- actors **enqueue** work (:meth:`DispatchPipeline.submit`) and get a
  ticket back immediately; :class:`LazySpfResult` defers the block to
  the first *use* of the result, so the host work between the dispatch
  call and the first consumption (LSDB walks, route bookkeeping)
  overlaps the device execution for free;
- the worker runs the split-phase backend API
  (``TpuSpfBackend.launch_* / finish_*``): while dispatch *i* executes
  on the device, dispatch *i+1*'s host marshal proceeds — depth-bounded
  double buffering (``depth=2`` default), with the finish (device sync
  + readback) of the oldest in-flight entry interleaved;
- **ordering** is strict per ``(instance topology uid, root)`` key:
  results complete in submission order for a key, and at most ONE entry
  per key is ever in flight — the *ownership handoff* the DeltaPath
  donation contract requires (an in-flight dispatch's donated previous
  tensors / resident graph buffers must never be consumed by a queued
  delta for the same chain; the next entry launches only after the
  previous one's ``finish`` has re-deposited the retained tensors);
- superseded **what-if batches coalesce**: a queued advisory batch for
  the same key is dropped (ticket marked superseded) when a batch for a
  newer topology generation arrives, and a resubmission of the same
  generation shares the queued ticket instead of duplicating work;
- **breaker awareness**: while a dispatch breaker is OPEN, advisory
  what-if batches are skipped at the submit seam — previously each one
  paid the full scalar re-run just to produce advisory output nobody
  was owed.

The survivability plane (ISSUE 19) hardens this queue into something a
serving system can stand on:

- **priority admission** — every ticket carries a class from
  :data:`holo_tpu.resilience.overload.CLASSES` (``correctness`` >
  ``advisory`` > ``background``).  The dequeue is class-aware (lowest
  rank first, FIFO within a rank), so FIB-feeding SPF/FRR work never
  queues behind what-if/twin batches; a FULL queue sheds
  lowest-class-first instead of blocking the submitting actor.
  ``correctness`` is NEVER shed — it keeps the bounded-blocking
  contract exactly as before;
- **deadline-aware shedding** — advisory tickets may carry a
  submit-time deadline and are dropped at dequeue once expired (an
  hour-old what-if batch is not owed a dispatch).  Sheds land in
  ``holo_pipeline_shed_total{class,reason}``, a flight event, and the
  critical-path ledger's ``shed`` disposition;
- **hung-dispatch watchdog hooks** — when a
  :class:`holo_tpu.resilience.watchdog.DispatchWatchdog` is armed, the
  worker stamps each in-flight launch/finish phase
  (``_begin_phase``/``_end_phase``); the sentinel may
  :meth:`DispatchPipeline.abandon_active` an overrunning phase — the
  wedged thread is disowned (it exits at its next ownership check),
  the per-key donation token is released through the
  ``consumes_donated`` handoff seam, and the ticket is served from its
  bit-identical scalar fallback while a fresh worker respawns
  (``respawn()``, supervised via ``Supervisor.watch_worker`` parity
  with ``watch_pump``);
- **transient-retry taxonomy** — ``_guarded_launch`` grants
  transient-classified device errors
  (:func:`holo_tpu.resilience.overload.is_transient`) one
  jittered-backoff retry BEFORE the breaker counts a strike;
  deterministic errors go straight to the fallback as before.

Chaos seams: the async dispatch closures run
``faults.crashpoint("pipeline.dispatch")`` inside the breaker guard;
the worker additionally traverses ``faults.killpoint("pipeline.worker")``
(thread death → supervised respawn) and
``faults.hangpoint("pipeline.launch"/"pipeline.finish")`` (wedge →
watchdog) — every arm must keep correctness FIB digests bit-identical
to the unfaulted control (tests/test_pipeline.py, tests/test_overload.py).

Everything lands in the ``holo_pipeline_*`` metric family: queue depth,
in-flight count, per-kind dispatch counters, coalesced/skipped/shed
tallies, worker respawns, caller wait time, and the measured overlap
ratio (device-in-flight seconds that ran while the worker was free to
do other host work).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from contextlib import nullcontext

from holo_tpu import telemetry
from holo_tpu.analysis.runtime import consumes_donated
from holo_tpu.resilience import faults
from holo_tpu.resilience.overload import CLASS_RANK, CLASSES
from holo_tpu.telemetry import convergence, critpath, flight, slo

log = logging.getLogger("holo_tpu.pipeline")

_QUEUE_DEPTH = telemetry.gauge(
    "holo_pipeline_queue_depth",
    "Entries waiting in the dispatch pipeline queue",
)
_INFLIGHT = telemetry.gauge(
    "holo_pipeline_inflight",
    "Launched-but-unfinished pipeline entries (device in flight)",
)
_DISPATCHES = telemetry.counter(
    "holo_pipeline_dispatch_total",
    "Pipeline entries completed, by dispatch kind",
    ("kind",),
)
_COALESCED = telemetry.counter(
    "holo_pipeline_coalesced_total",
    "Queued what-if batches coalesced (shared or superseded)",
    ("reason",),
)
_BREAKER_SKIPS = telemetry.counter(
    "holo_pipeline_breaker_skip_total",
    "Advisory batches skipped at submit because the circuit was open",
)
_WAIT_SECONDS = telemetry.histogram(
    "holo_pipeline_wait_seconds",
    "Caller-side wait from result force to completion",
    ("kind",),
)
_OVERLAP_RATIO = telemetry.gauge(
    "holo_pipeline_overlap_ratio",
    "Fraction of device-in-flight time overlapped with other host work",
)
_SHED = telemetry.counter(
    "holo_pipeline_shed_total",
    "Tickets shed by the overload plane, by ticket class and reason",
    ("class", "reason"),
)
# Margins span a just-missed dequeue (sub-millisecond past expiry) to
# an advisory that sat a whole storm behind correctness work — the
# default log ladder covers both ends.
_SHED_MARGIN = telemetry.histogram(
    "holo_pipeline_shed_margin_seconds",
    "How far past its deadline an expired ticket already was at "
    "dequeue (near-miss sheds vs hopeless ones)",
    ("class",),
)
_WORKER_RESPAWNS = telemetry.counter(
    "holo_pipeline_worker_respawns_total",
    "Pipeline worker threads respawned after a crash or abandoned hang",
)


class PipelineClosed(RuntimeError):
    """Submit against a closed pipeline."""


class PipelineTicket:
    """Completion handle for one submitted dispatch."""

    __slots__ = (
        "key", "kind", "generation", "cls", "_event", "_value", "_exc",
        "skipped", "superseded", "shed", "_done", "_pipeline", "_cbs",
        "_cb_lock", "eids",
    )

    def __init__(
        self, pipeline, key, kind: str, generation: int,
        cls: str = "correctness",
    ):
        self.key = key
        self.kind = kind
        self.generation = generation
        self.cls = cls
        self._pipeline = pipeline
        self._event = threading.Event()
        self._value = None
        self._exc: BaseException | None = None
        self.skipped = False  # breaker-open skip: never executed
        self.superseded = False  # coalesced away by a newer generation
        self.shed = None  # overload shed reason ("capacity"/"expired")
        # First-settler claim: a ticket may race two resolvers — the
        # watchdog serving the scalar fallback vs the wedged worker
        # finally unblocking — and exactly one outcome must win.
        self._done = False
        self._cbs: list = []
        self._cb_lock = threading.Lock()
        # Causal convergence ids captured at submit (the critical-path
        # ledger's cross-thread join key for the force-wait stamps).
        self.eids: tuple = ()

    def add_done_callback(self, fn) -> None:
        """Run ``fn(ticket)`` at completion (immediately when already
        done).  Callbacks fire on the COMPLETING thread — the pipeline
        worker for queued work — so receivers must hop back onto their
        own actor loop before touching instance state (the deferred
        FRR-attach seam posts itself a loop message).  Callback
        exceptions are swallowed: a consumer bug must not poison the
        worker or the other callbacks."""
        with self._cb_lock:
            if not self._event.is_set():
                self._cbs.append(fn)
                return
        self._run_cb(fn)

    def _run_cb(self, fn) -> None:
        try:
            fn(self)
        except Exception:  # noqa: BLE001 — see add_done_callback
            log.exception("pipeline ticket done-callback failed")

    def _fire_cbs(self) -> None:
        with self._cb_lock:
            cbs, self._cbs = self._cbs, []
        for fn in cbs:
            self._run_cb(fn)

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None):
        """Block until completion; re-raises a passthrough exception on
        the caller's thread (same contract as the synchronous dispatch).
        Skipped/superseded tickets return None."""
        if not self._event.is_set():
            critpath.note_force(self.eids, "b")
            t0 = time.perf_counter()
            if not self._event.wait(timeout):
                raise TimeoutError(
                    f"pipeline result for {self.key}/{self.kind} not ready"
                )
            # Span exemplar (ISSUE 17 satellite): a p99 force-wait is
            # joinable back to its flight-recorder timeline exactly like
            # holo_profile_stage_seconds buckets — the caller's active
            # span when one exists, the causal event id otherwise.
            sid = telemetry.current_span_id()
            exemplar = (
                {"span_id": sid}
                if sid is not None
                else ({"event_id": self.eids[0]} if self.eids else None)
            )
            _WAIT_SECONDS.labels(kind=self.kind).observe(
                time.perf_counter() - t0, exemplar=exemplar
            )
            critpath.note_force(self.eids, "e")
        if self._exc is not None:
            raise self._exc
        return self._value

    # pipeline-side completion (first settler wins; later attempts —
    # e.g. a disowned wedged worker completing after the watchdog
    # already served the fallback — are silently discarded)
    def _claim(self) -> bool:
        with self._cb_lock:
            if self._done:
                return False
            self._done = True
            return True

    def _complete(self, value) -> None:
        if not self._claim():
            return
        self._value = value
        self._event.set()
        self._fire_cbs()
        # Delivery-objective feed (ISSUE 20): a value delivered — even
        # a watchdog-served fallback — is a GOOD graded event for the
        # ticket's priority class; sheds grade bad in _shed_item.  One
        # module-global check while the SLO plane is disarmed.
        slo.note_served(self.cls)

    def _fail(self, exc: BaseException) -> None:
        if not self._claim():
            return
        self._exc = exc
        self._event.set()
        self._fire_cbs()

    def _skip(self, superseded: bool = False) -> None:
        if not self._claim():
            return
        if superseded:
            self.superseded = True
        else:
            self.skipped = True
        self._event.set()
        self._fire_cbs()

    def _shed(self, reason: str) -> None:
        """Overload shed: resolved-but-never-ran, like a breaker skip
        (``skipped`` stays the consumer-facing flag; ``shed`` carries
        the why)."""
        if not self._claim():
            return
        self.shed = reason
        self.skipped = True
        self._event.set()
        self._fire_cbs()


class _Item:
    """One queued dispatch."""

    __slots__ = (
        "key", "kind", "generation", "ticket", "run", "launch", "finish",
        "coalesce", "eids", "handle", "t_launch_end", "stalled",
        "cls", "rank", "deadline", "site", "fallback", "breaker",
        "abandoned",
    )

    def __init__(
        self, ticket, run=None, launch=None, finish=None,
        coalesce=False, eids=(), site=None, fallback=None, breaker=None,
    ):
        self.ticket = ticket
        self.key = ticket.key
        self.kind = ticket.kind
        self.generation = ticket.generation
        self.cls = ticket.cls
        self.rank = CLASS_RANK[ticket.cls]
        self.run = run
        self.launch = launch
        self.finish = finish
        self.coalesce = coalesce
        self.eids = tuple(eids)
        self.handle = None
        self.t_launch_end = 0.0
        # Per-key ordering-stall latch: stamped into the critical-path
        # waterfall on the FIRST skip only (worker rescans are routine).
        self.stalled = False
        # Survivability plane (ISSUE 19): absolute expiry (pipeline
        # clock; None = no deadline), the observatory site whose p99
        # sketches calibrate the watchdog budget, the bit-identical
        # scalar fallback + breaker the watchdog serves/escalates on a
        # hang, and the abandoned latch set by abandon_active.
        self.deadline = None
        self.site = site
        self.fallback = fallback
        self.breaker = breaker
        self.abandoned = False


class DispatchPipeline:
    """Bounded dispatch queue + one pipeline worker thread.

    ``depth`` bounds the launched-but-unfinished entries (2 = classic
    double buffering); ``capacity`` bounds the queue — a full queue
    backpressures the submitting actor (bounded means bounded).
    ``guard`` is an optional zero-arg callable returning a context
    manager entered around every worker-side phase: tests pass
    ``holo_tpu.testing.no_implicit_transfers`` so the pipelined path
    runs under the same transfer sanitizer as the synchronous suites.
    """

    def __init__(
        self,
        depth: int = 2,
        capacity: int = 32,
        name: str = "pipeline",
        guard=None,
        clock=time.monotonic,
        advisory_deadline: float | None = None,
    ):
        self.depth = max(int(depth), 1)
        self.capacity = max(int(capacity), 1)
        self.name = name
        self.guard = guard
        # Deadline clock — consulted ONLY when a ticket actually
        # carries a deadline (the disarmed-path identity contract:
        # tests submit through a poisoned clock and must never trip it).
        self._clock = clock
        #: default relative deadline stamped onto advisory tickets
        #: that did not pass their own (None = advisory never expires)
        self.advisory_deadline = advisory_deadline
        self._cv = threading.Condition()
        self._queue: deque[_Item] = deque()
        self._inflight: list[_Item] = []
        self._inflight_keys: set = set()
        # Items the worker popped but has not yet parked in _inflight /
        # finalized — without this, drain() would report empty while a
        # launch (or a whole single-phase run) is still executing.
        self._working = 0
        self._closed = False
        self._thread: threading.Thread | None = None
        self._worker_spawned = False  # first spawn vs respawn tally
        # Watchdog plane: (item, phase, since) stamp of the in-flight
        # launch/finish phase — ONE tuple store/read (GIL-atomic), only
        # while armed (_watch_clock not None); the sentinel reads it
        # lock-free and abandon_active re-verifies under _cv.
        self._watch_clock = None
        self._active = None
        # Crash seam (Supervisor.watch_worker): worker death marshals
        # through this callback when supervised, else self-respawns.
        self.on_worker_crash = None
        # stats (mutated under _cv or worker-only)
        self._submitted = 0
        self._completed = 0
        self._coalesced = 0
        self._skipped = 0
        self._sheds = 0
        self._shed_by_class: dict = {}
        self._hangs = 0
        self._worker_crashes = 0
        self._worker_respawns = 0
        self._launch_seconds = 0.0
        self._finish_seconds = 0.0
        self._overlap_seconds = 0.0
        self._max_inflight_per_key = 0  # invariant probe (tests): <= 1
        _QUEUE_DEPTH.set_fn(lambda: float(len(self._queue)))
        _INFLIGHT.set_fn(lambda: float(len(self._inflight)))

    # -- submit side ----------------------------------------------------

    def submit(
        self,
        key,
        kind: str,
        run=None,
        launch=None,
        finish=None,
        generation: int = 0,
        coalesce: bool = False,
        skip_when_open=None,
        cls: str = "correctness",
        deadline: float | None = None,
        site: str | None = None,
        fallback=None,
        breaker=None,
    ) -> PipelineTicket:
        """Enqueue one dispatch and return its ticket.

        Exactly one of ``run`` (single-phase: the worker executes it
        whole) or the ``launch``/``finish`` pair (split-phase: overlap
        eligible) must be given.  ``coalesce=True`` marks an advisory
        what-if batch: same-(key, generation) resubmissions share the
        queued ticket, a newer generation supersedes a queued older
        one, and ``skip_when_open`` (a CircuitBreaker) short-circuits
        the submit entirely while the circuit is open.

        Survivability plane: ``cls`` is the priority class
        (``correctness`` keeps bounded-blocking and is never shed;
        ``advisory``/``background`` shed instead of blocking when the
        queue is full).  ``deadline`` (relative seconds; advisory-only)
        expires the ticket at dequeue — advisory tickets default to the
        pipeline's ``advisory_deadline``.  ``site`` names the
        observatory cost-center whose p99 sketches calibrate the
        watchdog hang budget; ``fallback``/``breaker`` are what the
        watchdog serves/escalates when it abandons a hung phase."""
        if cls not in CLASS_RANK:
            raise ValueError(
                f"unknown ticket class {cls!r} (one of {CLASSES})"
            )
        if (run is None) == (launch is None or finish is None):
            raise ValueError("pass run=... OR launch=.../finish=...")
        if deadline is not None and cls == "correctness":
            # Correctness work is owed a dispatch, always — an expiry
            # would be a silent FIB-feeding drop.
            raise ValueError("correctness tickets cannot carry a deadline")
        if deadline is None and cls == "advisory":
            deadline = self.advisory_deadline
        ticket = PipelineTicket(self, key, kind, int(generation), cls=cls)
        if skip_when_open is not None and skip_when_open.state == "open":
            # The breaker is already serving FIB-feeding dispatches from
            # the oracle; an advisory batch is not owed a scalar re-run.
            ticket._skip()
            self._skipped += 1
            _BREAKER_SKIPS.inc()
            return ticket
        item = _Item(
            ticket, run=run, launch=launch, finish=finish,
            coalesce=coalesce, eids=convergence.current(),
            site=site, fallback=fallback, breaker=breaker,
        )
        ticket.eids = item.eids
        if deadline is not None:
            # The ONLY clock read on the submit path — disarmed tickets
            # (no deadline) never touch it (poisoned-clock contract).
            item.deadline = self._clock() + float(deadline)
        # Admission-time stamp, BEFORE the capacity gate: a submitter
        # blocked on a full queue books that wall as ``queue_wait`` in
        # the critical-path waterfalls (overload must be attributable),
        # not silently inside the caller's frame.  note_enqueue is
        # idempotent per record, so the coalesce-shared path needs no
        # second stamp.
        critpath.note_enqueue(item.eids)
        shed_self = False
        victims: list = []
        try:
            with self._cv:
                if self._closed:
                    raise PipelineClosed(self.name)
                if coalesce:
                    for old in list(self._queue):
                        if not (
                            old.coalesce
                            and old.key == key
                            and old.kind == kind
                        ):
                            continue
                        if old.generation == item.generation:
                            # Identical work already queued: share it —
                            # the new submit's causal events ride the
                            # queued item from here on (their
                            # queue-wait started now, at THIS
                            # admission).
                            if item.eids:
                                old.eids = tuple(
                                    dict.fromkeys(old.eids + item.eids)
                                )
                                old.ticket.eids = old.eids
                            self._coalesced += 1
                            _COALESCED.labels(reason="shared").inc()
                            return old.ticket
                        if old.generation < item.generation:
                            # Stale batch nobody needs anymore.
                            self._queue.remove(old)
                            old.ticket._skip(superseded=True)
                            self._coalesced += 1
                            _COALESCED.labels(reason="superseded").inc()
                while len(self._queue) >= self.capacity and not self._closed:
                    victim = self._capacity_victim_locked(item.rank)
                    if victim is not None:
                        # Graded load-shedding: evict the worst-class
                        # (oldest within it) queued ticket instead of
                        # walling the submitter.
                        self._queue.remove(victim)
                        self._note_shed_locked(victim)
                        victims.append(victim)
                        continue
                    if item.rank > 0:
                        # Queue full of equal-or-better work and the
                        # incoming ticket is sheddable: shed IT rather
                        # than block the actor — nobody is owed a
                        # stale advisory result.
                        self._note_shed_locked(item)
                        shed_self = True
                        break
                    # Correctness: bounded means bounded — block until
                    # space frees or the pipeline closes (close() wakes
                    # this wait; the recheck below raises).
                    self._cv.wait(0.5)
                if self._closed:
                    raise PipelineClosed(self.name)
                if not shed_self:
                    self._queue.append(item)
                    self._submitted += 1
                    self._ensure_worker_locked()
                    self._cv.notify_all()
        finally:
            # Victim tickets settle OUTSIDE the lock (done-callbacks
            # must never run under _cv) — including on the
            # PipelineClosed raise above.
            for v in victims:
                self._shed_item(v, "capacity")
        if shed_self:
            self._shed_item(item, "capacity")
        return ticket

    def _capacity_victim_locked(self, incoming_rank: int):
        """Worst-class victim a full queue gives up for an incoming
        ticket of ``incoming_rank``: highest rank wins, oldest within
        that rank; ``correctness`` (rank 0) is untouchable and a victim
        must rank >= the incoming ticket (an equal-rank advisory yields
        to a fresher one).  None = nothing sheddable."""
        victim = None
        for item in self._queue:
            if item.rank == 0 or item.rank < incoming_rank:
                continue
            if victim is None or item.rank > victim.rank:
                victim = item
        return victim

    def _note_shed_locked(self, item) -> None:
        self._sheds += 1
        self._shed_by_class[item.cls] = (
            self._shed_by_class.get(item.cls, 0) + 1
        )

    def _shed_item(self, item, reason: str, margin: float | None = None) -> None:
        """Settle a shed ticket (outside _cv: fires done-callbacks).
        ``margin`` — seconds past the deadline at dequeue — only exists
        for expiry sheds; capacity evictions have no deadline frame."""
        _SHED.labels(**{"class": item.cls, "reason": reason}).inc()
        if margin is not None:
            # Exemplar-joined to the ticket's causal events exactly like
            # the force-wait histogram: a p99 margin is traceable back to
            # the flight-recorder timeline of the event that missed.
            exemplar = {"event_id": item.eids[0]} if item.eids else None
            _SHED_MARGIN.labels(**{"class": item.cls}).observe(
                margin, exemplar=exemplar
            )
        flight.event(
            "pipeline-shed", pipeline=self.name, dispatch=item.kind,
            cls=item.cls, reason=reason,
        )
        critpath.note_shed(item.eids)
        slo.note_shed(item.cls, reason)
        item.ticket._shed(reason)

    def _ensure_worker_locked(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._spawn_worker_locked()

    def _spawn_worker_locked(self) -> None:
        # Callers hold _cv; the re-acquire is reentrant (Condition's
        # default lock is an RLock) and makes the publication of
        # self._thread an explicit lock-seam write.
        with self._cv:
            if self._worker_spawned:
                # Anything after the first spawn is a respawn —
                # crashed, abandoned-as-wedged, or close()-exited then
                # resubmitted.
                self._worker_respawns += 1
                _WORKER_RESPAWNS.inc()
            self._worker_spawned = True
            self._thread = threading.Thread(
                target=self._worker_main,
                name=f"holo-pipeline-{self.name}",
                daemon=True,
            )
            self._thread.start()

    def respawn(self) -> bool:
        """Start a fresh worker over the surviving queue (supervised
        restart hook — ``Supervisor.watch_worker`` duck-type — and the
        watchdog's post-abandon revival).  No-op when a healthy owned
        worker is already running; False once closed."""
        with self._cv:
            if self._closed:
                return False
            t = self._thread
            if (
                t is not None
                and t.is_alive()
                and t is not threading.current_thread()
            ):
                return True
            self._spawn_worker_locked()
            self._cv.notify_all()
            return True

    # -- worker side ----------------------------------------------------

    def _worker_main(self) -> None:
        """Thread target: the loop plus the crash seam.  A worker death
        from ANY cause (chaos killpoint, a bookkeeping bug) must never
        strand the queued tickets — it marshals to the supervisor when
        watched (``on_worker_crash`` → CrashNotice → RestartPolicy
        backoff) and self-respawns immediately otherwise."""
        try:
            self._worker()
        except BaseException as exc:  # noqa: BLE001 — last-resort seam;
            # the per-item paths already contain their own failures.
            with self._cv:
                self._worker_crashes += 1
                if self._thread is threading.current_thread():
                    self._thread = None
                self._cv.notify_all()
            log.exception("pipeline %s worker crashed", self.name)
            flight.event(
                "pipeline-worker-crash", pipeline=self.name,
                error=repr(exc),
            )
            cb = self.on_worker_crash
            if cb is not None:
                cb(exc)
            elif not self._closed:
                self.respawn()

    def _next_launchable_locked(
        self, stalled: list, expired: list
    ) -> _Item | None:
        """Best queued launchable item: lowest class rank first (FIB-
        feeding correctness work never queues behind advisory batches),
        FIFO within a rank, per-key ownership handoff respected (never
        two launches for one key).  Expired-deadline items are removed
        into ``expired`` (shed at dequeue — the hour-old what-if batch
        is not owed a dispatch); items skipped because their key IS in
        flight land in ``stalled`` on their first skip only (the
        ``_Item.stalled`` latch) — the per-key ordering-stall stamp of
        the critical-path ledger."""
        # The worker calls this holding _cv; the re-acquire is
        # reentrant (Condition's default lock is an RLock) and makes
        # the queue mutations explicit lock-seam writes.
        with self._cv:
            best = None
            now = None
            for item in list(self._queue):
                if item.deadline is not None:
                    if now is None:
                        now = self._clock()
                    if now >= item.deadline:
                        self._queue.remove(item)
                        self._note_shed_locked(item)
                        # Carry the lateness out with the item: the
                        # margin histogram observes OUTSIDE _cv.
                        expired.append((item, now - item.deadline))
                        continue
                if item.key in self._inflight_keys:
                    if not item.stalled:
                        item.stalled = True
                        stalled.append(item)
                    continue
                if best is None or item.rank < best.rank:
                    best = item
                    if best.rank == 0:
                        break  # nothing outranks correctness
            if best is not None:
                self._queue.remove(best)
            return best

    def _worker(self) -> None:
        while True:
            # Chaos seam: thread-death injection (supervised-respawn
            # coverage).  Traversed with no item in hand, so queued
            # tickets survive the kill intact.
            faults.killpoint("pipeline.worker")
            launch_item = None
            finish_item = None
            stalled: list = []
            expired: list = []
            with self._cv:
                if self._thread is not threading.current_thread():
                    # Disowned: the watchdog abandoned this thread as
                    # wedged (or a respawn superseded it) — a
                    # replacement owns the queue now.
                    return
                if (
                    self._closed
                    and not self._queue
                    and not self._inflight
                ):
                    self._cv.notify_all()
                    return
                launch_item = (
                    self._next_launchable_locked(stalled, expired)
                    if len(self._inflight) < self.depth
                    else None
                )
                if launch_item is None:
                    if self._inflight:
                        finish_item = self._inflight.pop(0)
                        self._working += 1
                    elif not expired:
                        self._cv.wait(0.5)
                else:
                    self._working += 1
            # Stall/shed stamps run OUTSIDE the cv lock (ISSUE 17
            # contract: no new work under the queue lock on the
            # dispatch thread).
            for it in stalled:
                critpath.note_stall(it.eids)
            for it, margin in expired:
                self._shed_item(it, "expired", margin=margin)
            if launch_item is not None:
                self._do_launch(launch_item)
            elif finish_item is not None:
                self._do_finish(finish_item)

    def _ctx(self, item: _Item):
        g = self.guard() if self.guard is not None else nullcontext()
        return g, convergence.activation(item.eids)

    # -- watchdog plane -------------------------------------------------

    def arm_watchdog(self, clock) -> None:
        """Begin stamping in-flight phase walls (DispatchWatchdog)."""
        self._watch_clock = clock

    def disarm_watchdog(self) -> None:
        self._watch_clock = None
        self._active = None

    def _begin_phase(self, item: _Item, phase: str) -> None:
        wc = self._watch_clock
        if wc is None:
            return  # disarmed: zero clock reads, zero stores
        # One tuple store (GIL-atomic); the sentinel reads it lock-free
        # and abandon_active re-verifies the exact tuple under _cv.
        self._active = (item, phase, wc())

    def _end_phase(self, item: _Item) -> bool:
        """True when this thread still owns ``item`` (the common case);
        False when the watchdog abandoned the phase while we were
        wedged — the ticket was served from the fallback, the
        bookkeeping was settled by abandon_active, and this thread was
        disowned (it exits at the next loop-top ownership check)."""
        if self._watch_clock is None and not item.abandoned:
            return True
        with self._cv:
            act = self._active
            if act is not None and act[0] is item:
                self._active = None
            return not item.abandoned

    def abandon_active(self, item, phase: str) -> bool:
        """Watchdog verdict: give up on the in-flight ``phase`` of
        ``item``.  False when the phase is no longer active (it
        completed while the sentinel decided) — nothing happens then.
        On True: the worker thread is disowned as wedged, the item's
        bookkeeping is settled as completed-by-fallback, and — for a
        finish-phase hang — the per-key donation token is released
        through the audited ``consumes_donated`` seam, so a queued
        delta of the same chain may launch on the respawned worker
        without ever violating donation ownership (the disowned
        thread's late completion is discarded by the ticket's
        first-settler claim and its _end_phase result)."""
        with self._cv:
            act = self._active
            if act is None or act[0] is not item or act[1] != phase:
                return False
            item.abandoned = True
            self._active = None
            self._hangs += 1
            if (
                self._thread is not None
                and self._thread is not threading.current_thread()
            ):
                self._thread = None  # wedged: ownership check exits it
            self._working -= 1
            self._completed += 1
            self._cv.notify_all()
        if phase == "finish":
            # The wedged finish() never re-deposited the donated
            # tensors; the scalar fallback path touches no device
            # residents, so ownership of the chain transfers through
            # the same audited handoff window the healthy path uses.
            with consumes_donated("pipeline.key.handoff"):
                with self._cv:
                    self._inflight_keys.discard(item.key)
                    self._cv.notify_all()
        _DISPATCHES.labels(kind=item.kind).inc()
        return True

    # -- phases ---------------------------------------------------------

    def _do_launch(self, item: _Item) -> None:
        critpath.note_launch(item.eids, "b")
        t0 = time.perf_counter()
        try:
            guard, act = self._ctx(item)
            with guard, act:
                self._begin_phase(item, "launch")
                # Chaos seam: wedge-the-worker injection (watchdog
                # coverage) — inside the phase stamp, like a real stall.
                faults.hangpoint("pipeline.launch")
                if item.run is not None:
                    value = item.run()
                    if not self._end_phase(item):
                        return  # abandoned: watchdog settled everything
                    item.ticket._complete(value)
                    critpath.note_finish(item.eids, "e")
                    self._finalize(item, finished=True)
                    return
                item.handle = item.launch()
                if not self._end_phase(item):
                    return  # abandoned mid-launch: drop the orphan handle
        except BaseException as exc:  # noqa: BLE001 — marshaled to the
            # caller's thread by ticket.result(); the worker survives.
            if not self._end_phase(item):
                return
            item.ticket._fail(exc)
            self._finalize(item, finished=True)
            return
        finally:
            self._launch_seconds += time.perf_counter() - t0
        critpath.note_launch(item.eids, "e")
        item.t_launch_end = time.perf_counter()
        with self._cv:
            self._inflight.append(item)
            self._inflight_keys.add(item.key)
            self._working -= 1
            per_key = sum(
                1 for i in self._inflight if i.key == item.key
            )
            self._max_inflight_per_key = max(
                self._max_inflight_per_key, per_key
            )
            self._cv.notify_all()

    def _do_finish(self, item: _Item) -> None:
        critpath.note_finish(item.eids, "b")
        t_fs = time.perf_counter()
        # Device time that elapsed while the worker was busy elsewhere
        # (launching the next entry / idle-waiting): the overlap the
        # double buffer exists to create.
        self._overlap_seconds += max(t_fs - item.t_launch_end, 0.0)
        owned = True
        try:
            guard, act = self._ctx(item)
            # The pipeline's per-key ownership handoff: finish()
            # re-deposits the fresh tensors that replace the donated
            # previous set, and only then may a queued delta of the
            # same chain launch (submit() serializes on the key).
            # consumes_donated is the HL109 seam vocabulary — the
            # runtime guard counts the window so tests can pin that
            # the handoff actually ran under the async path.
            with guard, act, consumes_donated("pipeline.key.handoff"):
                self._begin_phase(item, "finish")
                faults.hangpoint("pipeline.finish")
                value = item.finish(item.handle)
                owned = self._end_phase(item)
                if owned:
                    item.ticket._complete(value)
            if owned:
                critpath.note_finish(item.eids, "e")
        except BaseException as exc:  # noqa: BLE001 — see _do_launch
            owned = self._end_phase(item)
            if owned:
                item.ticket._fail(exc)
        finally:
            self._finish_seconds += time.perf_counter() - t_fs
            if owned:
                self._finalize(item, finished=False)

    def _finalize(self, item: _Item, finished: bool) -> None:
        with self._cv:
            self._inflight_keys.discard(item.key)
            self._working -= 1
            self._completed += 1
            self._cv.notify_all()
        _DISPATCHES.labels(kind=item.kind).inc()
        denom = self._overlap_seconds + self._finish_seconds
        if denom > 0:
            _OVERLAP_RATIO.set(self._overlap_seconds / denom)

    # -- lifecycle ------------------------------------------------------

    def drain(self, timeout: float | None = None) -> bool:
        """Block until queue + in-flight are empty (True on success)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._queue or self._inflight_keys or self._working:
                wait = 0.5
                if deadline is not None:
                    wait = deadline - time.monotonic()
                    if wait <= 0:
                        return False
                self._cv.wait(min(wait, 0.5))
        return True

    def close(self, timeout: float = 10.0) -> None:
        """Refuse new submits, drain, stop the worker."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)
        # Detach the sampled gauges: a set_fn closure over self would
        # otherwise pin this closed pipeline forever and keep scraping
        # its dead queue.  Safe ordering with configure_process_pipeline
        # (old closed BEFORE the replacement's __init__ re-points them).
        _QUEUE_DEPTH.set_fn(None)
        _QUEUE_DEPTH.set(0.0)
        _INFLIGHT.set_fn(None)
        _INFLIGHT.set(0.0)

    @property
    def closed(self) -> bool:
        return self._closed

    def stats(self) -> dict:
        with self._cv:
            denom = self._overlap_seconds + self._finish_seconds
            return {
                "depth": self.depth,
                "capacity": self.capacity,
                "queued": len(self._queue),
                "inflight": len(self._inflight),
                "submitted": self._submitted,
                "completed": self._completed,
                "coalesced": self._coalesced,
                "breaker-skipped": self._skipped,
                "launch-seconds": round(self._launch_seconds, 6),
                "finish-seconds": round(self._finish_seconds, 6),
                "overlap-seconds": round(self._overlap_seconds, 6),
                "overlap-ratio": round(
                    self._overlap_seconds / denom, 4
                ) if denom > 0 else 0.0,
                "max-inflight-per-key": self._max_inflight_per_key,
                "sheds": self._sheds,
                "shed-by-class": dict(self._shed_by_class),
                "hangs": self._hangs,
                "worker-crashes": self._worker_crashes,
                "worker-respawns": self._worker_respawns,
            }


# -- lazy results -------------------------------------------------------


class LazySpfResult:
    """Duck-typed :class:`holo_tpu.spf.backend.SpfResult`: attribute
    access forces the pipeline ticket.  The protocol layer reads
    ``dist``/``parent``/``hops``/``nexthop_words`` — each blocks until
    the worker completed the dispatch, which by then has usually
    overlapped the caller's own host work."""

    __slots__ = ("_ticket",)

    _FIELDS = (
        "dist", "parent", "hops", "nexthop_words",
        "parents", "pdist", "pweight", "npaths", "nh_weights",
    )

    def __init__(self, ticket: PipelineTicket):
        self._ticket = ticket

    def _force(self):
        res = self._ticket.result()
        if res is None:
            raise RuntimeError(
                f"pipelined SPF dispatch for {self._ticket.key} was "
                f"{'skipped' if self._ticket.skipped else 'superseded'}"
            )
        return res

    def __getattr__(self, name):
        if name in self._FIELDS:
            return getattr(self._force(), name)
        raise AttributeError(name)

    def wait(self):
        """Explicit force (returns the real SpfResult)."""
        return self._force()


class LazyBackupTable:
    """Duck-typed :class:`holo_tpu.frr.kernel.BackupTable`: any
    attribute access forces the FRR pipeline ticket — the protocol
    layer stores the table at SPF time but only consumes it when a
    repair is resolved (BFD/carrier flip), so the FRR dispatch rides
    the pipeline for free."""

    __slots__ = ("_ticket",)

    def __init__(self, ticket: PipelineTicket):
        self._ticket = ticket

    def _force(self):
        res = self._ticket.result()
        if res is None:
            raise RuntimeError(
                f"pipelined FRR dispatch for {self._ticket.key} skipped"
            )
        return res

    def pending(self) -> bool:
        """True while the dispatch is still in flight — the protocol's
        defer-the-force probe (ISSUE 10: the SPF path must not pay the
        FRR force; it re-attaches from a worker done-callback)."""
        return not self._ticket.done()

    def on_done(self, fn) -> None:
        """Completion hook (fires on the pipeline worker thread)."""
        self._ticket.add_done_callback(fn)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._force(), name)

    def wait(self):
        return self._force()


# -- async backend facades ---------------------------------------------

#: exception types the breaker never masks (bugs, not device failures);
#: mirrored from resilience.breaker so the split-phase closures agree.
def _passthrough():
    from holo_tpu.resilience.breaker import _PASSTHROUGH

    return _PASSTHROUGH


def _guarded_launch(breaker, context: str, launch_fn) -> tuple:
    """Phase 1 of a split breaker-guarded dispatch — ONE implementation
    shared by the SPF and FRR facades so the breaker contract (admit →
    chaos seam → retry taxonomy → passthrough abort → failure) cannot
    drift between them.  Returns the ``(verdict, guard, handle)`` state
    :func:`_guarded_finish` completes.

    Transient-retry taxonomy (ISSUE 19): a transient-classified device
    error (:func:`overload.is_transient` — a relay blip, UNAVAILABLE, a
    timed-out collective) gets the policy's jittered-backoff retries
    BEFORE the breaker counts a strike; deterministic errors (a shape
    bug reproduces identically — retrying is pure added latency) go
    straight to the fallback verdict as before."""
    from holo_tpu.resilience import overload

    guard = breaker.split(context)
    if not guard.admitted:
        return ("fallback", guard, None)
    policy = overload.default_retry_policy()
    attempt = 0
    while True:
        try:
            faults.crashpoint("pipeline.dispatch")
            handle = launch_fn()
        except _passthrough():
            guard.abort()
            raise
        except Exception as exc:  # noqa: BLE001 — breaker contract
            if attempt < policy.retries and overload.is_transient(exc):
                attempt += 1
                time.sleep(policy.backoff(context, attempt))
                continue
            if attempt:
                overload.note_retry("exhausted")
            guard.failure(exc)
            return ("fallback", guard, None)
        if attempt:
            overload.note_retry("recovered")
        return ("ok", guard, handle)


def _guarded_finish(state: tuple, finish_fn, fallback_fn):
    """Phase 2: complete the device dispatch or serve the bit-identical
    fallback; success records the whole launch→finish deadline span."""
    verdict, guard, handle = state
    if verdict == "fallback":
        return fallback_fn()
    try:
        res = finish_fn(handle)
    except _passthrough():
        guard.abort()
        raise
    except Exception as exc:  # noqa: BLE001 — breaker contract
        guard.failure(exc)
        return fallback_fn()
    guard.success()
    return res


class AsyncSpfBackend:
    """``SpfBackend`` facade routing dispatches through a pipeline.

    ``compute`` enqueues a split-phase (launch/finish) dispatch and
    returns a :class:`LazySpfResult`; the synchronous breaker contract
    is preserved phase by phase via ``CircuitBreaker.split`` — an XLA
    failure in either phase re-runs on the scalar oracle
    (bit-identical), repeated failures open the circuit, and
    passthrough exceptions surface on the caller's thread at force
    time.  ``compute_whatif_async`` adds the advisory-batch semantics
    (coalescing + breaker-open skip); the plain ``compute_whatif`` /
    ``compute_multiroot`` stay synchronous delegates — their callers
    (CLI, bench) want blocking results.
    """

    #: retained chain-root entries (one live dispatch chain per entry)
    CHAIN_CAPACITY = 512

    def __init__(self, inner, pipeline: DispatchPipeline):
        self.inner = inner
        self.pipeline = pipeline
        # Topology uid -> chain-root uid.  Every SPF run marshals a
        # FRESH Topology object (new uid), so the ordering/ownership
        # unit is the DELTA CHAIN: a topology carrying ``delta_base``
        # lineage joins its base's chain, everything else roots a new
        # one.  This is what makes "(instance, root)" concrete at the
        # backend layer — one instance area advances one chain.
        self._chains: dict = {}

    @property
    def name(self) -> str:
        return f"{self.inner.name}-async"

    def __getattr__(self, attr):
        # breaker / incremental / engine / prepare / oracle ... all
        # delegate: the facade adds scheduling, not behavior.
        return getattr(self.inner, attr)

    # -- keys ----------------------------------------------------------

    def _key(self, topo) -> tuple:
        """The strict-ordering / ownership-handoff unit: (delta-chain
        root uid, root vertex).  Consecutive generations of one
        instance area MUST serialize — an in-flight dispatch's donated
        previous tensors / resident graph buffers must never be
        consumed by a queued delta of the same chain — while unrelated
        areas/instances overlap freely."""
        uid = topo.cache_key[0]
        delta = getattr(topo, "delta_base", None)
        if delta is not None:
            base_uid = delta.base_key[0]
            chain = self._chains.get(base_uid, base_uid)
        else:
            chain = self._chains.get(uid, uid)
        self._chains[uid] = chain
        while len(self._chains) > self.CHAIN_CAPACITY:
            self._chains.pop(next(iter(self._chains)))
        return (chain, int(topo.root))

    # -- SpfBackend interface ------------------------------------------

    def compute(self, topo, edge_mask=None, multipath_k: int = 1):
        inner = self.inner
        pipe = self.pipeline
        if pipe is None or pipe.closed:
            return inner.compute(topo, edge_mask, multipath_k=multipath_k)
        if inner.breaker.state == "open":
            # Degraded mode runs on the CALLER's thread, exactly like
            # the unpipelined breaker: N threaded instances' scalar
            # fallbacks must not serialize behind the one pipeline
            # worker while the device is down.  Safe w.r.t. the
            # per-key contract: the scalar path touches no device
            # residents or retained tensors.
            return inner.compute(topo, edge_mask, multipath_k=multipath_k)
        if getattr(inner, "engine", None) == "blocked" and multipath_k <= 1:
            # The blocked-Pallas experiment has no split-phase path;
            # run it whole on the worker (actors still don't block).
            ticket = pipe.submit(
                self._key(topo), "one",
                run=lambda: inner.compute(topo, edge_mask),
                cls="correctness", site="spf.blocked",
                fallback=lambda: inner._noted_fallback(
                    lambda: inner._oracle.compute(topo, edge_mask)
                ),
                breaker=inner.breaker,
            )
            return LazySpfResult(ticket)
        use_part = getattr(inner, "_use_partitioned", None)
        if use_part is not None and use_part(topo):
            # Partitioned SPF (ISSUE 15) is a host-orchestrated
            # multi-dispatch (boundary solve -> skeleton stitch ->
            # halo-exchange rounds) with no single launch/finish seam:
            # run it whole on the worker.  Ordering still holds — the
            # per-key serialization covers the resident's donated
            # plane handoff exactly like the split-phase chains.
            fallback = lambda: inner._noted_fallback(  # noqa: E731
                lambda: inner._oracle.compute(
                    topo, edge_mask, multipath_k=multipath_k
                )
            )
            ticket = pipe.submit(
                self._key(topo), "one",
                run=lambda: inner.compute(
                    topo, edge_mask, multipath_k=multipath_k
                ),
                cls="correctness", site="spf.partitioned",
                fallback=fallback, breaker=inner.breaker,
            )
            return LazySpfResult(ticket)
        fallback = lambda: inner._noted_fallback(  # noqa: E731
            lambda: inner._oracle.compute(
                topo, edge_mask, multipath_k=multipath_k
            )
        )
        ticket = pipe.submit(
            self._key(topo), "one",
            launch=lambda: _guarded_launch(
                inner.breaker, "spf.one",
                lambda: inner.launch_one(
                    topo, edge_mask, multipath_k=multipath_k
                ),
            ),
            finish=lambda st: _guarded_finish(
                st, inner.finish_one, fallback
            ),
            cls="correctness", site="spf.one",
            fallback=fallback, breaker=inner.breaker,
        )
        return LazySpfResult(ticket)

    def compute_whatif(self, topo, edge_masks, multipath_k: int = 1):
        return self.inner.compute_whatif(
            topo, edge_masks, multipath_k=multipath_k
        )

    def compute_multiroot(self, topo, roots):
        return self.inner.compute_multiroot(topo, roots)

    # -- advisory what-if (the coalescing + breaker-skip seam) ----------

    def compute_whatif_async(
        self, topo, edge_masks, generation: int | None = None
    ) -> PipelineTicket:
        """Enqueue an advisory what-if batch.  Returns the ticket;
        ``result()`` yields the usual list of SpfResults — or None when
        the batch was skipped (circuit open) or superseded by a newer
        generation's batch for the same (uid, root).

        ``generation`` defaults to the topology's own generation, but
        protocol actors pass a monotonic per-instance stamp (their SPF
        run counter): every SPF marshals a FRESH topology whose local
        generation restarts, and without the stamp a queued batch from
        run N would be "shared" with run N+1 instead of superseded."""
        inner = self.inner
        pipe = self.pipeline
        gen = int(
            topo.cache_key[1] if generation is None else generation
        )
        if pipe is None or pipe.closed:
            t = PipelineTicket(None, self._key(topo), "whatif", gen)
            t._complete(inner.compute_whatif(topo, edge_masks))
            return t
        return pipe.submit(
            self._key(topo), "whatif",
            run=lambda: inner.compute_whatif(topo, edge_masks),
            generation=gen,
            coalesce=True,
            skip_when_open=inner.breaker,
            # Advisory class: first shed under overload, expires at the
            # pipeline's advisory_deadline.  No fallback — a hung
            # advisory batch is not owed a scalar re-run (the ticket
            # fails with WatchdogTimeout; consumers treat it like a
            # skip).
            cls="advisory", site="spf.whatif",
        )


class AsyncFrrEngine:
    """``FrrEngine`` facade: ``compute`` enqueues the batched
    backup-table dispatch (split-phase on the tpu engine) and returns a
    :class:`LazyBackupTable` — SPF and FRR dispatches for one topology
    then overlap, since the FRR planes derive from the topology, not
    the SPF result."""

    def __init__(self, inner, pipeline: DispatchPipeline):
        self.inner = inner
        self.pipeline = pipeline

    def __getattr__(self, attr):
        return getattr(self.inner, attr)

    @property
    def name(self) -> str:
        return f"{getattr(self.inner, 'engine', 'frr')}-async"

    def compute(self, topo):
        inner = self.inner
        pipe = self.pipeline
        if (
            pipe is None
            or pipe.closed
            or getattr(inner, "engine", "scalar") != "tpu"
            or inner.breaker.state == "open"  # see AsyncSpfBackend
        ):
            return inner.compute(topo)
        # Distinct ordering domain from the SPF dispatches of the same
        # topology: FRR reads the resident graph but donates nothing,
        # and the shared DeviceGraphCache serializes its own mutation
        # under its lock — so SPF(topo) and FRR(topo) may overlap.
        # Plane marshal (occupancy gauges included) rides the worker;
        # the failure path re-marshals for the oracle — paying the
        # host marshal twice on the RARE failed dispatch beats paying
        # it on the actor for every healthy one.
        key = ("frr", topo.cache_key[0], int(topo.root))
        ticket = pipe.submit(
            key, "frr",
            launch=lambda: _guarded_launch(
                inner.breaker, "frr.batch",
                lambda: inner._launch_tpu(
                    topo, inner.marshal_inputs(topo)
                ),
            ),
            finish=lambda st: _guarded_finish(
                st, inner._finish_tpu,
                lambda: inner._scalar_fallback(
                    topo, inner.marshal_inputs(topo)
                ),
            ),
            cls="correctness", site="frr.batch",
            fallback=lambda: inner._scalar_fallback(
                topo, inner.marshal_inputs(topo)
            ),
            breaker=inner.breaker,
        )
        return LazyBackupTable(ticket)


# -- process-wide singleton --------------------------------------------

_PIPELINE: DispatchPipeline | None = None
_PIPELINE_LOCK = threading.Lock()


def configure_process_pipeline(
    depth: int = 2, capacity: int = 32, guard=None,
    advisory_deadline: float | None = None,
) -> DispatchPipeline:
    """Install the process-wide dispatch pipeline (daemon boot from
    ``[pipeline]``; bench/tests call directly).  Closes any previous
    pipeline first so its worker cannot race the replacement."""
    global _PIPELINE
    with _PIPELINE_LOCK:
        if _PIPELINE is not None:
            _PIPELINE.close()
        _PIPELINE = DispatchPipeline(
            depth=depth, capacity=capacity, name="process", guard=guard,
            advisory_deadline=advisory_deadline,
        )
        return _PIPELINE


def process_pipeline() -> DispatchPipeline | None:
    return _PIPELINE


def reset_process_pipeline() -> None:
    """Close + uninstall (tests / bench teardown)."""
    global _PIPELINE
    with _PIPELINE_LOCK:
        if _PIPELINE is not None:
            _PIPELINE.close()
        _PIPELINE = None


def wrap_spf_backend(backend):
    """Route a TpuSpfBackend through the process pipeline when one is
    armed; scalar backends and unarmed processes pass through unchanged
    (the ``[pipeline] enabled=false`` default costs nothing)."""
    pipe = _PIPELINE
    if pipe is None or pipe.closed:
        return backend
    if backend is None or getattr(backend, "name", "") != "tpu":
        return backend
    return AsyncSpfBackend(backend, pipe)


def wrap_frr_engine(engine):
    """FRR analog of :func:`wrap_spf_backend`."""
    pipe = _PIPELINE
    if pipe is None or pipe.closed:
        return engine
    if engine is None or getattr(engine, "engine", "scalar") != "tpu":
        return engine
    return AsyncFrrEngine(engine, pipe)

"""Async dispatch pipeline + per-shape engine auto-tuner (ISSUE 9).

The execution layer between the protocol actors and the device:

- :mod:`holo_tpu.pipeline.dispatch` — bounded per-backend dispatch
  queue + pipeline worker overlapping marshal / device-execute /
  readback across consecutive SPF/FRR dispatches, with strict
  per-(uid, root) ordering, what-if coalescing, breaker-open skip, and
  the DeltaPath donation ownership handoff (depth-2 double buffering,
  one in-flight entry per key).  The dispatch survivability plane
  (ISSUE 19) rides the same queue: class-aware priority admission
  (correctness > advisory > background), deadline-aware graded
  load-shedding, the hung-dispatch watchdog hooks
  (:mod:`holo_tpu.resilience.watchdog`), and supervised worker
  respawn (``Supervisor.watch_worker``).
- :mod:`holo_tpu.pipeline.tuner` — measured per-(V, E, batch, mesh)
  shape-bucket engine selection from compile-time ``cost_analysis()``
  priors + dispatch-wall medians, persisted to a versioned table
  (``[pipeline] tuner-cache``) so restarts don't re-learn; the same
  table carries the auto-tuned DeltaPath ``max_delta_depth`` per
  bucket.

Both are armed from ``[pipeline]`` in holod.toml at daemon boot and
exported on the ``holo-telemetry`` state leaf; everything is off by
default and the disabled path costs one module-global check.
"""

from holo_tpu.pipeline.dispatch import (
    AsyncFrrEngine,
    AsyncSpfBackend,
    DispatchPipeline,
    LazyBackupTable,
    LazySpfResult,
    PipelineClosed,
    PipelineTicket,
    configure_process_pipeline,
    process_pipeline,
    reset_process_pipeline,
    wrap_frr_engine,
    wrap_spf_backend,
)
from holo_tpu.pipeline.tuner import (
    ENGINES,
    EngineTuner,
    active_tuner,
    configure_engine_tuner,
    reset_engine_tuner,
    shape_bucket,
)

__all__ = [
    "AsyncFrrEngine",
    "AsyncSpfBackend",
    "DispatchPipeline",
    "ENGINES",
    "EngineTuner",
    "LazyBackupTable",
    "LazySpfResult",
    "PipelineClosed",
    "PipelineTicket",
    "active_tuner",
    "configure_engine_tuner",
    "configure_process_pipeline",
    "process_pipeline",
    "reset_engine_tuner",
    "reset_process_pipeline",
    "shape_bucket",
    "wrap_frr_engine",
    "wrap_spf_backend",
]

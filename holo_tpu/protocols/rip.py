"""RIPv2 (RFC 2453) + RIPng (RFC 2080): distance-vector routing.

Reference: holo-rip (SURVEY.md §2.3) — route table with timeout/garbage
timers, split horizon with poisoned reverse, triggered updates, periodic
full updates.  The two versions share the instance machinery through the
version object (codec + multicast group), mirroring the reference's
``Version`` trait (holo-rip/src/version.rs:22).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from ipaddress import IPv4Address, IPv4Network

from ipaddress import IPv6Address, IPv6Network

from holo_tpu.utils.bytesbuf import DecodeError, Reader, Writer
from holo_tpu.utils.ip import RIPNG_GROUP, RIPV2_GROUP, mask_of
from holo_tpu.utils.netio import NetIo, NetRxPacket
from holo_tpu.utils.runtime import Actor

RIP_PORT = 520
RIPNG_PORT = 521
INFINITY_METRIC = 16


class RipCommand(enum.IntEnum):
    REQUEST = 1
    RESPONSE = 2


@dataclass(frozen=True)
class Rte:
    """Route table entry on the wire (RFC 2453 §4)."""

    prefix: IPv4Network
    nexthop: IPv4Address
    metric: int
    tag: int = 0


@dataclass
class RipPacket:
    command: RipCommand
    rtes: list[Rte] = field(default_factory=list)

    def encode(self) -> bytes:
        w = Writer()
        w.u8(int(self.command)).u8(2).u16(0)  # version 2
        for rte in self.rtes:
            w.u16(2)  # AF_INET
            w.u16(rte.tag)
            w.ipv4(rte.prefix.network_address)
            w.ipv4(mask_of(rte.prefix))
            w.ipv4(rte.nexthop)
            w.u32(rte.metric)
        return w.finish()

    @classmethod
    def decode(cls, data: bytes) -> "RipPacket":
        r = Reader(data)
        try:
            cmd = RipCommand(r.u8())
        except ValueError as e:
            raise DecodeError("unknown RIP command") from e
        version = r.u8()
        if version != 2:
            raise DecodeError(f"unsupported RIP version {version}")
        r.u16()
        rtes = []
        while r.remaining() >= 20:
            af = r.u16()
            tag = r.u16()
            addr = r.ipv4()
            mask = r.ipv4()
            nh = r.ipv4()
            metric = r.u32()
            if af != 2 or not 1 <= metric <= INFINITY_METRIC:
                raise DecodeError("bad RTE")
            m = int(mask)
            plen = bin(m).count("1")
            if m != (0xFFFFFFFF << (32 - plen)) & 0xFFFFFFFF and m != 0:
                raise DecodeError("non-contiguous mask")
            try:
                prefix = IPv4Network((int(addr) & m, plen))
            except ValueError as e:
                raise DecodeError(f"bad prefix: {e}") from e
            rtes.append(Rte(prefix, nh, metric, tag))
        return cls(cmd, rtes)


@dataclass
class RipngPacket:
    """RIPng (RFC 2080 §2): v6 RTEs are (prefix 16B, tag, plen, metric).

    Next-hop RTEs (metric 0xFF) are not yet emitted; receivers treat the
    packet source (link-local) as next hop, which is the common case.
    """

    command: RipCommand
    rtes: list = field(default_factory=list)  # [(IPv6Network, tag, metric)]

    def encode(self) -> bytes:
        w = Writer()
        w.u8(int(self.command)).u8(1).u16(0)  # version 1
        for prefix, tag, metric in self.rtes:
            w.ipv6(prefix.network_address)
            w.u16(tag).u8(prefix.prefixlen).u8(metric)
        return w.finish()

    @classmethod
    def decode(cls, data: bytes) -> "RipngPacket":
        r = Reader(data)
        try:
            cmd = RipCommand(r.u8())
        except ValueError as e:
            raise DecodeError("unknown RIPng command") from e
        if r.u8() != 1:
            raise DecodeError("unsupported RIPng version")
        r.u16()
        rtes = []
        while r.remaining() >= 20:
            addr = r.ipv6()
            tag = r.u16()
            plen = r.u8()
            metric = r.u8()
            if metric == 0xFF:
                # Next-hop RTE (RFC 2080 §2.1.1): sets the next hop for
                # following RTEs; not an error.  We currently use the
                # packet source as next hop, so it is skipped.
                continue
            if plen > 128 or not 1 <= metric <= INFINITY_METRIC:
                raise DecodeError("bad RIPng RTE")
            masked = int(addr) & ~((1 << (128 - plen)) - 1) if plen < 128 else int(addr)
            rtes.append((IPv6Network((masked, plen)), tag, metric))
        return cls(cmd, rtes)


class RipVersion:
    """Version strategy: v2 (IPv4) — reference version.rs Ripv2 arm."""

    name = "ripv2"
    group = RIPV2_GROUP

    @staticmethod
    def encode(command, entries) -> bytes:
        return RipPacket(
            command,
            [Rte(prefix, IPv4Address(0), metric, tag)
             for prefix, tag, metric in entries],
        ).encode()

    @staticmethod
    def decode(data: bytes):
        pkt = RipPacket.decode(data)
        return pkt.command, [
            (r.prefix, r.tag, r.metric, r.nexthop if int(r.nexthop) else None)
            for r in pkt.rtes
        ]


class RipngVersion:
    """Version strategy: RIPng (IPv6) — reference version.rs Ripng arm."""

    name = "ripng"
    group = RIPNG_GROUP

    @staticmethod
    def encode(command, entries) -> bytes:
        return RipngPacket(command, list(entries)).encode()

    @staticmethod
    def decode(data: bytes):
        pkt = RipngPacket.decode(data)
        return pkt.command, [
            (prefix, tag, metric, None) for prefix, tag, metric in pkt.rtes
        ]


@dataclass
class RipRoute:
    prefix: IPv4Network
    nexthop: IPv4Address | None  # None = connected
    ifname: str
    metric: int
    tag: int = 0
    changed: bool = True
    timeout_at: float | None = None  # None for connected
    garbage_at: float | None = None


@dataclass
class UpdateTimerMsg:
    pass


@dataclass
class TriggeredTimerMsg:
    pass


@dataclass
class AgeTimerMsg:
    pass


@dataclass
class RipIfConfig:
    cost: int = 1
    split_horizon: str = "poison-reverse"  # disabled|simple|poison-reverse


class RipInstance(Actor):
    """RIPv2 routing process."""

    name = "ripv2"

    def __init__(
        self,
        name: str,
        netio: NetIo,
        update_interval: float = 30.0,
        timeout: float = 180.0,
        garbage: float = 120.0,
        route_cb=None,
        version=RipVersion,
    ):
        self.name = name
        self.netio = netio
        self.V = version
        self.update_interval = update_interval
        self.timeout = timeout
        self.garbage = garbage
        self.route_cb = route_cb
        self.interfaces: dict[str, tuple[RipIfConfig, IPv4Address, IPv4Network]] = {}
        self.routes: dict[IPv4Network, RipRoute] = {}
        self._triggered_pending = False

    def attach(self, loop_):
        super().attach(loop_)
        self._update_timer = self.loop.timer(self.name, UpdateTimerMsg)
        self._age_timer = self.loop.timer(self.name, AgeTimerMsg)
        self._trig_timer = self.loop.timer(self.name, TriggeredTimerMsg)
        self._update_timer.start(0.1)
        self._age_timer.start(1.0)

    def add_interface(self, ifname: str, cfg: RipIfConfig, addr: IPv4Address, prefix: IPv4Network):
        self.interfaces[ifname] = (cfg, addr, prefix)
        self.routes[prefix] = RipRoute(
            prefix=prefix, nexthop=None, ifname=ifname, metric=cfg.cost
        )

    # -- actor

    def handle(self, msg):
        if isinstance(msg, NetRxPacket):
            self._rx(msg)
        elif isinstance(msg, UpdateTimerMsg):
            self._send_updates(changed_only=False)
            self._update_timer.start(self.update_interval)
        elif isinstance(msg, TriggeredTimerMsg):
            if self._triggered_pending:
                self._triggered_pending = False
                self._send_updates(changed_only=True)
        elif isinstance(msg, AgeTimerMsg):
            self._age()
            self._age_timer.start(1.0)

    # -- rx path (RFC 2453 §3.9.2)

    def _rx(self, msg: NetRxPacket) -> None:
        entry = self.interfaces.get(msg.ifname)
        if entry is None:
            return
        cfg, our_addr, _prefix = entry
        if msg.src == our_addr:
            return
        try:
            command, entries = self.V.decode(msg.data)
        except DecodeError:
            return
        if command != RipCommand.RESPONSE:
            return
        now = self.loop.clock.now()
        changed_any = False
        for prefix, tag, rte_metric, rte_nh in entries:
            metric = min(rte_metric + cfg.cost, INFINITY_METRIC)
            nh = rte_nh if rte_nh is not None else msg.src
            cur = self.routes.get(prefix)
            if cur is None:
                if metric < INFINITY_METRIC:
                    self.routes[prefix] = RipRoute(
                        prefix=prefix,
                        nexthop=nh,
                        ifname=msg.ifname,
                        metric=metric,
                        tag=tag,
                        timeout_at=now + self.timeout,
                    )
                    changed_any = True
                continue
            if cur.nexthop is None:
                continue  # connected beats learned
            from_same = cur.nexthop == nh and cur.ifname == msg.ifname
            if from_same:
                cur.timeout_at = now + self.timeout
            if (from_same and metric != cur.metric) or metric < cur.metric:
                old_metric = cur.metric
                cur.metric = metric
                cur.nexthop = nh
                cur.ifname = msg.ifname
                cur.changed = True
                changed_any = True
                if metric >= INFINITY_METRIC:
                    if cur.garbage_at is None:
                        cur.garbage_at = now + self.garbage
                else:
                    cur.garbage_at = None
                    cur.timeout_at = now + self.timeout
        if changed_any:
            self._schedule_triggered()
            self._notify()

    # -- tx path

    def _send_updates(self, changed_only: bool) -> None:
        for ifname, (cfg, our_addr, _prefix) in self.interfaces.items():
            entries = []
            for route in self.routes.values():
                if changed_only and not route.changed:
                    continue
                metric = route.metric
                if route.ifname == ifname and route.nexthop is not None:
                    if cfg.split_horizon == "simple":
                        continue
                    if cfg.split_horizon == "poison-reverse":
                        metric = INFINITY_METRIC
                entries.append((route.prefix, route.tag, metric))
            for i in range(0, len(entries), 25):
                data = self.V.encode(RipCommand.RESPONSE, entries[i : i + 25])
                self.netio.send(ifname, our_addr, self.V.group, data)
        for route in self.routes.values():
            route.changed = False

    def _schedule_triggered(self) -> None:
        if not self._triggered_pending:
            self._triggered_pending = True
            self._trig_timer.start(1.0)  # 1-5s randomized in the RFC

    # -- aging (RFC 2453 §3.8)

    def _age(self) -> None:
        now = self.loop.clock.now()
        changed = False
        for route in list(self.routes.values()):
            if route.timeout_at is not None and route.garbage_at is None:
                if now >= route.timeout_at:
                    route.metric = INFINITY_METRIC
                    route.garbage_at = now + self.garbage
                    route.changed = True
                    changed = True
            if route.garbage_at is not None and now >= route.garbage_at:
                del self.routes[route.prefix]
                changed = True
        if changed:
            self._schedule_triggered()
            self._notify()

    def _notify(self) -> None:
        if self.route_cb is not None:
            self.route_cb(
                {
                    p: r
                    for p, r in self.routes.items()
                    if r.metric < INFINITY_METRIC
                }
            )

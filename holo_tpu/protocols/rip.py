"""RIPv2 (RFC 2453) + RIPng (RFC 2080): distance-vector routing.

Reference: holo-rip (SURVEY.md §2.3) — route table with timeout/garbage
timers, split horizon with poisoned reverse, triggered updates, periodic
full updates.  The two versions share the instance machinery through the
version object (codec + multicast group), mirroring the reference's
``Version`` trait (holo-rip/src/version.rs:22).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from ipaddress import IPv4Address, IPv4Network

from ipaddress import IPv6Address, IPv6Network

from holo_tpu.utils.bytesbuf import DecodeError, Reader, Writer
from holo_tpu.utils.ip import RIPNG_GROUP, RIPV2_GROUP, mask_of
from holo_tpu.utils.netio import NetIo, NetRxPacket
from holo_tpu.utils.runtime import Actor

RIP_PORT = 520
RIPNG_PORT = 521
INFINITY_METRIC = 16


class RipCommand(enum.IntEnum):
    REQUEST = 1
    RESPONSE = 2


@dataclass(frozen=True)
class Rte:
    """Route table entry on the wire (RFC 2453 §4).  ``prefix`` None is
    the address-family-0 whole-table-request sentinel."""

    prefix: IPv4Network | None
    nexthop: IPv4Address
    metric: int
    tag: int = 0


AUTH_SIMPLE = 2  # RFC 2453 §4.1 simple password
AUTH_CRYPTO = 3  # RFC 2082/4822 keyed digest


@dataclass
class RipPacket:
    command: RipCommand
    rtes: list[Rte] = field(default_factory=list)
    # RFC 2082 sequence number of a crypto-authenticated packet (None
    # for unauthenticated/simple-password packets) — the receiver's
    # replay check compares it per source.
    auth_seqno: int | None = None

    def encode(self, auth_password: str | None = None, auth_key: bytes | None = None, auth_key_id: int = 1, seqno: int = 0) -> bytes:
        """RFC 2453 §4.1 / RFC 2082: with ``auth_password`` the first
        RTE is the 16-byte password; with ``auth_key`` a keyed-MD5
        header RTE plus trailing digest are emitted."""
        import hashlib

        w = Writer()
        w.u8(int(self.command)).u8(2).u16(0)  # version 2
        md5_hdr_pos = None
        if auth_password is not None:
            w.u16(0xFFFF).u16(AUTH_SIMPLE)
            w.bytes(auth_password.encode()[:16].ljust(16, b"\x00"))
        elif auth_key is not None:
            w.u16(0xFFFF).u16(AUTH_CRYPTO)
            md5_hdr_pos = len(w)
            w.u16(0)  # packet length (patched below)
            w.u8(auth_key_id).u8(20)  # key id + auth data length
            w.u32(seqno)
            w.u32(0).u32(0)  # reserved
        for rte in self.rtes:
            if rte.prefix is None:
                # Whole-table request RTE: AF 0, metric 16.
                w.u16(0).u16(0)
                w.u32(0).u32(0).u32(0)
                w.u32(rte.metric)
                continue
            w.u16(2)  # AF_INET
            w.u16(rte.tag)
            w.ipv4(rte.prefix.network_address)
            w.ipv4(mask_of(rte.prefix))
            w.ipv4(rte.nexthop)
            w.u32(rte.metric)
        if auth_key is not None:
            # The trailing digest RTE: AF 0xFFFF, type 1, then MD5 over
            # the packet with the key appended (RFC 2082 §3.2.2).
            w.patch_u16(md5_hdr_pos, len(w))
            w.u16(0xFFFF).u16(1)
            base = bytes(w.buf)
            digest = hashlib.md5(
                base + auth_key[:16].ljust(16, b"\x00")
            ).digest()
            w.bytes(digest)
        return w.finish()

    @classmethod
    def decode(
        cls,
        data: bytes,
        auth_password: str | None = None,
        auth_key: bytes | None = None,
        auth_key_lookup=None,
    ) -> "RipPacket":
        """``auth_key_lookup`` (key_id -> key bytes | None) serves
        keychain-backed interfaces: the wire key id selects the accept
        key by lifetime (utils/keychain.py), so rollover works for RIP
        MD5 the same way it does for OSPF/IS-IS."""
        r = Reader(data)
        try:
            cmd = RipCommand(r.u8())
        except ValueError as e:
            raise DecodeError("unknown RIP command") from e
        version = r.u8()
        if version != 2:
            raise DecodeError(f"unsupported RIP version {version}")
        r.u16()
        rtes = []
        import hashlib

        authed = (
            auth_password is None
            and auth_key is None
            and auth_key_lookup is None
        )
        auth_seqno = None
        first = True
        auth_len = len(data)
        while r.pos + 20 <= auth_len:
            af = r.u16()
            tag = None
            if af == 0xFFFF:
                atype = r.u16()
                if first and atype == AUTH_SIMPLE:
                    pw = r.bytes(16).rstrip(b"\x00").decode(errors="replace")
                    if auth_password is not None and pw == auth_password:
                        authed = True
                    elif auth_password is not None:
                        raise DecodeError("bad RIP password")
                    else:
                        # RFC 2453 §4.1: a router not configured for
                        # (this type of) authentication discards
                        # authenticated messages.
                        raise DecodeError("unexpected authenticated packet")
                    first = False
                    continue
                if first and atype == AUTH_CRYPTO:
                    pkt_len = r.u16()
                    key_id = r.u8()
                    r.u8()  # auth data length
                    rx_seqno = r.u32()
                    r.u32()
                    r.u32()
                    key = auth_key
                    if auth_key_lookup is not None:
                        key = auth_key_lookup(key_id)
                        if key is None:
                            raise DecodeError("unknown RIP key id")
                    if key is None:
                        # RFC 2453 §4.1: not configured for MD5 auth —
                        # discard rather than accept unverified.
                        raise DecodeError("unexpected authenticated packet")
                    want = hashlib.md5(
                        data[:pkt_len + 4]
                        + key[:16].ljust(16, b"\x00")
                    ).digest()
                    got = data[pkt_len + 4 : pkt_len + 20]
                    import hmac as _h

                    if not _h.compare_digest(want, got):
                        raise DecodeError("bad RIP MD5 digest")
                    authed = True
                    auth_seqno = rx_seqno
                    auth_len = min(auth_len, pkt_len)
                    first = False
                    continue
                raise DecodeError("unexpected auth RTE")
            first = False
            tag = r.u16()
            addr = r.ipv4()
            mask = r.ipv4()
            nh = r.ipv4()
            metric = r.u32()
            if af == 0:
                # Address-family 0: the whole-table request RTE
                # (RFC 2453 §3.9.1), prefix None as sentinel — only
                # meaningful in requests.
                if cmd != RipCommand.REQUEST:
                    raise DecodeError("AF-0 RTE in response")
                rtes.append(Rte(None, nh, metric, tag))
                continue
            if af != 2:
                raise DecodeError("bad RTE")
            if cmd == RipCommand.RESPONSE and not 1 <= metric <= INFINITY_METRIC:
                raise DecodeError("bad RTE metric")
            m = int(mask)
            plen = bin(m).count("1")
            if m != (0xFFFFFFFF << (32 - plen)) & 0xFFFFFFFF and m != 0:
                raise DecodeError("non-contiguous mask")
            try:
                prefix = IPv4Network((int(addr) & m, plen))
            except ValueError as e:
                raise DecodeError(f"bad prefix: {e}") from e
            rtes.append(Rte(prefix, nh, metric, tag))
        if not authed:
            raise DecodeError("RIP authentication required")
        return cls(cmd, rtes, auth_seqno=auth_seqno)


@dataclass
class RipngPacket:
    """RIPng (RFC 2080 §2): v6 RTEs are (prefix 16B, tag, plen, metric).

    Next-hop RTEs (metric 0xFF) are not yet emitted; receivers treat the
    packet source (link-local) as next hop, which is the common case.
    """

    command: RipCommand
    rtes: list = field(default_factory=list)  # [(IPv6Network, tag, metric)]

    def encode(self) -> bytes:
        w = Writer()
        w.u8(int(self.command)).u8(1).u16(0)  # version 1
        for prefix, tag, metric in self.rtes:
            w.ipv6(prefix.network_address)
            # Next-hop RTEs (metric 0xFF) carry prefix-len 0.
            plen = 0 if metric == 0xFF else prefix.prefixlen
            w.u16(tag).u8(plen).u8(metric)
        return w.finish()

    @classmethod
    def decode(cls, data: bytes) -> "RipngPacket":
        r = Reader(data)
        try:
            cmd = RipCommand(r.u8())
        except ValueError as e:
            raise DecodeError("unknown RIPng command") from e
        if r.u8() != 1:
            raise DecodeError("unsupported RIPng version")
        r.u16()
        rtes = []
        cur_nh = None
        while r.remaining() >= 20:
            addr = r.ipv6()
            tag = r.u16()
            plen = r.u8()
            metric = r.u8()
            if metric == 0xFF:
                # Next-hop RTE (RFC 2080 §2.1.1): sets the next hop for
                # the RTEs that follow (:: resets to the packet source).
                cur_nh = addr if int(addr) else None
                continue
            if plen > 128:
                raise DecodeError("bad RIPng RTE")
            if cmd == RipCommand.RESPONSE and not 1 <= metric <= INFINITY_METRIC:
                raise DecodeError("bad RIPng RTE metric")
            masked = int(addr) & ~((1 << (128 - plen)) - 1) if plen < 128 else int(addr)
            rtes.append((IPv6Network((masked, plen)), tag, metric, cur_nh))
        return cls(cmd, rtes)


class RipVersion:
    """Version strategy: v2 (IPv4) — reference version.rs Ripv2 arm."""

    name = "ripv2"
    group = RIPV2_GROUP

    @staticmethod
    def encode(command, entries, auth=None) -> bytes:
        pw, key, key_id, seqno = (auth or (None, None, 1, 0))[:4]
        return RipPacket(
            command,
            [Rte(prefix, IPv4Address(0), metric, tag)
             for prefix, tag, metric in entries],
        ).encode(
            auth_password=pw, auth_key=key, auth_key_id=key_id, seqno=seqno
        )

    @staticmethod
    def decode(data: bytes, auth=None):
        a = auth or (None, None, 1, 0)
        pw, key = a[:2]
        lookup = a[4] if len(a) > 4 else None
        pkt = RipPacket.decode(
            data, auth_password=pw, auth_key=key, auth_key_lookup=lookup
        )
        return pkt.command, [
            (r.prefix, r.tag, r.metric, r.nexthop if int(r.nexthop) else None)
            for r in pkt.rtes
        ], pkt.auth_seqno

    @staticmethod
    def encode_request_all() -> bytes:
        return RipPacket(
            RipCommand.REQUEST,
            [Rte(None, IPv4Address(0), INFINITY_METRIC)],
        ).encode()


class RipngVersion:
    """Version strategy: RIPng (IPv6) — reference version.rs Ripng arm."""

    name = "ripng"
    group = RIPNG_GROUP

    @staticmethod
    def encode(command, entries, auth=None) -> bytes:
        # RIPng has no in-protocol auth (RFC 2080 relies on IPsec).
        return RipngPacket(command, list(entries)).encode()

    @staticmethod
    def decode(data: bytes, auth=None):
        pkt = RipngPacket.decode(data)
        out = []
        for prefix, tag, metric, nh in pkt.rtes:
            if (
                pkt.command == RipCommand.REQUEST
                and metric == INFINITY_METRIC
                and int(prefix.network_address) == 0
                and prefix.prefixlen == 0
            ):
                out.append((None, tag, metric, None))
            else:
                out.append((prefix, tag, metric, nh))
        return pkt.command, out, None

    @staticmethod
    def encode_request_all() -> bytes:
        return RipngPacket(
            RipCommand.REQUEST,
            [(IPv6Network("::/0"), 0, INFINITY_METRIC)],
        ).encode()


@dataclass
class RipRoute:
    prefix: IPv4Network
    nexthop: IPv4Address | None  # None = connected
    ifname: str
    metric: int
    tag: int = 0
    changed: bool = True
    timeout_at: float | None = None  # None for connected
    garbage_at: float | None = None
    rcvd_metric: int | None = None  # wire metric before the iface cost
    source: object = None  # sender address (distinct from nexthop)
    # "connected" | "rip" | "redistributed" (operational state).
    route_type: str = "rip"


@dataclass
class UpdateTimerMsg:
    pass


@dataclass
class TriggeredTimerMsg:
    pass


@dataclass
class AgeTimerMsg:
    pass


@dataclass
class RipIfConfig:
    cost: int = 1
    split_horizon: str = "simple"  # disabled|simple|poison-reverse
    passive: bool = False
    # RFC 2453 §4.1 simple-password / RFC 2082 keyed-MD5 authentication.
    auth_password: str | None = None
    auth_key: bytes | None = None
    auth_key_id: int = 1
    # Lifetime-based key selection (utils/keychain.py, the OSPF/IS-IS
    # semantics): the active SEND key signs, the wire key id selects the
    # accept key by lifetime — rollover without packet loss.
    auth_keychain: object = None
    auth_clock: object = None

    def _now(self) -> float:
        import time as _time

        return (
            self.auth_clock() if callable(self.auth_clock) else _time.time()
        )

    def _accept_lookup(self):
        """key_id -> key bytes | None: the RFC 2082 u8 wire id selects
        the accept key by lifetime (masked compare in the keychain)."""
        kc = self.auth_keychain

        def lookup(key_id: int):
            k = kc.key_lookup_accept(key_id, self._now(), mask=0xFF)
            return k.string if k is not None else None

        return lookup

    def rx_auth_tuple(self):
        """Accept-side context only — decode never needs the send key,
        so the per-packet send-lifetime scan is skipped."""
        if self.auth_keychain is not None:
            return (None, None, 1, 0, self._accept_lookup())
        return self.auth_tuple()

    def auth_tuple(self, seqno: int = 0):
        if self.auth_keychain is not None:
            kc = self.auth_keychain
            lookup = self._accept_lookup()
            k = kc.key_lookup_send(self._now())
            # No active send key: tx goes unauthenticated (the peer's
            # auth requirement rejects it — a visible coverage gap, not
            # a forged-looking digest), rx still resolves by key id.
            return (
                None,
                k.string if k is not None else None,
                (k.id & 0xFF) if k is not None else 1,
                seqno,
                lookup,
            )
        if self.auth_password is None and self.auth_key is None:
            return None
        return (self.auth_password, self.auth_key, self.auth_key_id, seqno)


class RipInstance(Actor):
    """RIPv2 routing process."""

    name = "ripv2"

    def __init__(
        self,
        name: str,
        netio: NetIo,
        update_interval: float = 30.0,
        timeout: float = 180.0,
        garbage: float = 120.0,
        route_cb=None,
        version=RipVersion,
    ):
        self.name = name
        self.netio = netio
        self.V = version
        self.update_interval = update_interval
        self.timeout = timeout
        self.garbage = garbage
        self.route_cb = route_cb
        self.interfaces: dict[str, tuple[RipIfConfig, IPv4Address, IPv4Network]] = {}
        self.routes: dict[IPv4Network, RipRoute] = {}
        self._triggered_pending = False
        # RFC 2453 §4.2-ish peer table: source address -> last heard.
        self.neighbors: dict = {}
        # Explicitly configured unicast neighbors (RFC 2453 §4.3).
        self.static_neighbors: set = set()
        self.distance = 120
        self._seqno = 0  # RFC 4822 auth sequence number
        # RFC 2082 §3.2.2 replay floor per (ifname, source).
        self._rx_auth_seqnos: dict = {}
        # Triggered-update machinery (RFC 2453 §3.10.1, reference
        # events.rs:361-394): suppressed before the initial update;
        # rate-limited by the holdoff window afterwards.
        self._holdoff = False
        self._initial_pending = True

    def attach(self, loop_):
        super().attach(loop_)
        self._update_timer = self.loop.timer(self.name, UpdateTimerMsg)
        self._age_timer = self.loop.timer(self.name, AgeTimerMsg)
        self._trig_timer = self.loop.timer(self.name, TriggeredTimerMsg)
        self._update_timer.start(0.1)
        self._age_timer.start(1.0)

    def add_interface(self, ifname: str, cfg: RipIfConfig, addr: IPv4Address, prefix: IPv4Network):
        self.interfaces[ifname] = (cfg, addr, prefix)
        if prefix is not None:
            self.routes[prefix] = RipRoute(
                prefix=prefix, nexthop=None, ifname=ifname,
                metric=cfg.cost, route_type="connected",
            )
        if not cfg.passive and self.netio is not None:
            # Interface start solicits full tables (RFC 2453 §3.9.1) —
            # multicast plus any configured unicast neighbors on it.
            req = self.V.encode_request_all()
            self.netio.send(ifname, addr, self.V.group, req)
            for ifn, nbr in sorted(self.static_neighbors, key=str):
                if ifn == ifname:
                    self.netio.send(ifname, addr, nbr, req)
        self._schedule_triggered()
        self._notify()

    def remove_interface(self, ifname: str) -> None:
        """Circuit gone: connected route out, learned routes through it
        invalidated (metric 16, garbage collection)."""
        if self.interfaces.pop(ifname, None) is None:
            return
        changed = False
        for route in list(self.routes.values()):
            if route.ifname != ifname:
                continue
            if route.metric < INFINITY_METRIC:
                self._invalidate(route)
                changed = True
        if changed:
            self._notify()

    def add_connected(self, ifname: str, prefix, cost: int | None = None) -> None:
        """Connected prefix from an address event: always (re)placed,
        reviving an invalidated entry (reference connected_route_add)."""
        entry = self.interfaces.get(ifname)
        if entry is None:
            return
        self.routes[prefix] = RipRoute(
            prefix=prefix, nexthop=None, ifname=ifname,
            metric=cost if cost is not None else entry[0].cost,
            route_type="connected",
        )
        self._schedule_triggered()
        self._notify()

    def del_connected(self, prefix) -> None:
        route = self.routes.get(prefix)
        if route is not None and route.route_type == "connected":
            self._invalidate(route)
            self._notify()

    def redistribute(self, prefix, metric: int = 1, tag: int = 0) -> None:
        """Install a redistributed route (ibus RouteRedistributeAdd).
        Never displaces a connected or RIP-learned route."""
        if prefix in self.routes or prefix.network_address.is_link_local:
            return
        self.routes[prefix] = RipRoute(
            prefix=prefix, nexthop=None, ifname="", metric=max(1, metric),
            tag=tag, route_type="redistributed",
        )
        self._schedule_triggered()
        self._notify()

    def redistribute_del(self, prefix) -> None:
        route = self.routes.get(prefix)
        if route is not None and route.route_type == "redistributed":
            del self.routes[route.prefix]
            self._schedule_triggered()
            self._notify()

    # -- actor

    def handle(self, msg):
        if isinstance(msg, NetRxPacket):
            self._rx(msg)
        elif isinstance(msg, UpdateTimerMsg):
            if self._initial_pending:
                self.initial_update()
            else:
                self._send_updates(changed_only=False)
            self._update_timer.start(self.update_interval)
        elif isinstance(msg, TriggeredTimerMsg):
            if self._holdoff:
                self.holdoff_expired()
            else:
                self.drain_triggered()
        elif isinstance(msg, AgeTimerMsg):
            self._age()
            self._age_timer.start(1.0)

    # -- rx path (RFC 2453 §3.9.2)

    def _rx(self, msg: NetRxPacket) -> None:
        entry = self.interfaces.get(msg.ifname)
        if entry is None:
            return
        cfg, our_addr, _prefix = entry
        if msg.src == our_addr:
            return
        try:
            command, entries, auth_seqno = self.V.decode(
                msg.data, auth=cfg.rx_auth_tuple()
            )
        except DecodeError:
            return
        if auth_seqno is not None:
            # RFC 2082 §3.2.2 replay protection: a crypto-authenticated
            # packet whose sequence number is LOWER than the last one
            # accepted from this source is a replay — discard.
            key = (msg.ifname, msg.src)
            last = self._rx_auth_seqnos.get(key)
            if last is not None and auth_seqno < last:
                return
            self._rx_auth_seqnos[key] = auth_seqno
        now = self.loop.clock.now()
        if command == RipCommand.REQUEST:
            self._rx_request(msg, entries)
            return
        if command != RipCommand.RESPONSE:
            return
        self.neighbors[msg.src] = now
        changed_any = False
        for prefix, tag, rte_metric, rte_nh in entries:
            metric = min(rte_metric + cfg.cost, INFINITY_METRIC)
            nh = rte_nh if rte_nh is not None else msg.src
            cur = self.routes.get(prefix)
            if cur is None:
                if metric < INFINITY_METRIC:
                    self.routes[prefix] = RipRoute(
                        prefix=prefix,
                        nexthop=nh,
                        ifname=msg.ifname,
                        metric=metric,
                        tag=tag,
                        timeout_at=now + self.timeout,
                        rcvd_metric=rte_metric,
                        source=msg.src,
                    )
                    changed_any = True
                continue
            if cur.nexthop is None:
                continue  # connected beats learned
            from_same = cur.source == msg.src and cur.ifname == msg.ifname
            if from_same:
                cur.timeout_at = now + self.timeout
            if (
                from_same
                and (
                    metric != cur.metric
                    or nh != cur.nexthop
                    or tag != cur.tag
                )
            ) or metric < cur.metric:
                old_metric = cur.metric
                cur.metric = metric
                cur.rcvd_metric = rte_metric
                cur.nexthop = nh
                cur.tag = tag
                cur.source = msg.src
                cur.ifname = msg.ifname
                cur.changed = True
                changed_any = True
                if metric >= INFINITY_METRIC:
                    if cur.garbage_at is None:
                        cur.garbage_at = now + self.garbage
                else:
                    cur.garbage_at = None
                    cur.timeout_at = now + self.timeout
        if changed_any:
            self._schedule_triggered()
            self._notify()

    def _rx_request(self, msg: NetRxPacket, entries) -> None:
        """RFC 2453 §3.9.1: answer a whole-table request with normal
        output processing, unicast back to the asker; a specific-prefix
        request gets the metrics filled in verbatim."""
        iface = self.interfaces.get(msg.ifname)
        if iface is None:
            return
        cfg, our_addr, _prefix = iface
        whole = len(entries) == 1 and entries[0][0] is None
        if whole:
            out = self._routes_for(msg.ifname, cfg, changed_only=False)
            self._seqno += 1
            for i in range(0, len(out), 25):
                data = self.V.encode(
                    RipCommand.RESPONSE, out[i : i + 25],
                    auth=cfg.auth_tuple(self._seqno),
                )
                self.netio.send(msg.ifname, our_addr, msg.src, data)
        else:
            answered = [
                (
                    prefix, tag,
                    self.routes[prefix].metric
                    if prefix in self.routes
                    else INFINITY_METRIC,
                )
                for prefix, tag, _metric, _nh in entries
                if prefix is not None
            ]
            if not answered:
                return
            data = self.V.encode(RipCommand.RESPONSE, answered)
            self.netio.send(msg.ifname, our_addr, msg.src, data)

    # -- external timer events (recorded by the reference's tasks)

    def send_initial_requests(self) -> None:
        """Instance start: solicit full tables (RFC 2453 §3.9.1)."""
        for ifname, (cfg, our_addr, _p) in self.interfaces.items():
            if cfg.passive:
                continue
            data = self.V.encode_request_all()
            self.netio.send(ifname, our_addr, self.V.group, data)

    def nbr_timeout(self, addr) -> None:
        self.neighbors.pop(addr, None)
        # Drop the RFC 2082 replay floor with the neighbor: a restarted
        # peer resumes its sequence counter near zero, and a stale floor
        # would blackhole it forever.
        for key in [k for k in self._rx_auth_seqnos if k[1] == addr]:
            del self._rx_auth_seqnos[key]

    def route_timeout(self, prefix) -> None:
        route = self.routes.get(prefix)
        if route is not None and route.nexthop is not None:
            self._invalidate(route)
            self._notify()

    def route_gc(self, prefix) -> None:
        route = self.routes.get(prefix)
        if route is not None and route.garbage_at is not None:
            del self.routes[prefix]
            self._notify()

    def iface_cost_update(self, ifname: str, cost: int) -> None:
        """Interface cost change: every route's metric recomputes as
        cost + received metric.  NOTE: like the reference
        (configuration.rs InterfaceCostUpdate), the CHANGED circuit's
        cost applies to the whole table — including connected and
        redistributed entries — which its recorded conformance corpus
        asserts."""
        entry = self.interfaces.get(ifname)
        if entry is None:
            return
        entry[0].cost = cost
        now = self.loop.clock.now()
        for route in self.routes.values():
            if route.metric >= INFINITY_METRIC:
                continue
            metric = cost
            if route.rcvd_metric is not None:
                metric += route.rcvd_metric
            route.metric = min(metric, INFINITY_METRIC)
            route.changed = True
            self._schedule_triggered()
            if route.metric >= INFINITY_METRIC:
                route.garbage_at = now + self.garbage
        self._notify()

    def clear_routes(self) -> None:
        """ietf-rip clear-rip-route RPC: drop learned routes."""
        changed = False
        for route in list(self.routes.values()):
            if route.route_type == "rip":
                del self.routes[route.prefix]
                changed = True
        if changed:
            self._notify()

    # -- tx path

    def _routes_for(self, ifname: str, cfg: RipIfConfig, changed_only: bool) -> list:
        entries = []
        for route in self.routes.values():
            if changed_only and not route.changed:
                continue
            metric = route.metric
            if route.ifname == ifname and route.nexthop is not None:
                if cfg.split_horizon == "simple":
                    continue
                if cfg.split_horizon == "poison-reverse":
                    metric = INFINITY_METRIC
            entries.append((route.prefix, route.tag, metric))
        entries.sort(
            key=lambda e: (int(e[0].network_address), e[0].prefixlen)
        )
        return entries

    def _send_updates(self, changed_only: bool) -> None:
        for ifname, (cfg, our_addr, _prefix) in self.interfaces.items():
            if cfg.passive:
                continue
            entries = self._routes_for(ifname, cfg, changed_only)
            dsts = [self.V.group] + [
                n for ifn, n in self.static_neighbors if ifn == ifname
            ]
            self._seqno += 1
            for dst in dsts:
                for i in range(0, len(entries), 25):
                    data = self.V.encode(
                        RipCommand.RESPONSE, entries[i : i + 25],
                        auth=cfg.auth_tuple(self._seqno),
                    )
                    self.netio.send(ifname, our_addr, dst, data)
        for route in self.routes.values():
            route.changed = False
        if not changed_only:
            # A regular update supersedes any held-off triggered one
            # (reference output.rs:165-171 cancel_triggered_update).
            self._holdoff = False
            self._triggered_pending = False

    def _iface_of(self, addr):
        for ifname, (_cfg, _a, prefix) in self.interfaces.items():
            if prefix is not None and addr in prefix:
                return ifname
        return None

    def _invalidate(self, route: RipRoute) -> None:
        """RFC 2453 §3.8 invalidation: uninstall, metric 16, flag
        changed, start garbage collection, trigger an update."""
        now = self.loop.clock.now()
        route.metric = INFINITY_METRIC
        route.changed = True
        route.timeout_at = None
        route.garbage_at = now + self.garbage
        self._schedule_triggered()

    def triggered_fire(self) -> None:
        """Send changed routes and open the holdoff window."""
        self._send_updates(changed_only=True)
        self._holdoff = True
        if getattr(self, "_trig_timer", None) is not None:
            self._trig_timer.start(1.0)  # holdoff, 1-5s in the RFC

    def holdoff_expired(self) -> None:
        pending = self._triggered_pending
        self._holdoff = False
        self._triggered_pending = False
        if pending:
            self.triggered_fire()

    def initial_update(self) -> None:
        """Instance-start full update; unblocks triggered updates."""
        self._initial_pending = False
        self._send_updates(changed_only=False)

    def drain_triggered(self) -> None:
        """Process the self-posted trigger (reference
        process_triggered_update): dropped before the initial update,
        pended during holdoff, otherwise sent immediately."""
        if not self._triggered_pending:
            return
        if self._initial_pending:
            return
        if self._holdoff:
            return  # stays pending until the holdoff expires
        self._triggered_pending = False
        self.triggered_fire()

    def _schedule_triggered(self) -> None:
        self._triggered_pending = True
        # Production path: arm the short triggered-update timer (the
        # conformance replay instead drains at the recorded points).
        t = getattr(self, "_trig_timer", None)
        if t is not None and not self._holdoff and not t.armed:
            t.start(1.0)

    # -- aging (RFC 2453 §3.8)

    def _age(self) -> None:
        now = self.loop.clock.now()
        changed = False
        for route in list(self.routes.values()):
            if route.timeout_at is not None and route.garbage_at is None:
                if now >= route.timeout_at:
                    route.metric = INFINITY_METRIC
                    route.garbage_at = now + self.garbage
                    route.changed = True
                    changed = True
            if route.garbage_at is not None and now >= route.garbage_at:
                del self.routes[route.prefix]
                changed = True
        if changed:
            self._schedule_triggered()
            self._notify()

    def _notify(self) -> None:
        if self.route_cb is not None:
            self.route_cb(
                {
                    p: r
                    for p, r in self.routes.items()
                    if r.metric < INFINITY_METRIC
                }
            )

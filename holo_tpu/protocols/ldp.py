"""LDP (RFC 5036): label distribution for MPLS.

Reference: holo-ldp (SURVEY.md §2.3) — UDP hello discovery, TCP session
with init/keepalive, downstream-unsolicited label distribution with
liberal retention, FEC table driven by RIB routes.

Transport on the fabric: hellos are multicast frames, session messages
unicast frames (the daemon binds real UDP 646 + TCP 646).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from ipaddress import IPv4Address, IPv4Network

from holo_tpu.utils.bytesbuf import DecodeError, Reader, Writer
from holo_tpu.utils.mpls import IMPLICIT_NULL, LabelManager
from holo_tpu.utils.netio import NetIo, NetRxPacket
from holo_tpu.utils.runtime import Actor


class _McastAll(str):
    is_multicast = True


ALL_ROUTERS_LDP = _McastAll("224.0.0.2:646")

LDP_VERSION = 1


class LdpMsgType(enum.IntEnum):
    HELLO = 0x0100
    INIT = 0x0200
    KEEPALIVE = 0x0201
    LABEL_MAPPING = 0x0400
    LABEL_WITHDRAW = 0x0402
    LABEL_RELEASE = 0x0403


@dataclass
class LdpMsg:
    type: LdpMsgType
    lsr_id: IPv4Address
    # message payload fields (superset; relevant per type):
    hold_time: int = 15
    keepalive_time: int = 30
    fec: IPv4Network | None = None
    label: int | None = None

    def encode(self) -> bytes:
        w = Writer()
        w.u16(LDP_VERSION)
        len_pos = len(w)
        w.u16(0)
        w.ipv4(self.lsr_id).u16(0)  # LDP identifier (label space 0)
        body_start = len(w)
        w.u16(int(self.type))
        mlen_pos = len(w)
        w.u16(0)
        w.u32(0)  # message id (filled by sender when needed)
        mstart = len(w)
        if self.type == LdpMsgType.HELLO:
            # Common hello params TLV 0x0400
            w.u16(0x0400).u16(4).u16(self.hold_time).u16(0)
        elif self.type == LdpMsgType.INIT:
            # Common session params TLV 0x0500
            w.u16(0x0500).u16(14)
            w.u16(LDP_VERSION).u16(self.keepalive_time).u8(0).u8(0)
            w.u16(0)  # max pdu
            w.ipv4(self.lsr_id).u16(0)
        elif self.type in (
            LdpMsgType.LABEL_MAPPING,
            LdpMsgType.LABEL_WITHDRAW,
            LdpMsgType.LABEL_RELEASE,
        ):
            # FEC TLV 0x0100 (prefix element type 2)
            plen = self.fec.prefixlen
            nbytes = (plen + 7) // 8
            w.u16(0x0100).u16(4 + nbytes)
            w.u8(2).u8(1).u8(0).u8(plen)  # element 2, AF=1 (IPv4)
            w.bytes(self.fec.network_address.packed[:nbytes])
            if self.type != LdpMsgType.LABEL_RELEASE or self.label is not None:
                # Generic label TLV 0x0200
                w.u16(0x0200).u16(4).u32(self.label if self.label is not None else 0)
        w.patch_u16(mlen_pos, len(w) - mstart + 4)
        w.patch_u16(len_pos, len(w) - body_start + 6)
        return w.finish()

    @classmethod
    def decode(cls, data: bytes) -> "LdpMsg":
        r = Reader(data)
        if r.u16() != LDP_VERSION:
            raise DecodeError("bad LDP version")
        pdu_len = r.u16()
        lsr_id = r.ipv4()
        r.u16()  # label space
        try:
            mtype = LdpMsgType(r.u16())
        except ValueError as e:
            raise DecodeError("unknown LDP message") from e
        r.u16()  # msg length
        r.u32()  # msg id
        out = cls(mtype, lsr_id)
        while r.remaining() >= 4:
            tlv = r.u16()
            tlen = r.u16()
            body = r.sub(min(tlen, r.remaining()))
            if tlv == 0x0400:
                out.hold_time = body.u16()
            elif tlv == 0x0500:
                body.u16()
                out.keepalive_time = body.u16()
            elif tlv == 0x0100:
                el = body.u8()
                af = body.u8()
                body.u8()
                plen = body.u8()
                if el != 2 or plen > 32:
                    raise DecodeError("bad FEC element")
                nbytes = (plen + 7) // 8
                raw = body.bytes(nbytes) + bytes(4 - nbytes)
                out.fec = IPv4Network((int.from_bytes(raw, "big"), plen))
            elif tlv == 0x0200:
                out.label = body.u32()
        return out


class NbrState(enum.Enum):
    DISCOVERED = "discovered"
    INIT_SENT = "init-sent"
    OPERATIONAL = "operational"


@dataclass
class LdpNeighbor:
    lsr_id: IPv4Address
    addr: IPv4Address
    ifname: str
    state: NbrState = NbrState.DISCOVERED
    hold_time: int = 15
    # label bindings learned from this peer: fec -> label
    bindings: dict[IPv4Network, int] = field(default_factory=dict)


@dataclass
class HelloTimerMsg:
    pass


@dataclass
class NbrTimeoutMsg:
    lsr_id: IPv4Address


class LdpInstance(Actor):
    """One LDP LSR: discovery + sessions + DU label distribution."""

    name = "ldp"

    def __init__(
        self,
        name: str,
        lsr_id: IPv4Address,
        netio: NetIo,
        label_manager: LabelManager | None = None,
        lib_cb=None,
    ):
        self.name = name
        self.lsr_id = lsr_id
        self.netio = netio
        self.labels = label_manager or LabelManager()
        self.lib_cb = lib_cb  # callable(lib) on label-table change
        self.interfaces: dict[str, IPv4Address] = {}  # ifname -> our addr
        self.neighbors: dict[IPv4Address, LdpNeighbor] = {}
        # Our FECs: prefix -> (local label, is_egress)
        self.fec_table: dict[IPv4Network, tuple[int, bool]] = {}

    def attach(self, loop_):
        super().attach(loop_)
        self._hello_timer = self.loop.timer(self.name, HelloTimerMsg)
        self._hello_timer.start(0.1)

    def add_interface(self, ifname: str, addr: IPv4Address) -> None:
        self.interfaces[ifname] = addr

    def add_fec(self, prefix: IPv4Network, egress: bool) -> int:
        """Create a local binding (egress FECs bind implicit-null)."""
        if prefix in self.fec_table:
            return self.fec_table[prefix][0]
        label = IMPLICIT_NULL if egress else self.labels.allocate()
        self.fec_table[prefix] = (label, egress)
        for nbr in self.neighbors.values():
            if nbr.state == NbrState.OPERATIONAL:
                self._send_mapping(nbr, prefix, label)
        self._lib_changed()
        return label

    def remove_fec(self, prefix: IPv4Network) -> None:
        entry = self.fec_table.pop(prefix, None)
        if entry is None:
            return
        label, egress = entry
        if not egress:
            self.labels.release(label)
        for nbr in self.neighbors.values():
            if nbr.state == NbrState.OPERATIONAL:
                self._send(
                    nbr.ifname,
                    nbr.addr,
                    LdpMsg(LdpMsgType.LABEL_WITHDRAW, self.lsr_id,
                           fec=prefix, label=label),
                )
        self._lib_changed()

    # -- actor

    def handle(self, msg):
        if isinstance(msg, NetRxPacket):
            self._rx(msg)
        elif isinstance(msg, HelloTimerMsg):
            for ifname, addr in self.interfaces.items():
                hello = LdpMsg(LdpMsgType.HELLO, self.lsr_id, hold_time=15)
                self.netio.send(ifname, addr, ALL_ROUTERS_LDP, hello.encode())
            self._hello_timer.start(5.0)
        elif isinstance(msg, NbrTimeoutMsg):
            nbr = self.neighbors.pop(msg.lsr_id, None)
            if nbr is not None:
                self._lib_changed()

    def _rx(self, msg: NetRxPacket) -> None:
        try:
            pdu = LdpMsg.decode(msg.data)
        except DecodeError:
            return
        if pdu.lsr_id == self.lsr_id:
            return
        if pdu.type == LdpMsgType.HELLO:
            self._rx_hello(msg, pdu)
            return
        nbr = self.neighbors.get(pdu.lsr_id)
        if nbr is None:
            return
        if pdu.type == LdpMsgType.INIT:
            if nbr.state == NbrState.DISCOVERED:
                self._send_init(nbr)
            self._send(nbr.ifname, nbr.addr,
                       LdpMsg(LdpMsgType.KEEPALIVE, self.lsr_id))
        elif pdu.type == LdpMsgType.KEEPALIVE:
            if nbr.state != NbrState.OPERATIONAL:
                nbr.state = NbrState.OPERATIONAL
                # Advertise all local bindings (downstream unsolicited).
                for prefix, (label, _e) in self.fec_table.items():
                    self._send_mapping(nbr, prefix, label)
            self._touch(nbr)
        elif pdu.type == LdpMsgType.LABEL_MAPPING and pdu.fec is not None:
            nbr.bindings[pdu.fec] = pdu.label
            self._lib_changed()
        elif pdu.type == LdpMsgType.LABEL_WITHDRAW and pdu.fec is not None:
            nbr.bindings.pop(pdu.fec, None)
            self._send(nbr.ifname, nbr.addr,
                       LdpMsg(LdpMsgType.LABEL_RELEASE, self.lsr_id,
                              fec=pdu.fec, label=pdu.label))
            self._lib_changed()

    def _rx_hello(self, msg: NetRxPacket, pdu: LdpMsg) -> None:
        nbr = self.neighbors.get(pdu.lsr_id)
        if nbr is None:
            nbr = LdpNeighbor(pdu.lsr_id, msg.src, msg.ifname,
                              hold_time=pdu.hold_time)
            self.neighbors[pdu.lsr_id] = nbr
            # Active side: higher LSR id initiates the session (RFC 5036
            # §2.5.2 transport connection roles).
            if int(self.lsr_id) > int(pdu.lsr_id):
                self._send_init(nbr)
        self._touch(nbr)

    def _touch(self, nbr: LdpNeighbor) -> None:
        t = getattr(nbr, "_timeout", None)
        if t is None:
            t = self.loop.timer(
                self.name, lambda l=nbr.lsr_id: NbrTimeoutMsg(l)
            )
            nbr._timeout = t
        t.start(nbr.hold_time * 3)

    def _send(self, ifname: str, dst, pdu: LdpMsg) -> None:
        self.netio.send(ifname, self.interfaces.get(ifname), dst, pdu.encode())

    def _send_init(self, nbr: LdpNeighbor) -> None:
        nbr.state = NbrState.INIT_SENT
        self._send(nbr.ifname, nbr.addr,
                   LdpMsg(LdpMsgType.INIT, self.lsr_id))

    def _send_mapping(self, nbr: LdpNeighbor, prefix: IPv4Network, label: int) -> None:
        self._send(nbr.ifname, nbr.addr,
                   LdpMsg(LdpMsgType.LABEL_MAPPING, self.lsr_id,
                          fec=prefix, label=label))

    # -- LIB (label information base) view

    def lib(self) -> dict:
        """fec -> {local, remote: {lsr_id: label}} — the MPLS LIB the
        routing provider merges with RIB next hops to build LFIB entries
        (reference rib.rs:152-212)."""
        out = {}
        for prefix, (label, egress) in self.fec_table.items():
            out[prefix] = {
                "local": label,
                "egress": egress,
                "remote": {
                    str(n.lsr_id): n.bindings[prefix]
                    for n in self.neighbors.values()
                    if prefix in n.bindings
                },
            }
        return out

    def _lib_changed(self) -> None:
        if self.lib_cb is not None:
            self.lib_cb(self.lib())

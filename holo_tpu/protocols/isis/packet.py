"""IS-IS PDU and TLV codecs (ISO 10589 §9; RFCs 1195, 5303, 5305).

Reference: holo-isis packet layer.  System IDs are 6 bytes; LSP IDs are
sysid + pseudonode byte + fragment byte.  Wide metrics only.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from ipaddress import IPv4Address, IPv4Network, IPv6Network

from holo_tpu.utils.bytesbuf import DecodeError, Reader, Writer, fletcher16_checksum, fletcher16_verify

IRDP_DISCRIMINATOR = 0x83
SYSID_LEN = 6
LSP_MAX_AGE = 1200
LSP_REFRESH = 900


class PduType(enum.IntEnum):
    HELLO_LAN_L1 = 15
    HELLO_LAN_L2 = 16
    HELLO_P2P = 17
    LSP_L1 = 18
    LSP_L2 = 20
    CSNP_L1 = 24
    CSNP_L2 = 25
    PSNP_L1 = 26
    PSNP_L2 = 27


class TlvType(enum.IntEnum):
    AREA_ADDRESSES = 1
    IS_REACH = 2  # ISO 10589 narrow-metric IS reachability
    IS_NEIGHBORS = 6  # LAN hellos: heard SNPAs
    IP_INTERNAL_REACH = 128  # RFC 1195 narrow-metric IP reachability
    PROTOCOLS_SUPPORTED = 129
    IP_EXTERNAL_REACH = 130
    IP_INTERFACE_ADDRESS = 132
    EXT_IS_REACH = 22
    EXT_IP_REACH = 135
    DYNAMIC_HOSTNAME = 137  # RFC 5301
    MT_IS_REACH = 222  # RFC 5120 multi-topology
    MULTI_TOPOLOGY = 229
    IPV6_INTERFACE_ADDRESS = 232  # RFC 5308
    MT_IP_REACH = 235
    IPV6_REACH = 236
    MT_IPV6_REACH = 237
    LSP_ENTRIES = 9
    P2P_ADJ_STATE = 240  # RFC 5303 three-way handshake


@dataclass(frozen=True)
class LspId:
    sysid: bytes  # 6 bytes
    pseudonode: int = 0
    fragment: int = 0

    def encode(self) -> bytes:
        return self.sysid + bytes((self.pseudonode, self.fragment))

    @classmethod
    def decode(cls, b: bytes) -> "LspId":
        if len(b) != 8:
            raise DecodeError("bad LSP id")
        return cls(b[:6], b[6], b[7])

    def __lt__(self, other):
        return self.encode() < other.encode()


@dataclass(frozen=True)
class ExtIsReach:
    neighbor: bytes  # sysid + pseudonode byte (7 bytes)
    metric: int


@dataclass(frozen=True)
class ExtIpReach:
    prefix: IPv4Network | IPv6Network  # v6 when carried in TLV 236
    metric: int
    up_down: bool = False
    # RFC 1195 internal/external distinction (narrow TLV 130 or the I/E
    # metric bit); wide TLVs dropped it, so False there.
    external: bool = False


class AdjState3Way(enum.IntEnum):
    UP = 0
    INITIALIZING = 1
    DOWN = 2


@dataclass
class P2pAdjState:
    state: AdjState3Way
    ext_circuit_id: int = 0
    neighbor_sysid: bytes | None = None
    neighbor_ext_circuit_id: int | None = None


def _encode_tlvs(w: Writer, tlvs: dict) -> None:
    if tlvs.get("area_addresses"):
        body = b"".join(bytes((len(a),)) + a for a in tlvs["area_addresses"])
        w.u8(TlvType.AREA_ADDRESSES).u8(len(body)).bytes(body)
    if tlvs.get("is_neighbors"):
        body = b"".join(tlvs["is_neighbors"])  # 6-byte SNPAs
        w.u8(TlvType.IS_NEIGHBORS).u8(len(body)).bytes(body)
    if tlvs.get("protocols_supported"):
        body = bytes(tlvs["protocols_supported"])
        w.u8(TlvType.PROTOCOLS_SUPPORTED).u8(len(body)).bytes(body)
    if tlvs.get("ip_addresses"):
        body = b"".join(a.packed for a in tlvs["ip_addresses"])
        w.u8(TlvType.IP_INTERFACE_ADDRESS).u8(len(body)).bytes(body)
    if tlvs.get("ipv6_addresses"):
        body = b"".join(a.packed for a in tlvs["ipv6_addresses"])
        w.u8(TlvType.IPV6_INTERFACE_ADDRESS).u8(len(body)).bytes(body)
    if tlvs.get("hostname"):
        body = tlvs["hostname"].encode("ascii", "replace")
        w.u8(TlvType.DYNAMIC_HOSTNAME).u8(len(body)).bytes(body)
    if tlvs.get("p2p_adj") is not None:
        adj: P2pAdjState = tlvs["p2p_adj"]
        body = bytes((int(adj.state),)) + adj.ext_circuit_id.to_bytes(4, "big")
        if adj.neighbor_sysid is not None:
            body += adj.neighbor_sysid
            body += (adj.neighbor_ext_circuit_id or 0).to_bytes(4, "big")
        w.u8(TlvType.P2P_ADJ_STATE).u8(len(body)).bytes(body)
    for reach in _chunks(tlvs.get("ext_is_reach", []), 23):
        body = b""
        for r in reach:
            body += r.neighbor + r.metric.to_bytes(3, "big") + b"\x00"
        w.u8(TlvType.EXT_IS_REACH).u8(len(body)).bytes(body)
    for reach in _chunks(tlvs.get("ext_ip_reach", []), 20):
        body = b""
        for r in reach:
            ctrl = (0x80 if r.up_down else 0) | r.prefix.prefixlen
            plen_bytes = (r.prefix.prefixlen + 7) // 8
            body += r.metric.to_bytes(4, "big") + bytes((ctrl,))
            body += r.prefix.network_address.packed[:plen_bytes]
        w.u8(TlvType.EXT_IP_REACH).u8(len(body)).bytes(body)
    # Max 11 entries per TLV: a full-length /128 entry is 22 bytes and
    # the TLV length octet caps the body at 255 (11*22=242).
    for reach in _chunks(tlvs.get("ipv6_reach", []), 11):
        body = b""
        for r in reach:
            ctrl = 0x80 if r.up_down else 0
            plen_bytes = (r.prefix.prefixlen + 7) // 8
            body += r.metric.to_bytes(4, "big")
            body += bytes((ctrl, r.prefix.prefixlen))
            body += r.prefix.network_address.packed[:plen_bytes]
        w.u8(TlvType.IPV6_REACH).u8(len(body)).bytes(body)
    if tlvs.get("lsp_entries"):
        for chunk in _chunks(tlvs["lsp_entries"], 15):
            body = b""
            for lifetime, lsp_id, seqno, cksum in chunk:
                body += lifetime.to_bytes(2, "big") + lsp_id.encode()
                body += seqno.to_bytes(4, "big") + cksum.to_bytes(2, "big")
            w.u8(TlvType.LSP_ENTRIES).u8(len(body)).bytes(body)


def _chunks(seq, n):
    seq = list(seq)
    return [seq[i : i + n] for i in range(0, len(seq), n)] if seq else []


def _read_wide_is_entries(body: Reader, out: list) -> None:
    """TLV 22/222 entry stream: 7B neighbor + 3B metric + sub-TLVs."""
    while body.remaining() >= 11:
        nbr = body.bytes(7)
        metric = body.u24()
        sub_len = body.u8()
        body.bytes(min(sub_len, body.remaining()))
        out.append(ExtIsReach(nbr, metric))


def _read_wide_ip_entries(body: Reader, out: list) -> None:
    """TLV 135/235 entry stream: u32 metric + ctrl + truncated prefix."""
    while body.remaining() >= 5:
        metric = body.u32()
        ctrl = body.u8()
        plen = ctrl & 0x3F
        if plen > 32:
            raise DecodeError("bad prefix length")
        nbytes = (plen + 7) // 8
        raw = body.bytes(nbytes) + bytes(4 - nbytes)
        if ctrl & 0x40:  # sub-TLVs present
            sl = body.u8()
            body.bytes(min(sl, body.remaining()))
        prefix = IPv4Network((int.from_bytes(raw, "big"), plen))
        out.append(ExtIpReach(prefix, metric, bool(ctrl & 0x80)))


def _read_ipv6_entries(body: Reader, out: list) -> None:
    """TLV 236/237 entry stream (RFC 5308 §2): metric u32, control byte
    (U/X/S), prefix-len, truncated prefix, optional sub-TLVs."""
    while body.remaining() >= 6:
        metric = body.u32()
        ctrl = body.u8()
        plen = body.u8()
        if plen > 128:
            raise DecodeError("bad v6 prefix length")
        nbytes = (plen + 7) // 8
        raw = body.bytes(nbytes) + bytes(16 - nbytes)
        if ctrl & 0x20:  # sub-TLVs present
            sl = body.u8()
            body.bytes(min(sl, body.remaining()))
        prefix = IPv6Network((int.from_bytes(raw, "big"), plen))
        out.append(ExtIpReach(prefix, metric, bool(ctrl & 0x80)))


def _decode_tlvs(r: Reader) -> dict:
    out: dict = {
        "area_addresses": [],
        "is_neighbors": [],
        "protocols_supported": [],
        "ip_addresses": [],
        "ipv6_addresses": [],
        "ext_is_reach": [],
        "ext_ip_reach": [],
        "ipv6_reach": [],
        # RFC 5120 multi-topology: (mt_id, att, ovl) / (mt_id, entry).
        "mt_ids": [],
        "mt_is_reach": [],
        "mt_ip_reach": [],
        "mt_ipv6_reach": [],
        "hostname": None,
        "lsp_entries": [],
        "p2p_adj": None,
    }
    while r.remaining() >= 2:
        t = r.u8()
        length = r.u8()
        body = r.sub(length)
        if t == TlvType.AREA_ADDRESSES:
            while body.remaining() >= 1:
                n = body.u8()
                out["area_addresses"].append(body.bytes(n))
        elif t == TlvType.IS_NEIGHBORS:
            while body.remaining() >= 6:
                out["is_neighbors"].append(body.bytes(6))
        elif t == TlvType.PROTOCOLS_SUPPORTED:
            out["protocols_supported"] = list(body.rest())
        elif t == TlvType.IP_INTERFACE_ADDRESS:
            while body.remaining() >= 4:
                out["ip_addresses"].append(body.ipv4())
        elif t == TlvType.P2P_ADJ_STATE:
            try:
                state = AdjState3Way(body.u8())
            except ValueError as e:
                raise DecodeError("bad 3-way state") from e
            ext_id = int.from_bytes(body.bytes(4), "big")
            nbr_sys = nbr_ext = None
            if body.remaining() >= 10:
                nbr_sys = body.bytes(6)
                nbr_ext = int.from_bytes(body.bytes(4), "big")
            out["p2p_adj"] = P2pAdjState(state, ext_id, nbr_sys, nbr_ext)
        elif t == TlvType.IS_REACH:
            # ISO 10589 §9.8: virtual-flag byte, then 11-byte entries of
            # four metric octets + 7-byte neighbor id.  Only the default
            # metric (low 6 bits) is used; decoded into the same unified
            # reach list the wide TLV (22) fills.
            if body.remaining() >= 1:
                body.u8()  # virtual flag
            while body.remaining() >= 11:
                metric = body.u8() & 0x3F
                body.bytes(3)  # delay/expense/error metrics (unsupported)
                nbr = body.bytes(7)
                out["ext_is_reach"].append(ExtIsReach(nbr, metric))
        elif t in (TlvType.IP_INTERNAL_REACH, TlvType.IP_EXTERNAL_REACH):
            # RFC 1195 §3.2: 12-byte entries of four metric octets +
            # address + mask.  Bit 6 of the default metric is I/E.
            while body.remaining() >= 12:
                m = body.u8()
                body.bytes(3)
                addr = int.from_bytes(body.bytes(4), "big")
                mask = int.from_bytes(body.bytes(4), "big")
                plen = bin(mask).count("1")
                prefix = IPv4Network((addr & mask, plen))
                external = (
                    t == TlvType.IP_EXTERNAL_REACH or bool(m & 0x40)
                )
                out["ext_ip_reach"].append(
                    ExtIpReach(prefix, m & 0x3F, external=external)
                )
        elif t == TlvType.EXT_IS_REACH:
            _read_wide_is_entries(body, out["ext_is_reach"])
        elif t == TlvType.EXT_IP_REACH:
            _read_wide_ip_entries(body, out["ext_ip_reach"])
        elif t == TlvType.IPV6_INTERFACE_ADDRESS:
            while body.remaining() >= 16:
                out["ipv6_addresses"].append(body.ipv6())
        elif t == TlvType.DYNAMIC_HOSTNAME:
            out["hostname"] = body.rest().decode("ascii", "replace")
        elif t == TlvType.IPV6_REACH:
            _read_ipv6_entries(body, out["ipv6_reach"])
        elif t == TlvType.MULTI_TOPOLOGY:
            # RFC 5120 §7.1: u16 per topology — O(15) A(14) + 12-bit id.
            while body.remaining() >= 2:
                v = body.u16()
                out["mt_ids"].append(
                    (v & 0x0FFF, bool(v & 0x4000), bool(v & 0x8000))
                )
        elif t in (TlvType.MT_IS_REACH, TlvType.MT_IP_REACH,
                   TlvType.MT_IPV6_REACH):
            # RFC 5120 §7.2-7.4: 12-bit MT id, then the same entry stream
            # as the corresponding single-topology TLV (22/135/236).
            mt_id = body.u16() & 0x0FFF
            entries: list = []
            if t == TlvType.MT_IS_REACH:
                _read_wide_is_entries(body, entries)
                out["mt_is_reach"].extend((mt_id, e) for e in entries)
            elif t == TlvType.MT_IP_REACH:
                _read_wide_ip_entries(body, entries)
                out["mt_ip_reach"].extend((mt_id, e) for e in entries)
            else:
                _read_ipv6_entries(body, entries)
                out["mt_ipv6_reach"].extend((mt_id, e) for e in entries)
        elif t == TlvType.LSP_ENTRIES:
            while body.remaining() >= 16:
                lifetime = body.u16()
                lsp_id = LspId.decode(body.bytes(8))
                seqno = body.u32()
                cksum = body.u16()
                out["lsp_entries"].append((lifetime, lsp_id, seqno, cksum))
        # unknown TLVs skipped (body already consumed)
    return out


def _pdu_header(w: Writer, pdu_type: PduType, hdr_len: int) -> None:
    w.u8(IRDP_DISCRIMINATOR).u8(hdr_len).u8(1).u8(0)
    w.u8(int(pdu_type)).u8(1).u8(0).u8(0)


def _check_header(r: Reader) -> PduType:
    if r.u8() != IRDP_DISCRIMINATOR:
        raise DecodeError("not an IS-IS PDU")
    r.u8()  # header length
    if r.u8() != 1:
        raise DecodeError("bad protocol version")
    r.u8()  # sysid len (0 = 6)
    try:
        pdu_type = PduType(r.u8() & 0x1F)
    except ValueError as e:
        raise DecodeError("unknown PDU type") from e
    r.u8()
    r.u8()
    r.u8()
    return pdu_type


@dataclass
class HelloP2p:
    circuit_type: int  # 1=L1, 2=L2, 3=L1L2
    sysid: bytes
    hold_time: int
    local_circuit_id: int
    tlvs: dict = field(default_factory=dict)

    TYPE = PduType.HELLO_P2P

    def encode(self) -> bytes:
        w = Writer()
        _pdu_header(w, self.TYPE, 20)
        w.u8(self.circuit_type).bytes(self.sysid)
        w.u16(self.hold_time)
        len_pos = len(w)
        w.u16(0)
        w.u8(self.local_circuit_id)
        _encode_tlvs(w, self.tlvs)
        w.patch_u16(len_pos, len(w))
        return w.finish()

    @classmethod
    def decode_body(cls, r: Reader) -> "HelloP2p":
        ct = r.u8() & 0x3
        sysid = r.bytes(SYSID_LEN)
        hold = r.u16()
        r.u16()  # pdu length
        circuit_id = r.u8()
        return cls(ct, sysid, hold, circuit_id, _decode_tlvs(r))


@dataclass
class HelloLan:
    """LAN IIH (ISO 10589 §9.5/9.6): priority + LAN ID for DIS election."""

    circuit_type: int
    sysid: bytes
    hold_time: int
    priority: int
    lan_id: bytes  # DIS sysid + pseudonode byte (7 bytes)
    level: int = 2
    tlvs: dict = field(default_factory=dict)

    @property
    def TYPE(self):
        return PduType.HELLO_LAN_L2 if self.level == 2 else PduType.HELLO_LAN_L1

    def encode(self) -> bytes:
        w = Writer()
        _pdu_header(w, self.TYPE, 27)
        w.u8(self.circuit_type).bytes(self.sysid)
        w.u16(self.hold_time)
        len_pos = len(w)
        w.u16(0)
        w.u8(self.priority & 0x7F)
        w.bytes(self.lan_id)
        _encode_tlvs(w, self.tlvs)
        w.patch_u16(len_pos, len(w))
        return w.finish()

    @classmethod
    def decode_body(cls, r: Reader, level: int) -> "HelloLan":
        ct = r.u8() & 0x3
        sysid = r.bytes(SYSID_LEN)
        hold = r.u16()
        r.u16()  # pdu length
        prio = r.u8() & 0x7F
        lan_id = r.bytes(7)
        return cls(ct, sysid, hold, prio, lan_id, level, _decode_tlvs(r))


@dataclass
class Lsp:
    level: int  # 1 or 2
    lifetime: int
    lsp_id: LspId
    seqno: int
    flags: int = 0x03  # IS-type bits (L2)
    tlvs: dict = field(default_factory=dict)
    cksum: int = 0
    raw: bytes = b""

    @property
    def is_expired(self) -> bool:
        return self.lifetime == 0

    def encode(self) -> bytes:
        w = Writer()
        _pdu_header(w, PduType.LSP_L2 if self.level == 2 else PduType.LSP_L1, 27)
        len_pos = len(w)
        w.u16(0)  # pdu length
        w.u16(self.lifetime)
        w.bytes(self.lsp_id.encode())
        w.u32(self.seqno)
        cks_pos = len(w)
        w.u16(0)
        w.u8(self.flags)
        _encode_tlvs(w, self.tlvs)
        w.patch_u16(len_pos, len(w))
        # ISO 10589 §7.3.11: checksum over lsp_id..end (offset 12 in PDU).
        cks = fletcher16_checksum(bytes(w.buf[12:]), cks_pos - 12)
        w.patch_u16(cks_pos, cks)
        self.cksum = cks
        self.raw = w.finish()
        return self.raw

    @classmethod
    def decode_body(cls, r: Reader, level: int, raw: bytes) -> "Lsp":
        pdu_len = r.u16()
        if pdu_len > len(raw):
            raise DecodeError("bad LSP length")
        lifetime = r.u16()
        lsp_id = LspId.decode(r.bytes(8))
        seqno = r.u32()
        cksum = r.u16()
        flags = r.u8()
        if lifetime > 0 and not fletcher16_verify(raw[12:pdu_len]):
            raise DecodeError("LSP checksum mismatch")
        tlvs = _decode_tlvs(Reader(raw, r.pos, pdu_len))
        return cls(level, lifetime, lsp_id, seqno, flags, tlvs, cksum, raw[:pdu_len])

    def compare(self, lifetime: int, seqno: int, cksum: int) -> int:
        """ISO 10589 §7.3.16: newer comparison vs a summary tuple."""
        if self.seqno != seqno:
            return 1 if self.seqno > seqno else -1
        if (self.lifetime == 0) != (lifetime == 0):
            return 1 if self.lifetime == 0 else -1
        if self.cksum != cksum:
            return 1 if self.cksum > cksum else -1
        return 0


@dataclass
class Snp:
    """CSNP (complete, with range) or PSNP (partial)."""

    level: int
    complete: bool
    sysid: bytes
    entries: list = field(default_factory=list)  # (lifetime, LspId, seqno, cksum)
    start: LspId | None = None
    end: LspId | None = None

    def encode(self) -> bytes:
        w = Writer()
        if self.complete:
            t = PduType.CSNP_L2 if self.level == 2 else PduType.CSNP_L1
            _pdu_header(w, t, 33)
        else:
            t = PduType.PSNP_L2 if self.level == 2 else PduType.PSNP_L1
            _pdu_header(w, t, 17)
        len_pos = len(w)
        w.u16(0)
        w.bytes(self.sysid + b"\x00")  # source id (sysid + circuit 0)
        if self.complete:
            w.bytes((self.start or LspId(b"\x00" * 6)).encode())
            w.bytes((self.end or LspId(b"\xff" * 6, 0xFF, 0xFF)).encode())
        _encode_tlvs(w, {"lsp_entries": self.entries})
        w.patch_u16(len_pos, len(w))
        return w.finish()

    @classmethod
    def decode_body(cls, r: Reader, level: int, complete: bool) -> "Snp":
        r.u16()  # pdu length
        src = r.bytes(7)
        start = end = None
        if complete:
            start = LspId.decode(r.bytes(8))
            end = LspId.decode(r.bytes(8))
        tlvs = _decode_tlvs(r)
        return cls(level, complete, src[:6], tlvs["lsp_entries"], start, end)


def decode_pdu(data: bytes):
    """Top-level dispatch; returns (PduType, object)."""
    r = Reader(data)
    pdu_type = _check_header(r)
    if pdu_type == PduType.HELLO_P2P:
        return pdu_type, HelloP2p.decode_body(r)
    if pdu_type in (PduType.HELLO_LAN_L1, PduType.HELLO_LAN_L2):
        level = 2 if pdu_type == PduType.HELLO_LAN_L2 else 1
        return pdu_type, HelloLan.decode_body(r, level)
    if pdu_type in (PduType.LSP_L1, PduType.LSP_L2):
        level = 2 if pdu_type == PduType.LSP_L2 else 1
        return pdu_type, Lsp.decode_body(r, level, data)
    if pdu_type in (PduType.CSNP_L1, PduType.CSNP_L2):
        level = 2 if pdu_type == PduType.CSNP_L2 else 1
        return pdu_type, Snp.decode_body(r, level, True)
    if pdu_type in (PduType.PSNP_L1, PduType.PSNP_L2):
        level = 2 if pdu_type == PduType.PSNP_L2 else 1
        return pdu_type, Snp.decode_body(r, level, False)
    raise DecodeError("unhandled PDU type")

"""IS-IS PDU and TLV codecs (ISO 10589 §9; RFCs 1195, 5303, 5305).

Reference: holo-isis packet layer.  System IDs are 6 bytes; LSP IDs are
sysid + pseudonode byte + fragment byte.  Wide metrics only.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from ipaddress import IPv4Address, IPv4Network, IPv6Network

from holo_tpu.utils.bytesbuf import DecodeError, Reader, Writer, fletcher16_checksum, fletcher16_verify


class AuthError(DecodeError):
    """Authentication verification failed (bad digest / unknown key)."""


class AuthTypeError(AuthError):
    """Authentication TLV missing or of the wrong type."""

IRDP_DISCRIMINATOR = 0x83
SYSID_LEN = 6
LSP_MAX_AGE = 1200
LSP_REFRESH = 900


class PduType(enum.IntEnum):
    HELLO_LAN_L1 = 15
    HELLO_LAN_L2 = 16
    HELLO_P2P = 17
    LSP_L1 = 18
    LSP_L2 = 20
    CSNP_L1 = 24
    CSNP_L2 = 25
    PSNP_L1 = 26
    PSNP_L2 = 27


class TlvType(enum.IntEnum):
    AREA_ADDRESSES = 1
    IS_REACH = 2  # ISO 10589 narrow-metric IS reachability
    IS_NEIGHBORS = 6  # LAN hellos: heard SNPAs
    EXTENDED_SEQNUM = 11  # RFC 7602
    PURGE_ORIGINATOR = 13  # RFC 6232
    LSP_BUFFER_SIZE = 14  # ISO 10589 §9.8 originating-LSP-buffer-size
    IP_INTERNAL_REACH = 128  # RFC 1195 narrow-metric IP reachability
    PROTOCOLS_SUPPORTED = 129
    IP_EXTERNAL_REACH = 130
    IP_INTERFACE_ADDRESS = 132
    EXT_IS_REACH = 22
    EXT_IP_REACH = 135
    DYNAMIC_HOSTNAME = 137  # RFC 5301
    IPV4_ROUTER_ID = 134  # RFC 5305 TE router id
    IPV6_ROUTER_ID = 140  # RFC 6119
    MT_IS_REACH = 222  # RFC 5120 multi-topology
    MULTI_TOPOLOGY = 229
    IPV6_INTERFACE_ADDRESS = 232  # RFC 5308
    MT_IP_REACH = 235
    IPV6_REACH = 236
    MT_IPV6_REACH = 237
    LSP_ENTRIES = 9
    P2P_ADJ_STATE = 240  # RFC 5303 three-way handshake
    AUTHENTICATION = 10  # RFC 5304 (HMAC-MD5) / RFC 5310 (generic crypto)
    ROUTER_CAPABILITY = 242  # RFC 7981 (carries the RFC 8667 SR caps)


@dataclass(frozen=True)
class LspId:
    sysid: bytes  # 6 bytes
    pseudonode: int = 0
    fragment: int = 0

    def encode(self) -> bytes:
        return self.sysid + bytes((self.pseudonode, self.fragment))

    @classmethod
    def decode(cls, b: bytes) -> "LspId":
        if len(b) != 8:
            raise DecodeError("bad LSP id")
        return cls(b[:6], b[6], b[7])

    def __lt__(self, other):
        return self.encode() < other.encode()


@dataclass(frozen=True)
class ExtIsReach:
    neighbor: bytes  # sysid + pseudonode byte (7 bytes)
    metric: int
    # RFC 8491 Link MSD sub-TLV: ((msd-type, value), ...) or None.
    link_msd: tuple | None = None
    # RFC 8667 §2.2 Adjacency-SIDs: ((flags, weight, label), ...).
    # Flags: F=0x80 B=0x40 V=0x20 L=0x10 S=0x08 P=0x04.
    adj_sids: tuple | None = None


@dataclass(frozen=True)
class ExtIpReach:
    prefix: IPv4Network | IPv6Network  # v6 when carried in TLV 236
    metric: int
    up_down: bool = False
    # RFC 1195 internal/external distinction (narrow TLV 130 or the I/E
    # metric bit); wide TLVs dropped it, so False there.
    external: bool = False
    # RFC 8667 §2.1 Prefix-SID sub-TLV (index form) when not None.
    sid_index: int | None = None
    sid_flags: int = 0  # R=0x80 N=0x40 P=0x20 E=0x10 V=0x08 L=0x04
    # RFC 7794 prefix attributes (wide v4 + v6 only): raw flags byte
    # (X=0x80 external, R=0x40 re-advertisement, N=0x20 node) and the
    # source-router-id sub-TLVs.
    attr_flags: int | None = None
    src_rid4: IPv4Address | None = None
    src_rid6: object = None  # IPv6Address

PREFIX_ATTR_X = 0x80
PREFIX_ATTR_R = 0x40
PREFIX_ATTR_N = 0x20
MAX_NARROW_METRIC = 63


class AdjState3Way(enum.IntEnum):
    UP = 0
    INITIALIZING = 1
    DOWN = 2


@dataclass
class P2pAdjState:
    state: AdjState3Way
    ext_circuit_id: int = 0
    neighbor_sysid: bytes | None = None
    neighbor_ext_circuit_id: int | None = None


def _encode_tlvs(w: Writer, tlvs: dict) -> None:
    """TLV emission in the reference's serialization order
    (holo-isis/src/packet/pdu.rs LspTlvs/HelloTlvs field order) so that
    re-encoded and self-originated LSPs are byte-identical to the
    reference's — the conformance corpus's recorded SNP checksums
    assert this.  ``protocols_supported`` distinguishes present-but-
    empty ([] -> empty TLV, as in pseudonode LSPs) from absent (None).
    """
    if tlvs.get("protocols_supported") is not None:
        body = bytes(tlvs["protocols_supported"])
        w.u8(TlvType.PROTOCOLS_SUPPORTED).u8(len(body)).bytes(body)
    if (
        tlvs.get("sr_cap")
        or tlvs.get("srlb")
        or tlvs.get("node_tags")
        or tlvs.get("node_msd")
        or tlvs.get("cap_router_id") is not None
    ):
        # Router Capability (RFC 7981): router id + flags, then the
        # RFC 8667 §3.1 SR-Capabilities sub-TLV (flags + one SRGB
        # descriptor: range u24 + SID/Label sub-TLV type 1 with the base
        # label) and/or the RFC 7917 node-admin-tag sub-TLV (type 21).
        rid = tlvs.get("cap_router_id")
        body = (rid.packed if rid is not None else bytes(4))
        body += bytes((0,))  # capability flags
        if tlvs.get("sr_cap"):
            srgb_base, srgb_range = tlvs["sr_cap"]
            sub = bytes((0xC0,))  # I+V flags: MPLS v4+v6
            sub += srgb_range.to_bytes(3, "big")
            sub += bytes((1, 3)) + srgb_base.to_bytes(3, "big")
            body += bytes((2, len(sub))) + sub
            # SR-Algorithm sub-TLV (19): SPF only.
            body += bytes((19, 1, 0))
        if tlvs.get("srlb"):
            lb_base, lb_range = tlvs["srlb"]
            sub = bytes((0,))  # reserved flags
            sub += lb_range.to_bytes(3, "big")
            sub += bytes((1, 3)) + lb_base.to_bytes(3, "big")
            body += bytes((22, len(sub))) + sub
        if tlvs.get("node_tags"):
            sub = b"".join(t.to_bytes(4, "big") for t in tlvs["node_tags"])
            body += bytes((21, len(sub))) + sub
        if tlvs.get("node_msd"):
            # RFC 8491 Node MSD sub-TLV: (type, value) octet pairs.
            sub = b"".join(
                bytes((int(t), v)) for t, v in sorted(tlvs["node_msd"].items())
            )
            body += bytes((23, len(sub))) + sub
        w.u8(TlvType.ROUTER_CAPABILITY).u8(len(body)).bytes(body)
    if tlvs.get("area_addresses"):
        body = b"".join(bytes((len(a),)) + a for a in tlvs["area_addresses"])
        w.u8(TlvType.AREA_ADDRESSES).u8(len(body)).bytes(body)
    if tlvs.get("mt_ids"):
        # RFC 5120 §7.1: u16 per member topology — O(15) A(14) + 12-bit id.
        body = b"".join(
            (
                (0x8000 if ovl else 0)
                | (0x4000 if att else 0)
                | (mt_id & 0x0FFF)
            ).to_bytes(2, "big")
            for mt_id, att, ovl in tlvs["mt_ids"]
        )
        w.u8(TlvType.MULTI_TOPOLOGY).u8(len(body)).bytes(body)
    if tlvs.get("purge_originator"):
        ids = tlvs["purge_originator"]
        body = bytes((len(ids),)) + b"".join(ids)
        w.u8(TlvType.PURGE_ORIGINATOR).u8(len(body)).bytes(body)
    if tlvs.get("hostname"):
        body = tlvs["hostname"].encode("ascii", "replace")
        w.u8(TlvType.DYNAMIC_HOSTNAME).u8(len(body)).bytes(body)
    if tlvs.get("lsp_buf_size"):
        w.u8(TlvType.LSP_BUFFER_SIZE).u8(2).u16(tlvs["lsp_buf_size"])
    if tlvs.get("is_neighbors"):
        body = b"".join(tlvs["is_neighbors"])  # 6-byte SNPAs
        w.u8(TlvType.IS_NEIGHBORS).u8(len(body)).bytes(body)
    if tlvs.get("p2p_adj") is not None:
        adj: P2pAdjState = tlvs["p2p_adj"]
        body = bytes((int(adj.state),)) + adj.ext_circuit_id.to_bytes(4, "big")
        if adj.neighbor_sysid is not None:
            body += adj.neighbor_sysid
            body += (adj.neighbor_ext_circuit_id or 0).to_bytes(4, "big")
        w.u8(TlvType.P2P_ADJ_STATE).u8(len(body)).bytes(body)
    # ISO 10589 narrow-metric IS reach (TLV 2): virtual-flag byte + 11-byte
    # entries; the three QoS metrics are always unsupported (S bit 0x80).
    if tlvs.get("narrow_is_reach"):
        for chunk in _chunks(tlvs["narrow_is_reach"], 22):
            body = b"\x00"  # virtual flag
            for r in chunk:
                body += bytes((r.metric & 0x3F, 0x80, 0x80, 0x80)) + r.neighbor
            w.u8(TlvType.IS_REACH).u8(len(body)).bytes(body)
    def _is_entry(r) -> bytes:
        sub = b""
        for flags, weight, label in getattr(r, "adj_sids", None) or ():
            body31 = bytes((flags, weight)) + label.to_bytes(3, "big")
            sub += bytes((31, len(body31))) + body31
        if getattr(r, "link_msd", None):
            msd = b"".join(bytes((int(t), v)) for t, v in r.link_msd)
            sub += bytes((15, len(msd))) + msd
        return (
            r.neighbor + r.metric.to_bytes(3, "big")
            + bytes((len(sub),)) + sub
        )

    body = b""
    for r in tlvs.get("ext_is_reach", []):
        enc = _is_entry(r)
        if body and len(body) + len(enc) > 255:
            w.u8(TlvType.EXT_IS_REACH).u8(len(body)).bytes(body)
            body = b""
        body += enc
    if body:
        w.u8(TlvType.EXT_IS_REACH).u8(len(body)).bytes(body)
    # RFC 5120 §7.2/7.4: MT-prefixed variants of the reach TLVs.  Entries
    # arrive as [(mt_id, entry)]; group per topology, chunk like the
    # single-topology TLVs.
    _mt_is_groups: dict = {}
    for mt_id, entry in tlvs.get("mt_is_reach", []):
        _mt_is_groups.setdefault(mt_id, []).append(entry)
    for mt_id, entries in _mt_is_groups.items():
        for chunk in _chunks(entries, 23):
            body = (mt_id & 0x0FFF).to_bytes(2, "big")
            for r in chunk:
                body += r.neighbor + r.metric.to_bytes(3, "big") + b"\x00"
            w.u8(TlvType.MT_IS_REACH).u8(len(body)).bytes(body)
    if tlvs.get("ip_addresses"):
        body = b"".join(a.packed for a in tlvs["ip_addresses"])
        w.u8(TlvType.IP_INTERFACE_ADDRESS).u8(len(body)).bytes(body)
    # RFC 1195 narrow-metric IP reach (TLV 128 internal / 130 external).
    for key, tlv_type in (
        ("narrow_ip_reach", TlvType.IP_INTERNAL_REACH),
        ("narrow_ip_ext_reach", TlvType.IP_EXTERNAL_REACH),
    ):
        for chunk in _chunks(tlvs.get(key, []), 21):
            body = b""
            for r in chunk:
                m = (r.metric & 0x3F) | (
                    0x40 if r.external and key == "narrow_ip_reach" else 0
                )
                body += bytes((m, 0x80, 0x80, 0x80))
                body += r.prefix.network_address.packed
                body += r.prefix.netmask.packed
            w.u8(tlv_type).u8(len(body)).bytes(body)

    def _prefix_subtlvs(r) -> bytes:
        """RFC 7794 attr-flags/source-rid + RFC 8667 prefix-SID block."""
        sub = b""
        if getattr(r, "attr_flags", None) is not None:
            sub += bytes((4, 1, r.attr_flags))
        if getattr(r, "src_rid4", None) is not None:
            sub += bytes((11, 4)) + r.src_rid4.packed
        if getattr(r, "src_rid6", None) is not None:
            sub += bytes((12, 16)) + r.src_rid6.packed
        if getattr(r, "sid_index", None) is not None:
            # Prefix-SID sub-TLV (type 3): flags, algo 0, u32 index.
            sub += bytes((3, 6, getattr(r, "sid_flags", 0), 0))
            sub += r.sid_index.to_bytes(4, "big")
        return sub

    def _wide_ip_entry(r) -> bytes:
        sub = _prefix_subtlvs(r)
        ctrl = (
            (0x80 if r.up_down else 0)
            | (0x40 if sub else 0)
            | r.prefix.prefixlen
        )
        plen_bytes = (r.prefix.prefixlen + 7) // 8
        out = r.metric.to_bytes(4, "big") + bytes((ctrl,))
        out += r.prefix.network_address.packed[:plen_bytes]
        if sub:
            out += bytes((len(sub),)) + sub
        return out

    # Chunk by ENCODED size (entries vary 5..30 bytes with sub-TLVs; the
    # one-byte TLV length caps the body at 255).
    body = b""
    for r in tlvs.get("ext_ip_reach", []):
        enc = _wide_ip_entry(r)
        if body and len(body) + len(enc) > 255:
            w.u8(TlvType.EXT_IP_REACH).u8(len(body)).bytes(body)
            body = b""
        body += enc
    if body:
        w.u8(TlvType.EXT_IP_REACH).u8(len(body)).bytes(body)
    if tlvs.get("ipv4_router_id") is not None:
        w.u8(TlvType.IPV4_ROUTER_ID).u8(4).bytes(tlvs["ipv4_router_id"].packed)
    if tlvs.get("ipv6_addresses"):
        body = b"".join(a.packed for a in tlvs["ipv6_addresses"])
        w.u8(TlvType.IPV6_INTERFACE_ADDRESS).u8(len(body)).bytes(body)
    if tlvs.get("ext_seqnum"):
        session, packet = tlvs["ext_seqnum"]
        w.u8(TlvType.EXTENDED_SEQNUM).u8(12)
        w.bytes(session.to_bytes(8, "big") + packet.to_bytes(4, "big"))

    def _v6_entry(r) -> bytes:
        sub = _prefix_subtlvs(r)
        ctrl = (
            (0x80 if r.up_down else 0)
            | (0x40 if r.external else 0)
            | (0x20 if sub else 0)
        )
        plen_bytes = (r.prefix.prefixlen + 7) // 8
        out = r.metric.to_bytes(4, "big")
        out += bytes((ctrl, r.prefix.prefixlen))
        out += r.prefix.network_address.packed[:plen_bytes]
        if sub:
            out += bytes((len(sub),)) + sub
        return out

    body = b""
    for r in tlvs.get("ipv6_reach", []):
        enc = _v6_entry(r)
        if body and len(body) + len(enc) > 255:
            w.u8(TlvType.IPV6_REACH).u8(len(body)).bytes(body)
            body = b""
        body += enc
    if body:
        w.u8(TlvType.IPV6_REACH).u8(len(body)).bytes(body)
    _mt_v6_groups: dict = {}
    for mt_id, entry in tlvs.get("mt_ipv6_reach", []):
        _mt_v6_groups.setdefault(mt_id, []).append(entry)
    for mt_id, entries in _mt_v6_groups.items():
        body = (mt_id & 0x0FFF).to_bytes(2, "big")
        for r in entries:
            enc = _v6_entry(r)
            if len(body) + len(enc) > 255:
                w.u8(TlvType.MT_IPV6_REACH).u8(len(body)).bytes(body)
                body = (mt_id & 0x0FFF).to_bytes(2, "big")
            body += enc
        if len(body) > 2:
            w.u8(TlvType.MT_IPV6_REACH).u8(len(body)).bytes(body)
    if tlvs.get("ipv6_router_id") is not None:
        w.u8(TlvType.IPV6_ROUTER_ID).u8(16).bytes(tlvs["ipv6_router_id"].packed)
    if tlvs.get("lsp_entries"):
        for chunk in _chunks(tlvs["lsp_entries"], 15):
            body = b""
            for lifetime, lsp_id, seqno, cksum in chunk:
                body += lifetime.to_bytes(2, "big") + lsp_id.encode()
                body += seqno.to_bytes(4, "big") + cksum.to_bytes(2, "big")
            w.u8(TlvType.LSP_ENTRIES).u8(len(body)).bytes(body)


def _chunks(seq, n):
    seq = list(seq)
    return [seq[i : i + n] for i in range(0, len(seq), n)] if seq else []


def _read_wide_is_entries(body: Reader, out: list) -> None:
    """TLV 22/222 entry stream: 7B neighbor + 3B metric + sub-TLVs."""
    while body.remaining() >= 11:
        nbr = body.bytes(7)
        metric = body.u24()
        sub_len = body.u8()
        sub = body.sub(min(sub_len, body.remaining()))
        link_msd = None
        adj_sids = []
        while sub.remaining() >= 2:
            st = sub.u8()
            stl = sub.u8()
            sb = sub.sub(min(stl, sub.remaining()))
            if st == 15:
                pairs = []
                while sb.remaining() >= 2:
                    pairs.append((sb.u8(), sb.u8()))
                link_msd = tuple(pairs)
            elif st == 31 and stl >= 5:
                flags = sb.u8()
                weight = sb.u8()
                label = int.from_bytes(sb.bytes(3), "big")
                adj_sids.append((flags, weight, label))
        out.append(
            ExtIsReach(
                nbr, metric, link_msd=link_msd,
                adj_sids=tuple(adj_sids) or None,
            )
        )


def _read_prefix_subtlvs(body: Reader) -> dict:
    """Parse a prefix entry's sub-TLV block; returns {sid_index,
    attr_flags, src_rid4, src_rid6} (RFC 8667 §2.1, RFC 7794)."""
    sl = body.u8()
    sub = body.sub(min(sl, body.remaining()))
    out: dict = {}
    while sub.remaining() >= 2:
        st = sub.u8()
        stl = sub.u8()
        sb = sub.sub(min(stl, sub.remaining()))
        if st == 3 and stl >= 6:
            flags = sb.u8()
            sb.u8()  # algorithm
            if not (flags & 0x0C):  # V/L clear: 4-byte index
                out["sid_index"] = sb.u32()
                out["sid_flags"] = flags
        elif st == 4 and stl >= 1:
            out["attr_flags"] = sb.u8()
        elif st == 11 and stl == 4:
            out["src_rid4"] = sb.ipv4()
        elif st == 12 and stl == 16:
            out["src_rid6"] = sb.ipv6()
    return out


def _read_wide_ip_entries(body: Reader, out: list) -> None:
    """TLV 135/235 entry stream: u32 metric + ctrl + truncated prefix."""
    while body.remaining() >= 5:
        metric = body.u32()
        ctrl = body.u8()
        plen = ctrl & 0x3F
        if plen > 32:
            raise DecodeError("bad prefix length")
        nbytes = (plen + 7) // 8
        raw = body.bytes(nbytes) + bytes(4 - nbytes)
        sub: dict = {}
        if ctrl & 0x40:  # sub-TLVs present
            sub = _read_prefix_subtlvs(body)
        # strict=False masks trailing host bits inside the truncated
        # prefix (the wire permits them; the route is the covering net).
        prefix = IPv4Network((int.from_bytes(raw, "big"), plen), strict=False)
        out.append(ExtIpReach(prefix, metric, bool(ctrl & 0x80), **sub))


def _read_ipv6_entries(body: Reader, out: list) -> None:
    """TLV 236/237 entry stream (RFC 5308 §2): metric u32, control byte
    (U/X/S), prefix-len, truncated prefix, optional sub-TLVs."""
    while body.remaining() >= 6:
        metric = body.u32()
        ctrl = body.u8()
        plen = body.u8()
        if plen > 128:
            raise DecodeError("bad v6 prefix length")
        nbytes = (plen + 7) // 8
        raw = body.bytes(nbytes) + bytes(16 - nbytes)
        sub: dict = {}
        if ctrl & 0x20:  # sub-TLVs present
            sub = _read_prefix_subtlvs(body)
        prefix = IPv6Network((int.from_bytes(raw, "big"), plen), strict=False)
        out.append(
            ExtIpReach(
                prefix, metric, bool(ctrl & 0x80),
                external=bool(ctrl & 0x40), **sub,
            )
        )


def _decode_tlvs(r: Reader) -> dict:
    out: dict = {
        "area_addresses": [],
        "is_neighbors": [],
        # None = TLV absent; [] = present but empty (pseudonode LSPs).
        "protocols_supported": None,
        "ip_addresses": [],
        "ipv6_addresses": [],
        "ext_is_reach": [],
        "ext_ip_reach": [],
        "ipv6_reach": [],
        # RFC 5120 multi-topology: (mt_id, att, ovl) / (mt_id, entry).
        "mt_ids": [],
        "mt_is_reach": [],
        "mt_ip_reach": [],
        "mt_ipv6_reach": [],
        "hostname": None,
        "lsp_entries": [],
        "p2p_adj": None,
        "sr_cap": None,
        # ISO 10589 / RFC 1195 narrow-metric TLVs kept distinct from the
        # wide lists so originated PDUs round-trip TLV-exactly.
        "narrow_is_reach": [],
        "narrow_ip_reach": [],
        "narrow_ip_ext_reach": [],
        "lsp_buf_size": None,
        "purge_originator": [],
    }
    while r.remaining() >= 2:
        t = r.u8()
        length = r.u8()
        value_start = r.pos
        body = r.sub(length)
        if t == TlvType.AUTHENTICATION:
            if length < 1:
                raise DecodeError("short authentication TLV")
            out["auth"] = (body.u8(), body.rest())
            out["_auth_span"] = (value_start, length)
        elif t == TlvType.AREA_ADDRESSES:
            while body.remaining() >= 1:
                n = body.u8()
                out["area_addresses"].append(body.bytes(n))
        elif t == TlvType.IS_NEIGHBORS:
            while body.remaining() >= 6:
                out["is_neighbors"].append(body.bytes(6))
        elif t == TlvType.PROTOCOLS_SUPPORTED:
            out["protocols_supported"] = list(body.rest())
        elif t == TlvType.IP_INTERFACE_ADDRESS:
            while body.remaining() >= 4:
                out["ip_addresses"].append(body.ipv4())
        elif t == TlvType.P2P_ADJ_STATE:
            try:
                state = AdjState3Way(body.u8())
            except ValueError as e:
                raise DecodeError("bad 3-way state") from e
            ext_id = int.from_bytes(body.bytes(4), "big")
            nbr_sys = nbr_ext = None
            if body.remaining() >= 10:
                nbr_sys = body.bytes(6)
                nbr_ext = int.from_bytes(body.bytes(4), "big")
            out["p2p_adj"] = P2pAdjState(state, ext_id, nbr_sys, nbr_ext)
        elif t == TlvType.IS_REACH:
            # ISO 10589 §9.8: virtual-flag byte, then 11-byte entries of
            # four metric octets + 7-byte neighbor id.  Only the default
            # metric (low 6 bits) is used.
            if body.remaining() >= 1:
                body.u8()  # virtual flag
            while body.remaining() >= 11:
                metric = body.u8() & 0x3F
                body.bytes(3)  # delay/expense/error metrics (unsupported)
                nbr = body.bytes(7)
                out["narrow_is_reach"].append(ExtIsReach(nbr, metric))
        elif t in (TlvType.IP_INTERNAL_REACH, TlvType.IP_EXTERNAL_REACH):
            # RFC 1195 §3.2: 12-byte entries of four metric octets +
            # address + mask.  Bit 6 of the default metric is I/E.
            while body.remaining() >= 12:
                m = body.u8()
                body.bytes(3)
                addr = int.from_bytes(body.bytes(4), "big")
                mask = int.from_bytes(body.bytes(4), "big")
                plen = bin(mask).count("1")
                if mask != (((1 << plen) - 1) << (32 - plen) if plen else 0):
                    raise DecodeError("non-contiguous subnet mask")
                prefix = IPv4Network((addr & mask, plen))
                if t == TlvType.IP_EXTERNAL_REACH:
                    out["narrow_ip_ext_reach"].append(
                        ExtIpReach(prefix, m & 0x3F, external=True)
                    )
                else:
                    out["narrow_ip_reach"].append(
                        ExtIpReach(prefix, m & 0x3F, external=bool(m & 0x40))
                    )
        elif t == TlvType.IPV4_ROUTER_ID:
            if length >= 4:
                out["ipv4_router_id"] = body.ipv4()
        elif t == TlvType.IPV6_ROUTER_ID:
            if length >= 16:
                out["ipv6_router_id"] = body.ipv6()
        elif t == TlvType.EXTENDED_SEQNUM:
            if length == 12:
                session = int.from_bytes(body.bytes(8), "big")
                packet = body.u32()
                if session:
                    out["ext_seqnum"] = (session, packet)
        elif t == TlvType.LSP_BUFFER_SIZE:
            if length >= 2:
                out["lsp_buf_size"] = body.u16()
        elif t == TlvType.PURGE_ORIGINATOR:
            # RFC 6232: count byte + that many system ids.
            if body.remaining() >= 1:
                n_ids = body.u8()
                for _ in range(min(n_ids, body.remaining() // 6)):
                    out["purge_originator"].append(body.bytes(6))
        elif t == TlvType.EXT_IS_REACH:
            _read_wide_is_entries(body, out["ext_is_reach"])
        elif t == TlvType.EXT_IP_REACH:
            _read_wide_ip_entries(body, out["ext_ip_reach"])
        elif t == TlvType.IPV6_INTERFACE_ADDRESS:
            while body.remaining() >= 16:
                out["ipv6_addresses"].append(body.ipv6())
        elif t == TlvType.DYNAMIC_HOSTNAME:
            out["hostname"] = body.rest().decode("ascii", "replace")
        elif t == TlvType.IPV6_REACH:
            _read_ipv6_entries(body, out["ipv6_reach"])
        elif t == TlvType.MULTI_TOPOLOGY:
            # RFC 5120 §7.1: u16 per topology — O(15) A(14) + 12-bit id.
            while body.remaining() >= 2:
                v = body.u16()
                out["mt_ids"].append(
                    (v & 0x0FFF, bool(v & 0x4000), bool(v & 0x8000))
                )
        elif t in (TlvType.MT_IS_REACH, TlvType.MT_IP_REACH,
                   TlvType.MT_IPV6_REACH):
            # RFC 5120 §7.2-7.4: 12-bit MT id, then the same entry stream
            # as the corresponding single-topology TLV (22/135/236).
            mt_id = body.u16() & 0x0FFF
            entries: list = []
            if t == TlvType.MT_IS_REACH:
                _read_wide_is_entries(body, entries)
                out["mt_is_reach"].extend((mt_id, e) for e in entries)
            elif t == TlvType.MT_IP_REACH:
                _read_wide_ip_entries(body, entries)
                out["mt_ip_reach"].extend((mt_id, e) for e in entries)
            else:
                _read_ipv6_entries(body, entries)
                out["mt_ipv6_reach"].extend((mt_id, e) for e in entries)
        elif t == TlvType.ROUTER_CAPABILITY:
            rid = body.ipv4()
            if int(rid):
                out["cap_router_id"] = rid
            body.u8()  # flags
            while body.remaining() >= 2:
                st = body.u8()
                stl = body.u8()
                sb = body.sub(min(stl, body.remaining()))
                if st == 2 and stl >= 9:
                    out["sr_cap_flags"] = sb.u8()  # I=0x80 V=0x40
                    rng = int.from_bytes(sb.bytes(3), "big")
                    if sb.remaining() >= 5 and sb.u8() == 1:
                        sb.u8()  # length (3)
                        base = int.from_bytes(sb.bytes(3), "big")
                        out["sr_cap"] = (base, rng)
                elif st == 19:
                    # RFC 8667 §3.2 SR-Algorithm sub-TLV.
                    algos = []
                    while sb.remaining() >= 1:
                        algos.append(sb.u8())
                    out["sr_algos"] = tuple(algos)
                elif st == 22 and stl >= 9:
                    sb.u8()  # reserved
                    rng = int.from_bytes(sb.bytes(3), "big")
                    if sb.remaining() >= 5 and sb.u8() == 1:
                        sb.u8()  # length (3)
                        base = int.from_bytes(sb.bytes(3), "big")
                        out["srlb"] = (base, rng)
                elif st == 21:
                    tags = []
                    while sb.remaining() >= 4:
                        tags.append(sb.u32())
                    out["node_tags"] = tuple(
                        out.get("node_tags", ()) or ()
                    ) + tuple(tags)
                elif st == 23:
                    msd = dict(out.get("node_msd") or {})
                    while sb.remaining() >= 2:
                        mt = sb.u8()
                        msd[mt] = sb.u8()
                    out["node_msd"] = msd
        elif t == TlvType.LSP_ENTRIES:
            while body.remaining() >= 16:
                lifetime = body.u16()
                lsp_id = LspId.decode(body.bytes(8))
                seqno = body.u32()
                cksum = body.u16()
                out["lsp_entries"].append((lifetime, lsp_id, seqno, cksum))
        # unknown TLVs skipped (body already consumed)
    return out


AUTH_HMAC_MD5 = 54  # RFC 5304 authentication type
AUTH_CRYPTO = 3  # RFC 5310 generic cryptographic authentication

_ISIS_HMACS = {"hmac-md5": ("md5", 16), "hmac-sha1": ("sha1", 20),
               "hmac-sha256": ("sha256", 32), "hmac-sha384": ("sha384", 48),
               "hmac-sha512": ("sha512", 64)}

# ietf-key-chain crypto-algorithm identities use the OSPF-style names; a
# keychain shared between protocols must resolve to the IS-IS TLV algos
# (EVERY name the key-chain YANG enum allows must map, or a legal config
# would KeyError at signing time).
_KEYCHAIN_ALGO = {
    "md5": "hmac-md5",
    "hmac-sha-1": "hmac-sha1",
    "hmac-sha-256": "hmac-sha256",
    "hmac-sha-384": "hmac-sha384",
    "hmac-sha-512": "hmac-sha512",
}


def _isis_algo(name: str) -> str:
    return _KEYCHAIN_ALGO.get(name, name)


@dataclass
class AuthCtxIsis:
    """IS-IS cryptographic authentication context.

    ``hmac-md5`` emits the RFC 5304 TLV (type octet 54, no key id);
    the SHA family emits the RFC 5310 generic TLV (type octet 3 +
    16-bit key id).  The digest is computed over the whole PDU with the
    digest zeroed — and, for LSPs, the checksum and remaining lifetime
    zeroed too (RFC 5304 §3.2)."""

    key: bytes
    algo: str = "hmac-md5"
    key_id: int = 1
    # Lifetime-based key selection (reference holo-isis/src/packet/
    # auth.rs AuthMethod::Keychain over holo-utils keychain.rs:42-92):
    # send uses the active send key; RFC 5310 verification looks the
    # received key id up against accept lifetimes; RFC 5304 (no key id
    # on the wire) uses the first active accept key.
    keychain: object = None
    clock: object = None

    def _now(self) -> float:
        if callable(self.clock):
            return self.clock()
        import time as _time

        return _time.time()

    def for_send(self) -> "AuthCtxIsis | None":
        """Resolved fixed-key context for ONE outgoing PDU (key id,
        algo, and digest must all come from the same key).  None when
        the keychain has no active send key: the PDU goes out without
        an auth TLV, like the reference's get_key_send → None."""
        if self.keychain is None:
            return self
        k = self.keychain.key_lookup_send(self._now())
        if k is None:
            return None
        return AuthCtxIsis(
            key=k.string, algo=_isis_algo(k.algo), key_id=k.id & 0xFFFF
        )

    def for_accept(self, key_id: "int | None") -> "list[AuthCtxIsis]":
        """Resolved candidate contexts for verifying a received PDU.

        RFC 5310 TLVs carry the key id → at most one candidate.  RFC
        5304 (HMAC-MD5) has NO key id on the wire, so during rollover
        the receiver cannot know which accept-active key signed the PDU
        — EVERY accept-active md5 key is a candidate and verification
        tries each until a digest matches (otherwise the overlap window
        the lifetimes exist for would drop every PDU)."""
        if self.keychain is None:
            return [self]
        now = self._now()
        if key_id is not None:
            # Masked compare: RFC 5310 carries a u16 id, for_send masks.
            k = self.keychain.key_lookup_accept(key_id, now, mask=0xFFFF)
            keys = [k] if k is not None else []
        else:
            keys = [
                k
                for k in self.keychain.keys
                if k.accept_lifetime.is_active(now)
                and _isis_algo(k.algo) == "hmac-md5"
            ]
        return [
            AuthCtxIsis(
                key=k.string, algo=_isis_algo(k.algo), key_id=k.id & 0xFFFF
            )
            for k in keys
        ]

    def _hmac(self, data: bytes) -> bytes:
        import hashlib
        import hmac as _h

        name, _dlen = _ISIS_HMACS[self.algo]
        return _h.new(self.key, data, getattr(hashlib, name)).digest()

    def tlv_value_len(self) -> int:
        _name, dlen = _ISIS_HMACS[self.algo]
        return (1 + dlen) if self.algo == "hmac-md5" else (3 + dlen)


def _append_auth_tlv(w: Writer, auth: AuthCtxIsis) -> int:
    """Write the auth TLV with a zeroed digest; returns digest offset."""
    _name, dlen = _ISIS_HMACS[auth.algo]
    w.u8(TlvType.AUTHENTICATION).u8(auth.tlv_value_len())
    if auth.algo == "hmac-md5":
        w.u8(AUTH_HMAC_MD5)
    else:
        w.u8(AUTH_CRYPTO).u16(auth.key_id)
    pos = len(w)
    w.zeros(dlen)
    return pos


def _patch_auth_digest(
    w: Writer, auth: AuthCtxIsis, digest_pos: int, lsp_zero: tuple | None = None
) -> None:
    """Compute the digest over the current buffer (digest zeroed; for
    LSPs also lifetime/cksum zeroed) and patch it in."""
    _name, dlen = _ISIS_HMACS[auth.algo]
    buf = bytearray(w.buf)
    buf[digest_pos : digest_pos + dlen] = bytes(dlen)
    if lsp_zero is not None:
        life_pos, cks_pos = lsp_zero
        buf[life_pos : life_pos + 2] = b"\x00\x00"
        buf[cks_pos : cks_pos + 2] = b"\x00\x00"
    digest = auth._hmac(bytes(buf))
    for i, b in enumerate(digest):
        w.buf[digest_pos + i] = b


def verify_pdu_auth(data: bytes, tlvs: dict, auth: AuthCtxIsis) -> None:
    """Raises DecodeError unless the PDU carries a valid auth TLV."""
    import hmac as _h

    span = tlvs.get("_auth_span")
    info = tlvs.get("auth")
    if span is None or info is None:
        raise AuthTypeError("authentication TLV missing")
    atype, value = info
    # Accept-side key selection (auth.rs get_key_accept / RFC 5304
    # accept-any): RFC 5310 TLVs carry the key id; RFC 5304 does not,
    # so every accept-active md5 key is tried until a digest matches.
    rx_key_id = (
        int.from_bytes(value[:2], "big")
        if atype == AUTH_CRYPTO and len(value) >= 2
        else None
    )
    candidates = auth.for_accept(rx_key_id)
    if not candidates:
        raise AuthError("unknown authentication key id")
    last_err: AuthError | None = None
    for cand in candidates:
        try:
            _verify_pdu_auth_one(data, span, atype, value, cand)
            return
        except AuthError as e:  # try the next candidate key
            last_err = e
    raise last_err


def _verify_pdu_auth_one(
    data: bytes, span, atype: int, value: bytes, auth: AuthCtxIsis
) -> None:
    import hmac as _h

    _name, dlen = _ISIS_HMACS[auth.algo]
    if auth.algo == "hmac-md5":
        if atype != AUTH_HMAC_MD5 or len(value) != dlen:
            raise AuthTypeError("authentication type mismatch")
        digest_off = span[0] + 1
    else:
        if atype != AUTH_CRYPTO or len(value) != 2 + dlen:
            raise AuthTypeError("authentication type mismatch")
        key_id = int.from_bytes(value[:2], "big")
        if key_id != auth.key_id:
            raise AuthError("unknown authentication key id")
        digest_off = span[0] + 3
    got = data[digest_off : digest_off + dlen]
    buf = bytearray(data)
    buf[digest_off : digest_off + dlen] = bytes(dlen)
    pdu_type = PduType(data[4] & 0x1F)
    if pdu_type in (PduType.LSP_L1, PduType.LSP_L2):
        buf[10:12] = b"\x00\x00"  # remaining lifetime
        buf[24:26] = b"\x00\x00"  # checksum
    if not _h.compare_digest(auth._hmac(bytes(buf)), got):
        raise AuthError("authentication digest mismatch")


def _pdu_header(w: Writer, pdu_type: PduType, hdr_len: int) -> None:
    w.u8(IRDP_DISCRIMINATOR).u8(hdr_len).u8(1).u8(0)
    w.u8(int(pdu_type)).u8(1).u8(0).u8(0)


def _check_header(r: Reader) -> PduType:
    if r.u8() != IRDP_DISCRIMINATOR:
        raise DecodeError("not an IS-IS PDU")
    r.u8()  # header length
    if r.u8() != 1:
        raise DecodeError("bad protocol version")
    r.u8()  # sysid len (0 = 6)
    try:
        pdu_type = PduType(r.u8() & 0x1F)
    except ValueError as e:
        raise DecodeError("unknown PDU type") from e
    r.u8()
    r.u8()
    r.u8()
    return pdu_type


@dataclass
class HelloP2p:
    circuit_type: int  # 1=L1, 2=L2, 3=L1L2
    sysid: bytes
    hold_time: int
    local_circuit_id: int
    tlvs: dict = field(default_factory=dict)

    TYPE = PduType.HELLO_P2P

    def encode(self, auth: "AuthCtxIsis | None" = None) -> bytes:
        w = Writer()
        _pdu_header(w, self.TYPE, 20)
        w.u8(self.circuit_type).bytes(self.sysid)
        w.u16(self.hold_time)
        len_pos = len(w)
        w.u16(0)
        w.u8(self.local_circuit_id)
        # Resolve the keychain's active send key ONCE per PDU: key id,
        # algo, and digest must agree (auth.rs get_key_send).
        auth = auth.for_send() if auth is not None else None
        digest_pos = _append_auth_tlv(w, auth) if auth is not None else None
        _encode_tlvs(w, self.tlvs)
        w.patch_u16(len_pos, len(w))
        if digest_pos is not None:
            _patch_auth_digest(w, auth, digest_pos)
        return w.finish()

    @classmethod
    def decode_body(cls, r: Reader) -> "HelloP2p":
        ct = r.u8() & 0x3
        sysid = r.bytes(SYSID_LEN)
        hold = r.u16()
        r.u16()  # pdu length
        circuit_id = r.u8()
        return cls(ct, sysid, hold, circuit_id, _decode_tlvs(r))


@dataclass
class HelloLan:
    """LAN IIH (ISO 10589 §9.5/9.6): priority + LAN ID for DIS election."""

    circuit_type: int
    sysid: bytes
    hold_time: int
    priority: int
    lan_id: bytes  # DIS sysid + pseudonode byte (7 bytes)
    level: int = 2
    tlvs: dict = field(default_factory=dict)

    @property
    def TYPE(self):
        return PduType.HELLO_LAN_L2 if self.level == 2 else PduType.HELLO_LAN_L1

    def encode(self, auth: "AuthCtxIsis | None" = None) -> bytes:
        w = Writer()
        _pdu_header(w, self.TYPE, 27)
        w.u8(self.circuit_type).bytes(self.sysid)
        w.u16(self.hold_time)
        len_pos = len(w)
        w.u16(0)
        w.u8(self.priority & 0x7F)
        w.bytes(self.lan_id)
        # Resolve the keychain's active send key ONCE per PDU: key id,
        # algo, and digest must agree (auth.rs get_key_send).
        auth = auth.for_send() if auth is not None else None
        digest_pos = _append_auth_tlv(w, auth) if auth is not None else None
        _encode_tlvs(w, self.tlvs)
        w.patch_u16(len_pos, len(w))
        if digest_pos is not None:
            _patch_auth_digest(w, auth, digest_pos)
        return w.finish()

    @classmethod
    def decode_body(cls, r: Reader, level: int) -> "HelloLan":
        ct = r.u8() & 0x3
        sysid = r.bytes(SYSID_LEN)
        hold = r.u16()
        r.u16()  # pdu length
        prio = r.u8() & 0x7F
        lan_id = r.bytes(7)
        return cls(ct, sysid, hold, prio, lan_id, level, _decode_tlvs(r))


@dataclass
class Lsp:
    level: int  # 1 or 2
    lifetime: int
    lsp_id: LspId
    seqno: int
    flags: int = 0x03  # IS-type bits (L2)
    tlvs: dict = field(default_factory=dict)
    cksum: int = 0
    raw: bytes = b""

    @property
    def is_expired(self) -> bool:
        return self.lifetime == 0

    def encode(self, auth: "AuthCtxIsis | None" = None) -> bytes:
        w = Writer()
        _pdu_header(w, PduType.LSP_L2 if self.level == 2 else PduType.LSP_L1, 27)
        len_pos = len(w)
        w.u16(0)  # pdu length
        life_pos = len(w)
        w.u16(self.lifetime)
        w.bytes(self.lsp_id.encode())
        w.u32(self.seqno)
        cks_pos = len(w)
        w.u16(0)
        w.u8(self.flags)
        # Resolve the keychain's active send key ONCE per PDU: key id,
        # algo, and digest must agree (auth.rs get_key_send).
        auth = auth.for_send() if auth is not None else None
        digest_pos = _append_auth_tlv(w, auth) if auth is not None else None
        _encode_tlvs(w, self.tlvs)
        w.patch_u16(len_pos, len(w))
        if digest_pos is not None:
            # RFC 5304 §3.2: digest first (lifetime/cksum zeroed), then
            # the regular checksum over the final bytes.
            _patch_auth_digest(w, auth, digest_pos, (life_pos, cks_pos))
        # ISO 10589 §7.3.11: checksum over lsp_id..end (offset 12 in PDU).
        cks = fletcher16_checksum(bytes(w.buf[12:]), cks_pos - 12)
        w.patch_u16(cks_pos, cks)
        self.cksum = cks
        self.raw = w.finish()
        return self.raw

    @classmethod
    def decode_body(cls, r: Reader, level: int, raw: bytes) -> "Lsp":
        pdu_len = r.u16()
        if pdu_len > len(raw):
            raise DecodeError("bad LSP length")
        lifetime = r.u16()
        lsp_id = LspId.decode(r.bytes(8))
        seqno = r.u32()
        cksum = r.u16()
        flags = r.u8()
        if lifetime > 0 and not fletcher16_verify(raw[12:pdu_len]):
            raise DecodeError("LSP checksum mismatch")
        tlvs = _decode_tlvs(Reader(raw, r.pos, pdu_len))
        return cls(level, lifetime, lsp_id, seqno, flags, tlvs, cksum, raw[:pdu_len])

    def compare(self, lifetime: int, seqno: int, cksum: int = -1) -> int:
        """ISO 10589 §7.3.16: newer comparison vs a summary tuple.

        The checksum does NOT participate in the ordering (reference
        lsp_compare): an equal result with differing checksums is "LSP
        confusion" (§7.3.16.2), handled by the caller."""
        del cksum
        if self.seqno != seqno:
            return 1 if self.seqno > seqno else -1
        if (self.lifetime == 0) != (lifetime == 0):
            return 1 if self.lifetime == 0 else -1
        return 0


@dataclass
class Snp:
    """CSNP (complete, with range) or PSNP (partial)."""

    level: int
    complete: bool
    sysid: bytes
    entries: list = field(default_factory=list)  # (lifetime, LspId, seqno, cksum)
    start: LspId | None = None
    end: LspId | None = None
    tlvs: dict = field(default_factory=dict)

    def encode(self, auth: "AuthCtxIsis | None" = None) -> bytes:
        w = Writer()
        if self.complete:
            t = PduType.CSNP_L2 if self.level == 2 else PduType.CSNP_L1
            _pdu_header(w, t, 33)
        else:
            t = PduType.PSNP_L2 if self.level == 2 else PduType.PSNP_L1
            _pdu_header(w, t, 17)
        len_pos = len(w)
        w.u16(0)
        w.bytes(self.sysid + b"\x00")  # source id (sysid + circuit 0)
        if self.complete:
            w.bytes((self.start or LspId(b"\x00" * 6)).encode())
            w.bytes((self.end or LspId(b"\xff" * 6, 0xFF, 0xFF)).encode())
        # Resolve the keychain's active send key ONCE per PDU: key id,
        # algo, and digest must agree (auth.rs get_key_send).
        auth = auth.for_send() if auth is not None else None
        digest_pos = _append_auth_tlv(w, auth) if auth is not None else None
        _encode_tlvs(
            w,
            {
                "ext_seqnum": (self.tlvs or {}).get("ext_seqnum"),
                "lsp_entries": self.entries,
            },
        )
        w.patch_u16(len_pos, len(w))
        if digest_pos is not None:
            _patch_auth_digest(w, auth, digest_pos)
        return w.finish()

    @classmethod
    def decode_body(cls, r: Reader, level: int, complete: bool) -> "Snp":
        r.u16()  # pdu length
        src = r.bytes(7)
        start = end = None
        if complete:
            start = LspId.decode(r.bytes(8))
            end = LspId.decode(r.bytes(8))
        tlvs = _decode_tlvs(r)
        return cls(
            level, complete, src[:6], tlvs["lsp_entries"], start, end, tlvs
        )


def decode_pdu(data: bytes, auth: "AuthCtxIsis | None" = None):
    """Top-level dispatch; returns (PduType, object).

    With ``auth``, every PDU must carry a valid authentication TLV
    (RFC 5304/5310) or DecodeError is raised."""
    r = Reader(data)
    pdu_type = _check_header(r)
    if pdu_type == PduType.HELLO_P2P:
        out = HelloP2p.decode_body(r)
    elif pdu_type in (PduType.HELLO_LAN_L1, PduType.HELLO_LAN_L2):
        level = 2 if pdu_type == PduType.HELLO_LAN_L2 else 1
        out = HelloLan.decode_body(r, level)
    elif pdu_type in (PduType.LSP_L1, PduType.LSP_L2):
        level = 2 if pdu_type == PduType.LSP_L2 else 1
        out = Lsp.decode_body(r, level, data)
    elif pdu_type in (PduType.CSNP_L1, PduType.CSNP_L2):
        level = 2 if pdu_type == PduType.CSNP_L2 else 1
        out = Snp.decode_body(r, level, True)
    elif pdu_type in (PduType.PSNP_L1, PduType.PSNP_L2):
        level = 2 if pdu_type == PduType.PSNP_L2 else 1
        out = Snp.decode_body(r, level, False)
    else:
        raise DecodeError("unhandled PDU type")
    if auth is not None:
        tlvs = _tlvs_of(out)
        if tlvs is None:
            raise AuthTypeError("authentication required")
        verify_pdu_auth(data, tlvs, auth)
    return pdu_type, out


def _tlvs_of(pdu):
    return getattr(pdu, "tlvs", None)

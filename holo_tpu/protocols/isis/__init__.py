"""IS-IS (ISO 10589 + RFC 1195/5305) — second link-state family.

Reference crate: holo-isis (SURVEY.md §2.3).  Shares the pluggable SPF
backend with OSPF: the LSDB lowers to the same generic Topology (routers +
pseudonodes), so the TPU batch engine serves both protocols — the reason
the reference keeps `compute_spt` root-agnostic (holo-isis/src/spf.rs:520-526).

Round-1 scope: point-to-point circuits with 3-way handshake (RFC 5303),
single configurable level, wide metrics (ext IS reach TLV 22 + ext IP
reach TLV 135), LSP flooding with PSNP acks + CSNP sync, SPF + route
derivation.  LAN DIS election and multi-topology land next round.
"""

"""IS-IS flooding reduction.

Reference: holo-isis/src/flooding/manet.rs:24-176 + SURVEY.md §2.3 — after
each full SPF, per-neighbor hop-count SPTs (a multi-root batch on the SPF
backend — the root-agnostic requirement of holo-isis/src/spf.rs:520-526)
drive pruning of redundant LSP transmissions.

Pruning rule (sound): when re-flooding an LSP received from neighbor f,
skip neighbors adjacent to f — f floods its own neighborhood.  Proof that
every router still receives every LSP: consider the first neighbor y of
any router n to receive the LSP, with sender z.  If z were adjacent to n,
z would have received before y (contradiction with y first), so z is not
adjacent to n, hence y does not suppress n.  Self-originated LSPs always
flood everywhere (they have no sender).

As defense against stale coverage during topology-change windows, p2p
interfaces send periodic CSNPs while reduction is enabled (LAN already
has DIS CSNPs), so any suppressed-in-error LSP is recovered.
"""

from __future__ import annotations

import numpy as np

from holo_tpu.ops.graph import Topology


def hop_topology(topo: Topology) -> Topology:
    """Same graph with unit costs (distances = hop counts), memoized per
    topology generation so backend device caches stay warm."""
    cached = getattr(topo, "_hop_cache", None)
    if cached is not None and cached[0] == topo.generation:
        return cached[1]
    t = Topology(
        n_vertices=topo.n_vertices,
        is_router=topo.is_router,
        edge_src=topo.edge_src,
        edge_dst=topo.edge_dst,
        edge_cost=np.ones(topo.n_edges, np.int32),
        edge_direct_atom=topo.edge_direct_atom,
        root=topo.root,
    )
    topo._hop_cache = (topo.generation, t)
    return t


def neighbor_coverage(
    topo: Topology,
    backend,
    neighbor_vertices: list[int],
) -> dict[int, set[int]]:
    """coverage[m] = set of our neighbors adjacent to neighbor m.

    Computed from per-neighbor hop-count SPTs (dist == 1) via one
    multi-root backend batch.
    """
    if len(neighbor_vertices) <= 1:
        return {v: set() for v in neighbor_vertices}
    roots = np.array(neighbor_vertices, np.int32)
    res = backend.compute_multiroot(hop_topology(topo), roots)
    out: dict[int, set[int]] = {}
    for j, m in enumerate(neighbor_vertices):
        out[m] = {
            n for n in neighbor_vertices if n != m and res.dist[j, n] == 1
        }
    return out

"""Level-all (L1/L2) IS-IS: two single-level instances coupled per
ISO 10589 + RFC 1195 inter-level rules.

Reference: holo-isis runs one instance with per-level state; this
composition reproduces its externally observable behavior —

- shared circuits: hellos with circuit-type L1L2 feed both levels,
  LSPs/SNPs dispatch on their PDU level;
- L1->L2 route propagation (lsdb.rs lsp_propagate_l1_to_l2): each L1
  router's reachability joins our L2 LSP with metric increased by the
  L1 SPT distance, R-flag set on wide entries, deduped lowest-metric,
  minus prefixes covered by configured summaries (which are advertised
  instead, at their lowest contributing metric);
- the ATT bit on our L1 LSP while an up L2 adjacency reaches another
  area (instance.rs is_l2_attached_to_backbone), unless suppressed;
- merged route table with L1 preferred over L2 for equal prefixes.
"""

from __future__ import annotations

from holo_tpu.protocols.isis.instance import (
    IsisInstance,
    AdjacencyState,
)
from holo_tpu.protocols.isis.packet import (
    MAX_NARROW_METRIC,
    PREFIX_ATTR_R,
    ExtIpReach,
    PduType,
)
from holo_tpu.utils.runtime import Actor


class IsisLevelAllInstance(Actor):
    """Facade over an L1 and an L2 IsisInstance sharing the circuits.

    Also an actor in its own right: the daemon's fabric/sockets deliver
    raw packets to the NODE name, and :meth:`handle` dispatches them to
    the level that owns the PDU (L1 kinds to l1, L2 kinds to l2, P2P
    hellos to both — they cover both levels on a shared circuit)."""

    @property
    def notif_cb(self):
        return self.l1.notif_cb

    @notif_cb.setter
    def notif_cb(self, cb):
        # The daemon's placement marshals this attribute; both levels
        # share the sink.
        self.l1.notif_cb = cb
        self.l2.notif_cb = cb

    @property
    def frr(self):
        return self.l1.frr

    @frr.setter
    def frr(self, cfg):
        # IP fast reroute applies per level (each level's SPF computes
        # its own backup tables over its own IS graph).
        self.l1.frr = cfg
        self.l2.frr = cfg

    @property
    def frr_backups(self) -> dict:
        """Merged per-prefix repairs, same precedence as the route merge
        (L1 wins where both levels reach a prefix)."""
        merged = dict(self.l2.frr_backups)
        merged.update(self.l1.frr_backups)
        return merged

    def __init__(self, name: str, sysid: bytes, area: bytes, netio=None,
                 spf_backend_factory=None, route_cb=None, **kw):
        self.name = name
        self.sysid = sysid
        self.route_cb = route_cb
        mk = spf_backend_factory or (lambda: None)
        self.l1 = IsisInstance(
            f"{name}-l1", sysid, area, level=1, netio=netio,
            spf_backend=mk(), **kw,
        )
        self.l2 = IsisInstance(
            f"{name}-l2", sysid, area, level=2, netio=netio,
            spf_backend=mk(), **kw,
        )
        for inst in (self.l1, self.l2):
            inst.is_type = 0x03
            inst.route_cb = self._level_routes_changed
            inst.display_name = name
        # One node-wide adjacency-SID label space across both levels.
        self.l2._adj_sid_box = self.l1._adj_sid_box
        self.l1.att_cb = self._l2_attached
        self.l2.extra_reach_cb = self._propagated_reach
        self.att_suppress = False
        # {v4/v6 prefix: metric-or-None} — summary config (l1-to-l2).
        self.summaries: dict = {}
        # Active summaries (prefix -> advertised metric): installed as
        # discard routes for loop prevention.  Entries that become
        # inactive linger in the RIB until the next SPF run (the
        # reference uninstalls summary routes during route calc only).
        self._summary_routes: dict = {}
        self._lingering_summaries: dict = {}
        self.routes: dict = {}
        self.summary_prefixes: frozenset = frozenset()
        self.connected_prefixes: frozenset = frozenset()
        self.last_installable: dict = {}

    # -- shared-circuit plumbing

    def instances(self):
        return (self.l1, self.l2)

    def level(self, n: int) -> IsisInstance:
        return self.l1 if n == 1 else self.l2

    def attach_loop(self, loop) -> None:
        loop.register(self.l1)
        loop.register(self.l2)
        loop.register(self)  # packet entry point under the node name

    _HELLO_PDUS = frozenset(
        (int(PduType.HELLO_P2P), int(PduType.HELLO_LAN_L1),
         int(PduType.HELLO_LAN_L2))
    )

    def handle(self, msg) -> None:
        """Raw packet entry point: decode ONCE, then :meth:`rx_pdu`
        dispatches by level — including the P2P hello's circuit-type
        bits, so an L1-only neighbor never raises a bogus L2
        adjacency."""
        from holo_tpu.protocols.isis.packet import DecodeError, decode_pdu

        data = getattr(msg, "data", None)
        if data is None or len(data) <= 4:
            return
        iface = self.l1.interfaces.get(msg.ifname)
        if iface is None:
            return
        probe = data[4] & 0x1F
        rx_auth = (
            self.l1._hello_auth(iface)
            if probe in self._HELLO_PDUS
            else self.l1.auth
        )
        try:
            ptype, pdu = decode_pdu(data, auth=rx_auth)
        except DecodeError as e:
            self.l1._notify_decode_error(iface, data, e, rx_auth)
            return
        snpa = msg.src if isinstance(msg.src, bytes) else b""
        self.rx_pdu(msg.ifname, ptype, pdu, snpa)

    # -- daemon-facing delegation (the provider treats a node like a
    #    single instance for interface membership and state rendering)

    @property
    def interfaces(self):
        return self.l1.interfaces  # both levels share the circuit set

    @property
    def spf_run_count(self) -> int:
        return self.l1.spf_run_count + self.l2.spf_run_count

    @property
    def lsdb(self):
        return {**self.l1.lsdb, **self.l2.lsdb}

    @property
    def hostnames(self):
        return {**self.l1.hostnames, **self.l2.hostnames}

    def add_interface(self, ifname, cfg, addr, prefix, **kw):
        import copy

        for inst in self.instances():
            inst.add_interface(ifname, copy.copy(cfg), addr, prefix, **kw)

    def if_up(self, ifname: str) -> None:
        for inst in self.instances():
            inst.if_up(ifname)

    def if_down(self, ifname: str) -> None:
        for inst in self.instances():
            inst.if_down(ifname)

    def iface_metric_update(self, ifname: str, metric: int) -> None:
        for inst in self.instances():
            inst.iface_metric_update(ifname, metric)

    def rx_pdu(self, ifname: str, pdu_type: PduType, pdu, snpa: bytes = b"") -> None:
        """Dispatch by PDU level; L1L2 p2p hellos feed both levels."""
        if pdu_type == PduType.HELLO_P2P:
            ct = pdu.circuit_type
            if ct & 1:
                self.l1.rx_pdu(ifname, pdu_type, pdu, snpa)
            if ct & 2:
                self.l2.rx_pdu(ifname, pdu_type, pdu, snpa)
            return
        level = getattr(pdu, "level", 2)
        self.level(level).rx_pdu(ifname, pdu_type, pdu, snpa)

    # -- inter-level coupling

    def _l2_attached(self) -> bool:
        """ATT: an up L2 adjacency whose area addresses are all foreign
        (instance.rs:577-591)."""
        if self.att_suppress:
            return False
        ours = {self.l2.area}
        for iface in self.l2.interfaces.values():
            for adj in iface.up_adjacencies():
                areas = set(adj.area_addresses)
                if areas and areas.isdisjoint(ours):
                    return True
        return False

    def _propagated_reach(self):
        """L1 LSDB -> L2 LSP reachability (lsp_propagate_l1_to_l2)."""
        narrow: dict = {}
        narrow_ext: dict = {}
        wide: dict = {}
        v6: dict = {}
        summary_active: dict = {}  # prefix -> lowest contributing metric

        def covered(prefix):
            for sp in self.summaries:
                if (
                    sp.version == prefix.version
                    and prefix.subnet_of(sp)
                ):
                    return sp
            return None

        now = self.l1.loop.clock.now() if self.l1.loop else 0.0
        for lid, e in self.l1.lsdb.items():
            if (
                e.lsp.seqno == 0
                or e.remaining_lifetime(now) == 0
                or lid.pseudonode != 0
                or lid.sysid == self.sysid
            ):
                continue
            dist = self.l1.vertex_dist.get(lid.sysid)
            if dist is None:
                continue
            tlvs = e.lsp.tlvs

            def _prop(entries, out, is_wide):
                for r in entries:
                    if r.up_down:
                        continue
                    total = r.metric + dist
                    sp = covered(r.prefix)
                    if sp is not None:
                        cur = summary_active.get(sp)
                        if cur is None or total < cur:
                            summary_active[sp] = total
                        continue
                    cur = out.get(r.prefix)
                    if cur is not None and cur.metric <= total:
                        continue
                    if is_wide:
                        out[r.prefix] = ExtIpReach(
                            r.prefix, total, external=r.external,
                            attr_flags=(r.attr_flags or 0) | PREFIX_ATTR_R,
                            sid_index=r.sid_index,
                            src_rid4=r.src_rid4, src_rid6=r.src_rid6,
                        )
                    else:
                        out[r.prefix] = ExtIpReach(
                            r.prefix, min(total, MAX_NARROW_METRIC),
                            external=r.external,
                        )

            _prop(tlvs.get("narrow_ip_reach", []), narrow, False)
            _prop(tlvs.get("narrow_ip_ext_reach", []), narrow_ext, False)
            _prop(tlvs.get("ext_ip_reach", []), wide, True)
            _prop(tlvs.get("ipv6_reach", []), v6, True)
        # Active summaries advertise at their lowest contributing metric
        # (or the configured metric when set).
        old = dict(self._summary_routes)
        self._summary_routes = {}
        for sp, metric in summary_active.items():
            cfg_metric = self.summaries.get(sp)
            m = cfg_metric if cfg_metric is not None else metric
            self._summary_routes[sp] = m
            entry = ExtIpReach(
                sp, m,
                src_rid4=self.l2.te_rid4, src_rid6=self.l2.te_rid6,
            )
            if sp.version == 4:
                narrow[sp] = ExtIpReach(sp, min(m, MAX_NARROW_METRIC))
                wide[sp] = entry
            else:
                v6[sp] = entry
        for sp, m in old.items():
            if sp not in self._summary_routes:
                self._lingering_summaries[sp] = m
        return (
            list(narrow.values()),
            list(wide.values()),
            list(v6.values()),
            list(narrow_ext.values()),
        )

    # -- merged routes (L1 preferred over L2)

    def _level_routes_changed(self, _routes) -> None:
        merged = dict(self.l2.routes)
        merged.update(self.l1.routes)
        # Active summary prefixes install as nexthop-less discard routes
        # (loop prevention for the aggregated advertisement).
        summaries = {
            **self._lingering_summaries, **self._summary_routes
        }
        for sp, metric in summaries.items():
            merged[sp] = (metric, frozenset())
        self.routes = merged
        self.summary_prefixes = frozenset(summaries)
        # CONNECTED follows the level whose route won the merge.
        self.connected_prefixes = frozenset(
            p for p in merged
            if (
                p in self.l1.connected_prefixes
                if p in self.l1.routes
                else p in self.l2.connected_prefixes
            )
        )
        # One atomic publication for cross-thread readers (the daemon's
        # marshalled route_cb) — same contract as the single instance.
        self.last_installable = self.installable_routes()
        if self.route_cb is not None:
            self.route_cb(merged)

    def installable_routes(self) -> dict:
        """Merged-table RIB feed (route.rs:285-301): CONNECTED never
        installs; summary discard routes install despite having no
        nexthops; anything else needs nexthops."""
        return {
            p: r for p, r in self.routes.items()
            if p not in self.connected_prefixes
            and (p in self.summary_prefixes or r[1])
        }

    def _schedule_spf(self, topology: bool = True) -> None:
        # Config-driven reschedule (e.g. a fast-reroute change) applies
        # to both levels, like the frr setter above.
        for inst in self.instances():
            inst._schedule_spf(topology)

    def run_spf(self, level: int | None = None) -> None:
        for inst in self.instances():
            if level is None or inst.level == level:
                inst.run_spf()
        # SPF is where stale summary discard routes finally leave.
        self._lingering_summaries = {}
        # An L1 topology change alters our L2 LSP (propagation).
        self.l2._originate_lsp()
        self._level_routes_changed({})

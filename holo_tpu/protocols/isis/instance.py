"""IS-IS instance actor: p2p adjacencies, LSP flooding, SPF, routes.

Reference: holo-isis/src/{instance,adjacency,lsdb,spf}.rs.  The SPF lowers
the LSP database to the same generic Topology as OSPF (pseudonodes as
"network" vertices), so the scalar and TPU backends are shared.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from ipaddress import IPv4Address, IPv4Network

import numpy as np

from holo_tpu import telemetry
from holo_tpu.ops.graph import INF, Topology, mutual_keep_mask
from holo_tpu.protocols.isis.packet import (
    LSP_MAX_AGE,
    LSP_REFRESH,
    MAX_NARROW_METRIC,
    PREFIX_ATTR_N,
    AdjState3Way,
    ExtIpReach,
    ExtIsReach,
    HelloP2p,
    Lsp,
    LspId,
    P2pAdjState,
    PduType,
    Snp,
    decode_pdu,
)
from holo_tpu.spf.backend import ScalarSpfBackend, SpfBackend
from holo_tpu.telemetry import convergence
from holo_tpu.utils.bytesbuf import DecodeError
from holo_tpu.utils.netio import NetIo, NetRxPacket
from holo_tpu.utils.runtime import Actor

# Adjacency churn, PDU rx rate, and SPF runs per instance (L1/L2 actors
# carry distinct instance names, so levels separate naturally).
_ISIS_ADJ_TRANSITIONS = telemetry.counter(
    "holo_isis_adj_transitions_total",
    "IS-IS adjacency up/down changes",
    ("instance", "to"),
)
_ISIS_PDUS_RX = telemetry.counter(
    "holo_isis_pdus_rx_total", "IS-IS PDUs received (decoded)", ("instance",)
)
_ISIS_RX_BAD = telemetry.counter(
    "holo_isis_rx_bad_total", "IS-IS PDUs dropped in decode/auth", ("instance",)
)
_ISIS_SPF_RUNS = telemetry.counter(
    "holo_isis_spf_runs_total", "IS-IS SPF runs", ("instance",)
)

def _sid_flags(psid) -> int:
    """RFC 8667 §2.1 prefix-SID flags from config: no-PHP (P) and
    explicit-null (E)."""
    if psid is None:
        return 0
    flags = 0
    if getattr(psid, "no_php", False):
        flags |= 0x20
    if getattr(psid, "explicit_null", False):
        flags |= 0x10
    return flags


class _McastMac(str):
    """L2 multicast destination stand-in (AllISs); the fabric checks
    ``is_multicast`` like it does for IP groups."""

    is_multicast = True


ALL_ISS = _McastMac("01:80:c2:00:00:14")


class AdjacencyState(enum.Enum):
    DOWN = "down"
    INITIALIZING = "init"
    UP = "up"


MT_IPV6 = 2  # RFC 5120 IPv6 unicast topology id


@dataclass
class IsisIfConfig:
    metric: int = 10
    hello_interval: int = 3  # p2p default (holo uses 3x multiplier)
    hold_multiplier: int = 3
    level: int = 2
    circuit_type: str = "p2p"  # "p2p" | "broadcast"
    priority: int = 64  # DIS election priority (LAN)
    # packet.AuthCtxIsis: hello authentication on this circuit (LSPs/SNPs
    # use the instance-level area auth).
    auth: object = None
    # Passive circuits (loopbacks): prefixes are advertised but no
    # hellos are sent and no adjacencies form.
    passive: bool = False
    loopback: bool = False  # RFC 7794 N-flag eligibility
    # Per-circuit enabled address families (None = instance AFs).
    afs: object = None
    # RFC 8491 Link MSD ({msd-type: value}) from the kernel interface.
    msd: dict = None
    # RFC 7602 extended sequence numbers ("send-only"/"send-and-verify").
    esn_mode: str | None = None
    # BFD fast-failure detection on this circuit (RFC 5880 client).
    bfd_enabled: bool = False
    bfd_min_tx: int = 1000000
    bfd_min_rx: int = 1000000
    bfd_multiplier: int = 3
    # Fast-reroute SRLG membership of this circuit (ietf fast-reroute
    # config): lowered to the uint32 Topology.edge_srlg bitmask at SPT
    # marshal time (spf_run.srlg_bits semantics) — the srlg_disjoint
    # FRR policy input.  Ids fold mod 32, conservative-correct.
    srlg: tuple = ()


@dataclass
class Adjacency:
    sysid: bytes
    # RFC 8667 §2.2 adjacency SIDs ((flags, weight, label), ...).
    adj_sids: tuple = ()
    # Registered BFD session destinations (one per address family).
    bfd_sessions: tuple = ()
    state: AdjacencyState = AdjacencyState.DOWN
    hold_time: int = 9
    addr: IPv4Address | None = None
    addr6: object = None  # neighbor's link-local (RFC 5308 v6 next hop)
    priority: int = 64
    lan_id: bytes = b""  # DIS the neighbor declares
    snpa: bytes = b""  # neighbor's MAC (LAN 2-way check, hello TLV 6)
    # Last hello's TLV contents surfaced in operational state.
    area_addresses: tuple = ()
    protocols: tuple = ()
    addrs4: tuple = ()
    addrs6: tuple = ()
    # RFC 5120 topologies from the hello's MT TLV ((0,) when absent).
    topologies: tuple = (0,)


@dataclass
class IsisInterface:
    name: str
    config: IsisIfConfig
    addr_ip: IPv4Address
    prefix: IPv4Network
    addr6: object = None  # our link-local (RFC 5308 hello TLV 232)
    prefix6: object = None  # advertised global v6 prefix (TLV 236)
    # Full address lists (ip_interface objects); when empty the single
    # addr_ip/prefix (+prefix6) pair above is the effective list.
    addrs4: list = field(default_factory=list)
    addrs6: list = field(default_factory=list)  # global v6
    mac: bytes = b""  # our SNPA on this circuit
    circuit_id: int = 1
    adj: Adjacency | None = None  # p2p: single adjacency
    adjs: dict = field(default_factory=dict)  # LAN: sysid -> Adjacency
    dis_lan_id: bytes | None = None  # elected DIS (sysid + pn byte)
    srm: set = field(default_factory=set)  # LspIds pending flood on this iface
    # p2p circuits keep SRM set until the PSNP ack (§7.3.15.1); this
    # records the incarnation (seqno) already transmitted so only the
    # RETRANSMIT timer resends an unchanged LSP — inline flushes must
    # not (the reference's retransmission is a timer task, a no-op
    # under its `testing` feature).
    srm_sent: dict = field(default_factory=dict)  # lid -> seqno sent
    ssn: set = field(default_factory=set)  # LspIds pending PSNP ack
    # RFC 7602 state: last accepted (session, packet) per PDU class and
    # our transmit counter.
    esn_rx: dict = field(default_factory=dict)
    esn_tx: int = 0

    @property
    def is_lan(self) -> bool:
        return self.config.circuit_type == "broadcast"

    def v4_addresses(self) -> list:
        """[(ip, network)] — every IPv4 address on this circuit."""
        if self.addrs4:
            return [(ia.ip, ia.network) for ia in self.addrs4]
        return [(self.addr_ip, self.prefix)] if self.prefix is not None else []

    def v6_addresses(self) -> list:
        """[(ip|None, network)] — global IPv6 addresses."""
        if self.addrs6:
            return [(ia.ip, ia.network) for ia in self.addrs6]
        return [(None, self.prefix6)] if self.prefix6 is not None else []

    def all_adjacencies(self) -> list:
        """Every adjacency object regardless of state."""
        if self.is_lan:
            return list(self.adjs.values())
        return [self.adj] if self.adj is not None else []

    def up_adjacencies(self) -> list:
        if self.is_lan:
            return [a for a in self.adjs.values() if a.state == AdjacencyState.UP]
        if self.adj is not None and self.adj.state == AdjacencyState.UP:
            return [self.adj]
        return []

    def we_are_dis(self, self_sysid: bytes, circuit_id: int) -> bool:
        return self.dis_lan_id == self_sysid + bytes((circuit_id,))


@dataclass
class HelloTimerMsg:
    ifname: str


@dataclass
class HoldTimerMsg:
    ifname: str


@dataclass
class LanHoldTimerMsg:
    ifname: str
    sysid: bytes


@dataclass
class CsnpTimerMsg:
    ifname: str


@dataclass
class FloodTimerMsg:
    pass


@dataclass
class AgeTickMsg:
    pass


@dataclass
class SpfTimerMsg:
    pass


@dataclass
class IsisIfUpMsg:
    ifname: str


@dataclass
class IsisIfDownMsg:
    ifname: str


@dataclass
class LspEntry:
    lsp: Lsp
    installed_at: float
    # Provenance for operational-state rendering: received off the wire
    # (vs locally originated), and whether a database copy existed when
    # this instance was installed (a purge for an unknown LSP renders
    # without lifetime leaves; reference state.rs).
    rcvd: bool = False
    had_copy: bool = False
    # Header-only entry: a received purge for an LSP we never held
    # (§7.3.16.4) — renders as id + attributes, no lifetime leaves.
    hdr_only: bool = False

    def remaining_lifetime(self, now: float) -> int:
        return max(0, int(self.lsp.lifetime - (now - self.installed_at)))


class IsisInstance(Actor):
    """One IS-IS routing process (single level for now)."""

    name = "isis"

    def __init__(
        self,
        name: str,
        sysid: bytes,
        area: bytes = b"\x49\x00\x01",
        level: int = 2,
        netio: NetIo | None = None,
        spf_backend: SpfBackend | None = None,
        route_cb=None,
        notif_cb=None,
        auth=None,
        mt_enabled: bool = False,
        sr=None,
        metric_style: str = "wide",  # "wide" | "narrow" | "both"
        lsp_mtu: int | None = None,  # originate lsp-buf-size TLV when set
        te_rid4: IPv4Address | None = None,  # RFC 7794 source-rid stlvs
        te_rid6=None,
        protocols: list | None = None,  # NLPID list override ([0xCC,0x8E])
        node_flag: bool = True,  # RFC 7794 N on loopback host prefixes
    ):
        assert len(sysid) == 6
        self.name = name
        self.sysid = sysid
        self.area = area
        self.level = level
        self.notif_cb = notif_cb
        # Area/domain authentication (packet.AuthCtxIsis): signs LSPs and
        # SNPs end-to-end; hellos use it too unless the circuit overrides
        # (reference holo-isis/src/packet/auth.rs key semantics).
        self.auth = auth
        # RFC 5120 multi-topology ORIGINATION: carry IPv6 in the
        # ipv6-unicast topology (MT id 2) instead of the base topology
        # (the rx side consumes both forms regardless).
        self.mt_enabled = mt_enabled
        # Segment routing (utils.sr.SrConfig): SRGB advertised via the
        # Router Capability TLV, prefix-SIDs as sub-TLVs of the wide IP
        # reach entries (RFC 8667; reference holo-isis/src/sr.rs).
        self.sr = sr
        self.sr_labels: dict = {}
        self.metric_style = metric_style
        self.lsp_mtu = lsp_mtu
        self.te_rid4 = te_rid4
        self.te_rid6 = te_rid6
        self.protocols = protocols
        self.node_flag = node_flag
        # ISO 10589 §7.2.8.1 overload bit: advertised in our LSP flags;
        # an overloaded router stays reachable but is never transit.
        self.overload = False
        # Enabled address families gate route installation per AF.
        self.afs = {"ipv4", "ipv6"}
        # IS-type bits advertised in our LSP flags (ISO 10589 §9.9:
        # IS_TYPE1 always; IS_TYPE2 when the router runs L2).
        self.is_type = 0x03
        # Level-all coupling hooks (protocols.isis.multi): L1 queries
        # att_cb() for the ATT bit; L2 merges extra_reach_cb()'s
        # propagated L1 reachability into its LSP.
        self.att_cb = None
        self.extra_reach_cb = None
        # ISO 10589 §7.2.9.2 receive-side ATT handling can be disabled.
        self.att_ignore = False
        # sysid -> SPT distance from the last SPF (L1->L2 propagation).
        self.vertex_dist: dict = {}
        # RFC 8668-style ECMP clamp (reference spf.rs:920-929).
        self.max_paths: int | None = None
        # RFC 7981 node administrative tags (router-capability sub-TLV).
        self.node_tags: tuple = ()
        # RFC 8491 node MSD advertisement ({msd-type: value}).
        self.node_msd: dict = {}
        # RFC 6232 purge originator identification.
        self.purge_originator = False
        # Redistributed routes ({prefix: metric}) -> external reach.
        self.redist: dict = {}
        # BFD session plumbing: bfd_cb(op, ifname, dst, cfg) emits
        # register/unregister requests over the ibus ("reg"/"unreg").
        self.bfd_cb = None
        # RFC 8667 adjacency-SID label allocator (v4+v6 per adjacency).
        # A mutable box so a level-all composition can share one
        # node-wide label space across its L1/L2 instances.
        self._adj_sid_box = [16]
        # System IPv4 router id (ibus RouterIdUpdate): the router-
        # capability TLV's router-id when no TE rid overrides it.
        self.router_id: IPv4Address | None = None
        # Deferred origination (the reference's LspOriginate task model):
        # when True, non-forced origination only marks pending; the
        # conformance replay fires originate_pending() at the recorded
        # LspOriginate events so seqnos — and therefore LSP bytes and
        # checksums — match the reference's exactly.
        self.deferred_origination = False
        self._orig_pending = False
        # Purges of self-originated fragments we never originate: kept
        # out of the LSDB but flooded via SRM (events.rs:734-740).
        self._srm_phantom: dict = {}
        # lsp_id -> unauthenticated TLV bytes of our last origination
        # (content-unchanged suppression; see _originate_lsp).
        self._plain_raw: dict = {}
        self.netio = netio
        self.backend = spf_backend or ScalarSpfBackend()
        # DeltaPath: previous run's (vertex order, atoms, topology) per
        # MT id — the diff base for in-place device-graph updates.
        self._spf_delta_bases: dict = {}
        self.route_cb = route_cb
        # Production sends an immediate hello on circuit-up and on
        # adjacency transitions (the reference's IntervalTask fires
        # immediately on start).  The conformance harness turns this off:
        # under the reference's `testing` feature hello tasks are no-ops,
        # so recorded cases never contain transmitted hellos.
        self.inline_hellos = True
        self.interfaces: dict[str, IsisInterface] = {}
        self.lsdb: dict[LspId, LspEntry] = {}
        self.routes: dict[IPv4Network, tuple] = {}
        self.connected_prefixes: frozenset = frozenset()
        self.last_installable: dict = {}
        # RFC 5301 dynamic hostnames learned from LSPs (sysid -> name).
        self.hostname = name
        self.hostnames: dict[bytes, str] = {}
        self.spf_run_count = 0
        self._spf_pending = False
        # Convergence-observatory causal ids pending on the next run.
        self._conv_pending: list = []
        # Full-vs-RouteOnly classification (reference
        # holo-isis/src/spf.rs:150-156, lsdb.rs:1558-1612): an LSP whose
        # IS-reachability TLVs are unchanged only needs route
        # recomputation over the cached SPT, not a new Dijkstra.  Any
        # non-LSP event (adjacency churn, config) forces Full.
        self._spf_type_full = True
        self._spt_cache: dict | None = None
        # SPF run log ring (reference spf.rs log_spf_run; 32 entries).
        self.spf_log: list[dict] = []
        # RFC 8405 SPF-delay FSM state surfaced in operational state
        # (reference spf.rs delay FSM; transitions driven by IGP events
        # + the Learn/HoldDown timers the conformance harness replays).
        self.spf_delay_state = "quiet"
        # Flooding reduction: per-sender coverage map rebuilt after each
        # full SPF (reference flooding/manet.rs).  _covered_by[sender
        # sysid] = iface names whose neighbor is adjacent to that sender.
        self.flooding_reduction = False
        self._covered_by: dict[bytes, set[str]] = {}
        # IP fast reroute (holo_tpu.frr.FrrConfig; None = disabled):
        # the default-topology backup table is refreshed by every full
        # SPF; frr_backups maps prefix -> {primary (if, addr) ->
        # (backup, labels)} for the RIB feed.
        self.frr = None
        self.frr_tables: dict = {}
        self.frr_backups: dict = {}
        self._frr_engine = None

    def attach(self, loop_):
        super().attach(loop_)
        self._age_timer = self.loop.timer(self.name, AgeTickMsg)
        self._age_timer.start(1.0)
        self._flood_timer = self.loop.timer(self.name, FloodTimerMsg)
        self._spf_timer = self.loop.timer(self.name, SpfTimerMsg)

    def add_interface(self, ifname: str, cfg: IsisIfConfig, addr: IPv4Address, prefix: IPv4Network, addr6=None, prefix6=None, addrs4=None, addrs6=None, mac: bytes = b"", circuit_id: int | None = None):
        self.interfaces[ifname] = IsisInterface(
            name=ifname, config=cfg, addr_ip=addr, prefix=prefix,
            addr6=addr6, prefix6=prefix6,
            addrs4=list(addrs4 or []), addrs6=list(addrs6 or []), mac=mac,
            circuit_id=circuit_id or (len(self.interfaces) + 1),
        )

    # -- actor

    def handle(self, msg):
        if isinstance(msg, NetRxPacket):
            self._rx(msg)
        elif isinstance(msg, HelloTimerMsg):
            self._send_hello(msg.ifname)
        elif isinstance(msg, HoldTimerMsg):
            self._adj_down(msg.ifname)
        elif isinstance(msg, LanHoldTimerMsg):
            self._lan_adj_down(msg.ifname, msg.sysid)
        elif isinstance(msg, CsnpTimerMsg):
            self._send_periodic_csnp(msg.ifname)
        elif isinstance(msg, FloodTimerMsg):
            self._flush_flooding(retransmit=True)
        elif isinstance(msg, AgeTickMsg):
            self._age_tick()
        elif isinstance(msg, SpfTimerMsg):
            self._spf_pending = False
            self.run_spf()
        elif isinstance(msg, IsisIfUpMsg):
            self.if_up(msg.ifname)
        elif isinstance(msg, IsisIfDownMsg):
            self.if_down(msg.ifname)

    def if_up(self, ifname: str) -> None:
        if ifname in self.interfaces:
            if self.inline_hellos:
                self._send_hello(ifname)
            self._originate_lsp()

    def if_down(self, ifname: str) -> None:
        iface = self.interfaces.pop(ifname, None)
        if iface is None:
            return
        for attr in ("_hello_timer", "_hold_timer"):
            t = getattr(iface, attr, None)
            if t is not None:
                t.cancel()
        self._adj_changed()

    # -- hellos / adjacency (RFC 5303 three-way)

    def _send_hello(self, ifname: str) -> None:
        iface = self.interfaces.get(ifname)
        if iface is None or iface.config.passive:
            return
        if iface.is_lan:
            from holo_tpu.protocols.isis.packet import HelloLan

            lan_id = iface.dis_lan_id or (
                self.sysid + bytes((iface.circuit_id,))
            )
            hello = HelloLan(
                circuit_type=3,
                sysid=self.sysid,
                hold_time=iface.config.hello_interval
                * iface.config.hold_multiplier,
                priority=iface.config.priority,
                lan_id=lan_id,
                level=self.level,
                tlvs={
                    "area_addresses": [self.area],
                    "protocols_supported": self.protocols or [0xCC],
                    "ip_addresses": [ip for ip, _ in iface.v4_addresses()],
                    "ipv6_addresses": (
                        [iface.addr6] if iface.addr6 is not None else []
                    ),
                    # Heard SNPAs: neighbor MACs when known, else the
                    # mock fabric's system-id stand-ins.
                    "is_neighbors": sorted(
                        a.snpa or a.sysid for a in iface.adjs.values()
                    ),
                },
            )
            self._esn_stamp(iface, hello.tlvs)
            self.netio.send(
                ifname, iface.addr_ip, ALL_ISS,
                hello.encode(auth=self._hello_auth(iface)),
            )
        else:
            adj = iface.adj
            if adj is None or adj.state == AdjacencyState.DOWN:
                state = AdjState3Way.DOWN
                nbr_sys = None
            elif adj.state == AdjacencyState.INITIALIZING:
                state = AdjState3Way.INITIALIZING
                nbr_sys = adj.sysid
            else:
                state = AdjState3Way.UP
                nbr_sys = adj.sysid
            hello = HelloP2p(
                circuit_type=3,
                sysid=self.sysid,
                hold_time=iface.config.hello_interval * iface.config.hold_multiplier,
                local_circuit_id=iface.circuit_id,
                tlvs={
                    "area_addresses": [self.area],
                    "protocols_supported": [0xCC],  # IPv4
                    "ip_addresses": [iface.addr_ip],
                    "ipv6_addresses": (
                        [iface.addr6] if iface.addr6 is not None else []
                    ),
                    "p2p_adj": P2pAdjState(
                        state, iface.circuit_id, nbr_sys,
                        iface.circuit_id if nbr_sys else None,
                    ),
                },
            )
            self._esn_stamp(iface, hello.tlvs)
            self.netio.send(
                ifname, iface.addr_ip, ALL_ISS,
                hello.encode(auth=self._hello_auth(iface)),
            )
        t = getattr(iface, "_hello_timer", None)
        if t is None:
            t = self.loop.timer(self.name, lambda: HelloTimerMsg(ifname))
            iface._hello_timer = t
        t.start(iface.config.hello_interval)

    @staticmethod
    def _adj_learn_tlvs(adj: Adjacency, hello) -> None:
        """Record the neighbor's hello TLVs on the adjacency (next hops
        + operational state).  Each hello is authoritative: an address
        family that disappears from the TLVs is cleared."""
        addrs = hello.tlvs.get("ip_addresses") or []
        adj.addr = addrs[0] if addrs else None
        adj.addr6 = next(
            (
                a6
                for a6 in hello.tlvs.get("ipv6_addresses") or []
                if a6.is_link_local
            ),
            None,
        )
        adj.area_addresses = tuple(hello.tlvs.get("area_addresses") or ())
        adj.protocols = tuple(hello.tlvs.get("protocols_supported") or ())
        adj.addrs4 = tuple(addrs)
        adj.addrs6 = tuple(hello.tlvs.get("ipv6_addresses") or ())
        adj.topologies = tuple(
            mt for mt, _a, _o in hello.tlvs.get("mt_ids") or ()
        ) or (0,)

    # -- LAN hellos + DIS election (ISO 10589 §8.4.5)

    def _rx_hello_lan(self, iface: IsisInterface, hello, snpa: bytes = b"") -> None:
        if hello.sysid == self.sysid:
            return
        adj = iface.adjs.get(hello.sysid)
        if adj is None:
            adj = Adjacency(sysid=hello.sysid)
            iface.adjs[hello.sysid] = adj
        adj.hold_time = hello.hold_time
        adj.priority = hello.priority
        adj.lan_id = hello.lan_id
        if snpa:
            adj.snpa = snpa
        self._adj_learn_tlvs(adj, hello)
        old = adj.state
        # ISO 10589 §8.4.2 two-way check: our SNPA in their IS-Neighbors
        # TLV.  Our SNPA is the interface MAC when known (real circuits /
        # replay), else the system id (mock fabric).
        our_snpa = iface.mac or self.sysid
        new = (
            AdjacencyState.UP
            if our_snpa in (hello.tlvs.get("is_neighbors") or [])
            else AdjacencyState.INITIALIZING
        )
        adj.state = new
        if new != old and AdjacencyState.UP in (new, old):
            self._notify_adj_change(
                iface, hello.sysid, new == AdjacencyState.UP
            )
        t = getattr(adj, "_hold_timer", None)
        if t is None:
            t = self.loop.timer(
                self.name,
                lambda s=hello.sysid: LanHoldTimerMsg(iface.name, s),
            )
            adj._hold_timer = t
        t.start(adj.hold_time)
        self._bfd_update_adj(iface, adj)
        if new != old and self.inline_hellos:
            self._send_hello(iface.name)  # accelerate 2-way
        self._run_dis_election(iface)
        if new != old and new == AdjacencyState.UP:
            self._lan_adj_up(iface, adj)

    def _run_dis_election(self, iface: IsisInterface) -> None:
        ups = iface.up_adjacencies()
        if not ups:
            # ISO 10589 §8.4.5: no adjacencies — the LAN has no DIS;
            # purge our pseudonode if we held the role.
            if iface.we_are_dis(self.sysid, iface.circuit_id):
                self._flush_pseudonode(iface)
            if iface.dis_lan_id is not None:
                iface.dis_lan_id = None
                self._adj_changed()
            return
        cands = [(iface.config.priority, self.sysid)]
        for adj in ups:
            cands.append((adj.priority, adj.sysid))
        prio, winner = max(cands)
        new_lan_id = (
            self.sysid + bytes((iface.circuit_id,))
            if winner == self.sysid
            else next(
                (
                    a.lan_id
                    for a in iface.up_adjacencies()
                    if a.sysid == winner and a.lan_id
                ),
                winner + bytes((1,)),
            )
        )
        if new_lan_id == iface.dis_lan_id:
            return
        was_dis = iface.we_are_dis(self.sysid, iface.circuit_id)
        iface.dis_lan_id = new_lan_id
        now_dis = iface.we_are_dis(self.sysid, iface.circuit_id)
        if was_dis and not now_dis:
            self._flush_pseudonode(iface)
        if now_dis:
            t = getattr(iface, "_csnp_timer", None)
            if t is None:
                t = self.loop.timer(
                    self.name, lambda: CsnpTimerMsg(iface.name)
                )
                iface._csnp_timer = t
            t.start(1.0)
        self._adj_changed()

    def _lan_adj_up(self, iface: IsisInterface, adj: Adjacency) -> None:
        self._adj_up(iface)

    def _lan_adj_down(self, ifname: str, sysid: bytes) -> None:
        iface = self.interfaces.get(ifname)
        if iface is None:
            return
        gone = iface.adjs.get(sysid)
        if gone is not None:
            self._bfd_unreg_adj(iface, gone)
            if gone.state == AdjacencyState.UP:
                self._notify_adj_change(iface, sysid, False)
        if iface.adjs.pop(sysid, None) is not None:
            self._run_dis_election(iface)
            self._adj_changed()

    def _send_periodic_csnp(self, ifname: str) -> None:
        """Periodic CSNPs: DIS duty on LANs (10s); on p2p circuits only
        while flooding reduction is enabled (30s) — the recovery net for
        stale-coverage suppression windows."""
        iface = self.interfaces.get(ifname)
        if iface is None:
            return
        if iface.is_lan:
            if not iface.we_are_dis(self.sysid, iface.circuit_id):
                return
            self._send_csnp(iface)
            iface._csnp_timer.start(10.0)
        elif self.flooding_reduction and iface.up_adjacencies():
            self._send_csnp(iface)
            iface._csnp_timer.start(30.0)

    # -- deferred-event entry points (the reference models these as
    # dedicated tasks; the conformance replay drives them directly)

    def send_psnp(self, ifname: str) -> None:
        """Flush this circuit's SSN list as one PSNP (SendPsnp task)."""
        iface = self.interfaces.get(ifname)
        if iface is not None:
            self._flush_ssn(iface)

    def _flush_ssn(self, iface: IsisInterface) -> None:
        now = self.loop.clock.now()
        entries = []
        for lid in sorted(iface.ssn):
            e = self.lsdb.get(lid)
            if e is not None:
                entries.append(
                    (e.remaining_lifetime(now), lid, e.lsp.seqno, e.lsp.cksum)
                )
            iface.ssn.discard(lid)
        if entries:
            snp = Snp(self.level, False, self.sysid, entries)
            self._esn_stamp(iface, snp.tlvs)
            self.netio.send(
                iface.name, iface.addr_ip, ALL_ISS,
                snp.encode(auth=self.auth),
            )

    def send_csnp(self, ifname: str) -> None:
        """Describe the full LSDB on this circuit (SendCsnp task)."""
        iface = self.interfaces.get(ifname)
        if iface is not None:
            self._send_csnp(iface)

    def run_dis_election(self, ifname: str) -> None:
        iface = self.interfaces.get(ifname)
        if iface is not None and iface.is_lan:
            self._run_dis_election(iface)

    def clear_adjacencies(self, ifname: str | None = None) -> None:
        """ietf-isis clear-adjacency RPC: tear down adjacencies (all, or
        one interface's) — the neighbor re-forms them from hellos."""
        for iface in self.interfaces.values():
            if ifname is not None and iface.name != ifname:
                continue
            if iface.is_lan:
                for sysid in list(iface.adjs):
                    self._lan_adj_down(iface.name, sysid)
            elif iface.adj is not None:
                self._adj_down(iface.name)

    def clear_database(self) -> None:
        """ietf-isis clear-database RPC: drop the LSDB, RESTART every
        adjacency (the reference's clear tears them down; hellos re-form
        them), and rebuild our own LSPs from scratch."""
        self.lsdb.clear()
        self._plain_raw.clear()
        for iface in self.interfaces.values():
            iface.srm.clear()
            iface.srm_sent.clear()
            iface.ssn.clear()
            for adj in iface.all_adjacencies():
                self._bfd_unreg_adj(iface, adj)
            iface.adj = None
            iface.adjs.clear()
        self._originate_lsp(force=True)
        self._schedule_spf()

    def sr_allocate_adj_sids(self) -> None:
        """Allocate v4+v6 adjacency-SID labels for every up adjacency
        that lacks them (RFC 8667 §2.2; V|L value/local label form)."""
        for iface in self.interfaces.values():
            for adj in iface.up_adjacencies():
                if not adj.adj_sids:
                    v4 = self._adj_sid_box[0]
                    v6 = v4 + 1
                    self._adj_sid_box[0] = v4 + 2
                    adj.adj_sids = (
                        (0x30, 0, v4),  # V|L
                        (0xB0, 0, v6),  # F|V|L
                    )

    def set_hostname(self, hostname: str) -> None:
        """RFC 5301: our dynamic hostname changed; re-originate."""
        if hostname != self.hostname:
            self.hostname = hostname
            self._originate_lsp()

    def refresh_lsp(self, lid: LspId) -> None:
        """Periodic refresh of one self-originated LSP (seqno bump even
        with unchanged content)."""
        if lid.sysid != self.sysid:
            return
        if lid.pseudonode == 0:
            self._originate_lsp(force=True)
        else:
            self._originate_pseudonodes(force=True)

    def purge_lsp(self, lid: LspId) -> None:
        """ISO 10589 §7.3.16.4 purge: flood a body-less zero-lifetime
        header so neighbors drop the LSP too (the reference's LspPurge
        event on expiry)."""
        e = self.lsdb.get(lid)
        if e is None:
            return
        tlvs = {}
        if self.purge_originator:
            # RFC 6232 §3: the purge carries the POI TLV naming us plus
            # our dynamic hostname.
            tlvs["purge_originator"] = [self.sysid]
            tlvs["hostname"] = self.hostname
        dead = Lsp(self.level, 0, lid, e.lsp.seqno, e.lsp.flags, tlvs)
        dead.encode(auth=self.auth)
        # §7.3.16.4: the purge advertises the original checksum.  Patch
        # the wire bytes too so SNP descriptions and the flooded PDU
        # agree (zero-lifetime LSPs skip checksum verification).
        dead.cksum = e.lsp.cksum
        raw = bytearray(dead.raw)
        raw[24:26] = e.lsp.cksum.to_bytes(2, "big")
        dead.raw = bytes(raw)
        self._install_lsp(dead, flood_from=None)

    def _flush_pseudonode(self, iface: IsisInterface) -> None:
        lsp_id = LspId(self.sysid, pseudonode=iface.circuit_id)
        e = self.lsdb.get(lsp_id)
        if e is not None and e.lsp.lifetime > 0:
            self.purge_lsp(lsp_id)
            self._plain_raw.pop(lsp_id, None)

    def _rx_hello(self, iface: IsisInterface, hello: HelloP2p) -> None:
        if hello.sysid == self.sysid:
            return
        adj = iface.adj
        if adj is None or adj.sysid != hello.sysid:
            if adj is not None and adj.state == AdjacencyState.UP:
                # A different system took over the link: the old
                # neighbor is gone even though no timer fired.
                self._notify_adj_change(iface, adj.sysid, False)
            adj = Adjacency(sysid=hello.sysid)
            iface.adj = adj
        adj.hold_time = hello.hold_time
        # The hello's circuit type drives the adjacency's rendered usage
        # on p2p links (level-1/level-2/level-all), independent of our
        # own level (reference adjacency arena).
        adj.usage_ctype = hello.circuit_type
        self._adj_learn_tlvs(adj, hello)
        p2p = hello.tlvs.get("p2p_adj")
        old = adj.state
        if p2p is None:
            # Classic ISO 10589 §8.2.4 p2p: no three-way TLV, the
            # adjacency comes up on hello receipt.
            new = AdjacencyState.UP
        elif p2p.neighbor_sysid == self.sysid:
            new = AdjacencyState.UP
        else:
            new = AdjacencyState.INITIALIZING
        adj.state = new
        t = getattr(iface, "_hold_timer", None)
        if t is None:
            t = self.loop.timer(self.name, lambda: HoldTimerMsg(iface.name))
            iface._hold_timer = t
        t.start(adj.hold_time)
        self._bfd_update_adj(iface, adj)
        if new != old:
            if AdjacencyState.UP in (new, old):
                self._notify_adj_change(
                    iface, adj.sysid, new == AdjacencyState.UP
                )
            if self.inline_hellos:
                self._send_hello(iface.name)  # accelerate the handshake
            if new == AdjacencyState.UP:
                self._adj_up(iface)
            elif old == AdjacencyState.UP:
                self._adj_changed()

    def _send_csnp(self, iface: IsisInterface) -> None:
        """Describe the whole LSDB as a CSNP on this interface."""
        now = self.loop.clock.now()
        entries = [
            (e.remaining_lifetime(now), lid, e.lsp.seqno, e.lsp.cksum)
            for lid, e in sorted(self.lsdb.items())
        ]
        snp = Snp(self.level, True, self.sysid, entries)
        self._esn_stamp(iface, snp.tlvs)
        self.netio.send(
            iface.name, iface.addr_ip, ALL_ISS, snp.encode(auth=self.auth)
        )

    def _esn_stamp(self, iface: IsisInterface, tlvs: dict) -> None:
        """RFC 7602: stamp outgoing hellos/SNPs with the next extended
        sequence number when the circuit runs ESN."""
        if iface.config.esn_mode in ("send-only", "send-and-verify"):
            iface.esn_tx += 1
            tlvs["ext_seqnum"] = (1, iface.esn_tx)

    def _bfd_dsts(self, adj: Adjacency):
        out = []
        if adj.addr is not None:
            out.append(adj.addr)
        if adj.addr6 is not None:
            out.append(adj.addr6)
        return out

    def _bfd_update_adj(self, iface: IsisInterface, adj: Adjacency, force: bool = False) -> None:
        """(Re)register this adjacency's per-AF BFD sessions (reference
        adjacency.rs bfd_update_sessions: runs on every hello while BFD
        is enabled, any adjacency state)."""
        if not iface.config.bfd_enabled or self.bfd_cb is None:
            return
        cfg = {
            "local_multiplier": iface.config.bfd_multiplier,
            "min_tx": iface.config.bfd_min_tx,
            "min_rx": iface.config.bfd_min_rx,
        }
        want = self._bfd_dsts(adj)
        have = list(adj.bfd_sessions)
        for dst in want:
            if dst not in have or force:
                self.bfd_cb("reg", iface.name, dst, cfg)
        for dst in have:
            if dst not in want:
                self.bfd_cb("unreg", iface.name, dst, None)
        adj.bfd_sessions = tuple(want)

    def _bfd_unreg_adj(self, iface: IsisInterface, adj: Adjacency) -> None:
        if self.bfd_cb is None:
            return
        for dst in adj.bfd_sessions:
            self.bfd_cb("unreg", iface.name, dst, None)
        adj.bfd_sessions = ()

    def set_bfd_config(self, ifname: str, enabled: bool, min_tx: int | None = None, min_rx: int | None = None) -> None:
        """Enable/disable/retune BFD on a circuit; sessions for current
        up adjacencies (un)register accordingly."""
        iface = self.interfaces.get(ifname)
        if iface is None:
            return
        was = iface.config.bfd_enabled
        if was and not enabled:
            for adj in iface.all_adjacencies():
                self._bfd_unreg_adj(iface, adj)
        iface.config.bfd_enabled = enabled
        if min_tx is not None:
            iface.config.bfd_min_tx = min_tx
        if min_rx is not None:
            iface.config.bfd_min_rx = min_rx
        if enabled:
            # New registration or parameter change re-registration.
            for adj in iface.all_adjacencies():
                self._bfd_update_adj(iface, adj, force=True)

    def bfd_state_down(self, ifname: str, dst) -> None:
        """BFD declared the path dead: kill the matching adjacency
        immediately (the reference's fast-failure integration)."""
        iface = self.interfaces.get(ifname)
        if iface is None:
            return
        if iface.is_lan:
            for sysid, adj in list(iface.adjs.items()):
                if dst in (adj.addr, adj.addr6):
                    self._lan_adj_down(ifname, sysid)
        elif iface.adj is not None and dst in (
            iface.adj.addr, iface.adj.addr6
        ):
            # The failed adjacency stays visible in the Down state (the
            # reference deletes it only on hello re-init or hold expiry).
            adj = iface.adj
            self._bfd_unreg_adj(iface, adj)
            if adj.state == AdjacencyState.UP:
                self._notify_adj_change(iface, adj.sysid, False)
            adj.state = AdjacencyState.DOWN
            iface.srm.clear()
            iface.srm_sent.clear()
            iface.ssn.clear()
            self._adj_changed()

    def _adj_up(self, iface: IsisInterface) -> None:
        # Sync databases: send CSNP describing our LSDB + set SRM on all
        # (ISO 10589 §7.3.17 behavior for p2p).
        self._send_csnp(iface)
        for lid in self.lsdb:
            iface.srm.add(lid)
            iface.srm_sent.pop(lid, None)
        if self.flooding_reduction and not iface.is_lan:
            t = getattr(iface, "_csnp_timer", None)
            if t is None:
                t = self.loop.timer(
                    self.name, lambda n=iface.name: CsnpTimerMsg(n)
                )
                iface._csnp_timer = t
            t.start(30.0)
        self._arm_flood()
        self._adj_changed()

    def _adj_down(self, ifname: str) -> None:
        iface = self.interfaces.get(ifname)
        if iface is None or iface.adj is None:
            return
        if iface.adj.state == AdjacencyState.UP:
            self._notify_adj_change(iface, iface.adj.sysid, False)
        self._bfd_unreg_adj(iface, iface.adj)
        iface.adj = None
        iface.srm.clear()
        iface.srm_sent.clear()
        iface.ssn.clear()
        self._adj_changed()

    # ----- YANG notifications (reference holo-isis
    # northbound/notification.rs: common leaves per notification)

    def _notify(self, kind: str, data: dict) -> None:
        if self.notif_cb is not None:
            self.notif_cb({f"ietf-isis:{kind}": data})

    def _notif_common(self, iface=None) -> dict:
        lvl = {1: "level-1", 2: "level-2"}.get(self.level, "level-all")
        d = {
            # Level-all nodes override display_name: notifications name
            # the configured protocol instance, not the per-level actor.
            "routing-protocol-name": getattr(
                self, "display_name", self.name
            ),
            "isis-level": lvl,
        }
        if iface is not None:
            d["interface-name"] = iface.name
            d["interface-level"] = lvl
        return d

    def _notify_adj_change(self, iface, sysid: bytes, up: bool) -> None:
        from holo_tpu.protocols.isis.nb_state import sysid_str

        _ISIS_ADJ_TRANSITIONS.labels(
            instance=self.name, to="up" if up else "down"
        ).inc()
        self._notify(
            "adjacency-state-change",
            self._notif_common(iface)
            | {
                "neighbor-system-id": sysid_str(sysid),
                "state": "up" if up else "down",
            },
        )

    def _notify_decode_error(self, iface, data, err, rx_auth) -> None:
        """Reference notification.rs:161-188: wrong/missing auth TLV
        type vs a failed digest are separate notifications.  Only an
        authenticated circuit alarms — garbage frames on an open circuit
        are not a security event."""
        from holo_tpu.protocols.isis.packet import AuthError, AuthTypeError

        if rx_auth is None or not isinstance(err, AuthError):
            return
        import base64

        kind = (
            "authentication-type-failure"
            if isinstance(err, AuthTypeError)
            else "authentication-failure"
        )
        self._notify(
            kind,
            self._notif_common(iface)
            | {"raw-pdu": base64.b64encode(data[:64]).decode()},
        )

    def _notify_seqno_skipped(self, iface, lsp) -> None:
        from holo_tpu.protocols.isis.nb_state import lsp_id_str

        self._notify(
            "sequence-number-skipped",
            self._notif_common(iface) | {"lsp-id": lsp_id_str(lsp.lsp_id)},
        )

    def set_overload(self, on: bool) -> None:
        """ISO 10589 §7.2.8.1 overload bit with the reference's
        database-overload notification (notification.rs:28-38)."""
        if self.overload == bool(on):
            return
        self.overload = bool(on)
        self._notify(
            "database-overload",
            self._notif_common() | {"overload": "on" if on else "off"},
        )
        self._originate_lsp(force=True)

    def _adj_changed(self) -> None:
        # No direct SPF trigger: the RFC 8405 Igp event fires from LSP
        # CONTENT changes at install (reference lsdb.rs:1606-1618) — if
        # the adjacency change altered our LSP, the re-origination below
        # schedules it; a LAN member losing an adjacency it never
        # advertised (the DIS does) waits for the pseudonode update.
        self._originate_lsp()

    # -- LSP origination

    def _originate_lsp(self, force: bool = False, min_seqno: int = 0) -> None:
        """(Re-)originate our LSP.  ``force`` bypasses the content-unchanged
        skip (periodic refresh MUST bump seqno even with identical TLVs or
        neighbors age us out); ``min_seqno`` outpaces a stale incarnation
        seen in the network (ISO 10589 §7.3.16.1)."""
        if self.deferred_origination and not force:
            self._orig_pending = True
            return
        lsp_id = LspId(self.sysid)
        old = self.lsdb.get(lsp_id)
        wide = self.metric_style in ("wide", "both")
        narrow = self.metric_style in ("narrow", "both")
        is_reach = []
        narrow_is = []
        ip4_addrs: list = []
        ip4_prefixes: dict = {}  # prefix -> metric (BTreeMap dedup)
        ip6_reach_map: dict = {}
        ip6_addrs = []
        sids = (
            self.sr.prefix_sids
            if self.sr is not None and self.sr.enabled
            else {}
        )
        for iface in self.interfaces.values():
            metric = iface.config.metric
            if_afs = (
                iface.config.afs
                if iface.config.afs is not None
                else self.afs
            )
            for ip, net in iface.v4_addresses() if "ipv4" in if_afs else []:
                if ip not in ip4_addrs:
                    ip4_addrs.append(ip)
                ip4_prefixes.setdefault(net, (metric, iface))
            for ip6, net6 in iface.v6_addresses() if "ipv6" in if_afs else []:
                if ip6 is not None and ip6 not in ip6_addrs:
                    ip6_addrs.append(ip6)
                if net6 is not None and net6 not in ip6_reach_map:
                    attr = 0
                    if (
                        self.node_flag
                        and iface.config.loopback
                        and net6.prefixlen == 128
                    ):
                        attr |= PREFIX_ATTR_N
                    psid6 = sids.get(net6)
                    ip6_reach_map[net6] = ExtIpReach(
                        net6, metric,
                        sid_index=psid6.index if psid6 is not None else None,
                        sid_flags=_sid_flags(psid6),
                        attr_flags=attr or None,
                        src_rid4=self.te_rid4,
                        src_rid6=self.te_rid6,
                    )
            if iface.addr6 is not None:
                lla = iface.addr6
                if lla not in ip6_addrs and not lla.is_link_local:
                    ip6_addrs.append(lla)
            link_msd = (
                tuple(sorted(iface.config.msd.items()))
                if iface.config.msd
                else None
            )
            sr_on = self.sr is not None and self.sr.enabled
            if iface.is_lan:
                if iface.dis_lan_id is not None and iface.up_adjacencies():
                    # LAN: advertise reach to the pseudonode.
                    if wide:
                        is_reach.append(
                            ExtIsReach(
                                iface.dis_lan_id, metric, link_msd=link_msd
                            )
                        )
                    if narrow:
                        narrow_is.append(
                            ExtIsReach(
                                iface.dis_lan_id,
                                min(metric, MAX_NARROW_METRIC),
                            )
                        )
            elif iface.adj is not None and iface.adj.state == AdjacencyState.UP:
                if wide:
                    is_reach.append(
                        ExtIsReach(
                            iface.adj.sysid + b"\x00", metric,
                            link_msd=link_msd,
                            adj_sids=(
                                iface.adj.adj_sids
                                if sr_on and iface.adj.adj_sids
                                else None
                            ),
                        )
                    )
                if narrow:
                    narrow_is.append(
                        ExtIsReach(
                            iface.adj.sysid + b"\x00",
                            min(metric, MAX_NARROW_METRIC),
                        )
                    )
        ip_reach = []
        narrow_ip = []
        for net in sorted(ip4_prefixes, key=lambda p: (int(p.network_address), p.prefixlen)):
            metric, iface = ip4_prefixes[net]
            if wide:
                attr = 0
                if (
                    self.node_flag
                    and iface.config.loopback
                    and net.prefixlen == 32
                ):
                    attr |= PREFIX_ATTR_N
                psid = sids.get(net)
                ip_reach.append(
                    ExtIpReach(
                        net, metric,
                        sid_index=psid.index if psid is not None else None,
                        sid_flags=_sid_flags(psid),
                        attr_flags=attr or None,
                        src_rid4=self.te_rid4,
                        src_rid6=self.te_rid6,
                    )
                )
            if narrow:
                narrow_ip.append(
                    ExtIpReach(net, min(metric, MAX_NARROW_METRIC))
                )
        # Redistributed routes: RFC 1195 external reach (TLV 130 narrow;
        # wide entries share TLV 135; v6 entries set the X bit).
        narrow_ext = []
        for net in sorted(
            self.redist,
            key=lambda p: (p.version, int(p.network_address), p.prefixlen),
        ):
            metric = self.redist[net]
            if net.version == 4:
                if narrow:
                    narrow_ext.append(
                        ExtIpReach(
                            net, min(metric, MAX_NARROW_METRIC),
                            external=True,
                        )
                    )
                if wide and net not in ip4_prefixes:
                    ip_reach.append(ExtIpReach(net, metric))
            elif net not in ip6_reach_map:
                ip6_reach_map[net] = ExtIpReach(net, metric, external=True)
        ip6_reach = [
            ip6_reach_map[p]
            for p in sorted(
                ip6_reach_map,
                key=lambda p: (int(p.network_address), p.prefixlen),
            )
        ]
        ip4_addrs.sort(key=int)
        ip6_addrs.sort(key=int)
        if self.protocols is not None:
            protos = list(self.protocols)
        else:
            protos = [0xCC] + ([0x8E] if (ip6_reach or ip6_addrs) else [])
        tlvs = {
            "area_addresses": [self.area],
            "protocols_supported": protos,
            "hostname": self.hostname,
            "ext_is_reach": is_reach,
            "ext_ip_reach": ip_reach,
            "narrow_is_reach": narrow_is,
            "narrow_ip_reach": narrow_ip,
            "narrow_ip_ext_reach": narrow_ext,
            "ip_addresses": ip4_addrs,
            "ipv6_reach": ip6_reach,
            "ipv6_addresses": ip6_addrs,
        }
        if self.te_rid4 is not None:
            tlvs["ipv4_router_id"] = self.te_rid4
        if self.te_rid6 is not None:
            tlvs["ipv6_router_id"] = self.te_rid6
        if self.lsp_mtu is not None:
            tlvs["lsp_buf_size"] = self.lsp_mtu
        if self.node_tags:
            tlvs["node_tags"] = tuple(self.node_tags)
        if self.node_msd:
            tlvs["node_msd"] = dict(self.node_msd)
        if (
            self.sr is not None
            and self.sr.enabled
            and getattr(self.sr, "srgb_set", True)
        ):
            tlvs["sr_cap"] = (self.sr.srgb.lower, self.sr.srgb.size)
            if self.sr.srlb:
                lo, hi = self.sr.srlb
                tlvs["srlb"] = (lo, hi - lo + 1)
        if tlvs.get("sr_cap") or tlvs.get("node_tags") or tlvs.get("node_msd"):
            tlvs["cap_router_id"] = self.te_rid4 or self.router_id
        if self.mt_enabled:
            # Membership in the base + ipv6-unicast topologies, v6 reach
            # and v6-topology adjacencies under the MT TLVs.
            tlvs["mt_ids"] = [(0, False, False), (MT_IPV6, False, False)]
            tlvs["mt_ipv6_reach"] = [(MT_IPV6, e) for e in ip6_reach]
            tlvs["ipv6_reach"] = []
            tlvs["mt_is_reach"] = [(MT_IPV6, e) for e in is_reach]
        if self.extra_reach_cb is not None:
            # Level-all L2: merge propagated L1 reachability (metric
            # already includes the L1 SPT distance; lowest wins) and
            # active summaries (lsdb.rs lsp_propagate_l1_to_l2).
            xnarrow, xwide, xv6, xnarrow_ext = self.extra_reach_cb()

            def _merge(own_list, extra):
                have = {r.prefix: i for i, r in enumerate(own_list)}
                for r in extra:
                    i = have.get(r.prefix)
                    if i is None:
                        own_list.append(r)
                    elif r.metric < own_list[i].metric:
                        own_list[i] = r
                own_list.sort(
                    key=lambda r: (
                        int(r.prefix.network_address), r.prefix.prefixlen
                    )
                )

            if narrow:
                _merge(tlvs["narrow_ip_reach"], xnarrow)
                _merge(tlvs["narrow_ip_ext_reach"], xnarrow_ext)
            if wide:
                _merge(tlvs["ext_ip_reach"], xwide)
            if self.mt_enabled:
                # MT routers carry v6 under TLV 237 (topology 2).
                have6 = {r.prefix for _mt, r in tlvs.get("mt_ipv6_reach", [])}
                tlvs.setdefault("mt_ipv6_reach", []).extend(
                    (MT_IPV6, r) for r in xv6 if r.prefix not in have6
                )
            else:
                _merge(tlvs["ipv6_reach"], xv6)
        seqno = max((old.lsp.seqno + 1) if old else 1, min_seqno)
        flags = self.is_type | (0x04 if self.overload else 0)
        if (
            self.att_cb is not None
            and not self.overload
            and self.att_cb()
        ):
            flags |= 0x40  # ATT (default-metric bit)
        lsp = Lsp(self.level, LSP_MAX_AGE, lsp_id, seqno, flags=flags, tlvs=tlvs)
        # Content comparison uses the UNauthenticated bytes: the auth
        # digest covers the seqno, so authenticated raw always differs.
        plain = lsp.encode()
        if (
            not force
            and self._plain_raw.get(lsp_id) == plain[26:]
        ):
            self._originate_pseudonodes()
            return  # content unchanged
        self._plain_raw[lsp_id] = plain[26:]
        lsp.encode(auth=self.auth)
        self._install_lsp(lsp, flood_from=None)
        self._originate_pseudonodes()

    def originate_pending(self) -> None:
        """Run a deferred origination now (recorded LspOriginate event)."""
        self._orig_pending = False
        saved = self.deferred_origination
        self.deferred_origination = False
        try:
            self._originate_lsp()
        finally:
            self.deferred_origination = saved

    def _originate_pseudonodes(self, force: bool = False) -> None:
        """DIS duty: one pseudonode LSP per LAN we are DIS of, listing all
        members (incl. ourselves) at metric 0.  ``force`` bypasses the
        content-unchanged skip for periodic refresh (same seqno-bump
        requirement as the node LSP)."""
        for iface in self.interfaces.values():
            if not iface.is_lan or not iface.we_are_dis(
                self.sysid, iface.circuit_id
            ):
                continue
            lsp_id = LspId(self.sysid, pseudonode=iface.circuit_id)
            # Reference member order (lsdb.rs lsp_build_tlvs_pseudo):
            # adjacencies in arena (first-heard) order, ourselves last.
            members = [
                a.sysid + b"\x00" for a in iface.up_adjacencies()
            ] + [self.sysid + b"\x00"]
            tlvs = {"protocols_supported": []}
            if self.metric_style in ("wide", "both"):
                tlvs["ext_is_reach"] = [ExtIsReach(m, 0) for m in members]
            if self.metric_style in ("narrow", "both"):
                tlvs["narrow_is_reach"] = [ExtIsReach(m, 0) for m in members]
            old = self.lsdb.get(lsp_id)
            seqno = (old.lsp.seqno + 1) if old else 1
            lsp = Lsp(self.level, LSP_MAX_AGE, lsp_id, seqno, tlvs=tlvs)
            plain = lsp.encode()
            if not force and self._plain_raw.get(lsp_id) == plain[26:]:
                continue
            self._plain_raw[lsp_id] = plain[26:]
            lsp.encode(auth=self.auth)
            self._install_lsp(lsp, flood_from=None)

    # -- LSDB install + flooding (SRM/SSN model)

    def _install_lsp(self, lsp: Lsp, flood_from: str | None) -> None:
        now = self.loop.clock.now()
        prev = self.lsdb.get(lsp.lsp_id)
        self.lsdb[lsp.lsp_id] = LspEntry(
            lsp, now,
            rcvd=flood_from is not None,
            # Only a LIVE copy counts (not SNP shells or prior purges).
            had_copy=prev is not None
            and prev.lsp.seqno != 0
            and prev.lsp.lifetime > 0,
        )
        # RFC 5301: learn/forget the originator's dynamic hostname.
        if lsp.lsp_id.pseudonode == 0 and lsp.lsp_id.fragment == 0:
            name = lsp.tlvs.get("hostname")
            if name and lsp.lifetime > 0:
                self.hostnames[lsp.lsp_id.sysid] = name
            else:
                self.hostnames.pop(lsp.lsp_id.sysid, None)
        # Flooding reduction: interfaces whose neighbor the SENDER also
        # covers (sound: the sender floods its own neighborhood; periodic
        # CSNPs recover stale-coverage windows).
        suppressed: set[str] = set()
        if self.flooding_reduction and flood_from is not None:
            sender_iface = self.interfaces.get(flood_from)
            if sender_iface is not None and sender_iface.adj is not None:
                suppressed = self._covered_by.get(
                    sender_iface.adj.sysid, set()
                )
        for iface in self.interfaces.values():
            if not iface.up_adjacencies():
                continue
            if iface.name == flood_from:
                iface.srm.discard(lsp.lsp_id)
                iface.srm_sent.pop(lsp.lsp_id, None)
                if not iface.is_lan:
                    iface.ssn.add(lsp.lsp_id)  # p2p ack via PSNP
            elif iface.name in suppressed:
                continue
            else:
                iface.srm.add(lsp.lsp_id)
                iface.srm_sent.pop(lsp.lsp_id, None)
        self._arm_flood()
        # SPF (and the RFC 8405 Igp event) fires only on CONTENT change —
        # a pure refresh (same TLVs/flags/liveness, new seqno) schedules
        # nothing (reference lsdb.rs:1558-1618).
        content_change = not (
            prev is not None
            and prev.lsp.is_expired == lsp.is_expired
            and prev.lsp.flags == lsp.flags
            and prev.lsp.tlvs == lsp.tlvs
        )
        if content_change and lsp.seqno != 0:
            # Full SPF only when the IS-reachability (or flags/liveness)
            # changed; a prefix-only change is a RouteOnly run over the
            # cached SPT (reference lsdb.rs:1604-1612 topology_change).
            topology_change = not (
                prev is not None
                and prev.lsp.is_expired == lsp.is_expired
                and prev.lsp.flags == lsp.flags
                and all(
                    prev.lsp.tlvs.get(k) == lsp.tlvs.get(k)
                    for k in (
                        "ext_is_reach",
                        "narrow_is_reach",
                        "mt_is_reach",
                        "mt_ids",
                    )
                )
            )
            self._schedule_spf(topology=topology_change)

    def _arm_flood(self) -> None:
        if not self._flood_timer.armed:
            self._flood_timer.start(0.05)

    def _flush_flooding(
        self, srm_only: bool = False, retransmit: bool = False
    ) -> None:
        now = self.loop.clock.now()
        for iface in self.interfaces.values():
            if iface.srm:
                for lid in list(iface.srm):
                    e = self.lsdb.get(lid)
                    if e is None:
                        ph = self._srm_phantom.get(lid)
                        if ph is None or not ph.raw:
                            iface.srm.discard(lid)
                            iface.srm_sent.pop(lid, None)
                            continue
                        if (
                            not retransmit
                            and iface.srm_sent.get(lid) == (ph.seqno, ph.is_expired)
                        ):
                            continue  # ack pending; timer resends
                        self.netio.send(
                            iface.name, iface.addr_ip, ALL_ISS, ph.raw
                        )
                        if iface.is_lan:
                            iface.srm.discard(lid)
                        else:
                            iface.srm_sent[lid] = (ph.seqno, ph.is_expired)
                        continue
                    if not e.lsp.raw:
                        continue  # zero-seqno placeholder: nothing to send
                    if (
                        not retransmit
                        and iface.srm_sent.get(lid) == (e.lsp.seqno, e.lsp.is_expired)
                    ):
                        continue  # unchanged + unacked: timer's job
                    self.netio.send(iface.name, iface.addr_ip, ALL_ISS, e.lsp.raw)
                    if iface.is_lan:
                        # §7.3.15.1: broadcast circuits clear SRM after
                        # transmit (the DIS's CSNPs recover losses);
                        # p2p keeps it until the PSNP ack.
                        iface.srm.discard(lid)
                    else:
                        iface.srm_sent[lid] = (e.lsp.seqno, e.lsp.is_expired)
            if srm_only:
                continue
            if iface.ssn:
                self._flush_ssn(iface)
        if any(i.srm for i in self.interfaces.values()):
            self._flood_timer.start(5.0)  # p2p retransmit interval

    # -- rx dispatch

    def _hello_auth(self, iface):
        return iface.config.auth or self.auth

    def _rx(self, msg: NetRxPacket) -> None:
        iface = self.interfaces.get(msg.ifname)
        if iface is None:
            return
        # Hellos authenticate with the circuit key; LSPs/SNPs carry the
        # end-to-end area key (the originator's signature is forwarded).
        hello_types = (
            PduType.HELLO_P2P, PduType.HELLO_LAN_L1, PduType.HELLO_LAN_L2
        )
        probe = msg.data[4] & 0x1F if len(msg.data) > 4 else 0
        rx_auth = (
            self._hello_auth(iface)
            if probe in tuple(int(t) for t in hello_types)
            else self.auth
        )
        try:
            pdu_type, pdu = decode_pdu(msg.data, auth=rx_auth)
        except DecodeError as e:
            _ISIS_RX_BAD.labels(instance=self.name).inc()
            self._notify_decode_error(iface, msg.data, e, rx_auth)
            return
        _ISIS_PDUS_RX.labels(instance=self.name).inc()
        snpa = msg.src if isinstance(msg.src, bytes) else b""
        self.rx_pdu(msg.ifname, pdu_type, pdu, snpa)

    def rx_pdu(self, ifname: str, pdu_type: PduType, pdu, snpa: bytes = b"") -> None:
        """Dispatch one decoded PDU (the conformance replay feeds decoded
        objects directly, like the reference's testing stub)."""
        iface = self.interfaces.get(ifname)
        if iface is None or iface.config.passive:
            return
        # Circuit-type sanity precedes everything: mismatched hello
        # kinds never advance protocol state of any sort.
        if pdu_type == PduType.HELLO_P2P and iface.is_lan:
            return
        if (
            pdu_type in (PduType.HELLO_LAN_L1, PduType.HELLO_LAN_L2)
            and not iface.is_lan
        ):
            return
        if iface.config.esn_mode == "send-and-verify" and pdu_type not in (
            PduType.LSP_L1, PduType.LSP_L2
        ):
            # RFC 7602 §3: hellos and SNPs must carry a strictly
            # increasing extended sequence number or be discarded.
            # State is per sending system per PDU type — independent
            # neighbors run independent sequence spaces.
            esn = (getattr(pdu, "tlvs", None) or {}).get("ext_seqnum")
            if esn is None:
                return
            key = (getattr(pdu, "sysid", b""), int(pdu_type))
            last = iface.esn_rx.get(key)
            if last is not None and esn <= last:
                return  # replayed or stale
            iface.esn_rx[key] = esn
        if pdu_type == PduType.HELLO_P2P:
            self._rx_hello(iface, pdu)
        elif pdu_type in (PduType.HELLO_LAN_L1, PduType.HELLO_LAN_L2):
            self._rx_hello_lan(iface, pdu, snpa)
        elif pdu_type in (PduType.LSP_L1, PduType.LSP_L2):
            self._rx_lsp(iface, pdu)
        elif pdu_type in (PduType.CSNP_L1, PduType.CSNP_L2):
            self._rx_csnp(iface, pdu)
        elif pdu_type in (PduType.PSNP_L1, PduType.PSNP_L2):
            self._rx_psnp(iface, pdu)

    def _rx_lsp(self, iface: IsisInterface, lsp: Lsp) -> None:
        if not iface.up_adjacencies():
            return
        cur = self.lsdb.get(lsp.lsp_id)
        now = self.loop.clock.now()
        if lsp.lsp_id.sysid == self.sysid and lsp.is_expired:
            # ietf-isis own-lsp-purge: we RECEIVED a purged copy of one
            # of our own LSPs (reference events.rs gates the event on
            # zero remaining lifetime, not on stale live incarnations).
            from holo_tpu.protocols.isis.nb_state import lsp_id_str

            self._notify(
                "own-lsp-purge",
                self._notif_common(iface)
                | {"lsp-id": lsp_id_str(lsp.lsp_id)},
            )
        # LSP expiration synchronization (ISO 10589 §7.3.16.4.a): an
        # expired LSP we have no copy of is never installed; on p2p
        # circuits it is acknowledged directly with a PSNP.
        if lsp.is_expired and cur is None:
            if not iface.is_lan:
                snp = Snp(
                    self.level, False, self.sysid,
                    [(0, lsp.lsp_id, lsp.seqno, lsp.cksum)],
                )
                self.netio.send(
                    iface.name, iface.addr_ip, ALL_ISS,
                    snp.encode(auth=self.auth),
                )
            return
        # Self-originated received NEWER: outpace it (§7.3.16.1) — also
        # when we hold no copy (restart case: stale incarnation in the
        # network must not outlive our fresh origination).  An EQUAL or
        # older copy flows through the generic comparison below (equal =
        # implicit ack; older = send ours back).
        if lsp.lsp_id.sysid == self.sysid:
            if cur is None:
                # A fragment we don't currently originate: purge the
                # received incarnation network-wide without installing
                # it (reference events.rs:734-740).  The LSP checksum
                # excludes the lifetime field, so zeroing it in place
                # keeps the signature valid.
                lsp.lifetime = 0
                if lsp.raw:
                    raw = bytearray(lsp.raw)
                    raw[10:12] = b"\x00\x00"
                    lsp.raw = bytes(raw)
                self._srm_phantom[lsp.lsp_id] = lsp
                for other in self.interfaces.values():
                    if other.up_adjacencies():
                        other.srm.add(lsp.lsp_id)
                        other.srm_sent.pop(lsp.lsp_id, None)
                self._arm_flood()
                return
            if lsp.compare(
                cur.remaining_lifetime(now), cur.lsp.seqno, cur.lsp.cksum
            ) > 0:
                self._notify_seqno_skipped(iface, lsp)
                self._originate_lsp(force=True, min_seqno=lsp.seqno + 1)
                return
        if cur is None:
            c = 1
        else:
            c = lsp.compare(
                cur.remaining_lifetime(now), cur.lsp.seqno, cur.lsp.cksum
            )
        if c > 0:
            if (
                lsp.is_expired
                and self.purge_originator
                and not lsp.tlvs.get("purge_originator")
            ):
                # RFC 6232 §3: a relayed purge without a POI TLV gains
                # one naming us and the system we received it from.
                if iface.is_lan:
                    # Any single up adjacency identifies the relayer on
                    # a LAN only when unambiguous.
                    ups = iface.up_adjacencies()
                    sender = ups[0].sysid if len(ups) == 1 else None
                elif iface.adj is not None:
                    sender = iface.adj.sysid
                else:
                    sender = None
                lsp.tlvs["purge_originator"] = [self.sysid] + (
                    [sender] if sender else []
                )
                lsp.tlvs["hostname"] = self.hostname
                lsp.encode(auth=self.auth)
            if (
                lsp.is_expired
                and not lsp.tlvs.get("purge_originator")
                and (cur is None or cur.lsp.seqno == 0 or cur.lsp.is_expired)
            ):
                # §7.3.16.4: a purge for an LSP we never held installs
                # as a HEADER-ONLY entry (acked and remembered, but no
                # body/lifetime state — reference state.rs renders just
                # the id and attributes).
                self._install_lsp(lsp, flood_from=iface.name)
                self.lsdb[lsp.lsp_id].hdr_only = True
                return
            self._install_lsp(lsp, flood_from=iface.name)
        elif c == 0:
            if cur is not None and cur.lsp.cksum != lsp.cksum and cur.lsp.seqno != 0:
                # LSP confusion (§7.3.16.2): same seqno, different
                # contents.  Our own LSP skips ahead a seqno; a received
                # one is treated as expired and purged.
                if lsp.lsp_id.sysid == self.sysid:
                    self._notify_seqno_skipped(iface, lsp)
                    self._originate_lsp(force=True, min_seqno=lsp.seqno + 1)
                else:
                    self.purge_lsp(lsp.lsp_id)
                return
            iface.srm.discard(lsp.lsp_id)
            iface.srm_sent.pop(lsp.lsp_id, None)
            if not iface.is_lan:
                iface.ssn.add(lsp.lsp_id)
            self._arm_flood()
        else:
            # Ours is newer: send it back — and clear any pending ack
            # for the stale instance (§7.3.16.4.c: set SRM, clear SSN).
            iface.srm.add(lsp.lsp_id)
            iface.srm_sent.pop(lsp.lsp_id, None)
            iface.ssn.discard(lsp.lsp_id)
            self._arm_flood()

    def _snp_entry_update(self, iface: IsisInterface, lid: LspId, lt: int, seq: int, ck: int) -> None:
        """Apply one SNP entry against the stored LSP (reference
        events.rs process_pdu_snp comparison block)."""
        e = self.lsdb.get(lid)
        if e is None:
            return
        c = e.lsp.compare(lt, seq)
        if c == 0:
            if e.lsp.cksum != ck and e.lsp.seqno != 0:
                # LSP confusion (ISO 10589 §7.3.16.2): a received LSP is
                # treated as expired (purge); a self-originated one
                # skips ahead a sequence number.
                if lid.sysid == self.sysid:
                    self.refresh_lsp(lid)
                else:
                    self.purge_lsp(lid)
            else:
                iface.srm.discard(lid)  # implicit ack
                iface.srm_sent.pop(lid, None)
        elif c > 0:
            iface.ssn.discard(lid)
            iface.srm.add(lid)
            iface.srm_sent.pop(lid, None)  # they have older: send ours
        else:
            # §7.3.15.2(c): they described a newer incarnation —
            # request it (SSN) and stop offering ours.
            iface.srm.discard(lid)
            iface.srm_sent.pop(lid, None)
            iface.ssn.add(lid)

    def _rx_csnp(self, iface: IsisInterface, snp: Snp) -> None:
        now = self.loop.clock.now()
        described = {lid: (lt, seq, ck) for lt, lid, seq, ck in snp.entries}
        # LSPs we have that they didn't describe (in range): set SRM.
        for lid, e in self.lsdb.items():
            if lid not in described:
                iface.srm.add(lid)
                iface.srm_sent.pop(lid, None)
            else:
                lt, seq, ck = described[lid]
                self._snp_entry_update(iface, lid, lt, seq, ck)
        # LSPs they described that we lack: request via PSNP with seqno 0.
        missing = [
            (0, lid, 0, 0) for lid in described if lid not in self.lsdb
        ]
        if missing:
            psnp = Snp(self.level, False, self.sysid, missing)
            self.netio.send(
                iface.name, iface.addr_ip, ALL_ISS,
                psnp.encode(auth=self.auth),
            )
        self._arm_flood()

    def _rx_psnp(self, iface: IsisInterface, snp: Snp) -> None:
        now = self.loop.clock.now()
        for lt, lid, seq, ck in snp.entries:
            e = self.lsdb.get(lid)
            if e is None:
                # Acknowledge outstanding phantom purges (stale
                # self-originated fragments we flooded as expired).
                if lid in self._srm_phantom:
                    iface.srm.discard(lid)
                    iface.srm_sent.pop(lid, None)
                    if not any(
                        lid in i.srm for i in self.interfaces.values()
                    ):
                        del self._srm_phantom[lid]
                    continue
                # ISO 10589 §7.3.15.2(b): an entry for an LSP we lack
                # (all of lifetime/seqno/cksum nonzero) creates a
                # zero-seqno placeholder and requests it via SSN.
                if (
                    lt and seq and ck
                    and not iface.is_lan
                    and lid.sysid != self.sysid
                ):
                    ph = Lsp(self.level, 0, lid, 0, 0)
                    self.lsdb[lid] = LspEntry(ph, now)
                    iface.ssn.add(lid)
                    self._arm_flood()
                continue
            self._snp_entry_update(iface, lid, lt, seq, ck)
        self._arm_flood()

    # -- aging

    def _age_tick(self) -> None:
        now = self.loop.clock.now()
        for lid, e in list(self.lsdb.items()):
            if (
                lid.sysid == self.sysid
                and e.lsp.seqno > 0
                and e.remaining_lifetime(now) < (LSP_MAX_AGE - LSP_REFRESH)
            ):
                # Periodic refresh: force a seqno bump even with unchanged
                # content, or neighbors age our LSP out.  Pseudonode LSPs
                # refresh on the same rule.
                if lid.pseudonode == 0:
                    self._originate_lsp(force=True)
                else:
                    self._originate_pseudonodes(force=True)
            elif e.remaining_lifetime(now) == 0:
                del self.lsdb[lid]
                self._schedule_spf()
        self._age_timer.start(1.0)

    # -- SPF (shared backend)

    def iface_metric_update(self, ifname: str, metric: int) -> None:
        """Live metric reconfiguration (reference northbound
        InterfaceUpdate): re-originate our LSP with the new
        IS-reachability metric; neighbors reconverge via flooding."""
        iface = self.interfaces.get(ifname)
        if iface is None or iface.config.metric == metric:
            return
        iface.config.metric = metric
        # Pseudonode LSPs list members at metric 0 — only our own LSP
        # carries the metric, so no pseudonode re-origination needed.
        self._originate_lsp(force=True)

    def _schedule_spf(self, topology: bool = True) -> None:
        if topology:
            self._spf_type_full = True
        if self.spf_delay_state == "quiet":
            self.spf_delay_state = "short-wait"
        # Causal origin stamp (LSP arrival/change is the IS-IS trigger
        # class; shared contract, see the OSPFv2 instance).
        convergence.pend_schedule(
            self._conv_pending, convergence.TRIGGER_LSP, instance=self.name
        )
        if not self._spf_pending:
            self._spf_pending = True
            self._spf_timer.start(0.1)

    def spf_delay_event(self, event: str) -> None:
        """RFC 8405 timer transitions (LEARN/HOLDDOWN; the conformance
        harness replays them at the recorded positions)."""
        if event == "learn" and self.spf_delay_state == "short-wait":
            self.spf_delay_state = "long-wait"
        elif event == "holddown":
            self.spf_delay_state = "quiet"

    def run_spf(self) -> None:
        with convergence.spf_run(self._conv_pending, self.name):
            with telemetry.span("isis.spf", instance=self.name):
                self._run_spf_traced()

    def _run_spf_traced(self) -> None:
        _ISIS_SPF_RUNS.labels(instance=self.name).inc()
        self.spf_run_count += 1
        now = self.loop.clock.now()
        nodes: dict[bytes, dict] = {}  # key: sysid+pn byte
        for lid, e in self.lsdb.items():
            if e.remaining_lifetime(now) == 0:
                continue
            key = lid.sysid + bytes((lid.pseudonode,))
            node = nodes.setdefault(
                key,
                {"is": [], "ip": [], "ip6": [], "is6": [], "ip6mt": [],
                 "flags": 0, "mt": {}, "protos": set(), "areas": []},
            )
            tlvs = e.lsp.tlvs
            # Advertised area addresses (TLV 1; routers only — the
            # native hierarchical grouping the partitioned-SPF hint
            # reads, ISSUE 15).
            node["areas"].extend(tlvs.get("area_addresses") or ())
            node["is"].extend(tlvs.get("ext_is_reach", []))
            node["ip"].extend(tlvs.get("ext_ip_reach", []))
            node["ip6"].extend(tlvs.get("ipv6_reach", []))
            # Narrow-metric TLVs (2/128/130) join the same graph; when a
            # router advertises both styles the duplicate edges/prefixes
            # carry identical metrics and collapse in the SPF.
            node["is"].extend(tlvs.get("narrow_is_reach", []))
            node["ip"].extend(tlvs.get("narrow_ip_reach", []))
            node["ip"].extend(tlvs.get("narrow_ip_ext_reach", []))
            for mt_id, reach in tlvs.get("mt_is_reach", []):
                if mt_id == 0:
                    node["is"].append(reach)
                elif mt_id == MT_IPV6:
                    node["is6"].append(reach)
            for mt_id, reach in tlvs.get("mt_ip_reach", []):
                if mt_id == 0:
                    node["ip"].append(reach)
            for mt_id, reach in tlvs.get("mt_ipv6_reach", []):
                if mt_id == MT_IPV6:
                    node["ip6mt"].append(reach)
            for mt_id, att, ovl in tlvs.get("mt_ids", []):
                node["mt"][mt_id] = (att, ovl)
            node["protos"] |= set(tlvs.get("protocols_supported") or [])
            if lid.pseudonode == 0:
                node["flags"] |= e.lsp.flags

        self_key = self.sysid + b"\x00"
        if self_key not in nodes:
            return
        def _att(node, mt_id) -> bool:
            """Attached bit for one topology: LSP flags nibble (0x78 —
            the reference emits 0x40) for the default topology, the
            RFC 5120 TLV-229 A bit for others."""
            if mt_id == 0:
                return bool(node["flags"] & 0x78)
            return node["mt"].get(mt_id, (False, False))[0]

        def _ovl(node, mt_id) -> bool:
            """Overload bit per topology: LSP flags (ISO 10589) for the
            default topology, the TLV-229 O bit for others."""
            if mt_id == 0:
                return bool(node["flags"] & 0x04)
            return node["mt"].get(mt_id, (False, False))[1]


        spf_type_full_req = self._spf_type_full
        self._spf_type_full = False
        _cache = self._spt_cache
        spf_type = "full"
        if (
            not spf_type_full_req
            and _cache is not None
            and all(k in _cache["index"] for k in nodes)
        ):
            spf_type = "route-only"
        if spf_type == "route-only":
            # RouteOnly (reference spf.rs:744): prefix reachability
            # changed but the IS graph did not — reuse the cached SPTs
            # and recompute routes only; no Dijkstra dispatch.
            order, index = _cache["order"], _cache["index"]
            res4, atoms4 = _cache["res4"], _cache["atoms4"]
            mt6 = _cache["mt6"]
            res6, atoms6 = _cache["res6"], _cache["atoms6"]
        else:
            # Vertex ordering contract (same as OSPF): network vertices —
            # pseudonodes — sort before routers, so equal-distance paths
            # through a zero-cost pseudonode edge settle before the router
            # they lead to and ECMP unions are not dropped.
            order = sorted(nodes.keys(), key=lambda k: (k[6] == 0, k))
            index = {k: i for i, k in enumerate(order)}
            n = len(order)
            is_router = np.array([k[6] == 0 for k in order], bool)
            adj_by_sysid: dict[bytes, list] = {}  # key -> [(ifname, a4, a6)]
            lan_iface_by_id = {}  # pseudonode key -> ifname (LANs we sit on)
            for iface in self.interfaces.values():
                for adj in iface.up_adjacencies():
                    adj_by_sysid.setdefault(adj.sysid + b"\x00", []).append(
                        (iface.name, adj.addr, adj.addr6)
                    )
                if iface.is_lan and iface.dis_lan_id is not None:
                    lan_iface_by_id[iface.dis_lan_id] = iface.name

            def _build(edges_of, mt_id):
                """Topology + next-hop atoms for one edge selection (the
                default topology, or the RFC 5120 MT-2 overlay)."""
                src, dst, cost = [], [], []
                for k, node in nodes.items():
                    u = index[k]
                    for reach in edges_of(k, node):
                        v = index.get(reach.neighbor)
                        if v is not None:
                            src.append(u)
                            dst.append(v)
                            cost.append(reach.metric)
                src = np.array(src, np.int32).reshape(-1)
                dst = np.array(dst, np.int32).reshape(-1)
                cost = np.array(cost, np.int32).reshape(-1)
                keep = mutual_keep_mask(src, dst)
                # Overload (ISO 10589 §7.2.8.1, reference spf.rs:563-574):
                # an overloaded router stays REACHABLE — its own prefixes
                # install — but is never expanded for transit.  Drop its
                # out-edges AFTER the mutual filter so its in-edges survive.
                ovl_vertices = {
                    index[k]
                    for k, node in nodes.items()
                    if k[6] == 0 and k != self_key and _ovl(node, mt_id)
                }
                if ovl_vertices:
                    keep &= ~np.isin(src, np.array(list(ovl_vertices), np.int32))
                topo = Topology(
                    n_vertices=n,
                    is_router=is_router,
                    edge_src=src[keep],
                    edge_dst=dst[keep],
                    edge_cost=cost[keep],
                    root=index[self_key],
                )
                # Next-hop atoms: adjacencies out of the root.  A neighbor
                # reached over parallel p2p circuits has one adjacency per
                # circuit AND one duplicate is-reach edge per circuit — pair
                # them up so each edge carries its own interface atom
                # (reference spf next-hop model).
                atoms = []
                atom_ids = np.full(topo.n_edges, -1, np.int32)
                root_lans: set[int] = set()
                hops_used: dict[bytes, int] = {}
                for e_i in range(topo.n_edges):
                    if topo.edge_src[e_i] == topo.root:
                        k = order[int(topo.edge_dst[e_i])]
                        if k[6] == 0:  # router neighbor (p2p)
                            hops = adj_by_sysid.get(k)
                            if hops:
                                i = hops_used.get(k, 0)
                                hops_used[k] = i + 1
                                atom_ids[e_i] = len(atoms)
                                atoms.append(hops[min(i, len(hops) - 1)])
                        elif k in lan_iface_by_id:
                            root_lans.add(int(topo.edge_dst[e_i]))
                # Pseudonode -> member edges on root-adjacent LANs: direct
                # next hop is the member's address on that LAN (the generic
                # hops==0 rule).
                for e_i in range(topo.n_edges):
                    u = int(topo.edge_src[e_i])
                    if u in root_lans:
                        lan_key = order[u]
                        member = order[int(topo.edge_dst[e_i])]
                        if member == self_key:
                            continue
                        ifname = lan_iface_by_id.get(lan_key)
                        hop = next(
                            (h for h in adj_by_sysid.get(member, [])
                             if h[0] == ifname),
                            None,
                        )
                        if hop is not None:
                            atom_ids[e_i] = len(atoms)
                            atoms.append(hop)
                topo.edge_direct_atom = atom_ids
                from holo_tpu.protocols.ospf.spf_run import (
                    apply_interface_srlg,
                    srlg_bits,
                )

                iface_srlg = {
                    i.name: srlg_bits(i.config.srlg)
                    for i in self.interfaces.values()
                    if i.config.srlg
                }
                if iface_srlg:
                    # IS-IS atoms are (ifname, addr4, addr6) tuples.
                    apply_interface_srlg(
                        topo, [a[0] for a in atoms], iface_srlg
                    )
                # Native hierarchical partition hint (ISSUE 15): group
                # vertices by advertised area address — an L2 topology
                # spans areas and cuts along them; L1 (single area)
                # stays flat (apply_partition_hint stamps only when
                # >=2 groups cover every vertex).  Pseudonodes ride
                # their DIS router's area so zero-cost LAN edges stay
                # intra-partition.
                from holo_tpu.protocols.ospf.spf_run import (
                    apply_partition_hint,
                )

                area_of = {
                    k: min(node["areas"])
                    for k, node in nodes.items()
                    if k[6] == 0 and node["areas"]
                }
                apply_partition_hint(
                    topo,
                    [
                        area_of.get(
                            k if k[6] == 0 else k[:6] + b"\x00"
                        )
                        for k in order
                    ],
                )
                topo.touch()
                return topo, atoms

            def _link_delta(mt_id, topo_new, atoms_new):
                # DeltaPath seam (same contract as OSPF): identical
                # vertex ordering + atom table → diff against the
                # previous run so the resident device graph updates in
                # place instead of re-marshaling the LSP database.
                prev = self._spf_delta_bases.get(mt_id)
                if (
                    prev is not None
                    and prev[0] == order
                    and prev[1] == atoms_new
                ):
                    from holo_tpu.ops.graph import diff_topologies

                    delta = diff_topologies(prev[2], topo_new)
                    if delta is not None:
                        topo_new.link_delta(delta)
                self._spf_delta_bases[mt_id] = (order, atoms_new, topo_new)

            # IS-IS max-paths stays a HOST-side clamp with the
            # reference's lowest-address semantics (spf.rs:920-929,
            # bit-for-bit — conformance replays depend on it), so the
            # dispatch deliberately does NOT arm the widened multipath
            # kernel: its UCMP planes would be computed and never read
            # here.  The weight-consuming seams are the OSPF stacks
            # (v2 derive_routes / v3 _clamp_max_paths).
            topo, atoms4 = _build(lambda k, node: node["is"], 0)
            _link_delta(0, topo, atoms4)
            res4 = self.backend.compute(topo)
            # IP-FRR: the default-topology backup batch rides the full
            # SPF (route-only runs keep the tables — the IS graph is
            # unchanged by definition of RouteOnly).
            frr_cfg = self.frr
            if frr_cfg is not None and frr_cfg.active():
                from holo_tpu.frr.manager import ensure_engine

                self._frr_engine = ensure_engine(self._frr_engine, frr_cfg)
                self.frr_tables = {0: self._frr_engine.compute(topo)}
            else:
                self.frr_tables = {}
            self.vertex_dist = {
                k[:6]: int(res4.dist[index[k]])
                for k in nodes
                if k[6] == 0 and res4.dist[index[k]] < INF
            }
            # IPv6 path: routers running MT (RFC 5120) keep IPv6 in topology
            # 2 — a separate graph (pseudonodes contribute their plain TLV-22
            # membership; the mutual filter prunes members without an MT-2
            # back edge).  Single-topology routers share the default SPF.
            mt6 = MT_IPV6 in nodes[self_key]["mt"]
            if mt6:
                topo6, atoms6 = _build(
                    lambda k, node: node["is6"] if k[6] == 0 else node["is"],
                    MT_IPV6,
                )
                _link_delta(MT_IPV6, topo6, atoms6)
                res6 = self.backend.compute(topo6)
            else:
                res6, atoms6 = res4, atoms4

            # Flooding-reduction cache rebuild (reference spf.rs:763-779):
            # per-neighbor hop-count SPTs via one multi-root batch.
            if self.flooding_reduction:
                from holo_tpu.protocols.isis.flooding_reduction import (
                    neighbor_coverage,
                )

                nbr_vertex_by_iface = {}
                iface_by_vertex = {}
                sysid_by_vertex = {}
                for iface in self.interfaces.values():
                    if iface.is_lan or iface.adj is None:
                        continue
                    v = index.get(iface.adj.sysid + b"\x00")
                    if v is not None and iface.adj.state == AdjacencyState.UP:
                        nbr_vertex_by_iface[iface.name] = v
                        iface_by_vertex[v] = iface.name
                        sysid_by_vertex[v] = iface.adj.sysid
                self._covered_by = {}
                if len(nbr_vertex_by_iface) > 1:
                    cov = neighbor_coverage(
                        topo, self.backend, list(nbr_vertex_by_iface.values())
                    )
                    for m, others in cov.items():
                        self._covered_by[sysid_by_vertex[m]] = {
                            iface_by_vertex[n] for n in others
                        }

            self._spt_cache = {
                "order": order, "index": index, "res4": res4,
                "atoms4": atoms4, "mt6": mt6, "res6": res6,
                "atoms6": atoms6,
            }

        from holo_tpu.protocols.ospf.spf_run import atom_bits

        routes: dict = {}  # prefix (v4 or v6) -> (metric, {(ifname, addr)})
        rank_of: dict = {}  # prefix -> (external, metric): RFC 1195
        # §3.10.2 internal paths beat external regardless of metric.

        def _clamp(nhs):
            if self.max_paths is None or len(nhs) <= self.max_paths:
                return nhs
            # Reference spf.rs:920-929: deterministic ECMP clamp — keep
            # the lowest next-hop addresses.
            ranked = sorted(
                nhs,
                key=lambda h: (
                    h[1] is None,
                    h[1].packed if h[1] is not None else b"",
                    h[0] or "",
                ),
            )
            return frozenset(ranked[: self.max_paths])

        # Prefixes whose winning contribution comes from a zero-hop
        # vertex (ourselves): the reference marks these CONNECTED and
        # never installs them (route.rs:86-88,285-301).
        connected: set = set()
        # Winning SPT vertex per prefix (FRR consumption key): (v, v6?).
        vertex_of: dict = {}

        def _add(prefix, total, nhs, external=False, local=False, vertex=-1,
                 want_v6=False):
            rank = (external, total)
            cur = rank_of.get(prefix)
            if cur is None or rank < cur:
                rank_of[prefix] = rank
                routes[prefix] = (total, _clamp(nhs))
                if vertex >= 0 and not local:
                    vertex_of[prefix] = (vertex, want_v6)
                else:
                    vertex_of.pop(prefix, None)
                if local:
                    connected.add(prefix)
                else:
                    connected.discard(prefix)
            elif rank == cur:
                # Anycast merge keeps the original route's flags
                # (spf.rs:907-909 merge_nexthops).
                routes[prefix] = (
                    total, _clamp(routes[prefix][1] | nhs)
                )

        def _af_nexthops(res_, atoms_, v, want_v6):
            triples = [
                atoms_[a]
                for a in atom_bits(res_.nexthop_words[v], len(atoms_))
            ]
            if want_v6:
                return frozenset((ifn, a6) for ifn, _, a6 in triples)
            return frozenset((ifn, a4) for ifn, a4, _ in triples)

        af4 = "ipv4" in self.afs
        af6 = "ipv6" in self.afs
        for k, node in nodes.items():
            v = index[k]
            local = k == self_key  # hops==0 vertex: CONNECTED routes
            if af4 and res4.dist[v] < INF and node["ip"]:
                nhs4 = _af_nexthops(res4, atoms4, v, False)
                for reach in node["ip"]:
                    _add(reach.prefix, int(res4.dist[v]) + reach.metric,
                         nhs4, reach.external, local=local, vertex=v)
            ip6_list = node["ip6mt"] if mt6 else node["ip6"]
            if af6 and res6.dist[v] < INF and ip6_list:
                nhs6 = _af_nexthops(res6, atoms6, v, True)
                for reach in ip6_list:
                    _add(reach.prefix, int(res6.dist[v]) + reach.metric,
                         nhs6, local=local, vertex=v, want_v6=True)

        # Level-1 routers that are not themselves attached install a
        # per-AF default route toward the nearest attached router(s),
        # ECMP across equal-cost exits (ISO 10589 §7.2.9.2; ATT nibble
        # 0x78 — the reference emits 0x40).
        if self.level == 1:
            from ipaddress import IPv6Network

            for want_v6, res_, atoms_, proto, default in (
                (False, res4, atoms4, 0xCC, IPv4Network("0.0.0.0/0")),
                (True, res6, atoms6, 0x8E, IPv6Network("::/0")),
            ):
                if not (af6 if want_v6 else af4):
                    continue  # address family disabled
                mt_id = MT_IPV6 if (want_v6 and mt6) else 0
                if self.att_ignore:
                    continue  # §7.2.9.2 disabled by configuration
                if _att(nodes[self_key], mt_id):
                    continue  # we are an exit ourselves in this topology
                best = None
                nhs = frozenset()
                for k, node in nodes.items():
                    if k[6] != 0 or k == self_key:
                        continue
                    # Reference spf.rs:870-876: att && !overload — an
                    # overloaded exit must not attract default traffic.
                    if not _att(node, mt_id) or _ovl(node, mt_id):
                        continue
                    if node["protos"] and proto not in node["protos"]:
                        continue  # exit must route this address family
                    v = index[k]
                    d = int(res_.dist[v])
                    if d >= INF:
                        continue
                    cur = _af_nexthops(res_, atoms_, v, want_v6)
                    if best is None or d < best:
                        best, nhs = d, cur
                    elif d == best:
                        nhs |= cur
                if best is not None:
                    _add(default, best, nhs)
        # IP-FRR: join the default-topology backup table onto the route
        # table.  Direct LFAs only (no SR tunnel encapsulation wired for
        # the repair path here); the MT-2 IPv6 overlay is a separate
        # graph the default-topology table does not cover.
        self.frr_backups = {}
        frr_cfg = self.frr
        table = self.frr_tables.get(0)
        if frr_cfg is not None and frr_cfg.active() and table is not None:
            from holo_tpu.frr.manager import repair_map

            # Prefixes sharing a terminating vertex share the repair map.
            memo: dict[tuple, dict] = {}
            for prefix, (v, want_v6) in vertex_of.items():
                if want_v6 and mt6:
                    continue
                res_, atoms_ = (res6, atoms6) if want_v6 else (res4, atoms4)
                repairs = memo.get((want_v6, v))
                if repairs is None:
                    repairs = memo[(want_v6, v)] = repair_map(
                        table, frr_cfg, res_.nexthop_words[v], v
                    )
                backups = {}
                for a, entry in repairs.items():
                    if entry.kind != "lfa":
                        continue
                    ifn, p4, p6 = atoms_[a]
                    bifn, b4, b6 = atoms_[entry.atom]
                    paddr, baddr = (p6, b6) if want_v6 else (p4, b4)
                    if paddr is None or baddr is None:
                        continue
                    backups[(ifn, paddr)] = ((bifn, baddr), ())
                if backups:
                    self.frr_backups[prefix] = backups

        # SPF run log ring (reference spf.rs log_spf_run): records the
        # Full/RouteOnly split for operational state.
        self.spf_log.append(
            {
                "run": self.spf_run_count,
                "type": spf_type,
                "start-time": now,
                "end-time": self.loop.clock.now(),
                "route-count": len(routes),
            }
        )
        del self.spf_log[:-32]
        self.routes = routes
        self.connected_prefixes = frozenset(connected)
        self.sr_labels = self._resolve_sr_labels(routes)
        # Published LAST, as one atomic assignment: cross-thread readers
        # (the daemon's marshalled route_cb) get a view built entirely
        # on this thread, never a torn routes/connected combination.
        self.last_installable = self.installable_routes()
        if self.route_cb is not None:
            self.route_cb(routes)

    def installable_routes(self) -> dict:
        """The RIB-feed view of :attr:`routes` (route.rs:285-301):
        CONNECTED prefixes never install, and a route without nexthops
        (nexthop computation error) must leave the global RIB."""
        return {
            p: r for p, r in self.routes.items()
            if p not in self.connected_prefixes and r[1]
        }

    def _resolve_sr_labels(self, routes: dict) -> dict:
        """prefix -> (local label, route) for every prefix-SID heard,
        resolved through our SRGB (holo-isis/src/spf.rs:931-946)."""
        if self.sr is None or not self.sr.enabled:
            return {}
        out = {}
        for e in self.lsdb.values():
            if e.lsp.is_expired:
                continue
            entries = list(e.lsp.tlvs.get("ext_ip_reach", []))
            entries += [r for _mt, r in e.lsp.tlvs.get("mt_ip_reach", [])]
            for r in entries:
                idx = getattr(r, "sid_index", None)
                if idx is None:
                    continue
                label = self.sr.srgb.label_of(idx)
                route = routes.get(r.prefix)
                if label is not None and route is not None:
                    out[r.prefix] = (label, route)
        return out

"""ietf-isis operational-state rendering (YANG-modeled, full tree).

Builds the same ``ietf-isis:isis`` state tree the reference's northbound
walks (holo-isis/src/northbound/state.rs): spf-control, hostnames, the
per-level LSP database with every TLV rendered, the local RIB, and the
per-interface adjacency/SRM/SSN planes — so the conformance harness can
diff the FULL recorded northbound-state plane leaf by leaf
(VERDICT round-2 item 2; tools/stepwise_isis.py compare_state).
"""

from __future__ import annotations

from holo_tpu.protocols.isis.instance import AdjacencyState

def _adj_sid_flags(fl: int) -> list[str]:
    """RFC 8667 §2.2.1 flag names in the reference's render order."""
    names = []
    for bit, name in (
        (0x80, "f-flag"),
        (0x40, "b-flag"),
        (0x20, "vi-flag"),
        (0x10, "lg-flag"),
        (0x08, "s-flag"),
        (0x04, "p-flag"),
    ):
        if fl & bit:
            names.append(name)
    return names


_ALGO = {
    0: "ietf-segment-routing-common:prefix-sid-algorithm-shortest-path",
    1: "ietf-segment-routing-common:prefix-sid-algorithm-strict-spf",
}


def sysid_str(b: bytes) -> str:
    h = b.hex()
    return f"{h[0:4]}.{h[4:8]}.{h[8:12]}"


def lsp_id_str(lid) -> str:
    raw = lid.encode() if hasattr(lid, "encode") else bytes(lid)
    return f"{sysid_str(raw[:6])}.{raw[6]:02x}-{raw[7]:02x}"


def _area_str(a: bytes) -> str:
    h = a.hex()
    return h[0:2] + "".join(
        "." + h[i : i + 4] for i in range(2, len(h), 4)
    )


def _narrow_metric_block(metric: int, i_e: bool = False) -> dict:
    return {
        "i-e": i_e,
        "default-metric": {"metric": metric},
        "delay-metric": {"supported": False},
        "expense-metric": {"supported": False},
        "error-metric": {"supported": False},
    }


def _wide_prefix(entry, mt_id: int | None = None) -> dict:
    """extended-ipv4-reachability / ipv6-reachability prefix node."""
    out: dict = {}
    if mt_id is not None:
        out["mt-id"] = mt_id
    out |= {
        "up-down": bool(entry.up_down),
        "ip-prefix": str(entry.prefix.network_address),
        "prefix-len": entry.prefix.prefixlen,
        "metric": entry.metric,
    }
    # v6 reach (RFC 5308) carries X in its control byte, so the flag
    # always renders; v4 wide reach gets X/R/N only from the RFC 7794
    # prefix-attributes sub-TLV (matches the recorded trees).
    if entry.prefix.version == 6:
        out["external-prefix-flag"] = bool(entry.external) or bool(
            (entry.attr_flags or 0) & 0x80
        )
        if entry.attr_flags is not None:
            out["readvertisement-flag"] = bool(entry.attr_flags & 0x40)
            out["node-flag"] = bool(entry.attr_flags & 0x20)
    elif entry.attr_flags is not None:
        out["external-prefix-flag"] = bool(entry.attr_flags & 0x80)
        out["readvertisement-flag"] = bool(entry.attr_flags & 0x40)
        out["node-flag"] = bool(entry.attr_flags & 0x20)
    if getattr(entry, "src_rid4", None) is not None:
        out["ipv4-source-router-id"] = str(entry.src_rid4)
    if getattr(entry, "src_rid6", None) is not None:
        out["ipv6-source-router-id"] = str(entry.src_rid6)
    if entry.sid_index is not None:
        flags = []
        for bit, name in (
            (0x80, "r-flag"),
            (0x40, "n-flag"),
            (0x20, "p-flag"),
            (0x10, "e-flag"),
            (0x08, "v-flag"),
            (0x04, "l-flag"),
        ):
            if entry.sid_flags & bit:
                flags.append(name)
        out["ietf-isis-sr-mpls:prefix-sid-sub-tlvs"] = {
            "prefix-sid-sub-tlv": [
                {
                    "prefix-sid-flags": {"flag": flags},
                    "algorithm": _ALGO[0],
                    "index-value": entry.sid_index,
                }
            ]
        }
    return out


def _narrow_prefixes(entries) -> list:
    return [
        {
            "ip-prefix": str(e.prefix.network_address),
            "prefix-len": e.prefix.prefixlen,
        }
        | _narrow_metric_block(e.metric)
        for e in entries
    ]


def _render_lsp(lsp, entry_meta=None) -> dict:
    t = lsp.tlvs
    out: dict = {"lsp-id": lsp_id_str(lsp.lsp_id)}
    flags = []
    if lsp.flags & 0x01:
        flags.append("lsp-l1-system-flag")
    if lsp.flags & 0x02:
        flags.append("lsp-l2-system-flag")
    if lsp.flags & 0x04:
        flags.append("lsp-overload-flag")
    if lsp.flags & 0x40:
        # The reference models one ATT bit at 0x40 (packet/pdu.rs:137).
        flags.append("lsp-attached-default-metric-flag")
    # Descending bit order, as the reference's bitflags render.
    order = [
        "lsp-attached-default-metric-flag",
        "lsp-overload-flag",
        "lsp-l2-system-flag",
        "lsp-l1-system-flag",
    ]
    if lsp.seqno == 0:
        # Empty shell entry (a PSNP named an LSP we do not have yet):
        # the reference renders only the id and the zero sequence.
        return {"lsp-id": out["lsp-id"], "sequence": 0}
    out["attributes"] = {
        "lsp-flags": [f for f in order if f in flags]
    }
    if lsp.lifetime == 0:
        # Purged LSP (no sequence leaf — it is scrubbed as
        # nondeterministic for live LSPs and simply absent here);
        # whatever TLVs the purge carried still render (RFC 6232 purges
        # keep hostname + purge-originator).  Lifetime leaves depend on
        # provenance: a purge replacing a known received LSP pins both
        # at zero; a locally generated purge renders only
        # remaining-lifetime; a received purge for an UNKNOWN LSP
        # renders neither (reference state.rs).
        rcvd = getattr(entry_meta, "rcvd", True)
        had = getattr(entry_meta, "had_copy", True)
        if getattr(entry_meta, "hdr_only", False) or not (rcvd or had):
            # §7.3.16.4 header-only entry (a purge for an LSP we never
            # actually held): id + attributes only.
            return {"lsp-id": out["lsp-id"], "attributes": out["attributes"]}
        if rcvd:
            out["remaining-lifetime"] = 0
            out["holo-isis:received-remaining-lifetime"] = 0
        else:
            # Locally generated purge: no received lifetime to pin.
            out["remaining-lifetime"] = 0
        po = t.get("purge_originator")
        if po:
            node = {"originator": sysid_str(po[0])}
            if len(po) > 1:
                node["received-from"] = sysid_str(po[1])
            out["holo-isis:purge-originator-identification"] = node
    if t.get("ip_addresses"):
        out["ipv4-addresses"] = [str(a) for a in t["ip_addresses"]]
    if t.get("ipv6_addresses"):
        out["ipv6-addresses"] = [str(a) for a in t["ipv6_addresses"]]
    if t.get("protocols_supported"):
        out["protocol-supported"] = list(t["protocols_supported"])
    if t.get("hostname"):
        out["dynamic-hostname"] = t["hostname"]
    if t.get("ipv4_router_id"):
        out["ipv4-te-routerid"] = str(t["ipv4_router_id"])
    if t.get("ipv6_router_id"):
        out["ipv6-te-routerid"] = str(t["ipv6_router_id"])
    def _nbr_id(raw: bytes) -> str:
        return sysid_str(raw[:6]) + (
            f".{raw[6]:02x}" if len(raw) > 6 else ""
        )

    def _grouped(entries, instance_of):
        """Parallel adjacencies to one neighbor render as ONE list entry
        with per-instance ids (the reference groups by neighbor-id)."""
        by_id: dict[str, list] = {}
        for n in entries:
            by_id.setdefault(_nbr_id(n.neighbor), []).append(n)
        # BTreeMap order, like the reference renders.
        return [
            {
                "neighbor-id": nid,
                "instances": {
                    "instance": [
                        {"id": i} | instance_of(n)
                        for i, n in enumerate(group)
                    ]
                },
            }
            for nid, group in sorted(by_id.items())
        ]

    if t.get("narrow_is_reach"):
        out["is-neighbor"] = {
            "neighbor": _grouped(
                t["narrow_is_reach"],
                lambda n: _narrow_metric_block(n.metric),
            )
        }
    def _ext_instance(n) -> dict:
        node = {"metric": n.metric}
        if getattr(n, "adj_sids", None):
            node["ietf-isis-sr-mpls:adj-sid-sub-tlvs"] = {
                "adj-sid-sub-tlv": [
                    {
                        "adj-sid-flags": {"flag": _adj_sid_flags(fl)},
                        "weight": w,
                        "label-value": label,
                    }
                    for fl, w, label in n.adj_sids
                ]
            }
        if getattr(n, "link_msd", None):
            node["ietf-isis-msd:link-msd-sub-tlv"] = {
                "link-msds": [
                    {"msd-type": mt, "msd-value": v}
                    for mt, v in n.link_msd
                ]
            }
        return node

    if t.get("ext_is_reach"):
        out["extended-is-neighbor"] = {
            "neighbor": _grouped(t["ext_is_reach"], _ext_instance)
        }
    if t.get("mt_is_reach"):
        by_key: dict[tuple, list] = {}
        for mt, n in t["mt_is_reach"]:
            by_key.setdefault((mt, _nbr_id(n.neighbor)), []).append(n)
        out["mt-is-neighbor"] = {
            "neighbor": [
                {
                    "mt-id": mt,
                    "neighbor-id": nid,
                    "instances": {
                        "instance": [
                            {"id": i, "metric": n.metric}
                            for i, n in enumerate(group)
                        ]
                    },
                }
                for (mt, nid), group in sorted(by_key.items())
            ]
        }
    if t.get("narrow_ip_reach"):
        out["ipv4-internal-reachability"] = {
            "prefixes": _narrow_prefixes(t["narrow_ip_reach"])
        }
    if t.get("narrow_ip_ext_reach"):
        out["ipv4-external-reachability"] = {
            "prefixes": _narrow_prefixes(t["narrow_ip_ext_reach"])
        }
    # Wire/TLV order throughout: received LSPs replay byte-exact, and
    # our own origination emits the reference's order.
    if t.get("ext_ip_reach"):
        out["extended-ipv4-reachability"] = {
            "prefixes": [_wide_prefix(e) for e in t["ext_ip_reach"]]
        }
    if t.get("ipv6_reach"):
        out["ipv6-reachability"] = {
            "prefixes": [_wide_prefix(e) for e in t["ipv6_reach"]]
        }
    if t.get("mt_ipv6_reach"):
        out["mt-ipv6-reachability"] = {
            "prefixes": [
                _wide_prefix(e, mt_id=mt) for mt, e in t["mt_ipv6_reach"]
            ]
        }
    if t.get("mt_ids"):
        topo_nodes = []
        for mt, att, ovl in t["mt_ids"]:
            tn: dict = {"mt-id": mt}
            flags = []
            if ovl:
                flags.append("tlv229-overload-flag")
            if att:
                flags.append("tlv229-attached-flag")
            if flags:
                tn["attributes"] = {"flags": flags}
            topo_nodes.append(tn)
        out["mt-entries"] = {"topology": topo_nodes}
    if any(
        t.get(k)
        for k in ("sr_cap", "srlb", "node_msd", "node_tags", "sr_algos")
    ):
        rc: dict = {}
        if t.get("sr_cap"):
            base, rng = t["sr_cap"]
            cap_flags = t.get("sr_cap_flags", 0xC0)
            names = []
            if cap_flags & 0x80:
                names.append("mpls-ipv4")
            if cap_flags & 0x40:
                names.append("mpls-ipv6")
            rc["ietf-isis-sr-mpls:sr-capability"] = {
                "sr-capability-flag": names,
                "global-blocks": {
                    "global-block": [
                        {"range-size": rng, "label-value": base}
                    ]
                },
            }
        if t.get("sr_algos") or t.get("sr_cap"):
            rc["ietf-isis-sr-mpls:sr-algorithms"] = {
                "sr-algorithm": [
                    _ALGO.get(a, _ALGO[0])
                    for a in (t.get("sr_algos") or (0,))
                ]
            }
        if t.get("srlb"):
            base, rng = t["srlb"]
            rc["ietf-isis-sr-mpls:local-blocks"] = {
                "local-block": [{"range-size": rng, "label-value": base}]
            }
        if t.get("node_msd"):
            rc["ietf-isis-msd:node-msd-tlv"] = {
                "node-msds": [
                    {"msd-type": mt, "msd-value": v}
                    for mt, v in sorted(t["node_msd"].items())
                ]
            }
        if t.get("node_tags"):
            rc["node-tags"] = {
                "node-tag": [{"tag": tag} for tag in t["node_tags"]]
            }
        out["router-capabilities"] = {"router-capability": [rc]}
    if t.get("area_addresses"):
        out["holo-isis:area-addresses"] = [
            _area_str(a) for a in t["area_addresses"]
        ]
    if t.get("lsp_buf_size"):
        out["holo-isis:lsp-buffer-size"] = t["lsp_buf_size"]
    return out


def _render_level_db(inst, now: float) -> dict:
    entries = sorted(
        inst.lsdb.items(), key=lambda kv: bytes(kv[0].encode())
    )
    lsps = [_render_lsp(e.lsp, entry_meta=e) for _lid, e in entries]
    # The count excludes entries mid-purge (the ones rendering a pinned
    # zero remaining-lifetime); header-only shells still count
    # (reference lsp-count gauge).
    live = sum(1 for n in lsps if "remaining-lifetime" not in n)
    return {
        "level": inst.level,
        "lsp": lsps,
        "holo-isis:lsp-count": live,
    }


def _render_iface(insts, ifname: str) -> dict:
    out: dict = {"name": ifname}
    adjacencies = []
    state = "down"
    srm_levels = []
    ssn_levels = []
    # A p2p adjacency UP in both levels is ONE level-all adjacency in
    # the reference's arena (usage/sys-type "level-all").
    seen_levels: dict[tuple, set] = {}
    for inst in insts:
        iface = inst.interfaces.get(ifname)
        if iface is not None and not getattr(iface, "is_lan", False):
            for a in iface.all_adjacencies():
                seen_levels.setdefault((ifname, a.sysid), set()).add(
                    inst.level
                )
    rendered_all: set = set()
    for inst in insts:
        iface = inst.interfaces.get(ifname)
        if iface is None:
            continue
        if getattr(iface, "up", True) and getattr(inst, "enabled", True):
            state = "up"
        for a in iface.all_adjacencies():
            lvl = f"level-{inst.level}"
            sys_type = lvl
            ctype = getattr(a, "usage_ctype", None)
            if getattr(iface, "is_lan", False):
                # LAN adjacencies stay per-level in the arena, but the
                # sys-type reflects the NEIGHBOR's announced circuit
                # type (its LAN IIH carries it).
                if ctype == 3:
                    sys_type = "level-all"
                elif ctype in (1, 2):
                    sys_type = f"level-{ctype}"
            else:
                # p2p: sys-type is what the neighbor's hello announced;
                # usage is the negotiated intersection with our levels.
                if ctype == 3:
                    sys_type = "level-all"
                elif ctype in (1, 2):
                    sys_type = f"level-{ctype}"
                both_local = (
                    seen_levels.get((ifname, a.sysid), set()) == {1, 2}
                )
                if sys_type == "level-all" and both_local:
                    if (ifname, a.sysid) in rendered_all:
                        continue
                    rendered_all.add((ifname, a.sysid))
                    lvl = "level-all"
            node = {
                "neighbor-sys-type": sys_type,
                "neighbor-sysid": sysid_str(a.sysid),
                "usage": lvl,
            }
            if getattr(iface, "is_lan", False):
                node["neighbor-priority"] = a.priority
            node["state"] = {
                AdjacencyState.UP: "up",
                AdjacencyState.INITIALIZING: "init",
                AdjacencyState.DOWN: "down",
            }[a.state]
            if a.adj_sids:
                node["ietf-isis-sr-mpls:adjacency-sid"] = [
                    {
                        "value": label,
                        "address-family": "ipv6" if fl & 0x80 else "ipv4",
                        "weight": w,
                        "protection-requested": bool(fl & 0x40),
                    }
                    for fl, w, label in a.adj_sids
                ]
            if a.area_addresses:
                node["holo-isis:area-addresses"] = [
                    _area_str(x) for x in a.area_addresses
                ]
            if a.addrs4:
                node["holo-isis:ipv4-addresses"] = [
                    str(x) for x in a.addrs4
                ]
            if a.addrs6:
                node["holo-isis:ipv6-addresses"] = [
                    str(x) for x in a.addrs6
                ]
            if a.protocols:
                node["holo-isis:protocol-supported"] = list(a.protocols)
            node["holo-isis:topologies"] = sorted(set(a.topologies) | {0})
            adjacencies.append(node)
        for attr, acc in (("srm", srm_levels), ("ssn", ssn_levels)):
            ids = sorted(
                lsp_id_str(lid) for lid in getattr(iface, attr, ())
            )
            if ids:
                acc.append({"level": inst.level, "lsp-id": ids})
    if adjacencies:
        out["adjacencies"] = {"adjacency": adjacencies}
    out["holo-isis:state"] = state
    if srm_levels:
        out["holo-isis-dev:srm"] = {"level": srm_levels}
    if ssn_levels:
        out["holo-isis-dev:ssn"] = {"level": ssn_levels}
    return out


def instance_state(
    insts, node=None, now: float | None = None, ifnames=None
) -> dict:
    """The full ietf-isis:isis state tree over one or two level
    instances (``node`` = the L1/L2 facade when running level-all).
    ``ifnames``: ordered CONFIGURED interface list — a configured but
    down interface renders with state "down" even though the instances
    no longer hold it."""
    insts = list(insts)
    if now is None:
        now = insts[0].loop.clock.now() if insts else 0.0
    out: dict = {}
    if insts and not any(getattr(i, "enabled", True) for i in insts):
        # Disabled instance: only the interface table renders, all down
        # (reference: the torn-down Instance has no Up state).
        if ifnames is None:
            ifnames = [
                n for inst in insts for n in inst.interfaces
            ]
        out["interfaces"] = {
            "interface": [
                {"name": n, "holo-isis:state": "down"} for n in ifnames
            ]
        }
        return out
    spf_levels = [
        {
            "level": inst.level,
            "current-state": getattr(inst, "spf_delay_state", "quiet"),
        }
        for inst in insts
    ]
    out["spf-control"] = {
        "ietf-spf-delay": {"holo-isis:level": spf_levels}
    }
    names: dict[str, str] = {}
    for inst in insts:
        for sysid, name in inst.hostnames.items():
            names.setdefault(sysid_str(sysid), name)
    if names:
        out["hostnames"] = {
            "hostname": [
                {"system-id": sid, "hostname": n}
                for sid, n in sorted(names.items())
            ]
        }
    out["database"] = {
        "levels": [_render_level_db(inst, now) for inst in insts]
    }
    routes_src = node if node is not None else insts[0]
    route_nodes = []
    l1 = next((i for i in insts if i.level == 1), None)
    for prefix in sorted(
        routes_src.routes, key=lambda p: (p.version, int(p.network_address), p.prefixlen)
    ):
        metric, nhs = routes_src.routes[prefix][:2]
        level = 2 if len(insts) > 1 else insts[0].level
        if l1 is not None and routes_src.routes[prefix] == l1.routes.get(prefix):
            level = 1
        node_r: dict = {"prefix": str(prefix)}
        nh_nodes = []
        for ifn, addr in sorted(
            nhs, key=lambda x: (str(x[0]), str(x[1]))
        ):
            nh: dict = {}
            if addr is not None:
                nh["next-hop"] = str(addr)
            nh["outgoing-interface"] = ifn
            nh_nodes.append(nh)
        if nh_nodes:
            node_r["next-hops"] = {"next-hop": nh_nodes}
        node_r["metric"] = metric
        node_r["level"] = level
        route_nodes.append(node_r)
    if route_nodes:
        out["local-rib"] = {"route": route_nodes}
    if ifnames is None:
        ifnames = []
        for inst in insts:
            for name in inst.interfaces:
                if name not in ifnames:
                    ifnames.append(name)
    out["interfaces"] = {
        "interface": [_render_iface(insts, n) for n in ifnames]
    }
    return out

"""Protocol implementations (SURVEY.md §2.3).

Each protocol is an actor on the shared event loop with the common anatomy
of the reference crates: packet codecs, FSMs, an instance root, northbound
glue, and ibus rx/tx.
"""

"""YANG-modeled OSPFv2 operational state.

Renders a live :class:`OspfInstance` into the ietf-ospf state tree —
the exact shape the reference serves through its northbound and records
in conformance snapshots (holo-ospf/src/northbound/state.rs; corpus:
holo-ospf/tests/conformance/ospfv2/**/northbound-state.json).  Volatile
leaves the reference marks ``ignore_in_testing`` (ages, seqnos,
checksums, timestamps) are omitted, matching the recorded trees.

Empty lists/containers are dropped, mirroring the reference's JSON
printer.
"""

from __future__ import annotations

from ipaddress import IPv4Address

from holo_tpu.protocols.ospf.interface import IfType, IsmState, OspfInterface
from holo_tpu.protocols.ospf.lsdb import Lsdb
from holo_tpu.protocols.ospf.neighbor import NsmState
from holo_tpu.protocols.ospf.packet import (
    EXT_PREFIX_OPAQUE_TYPE,
    GRACE_OPAQUE_TYPE,
    MAX_AGE,
    RI_CAP_GR_CAPABLE,
    RI_CAP_GR_HELPER,
    RI_CAP_STUB_ROUTER,
    RI_OPAQUE_TYPE,
    EXT_PREFIX_FLAG_A,
    EXT_PREFIX_FLAG_N,
    EXT_PREFIX_FLAG_AC,
    Lsa,
    LsaType,
    Options,
    RouterFlags,
    RouterLinkType,
    decode_ext_prefix_entries,
    decode_grace_tlvs,
    decode_router_info,
)

# ietf-ospf identity per LSA type (module prefix implied by context).
LSA_TYPE_NAME = {
    LsaType.ROUTER: "ospfv2-router-lsa",
    LsaType.NETWORK: "ospfv2-network-lsa",
    LsaType.SUMMARY_NETWORK: "ospfv2-network-summary-lsa",
    LsaType.SUMMARY_ROUTER: "ospfv2-asbr-summary-lsa",
    LsaType.AS_EXTERNAL: "ospfv2-as-external-lsa",
    LsaType.NSSA_EXTERNAL: "ospfv2-nssa-lsa",
    LsaType.OPAQUE_LINK: "ospfv2-link-scope-opaque-lsa",
    LsaType.OPAQUE_AREA: "ospfv2-area-scope-opaque-lsa",
    LsaType.OPAQUE_AS: "ospfv2-as-scope-opaque-lsa",
}

# RFC 8665 SID flag bit names, in the RECORDED corpus vintage's
# spelling (ietf-ospf-sr module, '-bit' suffixes; the module prefix is
# canonicalized away by the tree diff).
_PREFIX_SID_BITS = [
    (0x40, "np-bit"),
    (0x20, "m-bit"),
    (0x10, "e-bit"),
    (0x08, "v-bit"),
    (0x04, "l-bit"),
]
_ADJ_SID_BITS = [
    (0x80, "b-bit"),
    (0x40, "vi-bit"),
    (0x20, "lo-bit"),
    (0x10, "g-bit"),
    (0x08, "p-bit"),
]
_EXT_LINK_TYPE = {
    1: "point-to-point-link",
    2: "transit-network-link",
}
EXT_LINK_OPAQUE_TYPE = 8

_OPTION_BITS = [
    (Options.E, "v2-e-bit"),
    (Options.MC, "mc-bit"),
    (Options.NP, "v2-p-bit"),
    (Options.L, "ietf-ospf-lls:lls-bit"),
    (Options.DC, "v2-dc-bit"),
    (Options.O, "o-bit"),
]

_RTR_BITS = [
    (RouterFlags.B, "abr-bit"),
    (RouterFlags.E, "asbr-bit"),
    (RouterFlags.V, "vlink-end-bit"),
]

_LINK_TYPE_NAME = {
    RouterLinkType.POINT_TO_POINT: "point-to-point-link",
    RouterLinkType.TRANSIT_NETWORK: "transit-network-link",
    RouterLinkType.STUB_NETWORK: "stub-network-link",
    RouterLinkType.VIRTUAL_LINK: "virtual-link",
}

_ISM_NAME = {
    IsmState.DOWN: "down",
    IsmState.LOOPBACK: "loopback",
    IsmState.WAITING: "waiting",
    IsmState.POINT_TO_POINT: "point-to-point",
    IsmState.DR_OTHER: "dr-other",
    IsmState.BACKUP: "bdr",
    IsmState.DR: "dr",
}

_NSM_NAME = {
    NsmState.DOWN: "down",
    NsmState.ATTEMPT: "attempt",
    NsmState.INIT: "init",
    NsmState.TWO_WAY: "2-way",
    NsmState.EX_START: "exstart",
    NsmState.EXCHANGE: "exchange",
    NsmState.LOADING: "loading",
    NsmState.FULL: "full",
}

_ROUTE_TYPE_NAME = {
    "intra": "intra-area",
    "inter": "inter-area",
    "external-1": "external-1",
    "external-2": "external-2",
    "nssa-1": "nssa-1",
    "nssa-2": "nssa-2",
}

_GR_REASON_NAME = {
    0: "unknown",
    1: "software-restart",
    2: "software-upgrade",
    3: "control-processor-switchover",
}

_EXT_PREFIX_ROUTE_TYPE = {
    0: "unspecified",
    1: "intra-area",
    3: "inter-area",
    5: "external",
    7: "nssa",
}


def _bits(value, table) -> list[str]:
    return [name for bit, name in table if value & bit]


def _a(x) -> str:
    return str(IPv4Address(x))


def lsa_header_yang(lsa: Lsa, age: int) -> dict:
    h: dict = {
        "lsa-id": _a(lsa.lsid),
        "type": LSA_TYPE_NAME[lsa.type],
        "adv-router": _a(lsa.adv_rtr),
        "length": len(lsa.raw),
    }
    bits = _bits(lsa.options, _OPTION_BITS)
    if bits:
        # Empty bit containers are omitted (reference JSON printer).
        h["lsa-options"] = {"lsa-options": bits}
    if lsa.type in (
        LsaType.OPAQUE_LINK,
        LsaType.OPAQUE_AREA,
        LsaType.OPAQUE_AS,
    ):
        h["opaque-type"] = int(lsa.lsid) >> 24
        h["opaque-id"] = int(lsa.lsid) & 0xFFFFFF
    if age >= MAX_AGE:
        h["holo-ospf-dev:maxage"] = [None]
    return h


def _topology(metric: int) -> dict:
    return {"topologies": {"topology": [{"mt-id": 0, "metric": metric}]}}


def _opaque_body_yang(lsa: Lsa) -> dict:
    otype = int(lsa.lsid) >> 24
    data = lsa.body.data
    if otype == GRACE_OPAQUE_TYPE:
        info = decode_grace_tlvs(data)
        grace: dict = {}
        if "grace_period" in info:
            grace["grace-period"] = info["grace_period"]
        if "reason" in info:
            grace["graceful-restart-reason"] = _GR_REASON_NAME.get(
                info["reason"], "unknown"
            )
        if "addr" in info:
            grace["ip-interface-address"] = str(info["addr"])
        return {"holo-ospf:grace": grace}
    if otype == RI_OPAQUE_TYPE:
        info = decode_router_info(data)
        ri: dict = {}
        caps = info["info_caps"]
        if caps:
            names = []
            flags = []
            for bit, name in (
                (RI_CAP_GR_CAPABLE, "graceful-restart"),
                (RI_CAP_GR_HELPER, "graceful-restart-helper"),
                (RI_CAP_STUB_ROUTER, "stub-router"),
            ):
                if caps & bit:
                    names.append(name)
                    flags.append({"informational-flag": bit})
            ri["router-capabilities-tlv"] = {
                "router-informational-capabilities": {
                    "informational-capabilities": names
                },
                "informational-capabilities-flags": flags,
            }
        if info.get("sr_algos"):
            ri["ietf-ospf-sr:sr-algorithm-tlv"] = {
                "sr-algorithm": list(info["sr_algos"])
            }
        if info.get("srgb_ranges"):
            ri["ietf-ospf-sr:sid-range-tlvs"] = {
                "sid-range-tlv": [
                    {
                        "range-size": size,
                        **(
                            {"sid-sub-tlv": {"sid": first}}
                            if first is not None
                            else {}
                        ),
                    }
                    for size, first in info["srgb_ranges"]
                ]
            }
        if info["hostname"]:
            ri["dynamic-hostname-tlv"] = {"hostname": info["hostname"]}
        if info["node_tags"]:
            ri["node-tag-tlvs"] = {
                "node-tag-tlv": [
                    {
                        "node-tag": [
                            {"tag": t} for t in info["node_tags"]
                        ]
                    }
                ]
            }
        return {"ri-opaque": ri}
    if otype == EXT_PREFIX_OPAQUE_TYPE:
        tlvs = []
        for prefix, route_type, flags, sids in decode_ext_prefix_entries(
            data
        ):
            entry: dict = {
                "route-type": _EXT_PREFIX_ROUTE_TYPE.get(
                    route_type, "unspecified"
                ),
            }
            fl = []
            if flags & EXT_PREFIX_FLAG_A:
                fl.append("a-flag")
            if flags & EXT_PREFIX_FLAG_N:
                fl.append("node-flag")
            if flags & EXT_PREFIX_FLAG_AC:
                fl.append("ietf-ospf-anycast-flag:ac-flag")
            if fl:
                entry["flags"] = {"extended-prefix-flags": fl}
            entry["prefix"] = str(prefix)
            if sids:
                entry["ietf-ospf-sr:prefix-sid-sub-tlvs"] = {
                    "prefix-sid-sub-tlv": [
                        {
                            "prefix-sid-flags": {
                                "bits": _bits(
                                    s["flags"], _PREFIX_SID_BITS
                                )
                            },
                            "mt-id": s["mt"],
                            "algorithm": s["algo"],
                            "sid": s["sid"],
                        }
                        for s in sids
                    ]
                }
            tlvs.append(entry)
        return {
            "extended-prefix-opaque": {"extended-prefix-tlv": tlvs}
        }
    if otype == EXT_LINK_OPAQUE_TYPE:
        from holo_tpu.protocols.ospf.packet import decode_ext_link

        links = decode_ext_link(data)
        if not links:
            return {}
        ltype, link_id, link_data, sids = links[0]
        out: dict = {
            "link-id": str(link_id),
            "link-data": str(link_data),
            "type": _EXT_LINK_TYPE.get(ltype, "unknown"),
        }
        p2p = [s for s in sids if "nbr" not in s]
        lan = [s for s in sids if "nbr" in s]
        if p2p:
            out["ietf-ospf-sr:adj-sid-sub-tlvs"] = {
                "adj-sid-sub-tlv": [
                    {
                        "adj-sid-flags": {
                            "bits": _bits(s["flags"], _ADJ_SID_BITS)
                        },
                        "mt-id": s["mt"],
                        "weight": s["weight"],
                        "sid": s["sid"],
                    }
                    for s in p2p
                ]
            }
        if lan:
            out["ietf-ospf-sr:lan-adj-sid-sub-tlvs"] = {
                "lan-adj-sid-sub-tlv": [
                    {
                        "lan-adj-sid-flags": {
                            "bits": _bits(s["flags"], _ADJ_SID_BITS)
                        },
                        "mt-id": s["mt"],
                        "weight": s["weight"],
                        "neighbor-router-id": str(s["nbr"]),
                        "sid": s["sid"],
                    }
                    for s in lan
                ]
            }
        return {"extended-link-opaque": {"extended-link-tlv": out}}
    return {}


def lsa_body_yang(lsa: Lsa) -> dict:
    t = lsa.type
    b = lsa.body
    if t == LsaType.ROUTER:
        body: dict = {"num-of-links": len(b.links)}
        bits = _bits(b.flags, _RTR_BITS)
        if bits:
            body["router-bits"] = {"rtr-lsa-bits": bits}
        if b.links:
            body["links"] = {
                "link": [
                    {
                        "link-id": _a(l.id),
                        "link-data": _a(l.data),
                        "type": _LINK_TYPE_NAME[l.link_type],
                        **_topology(l.metric),
                    }
                    for l in b.links
                ]
            }
        return {"router": body}
    if t == LsaType.NETWORK:
        body = {"network-mask": _a(b.mask)}
        if b.attached:
            body["attached-routers"] = {
                "attached-router": [_a(x) for x in b.attached]
            }
        return {"network": body}
    if t in (LsaType.SUMMARY_NETWORK, LsaType.SUMMARY_ROUTER):
        return {
            "summary": {
                "network-mask": _a(b.mask),
                **_topology(b.metric),
            }
        }
    if t in (LsaType.AS_EXTERNAL, LsaType.NSSA_EXTERNAL):
        topo = {
            "mt-id": 0,
            "flags": "v2-e-bit" if b.e_bit else "",
            "metric": b.metric,
            "external-route-tag": b.tag,
        }
        if int(b.fwd_addr):
            topo["forwarding-address"] = _a(b.fwd_addr)
        return {
            "external": {
                "network-mask": _a(b.mask),
                "topologies": {"topology": [topo]},
            }
        }
    if t in (LsaType.OPAQUE_LINK, LsaType.OPAQUE_AREA, LsaType.OPAQUE_AS):
        return {"opaque": _opaque_body_yang(lsa)}
    return {}


def render_lsa(lsa: Lsa, age: int) -> dict:
    out = {
        "lsa-id": _a(lsa.lsid),
        "adv-router": _a(lsa.adv_rtr),
        "decode-completed": True,
        "ospfv2": {
            "header": lsa_header_yang(lsa, age),
        },
    }
    body = lsa_body_yang(lsa)
    if body:
        out["ospfv2"]["body"] = body
    return out


def _db_buckets(entries, now, kind: str) -> tuple[list, list]:
    """Group LSA entries by type → (database list, statistics list)."""
    by_type: dict[int, list] = {}
    for e in entries:
        by_type.setdefault(int(e.lsa.type), []).append(e)
    db = []
    stats = []
    for t in sorted(by_type):
        lsas = sorted(
            by_type[t], key=lambda e: (int(e.lsa.lsid), int(e.lsa.adv_rtr))
        )
        db.append(
            {
                "lsa-type": t,
                f"{kind}-lsas": {
                    f"{kind}-lsa": [
                        render_lsa(e.lsa, e.current_age(now))
                        for e in lsas
                    ]
                },
            }
        )
        stats.append({"lsa-type": t, "lsa-count": len(lsas)})
    return db, stats


def _iface_state(
    inst, area, iface: OspfInterface, link_lsas: list, now
) -> dict:
    out: dict = {
        "name": iface.name,
        "state": _ISM_NAME[iface.state],
    }
    if int(iface.dr):
        out["dr-ip-addr"] = str(iface.dr)
        rid = _rid_for_addr(inst, iface, iface.dr)
        if rid is not None:
            out["dr-router-id"] = str(rid)
    if int(iface.bdr):
        out["bdr-ip-addr"] = str(iface.bdr)
        rid = _rid_for_addr(inst, iface, iface.bdr)
        if rid is not None:
            out["bdr-router-id"] = str(rid)
    db, stats = _db_buckets(link_lsas, now, "link-scope")
    out["statistics"] = {
        "link-scope-lsa-count": sum(s["lsa-count"] for s in stats)
    }
    if stats:
        out["statistics"]["database"] = {"link-scope-lsa-type": stats}
    if db:
        out["database"] = {"link-scope-lsa-type": db}
    nbrs = []
    for nbr in sorted(
        iface.neighbors.values(), key=lambda n: int(n.router_id)
    ):
        n: dict = {
            "neighbor-router-id": str(nbr.router_id),
            "address": str(nbr.src),
        }
        if int(nbr.dr):
            n["dr-ip-addr"] = str(nbr.dr)
            rid = _rid_for_addr(inst, iface, nbr.dr)
            if rid is not None:
                n["dr-router-id"] = str(rid)
        if int(nbr.bdr):
            n["bdr-ip-addr"] = str(nbr.bdr)
            rid = _rid_for_addr(inst, iface, nbr.bdr)
            if rid is not None:
                n["bdr-router-id"] = str(rid)
        n["state"] = _NSM_NAME[nbr.state]
        if nbr.gr_deadline is not None:
            n["holo-ospf:graceful-restart"] = {
                "restart-reason": _GR_REASON_NAME.get(
                    nbr.gr_reason, "unknown"
                )
            }
        n["statistics"] = {"nbr-retrans-qlen": len(nbr.ls_rxmt)}
        nbrs.append(n)
    if nbrs:
        out["neighbors"] = {"neighbor": nbrs}
    return out


def _rid_for_addr(inst, iface: OspfInterface, addr) -> IPv4Address | None:
    """Resolve an interface address to a router-id (self or a neighbor)."""
    if iface.addr_ip == addr:
        return inst.config.router_id
    for nbr in iface.neighbors.values():
        if nbr.src == addr:
            return nbr.router_id
    return None


def instance_state(inst) -> dict:
    """The full 'ietf-ospf:ospf' state subtree for an OspfInstance."""
    now = inst.loop.clock.now() if inst.loop is not None else 0.0
    if not getattr(inst, "enabled", True):
        # Disabled instance: minimal tree (areas + interface admin view),
        # like the reference's torn-down Instance<Down>.
        return _disabled_state(inst)
    ospf: dict = {"router-id": str(inst.config.router_id)}
    ospf["spf-control"] = {
        "ietf-spf-delay": {"current-state": inst.spf_state.value}
    }

    # Areas.
    areas = []
    hostnames: dict = {}
    as_entries: dict = {}  # LsaKey -> entry, deduped across areas
    for aid in sorted(inst.areas, key=int):
        area = inst.areas[aid]
        link_by_iface: dict[str, list] = {}
        area_entries = []
        for e in area.lsdb.all():
            t = e.lsa.type
            if t in (LsaType.AS_EXTERNAL, LsaType.OPAQUE_AS):
                as_entries[e.lsa.key] = e
                continue
            if t == LsaType.OPAQUE_LINK:
                ifname = inst._link_scope_iface.get(e.lsa.key)
                if ifname is not None:
                    link_by_iface.setdefault(ifname, []).append(e)
                continue
            area_entries.append(e)
            if t == LsaType.OPAQUE_AREA and (
                int(e.lsa.lsid) >> 24
            ) == RI_OPAQUE_TYPE:
                info = decode_router_info(e.lsa.body.data)
                if info["hostname"]:
                    hostnames[e.lsa.adv_rtr] = info["hostname"]

        db, stats = _db_buckets(area_entries, now, "area-scope")
        # Router flags come from the SPF products (captured at SPF time),
        # not the live LSDB — reference area.rs:164-182 counts
        # area.state.routers, which go stale together.
        reachable = inst._area_reachable_routers.get(aid, {})
        a: dict = {
            "area-id": str(aid),
            "statistics": {
                "abr-count": sum(
                    1
                    for fl in reachable.values()
                    if fl & RouterFlags.B
                ),
                "asbr-count": sum(
                    1
                    for fl in reachable.values()
                    if fl & RouterFlags.E
                ),
                "area-scope-lsa-count": sum(
                    s["lsa-count"] for s in stats
                ),
            },
        }
        if stats:
            a["statistics"]["database"] = {"area-scope-lsa-type": stats}
        if db:
            a["database"] = {"area-scope-lsa-type": db}
        # Virtual links render in their own container (§15), never in
        # the physical interface list.
        phys = [
            i for i in area.interfaces.values()
            if i.config.if_type != IfType.VIRTUAL_LINK
        ]
        vlinks = [
            i for i in area.interfaces.values()
            if i.config.if_type == IfType.VIRTUAL_LINK
        ]
        if vlinks:
            a["virtual-links"] = {
                "virtual-link": [
                    {
                        "transit-area-id": v.name.rsplit("-", 2)[-2],
                        "router-id": v.name.rsplit("-", 1)[-1],
                        "cost": v.config.cost,
                        "state": _ISM_NAME[v.state],
                        "statistics": {
                            "link-scope-lsa-count": len(
                                link_by_iface.get(v.name, [])
                            )
                        },
                        "neighbors": {
                            "neighbor": [
                                {
                                    "neighbor-router-id": str(
                                        n.router_id
                                    ),
                                    "address": str(n.src),
                                    "state": _NSM_NAME[n.state],
                                    "statistics": {
                                        "nbr-retrans-qlen": len(
                                            n.ls_rxmt
                                        )
                                    },
                                }
                                for n in v.neighbors.values()
                            ]
                        },
                    }
                    for v in sorted(vlinks, key=lambda i: i.name)
                ]
            }
        ifaces = [
            _iface_state(
                inst, area, iface, link_by_iface.get(iface.name, []), now
            )
            for iface in sorted(phys, key=lambda i: i.name)
        ]
        if ifaces:
            a["interfaces"] = {"interface": ifaces}
        areas.append(a)
    if areas:
        ospf["areas"] = {"area": areas}

    # AS-scope database + statistics.
    db, stats = _db_buckets(as_entries.values(), now, "as-scope")
    ospf["statistics"] = {
        "as-scope-lsa-count": sum(s["lsa-count"] for s in stats)
    }
    if stats:
        ospf["statistics"]["database"] = {"as-scope-lsa-type": stats}
    if db:
        ospf["database"] = {"as-scope-lsa-type": db}

    # Local RIB.
    routes = []
    for prefix in sorted(
        inst.routes, key=lambda p: (int(p.network_address), p.prefixlen)
    ):
        route = inst.routes[prefix]
        r: dict = {
            "prefix": str(prefix),
            "metric": route.dist,
            "route-type": _ROUTE_TYPE_NAME.get(route.rtype, route.rtype),
        }
        nhs = []
        for nh in sorted(
            route.nexthops,
            key=lambda n: (n.ifname, int(n.addr) if n.addr else 0),
        ):
            entry = {"outgoing-interface": nh.ifname}
            if nh.addr is not None:
                entry["next-hop"] = str(nh.addr)
            nhs.append(entry)
        if nhs:
            r["next-hops"] = {"next-hop": nhs}
        routes.append(r)
    if routes:
        ospf["local-rib"] = {"route": routes}

    if inst.hostname:
        hostnames[inst.config.router_id] = inst.hostname
    if hostnames:
        ospf["holo-ospf:hostnames"] = {
            "hostname": [
                {"router-id": str(rid), "hostname": hostnames[rid]}
                for rid in sorted(hostnames, key=int)
            ]
        }
    return ospf


def _disabled_state(inst) -> dict:
    areas = []
    for aid in sorted(inst.areas, key=int):
        area = inst.areas[aid]
        areas.append(
            {
                "area-id": str(aid),
                "statistics": {
                    "abr-count": 0,
                    "asbr-count": 0,
                    "area-scope-lsa-count": 0,
                },
                "interfaces": {
                    "interface": [
                        {
                            "name": iface.name,
                            "state": _ISM_NAME[iface.state],
                            "statistics": {"link-scope-lsa-count": 0},
                        }
                        for iface in sorted(
                            area.interfaces.values(), key=lambda i: i.name
                        )
                    ]
                },
            }
        )
    return {"areas": {"area": areas}} if areas else {}


def protocol_state(inst, name: str | None = None) -> dict:
    """Wrap in the ietf-routing control-plane-protocol envelope."""
    return {
        "ietf-routing:routing": {
            "control-plane-protocols": {
                "control-plane-protocol": [
                    {
                        "type": "ietf-ospf:ospfv2",
                        "name": name or inst.name,
                        "ietf-ospf:ospf": instance_state(inst),
                    }
                ]
            }
        }
    }

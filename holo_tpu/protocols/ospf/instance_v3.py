"""OSPFv3 instance actor (RFC 5340): p2p circuits, v6 routing.

Reference: holo-ospf's ospfv3 side of the Version trait.  Shares the
neighbor NSM (neighbor.py) and the DD/flooding semantics with the v2
instance; differs where the protocol differs — link-local transport,
router-id keyed hellos, LSA types with flooding scopes, prefixes carried
in Link / Intra-Area-Prefix LSAs, and the SPF topology built from router
links keyed by (router-id, interface-id).

Scope: p2p + broadcast interfaces (router-id DR election with a
Waiting/BackupSeen analog, network LSAs, network-referenced
Intra-Area-Prefix LSAs), single area, intra-area v6 routes over router
AND network vertices; inter-area (ABR) lands next.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from ipaddress import IPv4Address, IPv6Address, IPv6Network

import numpy as np

from holo_tpu import telemetry
from holo_tpu.ops.graph import INF, Topology
from holo_tpu.protocols.ospf import packet_v3 as P
from holo_tpu.protocols.ospf.instance import (
    _OSPF_NBR_TRANSITIONS,
    _OSPF_PACKETS,
    _OSPF_RX_BAD,
    _OSPF_SPF_RUNS,
)
from holo_tpu.protocols.ospf.interface import ElectionView, IfType, elect_dr_bdr
from holo_tpu.protocols.ospf.lsdb import MIN_LS_ARRIVAL, Lsdb, next_seq_no
from holo_tpu.protocols.ospf.spf_run import (
    apply_interface_srlg,
    atom_bits,
    srlg_bits,
)
from holo_tpu.protocols.ospf.neighbor import (
    Neighbor,
    NsmEvent,
    NsmState,
    nsm_transition,
)
from holo_tpu.spf.backend import ScalarSpfBackend, SpfBackend
from holo_tpu.telemetry import convergence
from holo_tpu.utils.ip import ALL_SPF_RTRS_V6
from holo_tpu.utils.netio import NetIo, NetRxPacket
from holo_tpu.utils.runtime import Actor

DD_CHUNK = 64
AGE_TICK = 1.0


@dataclass
class V3IfConfig:
    area_id: IPv4Address = IPv4Address(0)
    cost: int = 10
    hello_interval: int = 10
    dead_interval: int = 40
    rxmt_interval: int = 5
    mtu: int = 1500
    # RFC 2328 §10.6 / RFC 5340: DD Interface-MTU check bypass and the
    # §13.3 InfTransDelay LSA age increment (ietf-ospf interface leaves).
    mtu_ignore: bool = False
    transmit_delay: int = 1
    instance_id: int = 0
    if_type: IfType = IfType.POINT_TO_POINT
    priority: int = 1
    loopback: bool = False
    # Passive circuits advertise their prefixes but exchange no packets.
    passive: bool = False
    auth: object = None  # packet_v3.AuthCtxV3 or None (RFC 7166 trailer)
    # Fast-reroute SRLG membership (see IfConfig.srlg): lowered to
    # Topology.edge_srlg at SPF marshal time for the FRR policy masks.
    srlg: tuple = ()


@dataclass
class V3Interface:
    name: str
    config: V3IfConfig
    iface_id: int
    link_local: IPv6Address
    prefixes: list[IPv6Network] = field(default_factory=list)
    # Link-scope LSDB (RFC 5340 §4.4.2: Link LSAs live per circuit).
    link_lsdb: Lsdb = field(default_factory=Lsdb)
    up: bool = False
    neighbors: dict[IPv4Address, Neighbor] = field(default_factory=dict)
    # LAN state (RFC 5340 identifies DR/BDR by ROUTER-ID, not address).
    dr: IPv4Address = IPv4Address(0)
    bdr: IPv4Address = IPv4Address(0)
    # §9.4 Waiting state: no self-election until the wait timer expires
    # or a neighbor declares an existing DR/BDR (BackupSeen).
    wait_until: float = 0.0
    up_since: float = -1e9
    # RFC 7166 replay protection: highest verified seqno per neighbor.
    at_seqnos: dict = field(default_factory=dict)

    @property
    def is_lan(self) -> bool:
        return self.config.if_type == IfType.BROADCAST


@dataclass
class HelloTimerV3:
    ifname: str


@dataclass
class InactivityTimerV3:
    ifname: str
    nbr_id: IPv4Address


@dataclass
class RxmtTimerV3:
    ifname: str
    nbr_id: IPv4Address


@dataclass
class SpfTimerV3:
    pass


@dataclass
class WaitTimerV3:
    ifname: str


@dataclass
class AgeTickV3:
    pass


@dataclass
class V3IfUpMsg:
    ifname: str


@dataclass
class V3IfDownMsg:
    ifname: str


@dataclass
class V6Route:
    prefix: IPv6Network
    dist: int
    nexthops: frozenset  # {(ifname, link-local addr)}
    # ietf-ospf route-type identity for the local-rib state plane.
    route_type: str = "intra-area"
    # Prefix options from the originating LSA entry (LA propagates into
    # the ABR's inter-area advertisement, like the reference).
    prefix_options: int = 0
    # Area that contributed the winning path (None for external).
    area_id: object = None
    # SPT vertex the winning path terminates at (-1 when not derived
    # from an SPT vertex) — the IP-FRR consumption key.
    vertex: int = -1
    # IP-FRR repairs: {primary (ifname, ll-addr) -> (backup, labels)}.
    backups: dict | None = None


@dataclass
class V3Area:
    """One OSPFv3 area: its LSDB plus type flags (RFC 5340 areas carry
    the same stub/NSSA semantics as v2, with v6 LSA types)."""

    area_id: IPv4Address
    lsdb: Lsdb = field(default_factory=Lsdb)
    stub: bool = False
    nssa: bool = False
    # ietf-ospf summary=false: a totally-stubby area gets ONLY the
    # default inter-area-prefix from its ABRs.
    summary: bool = True
    stub_default_cost: int = 10  # ietf-ospf default-cost default

    @property
    def no_external(self) -> bool:
        return self.stub or self.nssa


class OspfV3Instance(Actor):
    """One OSPFv3 routing process: multi-area ABR (inter-area-prefix
    LSAs), stub areas, externals, LAN + p2p circuits."""

    name = "ospfv3"

    def __init__(
        self,
        name: str,
        router_id: IPv4Address,
        netio: NetIo,
        spf_backend: SpfBackend | None = None,
        route_cb=None,
        notif_cb=None,
        nvstore=None,
    ):
        self.name = name
        self.router_id = router_id
        self.netio = netio
        self.backend = spf_backend or ScalarSpfBackend()
        self.route_cb = route_cb
        self.notif_cb = notif_cb
        self.interfaces: dict[str, V3Interface] = {}
        self.areas: dict[IPv4Address, V3Area] = {}
        self.routes: dict[IPv6Network, V6Route] = {}
        # Configured virtual links [(transit area id, peer router id)];
        # when empty, vlink peers are discovered from our backbone
        # router-LSA and the best transit area is reported.
        self.vlink_config: list = []
        # Vlink endpoint state rows (ietf-ospf virtual-links render).
        self.vlink_state: list = []
        # v6 prefixes we redistribute as AS-external LSAs (ASBR duty).
        self.redistributed: dict[IPv6Network, int] = {}  # prefix -> metric
        self.spf_run_count = 0
        # IP fast reroute (holo_tpu.frr.FrrConfig; None = disabled) and
        # the per-area backup tables the area SPF refreshes.
        self.frr = None
        self.frr_tables: dict = {}
        self._frr_engine = None
        # ietf-ospf max-paths (ISSUE 10): None = unlimited ECMP;
        # 2..8 arms the vectorized multipath dispatch (same contract
        # as the v2 instance's config.max_paths).
        self.max_paths: int | None = None
        # DeltaPath: the previous run's (vertex keys, atoms, topology)
        # per area — the diff base for in-place device-graph updates.
        self._spf_delta_bases: dict = {}
        # Hierarchical partition hint (ISSUE 15): router-id -> group
        # label lowered through spf_run.apply_partition_hint at the
        # marshal seam (same contract as the v2 instance).
        self.spf_partition_of: dict | None = None
        # RFC 6987 stub-router: MaxLinkMetric on transit/p2p router-LSA
        # links (maintenance mode; same leaf as the v2 instance).
        self.stub_router = False
        # Full-vs-partial classification (reference ospfv3/spf.rs:97-163):
        # changed LSAs accumulate as (new, old) pairs; non-LSA events
        # force Full.  The cache keeps the last full run's SPTs + route
        # tables for prefix-scoped partial updates (route.rs:200-333).
        self._spf_triggers: list = []
        self._spf_force_full = True
        self._spf_cache: dict | None = None
        # Convergence-observatory causal ids pending on the next run.
        self._conv_pending: list = []
        # SPF run log ring (reference spf.rs:770-804).
        self.spf_log: list[dict] = []
        self._dd_seq = 0x3000
        self._next_iface_id = 1
        self._spf_pending = False
        self._timers: dict[tuple, object] = {}
        self._inter_ids: dict = {}  # summarized prefix/asbr -> lsid
        # RFC 7166 64-bit tx sequence number: restart-safe via a durable
        # reservation ceiling (same scheme as the v2 instance).
        self._nvstore = nvstore
        self._at_key = f"ospfv3/{name}/at-seqno-ceiling"
        self._at_reserved = 0
        if nvstore is not None:
            self._at_seqno = int(nvstore.get(self._at_key, 0))
            self._reserve_at_seqnos()
        else:
            self._at_seqno = 0

    _AT_WINDOW = 1 << 16

    def _reserve_at_seqnos(self) -> None:
        self._at_reserved = self._at_seqno + self._AT_WINDOW
        self._nvstore.put(self._at_key, self._at_reserved)

    def attach(self, loop_):
        super().attach(loop_)
        self._age_timer = self.loop.timer(self.name, AgeTickV3)
        self._age_timer.start(AGE_TICK)
        self._spf_timer = self.loop.timer(self.name, SpfTimerV3)

    def add_interface(
        self,
        ifname: str,
        cfg: V3IfConfig,
        link_local: IPv6Address,
        prefixes: list[IPv6Network],
        stub: bool = False,
        nssa: bool = False,
        stub_default_cost: int = 10,
        summary: bool = True,
    ) -> V3Interface:
        assert not (stub and nssa), "area cannot be both stub and NSSA"
        area = self.areas.get(cfg.area_id)
        if area is None:
            area = V3Area(cfg.area_id, stub=stub, nssa=nssa,
                          summary=summary,
                          stub_default_cost=stub_default_cost)
            self.areas[cfg.area_id] = area
        else:
            area.stub = stub
            area.nssa = nssa
            area.summary = summary
            area.stub_default_cost = stub_default_cost
        iface = V3Interface(
            name=ifname,
            config=cfg,
            iface_id=self._next_iface_id,
            link_local=link_local,
            prefixes=list(prefixes),
        )
        self._next_iface_id += 1
        self.interfaces[ifname] = iface
        return iface

    @property
    def is_abr(self) -> bool:
        return len(self.areas) > 1

    @property
    def lsdb(self) -> Lsdb:
        """Single-area convenience view: the backbone (or only) area."""
        backbone = self.areas.get(IPv4Address(0))
        if backbone is not None:
            return backbone.lsdb
        return next(iter(self.areas.values())).lsdb

    def _area_of(self, iface: V3Interface) -> V3Area:
        return self.areas[iface.config.area_id]

    def _area_ifaces(self, area: "V3Area"):
        return (
            i
            for i in self.interfaces.values()
            if i.config.area_id == area.area_id
        )

    # -- actor

    def handle(self, msg):
        if isinstance(msg, NetRxPacket):
            self._rx(msg)
        elif isinstance(msg, HelloTimerV3):
            self._send_hello(msg.ifname)
        elif isinstance(msg, InactivityTimerV3):
            self._nbr_event(msg.ifname, msg.nbr_id, NsmEvent.INACTIVITY_TIMER)
        elif isinstance(msg, RxmtTimerV3):
            self._rxmt(msg.ifname, msg.nbr_id)
        elif isinstance(msg, SpfTimerV3):
            self._spf_pending = False
            self.run_spf()
        elif isinstance(msg, WaitTimerV3):
            iface = self.interfaces.get(msg.ifname)
            if iface is not None and iface.up and iface.is_lan:
                iface.wait_until = 0.0
                self._run_dr_election(iface)
        elif isinstance(msg, AgeTickV3):
            self._age_tick()
        elif isinstance(msg, V3IfUpMsg):
            self.if_up(msg.ifname)
        elif isinstance(msg, V3IfDownMsg):
            self.if_down(msg.ifname)

    def if_up(self, ifname: str) -> None:
        iface = self.interfaces.get(ifname)
        if iface is None or iface.up:
            return
        iface.up = True
        if iface.is_lan and not iface.config.passive:
            # §9.4 Waiting: listen for an incumbent DR before claiming.
            iface.up_since = self.loop.clock.now()
            iface.wait_until = (
                self.loop.clock.now() + iface.config.dead_interval
            )
            self._timer(
                ("wait", ifname), lambda: WaitTimerV3(ifname)
            ).start(iface.config.dead_interval)
        self._send_hello(ifname)
        self._originate_router_lsa()
        self._originate_intra_area_prefix()

    def if_down(self, ifname: str) -> None:
        iface = self.interfaces.get(ifname)
        if iface is None or not iface.up:
            return
        iface.up = False  # before the kills: elections no-op on a dead iface
        for nbr_id in list(iface.neighbors):
            self._nbr_event(ifname, nbr_id, NsmEvent.KILL_NBR)
        iface.dr = IPv4Address(0)
        iface.bdr = IPv4Address(0)
        for key in (("hello", ifname),):
            t = self._timers.get(key)
            if t:
                t.cancel()
        self._originate_router_lsa()
        self._originate_intra_area_prefix()
        self._schedule_spf()

    # -- timers

    def _timer(self, key, fn):
        t = self._timers.get(key)
        if t is None:
            t = self.loop.timer(self.name, fn)
            self._timers[key] = t
        return t

    # -- hello

    def _send_hello(self, ifname: str) -> None:
        iface = self.interfaces.get(ifname)
        if iface is None or not iface.up or iface.config.passive:
            return
        opts = P.Options.V6 | P.Options.R
        if not self._area_of(iface).no_external:
            opts |= P.Options.E
        hello = P.Hello(
            iface_id=iface.iface_id,
            priority=iface.config.priority,
            options=opts,
            hello_interval=iface.config.hello_interval,
            dead_interval=iface.config.dead_interval,
            dr=iface.dr,
            bdr=iface.bdr,
            neighbors=[n.router_id for n in iface.neighbors.values()
                       if n.state >= NsmState.INIT],
        )
        self._send(iface, ALL_SPF_RTRS_V6, hello)
        self._timer(("hello", ifname), lambda: HelloTimerV3(ifname)).start(
            iface.config.hello_interval
        )

    def _rx_hello(self, iface: V3Interface, src, pkt) -> None:
        h = pkt.body
        if (
            h.hello_interval != iface.config.hello_interval
            or h.dead_interval != iface.config.dead_interval
        ):
            return
        # §10.5 E-bit agreement: both sides must agree on the area's
        # external capability (stub misconfig detection).
        want_e = not self._area_of(iface).no_external
        if bool(h.options & P.Options.E) != want_e:
            return
        nbr = iface.neighbors.get(pkt.router_id)
        if nbr is None:
            nbr = Neighbor(router_id=pkt.router_id, src=src)
            iface.neighbors[pkt.router_id] = nbr
        nbr.src = src  # link-local — the v6 next hop
        changed = (h.priority, h.dr, h.bdr) != (nbr.priority, nbr.dr, nbr.bdr)
        nbr.priority = h.priority
        nbr.iface_id = h.iface_id
        nbr.dr, nbr.bdr = h.dr, h.bdr
        self._nbr_event(iface.name, pkt.router_id, NsmEvent.HELLO_RECEIVED)
        self._timer(
            ("inactivity", iface.name, pkt.router_id),
            lambda: InactivityTimerV3(iface.name, pkt.router_id),
        ).start(iface.config.dead_interval)
        was_2way = nbr.state >= NsmState.TWO_WAY
        if self.router_id in h.neighbors:
            self._nbr_event(iface.name, pkt.router_id, NsmEvent.TWO_WAY_RECEIVED)
        else:
            self._nbr_event(iface.name, pkt.router_id, NsmEvent.ONE_WAY_RECEIVED)
        if iface.is_lan:
            now_2way = (
                pkt.router_id in iface.neighbors
                and iface.neighbors[pkt.router_id].state >= NsmState.TWO_WAY
            )
            if changed or was_2way != now_2way:
                self._run_dr_election(iface)

    # -- DR election (RFC 5340 §4.2.1.1: §9.4 with router-ids)

    def _run_dr_election(self, iface: V3Interface) -> None:
        if not iface.up or iface.config.passive:
            return
        if self.loop.clock.now() < iface.wait_until:
            # BackupSeen: an established DR/BDR declared by a 2-Way
            # neighbor ends Waiting early; otherwise keep listening.
            if any(
                n.state >= NsmState.TWO_WAY and (int(n.dr) or int(n.bdr))
                for n in iface.neighbors.values()
            ):
                iface.wait_until = 0.0
            else:
                return
        # Partial-view guard, active only in the first DeadInterval after
        # coming up: a 2-Way neighbor names an incumbent DR we have not
        # heard from yet (its hello is still in flight after our rejoin).
        # Electing now would self-promote and preempt it — defer until
        # the incumbent is in view.  Outside that window the named DR is
        # genuinely dead and elections must proceed (failover).
        if (
            self.loop.clock.now()
            < iface.up_since + iface.config.dead_interval
        ):
            twoway = {
                n.router_id: n
                for n in iface.neighbors.values()
                if n.state >= NsmState.TWO_WAY
            }
            for n in twoway.values():
                if (
                    int(n.dr)
                    and n.dr != self.router_id
                    and n.dr not in twoway
                ):
                    return
        for _ in range(2):  # §9.4 step 4: rerun when our own role changes
            views = [
                ElectionView(
                    iface.config.priority,
                    self.router_id,
                    self.router_id,  # v3 elects by router-id, not address
                    iface.dr,
                    iface.bdr,
                )
            ]
            for nbr in iface.neighbors.values():
                if nbr.state >= NsmState.TWO_WAY:
                    views.append(
                        ElectionView(
                            nbr.priority, nbr.router_id, nbr.router_id,
                            nbr.dr, nbr.bdr,
                        )
                    )
            new_dr, new_bdr = elect_dr_bdr(views)
            changed = (new_dr, new_bdr) != (iface.dr, iface.bdr)
            iface.dr, iface.bdr = new_dr, new_bdr
            if not changed:
                break
        # AdjOK? — the adjacency set depends on who is DR/BDR.
        for nbr_id in list(iface.neighbors):
            if iface.neighbors[nbr_id].state >= NsmState.TWO_WAY:
                self._nbr_event(iface.name, nbr_id, NsmEvent.ADJ_OK)
        self._originate_router_lsa()
        self._originate_network_lsa(iface)
        self._originate_intra_area_prefix()

    def _adj_ok(self, iface: V3Interface, nbr: Neighbor) -> bool:
        """p2p always; LAN only with/as the DR or BDR (§10.4)."""
        if not iface.is_lan:
            return True
        return (
            iface.dr in (self.router_id, nbr.router_id)
            or iface.bdr in (self.router_id, nbr.router_id)
        )

    # -- NSM plumbing

    def _nbr_event(self, ifname: str, nbr_id, event: NsmEvent) -> None:
        iface = self.interfaces.get(ifname)
        if iface is None:
            return
        nbr = iface.neighbors.get(nbr_id)
        if nbr is None:
            return
        old_state = nbr.state
        res = nsm_transition(nbr, event, adj_ok=self._adj_ok(iface, nbr))
        nbr.state = res.new_state
        if nbr.state != old_state:
            from holo_tpu.protocols.ospf.nb_state import _NSM_NAME

            _OSPF_NBR_TRANSITIONS.labels(
                instance=self.name, to=_NSM_NAME[nbr.state]
            ).inc()
        if nbr.state != old_state and self.notif_cb is not None:
            # Reference holo-ospf northbound/notification.rs (shared by
            # both versions): same shape as the v2 instance's notify.
            from holo_tpu.protocols.ospf.nb_state import _NSM_NAME

            self.notif_cb({
                "ietf-ospf:nbr-state-change": {
                    "routing-protocol-name": self.name,
                    "address-family": "ipv6",
                    "interface": {"interface": iface.name},
                    "neighbor-router-id": str(nbr.router_id),
                    "neighbor-ip-addr": str(nbr.src),
                    "state": _NSM_NAME[nbr.state],
                }
            })
        for act in res.actions:
            if act == "start_exstart":
                self._start_exstart(iface, nbr)
            elif act == "send_dd_summary":
                self._enter_exchange(iface, nbr)
            elif act == "send_ls_request":
                self._send_ls_request(iface, nbr)
            elif act == "clear_lists":
                nbr.ls_request.clear()
                nbr.ls_rxmt.clear()
                nbr.dd_summary.clear()
            elif act == "stop_timers":
                for key in ("inactivity", "rxmt"):
                    t = self._timers.get((key, ifname, nbr_id))
                    if t:
                        t.cancel()
            elif act == "full":
                t = self._timers.get(("rxmt", ifname, nbr_id))
                if t:
                    t.cancel()
        if nbr.state == NsmState.DOWN:
            del iface.neighbors[nbr_id]
            iface.at_seqnos.pop(nbr_id, None)
            if iface.is_lan:
                self._run_dr_election(iface)
        if (old_state >= NsmState.FULL) != (nbr.state >= NsmState.FULL) or (
            nbr.state == NsmState.DOWN
        ):
            self._originate_router_lsa()
            if iface.is_lan:
                self._originate_network_lsa(iface)
            self._originate_intra_area_prefix()

    # -- DD exchange (same semantics as v2; v3 codec)

    def _start_exstart(self, iface: V3Interface, nbr: Neighbor) -> None:
        self._dd_seq += 1
        nbr.dd_seq_no = self._dd_seq
        nbr.master = True
        dd = P.DbDesc(
            mtu=iface.config.mtu,
            options=P.Options.V6 | P.Options.E | P.Options.R,
            flags=P.DbDescFlags.I | P.DbDescFlags.M | P.DbDescFlags.MS,
            dd_seq_no=nbr.dd_seq_no,
        )
        nbr.last_sent_dd = dd
        self._send(iface, nbr.src, dd)
        self._arm_rxmt(iface, nbr)

    def _enter_exchange(self, iface: V3Interface, nbr: Neighbor) -> None:
        now = self.loop.clock.now()
        # Link-scope LSAs are excluded: they must only be exchanged with
        # neighbors on their own link (RFC 5340 §4.5; origin-link tracking
        # lands with Link-LSA origination).
        nbr.dd_summary = [
            e.lsa
            for e in self._area_of(iface).lsdb.entries.values()
            if e.current_age(now) < P.MAX_AGE
            and P.scope_of(int(e.lsa.type)) != "link"
        ]

    def _send_dd(self, iface: V3Interface, nbr: Neighbor) -> None:
        chunk = nbr.dd_summary[:DD_CHUNK]
        more = len(nbr.dd_summary) > len(chunk)
        flags = P.DbDescFlags(0)
        if nbr.master:
            flags |= P.DbDescFlags.MS
        if more:
            flags |= P.DbDescFlags.M
        dd = P.DbDesc(
            mtu=iface.config.mtu,
            options=P.Options.V6 | P.Options.E | P.Options.R,
            flags=flags,
            dd_seq_no=nbr.dd_seq_no,
            lsa_headers=chunk,
        )
        nbr.last_sent_dd = dd
        self._send(iface, nbr.src, dd)
        if nbr.master:
            self._arm_rxmt(iface, nbr)

    def _rx_db_desc(self, iface: V3Interface, src, pkt) -> None:
        dd = pkt.body
        nbr = iface.neighbors.get(pkt.router_id)
        if nbr is None or nbr.state < NsmState.EX_START:
            return
        # RFC 2328 §10.6 (per RFC 5340 §4.2.2 unchanged): reject a DD
        # whose Interface MTU exceeds ours, unless mtu-ignore is set.
        if dd.mtu > iface.config.mtu and not iface.config.mtu_ignore:
            return
        F = P.DbDescFlags
        if nbr.state == NsmState.EX_START:
            negotiated = False
            if (
                dd.flags == F.I | F.M | F.MS
                and not dd.lsa_headers
                and int(pkt.router_id) > int(self.router_id)
            ):
                nbr.master = False
                nbr.dd_seq_no = dd.dd_seq_no
                negotiated = True
            elif (
                not (dd.flags & F.I)
                and not (dd.flags & F.MS)
                and dd.dd_seq_no == nbr.dd_seq_no
                and int(pkt.router_id) < int(self.router_id)
            ):
                nbr.master = True
                negotiated = True
            if not negotiated:
                return
            self._nbr_event(iface.name, pkt.router_id, NsmEvent.NEGOTIATION_DONE)
            nbr = iface.neighbors.get(pkt.router_id)
            if nbr is None or nbr.state != NsmState.EXCHANGE:
                return
            nbr.last_dd = (dd.flags, dd.options, dd.dd_seq_no)
            self._process_dd_headers(iface, nbr, dd)
            if nbr.master:
                # Master always sends its first data DD — the slave can
                # only conclude the exchange from a master DD with M clear.
                nbr.dd_seq_no += 1
                self._send_dd(iface, nbr)
            else:
                self._slave_reply(iface, nbr, dd)
            return
        if nbr.state != NsmState.EXCHANGE:
            if (
                nbr.state in (NsmState.LOADING, NsmState.FULL)
                and not nbr.master
                and nbr.last_dd == (dd.flags, dd.options, dd.dd_seq_no)
            ):
                if nbr.last_sent_dd is not None:
                    self._send(iface, nbr.src, nbr.last_sent_dd)
                return
            if nbr.state in (NsmState.LOADING, NsmState.FULL):
                self._nbr_event(iface.name, pkt.router_id, NsmEvent.SEQ_NUMBER_MISMATCH)
            return
        if nbr.last_dd == (dd.flags, dd.options, dd.dd_seq_no):
            if not nbr.master and nbr.last_sent_dd is not None:
                self._send(iface, nbr.src, nbr.last_sent_dd)
            return
        if bool(dd.flags & F.MS) == nbr.master or dd.flags & F.I:
            self._nbr_event(iface.name, pkt.router_id, NsmEvent.SEQ_NUMBER_MISMATCH)
            return
        if nbr.master:
            if dd.dd_seq_no != nbr.dd_seq_no:
                self._nbr_event(iface.name, pkt.router_id, NsmEvent.SEQ_NUMBER_MISMATCH)
                return
            nbr.last_dd = (dd.flags, dd.options, dd.dd_seq_no)
            self._process_dd_headers(iface, nbr, dd)
            nbr.dd_summary = nbr.dd_summary[len(nbr.dd_summary[:DD_CHUNK]) :]
            nbr.dd_seq_no += 1
            if not nbr.dd_summary and not (dd.flags & F.M):
                self._nbr_event(iface.name, pkt.router_id, NsmEvent.EXCHANGE_DONE)
            else:
                self._send_dd(iface, nbr)
        else:
            nbr.last_dd = (dd.flags, dd.options, dd.dd_seq_no)
            self._process_dd_headers(iface, nbr, dd)
            self._slave_reply(iface, nbr, dd)

    def _slave_reply(self, iface: V3Interface, nbr: Neighbor, dd) -> None:
        nbr.dd_seq_no = dd.dd_seq_no
        chunk = nbr.dd_summary[:DD_CHUNK]
        nbr.dd_summary = nbr.dd_summary[len(chunk) :]
        flags = P.DbDescFlags(0)
        if nbr.dd_summary:
            flags |= P.DbDescFlags.M
        reply = P.DbDesc(
            mtu=iface.config.mtu,
            options=P.Options.V6 | P.Options.E | P.Options.R,
            flags=flags,
            dd_seq_no=nbr.dd_seq_no,
            lsa_headers=chunk,
        )
        nbr.last_sent_dd = reply
        self._send(iface, nbr.src, reply)
        if not (dd.flags & P.DbDescFlags.M) and not (flags & P.DbDescFlags.M):
            self._nbr_event(iface.name, nbr.router_id, NsmEvent.EXCHANGE_DONE)

    def _process_dd_headers(self, iface: V3Interface, nbr: Neighbor, dd) -> None:
        lsdb = self._area_of(iface).lsdb
        for hdr in dd.lsa_headers:
            cur = lsdb.get(hdr.key)
            if cur is None or hdr.compare(cur.lsa) > 0:
                nbr.ls_request[hdr.key] = hdr

    # -- request / update / ack / flooding

    def _send_ls_request(self, iface: V3Interface, nbr: Neighbor) -> None:
        keys = list(nbr.ls_request.keys())[:DD_CHUNK]
        if keys:
            self._send(iface, nbr.src, P.LsRequest(keys))
            self._arm_rxmt(iface, nbr)

    @staticmethod
    def _tx_copy(lsa, delay: int):
        """§13.3 InfTransDelay age increment (shared helper; RFC 5340
        keeps the header layout and §13.3 unchanged)."""
        from holo_tpu.protocols.ospf.packet import lsa_tx_copy

        return lsa_tx_copy(lsa, delay, P.MAX_AGE)

    def _rx_ls_request(self, iface: V3Interface, src, pkt) -> None:
        nbr = iface.neighbors.get(pkt.router_id)
        if nbr is None or nbr.state < NsmState.EXCHANGE:
            return
        lsas = []
        lsdb = self._area_of(iface).lsdb
        for key in pkt.body.entries:
            e = lsdb.get(key)
            if e is None:
                self._nbr_event(iface.name, pkt.router_id, NsmEvent.BAD_LS_REQ)
                return
            lsas.append(self._tx_copy(e.lsa, iface.config.transmit_delay))
        if lsas:
            self._send(iface, nbr.src, P.LsUpdate(lsas))

    def _any_nbr_exchanging(self) -> bool:
        return any(
            n.state in (NsmState.EXCHANGE, NsmState.LOADING)
            for i in self.interfaces.values()
            for n in i.neighbors.values()
        )

    def _rx_ls_update(self, iface: V3Interface, src, pkt) -> None:
        nbr = iface.neighbors.get(pkt.router_id)
        if nbr is None or nbr.state < NsmState.EXCHANGE:
            return
        acks = []
        now = self.loop.clock.now()
        area = self._area_of(iface)
        exchanging = self._any_nbr_exchanging()
        for lsa in pkt.body.lsas:
            cur = self._scope_db(area, lsa.type, iface).get(lsa.key)
            # §13 (4): a MaxAge LSA with no database copy (and no
            # exchange in progress) is acked directly, never installed —
            # otherwise flushes ping-pong around multi-access links.
            if lsa.is_maxage and cur is None and not exchanging:
                acks.append(lsa)
                continue
            if cur is None or lsa.compare(cur.lsa) > 0:
                if cur is not None and now - cur.rcvd_time < MIN_LS_ARRIVAL:
                    continue
                if lsa.adv_rtr == self.router_id and not lsa.is_maxage:
                    self._refresh_self_lsa(
                        area, lsa, from_iface=iface, from_nbr=nbr
                    )
                    continue
                self._install_and_flood(
                    area, lsa, from_iface=iface, from_nbr=nbr
                )
                acks.append(lsa)
            elif cur is not None and lsa.compare(cur.lsa) == 0:
                if lsa.key in nbr.ls_rxmt:
                    nbr.ls_rxmt.pop(lsa.key, None)
                else:
                    self._send(iface, nbr.src, P.LsAck([lsa]))
            else:
                self._send(
                    iface,
                    nbr.src,
                    P.LsUpdate(
                        [self._tx_copy(cur.lsa, iface.config.transmit_delay)]
                    ),
                )
            if lsa.key in nbr.ls_request:
                req = nbr.ls_request[lsa.key]
                if lsa.compare(req) >= 0:
                    del nbr.ls_request[lsa.key]
        if acks:
            self._send(iface, ALL_SPF_RTRS_V6, P.LsAck(acks))
        if nbr.state == NsmState.LOADING and not nbr.ls_request:
            self._nbr_event(iface.name, pkt.router_id, NsmEvent.LOADING_DONE)
        elif nbr.state == NsmState.LOADING:
            self._send_ls_request(iface, nbr)

    def _rx_ls_ack(self, iface: V3Interface, src, pkt) -> None:
        nbr = iface.neighbors.get(pkt.router_id)
        if nbr is None or nbr.state < NsmState.EXCHANGE:
            return
        drained = False
        for hdr in pkt.body.lsa_headers:
            cur = nbr.ls_rxmt.get(hdr.key)
            if cur is not None and hdr.compare(cur) == 0:
                del nbr.ls_rxmt[hdr.key]
                drained = cur.is_maxage or drained
        if drained:
            self._sweep_maxage()

    def _scope_db(self, area: V3Area, ltype, iface=None):
        """The database that owns LSAs of this type: the circuit's
        link-scope LSDB for Link LSAs, the area LSDB otherwise."""
        if P.scope_of(int(ltype)) == "link" and iface is not None:
            return iface.link_lsdb
        return area.lsdb

    def _install_and_flood(
        self, area: V3Area, lsa, from_iface=None, from_nbr=None
    ) -> None:
        now = self.loop.clock.now()
        if P.scope_of(int(lsa.type)) == "as":
            if area.no_external:
                return  # stub/NSSA areas refuse AS-scope LSAs outright
            # AS scope: one logical instance, installed + flooded through
            # every non-stub area (stub/NSSA areas refuse externals).
            for other in self.areas.values():
                if other.no_external:
                    continue
                if other is not area:
                    other.lsdb.install(lsa, now)
        if P.scope_of(int(lsa.type)) == "link":
            # Link scope lives in the circuit's own LSDB (§4.4.2) —
            # never the area database.
            if from_iface is None:
                return
            old = from_iface.link_lsdb.get(lsa.key)
            _, changed = from_iface.link_lsdb.install(lsa, now)
        else:
            old = area.lsdb.get(lsa.key)
            _, changed = area.lsdb.install(lsa, now)
        if changed:
            # Old body rides along: partial classification merges the
            # prefixes of both versions of an Intra-Area-Prefix LSA so
            # withdrawn prefixes drop their routes (ospfv3/spf.rs:120-131).
            self._schedule_spf(
                trigger=(lsa, old.lsa if old is not None else None)
            )
        as_scope = P.scope_of(int(lsa.type)) == "as"
        for iface in self.interfaces.values():
            if not iface.up:
                continue
            iface_area = self._area_of(iface)
            if as_scope:
                if iface_area.no_external:
                    continue
            elif iface_area is not area:
                continue
            # Link-scope LSAs only flood on their own link.
            if P.scope_of(int(lsa.type)) == "link" and iface is not from_iface:
                continue
            sent = False
            for nbr in iface.neighbors.values():
                if nbr.state < NsmState.EXCHANGE:
                    continue
                if nbr.exchange_or_loading():
                    req = nbr.ls_request.get(lsa.key)
                    if req is not None:
                        c = lsa.compare(req)
                        if c < 0:
                            continue
                        del nbr.ls_request[lsa.key]
                        if c == 0:
                            continue
                if from_nbr is not None and nbr is from_nbr:
                    continue
                nbr.ls_rxmt[lsa.key] = lsa
                sent = True
                self._arm_rxmt(iface, nbr)
            if sent:
                self._send(
                    iface,
                    ALL_SPF_RTRS_V6,
                    P.LsUpdate(
                        [self._tx_copy(lsa, iface.config.transmit_delay)]
                    ),
                )
        if lsa.is_maxage:
            # The MaxAge copy STAYS installed until every retransmission
            # list drains and no neighbor is in Exchange/Loading — the
            # RFC 2328 §14 removal condition (same as v2; the reference's
            # ospfv3 conformance expects the MaxAge copy visible in the
            # LSDB, packet-lsupd-self-orig2).
            self._sweep_maxage()

    def _sweep_maxage(self) -> None:
        """§14: drop MaxAge LSAs no rxmt list holds, unless an exchange
        is in progress (the DD summaries may still reference them)."""
        if self._any_nbr_exchanging():
            return
        held: set = set()
        for iface in self.interfaces.values():
            for nbr in iface.neighbors.values():
                held |= set(nbr.ls_rxmt)
        dbs = [a.lsdb for a in self.areas.values()] + [
            i.link_lsdb for i in self.interfaces.values()
        ]
        for db in dbs:
            for key in [
                k
                for k, e in db.entries.items()
                if e.lsa.is_maxage and k not in held
            ]:
                db.remove(key)

    def _arm_rxmt(self, iface: V3Interface, nbr: Neighbor) -> None:
        t = self._timer(
            ("rxmt", iface.name, nbr.router_id),
            lambda: RxmtTimerV3(iface.name, nbr.router_id),
        )
        if not t.armed:
            t.start(iface.config.rxmt_interval)

    def _rxmt(self, ifname: str, nbr_id) -> None:
        iface = self.interfaces.get(ifname)
        if iface is None:
            return
        nbr = iface.neighbors.get(nbr_id)
        if nbr is None:
            return
        if nbr.state == NsmState.EX_START or (
            nbr.state == NsmState.EXCHANGE and nbr.master
        ):
            if nbr.last_sent_dd is not None:
                self._send(iface, nbr.src, nbr.last_sent_dd)
        if nbr.state == NsmState.LOADING and nbr.ls_request:
            self._send_ls_request(iface, nbr)
        if nbr.ls_rxmt:
            self._send(
                iface,
                nbr.src,
                P.LsUpdate(
                    [
                        self._tx_copy(l, iface.config.transmit_delay)
                        for l in list(nbr.ls_rxmt.values())[:20]
                    ]
                ),
            )
        if (
            nbr.state in (NsmState.EX_START, NsmState.EXCHANGE, NsmState.LOADING)
            or nbr.ls_rxmt
        ):
            self._arm_rxmt(iface, nbr)

    # -- origination

    def _originate(
        self, area: V3Area, ltype: P.LsaType, lsid: IPv4Address, body,
        iface: "V3Interface | None" = None,
    ) -> None:
        key = P.LsaKey(ltype, lsid, self.router_id)
        scope_db = (
            iface.link_lsdb
            if iface is not None and P.scope_of(int(ltype)) == "link"
            else area.lsdb
        )
        old = scope_db.get(key)
        lsa = P.Lsa(
            age=0,
            type=ltype,
            lsid=lsid,
            adv_rtr=self.router_id,
            seq_no=next_seq_no(old.lsa if old else None),
            body=body,
        )
        lsa.encode()
        if (
            old is not None
            and not old.lsa.is_maxage
            and old.lsa.raw[20:] == lsa.raw[20:]
        ):
            # Unchanged content: no re-origination — but a MaxAge copy
            # (mid-flush, retained until rxmt lists drain) never
            # suppresses; wanting the LSA again needs a fresh instance.
            return
        self._install_and_flood(area, lsa, from_iface=iface)

    def _refresh_self_lsa(
        self, area: V3Area, received, from_iface=None, from_nbr=None
    ) -> None:
        """§13.4 received self-originated LSA: the newer received copy is
        first flooded on as usual (reference §13 step 5.b runs before the
        self-orig check — one LS Update per adjacency with the received
        instance), then either outpaced with a fresh re-origination or
        flushed with MaxAge (a second LS Update), exactly the two-update
        sequence the reference's ospfv3 conformance cases record
        (tests/conformance/ospfv3/packet-lsupd-self-orig{1,2})."""
        cur = self._scope_db(area, received.type, from_iface).get(
            received.key
        )
        self._install_and_flood(
            area, received, from_iface=from_iface, from_nbr=from_nbr
        )
        if cur is None or received.seq_no >= P.MAX_SEQ_NO:
            # No live incarnation of ours, or the sequence space is
            # exhausted (§12.1.6): flush the received copy — the refresh
            # machinery re-originates from INITIAL_SEQ_NO once the
            # MaxAge instance drains.
            self._flush_self(area, received.key)
            return
        lsa = P.Lsa(
            age=0,
            type=cur.lsa.type,
            lsid=cur.lsa.lsid,
            adv_rtr=cur.lsa.adv_rtr,
            seq_no=received.seq_no + 1,
            body=cur.lsa.body,
        )
        lsa.encode()
        self._install_and_flood(area, lsa)

    def _transit_active(self, iface: V3Interface) -> bool:
        """A LAN contributes a transit link once a DR exists and we are
        synchronized with it (or are it)."""
        if not iface.is_lan or int(iface.dr) == 0:
            return False
        if iface.dr == self.router_id:
            return any(
                n.state == NsmState.FULL for n in iface.neighbors.values()
            )
        dr = iface.neighbors.get(iface.dr)
        return dr is not None and dr.state == NsmState.FULL

    def _dr_iface_id(self, iface: V3Interface) -> int:
        if iface.dr == self.router_id:
            return iface.iface_id
        dr = iface.neighbors.get(iface.dr)
        return dr.iface_id if dr is not None else 0

    def _originate_router_lsa(self) -> None:
        for area in self.areas.values():
            self._originate_router_lsa_area(area)

    def set_stub_router(self, enabled: bool) -> None:
        """RFC 6987 stub-router (max-metric) maintenance mode: flip the
        leaf and re-originate every area's router-LSA."""
        if enabled == self.stub_router:
            return
        self.stub_router = enabled
        self._originate_router_lsa()

    def _originate_router_lsa_area(self, area: V3Area) -> None:
        links = []
        flags = P.RouterFlags(0)
        if self.is_abr:
            flags |= P.RouterFlags.B
        if self.redistributed and not area.no_external:
            flags |= P.RouterFlags.E
        # RFC 6987 stub-router: every router-LSA link (all v3 router
        # links are transit — prefixes live in intra-area-prefix LSAs,
        # which keep their real metric) advertises MaxLinkMetric.
        from holo_tpu.protocols.ospf.packet import MAX_LINK_METRIC

        def transit_cost(cost: int) -> int:
            return MAX_LINK_METRIC if self.stub_router else cost

        for iface in self._area_ifaces(area):
            if not iface.up:
                continue
            if iface.is_lan:
                if self._transit_active(iface):
                    # RFC 5340 §4.4.3.2: transit link names the DR's
                    # (interface id, router id) — the network vertex.
                    links.append(
                        P.RouterLinkV3(
                            P.RouterLinkType.TRANSIT_NETWORK,
                            transit_cost(iface.config.cost),
                            iface.iface_id,
                            self._dr_iface_id(iface),
                            iface.dr,
                        )
                    )
                continue
            for nbr in iface.neighbors.values():
                if nbr.state == NsmState.FULL:
                    links.append(
                        P.RouterLinkV3(
                            P.RouterLinkType.POINT_TO_POINT,
                            transit_cost(iface.config.cost),
                            iface.iface_id,
                            nbr.iface_id,
                            nbr.router_id,
                        )
                    )
        self._originate(
            area,
            P.LsaType.ROUTER,
            IPv4Address(0),
            P.LsaRouterV3(flags=flags, links=links),
        )

    def _originate_network_lsa(self, iface: V3Interface) -> None:
        """DR duty: the network LSA (lsid = DR's interface id) lists all
        fully-adjacent members plus the DR itself (RFC 5340 §4.4.3.3)."""
        area = self._area_of(iface)
        lsid = IPv4Address(iface.iface_id)
        key = P.LsaKey(P.LsaType.NETWORK, lsid, self.router_id)
        if (
            iface.up
            and iface.dr == self.router_id
            and any(n.state == NsmState.FULL for n in iface.neighbors.values())
        ):
            attached = [self.router_id] + sorted(
                (n.router_id for n in iface.neighbors.values()
                 if n.state == NsmState.FULL),
                key=int,
            )
            self._originate(
                area, P.LsaType.NETWORK, lsid, P.LsaNetworkV3(attached=attached)
            )
        else:
            self._flush_self(area, key)

    @staticmethod
    def _maxage_copy(lsa):
        """A copy of ``lsa`` with the header age pinned at MaxAge."""
        import copy

        flush = copy.copy(lsa)
        flush.age = P.MAX_AGE
        if flush.raw:
            raw = bytearray(flush.raw)
            raw[0:2] = P.MAX_AGE.to_bytes(2, "big")
            flush.raw = bytes(raw)
        return flush

    def _flush_self(self, area: V3Area, key) -> None:
        e = area.lsdb.get(key)
        if e is None or e.lsa.is_maxage:
            return
        self._install_and_flood(area, self._maxage_copy(e.lsa))

    def _originate_intra_area_prefix(self) -> None:
        for area in self.areas.values():
            self._originate_intra_area_prefix_area(area)
            self._originate_router_information(area)
        self._originate_link_lsas()

    def _originate_link_lsas(self) -> None:
        """RFC 5340 §4.4.3.8: one Link LSA per up circuit — our
        priority, options, link-local address, and the link's global
        prefixes; link-state id = interface id."""
        for iface in self.interfaces.values():
            if not iface.up:
                continue
            area = self._area_of(iface)
            self._originate(
                area,
                P.LsaType.LINK,
                IPv4Address(iface.iface_id),
                P.LsaLink(
                    priority=iface.config.priority,
                    link_local=iface.link_local,
                    prefixes=list(iface.prefixes),
                ),
                iface=iface,
            )

    def _originate_router_information(self, area: V3Area) -> None:
        """RFC 7770 Router-Information LSA, one per area (the v3 analog
        of v2's RI opaque; the reference originates GR-helper +
        stub-router capabilities at area start — both real here:
        ``set_stub_router`` implements the RFC 6987 max-metric mode)."""
        from holo_tpu.protocols.ospf.packet import (
            RI_CAP_GR_HELPER,
            RI_CAP_STUB_ROUTER,
            encode_router_info,
        )

        caps = RI_CAP_STUB_ROUTER | RI_CAP_GR_HELPER
        self._originate(
            area,
            P.LsaType.ROUTER_INFORMATION,
            IPv4Address(0),
            P.LsaRawBody(data=encode_router_info(caps)),
        )

    def _originate_intra_area_prefix_area(self, area: V3Area) -> None:
        # Router-referenced LSA: p2p prefixes plus LAN prefixes whose LAN
        # has no active network LSA yet (stub behavior, RFC 5340 §4.4.3.9).
        # Host prefixes carry the LA bit (§A.4.1.1 — local addresses).
        prefixes = []
        for iface in self._area_ifaces(area):
            if iface.up and not self._transit_active(iface):
                for p in iface.prefixes:
                    prefixes.append((
                        p,
                        iface.config.cost,
                        P.PREFIX_OPT_LA if p.prefixlen == 128 else 0,
                    ))
        body = P.LsaIntraAreaPrefix(
            ref_type=int(P.LsaType.ROUTER),
            ref_lsid=IPv4Address(0),
            ref_adv_rtr=self.router_id,
            prefixes=prefixes,
        )
        self._originate(area, P.LsaType.INTRA_AREA_PREFIX, IPv4Address(1), body)
        # Network-referenced LSAs: the DR advertises each transit LAN's
        # prefixes against the network vertex (metric 0 — the path cost
        # to the network vertex already includes the link cost).
        for iface in self._area_ifaces(area):
            lsid = IPv4Address(0x100 + iface.iface_id)
            if (
                iface.up
                and iface.is_lan
                and iface.dr == self.router_id
                and self._transit_active(iface)
            ):
                self._originate(
                    area,
                    P.LsaType.INTRA_AREA_PREFIX,
                    lsid,
                    P.LsaIntraAreaPrefix(
                        ref_type=int(P.LsaType.NETWORK),
                        ref_lsid=IPv4Address(iface.iface_id),
                        ref_adv_rtr=self.router_id,
                        prefixes=[(p, 0) for p in iface.prefixes],
                    ),
                )
            else:
                self._flush_self(
                    area,
                    P.LsaKey(P.LsaType.INTRA_AREA_PREFIX, lsid, self.router_id),
                )

    # -- aging

    def _age_tick(self) -> None:
        now = self.loop.clock.now()
        for area in self.areas.values():
            # Link-scope databases age/refresh alongside the area's.
            ifaces = [
                i for i in self.interfaces.values()
                if self._area_of(i) is area
            ]
            dbs = [(area.lsdb, None)] + [(i.link_lsdb, i) for i in ifaces]
            for db, iface in dbs:
                for e in db.refresh_due(now, self.router_id):
                    lsa = P.Lsa(
                        age=0,
                        type=e.lsa.type,
                        lsid=e.lsa.lsid,
                        adv_rtr=e.lsa.adv_rtr,
                        seq_no=next_seq_no(e.lsa),
                        body=e.lsa.body,
                    )
                    lsa.encode()
                    self._install_and_flood(area, lsa, from_iface=iface)
                for key in db.maxage_keys(now):
                    e = db.get(key)
                    if e is not None and not e.lsa.is_maxage:
                        # Natural expiry: pin the header age at MaxAge so
                        # the flood (and the §14 sweep) see the flush.
                        self._install_and_flood(
                            area, self._maxage_copy(e.lsa),
                            from_iface=iface,
                        )
        # One §14 sweep per tick drops every drained MaxAge entry.
        self._sweep_maxage()
        self._age_timer.start(AGE_TICK)

    # -- SPF

    def _schedule_spf(self, trigger=None) -> None:
        """``trigger`` is a ``(new_lsa, old_lsa | None)`` pair for LSDB
        installs; trigger-less calls (interface/config events) force the
        next run Full (reference spf.rs:511-516)."""
        if trigger is None:
            self._spf_force_full = True
        else:
            self._spf_triggers.append(trigger)
        # Causal origin stamp (shared contract; see the v2 instance).
        convergence.pend_schedule(
            self._conv_pending,
            convergence.TRIGGER_LSA
            if trigger is not None
            else convergence.TRIGGER_IFCONFIG,
            instance=self.name,
        )
        if not self._spf_pending:
            self._spf_pending = True
            self._spf_timer.start(0.1)

    @staticmethod
    def _expand_atoms(words, atoms) -> frozenset:
        """Atom bits -> next-hop tuples; NexthopAtom vlink atoms expand
        to their borrowed transit-area set (§16.1), same typed design
        as the v2 marshaling (spf_run.NexthopAtom.expand)."""
        from holo_tpu.protocols.ospf.spf_run import NexthopAtom

        out = set()
        for a in atom_bits(words, len(atoms)):
            atom = atoms[a]
            if isinstance(atom, NexthopAtom):
                if atom.expand:
                    out |= atom.expand
            else:
                out.add(atom)
        return frozenset(out)

    def _vlink_nexthops(self, backbone: V3Area, area_results: dict) -> dict:
        """{vlink peer rid: frozenset[(ifname, ll)]} from each transit
        area's path to the peer (mirrors the v2 instance §16.1 logic;
        our backbone router-LSA names the vlink peers)."""
        from holo_tpu.ops.graph import INF

        now = self.loop.clock.now()
        peers = set()
        for e in backbone.lsdb.all():
            lsa = e.lsa
            if (
                lsa.type == P.LsaType.ROUTER
                and lsa.adv_rtr == self.router_id
                and e.current_age(now) < P.MAX_AGE
            ):
                for link in lsa.body.links:
                    if link.link_type == P.RouterLinkType.VIRTUAL_LINK:
                        peers.add(link.nbr_router_id)
        best: dict = {}
        via: dict = {}  # rid -> (transit aid, dist) for state rendering
        for rid in peers:
            for aid, (index, _k, res, atoms, _pl) in area_results.items():
                if aid == IPv4Address(0):
                    continue
                v = index.get(("R", rid))
                if v is None or res.dist[v] >= INF:
                    continue
                nhs = self._expand_atoms(res.nexthop_words[v], atoms)
                if not nhs:
                    continue
                dist = int(res.dist[v])
                cur = best.get(rid)
                if cur is None or dist < cur[0]:
                    best[rid] = (dist, nhs)
                    via[rid] = (aid, dist)
                elif dist == cur[0]:
                    # Parallel virtual links through different transit
                    # areas at equal cost: ECMP union (topo3-3 shape).
                    best[rid] = (dist, cur[1] | nhs)
        # Operational state for the vlink endpoints (ietf-ospf
        # virtual-links): peer, transit area, cost, and the peer's
        # endpoint address — the LA host prefix it advertises in the
        # transit area (RFC 5340 §4.4.3.9).
        self.vlink_state = []
        if self.vlink_config:
            rows = []
            for aid, rid in self.vlink_config:
                pair = area_results.get(aid)
                dist = None
                if pair is not None:
                    index, _k, res, atoms, _pl = pair
                    v = index.get(("R", rid))
                    if v is not None and res.dist[v] < INF:
                        dist = int(res.dist[v])
                if dist is not None:
                    rows.append((rid, aid, dist))
        else:
            rows = [
                (rid, aid, dist)
                for rid, (aid, dist) in sorted(
                    via.items(), key=lambda kv: int(kv[0])
                )
            ]
        for rid, aid, dist in rows:
            addr = None
            transit = self.areas.get(aid)
            if transit is not None:
                for e in transit.lsdb.all():
                    lsa = e.lsa
                    if (
                        lsa.type == P.LsaType.INTRA_AREA_PREFIX
                        and lsa.adv_rtr == rid
                    ):
                        for entry in lsa.body.prefixes:
                            if (
                                entry[0].prefixlen == 128
                                and lsa.body.entry_opts(entry)
                                & P.PREFIX_OPT_LA
                            ):
                                addr = entry[0].network_address
            self.vlink_state.append(
                {
                    "transit_area_id": aid,
                    "router_id": rid,
                    "cost": dist,
                    "address": addr,
                }
            )
        return {rid: nhs for rid, (_d, nhs) in best.items()}

    def iface_update(
        self,
        ifname: str,
        hello: int | None = None,
        dead: int | None = None,
        priority: int | None = None,
        passive: bool | None = None,
        mtu: int | None = None,
        mtu_ignore: bool | None = None,
        transmit_delay: int | None = None,
    ) -> None:
        """Live interface reconfiguration beyond cost (the v2
        iface_update analog): hello/dead apply from the next hello (the
        hello timer re-arms with the config value), priority is
        advertised from the next hello, and a passive flip tears
        down / revives the circuit's packet exchange while its prefixes
        stay advertised."""
        iface = self.interfaces.get(ifname)
        if iface is None:
            return
        cfg = iface.config
        if hello is not None:
            cfg.hello_interval = hello
        if dead is not None:
            cfg.dead_interval = dead
        if priority is not None:
            cfg.priority = priority
        if mtu is not None:
            # Live input to the §10.6 DD Interface-MTU check.
            cfg.mtu = mtu
        if mtu_ignore is not None:
            cfg.mtu_ignore = mtu_ignore
        if transmit_delay is not None:
            cfg.transmit_delay = transmit_delay
        if passive is not None and cfg.passive != passive:
            cfg.passive = passive
            if passive:
                for nbr_id in list(iface.neighbors):
                    self._nbr_event(ifname, nbr_id, NsmEvent.KILL_NBR)
                iface.dr = IPv4Address(0)
                iface.bdr = IPv4Address(0)
                for key in (("hello", ifname), ("wait", ifname)):
                    t = self._timers.get(key)
                    if t:
                        t.cancel()
                self._originate_router_lsa()
            elif iface.up:
                if iface.is_lan:
                    # §9.4 Waiting again before claiming DR.
                    iface.up_since = self.loop.clock.now()
                    iface.wait_until = (
                        self.loop.clock.now() + cfg.dead_interval
                    )
                    self._timer(
                        ("wait", ifname), lambda: WaitTimerV3(ifname)
                    ).start(cfg.dead_interval)
                self._send_hello(ifname)

    def iface_cost_update(self, ifname: str, cost: int) -> None:
        """Live cost reconfiguration (reference InterfaceCostUpdate):
        re-originate the router-LSA with the new metric."""
        iface = self.interfaces.get(ifname)
        if iface is None or iface.config.cost == cost:
            return
        iface.config.cost = cost
        self._originate_router_lsa()
        # The interface cost is ALSO the stub-prefix metric in the
        # intra-area-prefix LSA — without re-originating it, neighbors
        # keep routing to our prefixes at the stale cost.
        self._originate_intra_area_prefix()

    def _classify_spf(self, triggers: list) -> dict | None:
        """Full-vs-partial classification (reference ospfv3/spf.rs:97-163).
        Returns None when a full SPF is required.

        Router/Network-LSAs are topological; Link-LSAs and Router
        Information changes also force Full (next-hop resolution and SR
        state depend on them — the reference makes the same
        simplification).  Intra-Area-Prefix changes merge prefixes from
        BOTH the old and new versions so withdrawn prefixes drop."""
        intra: set = set()
        inter_network: set = set()
        inter_router: set = set()
        external: set = set()
        for new, old in triggers:
            t = new.type
            if t in (
                P.LsaType.ROUTER,
                P.LsaType.NETWORK,
                P.LsaType.LINK,
                P.LsaType.ROUTER_INFORMATION,
            ):
                return None
            if t == P.LsaType.INTRA_AREA_PREFIX:
                for lsa in (new, old):
                    if lsa is not None:
                        for entry in lsa.body.prefixes:
                            intra.add(entry[0])
            elif t == P.LsaType.INTER_AREA_PREFIX:
                for lsa in (new, old):
                    if lsa is not None:
                        inter_network.add(lsa.body.prefix)
            elif t == P.LsaType.INTER_AREA_ROUTER:
                inter_router.add(new.body.dest_router_id)
            elif t == P.LsaType.AS_EXTERNAL:
                for lsa in (new, old):
                    if lsa is not None:
                        external.add(lsa.body.prefix)
            else:
                return None  # unknown type: be safe, run full
        return {
            "intra": intra,
            "inter_network": inter_network,
            "inter_router": inter_router,
            "external": external,
        }

    def run_spf(self) -> None:
        with convergence.spf_run(self._conv_pending, self.name):
            with telemetry.span("ospfv3.spf", instance=self.name):
                self._run_spf_traced()

    def _run_spf_traced(self) -> None:
        triggers = self._spf_triggers
        self._spf_triggers = []
        force_full = self._spf_force_full
        self._spf_force_full = False
        partial = None if force_full else self._classify_spf(triggers)
        if partial is not None and self._spf_cache is not None:
            _OSPF_SPF_RUNS.labels(instance=self.name, type="partial").inc()
            self._run_spf_partial(partial)
            return
        _OSPF_SPF_RUNS.labels(instance=self.name, type="full").inc()
        self.spf_run_count += 1
        start_time = self.loop.clock.now()
        area_results = {}
        # Backbone last: its SPF borrows transit-area next hops for
        # virtual links (§16.1), like the v2 instance.
        ordered = sorted(
            self.areas.values(), key=lambda a: int(a.area_id) == 0
        )
        for area in ordered:
            vlink_nexthops = None
            if int(area.area_id) == 0:
                vlink_nexthops = self._vlink_nexthops(
                    area, area_results
                )
            out = self._area_spf(area, vlink_nexthops)
            if out is not None:
                area_results[area.area_id] = out

        routes: dict[IPv6Network, V6Route] = {}
        intra_by_area: dict[IPv4Address, dict] = {}
        # 1. intra-area routes (preferred over inter/external).
        for aid, (index, keys, res, atoms, prefix_lsas) in area_results.items():
            intra = {}
            for adv, body in prefix_lsas:
                if body.ref_type == int(P.LsaType.ROUTER):
                    v = index.get(("R", body.ref_adv_rtr))
                elif body.ref_type == int(P.LsaType.NETWORK):
                    v = index.get(("N", body.ref_adv_rtr, int(body.ref_lsid)))
                else:
                    continue
                if v is None or res.dist[v] >= INF:
                    continue
                nhs = self._expand_atoms(res.nexthop_words[v], atoms)
                for entry in body.prefixes:
                    prefix, metric = entry[0], entry[1]
                    opts = body.entry_opts(entry)
                    total = int(res.dist[v]) + metric
                    cur = intra.get(prefix)
                    if cur is None or total < cur.dist:
                        intra[prefix] = V6Route(
                            prefix, total, nhs, prefix_options=opts,
                            area_id=aid, vertex=v,
                        )
                    elif total == cur.dist:
                        intra[prefix] = V6Route(
                            prefix, total, cur.nexthops | nhs,
                            prefix_options=cur.prefix_options,
                            area_id=aid, vertex=cur.vertex,
                        )
            intra_by_area[aid] = intra
            for prefix, route in intra.items():
                cur = routes.get(prefix)
                if cur is None or route.dist < cur.dist:
                    routes[prefix] = route
                elif route.dist == cur.dist:
                    # Cross-area ECMP union keeps the first contributing
                    # area's (area_id, vertex) — the FRR consumption key
                    # must stay a consistent pair.
                    routes[prefix] = V6Route(
                        prefix, route.dist, cur.nexthops | route.nexthops,
                        route_type=cur.route_type,
                        prefix_options=cur.prefix_options,
                        area_id=cur.area_id, vertex=cur.vertex,
                    )

        # 2. inter-area routes from received Inter-Area-Prefix LSAs:
        #    distance = dist(advertising ABR in that area) + metric.
        #    The candidate table covers EVERY advertised prefix (intra
        #    preference applies only at install time) so a later partial
        #    run can fall back to it when an intra path withdraws.
        inter_routes: dict[IPv6Network, V6Route] = {}
        self._derive_inter_area(area_results, inter_routes)
        for prefix, route in inter_routes.items():
            if prefix not in routes:
                routes[prefix] = route

        # 3. AS-external routes (lowest preference): RFC 5340 type 0x4005.
        #    E2 ranks on the external metric, E1 on asbr-dist + metric.
        routes.update(self._derive_external(area_results, routes))

        # 4. ABR duties: inter-area-prefix origination (each area's intra
        #    prefixes into every other area; default into stub areas).
        if self.is_abr:
            self._originate_inter_area(
                intra_by_area, inter_routes, area_results
            )

        self.spf_log.append(
            {
                "run": self.spf_run_count,
                "type": "full",
                "start-time": start_time,
                "end-time": self.loop.clock.now(),
                "route-count": len(routes),
            }
        )
        del self.spf_log[:-32]
        # Cache the run's products for prefix-scoped partial updates
        # (reference route.rs:200-333 update_rib_partial).
        self._spf_cache = {
            "area_results": area_results,
            "intra_by_area": intra_by_area,
            "routes": routes,
            "inter_routes": inter_routes,
        }
        self._clamp_max_paths(routes, area_results)
        self._attach_frr_backups(routes, area_results)
        self.routes = routes
        if self.route_cb is not None:
            self.route_cb(routes)

    def _clamp_max_paths(self, routes: dict, area_results: dict | None = None) -> None:
        """ietf-ospf max-paths (ISSUE 10): truncate every route's ECMP
        set deterministically to the configured width.  With the
        multipath dispatch armed (max_paths > 1 → the kernel computed
        UCMP planes) the rank is weight-DESCENDING — the highest-mass
        paths survive — tie-broken by lowest link-local address (the
        reference's clamp key); without weights the address key alone
        decides."""
        m = self.max_paths
        if not m or m < 1:
            return
        from dataclasses import replace as _replace

        def weights_for(r) -> dict:
            """{(ifname, ll) -> UCMP weight} from the winning area's
            multipath planes (empty when unavailable)."""
            ar = (area_results or {}).get(r.area_id)
            if ar is None or r.vertex < 0:
                return {}
            res, atoms = ar[2], ar[3]
            nhw = getattr(res, "nh_weights", None)
            if nhw is None or r.vertex >= len(res.dist):
                return {}
            from holo_tpu.protocols.ospf.spf_run import (
                NexthopAtom,
                atom_bits,
            )

            out: dict = {}
            row = nhw[r.vertex]
            for a in atom_bits(res.nexthop_words[r.vertex], len(atoms)):
                atom = atoms[a]
                w = int(row[a]) if a < len(row) else 0
                targets = (
                    atom.expand or ()
                    if isinstance(atom, NexthopAtom)
                    else (atom,)
                )
                for nh in targets:
                    out[nh] = out.get(nh, 0) + w
            return out

        for prefix, r in list(routes.items()):
            if len(r.nexthops) <= m:
                continue
            w = weights_for(r)
            ranked = sorted(
                r.nexthops,
                key=lambda h: (
                    -w.get(h, 1),
                    h[1] is None,
                    h[1].packed if h[1] is not None else b"",
                    h[0] or "",
                ),
            )
            routes[prefix] = _replace(r, nexthops=frozenset(ranked[:m]))

    def _attach_frr_backups(self, routes: dict, area_results: dict) -> None:
        """Join the per-area backup tables onto the v6 route table.

        Direct LFAs only: OSPFv3 here has no SRv6/SRH machinery to
        encapsulate a remote-LFA or TI-LFA repair, so tunnel repairs
        stay in ``frr_tables`` (operational visibility) without a
        forwarding entry — RFC 7490 §2's encapsulation requirement."""
        cfg = self.frr
        if cfg is None or not cfg.active() or not self.frr_tables:
            return
        from holo_tpu.frr.manager import repair_map
        from holo_tpu.protocols.ospf.spf_run import NexthopAtom

        # Prefixes sharing a terminating vertex share the repair map.
        memo: dict[tuple, dict] = {}
        for route in routes.values():
            v = getattr(route, "vertex", -1)
            out = area_results.get(route.area_id)
            table = self.frr_tables.get(route.area_id)
            if v < 0 or out is None or table is None:
                continue
            _index, _keys, res, atoms, _pl = out
            repairs = memo.get((route.area_id, v))
            if repairs is None:
                repairs = memo[(route.area_id, v)] = repair_map(
                    table, cfg, res.nexthop_words[v], v
                )
            backups = {}
            for a, entry in repairs.items():
                if entry.kind != "lfa":
                    continue
                atom, batom = atoms[a], atoms[entry.atom]
                if isinstance(atom, NexthopAtom) or isinstance(
                    batom, NexthopAtom
                ):
                    continue  # vlink bundles: no single protected link
                backups[atom] = (batom, ())
            if backups:
                route.backups = backups

    def _derive_inter_area(
        self, area_results: dict, inter_routes: dict, only: set | None = None
    ) -> None:
        """Accumulate inter-area candidates into ``inter_routes`` from
        received Inter-Area-Prefix LSAs (RFC 2328 §16.2 hierarchy rules).
        Shared by the full run and the prefix-scoped partial run
        (``only`` restricts to the changed prefixes)."""
        for aid, (index, _k, res, atoms, _pl) in area_results.items():
            area = self.areas.get(aid)
            if area is None:
                continue
            if self.is_abr and aid != IPv4Address(0):
                # §16.2 hierarchy: an ABR examines summaries from the
                # backbone only (non-ABRs use their single attached area).
                continue
            for e in area.lsdb.all():
                lsa = e.lsa
                if (
                    lsa.type != P.LsaType.INTER_AREA_PREFIX
                    or lsa.adv_rtr == self.router_id
                    or lsa.is_maxage
                ):
                    continue
                prefix = lsa.body.prefix
                if only is not None and prefix not in only:
                    continue  # partial run: out-of-scope prefix
                abr_v = index.get(("R", lsa.adv_rtr))
                if abr_v is None or res.dist[abr_v] >= INF:
                    continue
                dist = int(res.dist[abr_v]) + lsa.body.metric
                nhs = self._expand_atoms(res.nexthop_words[abr_v], atoms)
                cur = inter_routes.get(prefix)
                if cur is None or dist < cur.dist:
                    inter_routes[prefix] = V6Route(
                        prefix, dist, nhs, route_type="inter-area",
                        prefix_options=lsa.body.prefix_options,
                        area_id=aid, vertex=abr_v,
                    )
                elif dist == cur.dist:
                    inter_routes[prefix] = V6Route(
                        prefix, dist, cur.nexthops | nhs,
                        route_type="inter-area",
                        prefix_options=cur.prefix_options,
                        area_id=cur.area_id, vertex=cur.vertex,
                    )

    def _derive_external(
        self, area_results: dict, routes: dict, only: set | None = None
    ) -> dict:
        """AS-external route derivation (E1/E2 ranking, ASBR resolution
        through Inter-Area-Router LSAs).  Returns winners for prefixes
        with no internal path; shared by the full and partial runs."""
        ext_best: dict = {}
        seen_ext = set()
        for aid, (index, _k, res, atoms, _pl) in area_results.items():
            area = self.areas.get(aid)
            if area is None or area.no_external:
                continue
            for e in area.lsdb.all():
                lsa = e.lsa
                if lsa.type != P.LsaType.AS_EXTERNAL or lsa.is_maxage:
                    continue
                if lsa.adv_rtr == self.router_id:
                    continue
                prefix = lsa.body.prefix
                if only is not None and prefix not in only:
                    continue  # partial run: out-of-scope prefix
                if (lsa.key, aid) in seen_ext:
                    continue
                seen_ext.add((lsa.key, aid))
                asbr_v = index.get(("R", lsa.adv_rtr))
                if asbr_v is not None and res.dist[asbr_v] < INF:
                    asbr_dist = int(res.dist[asbr_v])
                    nhs = self._expand_atoms(
                        res.nexthop_words[asbr_v], atoms
                    )
                else:
                    # ASBR outside this area: resolve through an ABR's
                    # Inter-Area-Router LSA (RFC 5340 type 0x2004 — the
                    # v3 analog of the v2 type-4 summary).
                    resolved = self._asbr_via_inter_router(
                        area, index, res, atoms, lsa.adv_rtr
                    )
                    if resolved is None:
                        continue
                    asbr_dist, nhs = resolved
                if prefix in routes:
                    continue  # intra/inter win
                if lsa.body.e_bit:
                    rank = (1, lsa.body.metric, asbr_dist)
                    dist = lsa.body.metric
                else:
                    rank = (0, asbr_dist + lsa.body.metric, 0)
                    dist = asbr_dist + lsa.body.metric
                cur = ext_best.get(prefix)
                if cur is None or rank < cur[0]:
                    ext_best[prefix] = (
                        rank,
                        V6Route(prefix, dist, nhs, route_type="external"),
                    )
                elif rank == cur[0]:
                    ext_best[prefix] = (
                        rank,
                        V6Route(prefix, dist, cur[1].nexthops | nhs,
                                route_type="external"),
                    )
        return {p: r for p, (_rank, r) in ext_best.items()}

    def _run_spf_partial(self, partial: dict) -> None:
        """Prefix-scoped route recomputation over the cached per-area
        SPTs — no Dijkstra runs (reference route.rs:200-333).  Prefix
        LSAs are re-read from the live LSDB; reachability and next hops
        come from the cached SPT results."""
        self.spf_run_count += 1
        start_time = now = self.loop.clock.now()
        cache = self._spf_cache
        area_results = cache["area_results"]
        intra_by_area = cache["intra_by_area"]
        routes = dict(cache["routes"])
        inter_routes = dict(cache["inter_routes"])
        intra_set = set(partial["intra"])
        inter_network = set(partial["inter_network"])
        inter_router = set(partial["inter_router"])
        external = set(partial["external"])
        origination_dirty = False

        if intra_set:
            # Drop affected intra routes, then re-derive them for exactly
            # those prefixes (route.rs:214-237).
            for prefix in intra_set:
                r = routes.get(prefix)
                if r is not None and r.route_type == "intra-area":
                    del routes[prefix]
            for intra in intra_by_area.values():
                for prefix in intra_set:
                    intra.pop(prefix, None)
            for aid, (index, _k, res, atoms, _pl) in area_results.items():
                area = self.areas.get(aid)
                if area is None:
                    continue
                intra = intra_by_area.setdefault(aid, {})
                for e in area.lsdb.all():
                    lsa = e.lsa
                    if (
                        lsa.type != P.LsaType.INTRA_AREA_PREFIX
                        # current_age, not the stored header: a wall-clock
                        # expired LSA must not resurrect a route the full
                        # run (_area_spf) would exclude.
                        or e.current_age(now) >= P.MAX_AGE
                    ):
                        continue
                    body = lsa.body
                    if body.ref_type == int(P.LsaType.ROUTER):
                        v = index.get(("R", body.ref_adv_rtr))
                    elif body.ref_type == int(P.LsaType.NETWORK):
                        v = index.get(
                            ("N", body.ref_adv_rtr, int(body.ref_lsid))
                        )
                    else:
                        continue
                    if v is None or res.dist[v] >= INF:
                        continue
                    nhs = self._expand_atoms(res.nexthop_words[v], atoms)
                    for entry in body.prefixes:
                        prefix, metric = entry[0], entry[1]
                        if prefix not in intra_set:
                            continue  # scoped
                        opts = body.entry_opts(entry)
                        total = int(res.dist[v]) + metric
                        cur = intra.get(prefix)
                        if cur is None or total < cur.dist:
                            intra[prefix] = V6Route(
                                prefix, total, nhs, prefix_options=opts,
                                area_id=aid, vertex=v,
                            )
                        elif total == cur.dist:
                            intra[prefix] = V6Route(
                                prefix, total, cur.nexthops | nhs,
                                prefix_options=cur.prefix_options,
                                area_id=aid, vertex=cur.vertex,
                            )
            # Merge the recomputed intra winners across areas (same
            # preference as the full run: lowest dist, ECMP union).
            for intra in intra_by_area.values():
                for prefix in intra_set:
                    route = intra.get(prefix)
                    if route is None:
                        continue
                    cur = routes.get(prefix)
                    if cur is not None and cur.route_type != "intra-area":
                        cur = None  # intra beats inter/external
                    if cur is None or route.dist < cur.dist:
                        routes[prefix] = route
                    elif route.dist == cur.dist:
                        # Same cross-area ECMP merge as the full run:
                        # keep the first area's FRR consumption key.
                        routes[prefix] = V6Route(
                            prefix, route.dist,
                            cur.nexthops | route.nexthops,
                            route_type=cur.route_type,
                            prefix_options=cur.prefix_options,
                            area_id=cur.area_id, vertex=cur.vertex,
                        )
            # Prefixes now without an intra path fall back to a cached
            # inter-area candidate, else to the external stage.
            for prefix in intra_set:
                if prefix not in routes and prefix in inter_routes:
                    routes[prefix] = inter_routes[prefix]
            external |= {p for p in intra_set if p not in routes}
            origination_dirty = True

        if inter_network:
            for prefix in inter_network:
                inter_routes.pop(prefix, None)
                r = routes.get(prefix)
                if r is not None and r.route_type == "inter-area":
                    del routes[prefix]
            self._derive_inter_area(
                area_results, inter_routes, only=inter_network
            )
            for prefix in inter_network:
                cand = inter_routes.get(prefix)
                if cand is None:
                    continue
                cur = routes.get(prefix)
                if cur is None or cur.route_type != "intra-area":
                    routes[prefix] = cand
            external |= {p for p in inter_network if p not in routes}
            origination_dirty = True

        if inter_router or external:
            # An Inter-Area-Router change alters ASBR reachability, which
            # can affect ANY external route (route.rs:302-306).
            reevaluate_all = bool(inter_router)
            for prefix in list(routes):
                if routes[prefix].route_type == "external" and (
                    reevaluate_all or prefix in external
                ):
                    del routes[prefix]
            routes.update(
                self._derive_external(
                    area_results,
                    routes,
                    only=None if reevaluate_all else external,
                )
            )

        if origination_dirty and self.is_abr:
            self._originate_inter_area(
                intra_by_area, inter_routes, area_results
            )

        log_type = (
            "intra" if intra_set
            else "inter" if inter_network
            else "external"
        )
        self.spf_log.append(
            {
                "run": self.spf_run_count,
                "type": log_type,
                "start-time": start_time,
                "end-time": self.loop.clock.now(),
                "route-count": len(routes),
            }
        )
        del self.spf_log[:-32]
        cache["routes"] = routes
        cache["inter_routes"] = inter_routes
        # Rebuilt routes need their repairs re-joined like the full run,
        # or a partial run would publish them backup-less and flap the
        # kernel entries off/on their precomputed repairs.
        self._clamp_max_paths(routes, area_results)
        self._attach_frr_backups(routes, area_results)
        self.routes = routes
        if self.route_cb is not None:
            self.route_cb(routes)

    def _originate_inter_area(
        self, intra_by_area: dict, inter_routes: dict, area_results: dict
    ) -> None:
        backbone = IPv4Address(0)
        wanted: dict[IPv4Address, dict] = {aid: {} for aid in self.areas}

        def _nexthops_in_area(route, dst_aid) -> bool:
            # area.rs:628-630 split horizon: skip a route whose next
            # hops already exit through the destination area.
            for ifname, _addr in route.nexthops:
                iface = self.interfaces.get(ifname)
                if iface is not None and iface.config.area_id == dst_aid:
                    return True
            return False

        # The reference walks the final RIB (area.rs:602-643): intra
        # routes summarize everywhere, inter routes into non-backbone
        # areas only; a route never returns to its own area.
        candidates: dict = {}
        for src_aid, intra in intra_by_area.items():
            for prefix, route in intra.items():
                cur = candidates.get(prefix)
                if cur is None or route.dist < cur.dist:
                    candidates[prefix] = route
        for prefix, route in inter_routes.items():
            if prefix not in candidates:  # intra always wins
                candidates[prefix] = route
        for prefix, route in candidates.items():
            for dst_aid in self.areas:
                if route.area_id == dst_aid:
                    continue
                if (
                    route.route_type != "intra-area"
                    and dst_aid == backbone
                ):
                    continue  # only intra advertises into the backbone
                if not self.areas[dst_aid].summary:
                    continue  # totally stubby: default only
                if _nexthops_in_area(route, dst_aid):
                    continue
                cur = wanted[dst_aid].get(prefix)
                if cur is None or route.dist < cur[0]:
                    wanted[dst_aid][prefix] = (
                        route.dist, route.prefix_options
                    )
        default = IPv6Network("::/0")
        for aid, area in self.areas.items():
            if area.stub:
                wanted[aid][default] = (area.stub_default_cost, 0)
        # ASBR reachability into other areas (Inter-Area-Router LSAs).
        asbr_wanted: dict[IPv4Address, dict] = {aid: {} for aid in self.areas}
        for src_aid, (index, keys, res, atoms, _pl) in area_results.items():
            src_area = self.areas.get(src_aid)
            if src_area is None:
                continue
            for e in src_area.lsdb.all():
                if e.lsa.type != P.LsaType.ROUTER or e.lsa.is_maxage:
                    continue
                if P.RouterFlags.E not in e.lsa.body.flags:
                    continue
                if e.lsa.adv_rtr == self.router_id:
                    continue
                v = index.get(("R", e.lsa.adv_rtr))
                if v is None or res.dist[v] >= INF:
                    continue
                for dst_aid in self.areas:
                    if dst_aid == src_aid or self.areas[dst_aid].no_external:
                        continue
                    cur = asbr_wanted[dst_aid].get(e.lsa.adv_rtr)
                    if cur is None or int(res.dist[v]) < cur:
                        asbr_wanted[dst_aid][e.lsa.adv_rtr] = int(res.dist[v])
        for aid, asbrs in asbr_wanted.items():
            area = self.areas[aid]
            wanted_lsids = set()
            for rid, dist in asbrs.items():
                lsid = self._inter_lsid(aid, ("asbr", rid))
                wanted_lsids.add(lsid)
                self._originate(
                    area,
                    P.LsaType.INTER_AREA_ROUTER,
                    lsid,
                    P.LsaInterAreaRouter(metric=dist, dest_router_id=rid),
                )
            for key in list(area.lsdb.entries):
                if (
                    key.type == P.LsaType.INTER_AREA_ROUTER
                    and key.adv_rtr == self.router_id
                    and key.lsid not in wanted_lsids
                ):
                    e = area.lsdb.entries.get(key)
                    if e is not None and not e.lsa.is_maxage:
                        self._flush_self(area, key)
        for aid, prefixes in wanted.items():
            area = self.areas[aid]
            wanted_lsids = set()
            for prefix, (dist, popts) in prefixes.items():
                lsid = self._inter_lsid(aid, prefix)
                wanted_lsids.add(lsid)
                self._originate(
                    area,
                    P.LsaType.INTER_AREA_PREFIX,
                    lsid,
                    P.LsaInterAreaPrefix(
                        metric=dist, prefix=prefix, prefix_options=popts
                    ),
                )
            for key in list(area.lsdb.entries):
                if (
                    key.type == P.LsaType.INTER_AREA_PREFIX
                    and key.adv_rtr == self.router_id
                    and key.lsid not in wanted_lsids
                ):
                    # .get: a flush above may have swept drained MaxAge
                    # entries out of the snapshot already (§14 sweep).
                    e = area.lsdb.entries.get(key)
                    if e is not None and not e.lsa.is_maxage:
                        self._flush_self(area, key)

    def _asbr_via_inter_router(self, area, index, res, atoms, asbr_rid):
        """(dist, nexthops) toward an out-of-area ASBR via the best ABR's
        Inter-Area-Router LSA in this area, or None."""
        best = None
        for e in area.lsdb.all():
            lsa = e.lsa
            if (
                lsa.type != P.LsaType.INTER_AREA_ROUTER
                or lsa.is_maxage
                or lsa.adv_rtr == self.router_id
                or lsa.body.dest_router_id != asbr_rid
            ):
                continue
            abr_v = index.get(("R", lsa.adv_rtr))
            if abr_v is None or res.dist[abr_v] >= INF:
                continue
            dist = int(res.dist[abr_v]) + lsa.body.metric
            nhs = self._expand_atoms(res.nexthop_words[abr_v], atoms)
            if best is None or dist < best[0]:
                best = (dist, nhs)
            elif dist == best[0]:
                best = (dist, best[1] | nhs)
        return best

    def _inter_lsid(self, area_id, prefix) -> IPv4Address:
        """v3 link-state ids are opaque; allocate one per (area,
        summarized prefix) — the reference numbers them per area, and a
        prefix summarized into two areas gets independent ids."""
        ids = self._inter_ids
        key = (area_id, prefix)
        lsid = ids.get(key)
        if lsid is None:
            # Gap-safe: next id after the highest in this area (seeded
            # sets may be sparse after completed flushes).
            top = max(
                (int(l) for (a, _p), l in ids.items() if a == area_id),
                default=0x0FFF,
            )
            lsid = IPv4Address(top + 1)
            ids[key] = lsid
        return lsid

    def redistribute(self, prefix: IPv6Network, metric: int = 20) -> None:
        """ASBR: inject a v6 external as an AS-external LSA (AS scope)."""
        was_asbr = bool(self.redistributed)
        self.redistributed[prefix] = metric
        lsid = self._inter_lsid(None, prefix)  # AS scope: one id space
        for area in self.areas.values():
            if area.no_external:
                continue
            self._originate(
                area,
                P.LsaType.AS_EXTERNAL,
                lsid,
                P.LsaAsExternalV3(metric=metric, e_bit=True, prefix=prefix),
            )
            break  # AS scope: one origination floods everywhere eligible
        if not was_asbr:
            self._originate_router_lsa()

    def _area_spf(self, area: V3Area, vlink_nexthops: dict | None = None):
        """Per-area SPF: returns (index, keys, result, atoms, prefix_lsas)
        or None when we have no router LSA in the area."""
        now = self.loop.clock.now()
        routers: dict[IPv4Address, P.LsaRouterV3] = {}
        networks: dict[tuple, P.LsaNetworkV3] = {}  # (adv, iface id)
        prefix_lsas: list[tuple] = []  # (adv_rtr, body)
        for e in area.lsdb.all():
            if e.current_age(now) >= P.MAX_AGE:
                continue
            if e.lsa.type == P.LsaType.ROUTER:
                routers[e.lsa.adv_rtr] = e.lsa.body
            elif e.lsa.type == P.LsaType.NETWORK:
                networks[(e.lsa.adv_rtr, int(e.lsa.lsid))] = e.lsa.body
            elif e.lsa.type == P.LsaType.INTRA_AREA_PREFIX:
                prefix_lsas.append((e.lsa.adv_rtr, e.lsa.body))
        if self.router_id not in routers:
            return None
        # Vertex ordering contract: network vertices sort before routers
        # so zero-cost network->router edges settle first (shared engine
        # semantics — see the v2/IS-IS marshaling).
        keys = [("N",) + k for k in sorted(networks, key=lambda k: (int(k[0]), k[1]))]
        keys += [("R", rid) for rid in sorted(routers, key=int)]
        index = {k: i for i, k in enumerate(keys)}
        n = len(keys)
        is_router = np.array([k[0] == "R" for k in keys], bool)
        src, dst, cost = [], [], []
        edge_kind = []  # per edge: router-link type int, or -1 (network)
        edge_nbr_ifid = []  # p2p/vlink: the neighbor's iface id
        for rid, body in routers.items():
            u = index[("R", rid)]
            for link in body.links:
                if link.link_type == P.RouterLinkType.TRANSIT_NETWORK:
                    v = index.get(
                        ("N", link.nbr_router_id, link.nbr_iface_id)
                    )
                else:
                    v = index.get(("R", link.nbr_router_id))
                if v is not None:
                    src.append(u)
                    dst.append(v)
                    cost.append(link.metric)
                    edge_kind.append(int(link.link_type))
                    edge_nbr_ifid.append(link.nbr_iface_id)
        for (adv, ifid), body in networks.items():
            u = index[("N", adv, ifid)]
            for member in body.attached:
                v = index.get(("R", member))
                if v is not None:
                    src.append(u)
                    dst.append(v)
                    cost.append(0)
                    edge_kind.append(-1)
                    edge_nbr_ifid.append(0)
        from holo_tpu.ops.graph import mutual_keep_mask

        src_a = np.array(src, np.int32).reshape(-1)
        dst_a = np.array(dst, np.int32).reshape(-1)
        keep = mutual_keep_mask(src_a, dst_a)
        edge_kind = [k for k, kp in zip(edge_kind, keep) if kp]
        edge_nbr_ifid = [
            i for i, kp in zip(edge_nbr_ifid, keep) if kp
        ]
        topo = Topology(
            n_vertices=n,
            is_router=is_router,
            edge_src=src_a[keep],
            edge_dst=dst_a[keep],
            edge_cost=np.array(cost, np.int32).reshape(-1)[keep],
            root=index[("R", self.router_id)],
        )

        atoms = []
        atom_ids = np.full(topo.n_edges, -1, np.int32)
        # Per-link hop resolution: parallel p2p links to the same
        # neighbor are distinct atoms, matched by the neighbor's
        # interface id carried in its hellos (and in our router-LSA's
        # link entries) so each link's atom rides the right interface.
        nbr_hop = {}  # rid -> (ifname, src) — any one link (fallback)
        nbr_hop_by_ifid = {}  # (rid, nbr iface id) -> (ifname, src)
        lan_iface_of = {}  # network vertex key -> our iface on that LAN
        for iface in self._area_ifaces(area):
            for nbr in iface.neighbors.values():
                if nbr.state == NsmState.FULL and not iface.is_lan:
                    nbr_hop[nbr.router_id] = (iface.name, nbr.src)
                    nbr_hop_by_ifid[(nbr.router_id, nbr.iface_id)] = (
                        iface.name,
                        nbr.src,
                    )
            if iface.is_lan and self._transit_active(iface):
                lan_iface_of[
                    ("N", iface.dr, self._dr_iface_id(iface))
                ] = iface
        root_lans: set[int] = set()
        for e_i in range(topo.n_edges):
            if topo.edge_src[e_i] == topo.root:
                k = keys[int(topo.edge_dst[e_i])]
                if k[0] == "R":
                    hop = None
                    if edge_kind[e_i] == int(
                        P.RouterLinkType.VIRTUAL_LINK
                    ):
                        # Virtual link: borrowed transit-area set only —
                        # a direct-adjacency fallback here would pair
                        # the vlink metric with the wrong next hop.
                        borrowed = (vlink_nexthops or {}).get(k[1])
                        if borrowed:
                            from holo_tpu.protocols.ospf.spf_run import (
                                NexthopAtom,
                            )

                            hop = NexthopAtom(None, None, borrowed)
                    else:
                        hop = nbr_hop_by_ifid.get(
                            (k[1], edge_nbr_ifid[e_i])
                        ) or nbr_hop.get(k[1])
                    if hop is not None:
                        atom_ids[e_i] = len(atoms)
                        atoms.append(hop)
                elif k in lan_iface_of:
                    # Directly-attached LAN: the network vertex's route
                    # (the LAN prefix) is reached on the interface itself
                    # — same (ifname, no-address) atom the v2 marshaling
                    # assigns (spf_run.py root_edge_data).
                    root_lans.add(int(topo.edge_dst[e_i]))
                    atom_ids[e_i] = len(atoms)
                    atoms.append((lan_iface_of[k].name, None))
        # Network -> member edges on root-attached LANs: the direct next
        # hop is the member's link-local on that LAN (hops==0 rule).
        for e_i in range(topo.n_edges):
            u = int(topo.edge_src[e_i])
            if u in root_lans:
                iface = lan_iface_of[keys[u]]
                member = keys[int(topo.edge_dst[e_i])][1]
                if member == self.router_id:
                    continue
                nbr = iface.neighbors.get(member)
                if nbr is not None:
                    atom_ids[e_i] = len(atoms)
                    atoms.append((iface.name, nbr.src))
        topo.edge_direct_atom = atom_ids
        iface_srlg = {
            i.name: srlg_bits(i.config.srlg)
            for i in self._area_ifaces(area)
            if i.config.srlg
        }
        if iface_srlg:
            # v3 atoms are NexthopAtom (vlinks) or (ifname, addr)
            # tuples — normalize to per-atom interface names.
            apply_interface_srlg(
                topo,
                [
                    a.ifname if hasattr(a, "ifname") else a[0]
                    for a in atoms
                ],
                iface_srlg,
            )
        if self.spf_partition_of:
            # Hierarchical partition hint (ISSUE 15): router groups
            # from config; a network vertex rides the lowest-labeled
            # attached router (v2 contract — zero-cost net->rtr edges
            # stay intra-partition wherever the grouping allows).
            from holo_tpu.protocols.ospf.spf_run import (
                apply_partition_hint,
            )

            part_of = self.spf_partition_of
            groups: list = []
            for k in keys:
                if k[0] == "R":
                    groups.append(part_of.get(k[1]))
                else:
                    att = [
                        part_of[m]
                        for m in networks[(k[1], k[2])].attached
                        if m in part_of
                    ]
                    groups.append(min(att) if att else None)
            apply_partition_hint(topo, groups)
        topo.touch()

        # DeltaPath seam (same contract as the v2 instance): identical
        # vertex ordering + atom table → diff against the previous
        # run's topology so the device-resident graph updates in place.
        prev = self._spf_delta_bases.get(area.area_id)
        if prev is not None and prev[0] == keys and prev[1] == atoms:
            from holo_tpu.ops.graph import diff_topologies

            delta = diff_topologies(prev[2], topo)
            if delta is not None:
                topo.link_delta(delta)
        self._spf_delta_bases[area.area_id] = (keys, atoms, topo)

        mp_k = (
            self.max_paths
            if self.max_paths is not None and self.max_paths > 1
            else 1
        )
        res = self.backend.compute(topo, multipath_k=mp_k)
        # IP-FRR: the area's backup-table batch rides the same SPF
        # moment (all-roots matrix + per-link post-convergence planes).
        cfg = self.frr
        if cfg is not None and cfg.active():
            from holo_tpu.frr.manager import ensure_engine

            self._frr_engine = ensure_engine(self._frr_engine, cfg)
            self.frr_tables[area.area_id] = self._frr_engine.compute(topo)
        else:
            self.frr_tables.pop(area.area_id, None)
        return index, keys, res, atoms, prefix_lsas

    # -- rx/tx

    def _rx(self, msg: NetRxPacket) -> None:
        iface = self.interfaces.get(msg.ifname)
        if iface is None or not iface.up or iface.config.passive:
            # Passive circuits neither send NOR process OSPF packets.
            return
        try:
            pkt = P.Packet.decode(
                msg.data, src=msg.src, dst=msg.dst, auth=iface.config.auth
            )
        except Exception:
            _OSPF_RX_BAD.labels(instance=self.name).inc()
            return
        _OSPF_PACKETS.labels(instance=self.name, dir="rx").inc()
        if pkt.router_id == self.router_id:
            return
        if iface.config.auth is not None:
            # RFC 7166 §4.1 replay protection: per-neighbor monotonic
            # sequence numbers.
            last = iface.at_seqnos.get(pkt.router_id, -1)
            if pkt.auth_seqno <= last:
                return
            iface.at_seqnos[pkt.router_id] = pkt.auth_seqno
        # RFC 5340 §4.1.2: area and instance-id must match the interface.
        if (
            pkt.area_id != iface.config.area_id
            or pkt.instance_id != iface.config.instance_id
        ):
            return
        t = pkt.body.TYPE
        if t == P.PacketType.HELLO:
            self._rx_hello(iface, msg.src, pkt)
        elif t == P.PacketType.DB_DESC:
            self._rx_db_desc(iface, msg.src, pkt)
        elif t == P.PacketType.LS_REQUEST:
            self._rx_ls_request(iface, msg.src, pkt)
        elif t == P.PacketType.LS_UPDATE:
            self._rx_ls_update(iface, msg.src, pkt)
        elif t == P.PacketType.LS_ACK:
            self._rx_ls_ack(iface, msg.src, pkt)

    def _send(self, iface: V3Interface, dst, body) -> None:
        pkt = P.Packet(router_id=self.router_id,
                       area_id=iface.config.area_id, body=body,
                       instance_id=iface.config.instance_id)
        auth = iface.config.auth
        if auth is not None:
            # One keychain consultation per packet: SA id and digest
            # must come from the same key (resolve_send; no active key
            # sends unauthenticated, like the v2/IS-IS paths).
            auth = auth.resolve_send()
        if auth is not None:
            self._at_seqno += 1
            if self._nvstore is not None and self._at_seqno >= self._at_reserved:
                self._reserve_at_seqnos()
            auth.seqno = self._at_seqno
        _OSPF_PACKETS.labels(instance=self.name, dir="tx").inc()
        self.netio.send(
            iface.name,
            iface.link_local,
            dst,
            pkt.encode(iface.link_local, dst, auth=auth),
        )

"""LSDB: link-state database with install/originate/flush and aging.

Reference: holo-ospf/src/lsdb.rs (install :397-489, originate :518, flush
:665).  LSAs are stored per scope (area / AS) keyed by (type, lsid, adv_rtr);
install performs the RFC 2328 §13.2 content-change check that drives SPF
scheduling, and origination handles sequence numbers, MinLSInterval batching
and refresh.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from ipaddress import IPv4Address

from holo_tpu.protocols.ospf.packet import (
    INITIAL_SEQ_NO,
    LS_REFRESH_TIME,
    MAX_AGE,
    MAX_SEQ_NO,
    Lsa,
    LsaKey,
)

MIN_LS_INTERVAL = 5.0  # §12.4: min seconds between originations of same LSA
MIN_LS_ARRIVAL = 1.0  # §13 (5)(a): min seconds between accepting copies


@dataclass
class LsaEntry:
    lsa: Lsa
    installed_at: float  # loop-clock time of install (for age computation)
    rcvd_time: float = 0.0
    # Origination bookkeeping for self-originated LSAs:
    last_originated: float | None = None

    def current_age(self, now: float) -> int:
        return min(int(self.lsa.age + (now - self.installed_at)), MAX_AGE)


@dataclass
class Lsdb:
    """One LSA scope (an area's LSDB, or the AS-scope external LSDB)."""

    entries: dict[LsaKey, LsaEntry] = field(default_factory=dict)
    # Pending (delayed) originations blocked by MinLSInterval.
    pending: dict[LsaKey, Lsa] = field(default_factory=dict)

    def get(self, key: LsaKey) -> LsaEntry | None:
        return self.entries.get(key)

    def all(self):
        return self.entries.values()

    def install(self, lsa: Lsa, now: float) -> tuple[LsaEntry, bool]:
        """Install (replacing any old copy).  Returns (entry, content_changed).

        content_changed implements the §13.2 comparison: options/body bytes
        differ, or MaxAge transition — the trigger condition for SPF
        (lsdb.rs:457-469).
        """
        old = self.entries.get(lsa.key)
        changed = True
        if old is not None:
            old_lsa = old.lsa
            changed = (
                old_lsa.options != lsa.options
                or old_lsa.is_maxage != lsa.is_maxage
                or old_lsa.raw[LsaBodyOffset:] != lsa.raw[LsaBodyOffset:]
            )
        entry = LsaEntry(lsa=lsa, installed_at=now, rcvd_time=now)
        if old is not None:
            entry.last_originated = old.last_originated
        self.entries[lsa.key] = entry
        return entry, changed

    def remove(self, key: LsaKey) -> None:
        self.entries.pop(key, None)

    def maxage_keys(self, now: float) -> list[LsaKey]:
        return [
            k for k, e in self.entries.items() if e.current_age(now) >= MAX_AGE
        ]

    def refresh_due(self, now: float, self_rid: IPv4Address) -> list[LsaEntry]:
        return [
            e
            for e in self.entries.values()
            if e.lsa.adv_rtr == self_rid
            and not e.lsa.is_maxage
            and e.current_age(now) >= LS_REFRESH_TIME
        ]


LsaBodyOffset = 20  # compare body beyond the 20-byte header (age/seq differ)


def next_seq_no(old: Lsa | None) -> int:
    if old is None:
        return INITIAL_SEQ_NO
    if old.seq_no >= MAX_SEQ_NO:
        # Sequence wrap requires premature aging first (§12.1.6); callers
        # flush then re-originate at INITIAL_SEQ_NO.
        return INITIAL_SEQ_NO
    return old.seq_no + 1

"""OSPFv2 packet and LSA codecs (RFC 2328 §A).

Zero-copy-ish cursor codecs in the style of the reference's packet layer
(holo-ospf/src/ospfv2/packet/), with strict length/checksum validation.
All multi-byte fields are network byte order via utils.bytesbuf.
"""

from __future__ import annotations

import enum
import hashlib
import hmac as _hmac
from dataclasses import dataclass, field
from ipaddress import IPv4Address

from holo_tpu.utils.bytesbuf import (
    DecodeError,
    Reader,
    Writer,
    fletcher16_checksum,
    fletcher16_verify,
    ip_checksum,
)

OSPF_VERSION = 2
PKT_HDR_LEN = 24
LSA_HDR_LEN = 20
MAX_AGE = 3600  # seconds (RFC 2328 §B)
LS_REFRESH_TIME = 1800
MAX_AGE_DIFF = 900
LS_INFINITY = 0xFFFFFF
# RFC 6987 §2: the largest 16-bit router-link metric — a stub router
# advertises it on transit links so neighbors avoid it for transit
# traffic while its own prefixes stay reachable.
MAX_LINK_METRIC = 0xFFFF
INITIAL_SEQ_NO = -0x7FFFFFFF  # 0x80000001 signed
MAX_SEQ_NO = 0x7FFFFFFF


def lsa_tx_copy(lsa, delay: int, max_age: int = MAX_AGE):
    """§13.3: LS age is incremented by the interface's InfTransDelay
    (transmit-delay leaf) when copied into an outgoing LS Update, capped
    at MaxAge.  The Fletcher checksum excludes the age field, so the raw
    bytes only need the age halfword patched.  RFC 5340 keeps both the
    header layout and §13.3 unchanged, so the v2 and v3 instances share
    this one helper."""
    if delay <= 0 or lsa.age >= max_age:
        return lsa
    import copy

    out = copy.copy(lsa)
    out.age = min(lsa.age + delay, max_age)
    if lsa.raw:
        raw = bytearray(lsa.raw)
        raw[0:2] = out.age.to_bytes(2, "big")
        out.raw = bytes(raw)
    return out


class PacketType(enum.IntEnum):
    HELLO = 1
    DB_DESC = 2
    LS_REQUEST = 3
    LS_UPDATE = 4
    LS_ACK = 5


class LsaType(enum.IntEnum):
    ROUTER = 1
    NETWORK = 2
    SUMMARY_NETWORK = 3
    SUMMARY_ROUTER = 4
    AS_EXTERNAL = 5
    NSSA_EXTERNAL = 7  # RFC 3101 type-7 (same body as type-5)
    OPAQUE_LINK = 9
    OPAQUE_AREA = 10
    OPAQUE_AS = 11


class Options(enum.IntFlag):
    E = 0x02  # external routing capability (not a stub area)
    MC = 0x04
    NP = 0x08  # NSSA
    L = 0x10  # LLS data block present (RFC 5613)
    DC = 0x20
    O = 0x40  # opaque capable


class RouterLinkType(enum.IntEnum):
    POINT_TO_POINT = 1
    TRANSIT_NETWORK = 2
    STUB_NETWORK = 3
    VIRTUAL_LINK = 4


class RouterFlags(enum.IntFlag):
    B = 0x01  # area border router
    E = 0x02  # AS boundary router
    V = 0x04  # virtual link endpoint


class AuthType(enum.IntEnum):
    NULL = 0
    SIMPLE = 1
    CRYPTOGRAPHIC = 2


# ===== LSA bodies =====


@dataclass(frozen=True)
class RouterLink:
    link_type: RouterLinkType
    id: IPv4Address  # neighbor router id / DR addr / network
    data: IPv4Address  # iface addr / mask for stub
    metric: int


@dataclass
class LsaRouter:
    flags: RouterFlags = RouterFlags(0)
    links: list[RouterLink] = field(default_factory=list)

    def encode(self, w: Writer) -> None:
        w.u8(int(self.flags)).u8(0).u16(len(self.links))
        for l in self.links:
            w.ipv4(l.id).ipv4(l.data).u8(int(l.link_type)).u8(0).u16(l.metric)

    @classmethod
    def decode(cls, r: Reader) -> "LsaRouter":
        flags = RouterFlags(r.u8() & 0x07)
        r.u8()
        n = r.u16()
        links = []
        for _ in range(n):
            lid, data = r.ipv4(), r.ipv4()
            ltype = r.u8()
            ntos = r.u8()
            metric = r.u16()
            for _ in range(ntos):  # skip per-TOS metrics
                r.u32()
            try:
                lt = RouterLinkType(ltype)
            except ValueError as e:
                raise DecodeError(f"bad router link type {ltype}") from e
            links.append(RouterLink(lt, lid, data, metric))
        return cls(RouterFlags(flags), links)


@dataclass
class LsaNetwork:
    mask: IPv4Address = IPv4Address(0)
    attached: list[IPv4Address] = field(default_factory=list)

    def encode(self, w: Writer) -> None:
        w.ipv4(self.mask)
        for a in self.attached:
            w.ipv4(a)

    @classmethod
    def decode(cls, r: Reader) -> "LsaNetwork":
        mask = r.ipv4()
        attached = []
        while r.remaining() >= 4:
            attached.append(r.ipv4())
        return cls(mask, attached)


@dataclass
class LsaSummary:
    """Type 3 (network) and 4 (ASBR) summary share the body format."""

    mask: IPv4Address = IPv4Address(0)
    metric: int = 0

    def encode(self, w: Writer) -> None:
        w.ipv4(self.mask).u32(self.metric & LS_INFINITY)

    @classmethod
    def decode(cls, r: Reader) -> "LsaSummary":
        mask = r.ipv4()
        metric = r.u32() & LS_INFINITY
        return cls(mask, metric)


@dataclass
class LsaAsExternal:
    mask: IPv4Address = IPv4Address(0)
    e_bit: bool = True  # type 2 external metric
    metric: int = 0
    fwd_addr: IPv4Address = IPv4Address(0)
    tag: int = 0

    def encode(self, w: Writer) -> None:
        w.ipv4(self.mask)
        w.u32(((0x80000000 if self.e_bit else 0) | (self.metric & LS_INFINITY)))
        w.ipv4(self.fwd_addr).u32(self.tag)

    @classmethod
    def decode(cls, r: Reader) -> "LsaAsExternal":
        mask = r.ipv4()
        word = r.u32()
        fwd = r.ipv4()
        tag = r.u32()
        # additional TOS routes ignored
        return cls(mask, bool(word & 0x80000000), word & LS_INFINITY, fwd, tag)


@dataclass
class LsaOpaque:
    data: bytes = b""

    def encode(self, w: Writer) -> None:
        w.bytes(self.data)

    @classmethod
    def decode(cls, r: Reader) -> "LsaOpaque":
        return cls(r.rest())


GRACE_OPAQUE_TYPE = 3  # RFC 3623 Grace-LSA (opaque type 9.3)
RI_OPAQUE_TYPE = 4  # RFC 7770 Router Information (opaque type 10.4)
EXT_PREFIX_OPAQUE_TYPE = 7  # RFC 7684 Extended Prefix (opaque type 10.7)

# RFC 7770 informational capability bits (bit 0 = MSB of the 32-bit field).
RI_CAP_GR_CAPABLE = 0x80000000
RI_CAP_GR_HELPER = 0x40000000
RI_CAP_STUB_ROUTER = 0x20000000


def ri_lsid() -> IPv4Address:
    return IPv4Address(RI_OPAQUE_TYPE << 24)


def encode_router_info(
    info_caps: int,
    hostname: str | None = None,
    node_tags: tuple[int, ...] = (),
) -> bytes:
    """RI LSA body: Informational Capabilities TLV (type 1, RFC 7770
    §2.2), Dynamic Hostname TLV (type 7, RFC 5642), and Node Admin Tag
    TLV (type 10, RFC 7777) when set."""
    w = Writer()
    w.u16(1).u16(4).u32(info_caps & 0xFFFFFFFF)
    if hostname:
        raw = hostname.encode()[:255]
        w.u16(7).u16(len(raw)).bytes(raw)
        w.zeros((4 - len(raw) % 4) % 4)
    if node_tags:
        w.u16(10).u16(4 * len(node_tags))
        for tag in node_tags:
            w.u32(tag)
    return w.finish()


def decode_router_info(data: bytes) -> dict:
    """Returns {'info_caps': int, 'hostname': str|None, 'node_tags': tuple}."""
    r = Reader(data)
    out = {
        "info_caps": 0, "hostname": None, "node_tags": (),
        "sr_algos": (), "srgb_ranges": (),
    }
    while r.remaining() >= 4:
        t = r.u16()
        length = r.u16()
        body = r.sub(min((length + 3) // 4 * 4, r.remaining()))
        if t == 1 and body.remaining() >= 4:
            out["info_caps"] = body.u32()
        elif t == 8:  # SR-Algorithm TLV (RFC 8665 §3.1)
            out["sr_algos"] = tuple(
                body.u8()
                for _ in range(min(length, body.remaining()))
            )
        elif t == 9 and body.remaining() >= 4:  # SID/Label Range (§3.2)
            size = body.u24()
            body.u8()
            first = None
            if body.remaining() >= 4:
                st = body.u16()
                sl = body.u16()
                if st == 1 and body.remaining() >= 3:
                    first = (
                        body.u24()
                        if sl == 3 or body.remaining() < 4
                        else body.u32()
                    )
            out["srgb_ranges"] = out["srgb_ranges"] + (
                (size, first),
            )
        elif t == 7 and body.remaining() >= length:
            try:
                out["hostname"] = body.bytes(length).decode()
            except UnicodeDecodeError:
                pass
        elif t == 10:
            tags = []
            while body.remaining() >= 4:
                tags.append(body.u32())
            out["node_tags"] = tuple(tags)
    return out


def ext_prefix_lsid(opaque_id: int) -> IPv4Address:
    return IPv4Address((EXT_PREFIX_OPAQUE_TYPE << 24) | (opaque_id & 0xFFFFFF))


# Extended-prefix attribute flags (RFC 7684/9085; reference iana.rs).
EXT_PREFIX_FLAG_A = 0x80  # attach
EXT_PREFIX_FLAG_N = 0x40  # node
EXT_PREFIX_FLAG_AC = 0x10  # anycast


def _encode_ext_prefix_tlv1(prefix, sub_tlvs: bytes, flags: int = 0) -> bytes:
    """Extended-Prefix TLV (1) framing shared by the SR/BIER/flag
    encoders (RFC 7684 §2.1)."""
    w = Writer()
    body = Writer()
    plen = prefix.prefixlen
    body.u8(1).u8(plen).u8(0).u8(flags)  # route-type IntraArea, af 0
    nbytes = (plen + 7) // 8
    body.bytes(prefix.network_address.packed[:nbytes])
    body.zeros((4 - nbytes % 4) % 4)
    body.bytes(sub_tlvs)
    w.u16(1).u16(len(body)).bytes(body.finish())
    return w.finish()


def encode_ext_prefix_flags(entries) -> bytes:
    """One Extended-Prefix TLV per (prefix, flags) pair — the N/AC
    attribute advertisement (reference ospfv2/lsdb.rs:760-800)."""
    out = b""
    for prefix, flags in entries:
        out += _encode_ext_prefix_tlv1(prefix, b"", flags=flags)
    return out


def _walk_ext_prefix_tlv1(data: bytes, with_meta: bool = False):
    """Yield (prefix, sub-TLV Reader) — or (prefix, route_type, flags,
    Reader) with ``with_meta`` — for each Extended-Prefix TLV; host bits
    below the prefix length are masked off (foreign advertisements may
    carry them)."""
    from ipaddress import IPv4Network

    r = Reader(data)
    while r.remaining() >= 4:
        t = r.u16()
        length = r.u16()
        body = r.sub(min((length + 3) // 4 * 4, r.remaining()))
        if t != 1 or body.remaining() < 4:
            continue
        route_type = body.u8()
        plen = body.u8()
        body.u8()  # AF
        flags = body.u8()
        if plen > 32:
            continue
        nbytes = (plen + 7) // 8
        if body.remaining() < nbytes:
            continue
        raw = body.bytes(nbytes) + bytes(4 - nbytes)
        pad = (4 - nbytes % 4) % 4
        if body.remaining() >= pad:
            body.bytes(pad)
        val = int.from_bytes(raw, "big")
        if plen < 32:
            val &= ~((1 << (32 - plen)) - 1)
        prefix = IPv4Network((val, plen))
        if with_meta:
            yield prefix, route_type, flags, body
        else:
            yield prefix, body


def decode_ext_prefix_entries(data: bytes) -> list:
    """All Extended-Prefix TLVs of an opaque LSA, fully parsed:
    [(prefix, route_type, flags, [{flags, mt, algo, sid}])] — the SID
    sub-TLV fields per RFC 8665 §5."""
    out = []
    for prefix, route_type, flags, body in _walk_ext_prefix_tlv1(
        data, with_meta=True
    ):
        sids = []
        while body.remaining() >= 4:
            st = body.u16()
            sl = body.u16()
            sbody = body.sub(min((sl + 3) // 4 * 4, body.remaining()))
            if st == 2 and sbody.remaining() >= 8:
                sid_flags = sbody.u8()
                sbody.u8()  # reserved
                mt = sbody.u8()
                algo = sbody.u8()
                sids.append(
                    {
                        "flags": sid_flags,
                        "mt": mt,
                        "algo": algo,
                        "sid": sbody.u32(),
                    }
                )
        out.append((prefix, route_type, flags, sids))
    return out


def decode_ext_link(data: bytes) -> list:
    """Extended-Link TLVs (RFC 7684 §3, opaque type 8) with their
    Adj-SID sub-TLVs (RFC 8665 §6.1):
    [(link_type, link_id, link_data, [{flags, mt, weight, sid}])]."""
    r = Reader(data)
    out = []
    while r.remaining() >= 4:
        t = r.u16()
        length = r.u16()
        body = r.sub(min((length + 3) // 4 * 4, r.remaining()))
        if t != 1 or body.remaining() < 12:  # Extended-Link TLV
            continue
        ltype = body.u8()
        body.u8()
        body.u16()
        link_id = body.ipv4()
        link_data = body.ipv4()
        sids = []
        while body.remaining() >= 4:
            st = body.u16()
            sl = body.u16()
            sbody = body.sub(min((sl + 3) // 4 * 4, body.remaining()))
            if st == 2 and sbody.remaining() >= 7:  # Adj-SID
                fl = sbody.u8()
                sbody.u8()  # reserved
                mt = sbody.u8()
                weight = sbody.u8()
                # sub-TLV length decides the SID width: 7 = 3-byte
                # label (L flag), 8 = 4-byte index (§6.1).
                sid = (
                    sbody.u24()
                    if sl == 7 or sbody.remaining() < 4
                    else sbody.u32()
                )
                sids.append(
                    {"flags": fl, "mt": mt, "weight": weight, "sid": sid}
                )
            elif st == 3 and sbody.remaining() >= 11:  # LAN Adj-SID
                fl = sbody.u8()
                sbody.u8()
                mt = sbody.u8()
                weight = sbody.u8()
                nbr = sbody.ipv4()
                sid = (
                    sbody.u24()
                    if sl == 11 or sbody.remaining() < 4
                    else sbody.u32()
                )
                sids.append(
                    {
                        "flags": fl, "mt": mt, "weight": weight,
                        "nbr": nbr, "sid": sid,
                    }
                )
        out.append((ltype, link_id, link_data, sids))
    return out


def encode_ext_prefix_sid(prefix, sid_index: int, flags: int = 0) -> bytes:
    """Extended-Prefix TLV (1) with a Prefix-SID sub-TLV (2) — the RFC
    7684/8665 shape, condensed to the fields the SPF/SR path consumes."""
    sub = Writer()
    # Prefix-SID sub-TLV: type 2, flags, reserved, MT, algo, SID index.
    inner = Writer()
    inner.u8(flags).u8(0).u8(0).u8(0).u32(sid_index)
    sub.u16(2).u16(len(inner)).bytes(inner.finish())
    return _encode_ext_prefix_tlv1(prefix, sub.finish())


def encode_ext_prefix_bier(
    prefix, sd_id: int, bfr_id: int, bsls, mt_id: int = 0
) -> bytes:
    """Extended-Prefix TLV (1) with a BIER sub-TLV (9, RFC 9089 §2.1)
    carrying our BFR-id in a sub-domain plus one BIER MPLS Encapsulation
    sub-sub-TLV (1) per advertised bitstring length."""
    sub = Writer()
    inner = Writer()
    inner.u8(sd_id).u8(mt_id).u16(bfr_id)
    inner.u8(0).u8(0).u16(0)  # BAR, IPA, reserved
    for bsl in bsls:
        # RFC 8296 BSL identifier: 1 = 64 bits, doubling per step.
        bsl_id = (bsl // 64).bit_length()
        inner.u16(1).u16(4).u8(0).u8(bsl_id << 4).u16(0)
    sub.u16(9).u16(len(inner)).bytes(inner.finish())
    return _encode_ext_prefix_tlv1(prefix, sub.finish())


def decode_ext_prefix_bier(data: bytes):
    """Returns (IPv4Network prefix, sd_id, mt_id, bfr_id, (bsl, ...))
    or None when no BIER sub-TLV is present."""
    for prefix, body in _walk_ext_prefix_tlv1(data):
        while body.remaining() >= 4:
            st = body.u16()
            sl = body.u16()
            sbody = body.sub(min((sl + 3) // 4 * 4, body.remaining()))
            if st != 9 or sbody.remaining() < 8:
                continue
            sd_id = sbody.u8()
            mt_id = sbody.u8()
            bfr_id = sbody.u16()
            sbody.u8()
            sbody.u8()
            sbody.u16()
            bsls = []
            while sbody.remaining() >= 4:
                sst = sbody.u16()
                ssl = sbody.u16()
                ssb = sbody.sub(min((ssl + 3) // 4 * 4, sbody.remaining()))
                if sst == 1 and ssb.remaining() >= 4:
                    ssb.u8()
                    bsl_id = ssb.u8() >> 4
                    if bsl_id >= 1:
                        bsls.append(64 << (bsl_id - 1))
            return prefix, sd_id, mt_id, bfr_id, tuple(bsls)
    return None


def decode_ext_prefix_sid(data: bytes):
    """Returns (IPv4Network prefix, sid_index, flags) or None."""
    for prefix, body in _walk_ext_prefix_tlv1(data):
        while body.remaining() >= 4:
            st = body.u16()
            sl = body.u16()
            sbody = body.sub(min((sl + 3) // 4 * 4, body.remaining()))
            if st == 2 and sbody.remaining() >= 8:
                flags = sbody.u8()
                sbody.u8()
                sbody.u8()
                sbody.u8()
                return prefix, sbody.u32(), flags
    return None


def grace_lsa_lsid(opaque_id: int = 0) -> IPv4Address:
    """Opaque LSAs carry (opaque type, opaque id) in the link-state id;
    the opaque id keeps per-interface Grace-LSAs distinct."""
    return IPv4Address((GRACE_OPAQUE_TYPE << 24) | (opaque_id & 0xFFFFFF))


def encode_grace_tlvs(
    grace_period: int, reason: int, addr: IPv4Address | None
) -> bytes:
    """RFC 3623 §B: grace period (1), restart reason (2), and — only when
    present (it is optional on p2p links) — IP address (3)."""
    w = Writer()
    w.u16(1).u16(4).u32(grace_period)
    w.u16(2).u16(1).u8(reason).zeros(3)
    if addr is not None:
        w.u16(3).u16(4).ipv4(addr)
    return w.finish()


def decode_grace_tlvs(data: bytes) -> dict:
    """Tolerant parse: gates on ACTUAL remaining bytes, never the declared
    length (a crafted short TLV must not raise out of the rx path)."""
    r = Reader(data)
    out: dict = {}
    while r.remaining() >= 4:
        t = r.u16()
        length = r.u16()
        body = r.sub(min((length + 3) // 4 * 4, r.remaining()))
        if t == 1 and body.remaining() >= 4:
            out["grace_period"] = body.u32()
        elif t == 2 and body.remaining() >= 1:
            out["reason"] = body.u8()
        elif t == 3 and body.remaining() >= 4:
            out["addr"] = body.ipv4()
    return out


_BODY_CODECS = {
    LsaType.ROUTER: LsaRouter,
    LsaType.NETWORK: LsaNetwork,
    LsaType.SUMMARY_NETWORK: LsaSummary,
    LsaType.SUMMARY_ROUTER: LsaSummary,
    LsaType.AS_EXTERNAL: LsaAsExternal,
    LsaType.NSSA_EXTERNAL: LsaAsExternal,
    LsaType.OPAQUE_LINK: LsaOpaque,
    LsaType.OPAQUE_AREA: LsaOpaque,
    LsaType.OPAQUE_AS: LsaOpaque,
}


@dataclass(frozen=True)
class LsaKey:
    """LSDB key (RFC 2328 §12.1: type, link-state id, advertising router)."""

    type: LsaType
    lsid: IPv4Address
    adv_rtr: IPv4Address


@dataclass
class Lsa:
    """Header + body; raw wire image cached for flooding/checksum."""

    age: int
    options: Options
    type: LsaType
    lsid: IPv4Address
    adv_rtr: IPv4Address
    seq_no: int
    body: object
    cksum: int = 0
    length: int = 0
    raw: bytes = b""

    @property
    def key(self) -> LsaKey:
        return LsaKey(self.type, self.lsid, self.adv_rtr)

    @property
    def is_maxage(self) -> bool:
        return self.age >= MAX_AGE

    def encode(self) -> bytes:
        """Encode body, compute length + Fletcher checksum, cache raw."""
        w = Writer()
        w.u16(self.age).u8(int(self.options)).u8(int(self.type))
        w.ipv4(self.lsid).ipv4(self.adv_rtr)
        w.u32(self.seq_no & 0xFFFFFFFF)
        w.u16(0)  # checksum placeholder
        w.u16(0)  # length placeholder
        self.body.encode(w)
        w.patch_u16(18, len(w))
        self.length = len(w)
        # Fletcher over everything except the age field (first 2 bytes).
        cks = fletcher16_checksum(bytes(w.buf[2:]), 14)
        w.patch_u16(16, cks)
        self.cksum = cks
        self.raw = w.finish()
        return self.raw

    @classmethod
    def decode(cls, r: Reader) -> "Lsa":
        start = r.pos
        if r.remaining() < LSA_HDR_LEN:
            raise DecodeError("short LSA header")
        age = r.u16()
        options = Options(r.u8())
        try:
            ltype = LsaType(r.u8())
        except ValueError as e:
            raise DecodeError("unknown LSA type") from e
        lsid, adv = r.ipv4(), r.ipv4()
        seq = r.u32()
        if seq & 0x80000000:
            seq -= 1 << 32
        cksum = r.u16()
        length = r.u16()
        if length < LSA_HDR_LEN:
            raise DecodeError(f"bad LSA length {length}")
        body_len = length - LSA_HDR_LEN
        if r.remaining() < body_len:
            raise DecodeError("LSA length exceeds buffer")
        raw = r.data[start : start + length]
        # A checksum mismatch does NOT abort the decode: the rx path
        # validates separately and emits if-rx-bad-lsa (reference decodes
        # tolerantly, lsa.rs validate() flags it — events.rs:830-845).
        body = _BODY_CODECS[ltype].decode(r.sub(body_len))
        return cls(age, options, ltype, lsid, adv, seq, body, cksum, length, raw)

    @classmethod
    def decode_header(cls, r: Reader) -> "Lsa":
        """Header-only decode (DD packets, LS Ack)."""
        age = r.u16()
        options = Options(r.u8())
        ltype = LsaType(r.u8())
        lsid, adv = r.ipv4(), r.ipv4()
        seq = r.u32()
        if seq & 0x80000000:
            seq -= 1 << 32
        cksum = r.u16()
        length = r.u16()
        return cls(age, options, ltype, lsid, adv, seq, None, cksum, length)

    def encode_header(self, w: Writer) -> None:
        w.u16(self.age).u8(int(self.options)).u8(int(self.type))
        w.ipv4(self.lsid).ipv4(self.adv_rtr).u32(self.seq_no & 0xFFFFFFFF)
        w.u16(self.cksum).u16(self.length)

    def compare(self, other: "Lsa") -> int:
        """RFC 2328 §13.1 which-is-newer: >0 self newer, <0 other newer."""
        if self.seq_no != other.seq_no:
            return 1 if self.seq_no > other.seq_no else -1
        if self.cksum != other.cksum:
            return 1 if self.cksum > other.cksum else -1
        if self.is_maxage != other.is_maxage:
            return 1 if self.is_maxage else -1
        if abs(self.age - other.age) > MAX_AGE_DIFF:
            return 1 if self.age < other.age else -1
        return 0


# ===== Packets =====


@dataclass
class Hello:
    mask: IPv4Address
    hello_interval: int
    options: Options
    priority: int
    dead_interval: int
    dr: IPv4Address
    bdr: IPv4Address
    neighbors: list[IPv4Address] = field(default_factory=list)

    TYPE = PacketType.HELLO

    def encode_body(self, w: Writer) -> None:
        w.ipv4(self.mask).u16(self.hello_interval).u8(int(self.options))
        w.u8(self.priority).u32(self.dead_interval)
        w.ipv4(self.dr).ipv4(self.bdr)
        for n in self.neighbors:
            w.ipv4(n)

    @classmethod
    def decode_body(cls, r: Reader) -> "Hello":
        mask = r.ipv4()
        hello_int = r.u16()
        options = Options(r.u8())
        prio = r.u8()
        dead = r.u32()
        dr, bdr = r.ipv4(), r.ipv4()
        nbrs = []
        while r.remaining() >= 4:
            nbrs.append(r.ipv4())
        return cls(mask, hello_int, options, prio, dead, dr, bdr, nbrs)


class DbDescFlags(enum.IntFlag):
    MS = 0x01  # master
    M = 0x02  # more
    I = 0x04  # init


@dataclass
class DbDesc:
    mtu: int
    options: Options
    flags: DbDescFlags
    dd_seq_no: int
    lsa_headers: list[Lsa] = field(default_factory=list)

    TYPE = PacketType.DB_DESC

    def encode_body(self, w: Writer) -> None:
        w.u16(self.mtu).u8(int(self.options)).u8(int(self.flags))
        w.u32(self.dd_seq_no)
        for h in self.lsa_headers:
            h.encode_header(w)

    @classmethod
    def decode_body(cls, r: Reader) -> "DbDesc":
        mtu = r.u16()
        options = Options(r.u8())
        flags = DbDescFlags(r.u8())
        seq = r.u32()
        hdrs = []
        while r.remaining() >= LSA_HDR_LEN:
            hdrs.append(Lsa.decode_header(r))
        return cls(mtu, options, flags, seq, hdrs)


@dataclass
class LsRequest:
    entries: list[LsaKey] = field(default_factory=list)

    TYPE = PacketType.LS_REQUEST

    def encode_body(self, w: Writer) -> None:
        for k in self.entries:
            w.u32(int(k.type)).ipv4(k.lsid).ipv4(k.adv_rtr)

    @classmethod
    def decode_body(cls, r: Reader) -> "LsRequest":
        entries = []
        while r.remaining() >= 12:
            t = LsaType(r.u32())
            entries.append(LsaKey(t, r.ipv4(), r.ipv4()))
        return cls(entries)


@dataclass
class LsUpdate:
    lsas: list[Lsa] = field(default_factory=list)

    TYPE = PacketType.LS_UPDATE

    def encode_body(self, w: Writer) -> None:
        w.u32(len(self.lsas))
        for lsa in self.lsas:
            w.bytes(lsa.raw if lsa.raw else lsa.encode())

    @classmethod
    def decode_body(cls, r: Reader) -> "LsUpdate":
        n = r.u32()
        lsas = []
        for _ in range(n):
            start = r.pos
            try:
                lsas.append(Lsa.decode(r))
            except DecodeError:
                # §13 steps 2-3: an LSA of unknown type (or otherwise
                # undecodable body) is discarded; the REST of the update
                # is still processed.  Advance by the header's length
                # field; if even that is unusable, the packet is
                # unrecoverable.
                r.pos = start
                if r.remaining() < LSA_HDR_LEN:
                    raise
                length = int.from_bytes(r.data[start + 18 : start + 20], "big")
                if length < LSA_HDR_LEN or r.remaining() < length:
                    raise
                r.pos = start + length
        return cls(lsas)


@dataclass
class LsAck:
    lsa_headers: list[Lsa] = field(default_factory=list)

    TYPE = PacketType.LS_ACK

    def encode_body(self, w: Writer) -> None:
        for h in self.lsa_headers:
            h.encode_header(w)

    @classmethod
    def decode_body(cls, r: Reader) -> "LsAck":
        hdrs = []
        while r.remaining() >= LSA_HDR_LEN:
            hdrs.append(Lsa.decode_header(r))
        return cls(hdrs)


_PKT_CODECS = {
    PacketType.HELLO: Hello,
    PacketType.DB_DESC: DbDesc,
    PacketType.LS_REQUEST: LsRequest,
    PacketType.LS_UPDATE: LsUpdate,
    PacketType.LS_ACK: LsAck,
}


# Digest algorithms: RFC 2328 Appendix D keyed-MD5 plus the RFC 5709
# HMAC-SHA family.  Value = (digest_len, hmac_name or None for keyed-md5).
AUTH_ALGOS = {
    "md5": (16, None),
    "hmac-sha-1": (20, "sha1"),
    "hmac-sha-256": (32, "sha256"),
    "hmac-sha-384": (48, "sha384"),
    "hmac-sha-512": (64, "sha512"),
}


@dataclass
class AuthCtx:
    """Interface authentication context (RFC 2328 Appendix D / RFC 5709).

    type SIMPLE: ``key`` is the 8-byte password.  type CRYPTOGRAPHIC: a
    keyed digest (per ``algo``) is appended after the packet; ``seqno``
    provides replay protection (non-decreasing per neighbor).
    """

    type: AuthType = AuthType.NULL
    key: bytes = b""
    key_id: int = 1
    seqno: int = 0
    algo: str = "md5"
    # Lifetime-based key selection (reference holo-utils/src/keychain.rs
    # :42-92): when set, the active SEND key signs outgoing packets and
    # received key ids validate against their ACCEPT lifetimes — this is
    # what makes key rollover work.  ``clock`` supplies epoch seconds
    # (the owning loop's clock; virtual in tests).
    keychain: object = None
    clock: object = None

    def _now(self) -> float:
        if callable(self.clock):
            return self.clock()
        import time as _time

        return _time.time()

    def _send_key(self):
        if self.keychain is None:
            return None
        return self.keychain.key_lookup_send(self._now())

    def accept_params(self, key_id: int) -> "tuple[bytes, str] | None":
        """(key, algo) accepted for a received packet carrying
        ``key_id`` — None rejects (keychain.rs key_lookup_accept)."""
        if self.keychain is None:
            if key_id != self.key_id:
                return None
            return self.key, self.algo
        # Masked compare: the OSPFv2 key id is u8 on the wire and
        # tx_key_id masks — the accept side must match the same way.
        k = self.keychain.key_lookup_accept(key_id, self._now(), mask=0xFF)
        if k is None:
            return None
        return k.string, k.algo

    @property
    def tx_key_id(self) -> int:
        k = self._send_key()
        return (k.id & 0xFF) if k is not None else self.key_id

    def resolve_send(self) -> "AuthCtx | None":
        """Fixed-key context for ONE outgoing packet: key id, digest
        length, packet digest, and LLS digest must all come from the
        SAME key, so the keychain is consulted exactly once per encode.
        None when the keychain has no active send key — the packet goes
        out unauthenticated, like the reference's get_key_send → None
        (the peer's type check rejects it, which is the correct signal
        for a lifetime coverage gap, not a forged-looking digest)."""
        if self.keychain is None:
            return self
        k = self.keychain.key_lookup_send(self._now())
        if k is None:
            return None
        return AuthCtx(
            self.type, k.string, k.id & 0xFF, self.seqno, k.algo
        )

    def resolve_accept(self, key_id: int) -> "AuthCtx | None":
        """Fixed-key context for verifying ONE received packet (same
        single-consultation rule on the accept side)."""
        params = self.accept_params(key_id)
        if params is None:
            return None
        key, algo = params
        return AuthCtx(self.type, key, key_id, self.seqno, algo)

    @staticmethod
    def make_digest(key: bytes, algo: str, data: bytes) -> bytes:
        dlen, hname = AUTH_ALGOS[algo]
        if hname is None:  # RFC 2328 keyed-MD5: md5(packet || padded key)
            return hashlib.md5(data + key[:16].ljust(16, b"\x00")).digest()
        return _hmac.new(key, data, hname).digest()

    def digest(self, data: bytes) -> bytes:
        """Sign with this context's key.  Keychain contexts are resolved
        to a fixed key via resolve_send/resolve_accept BEFORE any digest
        is computed (one consultation per packet); the dynamic fallback
        here covers direct callers only."""
        k = self._send_key()
        key, algo = (k.string, k.algo) if k is not None else (
            self.key, self.algo
        )
        return self.make_digest(key, algo, data)

    @property
    def digest_len(self) -> int:
        k = self._send_key()
        return AUTH_ALGOS[k.algo if k is not None else self.algo][0]


# LLS Extended Options and Flags bits (RFC 5613 / lls.rs:115-125).
LLS_EOF_LR = 0x00000001  # LSDB resynchronization (RFC 4811)
LLS_EOF_RS = 0x00000002  # restart signal (RFC 4812)


@dataclass
class LlsBlock:
    """RFC 5613 link-local signaling data block, appended after the
    OSPF packet (reference holo-ospf/src/packet/lls.rs).

    Carried on Hello/DbDesc packets whose options set the L bit; the
    Extended Options and Flags TLV transports the LR (out-of-band LSDB
    resync capability) and RS (restart signal) bits.
    """

    eof: int | None = None  # LLS_EOF_* bits

    def encode(self, auth: "AuthCtx | None" = None) -> bytes:
        crypto = auth is not None and auth.type == AuthType.CRYPTOGRAPHIC
        w = Writer()
        w.u16(0)  # checksum (0 under cryptographic auth, §2.2)
        len_pos = len(w)
        w.u16(0)  # block length in 32-bit words (incl. header)
        if self.eof is not None:
            w.u16(1).u16(4).u32(self.eof)  # Extended Options TLV
        if crypto:
            # §2.5 Cryptographic Authentication TLV: MUST be last; the
            # digest covers the block with the length field final
            # (ospfv2/packet/lls.rs:88-120).
            dlen = auth.digest_len
            w.u16(2).u16(4 + dlen).u32(auth.seqno & 0xFFFFFFFF)
            digest_start = len(w)
            w.zeros(dlen)
            w.patch_u16(len_pos, len(w) // 4)
            out = bytearray(w.finish())
            digest = auth.digest(bytes(out[:digest_start]))
            out[digest_start:] = digest
            return bytes(out)
        w.patch_u16(len_pos, len(w) // 4)
        out = bytearray(w.finish())
        cks = ip_checksum(bytes(out))
        out[0:2] = cks.to_bytes(2, "big")
        return bytes(out)

    @classmethod
    def decode(
        cls, data: bytes, auth: "AuthCtx | None" = None
    ) -> "LlsBlock":
        """``auth`` is already key-resolved by Packet.decode (the LLS
        digest must verify with the SAME accept key as the packet)."""
        crypto = auth is not None and auth.type == AuthType.CRYPTOGRAPHIC
        if len(data) < 4:
            raise DecodeError("short LLS block")
        words = int.from_bytes(data[2:4], "big")
        blen = words * 4
        if blen < 4 or blen > len(data):
            raise DecodeError("bad LLS length")
        if not crypto and ip_checksum(data[:blen]) != 0:
            raise DecodeError("LLS checksum mismatch")
        r = Reader(data, 4, blen)
        out = cls()
        ca_verified = False
        while r.remaining() >= 4:
            tlv_start = 4 + (r.pos - 4)
            ttype = r.u16()
            tlen = r.u16()
            if tlen > r.remaining():
                raise DecodeError("bad LLS TLV length")
            body = r.sub(tlen)
            # TLVs are padded to 32-bit alignment.
            pad = (-tlen) % 4
            if pad and r.remaining() >= pad:
                r.bytes(pad)
            if ttype == 1:
                if tlen != 4:
                    raise DecodeError("bad LLS EOF TLV length")
                out.eof = body.u32()
            elif ttype == 2 and crypto:
                # CA TLV digest covers the block up to the digest field.
                body.u32()  # seqno (replay handled at the packet layer)
                dlen = tlen - 4
                if dlen != auth.digest_len:
                    raise DecodeError("bad LLS CA digest length")
                digest_off = tlv_start + 8
                want = auth.digest(data[:digest_off])
                got = data[digest_off : digest_off + dlen]
                if not _hmac.compare_digest(want, got):
                    raise DecodeError("LLS CA digest mismatch")
                ca_verified = True
            # Other unknown LLS TLVs are skipped.
        if crypto and not ca_verified:
            raise DecodeError("missing LLS CA TLV under crypto auth")
        return out


@dataclass
class Packet:
    """OSPFv2 packet: 24-byte header + typed body (RFC 2328 §A.3.1) +
    optional LLS data block (RFC 5613) when the body options set L."""

    router_id: IPv4Address
    area_id: IPv4Address
    body: object
    # auth_type/auth_data/auth_seqno are DECODE OUTPUTS (what the wire
    # carried); encode() authenticates solely from its ``auth`` argument.
    auth_type: AuthType = AuthType.NULL
    auth_data: bytes = bytes(8)
    auth_seqno: int = 0
    lls: LlsBlock | None = None

    def encode(self, auth: AuthCtx | None = None) -> bytes:
        auth = auth or AuthCtx()
        if auth.type == AuthType.CRYPTOGRAPHIC:
            # One keychain consultation per packet: key id, digest
            # length, and both digests must agree (resolve_send).
            auth = auth.resolve_send() or AuthCtx()
        w = Writer()
        w.u8(OSPF_VERSION).u8(int(self.body.TYPE)).u16(0)
        w.ipv4(self.router_id).ipv4(self.area_id)
        w.u16(0)  # checksum
        w.u16(int(auth.type))
        w.zeros(8)
        self.body.encode_body(w)
        w.patch_u16(2, len(w))
        if auth.type == AuthType.CRYPTOGRAPHIC:
            # Appendix D.4.3: checksum not computed; auth field carries
            # (0, key id, digest length, seqno); digest appended.
            w.patch_bytes(
                16,
                bytes((0, 0, auth.tx_key_id, auth.digest_len))
                + (auth.seqno & 0xFFFFFFFF).to_bytes(4, "big"),
            )
            w.bytes(auth.digest(bytes(w.buf)))
            out = w.finish()
            if self.lls is not None:
                out += self.lls.encode(auth=auth)
            return out
        cks = ip_checksum(bytes(w.buf[:16]) + bytes(w.buf[24:]))
        w.patch_u16(12, cks)
        if auth.type == AuthType.SIMPLE:
            w.patch_bytes(16, auth.key[:8].ljust(8, b"\x00"))
        out = w.finish()
        if self.lls is not None:
            out += self.lls.encode()
        return out

    @classmethod
    def decode(cls, data: bytes, auth: AuthCtx | None = None) -> "Packet":
        """Decode + authenticate.  ``auth`` is the receiving interface's
        configured context; a type/credential mismatch raises DecodeError
        (the reference drops such packets with an auth error counter)."""
        r = Reader(data)
        if r.remaining() < PKT_HDR_LEN:
            raise DecodeError("short packet")
        version = r.u8()
        if version != OSPF_VERSION:
            raise DecodeError(f"bad version {version}")
        try:
            ptype = PacketType(r.u8())
        except ValueError as e:
            raise DecodeError("unknown packet type") from e
        length = r.u16()
        if length < PKT_HDR_LEN or length > len(data):
            raise DecodeError("bad packet length")
        router_id, area_id = r.ipv4(), r.ipv4()
        r.u16()  # checksum (verified below)
        try:
            auth_type = AuthType(r.u16())
        except ValueError as e:
            raise DecodeError("unknown auth type") from e
        auth_data = r.bytes(8)
        expected = auth.type if auth is not None else AuthType.NULL
        if auth_type != expected:
            raise DecodeError(f"auth type mismatch: got {auth_type}")
        seqno = 0
        dlen = 0
        if auth_type == AuthType.CRYPTOGRAPHIC:
            rx_key_id = auth_data[2]
            dlen = auth_data[3]
            seqno = int.from_bytes(auth_data[4:8], "big")
            # Accept-side key selection, resolved ONCE for the whole
            # packet (incl. the LLS block below): the received key id
            # must name a key whose ACCEPT lifetime is active
            # (keychain.rs key_lookup_accept); fixed-key contexts only
            # accept their own id.
            eff = auth.resolve_accept(rx_key_id)
            if eff is None or eff.digest_len != dlen:
                raise DecodeError("bad crypto auth parameters")
            auth = eff
            if len(data) < length + dlen:
                raise DecodeError("missing auth digest")
            digest = auth.digest(data[:length])
            if not _hmac.compare_digest(digest, data[length : length + dlen]):
                raise DecodeError("auth digest mismatch")
        else:
            if auth_type == AuthType.SIMPLE:
                want = (auth.key[:8] if auth else b"").ljust(8, b"\x00")
                if not _hmac.compare_digest(auth_data, want):
                    raise DecodeError("bad simple password")
            if ip_checksum(data[:16] + data[24:length]) != 0:
                raise DecodeError("packet checksum mismatch")
        body = _PKT_CODECS[ptype].decode_body(Reader(data, PKT_HDR_LEN, length))
        lls = None
        if Options.L & getattr(body, "options", 0):
            crypto = auth_type == AuthType.CRYPTOGRAPHIC
            off = length + (dlen if crypto else 0)
            if len(data) > off:
                # auth was rebound to the resolved accept key above —
                # the LLS digest verifies with the SAME key.
                lls = LlsBlock.decode(data[off:], auth=auth)
        return cls(
            router_id, area_id, body, auth_type, auth_data, seqno, lls
        )

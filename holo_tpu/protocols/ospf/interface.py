"""OSPF interface state machine (ISM, RFC 2328 §9) + DR election (§9.4).

Reference: holo-ospf/src/interface.rs.  States for p2p and broadcast
networks; NBMA/p2mp deferred.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from ipaddress import IPv4Address, IPv4Network

from holo_tpu.protocols.ospf.packet import Options


class IfType(enum.Enum):
    POINT_TO_POINT = "p2p"
    BROADCAST = "broadcast"
    # RFC 2328 §15: unnumbered point-to-point through a transit area.
    VIRTUAL_LINK = "virtual-link"


class IsmState(enum.IntEnum):
    DOWN = 0
    LOOPBACK = 1
    WAITING = 2
    POINT_TO_POINT = 3
    DR_OTHER = 4
    BACKUP = 5
    DR = 6


class IsmEvent(enum.Enum):
    INTERFACE_UP = "up"
    WAIT_TIMER = "wait_timer"
    BACKUP_SEEN = "backup_seen"
    NEIGHBOR_CHANGE = "neighbor_change"
    INTERFACE_DOWN = "down"


@dataclass
class IfConfig:
    area_id: IPv4Address = IPv4Address("0.0.0.0")
    if_type: IfType = IfType.BROADCAST
    cost: int = 10
    hello_interval: int = 10
    dead_interval: int = 40
    rxmt_interval: int = 5
    priority: int = 1
    passive: bool = False
    # Loopback interfaces advertise their host address as a zero-cost
    # stub link and run no hello machinery (reference: holo-ospf treats
    # kernel-loopback interfaces this way in the router-LSA build).
    loopback: bool = False
    mtu: int = 1500
    # RFC 2328 §10.6: a DD whose Interface MTU exceeds ours is rejected
    # (adjacency sticks in ExStart) unless mtu-ignore bypasses the check
    # (ietf-ospf interface leaf of the same name).
    mtu_ignore: bool = False
    # §13.3 InfTransDelay: seconds added to every LSA's age when it is
    # copied into an outgoing Link State Update on this interface
    # (ietf-ospf transmit-delay leaf).
    transmit_delay: int = 1
    bfd_enabled: bool = False
    auth: object = None  # AuthCtx (packet.py) or None
    # RFC 7684 prefix attribute flags advertised in extended-prefix
    # opaque LSAs: N marks a node host address, AC an anycast address
    # (reference ospfv2/lsdb.rs:760-783, iana.rs LsaExtPrefixFlags).
    node_flag: bool = False
    anycast_flag: bool = False
    # Shared-risk link group ids of this interface (ietf fast-reroute
    # SRLG membership).  Lowered to the uint32 ``Topology.edge_srlg``
    # bitmask at SPF marshal time (spf_run.srlg_bits; ids fold mod 32,
    # conservative-correct) — the srlg_disjoint FRR policy input.
    srlg: tuple = ()


@dataclass
class OspfInterface:
    name: str
    config: IfConfig
    addr_ip: IPv4Address | None = None  # our interface address
    prefix: IPv4Network | None = None  # attached subnet
    ifindex: int = 0
    state: IsmState = IsmState.DOWN
    dr: IPv4Address = IPv4Address(0)  # DR *interface address* (v2, §9)
    bdr: IPv4Address = IPv4Address(0)
    neighbors: dict = field(default_factory=dict)  # nbr router-id -> Neighbor
    # Additional subnets on the interface: advertised as stub links
    # (reference advertises every interface address).
    secondary: list = field(default_factory=list)  # [IPv4Network]
    # Virtual-link state (reference interface.rs:50,84,135-148): the
    # configured peer router-id, the transit area carrying the link, the
    # resolved unicast destination (the peer's transit-area interface
    # address) and the physical interface packets leave through.
    vlink_peer: IPv4Address | None = None
    vlink_transit: IPv4Address | None = None
    vlink_dst: IPv4Address | None = None
    vlink_out_ifname: str | None = None

    def options(self) -> Options:
        return Options.E  # stub-area support sets E=0 per area config later

    def is_dr(self) -> bool:
        return self.state == IsmState.DR

    def is_dr_or_bdr(self) -> bool:
        return self.state in (IsmState.DR, IsmState.BACKUP)


@dataclass(frozen=True)
class ElectionView:
    """A router's view for DR election: (priority, router-id, declared DR/BDR)."""

    priority: int
    router_id: IPv4Address
    addr: IPv4Address
    dr: IPv4Address
    bdr: IPv4Address


def elect_dr_bdr(views: list[ElectionView]) -> tuple[IPv4Address, IPv4Address]:
    """RFC 2328 §9.4 steps 2-3 (single pass; caller reruns on state change).

    Returns (dr_addr, bdr_addr) as interface addresses (0.0.0.0 if none).
    """
    eligible = [v for v in views if v.priority > 0]

    def best(cands):
        return max(cands, key=lambda v: (v.priority, int(v.router_id)))

    # BDR: routers not declaring themselves DR; prefer those declaring BDR.
    bdr_cands = [v for v in eligible if v.dr != v.addr]
    declared_bdr = [v for v in bdr_cands if v.bdr == v.addr]
    bdr = best(declared_bdr) if declared_bdr else (best(bdr_cands) if bdr_cands else None)

    # DR: routers declaring themselves DR; else the BDR is promoted.
    declared_dr = [v for v in eligible if v.dr == v.addr]
    if declared_dr:
        dr = best(declared_dr)
    else:
        dr = bdr
    if dr is not None and dr is bdr:
        # Promoted BDR: re-elect BDR excluding the new DR.
        rest = [v for v in bdr_cands if v is not dr]
        declared = [v for v in rest if v.bdr == v.addr]
        bdr = best(declared) if declared else (best(rest) if rest else None)

    zero = IPv4Address(0)
    return (dr.addr if dr else zero, bdr.addr if bdr else zero)

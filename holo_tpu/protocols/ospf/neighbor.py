"""OSPF neighbor state machine (NSM, RFC 2328 §10) + DD exchange state.

Reference: holo-ospf/src/neighbor.rs.  The NSM here is table-driven; the
instance actor supplies the side effects (packet sends, timer management,
LSA list maintenance) via the transition result.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from ipaddress import IPv4Address

from holo_tpu.protocols.ospf.packet import DbDescFlags, Lsa, LsaKey


class NsmState(enum.IntEnum):
    DOWN = 0
    ATTEMPT = 1
    INIT = 2
    TWO_WAY = 3
    EX_START = 4
    EXCHANGE = 5
    LOADING = 6
    FULL = 7


class NsmEvent(enum.Enum):
    HELLO_RECEIVED = "hello_received"
    START = "start"
    TWO_WAY_RECEIVED = "2way_received"
    NEGOTIATION_DONE = "negotiation_done"
    EXCHANGE_DONE = "exchange_done"
    BAD_LS_REQ = "bad_ls_req"
    LOADING_DONE = "loading_done"
    ADJ_OK = "adj_ok"
    SEQ_NUMBER_MISMATCH = "seq_mismatch"
    ONE_WAY_RECEIVED = "1way_received"
    KILL_NBR = "kill_nbr"
    INACTIVITY_TIMER = "inactivity_timer"
    LL_DOWN = "ll_down"


@dataclass
class Neighbor:
    router_id: IPv4Address
    src: IPv4Address  # neighbor interface address
    state: NsmState = NsmState.DOWN
    priority: int = 0
    dr: IPv4Address = IPv4Address(0)
    bdr: IPv4Address = IPv4Address(0)
    # OSPFv3: the neighbor's interface id from its hellos (RFC 5340
    # §4.2.1 — needed for transit links and network-LSA vertex keys).
    iface_id: int = 0
    # DD exchange (§10.8):
    master: bool = False  # True if WE are master
    dd_seq_no: int = 0
    dd_pending_flags: DbDescFlags = DbDescFlags(0)
    last_dd: tuple | None = None  # (flags, options, seq) for duplicate detect
    dd_summary: list[Lsa] = field(default_factory=list)  # headers to send
    last_sent_dd: object = None  # retransmit copy (master) / echo copy (slave)
    # Lists (§10: Link state request / retransmission lists):
    ls_request: dict[LsaKey, Lsa] = field(default_factory=dict)
    ls_rxmt: dict[LsaKey, Lsa] = field(default_factory=dict)
    # Timers owned by the instance actor:
    timers: dict = field(default_factory=dict)
    # Cryptographic auth replay protection (RFC 2328 D.3): last accepted
    # sequence number from this neighbor.
    crypto_seqno: int = -1
    # RFC 5613 LLS: extended-options flags from the peer's last hello
    # (LR = OOB resync capable, RS = restart signal), None = no block.
    lls_eof: int | None = None
    # Graceful-restart helper (RFC 3623): while now < gr_deadline the
    # inactivity timer must not kill this neighbor.
    gr_deadline: float | None = None
    gr_reason: int = 0  # Grace-LSA restart reason while helping

    def is_adjacent(self) -> bool:
        return self.state >= NsmState.EX_START

    def exchange_or_loading(self) -> bool:
        return self.state in (NsmState.EXCHANGE, NsmState.LOADING)


# NSM transition core: (state, event) -> new_state or callable deciding it.
# Actions are returned as labels the instance interprets (keeps IO out of
# the pure FSM, which the golden tests exercise directly).


@dataclass
class NsmResult:
    new_state: NsmState
    actions: list[str]


def nsm_transition(nbr: Neighbor, event: NsmEvent, adj_ok: bool = True) -> NsmResult:
    s = nbr.state
    E, S = NsmEvent, NsmState
    acts: list[str] = []

    if event == E.HELLO_RECEIVED:
        new = max(s, S.INIT)
        acts.append("restart_inactivity")
        return NsmResult(new, acts)
    if event == E.TWO_WAY_RECEIVED:
        if s == S.INIT:
            if adj_ok:
                acts += ["start_exstart"]
                return NsmResult(S.EX_START, acts)
            return NsmResult(S.TWO_WAY, acts)
        return NsmResult(s, acts)
    if event == E.ADJ_OK:
        if s == S.TWO_WAY and adj_ok:
            acts += ["start_exstart"]
            return NsmResult(S.EX_START, acts)
        if s > S.TWO_WAY and not adj_ok:
            acts += ["clear_lists"]
            return NsmResult(S.TWO_WAY, acts)
        return NsmResult(s, acts)
    if event == E.NEGOTIATION_DONE:
        acts += ["send_dd_summary"]
        return NsmResult(S.EXCHANGE, acts)
    if event == E.EXCHANGE_DONE:
        if nbr.ls_request:
            acts += ["send_ls_request"]
            return NsmResult(S.LOADING, acts)
        return NsmResult(S.FULL, acts + ["full"])
    if event == E.LOADING_DONE:
        return NsmResult(S.FULL, acts + ["full"])
    if event in (E.SEQ_NUMBER_MISMATCH, E.BAD_LS_REQ):
        if s >= S.EXCHANGE or s == S.EX_START:
            acts += ["clear_lists", "start_exstart"]
            return NsmResult(S.EX_START, acts)
        return NsmResult(s, acts)
    if event == E.ONE_WAY_RECEIVED:
        if s >= S.TWO_WAY:
            acts += ["clear_lists"]
            return NsmResult(S.INIT, acts)
        return NsmResult(s, acts)
    if event in (E.KILL_NBR, E.LL_DOWN, E.INACTIVITY_TIMER):
        acts += ["clear_lists", "stop_timers"]
        return NsmResult(S.DOWN, acts)
    return NsmResult(s, acts)

"""OSPFv2 (RFC 2328) — link-state IGP with the SPF hot path on a pluggable
backend (scalar CPU default, TPU batch engine opt-in).

Reference crate: holo-ospf (SURVEY.md §2.3).  This implementation follows
the same anatomy — packet codecs (packet.py), LSDB (lsdb.py), interface ISM
(interface.py), neighbor NSM (neighbor.py), flooding (flooding.py), SPF
delay FSM + route calc (spf_run.py), instance actor (instance.py) — but is
structured for the deterministic event loop and tensor SPF backend.

Round-1 scope: OSPFv2 single/multi-area, p2p + broadcast interfaces,
null auth, intra-area + inter-area routes; NSSA/virtual-link/GR/SR later.
"""

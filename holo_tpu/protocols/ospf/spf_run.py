"""SPF marshaling + route derivation for OSPFv2.

Bridges the protocol LSDB to the tensor/scalar SPF backends:

- :func:`build_topology` lowers an area LSDB into the generic
  :class:`~holo_tpu.ops.graph.Topology` (vertex model of RFC 2328 §16.1,
  ordering contract of holo_tpu.ops.graph), assigning next-hop atoms for
  exactly the parent-hops==0 cases (reference calc_nexthops,
  holo-ospf/src/ospfv2/spf.rs:172-…).
- :func:`derive_routes` turns backend results (distances + ECMP atom
  bitmasks) into per-prefix intra-area routes (reference
  route::update_rib_full, holo-ospf/src/route.rs:146-197).
"""

from __future__ import annotations

from dataclasses import dataclass
from ipaddress import IPv4Address, IPv4Network

import numpy as np

from holo_tpu.ops.graph import INF, Topology
from holo_tpu.protocols.ospf.lsdb import Lsdb
from holo_tpu.protocols.ospf.packet import (
    LsaNetwork,
    LsaRouter,
    LsaType,
    RouterLinkType,
)
from holo_tpu.spf.backend import SpfResult
from holo_tpu.utils.ip import apply_mask


def srlg_bits(groups) -> int:
    """uint32 bitmask of configured SRLG group ids.

    Group ids fold modulo 32 onto the mask bits — membership testing
    stays conservative-correct under folding (a shared bit is treated
    as a shared risk, never the reverse), matching the FRR engines'
    ``srlg_disjoint`` exclusion semantics over ``Topology.edge_srlg``.
    """
    bits = 0
    for gid in groups or ():
        bits |= 1 << (int(gid) % 32)
    return bits


def apply_interface_srlg(
    topo: Topology, atom_ifnames, srlg_of_ifname: dict
) -> None:
    """Stamp ``Topology.edge_srlg`` from per-interface fast-reroute
    config (the ROADMAP carry-over: until now only tests/synth ever set
    the seam).

    ``atom_ifnames[a]`` is the outgoing interface of next-hop atom
    ``a`` (None for borrowed/vlink atoms); ``srlg_of_ifname`` maps
    interface name -> uint32 SRLG bitmask (:func:`srlg_bits`).  Every
    edge resolving through a configured interface — exactly the root
    out-edges the FRR engines treat as protected links and repair
    candidates — carries that interface's groups.  In-place: callers
    stamp after ``edge_direct_atom`` is final."""
    if not srlg_of_ifname:
        return
    srlg = np.zeros(topo.n_edges, np.uint32)
    for e in range(topo.n_edges):
        a = int(topo.edge_direct_atom[e])
        if a < 0 or a >= len(atom_ifnames):
            continue
        ifn = atom_ifnames[a]
        if ifn is not None:
            srlg[e] = np.uint32(srlg_of_ifname.get(ifn, 0))
    topo.edge_srlg = srlg


def apply_partition_hint(topo: Topology, groups) -> None:
    """Stamp ``Topology.partition_hint`` from a per-vertex grouping
    (ISSUE 15): the protocol seam the hierarchical partitioned-SPF path
    reads (``ops/graph.partition_topology`` honors the hint verbatim).

    ``groups`` is a sequence of hashable, orderable group labels — one
    per vertex in vertex order (IS-IS area addresses, OSPF sub-area
    groupings, synth multi-area ids) — or None entries for ungrouped
    vertices.  The stamp happens only when EVERY vertex is grouped and
    at least two distinct groups exist; otherwise the topology stays
    flat and the deterministic BFS/greedy cut decides at partition
    time.  Distinct labels map onto dense partition ids in ascending
    label order, so the hint is reproducible across marshals (the
    DeltaPath chain contract).  Like ``edge_srlg`` the hint never
    enters the DeviceGraph planes — residents cannot serve it stale."""
    if groups is None:
        return
    labels = list(groups)
    if len(labels) != topo.n_vertices or any(
        g is None for g in labels
    ):
        return
    uniq = sorted(set(labels))
    if len(uniq) < 2:
        return
    dense = {g: i for i, g in enumerate(uniq)}
    topo.partition_hint = np.array(
        [dense[g] for g in labels], np.int32
    )


@dataclass(frozen=True)
class NexthopAtom:
    """Resolved direct next hop: outgoing interface + neighbor address.

    addr is None for p2p links where the neighbor address is learned from
    the adjacency (filled by the instance) — kept explicit for RIB parity.
    ``expand`` (virtual links, §16.1): the atom stands for the transit
    area's next-hop set toward the vlink neighbor and expands to it when
    atoms are converted to route next hops.
    """

    ifname: str | None
    addr: IPv4Address | None
    expand: frozenset = None


@dataclass
class SpfTopology:
    topo: Topology
    atoms: list[NexthopAtom]
    # vertex index maps
    router_index: dict[IPv4Address, int]
    network_index: dict[IPv4Address, int]


def build_topology(
    lsdb: Lsdb,
    router_id: IPv4Address,
    now: float,
    iface_by_addr: dict[IPv4Address, str],
    iface_by_nbr: dict[IPv4Address, tuple[str, IPv4Address]],
    p2p_nbr_addr: dict[tuple, IPv4Address] | None = None,
    iface_by_ifindex: dict[int, str] | None = None,
    vlink_nexthops: dict | None = None,
    iface_srlg: dict[str, int] | None = None,
    partition_of: dict | None = None,
) -> SpfTopology | None:
    """Lower the area LSDB to the SPF vertex/edge model.

    iface_by_addr: our interface address -> ifname (for transit networks we
    attach to).  iface_by_nbr: neighbor router-id -> (ifname, nbr addr)
    for p2p adjacencies (direct next-hop resolution); with
    ``p2p_nbr_addr`` {(ifname, nbr_rid): addr} parallel p2p links each
    resolve through their own interface (the per-link link_data of our
    router LSA selects the interface).
    MaxAge LSAs are excluded (RFC 2328 §16.1 note).
    """
    routers: list[IPv4Address] = []
    networks: list[IPv4Address] = []  # keyed by DR interface address (lsid)
    rlsa: dict[IPv4Address, LsaRouter] = {}
    nlsa: dict[IPv4Address, LsaNetwork] = {}
    for e in lsdb.all():
        if e.current_age(now) >= 3600:
            continue
        lsa = e.lsa
        if lsa.type == LsaType.ROUTER:
            rlsa[lsa.adv_rtr] = lsa.body
            routers.append(lsa.adv_rtr)
        elif lsa.type == LsaType.NETWORK:
            nlsa[lsa.lsid] = lsa.body
            networks.append(lsa.lsid)

    if router_id not in rlsa:
        return None  # no self LSA yet (reference: SpfRootNotFound)

    # Vertex ordering contract: Network < Router (ospfv2/spf.rs:42-45).
    networks.sort()
    routers.sort()
    network_index = {a: i for i, a in enumerate(networks)}
    router_index = {r: len(networks) + i for i, r in enumerate(routers)}
    n = len(networks) + len(routers)
    is_router = np.zeros(n, bool)
    is_router[len(networks) :] = True

    src, dst, cost = [], [], []
    # Per-edge link_data for edges out of the root (parallel p2p links
    # each resolve to their own interface); vlink edges tracked apart.
    root_edge_data: dict[int, IPv4Address] = {}
    root_vlink_edges: dict[int, IPv4Address] = {}  # edge -> nbr router id
    for rid, body in rlsa.items():
        u = router_index[rid]
        for link in body.links:
            if link.link_type in (
                RouterLinkType.POINT_TO_POINT,
                RouterLinkType.VIRTUAL_LINK,
            ):
                # Virtual links are router-router edges whose cost is the
                # transit-area distance (§15); for SPF they behave as p2p.
                v = router_index.get(link.id)
                if v is not None:
                    if rid == router_id:
                        if link.link_type == RouterLinkType.VIRTUAL_LINK:
                            root_vlink_edges[len(src)] = link.id
                        else:
                            root_edge_data[len(src)] = link.data
                    src.append(u), dst.append(v), cost.append(link.metric)
            elif link.link_type == RouterLinkType.TRANSIT_NETWORK:
                v = network_index.get(link.id)
                if v is not None:
                    if rid == router_id:
                        root_edge_data[len(src)] = link.data
                    src.append(u), dst.append(v), cost.append(link.metric)
    for dr_addr, body in nlsa.items():
        u = network_index[dr_addr]
        for rid in body.attached:
            v = router_index.get(rid)
            if v is not None:
                src.append(u), dst.append(v), cost.append(0)

    # Mutual-link filter (bidirectionality check, spf.rs:653-664) applied
    # here with index tracking so root-edge link_data survives filtering.
    from holo_tpu.ops.graph import mutual_keep_mask

    keep_mask = mutual_keep_mask(
        np.array(src, np.int32), np.array(dst, np.int32)
    )
    keep = [i for i in range(len(src)) if keep_mask[i]]
    remap = {old: new for new, old in enumerate(keep)}
    root_edge_data = {
        remap[i]: d for i, d in root_edge_data.items() if i in remap
    }
    root_vlink_edges = {
        remap[i]: r for i, r in root_vlink_edges.items() if i in remap
    }
    topo = Topology(
        n_vertices=n,
        is_router=is_router,
        edge_src=np.array([src[i] for i in keep], np.int32).reshape(-1),
        edge_dst=np.array([dst[i] for i in keep], np.int32).reshape(-1),
        edge_cost=np.array([cost[i] for i in keep], np.int32).reshape(-1),
        root=router_index[router_id],
    )

    # Next-hop atoms: edges out of the root, and edges out of root-adjacent
    # transit networks (the hops==0 direct-calculation cases).
    atoms: list[NexthopAtom] = []
    atom_ids = np.full(topo.n_edges, -1, np.int32)
    root = topo.root
    root_nets: set[int] = set()
    self_body = rlsa[router_id]
    # Map vertex index -> transit our-iface (for root->net edges).
    net_if: dict[int, str] = {}
    for link in self_body.links:
        if link.link_type == RouterLinkType.TRANSIT_NETWORK:
            vi = network_index.get(link.id)
            if vi is not None:
                ifname = iface_by_addr.get(link.data)
                if ifname is not None:
                    net_if[vi] = ifname
    for e in range(topo.n_edges):
        if topo.edge_src[e] == root:
            v = int(topo.edge_dst[e])
            if e in root_vlink_edges:
                # Virtual link: next hops borrowed from the transit area's
                # path to the vlink neighbor (§16.1).
                nbr_rid = root_vlink_edges[e]
                expand = (vlink_nexthops or {}).get(nbr_rid)
                if expand:
                    atom_ids[e] = len(atoms)
                    atoms.append(NexthopAtom(None, None, expand))
                continue
            link_data = root_edge_data.get(e)
            if is_router[v]:
                # p2p neighbor: the link's own interface (parallel links
                # each get their own atom), neighbor addr per interface.
                # Unnumbered links carry the MIB ifIndex in link_data
                # (RFC 2328 A.4.2) instead of an address.
                rid = routers[v - len(networks)]
                ifname = (
                    iface_by_addr.get(link_data)
                    if link_data is not None
                    else None
                )
                if (
                    ifname is None
                    and link_data is not None
                    and iface_by_ifindex is not None
                    and int(link_data) < 0x1000000  # 0.x.y.z: never an addr
                ):
                    ifname = iface_by_ifindex.get(int(link_data))
                addr = None
                if ifname is not None and p2p_nbr_addr is not None:
                    addr = p2p_nbr_addr.get((ifname, rid))
                if ifname is not None and addr is not None:
                    atom_ids[e] = len(atoms)
                    atoms.append(NexthopAtom(ifname, addr))
                else:
                    hop = iface_by_nbr.get(rid)
                    if hop is not None:
                        atom_ids[e] = len(atoms)
                        atoms.append(NexthopAtom(hop[0], hop[1]))
            else:
                root_nets.add(v)
                # Directly-attached transit network: next hop is the
                # outgoing interface itself (no gateway address).
                ifname = (
                    iface_by_addr.get(link_data)
                    if link_data is not None
                    else None
                )
                if ifname is not None:
                    atom_ids[e] = len(atoms)
                    atoms.append(NexthopAtom(ifname, None))
        # second pass below needs root_nets complete
    for e in range(topo.n_edges):
        u = int(topo.edge_src[e])
        v = int(topo.edge_dst[e])
        if u in root_nets and is_router[v] and v != root:
            # Destination router's address on that network = the link.data
            # of ITS transit link pointing at this network's DR address.
            rid = routers[v - len(networks)]
            dr_addr = networks[u]
            body = rlsa.get(rid)
            ifname = net_if.get(u)
            if body is None or ifname is None:
                continue
            for link in body.links:
                if (
                    link.link_type == RouterLinkType.TRANSIT_NETWORK
                    and link.id == dr_addr
                ):
                    atom_ids[e] = len(atoms)
                    atoms.append(NexthopAtom(ifname, link.data))
                    break

    topo.edge_direct_atom = atom_ids
    if iface_srlg:
        # Interface fast-reroute SRLG config -> the edge_srlg seam the
        # FRR policy masks consume (srlg_disjoint).
        apply_interface_srlg(
            topo, [a.ifname for a in atoms], iface_srlg
        )
    if partition_of:
        # Hierarchical partition hint (ISSUE 15): per-router group
        # labels (config/topology-design groupings the operator knows —
        # PoPs, rings, sub-area clusters); a transit network rides the
        # lowest-labeled attached router so zero-cost net->rtr edges
        # stay intra-partition wherever the grouping allows.
        groups: list = []
        for dr_addr in networks:
            att = [
                partition_of[r]
                for r in nlsa[dr_addr].attached
                if r in partition_of
            ]
            groups.append(min(att) if att else None)
        for rid in routers:
            groups.append(partition_of.get(rid))
        apply_partition_hint(topo, groups)
    topo.touch()
    return SpfTopology(topo, atoms, router_index, network_index)


def link_spf_delta(
    prev: SpfTopology | None, new: SpfTopology, max_ops: int = 512
) -> bool:
    """DeltaPath construction at the LSDB seam: attach delta lineage to
    ``new`` when it differs from the previous run's marshaled topology
    by a small edge-level change over the SAME vertex model and
    next-hop atom table.  The device-graph cache then updates the
    resident EllGraph in place and the TPU backend recomputes
    incrementally instead of re-marshaling the whole LSDB (ROADMAP
    item 1).  Returns whether lineage was attached; False always means
    the full-rebuild path, never an error."""
    if prev is None:
        return False
    if (
        prev.atoms != new.atoms
        or prev.router_index != new.router_index
        or prev.network_index != new.network_index
    ):
        return False
    from holo_tpu.ops.graph import diff_topologies

    delta = diff_topologies(prev.topo, new.topo, max_ops=max_ops)
    if delta is None:
        return False
    new.topo.link_delta(delta)
    return True


@dataclass(frozen=True)
class RouteNexthop:
    ifname: str
    addr: IPv4Address | None


@dataclass
class IntraRoute:
    prefix: IPv4Network
    dist: int
    nexthops: frozenset[RouteNexthop]
    area_id: IPv4Address
    # "intra" | "inter" | "external-1" | "external-2" | "nssa-1" |
    # "nssa-2" — drives per-type admin distance and maps onto the
    # ietf-ospf route-type enumeration in operational state.
    rtype: str = "intra"
    # SPF vertex the winning path terminates at (-1 when the route was
    # not derived from an SPT vertex, e.g. externals): the IP-FRR
    # consumption key — backup tables are indexed by destination vertex.
    vertex: int = -1
    # IP-FRR repairs attached after the backup-table run:
    # {primary RouteNexthop -> (backup RouteNexthop, label stack)}.
    backups: dict | None = None
    # UCMP weights {RouteNexthop -> saturated shortest-path count}
    # (ISSUE 10): present only when the SPF ran with multipath planes;
    # rides RouteMsg.nh_weights into the RIB's weighted install.
    nh_weights: dict | None = None


def atom_bits(words: np.ndarray, n_atoms: int) -> list[int]:
    """Indices of set bits in an ECMP atom bitmask (uint32 words)."""
    return [
        a
        for a in range(n_atoms)
        if words[a // 32] & (np.uint32(1) << np.uint32(a % 32))
    ]


def _atoms_of(words: np.ndarray, atoms: list[NexthopAtom]) -> frozenset[RouteNexthop]:
    out = set()
    for a in atom_bits(words, len(atoms)):
        atom = atoms[a]
        if atom.expand is not None:
            out |= atom.expand
        else:
            out.add(RouteNexthop(atom.ifname, atom.addr))
    return frozenset(out)


def _atom_weights_of(
    words: np.ndarray, weights_row: np.ndarray, atoms: list[NexthopAtom]
) -> dict:
    """{RouteNexthop -> UCMP weight} for one vertex's next-hop set;
    atoms resolving to the same next hop (or a vlink expansion) sum."""
    out: dict = {}
    for a in atom_bits(words, len(atoms)):
        atom = atoms[a]
        w = int(weights_row[a]) if a < len(weights_row) else 0
        targets = (
            atom.expand
            if atom.expand is not None
            else (RouteNexthop(atom.ifname, atom.addr),)
        )
        for nh in targets:
            out[nh] = out.get(nh, 0) + w
    return out


def _nh_rank(nh, weights: dict):
    """Deterministic multipath clamp order: UCMP weight descending,
    then lowest next-hop address (the reference's ECMP clamp key),
    then interface name."""
    return (
        -weights.get(nh, 1),
        nh.addr is None,
        nh.addr.packed if nh.addr is not None else b"",
        nh.ifname or "",
    )


def clamp_multipath(routes: dict, max_paths: int | None) -> int:
    """Truncate every route's ECMP set to ``max_paths`` next hops (the
    OSPF ``max-paths`` seam), keeping the highest-weight paths; weights
    dicts are filtered to the survivors.  Returns routes clamped."""
    if not max_paths or max_paths < 1:
        return 0
    clamped = 0
    for route in routes.values():
        if len(route.nexthops) <= max_paths:
            continue
        w = route.nh_weights or {}
        ranked = sorted(route.nexthops, key=lambda nh: _nh_rank(nh, w))
        keep = frozenset(ranked[:max_paths])
        route.nexthops = keep
        if route.nh_weights:
            route.nh_weights = {
                nh: ww for nh, ww in route.nh_weights.items() if nh in keep
            }
        clamped += 1
    return clamped


def derive_routes(
    st: SpfTopology,
    res: SpfResult,
    lsdb: Lsdb,
    now: float,
    area_id: IPv4Address,
    max_paths: int | None = None,
) -> dict[IPv4Network, IntraRoute]:
    """Intra-area routes from SPF results (RFC 2328 §16.1 steps 2-4).

    Transit networks yield their prefix at the network vertex's distance;
    router stub links yield prefix routes at dist(router)+metric.  Equal
    cost contributions union their next-hop sets; the root's own stubs
    are local (empty next-hop set).  Address-less next-hops (interface
    only) mean DIRECTLY ATTACHED (reference route.rs:96): they render in
    operational state but are never installed to the RIB — the connected
    route owns the FIB entry (see OspfInstance._sync_rib).
    """
    routes: dict[IPv4Network, IntraRoute] = {}

    def offer(prefix, dist, nhs, vertex=-1, weights=None):
        cur = routes.get(prefix)
        if cur is None or dist < cur.dist:
            routes[prefix] = IntraRoute(
                prefix, dist, nhs, area_id, vertex=vertex,
                nh_weights=dict(weights) if weights else None,
            )
        elif dist == cur.dist:
            # Equal-cost contributions union next hops; the first
            # contributing vertex keeps the FRR consumption key (its
            # backup covers the merged set's shared failure domain only
            # approximately, matching the reference's per-route pick).
            merged = None
            if cur.nh_weights or weights:
                merged = dict(cur.nh_weights or {})
                for nh, w in (weights or {}).items():
                    merged[nh] = merged.get(nh, 0) + w
            routes[prefix] = IntraRoute(
                prefix, dist, cur.nexthops | nhs, area_id,
                vertex=cur.vertex, nh_weights=merged,
            )

    inv_net = {i: a for a, i in st.network_index.items()}
    inv_rtr = {i: r for r, i in st.router_index.items()}
    nlsa = {}
    rlsa = {}
    for e in lsdb.all():
        if e.current_age(now) >= 3600:
            continue
        if e.lsa.type == LsaType.NETWORK:
            nlsa[e.lsa.lsid] = e.lsa.body
        elif e.lsa.type == LsaType.ROUTER:
            rlsa[e.lsa.adv_rtr] = e.lsa.body

    # Per-vertex UCMP weights ride the multipath planes when the
    # dispatch carried them (max-paths > 1 → multipath kernel).
    nhw = getattr(res, "nh_weights", None)
    n = st.topo.n_vertices
    for v in range(n):
        if res.dist[v] >= INF:
            continue
        nhs = _atoms_of(res.nexthop_words[v], st.atoms)
        weights = (
            _atom_weights_of(res.nexthop_words[v], nhw[v], st.atoms)
            if nhw is not None
            else None
        )
        if v in inv_net:
            body = nlsa.get(inv_net[v])
            if body is None:
                continue
            prefix = apply_mask(inv_net[v], body.mask)
            offer(prefix, int(res.dist[v]), nhs, vertex=v, weights=weights)
        else:
            body = rlsa.get(inv_rtr[v])
            if body is None:
                continue
            for link in body.links:
                if link.link_type == RouterLinkType.STUB_NETWORK:
                    prefix = apply_mask(link.id, link.data)
                    offer(
                        prefix, int(res.dist[v]) + link.metric, nhs,
                        vertex=v, weights=weights,
                    )
    clamp_multipath(routes, max_paths)
    return routes


def attach_frr_backups(
    st: SpfTopology,
    res: SpfResult,
    routes: dict,
    table,
    cfg,
    label_of_vertex=None,
    area_id=None,
) -> int:
    """Attach precomputed repairs to routes derived from ``st``/``res``.

    For every route whose winning path ends at an SPT vertex, each
    primary next-hop atom maps (via the backup table's ``atom_link``) to
    its protected link, and ``resolve_backup`` picks the repair.  Direct
    LFAs attach as plain next hops; remote-LFA / TI-LFA repairs need a
    tunnel to their release vertex, so they attach only when
    ``label_of_vertex`` resolves a segment (node-SID label) for every
    repair vertex — without SR there is no loop-free encapsulation and
    the destination stays unprotected (RFC 7490 §2 applies).  Returns
    the number of routes that gained at least one backup."""
    from holo_tpu.frr.manager import repair_map

    n = st.topo.n_vertices
    attached = 0
    # All prefixes terminating at the same SPT vertex share one repair
    # map — memoize per vertex (O(reachable vertices), not O(routes)).
    memo: dict[int, dict] = {}
    for route in routes.values():
        if area_id is not None and route.area_id != area_id:
            continue
        if not cfg.protects_prefix(route.prefix):
            continue  # per-prefix protection filtering (policy scope)
        v = getattr(route, "vertex", -1)
        if v < 0 or v >= n:
            continue
        repairs = memo.get(v)
        if repairs is None:
            repairs = memo[v] = repair_map(
                table, cfg, res.nexthop_words[v], v
            )
        backups = {}
        for a, entry in repairs.items():
            atom = st.atoms[a]
            batom = st.atoms[entry.atom]
            if atom.expand is not None or batom.expand is not None:
                continue  # vlink bundles have no single protected link
            labels: tuple = ()
            if entry.kind != "lfa":
                if label_of_vertex is None:
                    continue
                resolved = [label_of_vertex(p) for p in entry.via]
                if any(l is None for l in resolved):
                    continue
                labels = tuple(resolved)
            backups[RouteNexthop(atom.ifname, atom.addr)] = (
                RouteNexthop(batom.ifname, batom.addr),
                labels,
            )
        if backups:
            route.backups = backups
            attached += 1
    return attached

"""YANG-modeled OSPFv3 operational state.

Renders a live :class:`OspfV3Instance` into the ietf-ospf state tree —
the shape the reference serves and records in its v3 conformance
snapshots (holo-ospf/src/northbound/state.rs; corpus:
holo-ospf/tests/conformance/ospfv3/**/northbound-state.json).  Volatile
leaves the reference marks ``ignore_in_testing`` (ages, seqnos,
checksums, timestamps) are omitted, matching the recorded trees.

Empty lists/containers are dropped, mirroring the reference's JSON
printer.
"""

from __future__ import annotations

from ipaddress import IPv4Address

from holo_tpu.protocols.ospf import packet_v3 as P
from holo_tpu.protocols.ospf.interface import IfType
from holo_tpu.protocols.ospf.neighbor import NsmState
from holo_tpu.protocols.ospf.packet import (
    RI_CAP_GR_CAPABLE,
    RI_CAP_GR_HELPER,
    RI_CAP_STUB_ROUTER,
    decode_router_info,
)

LSA_TYPE_NAME = {
    P.LsaType.ROUTER: "ospfv3-router-lsa",
    P.LsaType.NETWORK: "ospfv3-network-lsa",
    P.LsaType.INTER_AREA_PREFIX: "ospfv3-inter-area-prefix-lsa",
    P.LsaType.INTER_AREA_ROUTER: "ospfv3-inter-area-router-lsa",
    P.LsaType.AS_EXTERNAL: "ospfv3-external-lsa-type",
    P.LsaType.LINK: "ospfv3-link-lsa",
    P.LsaType.INTRA_AREA_PREFIX: "ospfv3-intra-area-prefix-lsa",
    P.LsaType.ROUTER_INFORMATION: "ospfv3-router-information-lsa",
}

_LSA_OPTION_BITS = [
    (P.Options.V6, "v6-bit"),
    (P.Options.E, "e-bit"),
    (P.Options.DC, "dc-bit"),
    (P.Options.R, "r-bit"),
    (P.Options.AF, "af-bit"),
]

_PREFIX_OPTION_BITS = [
    (0x01, "nu-bit"),
    (P.PREFIX_OPT_LA, "la-bit"),
    (0x08, "p-bit"),
    (0x10, "dn-bit"),
]

_ROUTER_LINK_TYPE = {
    P.RouterLinkType.POINT_TO_POINT: "point-to-point-link",
    P.RouterLinkType.TRANSIT_NETWORK: "transit-network-link",
    P.RouterLinkType.VIRTUAL_LINK: "virtual-link",
}

_RI_CAP_BITS = [
    (RI_CAP_GR_CAPABLE, "graceful-restart"),
    (RI_CAP_GR_HELPER, "graceful-restart-helper"),
    (RI_CAP_STUB_ROUTER, "stub-router"),
]


def _a(x) -> str:
    return str(x)


def _bits(value, table) -> list[str]:
    return [name for bit, name in table if int(value) & int(bit)]


def _lsa_options(value) -> dict:
    return {"lsa-options": _bits(value, _LSA_OPTION_BITS)}


def _prefix_options(value) -> dict:
    return {"prefix-options": _bits(value, _PREFIX_OPTION_BITS)}


def lsa_header_yang(lsa: P.Lsa) -> dict:
    return {
        "lsa-id": int(lsa.lsid),
        "type": LSA_TYPE_NAME.get(
            lsa.type, "ospfv3-unknown-lsa-type"
        ),
        "adv-router": _a(lsa.adv_rtr),
        "length": lsa.length or len(lsa.raw),
    }


def _ri_body_yang(lsa: P.Lsa) -> dict:
    info = decode_router_info(lsa.body.data)
    caps = info.get("info_caps", 0)
    out: dict = {
        "router-capabilities-tlv": {
            "router-informational-capabilities": {
                "informational-capabilities": _bits(caps, _RI_CAP_BITS)
            },
            "informational-capabilities-flags": [
                {"informational-flag": int(bit)}
                for bit, _name in _RI_CAP_BITS
                if caps & bit
            ],
        }
    }
    return {"router-information": out}


def lsa_body_yang(lsa: P.Lsa) -> dict:
    body = lsa.body
    t = lsa.type
    if t == P.LsaType.ROUTER:
        out: dict = {}
        bits = []
        if body.flags & P.RouterFlags.B:
            bits.append("abr-bit")
        if body.flags & P.RouterFlags.E:
            bits.append("asbr-bit")
        if body.flags & P.RouterFlags.V:
            bits.append("vlink-end-bit")
        if bits:
            out["router-bits"] = {"rtr-lsa-bits": bits}
        out["lsa-options"] = _lsa_options(body.options)
        links = [
            {
                "interface-id": l.iface_id,
                "neighbor-interface-id": l.nbr_iface_id,
                "neighbor-router-id": _a(l.nbr_router_id),
                "type": _ROUTER_LINK_TYPE.get(l.link_type, "unknown"),
                "metric": l.metric,
            }
            for l in body.links
        ]
        if links:
            out["links"] = {"link": links}
        return {"router": out}
    if t == P.LsaType.NETWORK:
        return {
            "network": {
                "lsa-options": _lsa_options(body.options),
                "attached-routers": {
                    "attached-router": [_a(r) for r in body.attached]
                },
            }
        }
    if t == P.LsaType.INTER_AREA_PREFIX:
        out = {"metric": body.metric, "prefix": str(body.prefix)}
        if body.prefix_options:
            out["prefix-options"] = _prefix_options(body.prefix_options)
        return {"inter-area-prefix": out}
    if t == P.LsaType.INTER_AREA_ROUTER:
        return {
            "inter-area-router": {
                "lsa-options": _lsa_options(body.options),
                "metric": body.metric,
                "destination-router-id": _a(body.dest_router_id),
            }
        }
    if t == P.LsaType.AS_EXTERNAL:
        return {
            "as-external": {
                "metric": body.metric,
                "flags": {"ospfv3-e-external-prefix-flags": (
                    ["e-bit"] if body.e_bit else []
                )},
                "prefix": str(body.prefix),
            }
        }
    if t == P.LsaType.LINK:
        prefixes = [{"prefix": str(p)} for p in body.prefixes]
        out = {
            "rtr-priority": body.priority,
            "lsa-options": _lsa_options(body.options),
            "link-local-interface-address": str(body.link_local),
            "num-of-prefixes": len(prefixes),
        }
        if prefixes:
            out["prefixes"] = {"prefix": prefixes}
        return {"link": out}
    if t == P.LsaType.INTRA_AREA_PREFIX:
        prefixes = []
        for entry in body.prefixes:
            prefix, metric = entry[0], entry[1]
            opts = body.entry_opts(entry)
            p: dict = {"prefix": str(prefix)}
            if opts:
                p["prefix-options"] = _prefix_options(opts)
            p["metric"] = metric
            prefixes.append(p)
        out = {
            "referenced-ls-type": LSA_TYPE_NAME.get(
                P.LsaType(body.ref_type), "ospfv3-unknown-lsa-type"
            ),
            "referenced-link-state-id": int(body.ref_lsid),
            "referenced-adv-router": _a(body.ref_adv_rtr),
            "num-of-prefixes": len(prefixes),
        }
        if prefixes:
            out["prefixes"] = {"prefix": prefixes}
        return {"intra-area-prefix": out}
    if t == P.LsaType.ROUTER_INFORMATION:
        return _ri_body_yang(lsa)
    return {}


def render_lsa(lsa: P.Lsa) -> dict:
    return {
        "lsa-id": _a(lsa.lsid),
        "adv-router": _a(lsa.adv_rtr),
        "decode-completed": True,
        "ospfv3": {
            "header": lsa_header_yang(lsa),
            "body": lsa_body_yang(lsa),
        },
    }


def _db_buckets(entries, kind: str) -> tuple[list, list]:
    """(full database buckets, statistics buckets) per 16-bit LSA type."""
    by_type: dict[int, list] = {}
    for e in entries:
        by_type.setdefault(int(e.lsa.type), []).append(e.lsa)
    full, stats = [], []
    for ltype in sorted(by_type):
        lsas = sorted(
            by_type[ltype], key=lambda l: (int(l.adv_rtr), int(l.lsid))
        )
        full.append(
            {
                "lsa-type": ltype,
                f"{kind}-scope-lsas": {
                    f"{kind}-scope-lsa": [render_lsa(l) for l in lsas]
                },
            }
        )
        stats.append({"lsa-type": ltype, "lsa-count": len(lsas)})
    return full, stats


_ISM_NAME = {
    "down": "down",
    "loopback": "loopback",
    "waiting": "waiting",
    "point-to-point": "point-to-point",
    "dr-other": "dr-other",
    "bdr": "bdr",
    "dr": "dr",
}

_NSM_NAME = {
    NsmState.DOWN: "down",
    NsmState.INIT: "init",
    NsmState.TWO_WAY: "2-way",
    NsmState.EX_START: "exstart",
    NsmState.EXCHANGE: "exchange",
    NsmState.LOADING: "loading",
    NsmState.FULL: "full",
}


def _iface_state_name(inst, iface) -> str:
    if not iface.up:
        return "down"
    if getattr(iface.config, "loopback", False):
        return "loopback"
    if iface.config.if_type == IfType.POINT_TO_POINT:
        return "point-to-point"
    if iface.dr == inst.router_id:
        return "dr"
    if iface.bdr == inst.router_id:
        return "bdr"
    return "dr-other"


def _addr_of(inst, iface, rid):
    if rid == inst.router_id:
        return str(iface.link_local)
    for nbr in iface.neighbors.values():
        if nbr.router_id == rid:
            return str(nbr.src)
    return None


def _dr_bdr_leaves(inst, iface) -> dict:
    out: dict = {}
    if int(iface.dr):
        out["dr-router-id"] = _a(iface.dr)
        addr = _addr_of(inst, iface, iface.dr)
        if addr:
            out["dr-ip-addr"] = addr
    if int(iface.bdr):
        out["bdr-router-id"] = _a(iface.bdr)
        addr = _addr_of(inst, iface, iface.bdr)
        if addr:
            out["bdr-ip-addr"] = addr
    return out


def _iface_yang(inst, iface, link_entries) -> dict:
    out: dict = {
        "name": iface.name,
        "state": _iface_state_name(inst, iface),
    }
    if iface.is_lan:
        out.update(_dr_bdr_leaves(inst, iface))
    full, stats = _db_buckets(link_entries, "link")
    out["statistics"] = {
        "link-scope-lsa-count": sum(s["lsa-count"] for s in stats),
    }
    if stats:
        out["statistics"]["database"] = {"link-scope-lsa-type": stats}
    nbrs = []
    for rid, nbr in sorted(iface.neighbors.items(), key=lambda kv: int(kv[0])):
        n: dict = {
            "neighbor-router-id": _a(rid),
            "address": str(nbr.src),
        }
        if iface.is_lan:
            n.update(_dr_bdr_leaves(inst, iface))
        n["state"] = _NSM_NAME.get(nbr.state, "down")
        n["statistics"] = {"nbr-retrans-qlen": 0}
        nbrs.append(n)
    if nbrs:
        out["neighbors"] = {"neighbor": nbrs}
    if full:
        out["database"] = {"link-scope-lsa-type": full}
    out["interface-id"] = iface.iface_id
    return out


def instance_state(inst) -> dict:
    """The ietf-ospf:ospf state subtree for one OSPFv3 instance."""
    out: dict = {
        "spf-control": {"ietf-spf-delay": {"current-state": "quiet"}},
        "router-id": _a(inst.router_id),
    }

    routes = []
    for prefix in sorted(
        inst.routes,
        key=lambda p: (int(p.network_address), p.prefixlen),
    ):
        r = inst.routes[prefix]
        row: dict = {"prefix": str(prefix)}
        nhs = []
        for ifn, addr in sorted(
            r.nexthops,
            key=lambda t: (t[0], int(t[1]) if t[1] else 0),
        ):
            nh = {"outgoing-interface": ifn}
            if addr is not None:
                nh["next-hop"] = str(addr)
            nhs.append(nh)
        if nhs:
            row["next-hops"] = {"next-hop": nhs}
        row["metric"] = r.dist
        row["route-type"] = r.route_type
        routes.append(row)
    if routes:
        out["local-rib"] = {"route": routes}

    out["statistics"] = {"as-scope-lsa-count": 0}

    areas = []
    for aid in sorted(inst.areas, key=int):
        area = inst.areas[aid]
        entries = list(area.lsdb.all())
        full, stats = _db_buckets(entries, "area")
        abr = sum(
            1
            for e in entries
            if e.lsa.type == P.LsaType.ROUTER
            and e.lsa.body.flags & P.RouterFlags.B
        )
        asbr = sum(
            1
            for e in entries
            if e.lsa.type == P.LsaType.ROUTER
            and e.lsa.body.flags & P.RouterFlags.E
        )
        a: dict = {
            "area-id": _a(aid),
            "statistics": {
                "abr-count": abr,
                "asbr-count": asbr,
                "area-scope-lsa-count": sum(s["lsa-count"] for s in stats),
            },
        }
        if stats:
            a["statistics"]["database"] = {"area-scope-lsa-type": stats}
        if int(aid) == 0 and getattr(inst, "vlink_state", None):
            a["virtual-links"] = {
                "virtual-link": [
                    {
                        "transit-area-id": _a(v["transit_area_id"]),
                        "router-id": _a(v["router_id"]),
                        "cost": v["cost"],
                        "state": "point-to-point",
                        "statistics": {"link-scope-lsa-count": 0},
                        "neighbors": {
                            "neighbor": [
                                {
                                    "neighbor-router-id": _a(
                                        v["router_id"]
                                    ),
                                    **(
                                        {"address": _a(v["address"])}
                                        if v["address"] is not None
                                        else {}
                                    ),
                                    "state": "full",
                                    "statistics": {
                                        "nbr-retrans-qlen": 0
                                    },
                                }
                            ]
                        },
                    }
                    for v in inst.vlink_state
                ]
            }
        if full:
            a["database"] = {"area-scope-lsa-type": full}
        ifaces = []
        for iface in sorted(inst.interfaces.values(), key=lambda i: i.name):
            if inst._area_of(iface) is not area:
                continue
            ifaces.append(
                _iface_yang(inst, iface, list(iface.link_lsdb.all()))
            )
        if ifaces:
            a["interfaces"] = {"interface": ifaces}
        areas.append(a)
    if areas:
        out["areas"] = {"area": areas}
    return out

"""OSPFv3 packet and LSA codecs (RFC 5340 §A).

Mirrors the API of packet.py (the v2 codecs) so the instance machinery can
be parameterized over the version — the analog of the reference's
``Version`` trait split (holo-ospf/src/version.rs:27-54).

v3 specifics: 16-byte header with instance id and a checksum over an IPv6
pseudo-header; options are 24-bit; DR/BDR are router-ids; LSA types are
16-bit with flooding-scope bits; prefixes encode as (len, options,
truncated address).
"""

from __future__ import annotations

import enum
import struct
from dataclasses import dataclass, field
from ipaddress import IPv4Address, IPv6Address, IPv6Network

from holo_tpu.utils.bytesbuf import (
    DecodeError,
    Reader,
    Writer,
    fletcher16_checksum,
    fletcher16_verify,
)

OSPF_VERSION = 3
PKT_HDR_LEN = 16
LSA_HDR_LEN = 20
MAX_AGE = 3600
LS_REFRESH_TIME = 1800
MAX_AGE_DIFF = 900
INITIAL_SEQ_NO = -0x7FFFFFFF
MAX_SEQ_NO = 0x7FFFFFFF


class PacketType(enum.IntEnum):
    HELLO = 1
    DB_DESC = 2
    LS_REQUEST = 3
    LS_UPDATE = 4
    LS_ACK = 5


class LsaType(enum.IntEnum):
    """Function codes with flooding scope (RFC 5340 §A.4.2.1)."""

    ROUTER = 0x2001
    NETWORK = 0x2002
    INTER_AREA_PREFIX = 0x2003
    INTER_AREA_ROUTER = 0x2004
    AS_EXTERNAL = 0x4005
    LINK = 0x0008
    INTRA_AREA_PREFIX = 0x2009
    # RFC 7770 Router Information, area scope (function code 12).
    ROUTER_INFORMATION = 0xA00C

    # aliases used by the version-generic machinery:
    SUMMARY_NETWORK = 0x2003


def scope_of(ltype: int) -> str:
    s = (ltype >> 13) & 0x3
    return {0: "link", 1: "area", 2: "as"}.get(s, "reserved")


class Options(enum.IntFlag):
    V6 = 0x01
    E = 0x02
    R = 0x10
    DC = 0x20
    AF = 0x0100  # RFC 5838 address-family capability


# RFC 5340 §A.4.1.1 prefix options.
PREFIX_OPT_LA = 0x02  # local address (host prefixes)


class RouterLinkType(enum.IntEnum):
    POINT_TO_POINT = 1
    TRANSIT_NETWORK = 2
    VIRTUAL_LINK = 4


class RouterFlags(enum.IntFlag):
    B = 0x01
    E = 0x02
    V = 0x04


@dataclass(frozen=True)
class RouterLinkV3:
    link_type: RouterLinkType
    metric: int
    iface_id: int
    nbr_iface_id: int
    nbr_router_id: IPv4Address


@dataclass
class LsaRouterV3:
    flags: RouterFlags = RouterFlags(0)
    options: Options = Options.V6 | Options.E | Options.R | Options.AF
    links: list[RouterLinkV3] = field(default_factory=list)

    def encode(self, w: Writer) -> None:
        w.u8(int(self.flags)).u24(int(self.options))
        for l in self.links:
            w.u8(int(l.link_type)).u8(0).u16(l.metric)
            w.u32(l.iface_id).u32(l.nbr_iface_id)
            w.ipv4(l.nbr_router_id)

    @classmethod
    def decode(cls, r: Reader) -> "LsaRouterV3":
        flags = RouterFlags(r.u8() & 0x07)
        options = Options(r.u24())
        links = []
        while r.remaining() >= 16:
            try:
                lt = RouterLinkType(r.u8())
            except ValueError as e:
                raise DecodeError("bad v3 router link type") from e
            r.u8()
            metric = r.u16()
            links.append(
                RouterLinkV3(lt, metric, r.u32(), r.u32(), r.ipv4())
            )
        return cls(flags, options, links)


@dataclass
class LsaNetworkV3:
    options: Options = Options.V6 | Options.E | Options.R | Options.AF
    attached: list[IPv4Address] = field(default_factory=list)

    def encode(self, w: Writer) -> None:
        w.u8(0).u24(int(self.options))
        for a in self.attached:
            w.ipv4(a)

    @classmethod
    def decode(cls, r: Reader) -> "LsaNetworkV3":
        r.u8()
        options = Options(r.u24())
        attached = []
        while r.remaining() >= 4:
            attached.append(r.ipv4())
        return cls(options, attached)


def _encode_prefix(w: Writer, prefix: IPv6Network, options: int = 0, metric: int | None = None) -> None:
    w.u8(prefix.prefixlen).u8(options)
    if metric is None:
        w.u16(0)
    else:
        w.u16(metric)
    nbytes = (prefix.prefixlen + 31) // 32 * 4
    w.bytes(prefix.network_address.packed[:nbytes])


def _decode_prefix(r: Reader) -> tuple[IPv6Network, int, int]:
    plen = r.u8()
    opts = r.u8()
    metric = r.u16()
    if plen > 128:
        raise DecodeError("bad v6 prefix length")
    nbytes = (plen + 31) // 32 * 4
    raw = r.bytes(nbytes) + bytes(16 - nbytes)
    # Mask stray host bits (strict construction would raise ValueError on
    # hostile padding, violating the decoder contract).
    val = int.from_bytes(raw, "big")
    if plen < 128:
        val &= ~((1 << (128 - plen)) - 1)
    return IPv6Network((val, plen)), opts, metric


@dataclass
class LsaInterAreaPrefix:
    metric: int = 0
    prefix: IPv6Network = IPv6Network("::/0")
    # Propagated prefix options (the reference carries the summarized
    # intra prefix's LA bit through its inter-area advertisement).
    prefix_options: int = 0

    def encode(self, w: Writer) -> None:
        w.u32(self.metric & 0xFFFFFF)
        _encode_prefix(w, self.prefix, options=self.prefix_options)

    @classmethod
    def decode(cls, r: Reader) -> "LsaInterAreaPrefix":
        metric = r.u32() & 0xFFFFFF
        prefix, opts, _ = _decode_prefix(r)
        return cls(metric, prefix, opts)

    # duck-type v2 LsaSummary for the generic ABR machinery
    @property
    def mask(self):
        return self.prefix


@dataclass
class LsaInterAreaRouter:
    """RFC 5340 §A.4.6: ABR-advertised reachability to an ASBR."""

    options: Options = Options.V6 | Options.E | Options.R | Options.AF
    metric: int = 0
    dest_router_id: IPv4Address = IPv4Address(0)

    def encode(self, w: Writer) -> None:
        w.u8(0).u24(int(self.options))
        w.u32(self.metric & 0xFFFFFF)
        w.ipv4(self.dest_router_id)

    @classmethod
    def decode(cls, r: Reader) -> "LsaInterAreaRouter":
        r.u8()
        options = Options(r.u24())
        metric = r.u32() & 0xFFFFFF
        dest = r.ipv4()
        return cls(options, metric, dest)


@dataclass
class LsaLink:
    priority: int = 1
    options: Options = Options.V6 | Options.E | Options.R | Options.AF
    link_local: IPv6Address = IPv6Address("fe80::1")
    prefixes: list[IPv6Network] = field(default_factory=list)

    def encode(self, w: Writer) -> None:
        w.u8(self.priority).u24(int(self.options))
        w.ipv6(self.link_local)
        w.u32(len(self.prefixes))
        for p in self.prefixes:
            _encode_prefix(w, p)

    @classmethod
    def decode(cls, r: Reader) -> "LsaLink":
        prio = r.u8()
        options = Options(r.u24())
        ll = r.ipv6()
        n = r.u32()
        prefixes = []
        for _ in range(n):
            p, _, _ = _decode_prefix(r)
            prefixes.append(p)
        return cls(prio, options, ll, prefixes)


@dataclass
class LsaIntraAreaPrefix:
    """Prefixes attached to a router/network vertex (RFC 5340 §A.4.10).

    ``prefixes`` entries are (prefix, metric) or (prefix, metric,
    prefix-options) — the 2-tuple form implies options 0, so existing
    builders keep working while decode preserves the received bits
    (LA etc.) for state rendering.
    """

    ref_type: int = 0x2001
    ref_lsid: IPv4Address = IPv4Address(0)
    ref_adv_rtr: IPv4Address = IPv4Address(0)
    prefixes: list[tuple] = field(default_factory=list)

    @staticmethod
    def entry_opts(entry: tuple) -> int:
        return entry[2] if len(entry) > 2 else 0

    def encode(self, w: Writer) -> None:
        w.u16(len(self.prefixes)).u16(self.ref_type)
        w.ipv4(self.ref_lsid).ipv4(self.ref_adv_rtr)
        for entry in self.prefixes:
            prefix, metric = entry[0], entry[1]
            _encode_prefix(
                w, prefix, options=self.entry_opts(entry), metric=metric
            )

    @classmethod
    def decode(cls, r: Reader) -> "LsaIntraAreaPrefix":
        n = r.u16()
        ref_type = r.u16()
        ref_lsid, ref_adv = r.ipv4(), r.ipv4()
        prefixes = []
        for _ in range(n):
            p, opts, metric = _decode_prefix(r)
            prefixes.append((p, metric, opts))
        return cls(ref_type, ref_lsid, ref_adv, prefixes)


@dataclass
class LsaAsExternalV3:
    metric: int = 0
    e_bit: bool = True
    prefix: IPv6Network = IPv6Network("::/0")

    def encode(self, w: Writer) -> None:
        w.u32((0x04000000 if self.e_bit else 0) | (self.metric & 0xFFFFFF))
        _encode_prefix(w, self.prefix)

    @classmethod
    def decode(cls, r: Reader) -> "LsaAsExternalV3":
        word = r.u32()
        prefix, _, _ = _decode_prefix(r)
        return cls(word & 0xFFFFFF, bool(word & 0x04000000), prefix)


@dataclass
class LsaRawBody:
    """Opaque body for types we flood but do not interpret (e.g.
    Inter-Area-Router until ASBR support lands)."""

    data: bytes = b""

    def encode(self, w: Writer) -> None:
        w.bytes(self.data)

    @classmethod
    def decode(cls, r: Reader) -> "LsaRawBody":
        return cls(r.rest())


_BODY_CODECS = {
    LsaType.ROUTER: LsaRouterV3,
    LsaType.NETWORK: LsaNetworkV3,
    LsaType.INTER_AREA_PREFIX: LsaInterAreaPrefix,
    LsaType.INTER_AREA_ROUTER: LsaInterAreaRouter,
    LsaType.LINK: LsaLink,
    LsaType.INTRA_AREA_PREFIX: LsaIntraAreaPrefix,
    LsaType.AS_EXTERNAL: LsaAsExternalV3,
    # RFC 7770 RI: same TLV wire format as v2's opaque RI — carried raw
    # and parsed by the shared TLV decoder at state-render time.
    LsaType.ROUTER_INFORMATION: LsaRawBody,
}


@dataclass(frozen=True)
class LsaKey:
    type: LsaType
    lsid: IPv4Address
    adv_rtr: IPv4Address


@dataclass
class Lsa:
    """v3 LSA: same header geometry as v2 with 16-bit type."""

    age: int
    type: LsaType
    lsid: IPv4Address
    adv_rtr: IPv4Address
    seq_no: int
    body: object
    cksum: int = 0
    length: int = 0
    raw: bytes = b""
    options: int = 0  # kept for interface parity with v2 (unused in v3 hdr)

    @property
    def key(self) -> LsaKey:
        return LsaKey(self.type, self.lsid, self.adv_rtr)

    @property
    def is_maxage(self) -> bool:
        return self.age >= MAX_AGE

    def encode(self) -> bytes:
        w = Writer()
        w.u16(self.age).u16(int(self.type))
        w.ipv4(self.lsid).ipv4(self.adv_rtr)
        w.u32(self.seq_no & 0xFFFFFFFF)
        w.u16(0).u16(0)
        self.body.encode(w)
        w.patch_u16(18, len(w))
        self.length = len(w)
        cks = fletcher16_checksum(bytes(w.buf[2:]), 14)
        w.patch_u16(16, cks)
        self.cksum = cks
        self.raw = w.finish()
        return self.raw

    @classmethod
    def decode(cls, r: Reader) -> "Lsa":
        start = r.pos
        if r.remaining() < LSA_HDR_LEN:
            raise DecodeError("short LSA header")
        age = r.u16()
        try:
            ltype = LsaType(r.u16())
        except ValueError as e:
            raise DecodeError("unknown v3 LSA type") from e
        lsid, adv = r.ipv4(), r.ipv4()
        seq = r.u32()
        if seq & 0x80000000:
            seq -= 1 << 32
        cksum = r.u16()
        length = r.u16()
        if length < LSA_HDR_LEN:
            raise DecodeError("bad LSA length")
        body_len = length - LSA_HDR_LEN
        if r.remaining() < body_len:
            raise DecodeError("LSA length exceeds buffer")
        raw = r.data[start : start + length]
        if not fletcher16_verify(raw[2:]):
            raise DecodeError("LSA checksum mismatch")
        body = _BODY_CODECS[ltype].decode(r.sub(body_len))
        return cls(age, ltype, lsid, adv, seq, body, cksum, length, raw)

    @classmethod
    def decode_header(cls, r: Reader) -> "Lsa":
        age = r.u16()
        try:
            ltype = LsaType(r.u16())
        except ValueError as e:
            raise DecodeError("unknown v3 LSA type") from e
        lsid, adv = r.ipv4(), r.ipv4()
        seq = r.u32()
        if seq & 0x80000000:
            seq -= 1 << 32
        return cls(age, ltype, lsid, adv, seq, None, r.u16(), r.u16())

    def encode_header(self, w: Writer) -> None:
        w.u16(self.age).u16(int(self.type))
        w.ipv4(self.lsid).ipv4(self.adv_rtr).u32(self.seq_no & 0xFFFFFFFF)
        w.u16(self.cksum).u16(self.length)

    def compare(self, other: "Lsa") -> int:
        if self.seq_no != other.seq_no:
            return 1 if self.seq_no > other.seq_no else -1
        if self.cksum != other.cksum:
            return 1 if self.cksum > other.cksum else -1
        if self.is_maxage != other.is_maxage:
            return 1 if self.is_maxage else -1
        if abs(self.age - other.age) > MAX_AGE_DIFF:
            return 1 if self.age < other.age else -1
        return 0


# ===== packet bodies (same shapes as v2 where possible) =====


@dataclass
class Hello:
    iface_id: int
    priority: int
    options: Options
    hello_interval: int
    dead_interval: int
    dr: IPv4Address  # router-id of DR (not an address, unlike v2)
    bdr: IPv4Address
    neighbors: list[IPv4Address] = field(default_factory=list)

    TYPE = PacketType.HELLO

    def encode_body(self, w: Writer) -> None:
        w.u32(self.iface_id)
        w.u8(self.priority).u24(int(self.options))
        w.u16(self.hello_interval).u16(self.dead_interval)
        w.ipv4(self.dr).ipv4(self.bdr)
        for n in self.neighbors:
            w.ipv4(n)

    @classmethod
    def decode_body(cls, r: Reader) -> "Hello":
        iface_id = r.u32()
        prio = r.u8()
        options = Options(r.u24())
        hi, di = r.u16(), r.u16()
        dr, bdr = r.ipv4(), r.ipv4()
        nbrs = []
        while r.remaining() >= 4:
            nbrs.append(r.ipv4())
        return cls(iface_id, prio, options, hi, di, dr, bdr, nbrs)


class DbDescFlags(enum.IntFlag):
    MS = 0x01
    M = 0x02
    I = 0x04


@dataclass
class DbDesc:
    mtu: int
    options: Options
    flags: DbDescFlags
    dd_seq_no: int
    lsa_headers: list[Lsa] = field(default_factory=list)

    TYPE = PacketType.DB_DESC

    def encode_body(self, w: Writer) -> None:
        w.u8(0).u24(int(self.options))
        w.u16(self.mtu).u8(0).u8(int(self.flags))
        w.u32(self.dd_seq_no)
        for h in self.lsa_headers:
            h.encode_header(w)

    @classmethod
    def decode_body(cls, r: Reader) -> "DbDesc":
        r.u8()
        options = Options(r.u24())
        mtu = r.u16()
        r.u8()
        flags = DbDescFlags(r.u8() & 0x07)
        seq = r.u32()
        hdrs = []
        while r.remaining() >= LSA_HDR_LEN:
            hdrs.append(Lsa.decode_header(r))
        return cls(mtu, options, flags, seq, hdrs)


@dataclass
class LsRequest:
    entries: list[LsaKey] = field(default_factory=list)

    TYPE = PacketType.LS_REQUEST

    def encode_body(self, w: Writer) -> None:
        for k in self.entries:
            w.u16(0).u16(int(k.type)).ipv4(k.lsid).ipv4(k.adv_rtr)

    @classmethod
    def decode_body(cls, r: Reader) -> "LsRequest":
        entries = []
        while r.remaining() >= 12:
            r.u16()
            try:
                t = LsaType(r.u16())
            except ValueError as e:
                raise DecodeError("unknown v3 LSA type in request") from e
            entries.append(LsaKey(t, r.ipv4(), r.ipv4()))
        return cls(entries)


@dataclass
class LsUpdate:
    lsas: list[Lsa] = field(default_factory=list)

    TYPE = PacketType.LS_UPDATE

    def encode_body(self, w: Writer) -> None:
        w.u32(len(self.lsas))
        for lsa in self.lsas:
            w.bytes(lsa.raw if lsa.raw else lsa.encode())

    @classmethod
    def decode_body(cls, r: Reader) -> "LsUpdate":
        n = r.u32()
        return cls([Lsa.decode(r) for _ in range(n)])


@dataclass
class LsAck:
    lsa_headers: list[Lsa] = field(default_factory=list)

    TYPE = PacketType.LS_ACK

    def encode_body(self, w: Writer) -> None:
        for h in self.lsa_headers:
            h.encode_header(w)

    @classmethod
    def decode_body(cls, r: Reader) -> "LsAck":
        hdrs = []
        while r.remaining() >= LSA_HDR_LEN:
            hdrs.append(Lsa.decode_header(r))
        return cls(hdrs)


_PKT_CODECS = {
    PacketType.HELLO: Hello,
    PacketType.DB_DESC: DbDesc,
    PacketType.LS_REQUEST: LsRequest,
    PacketType.LS_UPDATE: LsUpdate,
    PacketType.LS_ACK: LsAck,
}


def _pseudo_header(src: IPv6Address, dst: IPv6Address, length: int) -> bytes:
    return (
        src.packed + dst.packed + struct.pack(">I", length) + b"\x00\x00\x00\x59"
    )  # next header 89


def _cksum16(data: bytes) -> int:
    if len(data) % 2:
        data += b"\x00"
    s = sum(struct.unpack(f">{len(data) // 2}H", data))
    while s >> 16:
        s = (s & 0xFFFF) + (s >> 16)
    return (~s) & 0xFFFF


@dataclass
class Packet:
    """OSPFv3 packet: 16-byte header; checksum over IPv6 pseudo-header.

    Authentication: RFC 7166 authentication trailer (HMAC family).  With
    an :class:`AuthCtxV3`, ``encode`` appends the trailer (SA id, 64-bit
    sequence number, HMAC digest over header+body+trailer-preamble) and
    ``decode`` requires and verifies it.  Reference:
    holo-ospf/src/packet/auth.rs applied to the v3 trailer."""

    router_id: IPv4Address
    area_id: IPv4Address
    body: object
    instance_id: int = 0
    auth_seqno: int = 0  # from a verified trailer on decode

    def encode(
        self,
        src: IPv6Address | None = None,
        dst: IPv6Address | None = None,
        auth: "AuthCtxV3 | None" = None,
    ) -> bytes:
        w = Writer()
        w.u8(OSPF_VERSION).u8(int(self.body.TYPE)).u16(0)
        w.ipv4(self.router_id).ipv4(self.area_id)
        w.u16(0)  # checksum
        w.u8(self.instance_id).u8(0)
        self.body.encode_body(w)
        w.patch_u16(2, len(w))
        if src is not None and dst is not None:
            cks = _cksum16(_pseudo_header(src, dst, len(w)) + bytes(w.buf))
            w.patch_u16(12, cks)
        pkt = w.finish()
        if auth is None:
            return pkt
        return pkt + auth.trailer(pkt)

    @classmethod
    def decode(
        cls,
        data: bytes,
        src: IPv6Address | None = None,
        dst: IPv6Address | None = None,
        auth: "AuthCtxV3 | None" = None,
    ) -> "Packet":
        r = Reader(data)
        if r.remaining() < PKT_HDR_LEN:
            raise DecodeError("short packet")
        if r.u8() != OSPF_VERSION:
            raise DecodeError("bad version")
        try:
            ptype = PacketType(r.u8())
        except ValueError as e:
            raise DecodeError("unknown packet type") from e
        length = r.u16()
        if length < PKT_HDR_LEN or length > len(data):
            raise DecodeError("bad packet length")
        router_id, area_id = r.ipv4(), r.ipv4()
        cksum = r.u16()
        instance_id = r.u8()
        r.u8()
        if src is not None and dst is not None:
            # RFC 5340 §4.2.2: the checksum is mandatory; a zero wire value
            # is not a bypass (the reference permits that only under its
            # 'testing' cfg — holo-ospf lsa.rs is_checksum_valid).  Callers
            # that cannot reconstruct the pseudo-header pass src/dst=None.
            if _cksum16(_pseudo_header(src, dst, length) + data[:length]) != 0:
                raise DecodeError("packet checksum mismatch")
        seqno = 0
        if auth is not None:
            seqno = auth.verify(data[:length], data[length:])
        body = _PKT_CODECS[ptype].decode_body(Reader(data, PKT_HDR_LEN, length))
        return cls(router_id, area_id, body, instance_id, auth_seqno=seqno)


_AT_HMACS = {"sha256": ("sha256", 32), "sha384": ("sha384", 48),
             "sha1": ("sha1", 20), "sha512": ("sha512", 64)}
AT_TYPE_HMAC = 1  # RFC 7166 §2.1 authentication type

# ietf-key-chain crypto-algorithm identities -> RFC 7166 HMAC names.
# MD5 has no RFC 7166 authentication type: md5 keys resolve to None, and
# commit validation rejects chains containing them for OSPFv3 use
# (providers.py validate) so the gap can never be configured silently.
_AT_KEYCHAIN_ALGO = {
    "hmac-sha-1": "sha1",
    "hmac-sha-256": "sha256",
    "hmac-sha-384": "sha384",
    "hmac-sha-512": "sha512",
    "sha1": "sha1",
    "sha256": "sha256",
    "sha384": "sha384",
    "sha512": "sha512",
}


@dataclass
class AuthCtxV3:
    """RFC 7166 authentication-trailer context (HMAC family).

    With a ``keychain`` (reference ospfv3/packet/mod.rs:860-876
    AuthMethod::Keychain over holo-utils keychain.rs), the SA id on the
    wire IS the key id: sending resolves the active send key once per
    packet, verification looks the received SA id up against accept
    lifetimes — key rollover without packet loss."""

    key: bytes
    sa_id: int = 1
    algo: str = "sha256"
    seqno: int = 0  # 64-bit, monotonic per sender
    keychain: object = None  # utils.keychain.Keychain
    clock: object = None

    def _now(self) -> float:
        if callable(self.clock):
            return self.clock()
        import time as _time

        return _time.time()

    def resolve_send(self) -> "AuthCtxV3 | None":
        """Fixed-key context for ONE outgoing packet (SA id, digest
        length, and digest must agree).  None when the keychain has no
        usable active send key: the packet goes out unauthenticated and
        the peer's auth requirement rejects it (a visible coverage gap,
        like the v2/IS-IS paths)."""
        if self.keychain is None:
            return self
        k = self.keychain.key_lookup_send(self._now())
        if k is None:
            return None
        algo = _AT_KEYCHAIN_ALGO.get(k.algo)
        if algo is None:
            return None  # md5 etc.: not valid for RFC 7166
        return AuthCtxV3(
            key=k.string, sa_id=k.id & 0xFFFF, algo=algo, seqno=self.seqno
        )

    def _resolve_accept(self, sa_id: int) -> "AuthCtxV3 | None":
        if self.keychain is None:
            return self if sa_id == self.sa_id else None
        # Masked compare: the SA field is u16 and resolve_send masks.
        k = self.keychain.key_lookup_accept(sa_id, self._now(), mask=0xFFFF)
        if k is None:
            return None
        algo = _AT_KEYCHAIN_ALGO.get(k.algo)
        if algo is None:
            return None
        return AuthCtxV3(key=k.string, sa_id=sa_id, algo=algo)

    def _digest(self, pkt: bytes, preamble: bytes) -> bytes:
        import hashlib
        import hmac as _hmac

        name, _dlen = _AT_HMACS[self.algo]
        return _hmac.new(self.key, pkt + preamble, getattr(hashlib, name)).digest()

    def trailer(self, pkt: bytes) -> bytes:
        name, dlen = _AT_HMACS[self.algo]
        pre = struct.pack(
            ">HHHHQ", AT_TYPE_HMAC, 16 + dlen, 0, self.sa_id, self.seqno
        )
        return pre + self._digest(pkt, pre)

    def verify(self, pkt: bytes, trailer: bytes) -> int:
        """Returns the trailer's sequence number; raises on any failure
        (missing trailer, unknown SA, bad digest).  The received SA id
        selects the accept key (keychain-aware)."""
        import hmac as _hmac

        if len(trailer) < 16:
            raise DecodeError("authentication trailer missing/short")
        at_type, at_len, _res, sa_id, seqno = struct.unpack(
            ">HHHHQ", trailer[:16]
        )
        eff = self._resolve_accept(sa_id)
        if eff is None:
            raise DecodeError("unknown authentication SA")
        name, dlen = _AT_HMACS[eff.algo]
        if len(trailer) < 16 + dlen:
            raise DecodeError("authentication trailer missing/short")
        if at_type != AT_TYPE_HMAC or at_len != 16 + dlen:
            raise DecodeError("bad authentication trailer parameters")
        want = eff._digest(pkt, trailer[:16])
        if not _hmac.compare_digest(want, trailer[16 : 16 + dlen]):
            raise DecodeError("authentication digest mismatch")
        return seqno

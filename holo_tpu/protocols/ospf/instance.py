"""OSPFv2 instance actor: event dispatch, adjacency, flooding, SPF, routes.

Reference anatomy: holo-ospf/src/instance.rs (root state machine),
events.rs (packet handlers), flood.rs (flooding), spf.rs (delay FSM).
One actor per instance on the shared event loop; all IO via NetIo; all
timers via loop timers (virtual-clock testable).

Implemented here: multi-area ABR (type-3/4), AS externals (type-5) with
redistribution, stub + NSSA areas (RFC 3101, elected translator), virtual
links, keyed-MD5/HMAC auth with keychains and restart-safe seqno
reservation (persisted ceiling; replaces the reference's boot-count seed),
graceful restart (RFC 3623, both sides), RFC 8405 SPF delay FSM.
Simplifications: DD packets carry up to DD_CHUNK headers (MTU pagination
simplified); MaxAge LSAs are removed once flooded with empty
retransmission lists.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from ipaddress import IPv4Address, IPv4Network

from holo_tpu import telemetry
from holo_tpu.protocols.ospf.interface import (
    ElectionView,
    IfConfig,
    IfType,
    IsmState,
    OspfInterface,
    elect_dr_bdr,
)

# Protocol observability shared by OSPFv2 and OSPFv3 (the v3 instance
# imports these families): NSM transitions, wire rx/tx/retransmit
# rates, and SPF runs.  Labels stay low-cardinality (instance name +
# an 8-state enum / direction).
_OSPF_NBR_TRANSITIONS = telemetry.counter(
    "holo_ospf_nbr_transitions_total",
    "OSPF neighbor FSM state changes",
    ("instance", "to"),
)
_OSPF_PACKETS = telemetry.counter(
    "holo_ospf_packets_total", "OSPF packets", ("instance", "dir")
)
_OSPF_RX_BAD = telemetry.counter(
    "holo_ospf_rx_bad_total",
    "OSPF packets dropped in decode/auth",
    ("instance",),
)
_OSPF_RETRANSMITS = telemetry.counter(
    "holo_ospf_retransmits_total",
    "OSPF rxmt-timer firings that resent DD/request/update state",
    ("instance",),
)
_OSPF_SPF_RUNS = telemetry.counter(
    "holo_ospf_spf_runs_total", "SPF runs", ("instance", "type")
)
from holo_tpu.protocols.ospf.lsdb import (
    MIN_LS_ARRIVAL,
    Lsdb,
    next_seq_no,
)
from holo_tpu.protocols.ospf.neighbor import (
    Neighbor,
    NsmEvent,
    NsmState,
    nsm_transition,
)
from holo_tpu.protocols.ospf.packet import (
    MAX_AGE,
    MAX_LINK_METRIC,
    AuthType,
    DbDesc,
    DbDescFlags,
    Hello,
    Lsa,
    LsaKey,
    LsaRouter,
    LsaNetwork,
    LsaType,
    LsAck,
    LsRequest,
    LsUpdate,
    Options,
    Packet,
    PacketType,
    RouterFlags,
    RouterLink,
    RouterLinkType,
)
from holo_tpu.protocols.ospf.spf_run import (
    build_topology,
    derive_routes,
    link_spf_delta,
)
from holo_tpu.spf.backend import ScalarSpfBackend, SpfBackend
from holo_tpu.telemetry import convergence
from holo_tpu.utils.ip import ALL_DR_RTRS_V4, ALL_SPF_RTRS_V4, mask_of
from holo_tpu.utils.netio import NetIo, NetRxPacket
from holo_tpu.utils.runtime import Actor

DD_CHUNK = 64  # LSA headers per DD packet
LSREQ_CHUNK = 64
AGE_TICK = 1.0


# ===== timer messages =====


@dataclass
class HelloTimerMsg:
    ifname: str


@dataclass
class WaitTimerMsg:
    ifname: str


@dataclass
class InactivityTimerMsg:
    ifname: str
    nbr_id: IPv4Address


@dataclass
class RxmtTimerMsg:
    ifname: str
    nbr_id: IPv4Address


@dataclass
class SpfDelayTimerMsg:
    pass


@dataclass
class SpfHoldDownMsg:
    pass


@dataclass
class GrRestartExpireMsg:
    pass


@dataclass
class FrrTablesReadyMsg:
    """Posted (cross-thread) by the pipeline worker's done-callback
    when every pending lazy backup table of an SPF run completed: the
    actor then attaches backups and republishes routes that gained
    them — the force never runs on the SPF critical path (ISSUE 10)."""

    run: int = 0  # spf_run_count stamp (stale messages are harmless)


@dataclass
class AgeTickMsg:
    pass


@dataclass
class IfUpMsg:
    ifname: str


@dataclass
class IfDownMsg:
    ifname: str


# ===== SPF delay FSM (RFC 8405; reference holo-ospf/src/spf.rs:270-484) ==


class SpfFsmState(enum.Enum):
    QUIET = "quiet"
    SHORT_WAIT = "short-wait"
    LONG_WAIT = "long-wait"


@dataclass
class SpfTimers:
    initial_delay: float = 0.05
    short_delay: float = 0.2
    long_delay: float = 5.0
    hold_down: float = 10.0
    time_to_learn: float = 0.5


@dataclass
class InstanceConfig:
    router_id: IPv4Address = IPv4Address("0.0.0.0")
    spf: SpfTimers = field(default_factory=SpfTimers)
    sr: object = None  # holo_tpu.utils.sr.SrConfig (None = SR disabled)
    bier: object = None  # holo_tpu.utils.bier.BierCfg (None = disabled)
    # Administrative distances for routes published to the RIB manager
    # (ietf-ospf preference hierarchy: specific type > internal > all).
    preference: int = 110
    preference_intra: int | None = None
    preference_inter: int | None = None
    preference_internal: int | None = None
    preference_external: int | None = None
    # RFC 3623 helper-mode capability (advertised in the RI LSA).
    gr_helper_enabled: bool = True
    # RFC 2328 §15 virtual links: (transit_area_id, peer_router_id)
    # pairs.  The vlink interface itself materializes when the peer
    # becomes reachable through the transit area (see
    # _sync_virtual_links); hello/dead intervals for vlink adjacencies.
    virtual_links: tuple = ()
    vlink_hello_interval: int = 10
    vlink_dead_interval: int = 60
    # IP fast reroute (holo_tpu.frr.FrrConfig; None = disabled): after
    # every full SPF one batched backup-table run per area precomputes
    # LFA/remote-LFA/TI-LFA repairs, attached to published routes.
    frr: object = None
    # ECMP width limit (ietf-ospf ``max-paths``): None = unlimited
    # (every equal-cost next hop installs, the historical behavior).
    # 2..8 arms the vectorized multipath dispatch (ISSUE 10): the SPF
    # runs with k-wide parent-set planes, routes carry UCMP weights,
    # and ECMP sets clamp to the highest-weight max-paths next hops.
    max_paths: int | None = None
    # Advisory what-if batching (PR 9 follow-up): > 0 enqueues that
    # many single-link-failure scenarios through the async pipeline
    # after every full SPF (coalesced/skipped by the pipeline; results
    # feed the whatif-advisory stats only, never the RIB).
    whatif_advisory: int = 0
    # RFC 6987 stub-router: advertise MaxLinkMetric (0xFFFF) on every
    # transit/p2p link so neighbors route around us while our own
    # adjacencies and stub prefixes stay reachable (maintenance mode).
    stub_router: bool = False
    # Interop knobs for replaying the reference's recorded exchanges
    # (tools/stepwise.py): seed DD seqnos like the reference's
    # 'deterministic' build, and override the §13(5a) arrival throttle
    # (frozen-clock replays carry no timestamps).
    deterministic_dd: bool = False
    min_ls_arrival: float = MIN_LS_ARRIVAL
    # Two-phase origination (reference lsdb.rs LsaOriginateEvent →
    # originate_check): triggers queue re-origination CHECKS; flushing
    # rebuilds each LSA from current state and skips unchanged content.
    # False (production): checks run immediately at the trigger site.
    # True (conformance replay): checks accumulate until the harness
    # flushes at the recorded LsaOrigCheck positions, reproducing the
    # reference's exact instance counts.
    external_orig_checks: bool = False


@dataclass
class Area:
    area_id: IPv4Address
    lsdb: Lsdb = field(default_factory=Lsdb)
    interfaces: dict[str, OspfInterface] = field(default_factory=dict)
    # RFC 2328 stub areas: no type-5 flooding; ABRs inject a default
    # summary with this cost instead.  RFC 3101 NSSA: no type-5s either,
    # but type-7s circulate inside and the elected ABR translates them.
    stub: bool = False
    nssa: bool = False
    stub_default_cost: int = 10  # holo-ietf-ospf-deviations.yang:61-66
    # Totally-stubby variant: ABRs inject only the default summary into
    # the (stub/NSSA) area, no per-prefix type-3s (RFC 2328 §12.4.3.1).
    summary: bool = True
    # RFC 2328 area address ranges: [{prefix, advertise, cost}] — intra
    # routes inside an active range are aggregated when summarized into
    # other areas.
    ranges: list = field(default_factory=list)

    @property
    def no_type5(self) -> bool:
        return self.stub or self.nssa


@dataclass
class ExternalRoute:
    """A route this ASBR redistributes into OSPF (→ type-5 LSA)."""

    prefix: IPv4Network
    metric: int = 20
    e2: bool = True  # type-2 external metric (default, like the reference)
    tag: int = 0


_PKT_TYPE_YANG = {
    PacketType.HELLO: "hello",
    PacketType.DB_DESC: "database-description",
    PacketType.LS_REQUEST: "link-state-request",
    PacketType.LS_UPDATE: "link-state-update",
    PacketType.LS_ACK: "link-state-ack",
}


# Sentinel: a queued origination check whose subject vanished between
# trigger and dequeue (area/interface removed) — dropped, never installed.
_CHECK_SKIP = object()


class OspfInstance(Actor):
    """One OSPFv2 routing process."""

    def __init__(
        self,
        name: str,
        config: InstanceConfig,
        netio: NetIo,
        spf_backend: SpfBackend | None = None,
        route_cb=None,
        nvstore=None,
        notif_cb=None,
    ):
        self.name = name
        self.config = config
        self.netio = netio
        # YANG notification sink: receives ietf-ospf notification dicts
        # (reference holo-ospf/src/northbound/notification.rs).
        self.notif_cb = notif_cb
        self.backend = spf_backend or ScalarSpfBackend()
        self.route_cb = route_cb  # callable(dict[prefix -> IntraRoute])
        self.areas: dict[IPv4Address, Area] = {}
        self._if_area: dict[str, IPv4Address] = {}
        self._timers: dict[tuple, object] = {}
        self._dd_seq = 0x1000  # deterministic DD seq seed
        self.hostname: str | None = None  # RFC 5642, advertised in RI LSA
        self.node_tags: tuple[int, ...] = ()  # RFC 7777, RI LSA TLV 10
        # Cryptographic-auth sequence numbers must be strictly higher after
        # a restart than anything a neighbor saw before it, or every packet
        # is dropped as a replay until the dead interval expires.  The
        # reference seeds from a persisted boot count
        # (holo-ospf/src/instance.rs:231,257-258 initial_auth_seqno).  We
        # persist a *reserved ceiling* instead: the store always holds a
        # seqno no packet has used yet, and tx extends the reservation in
        # 2^16-packet windows (one durable write per window), so restarts
        # always seed above every previously sent seqno regardless of
        # uptime.  Without a store (deterministic tests) the seed stays 0.
        self._nvstore = nvstore
        self._seqno_key = f"ospf/{name}/seqno-ceiling"
        self._grace_seqno_key = f"ospf/{name}/grace-seqno"
        self._crypto_reserved = 0
        if nvstore is not None:
            # Boot count is operational state only (exposed for debugging,
            # GR bookkeeping later); the seqno seed comes from the ceiling.
            nvstore.incr(f"ospf/{name}/boot-count")
            self._crypto_seq = int(nvstore.get(self._seqno_key, 0))
            self._reserve_seqnos()
        else:
            self._crypto_seq = 0

        # RFC 3623 restarting side: while True, self-LSA origination is
        # suppressed and pre-restart copies are adopted (not outpaced) so
        # helpers keep forwarding on the pre-restart topology.
        self.gr_restarting = False
        self._gr_grace_period = 120  # last announced/entered grace params
        self._gr_reason = 1
        # Admin state: False after a disable (operational state renders a
        # minimal tree, like the reference's torn-down Instance).
        self.enabled = True
        # SPF FSM state
        self.spf_state = SpfFsmState.QUIET
        self._spf_timer = None
        self._hold_timer = None
        self._spf_scheduled = False
        self._last_event_time: float | None = None
        self._first_full_run = False
        self._learn_deadline: float | None = None
        self.routes = {}
        self.spf_run_count = 0
        # SPF run log: ring of the last 32 runs with schedule/start/end
        # times and trigger counts (reference holo-ospf/src/spf.rs:33-36,
        # 770-804 — exposed via operational state).
        self.spf_log: list[dict] = []
        self._spf_scheduled_at: float | None = None
        self._spf_trigger_count = 0
        # Full-vs-partial trigger classification (reference
        # holo-ospf/src/spf.rs:49-60,513-516): LSAs that changed since the
        # last run accumulate here; non-LSA events (config, interface
        # state, clear) force a full run.  The cache holds the last full
        # run's products (per-area SPTs + derived route tables) so a
        # summary/external-only change recomputes scoped table entries
        # without re-running Dijkstra (route.rs:200-333).
        self._spf_triggers: list = []
        self._spf_force_full = True
        self._spf_cache: dict | None = None
        # DeltaPath: the previous full run's marshaled SpfTopology per
        # area — the diff base for incremental device-graph updates.
        self._spf_delta_bases: dict = {}
        # Hierarchical partition hint (ISSUE 15): router-id -> group
        # label, stamped onto Topology.partition_hint at marshal time
        # (spf_run.apply_partition_hint) so the partitioned-SPF path
        # cuts along operator-known structure instead of a flat BFS cut.
        self.spf_partition_of: dict | None = None
        # Convergence-observatory causal ids pending on the next SPF run
        # (bounded; stamped in _schedule_spf, drained by run_spf).
        self._conv_pending: list = []
        self.ibus = None  # set via attach_ibus for RIB integration
        self.routing_actor = "routing"
        # Externals we originate (type 5; stored in every area's LSDB with
        # install-time cross-area propagation = AS flooding scope).
        self.redistributed: dict[IPv4Network, ExternalRoute] = {}
        self._external_lsids: dict[IPv4Network, IPv4Address] = {}
        # Prefixes we currently translate type-7 -> type-5 for (RFC 3101
        # §3, elected NSSA ABR translator duty).
        self._nssa_translated: set[IPv4Network] = set()
        # Segment routing state (labels resolved after each SPF).
        self.sr_labels: dict = {}
        # IP-FRR backup tables (area_id -> BackupTable), refreshed by
        # every full SPF run; partial runs keep them (no topology change
        # by definition).  The engine persists for its shape-bucket
        # compile cache.
        self.frr_tables: dict = {}
        self._frr_engine = None
        # ISSUE 10 satellite: deferred FRR-backup attach (pipelined
        # tables are forced on the worker, never on the SPF path) and
        # advisory what-if tickets + counters per area.
        self._frr_attach_deferred = False
        self._whatif_tickets: dict = {}
        self._whatif_stats: dict = {"enqueued": 0, "completed": 0}
        self.bier_routes: dict = {}
        # Shared opaque-id allocator for RFC 7684 extended-prefix LSAs:
        # keys are ("sr", prefix) and ("bier", sd_id); ids never reused.
        self._ext_prefix_opaque_ids: dict[tuple, int] = {}
        # Which interface each link-scope (type 9) LSA belongs to, for
        # per-interface operational-state grouping (state.rs link db).
        self._link_scope_iface: dict[LsaKey, str] = {}
        # Routers reachable per area in the last SPF (intra-area paths),
        # rid -> RouterFlags captured at SPF time: serves abr-count/
        # asbr-count (reference area.rs:164-182).
        self._area_reachable_routers: dict[IPv4Address, dict] = {}
        # Deferred origination checks (see InstanceConfig.external_orig_checks):
        # key -> kwargs, deduped so N triggers collapse into one rebuild at
        # the recorded check position (see _queue_check).
        self._pending_checks: dict[tuple, dict] = {}
        # Prefixes we've actually pushed to the RIB — tracked explicitly
        # because route objects can mutate between syncs, so inferring
        # "was installed" from snapshots is unreliable (see _sync_rib).
        self._installed_prefixes: set = set()

    _SEQNO_WINDOW = 1 << 16

    def _reserve_seqnos(self) -> None:
        """Durably reserve the next window of auth sequence numbers."""
        self._crypto_reserved = self._crypto_seq + self._SEQNO_WINDOW
        self._nvstore.put(self._seqno_key, self._crypto_reserved)

    def attach_ibus(
        self, ibus, routing_actor: str = "routing", bfd_actor: str = "bfd"
    ) -> None:
        """Wire route programming + BFD registration over the ibus."""
        self.ibus = ibus
        self.routing_actor = routing_actor
        self.bfd_actor = bfd_actor

    # ----- wiring helpers

    def attach(self, loop_):
        super().attach(loop_)
        self._age_timer = self.loop.timer(self.name, AgeTickMsg)
        self._age_timer.start(AGE_TICK)

    def add_interface(
        self,
        ifname: str,
        cfg: IfConfig,
        addr: IPv4Network,
        addr_ip: IPv4Address,
        stub: bool = False,
        stub_default_cost: int = 10,  # deviation holo-ietf-ospf-deviations.yang:61-66
        nssa: bool = False,
    ) -> OspfInterface:
        """Area type is part of area creation — the stub/NSSA flags must
        be set BEFORE any LSA origination touches the area."""
        assert not (stub and nssa), "area cannot be both stub and NSSA"
        new_area = cfg.area_id not in self.areas
        area = self.areas.setdefault(cfg.area_id, Area(cfg.area_id))
        if new_area:
            area.stub = stub
            area.nssa = nssa
            area.stub_default_cost = stub_default_cost
        elif area.stub != stub or area.nssa != nssa:
            self.set_area_type(cfg.area_id, stub=stub, nssa=nssa)
        iface = OspfInterface(
            name=ifname, config=cfg, addr_ip=addr_ip, prefix=addr
        )
        area.interfaces[ifname] = iface
        self._if_area[ifname] = cfg.area_id
        if new_area and self.redistributed:
            # AS-scope LSAs must exist in every (non-stub) area, incl.
            # late-attached ones.
            for prefix in list(self.redistributed):
                self._originate_external(prefix)
        if new_area:
            self._originate_router_info(area)
        return iface

    def _build_router_info(self, area: Area):
        """RFC 7770 Router-Information opaque LSA (one per area).

        Advertises the informational capabilities the instance actually
        has: GR helper (gr.rs) and stub-router support — real since
        ``set_stub_router`` implements the RFC 6987 max-metric behavior
        (reference holo-ospf originates the same pair at area start).
        Returns (lsid, body) for the deferred-check queue.
        """
        from holo_tpu.protocols.ospf.packet import (
            RI_CAP_GR_HELPER,
            RI_CAP_STUB_ROUTER,
            LsaOpaque,
            encode_router_info,
            ri_lsid,
        )

        caps = RI_CAP_STUB_ROUTER
        if self.config.gr_helper_enabled:
            caps |= RI_CAP_GR_HELPER
        return (
            ri_lsid(),
            LsaOpaque(
                data=encode_router_info(caps, self.hostname, self.node_tags)
            ),
        )


    def set_stub_router(self, enabled: bool) -> None:
        """RFC 6987 stub-router (max-metric) maintenance mode: flip the
        leaf and re-originate every area's router-LSA with MaxLinkMetric
        on transit links (reference: the same leaf re-triggers
        lsa_orig_router)."""
        if enabled == self.config.stub_router:
            return
        self.config.stub_router = enabled
        for area in self.areas.values():
            self._originate_router_lsa(area)

    def set_node_tags(self, tags: tuple[int, ...]) -> None:
        """RFC 7777 node administrative tags (RI LSA, re-originated on
        change — reference NodeTagsChange event)."""
        if tuple(tags) == self.node_tags:
            return
        self.node_tags = tuple(tags)
        for area in self.areas.values():
            self._originate_router_info(area)

    def set_hostname(self, hostname: str | None) -> None:
        """RFC 5642 dynamic hostname: carried in the RI LSA, re-originated
        on change (reference: HostnameChange -> lsa_orig_router_info)."""
        if hostname == self.hostname:
            return
        self.hostname = hostname
        for area in self.areas.values():
            self._originate_router_info(area)

    def interface_address_add(self, ifname: str, prefix: IPv4Network) -> None:
        """Secondary subnet on a live interface: advertise it as a stub
        link (kernel address-add path, holo-interface ibus feed)."""
        ai = self._iface(ifname)
        if ai is None:
            return
        area, iface = ai
        if prefix == iface.prefix or prefix in iface.secondary:
            return
        iface.secondary.append(prefix)
        if iface.state != IsmState.DOWN:
            self._originate_router_lsa(area)

    def interface_address_del(self, ifname: str, prefix: IPv4Network) -> None:
        ai = self._iface(ifname)
        if ai is None:
            return
        area, iface = ai
        if prefix in iface.secondary:
            iface.secondary.remove(prefix)
            if iface.state != IsmState.DOWN:
                self._originate_router_lsa(area)

    def set_area_stub(self, area_id: IPv4Address, stub: bool) -> None:
        self.set_area_type(area_id, stub=stub)

    def set_area_type(
        self, area_id: IPv4Address, stub: bool = False, nssa: bool = False
    ) -> None:
        """Flip an area's type at runtime: purge now-forbidden LSAs and
        restart the area's adjacencies (the E/N option bits changed, so
        existing neighbors would reject our hellos anyway)."""
        assert not (stub and nssa), "area cannot be both stub and NSSA"
        area = self.areas.get(area_id)
        if area is None or (area.stub == stub and area.nssa == nssa):
            return
        was_nssa = area.nssa
        area.stub = stub
        area.nssa = nssa
        if was_nssa and not nssa:
            # Leaving NSSA: type-7s are meaningless outside one.
            for key in list(area.lsdb.entries):
                if key.type == LsaType.NSSA_EXTERNAL:
                    area.lsdb.remove(key)
        if area.no_type5:
            for key in list(area.lsdb.entries):
                if key.type == LsaType.AS_EXTERNAL:
                    area.lsdb.remove(key)
            if nssa and self.redistributed:
                for prefix in list(self.redistributed):
                    self._originate_external(prefix)  # as type-7 now
        else:
            if self.redistributed:
                for prefix in list(self.redistributed):
                    self._originate_external(prefix)
            # Foreign type-5s held in our other areas must reach the
            # newly-normal area too (AS scope).
            seen: dict = {}
            for other in self.areas.values():
                if other is area:
                    continue
                for key, e in other.lsdb.entries.items():
                    if key.type != LsaType.AS_EXTERNAL:
                        continue
                    cur = seen.get(key)
                    if cur is None or e.lsa.compare(cur) > 0:
                        seen[key] = e.lsa
            for lsa in seen.values():
                cur = area.lsdb.get(lsa.key)
                if cur is None or lsa.compare(cur.lsa) > 0:
                    self._install_and_flood(area, lsa)
        for ifname, iface in list(area.interfaces.items()):
            if iface.state != IsmState.DOWN:
                self.if_down(ifname)
                self.if_up(ifname)
        self._schedule_spf()

    def _iface(self, ifname: str) -> tuple[Area, OspfInterface] | None:
        aid = self._if_area.get(ifname)
        if aid is None:
            return None
        area = self.areas[aid]
        iface = area.interfaces.get(ifname)
        return None if iface is None else (area, iface)

    def _timer(self, key: tuple, msg_fn):
        t = self._timers.get(key)
        if t is None:
            t = self.loop.timer(self.name, msg_fn)
            self._timers[key] = t
        return t

    # ----- message dispatch

    def handle(self, msg) -> None:
        if isinstance(msg, NetRxPacket):
            self._rx_packet(msg)
        elif isinstance(msg, HelloTimerMsg):
            self._send_hello(msg.ifname)
        elif isinstance(msg, WaitTimerMsg):
            self._wait_timer(msg.ifname)
        elif isinstance(msg, InactivityTimerMsg):
            self._inactivity_expired(msg.ifname, msg.nbr_id)
        elif isinstance(msg, RxmtTimerMsg):
            self._rxmt(msg.ifname, msg.nbr_id)
        elif isinstance(msg, SpfDelayTimerMsg):
            self._spf_timer_fired()
        elif isinstance(msg, SpfHoldDownMsg):
            self._spf_holddown_fired()
        elif isinstance(msg, GrRestartExpireMsg):
            self._gr_restart_expired()
        elif isinstance(msg, FrrTablesReadyMsg):
            self._frr_tables_ready()
        elif isinstance(msg, AgeTickMsg):
            self._age_tick()
        elif isinstance(msg, IfUpMsg):
            self.if_up(msg.ifname)
        elif isinstance(msg, IfDownMsg):
            self.if_down(msg.ifname)
        else:
            self._rx_ibus(msg)

    def _rx_ibus(self, msg) -> None:
        """BFD fast failure: a Down state update kills the adjacency
        immediately (reference: SURVEY.md §3.5 BfdStateUpd path)."""
        from holo_tpu.utils.ibus import TOPIC_BFD_STATE, BfdStateUpd, IbusMsg

        if not isinstance(msg, IbusMsg) or msg.topic != TOPIC_BFD_STATE:
            return
        upd = msg.payload
        if not isinstance(upd, BfdStateUpd) or upd.state != "down":
            return
        ifname, peer = upd.key
        ai = self._iface(ifname)
        if ai is None:
            return
        _, iface = ai
        for nbr_id, nbr in list(iface.neighbors.items()):
            if nbr.src == peer:
                self._nbr_event(ifname, nbr_id, NsmEvent.KILL_NBR)

    # ----- YANG notifications (reference northbound/notification.rs)

    def _notify(self, kind: str, data: dict) -> None:
        if self.notif_cb is not None:
            self.notif_cb({kind: data})

    def _notif_iface(self, iface: OspfInterface) -> dict:
        return {
            "routing-protocol-name": self.name,
            "address-family": "ipv4",
            "interface": {"interface": iface.name},
        }

    def _set_ism_state(self, iface: OspfInterface, new: IsmState) -> None:
        if iface.state == new:
            return
        iface.state = new
        from holo_tpu.protocols.ospf.nb_state import _ISM_NAME

        self._notify(
            "ietf-ospf:if-state-change",
            self._notif_iface(iface) | {"state": _ISM_NAME[new]},
        )

    def _notify_if_config_error(
        self, iface: OspfInterface, src, pkt_type: str, error: str
    ) -> None:
        self._notify(
            "ietf-ospf:if-config-error",
            self._notif_iface(iface)
            | {
                "packet-source": str(src),
                "packet-type": pkt_type,
                "error": error,
            },
        )

    def gr_helper_enter(
        self, area: Area, iface: OspfInterface, nbr, grace_period: int
    ) -> None:
        self._notify(
            "ietf-ospf:nbr-restart-helper-status-change",
            self._notif_iface(iface)
            | {
                "neighbor-router-id": str(nbr.router_id),
                "neighbor-ip-addr": str(nbr.src),
                "status": "helping",
                "age": grace_period,
            },
        )

    def gr_helper_exit(
        self, area: Area, iface: OspfInterface, nbr, reason: str
    ) -> None:
        """End the helper window (gr.rs:166-203): notify, clear the GR
        state, and re-originate the segment's LSAs.  The adjacency itself
        is untouched — it only dies later on the inactivity timer."""
        nbr.gr_deadline = None
        self._notify(
            "ietf-ospf:nbr-restart-helper-status-change",
            self._notif_iface(iface)
            | {
                "neighbor-router-id": str(nbr.router_id),
                "neighbor-ip-addr": str(nbr.src),
                "status": "not-helping",
                "exit-reason": reason,
            },
        )
        self._originate_router_lsa(area)
        self._originate_network_lsa(area, iface)

    # ----- ISM

    def if_up(self, ifname: str) -> None:
        ai = self._iface(ifname)
        if ai is None:
            return
        area, iface = ai
        if iface.state != IsmState.DOWN:
            return
        if iface.config.loopback:
            self._set_ism_state(iface, IsmState.LOOPBACK)
            self._originate_router_lsa(area)
            return
        if iface.config.if_type == IfType.POINT_TO_POINT:
            self._set_ism_state(iface, IsmState.POINT_TO_POINT)
        else:
            self._set_ism_state(iface, IsmState.WAITING)
            self._timer(("wait", ifname), lambda: WaitTimerMsg(ifname)).start(
                iface.config.dead_interval
            )
        self._timer(("hello", ifname), lambda: HelloTimerMsg(ifname)).start(0.0)
        self._originate_router_lsa(area)

    def if_down(self, ifname: str) -> None:
        ai = self._iface(ifname)
        if ai is None:
            return
        area, iface = ai
        # No network-LSA flush here: the reference's interface stop only
        # resets state (interface.rs:391-437) — the MaxAge flood happens
        # solely on a DR change while the interface is still up.  The
        # stale network LSA is invalidated anyway once our router-LSA
        # stops listing the transit link.
        # Teardown kills neighbors without re-running DR election — the
        # reference's InterfaceDown FSM goes straight to Down; an interim
        # election here would emit a spurious if-state-change (e.g. "dr")
        # before the "down" notification.
        iface.going_down = True
        try:
            for nbr_id in list(iface.neighbors):
                self._nbr_event(ifname, nbr_id, NsmEvent.KILL_NBR)
        finally:
            iface.going_down = False
        self._set_ism_state(iface, IsmState.DOWN)
        iface.dr = IPv4Address(0)
        iface.bdr = IPv4Address(0)
        for key in ("hello", "wait"):
            t = self._timers.get((key, ifname))
            if t:
                t.cancel()
        self._originate_router_lsa(area)

    def _wait_timer(self, ifname: str) -> None:
        ai = self._iface(ifname)
        if ai and ai[1].state == IsmState.WAITING:
            self._run_dr_election(*ai)

    def _run_dr_election(self, area: Area, iface: OspfInterface) -> None:
        """§9.4 (run twice when our own role changes, per step 4)."""
        for _ in range(2):
            views = [
                ElectionView(
                    iface.config.priority,
                    self.config.router_id,
                    iface.addr_ip,
                    iface.dr,
                    iface.bdr,
                )
            ]
            for nbr in iface.neighbors.values():
                if nbr.state >= NsmState.TWO_WAY:
                    views.append(
                        ElectionView(nbr.priority, nbr.router_id, nbr.src, nbr.dr, nbr.bdr)
                    )
            new_dr, new_bdr = elect_dr_bdr(views)
            changed = (new_dr, new_bdr) != (iface.dr, iface.bdr)
            iface.dr, iface.bdr = new_dr, new_bdr
            if new_dr == iface.addr_ip:
                self._set_ism_state(iface, IsmState.DR)
            elif new_bdr == iface.addr_ip:
                self._set_ism_state(iface, IsmState.BACKUP)
            else:
                self._set_ism_state(iface, IsmState.DR_OTHER)
            if not changed:
                break
        # AdjOK? on all 2-Way+ neighbors (adjacency set may change).
        for nbr_id in list(iface.neighbors):
            nbr = iface.neighbors[nbr_id]
            if nbr.state >= NsmState.TWO_WAY:
                self._nbr_event(iface.name, nbr_id, NsmEvent.ADJ_OK)
        self._originate_router_lsa(area)
        self._originate_network_lsa(area, iface)

    # ----- hello

    def _send_hello(self, ifname: str) -> None:
        ai = self._iface(ifname)
        if ai is None:
            return
        area, iface = ai
        if iface.state == IsmState.DOWN or iface.config.passive:
            return
        options = (
            Options.NP if area.nssa
            else Options(0) if area.stub
            else Options.E
        )
        lls = None
        if self.gr_restarting:
            # RFC 4812 restart signal: hellos during graceful restart
            # carry an LLS block with the RS bit so helpers keep the
            # adjacency without resetting it.
            from holo_tpu.protocols.ospf.packet import LLS_EOF_RS, LlsBlock

            options |= Options.L
            lls = LlsBlock(eof=LLS_EOF_RS)
        hello = Hello(
            # §15/A.3.2: unnumbered p2p and virtual links send mask 0.
            mask=mask_of(iface.prefix) if iface.prefix else IPv4Address(0),
            hello_interval=iface.config.hello_interval,
            options=options,
            priority=iface.config.priority,
            dead_interval=iface.config.dead_interval,
            dr=iface.dr,
            bdr=iface.bdr,
            neighbors=[n.router_id for n in iface.neighbors.values()
                       if n.state >= NsmState.INIT],
        )
        self._send(iface, ALL_SPF_RTRS_V4, hello, area, lls=lls)
        self._timer(("hello", ifname), lambda: HelloTimerMsg(ifname)).start(
            iface.config.hello_interval
        )

    def _rx_hello(self, area: Area, iface: OspfInterface, src: IPv4Address, pkt: Packet) -> None:
        h: Hello = pkt.body
        if h.hello_interval != iface.config.hello_interval:
            # §10.5 parameter mismatch (notification per error.rs to_yang).
            self._notify_if_config_error(
                iface, src, "hello", "hello-interval-mismatch"
            )
            return
        if h.dead_interval != iface.config.dead_interval:
            self._notify_if_config_error(
                iface, src, "hello", "dead-interval-mismatch"
            )
            return
        if bool(h.options & Options.E) == area.no_type5:
            # §10.5: E-bit must agree with the area's type.
            self._notify_if_config_error(iface, src, "hello", "option-mismatch")
            return
        # RFC 5613: record the peer's LLS extended options (restart
        # signal / OOB-resync capability) on the neighbor.
        lls_eof = pkt.lls.eof if pkt.lls is not None else None
        if bool(h.options & Options.NP) != area.nssa:
            # RFC 3101 §2.4: N-bit must agree on NSSA-ness.
            self._notify_if_config_error(iface, src, "hello", "option-mismatch")
            return
        if (
            iface.config.if_type == IfType.BROADCAST
            and iface.prefix is not None
            and h.mask != mask_of(iface.prefix)
        ):
            self._notify_if_config_error(
                iface, src, "hello", "net-mask-mismatch"
            )
            return
        nbr = iface.neighbors.get(pkt.router_id)
        created = nbr is None
        if created:
            nbr = Neighbor(router_id=pkt.router_id, src=src)
            iface.neighbors[pkt.router_id] = nbr
        nbr.lls_eof = lls_eof
        if created:
            if iface.config.bfd_enabled and self.ibus is not None:
                # Register a BFD session for fast failure detection
                # (ibus bfd_session_reg path, SURVEY.md §3.5).
                from holo_tpu.utils.ibus import TOPIC_BFD_STATE, BfdSessionReg

                self.ibus.subscribe(TOPIC_BFD_STATE, self.name)
                self.ibus.request(
                    self.bfd_actor,
                    BfdSessionReg(
                        sender=self.name,
                        key=(iface.name, src),
                        local=iface.addr_ip,
                    ),
                    sender=self.name,
                )
        prev = (nbr.priority, nbr.dr, nbr.bdr)
        nbr.src = src
        nbr.priority = h.priority
        nbr.dr, nbr.bdr = h.dr, h.bdr
        self._nbr_event(iface.name, pkt.router_id, NsmEvent.HELLO_RECEIVED)
        self._timer(
            ("inactivity", iface.name, pkt.router_id),
            lambda: InactivityTimerMsg(iface.name, pkt.router_id),
        ).start(iface.config.dead_interval)
        if self.config.router_id in h.neighbors:
            self._nbr_event(iface.name, pkt.router_id, NsmEvent.TWO_WAY_RECEIVED)
        else:
            self._nbr_event(iface.name, pkt.router_id, NsmEvent.ONE_WAY_RECEIVED)
            return
        if iface.config.if_type == IfType.BROADCAST:
            if iface.state == IsmState.WAITING:
                # BackupSeen (§9.2): nbr declares itself BDR, or DR with no BDR.
                if h.bdr == src or (h.dr == src and h.bdr == IPv4Address(0)):
                    t = self._timers.get(("wait", iface.name))
                    if t:
                        t.cancel()
                    self._run_dr_election(area, iface)
            elif (nbr.priority, nbr.dr, nbr.bdr) != prev:
                self._run_dr_election(area, iface)

    # ----- AS-external routes (type 5, §12.4.4 / §16.4)

    @property
    def is_asbr(self) -> bool:
        # An NSSA translator originates type-5s, so it is an ASBR to the
        # rest of the domain (RFC 3101 §3.1).
        return bool(self.redistributed) or bool(self._nssa_translated)

    def _external_lsid(self, prefix: IPv4Network) -> IPv4Address:
        """Appendix E link-state-id assignment for type-5 LSAs: prefixes
        sharing a network address get host bits set so keys stay unique."""
        from holo_tpu.utils.ip import mask_of

        cur = self._external_lsids.get(prefix)
        if cur is not None:
            return cur
        net = prefix.network_address
        taken = set(self._external_lsids.values())
        lsid = net
        if lsid in taken:
            lsid = IPv4Address(int(net) | (~int(mask_of(prefix)) & 0xFFFFFFFF))
        self._external_lsids[prefix] = lsid
        return lsid

    def redistribute(
        self,
        prefix: IPv4Network,
        metric: int = 20,
        e2: bool = True,
        tag: int = 0,
    ) -> None:
        """ASBR: inject an external route as a type-5 LSA (AS scope — one
        copy per area LSDB, kept consistent by install-time propagation)."""
        was_asbr = self.is_asbr
        self.redistributed[prefix] = ExternalRoute(prefix, metric, e2, tag)
        self._originate_external(prefix)
        if not was_asbr:
            for area in self.areas.values():
                self._originate_router_lsa(area)  # E flag

    def _originate_external(
        self, prefix: IPv4Network, force: bool = False
    ) -> None:
        from holo_tpu.protocols.ospf.packet import LsaAsExternal
        from holo_tpu.utils.ip import mask_of

        route = self.redistributed[prefix]
        body = LsaAsExternal(
            mask=mask_of(prefix), e_bit=route.e2, metric=route.metric,
            fwd_addr=IPv4Address(0), tag=route.tag,
        )
        lsid = self._external_lsid(prefix)
        for area in self.areas.values():
            if area.nssa:
                # RFC 3101 §2.4: inside an NSSA the ASBR originates a
                # type-7 instead.  P-bit set so the elected ABR
                # translates it — unless we are an ABR ourselves (we
                # already flood the type-5 into the other areas, and
                # §2.3 forbids translating our own).
                opts = Options(0) if self.is_abr else Options.NP
                self._originate(
                    area, LsaType.NSSA_EXTERNAL, lsid, body,
                    options=opts, force=force,
                )
            elif not area.stub:  # §3.6: no type-5s in stub areas
                self._originate(
                    area, LsaType.AS_EXTERNAL, lsid, body, force=force
                )

    def withdraw_redistributed(self, prefix: IPv4Network) -> None:
        if self.redistributed.pop(prefix, None) is None:
            return
        lsid = self._external_lsids.pop(prefix, prefix.network_address)
        for area in self.areas.values():
            for ltype in (LsaType.AS_EXTERNAL, LsaType.NSSA_EXTERNAL):
                self._flush_self_lsa(
                    area, LsaKey(ltype, lsid, self.config.router_id)
                )
        if not self.is_asbr:
            for area in self.areas.values():
                self._originate_router_lsa(area)

    def _propagate_external(self, from_area: Area, lsa: Lsa) -> None:
        """AS scope: a type-5 installed in one area is installed (and thus
        flooded) into every other non-stub, non-NSSA area by ABRs
        (§3.6, RFC 3101 §2.2)."""
        for area in self.areas.values():
            if area is from_area or area.no_type5:
                continue
            cur = area.lsdb.get(lsa.key)
            if cur is None or lsa.compare(cur.lsa) > 0:
                self._install_and_flood(area, lsa)

    def _asbr_distance(self, aid, st, res, asbr: IPv4Address, now: float):
        """Distance + next hops to an ASBR within one area — directly if
        it is in this area's SPF, else via a type-4 ASBR-summary from a
        reachable ABR (§16.4 step 3)."""
        from holo_tpu.protocols.ospf.spf_run import _atoms_of

        v = st.router_index.get(asbr)
        if v is not None and res.dist[v] < 0x40000000:
            return int(res.dist[v]), _atoms_of(res.nexthop_words[v], st.atoms)
        best = None
        area = self.areas[aid]
        for e in area.lsdb.all():
            lsa = e.lsa
            if (
                lsa.type != LsaType.SUMMARY_ROUTER
                or lsa.lsid != asbr
                or lsa.adv_rtr == self.config.router_id
                or e.current_age(now) >= MAX_AGE
            ):
                continue
            abr_v = st.router_index.get(lsa.adv_rtr)
            if abr_v is None or res.dist[abr_v] >= 0x40000000:
                continue
            dist = int(res.dist[abr_v]) + lsa.body.metric
            if best is None or dist < best[0]:
                best = (dist, _atoms_of(res.nexthop_words[abr_v], st.atoms))
        return best if best is not None else (None, None)

    def _external_routes(
        self, area_results: dict, known: set, only: set | None = None
    ) -> dict:
        """§16.4 condensed: E1 = dist(ASBR)+metric; E2 ranked by (metric,
        dist(ASBR)) after all internal paths; intra/inter always win.

        ``only`` scopes a partial run to the changed prefixes
        (route.rs:307-321): other externals keep their table entries."""
        best: dict = {}
        now = self.loop.clock.now()
        for aid, (st, res) in area_results.items():
            area = self.areas[aid]
            # RFC 3101 §2.5: inside an NSSA, type-7s are examined
            # alongside type-5s from the other attached areas.
            wanted_types = (
                (LsaType.AS_EXTERNAL, LsaType.NSSA_EXTERNAL)
                if area.nssa
                else (LsaType.AS_EXTERNAL,)
            )
            for e in area.lsdb.all():
                lsa = e.lsa
                if (
                    lsa.type not in wanted_types
                    or lsa.adv_rtr == self.config.router_id
                    or e.current_age(now) >= MAX_AGE
                    or lsa.body.metric >= 0xFFFFFF
                ):
                    continue
                asbr_dist, nhs = self._asbr_distance(
                    aid, st, res, lsa.adv_rtr, now
                )
                if asbr_dist is None:
                    continue
                from holo_tpu.protocols.ospf.spf_run import IntraRoute
                from holo_tpu.utils.ip import apply_mask

                prefix = apply_mask(lsa.lsid, lsa.body.mask)
                if only is not None and prefix not in only:
                    continue  # partial run: out-of-scope prefix
                if prefix in known:
                    continue  # internal paths always preferred
                # Ranking key: E1 before E2; E1 by total; E2 by (metric,
                # asbr dist); type-5 over type-7 on full ties (§2.5).
                is_t7 = lsa.type == LsaType.NSSA_EXTERNAL
                if is_t7 and self.is_abr and prefix.prefixlen == 0:
                    # RFC 3101 §2.5: type-7 default LSAs are examined
                    # only by non-border NSSA routers — two ABRs would
                    # otherwise default-route into each other.
                    continue
                if lsa.body.e_bit:
                    rank = (1, lsa.body.metric, asbr_dist, is_t7)
                    dist = lsa.body.metric
                    rtype = "nssa-2" if is_t7 else "external-2"
                else:
                    rank = (0, asbr_dist + lsa.body.metric, 0, is_t7)
                    dist = asbr_dist + lsa.body.metric
                    rtype = "nssa-1" if is_t7 else "external-1"
                cur = best.get(prefix)
                if cur is None or rank < cur[0]:
                    best[prefix] = (
                        rank, IntraRoute(prefix, dist, nhs, aid, rtype)
                    )
                elif rank == cur[0]:
                    merged = IntraRoute(
                        prefix, dist, cur[1].nexthops | nhs, aid, rtype
                    )
                    best[prefix] = (rank, merged)
        return {p: r for p, (rank, r) in best.items()}

    def _nssa_translate(self, area_results: dict) -> None:
        """RFC 3101 §3: the reachable NSSA ABR with the highest router-id
        translates P-bit type-7s into type-5s for the rest of the domain;
        everyone else (and routers losing the election) withdraws."""
        from holo_tpu.protocols.ospf.packet import LsaAsExternal, RouterFlags
        from holo_tpu.utils.ip import apply_mask

        now = self.loop.clock.now()
        wanted: dict[IPv4Network, LsaAsExternal] = {}
        if self.is_abr:
            for aid, (st, res) in area_results.items():
                area = self.areas[aid]
                if not area.nssa:
                    continue
                # Translator election (§3.1): highest-RID reachable ABR.
                abrs = {self.config.router_id}
                for e in area.lsdb.all():
                    lsa = e.lsa
                    if (
                        lsa.type != LsaType.ROUTER
                        or not (lsa.body.flags & RouterFlags.B)
                        or e.current_age(now) >= MAX_AGE
                    ):
                        continue
                    v = st.router_index.get(lsa.adv_rtr)
                    if v is not None and res.dist[v] < 0x40000000:
                        abrs.add(lsa.adv_rtr)
                if max(abrs) != self.config.router_id:
                    continue  # someone else translates for this NSSA
                for e in area.lsdb.all():
                    lsa = e.lsa
                    if (
                        lsa.type != LsaType.NSSA_EXTERNAL
                        or lsa.adv_rtr == self.config.router_id
                        or not (lsa.options & Options.NP)  # P=0: never
                        or e.current_age(now) >= MAX_AGE
                        or lsa.body.metric >= 0xFFFFFF
                    ):
                        continue
                    v = st.router_index.get(lsa.adv_rtr)
                    if v is None or res.dist[v] >= 0x40000000:
                        continue  # §3.2: ASBR must be reachable
                    prefix = apply_mask(lsa.lsid, lsa.body.mask)
                    body = LsaAsExternal(
                        mask=lsa.body.mask,
                        e_bit=lsa.body.e_bit,
                        metric=lsa.body.metric,
                        fwd_addr=lsa.body.fwd_addr,
                        tag=lsa.body.tag,
                    )
                    cur = wanted.get(prefix)
                    # Aggregate duplicates: best (E1-first, lowest metric).
                    if cur is None or (not body.e_bit, body.metric) < (
                        not cur.e_bit, cur.metric
                    ):
                        wanted[prefix] = body
        was_asbr = self.is_asbr
        for prefix in self._nssa_translated - set(wanted):
            if prefix in self.redistributed:
                continue  # still advertised in our own right
            lsid = self._external_lsids.pop(prefix, prefix.network_address)
            key = LsaKey(LsaType.AS_EXTERNAL, lsid, self.config.router_id)
            for area in self.areas.values():
                self._flush_self_lsa(area, key)
        self._nssa_translated = set(wanted)
        for prefix, body in wanted.items():
            if prefix in self.redistributed:
                continue  # our own type-5 wins; no translated duplicate
            lsid = self._external_lsid(prefix)
            for area in self.areas.values():
                if not area.no_type5:
                    self._originate(area, LsaType.AS_EXTERNAL, lsid, body)
        if was_asbr != self.is_asbr:
            for area in self.areas.values():
                self._originate_router_lsa(area)  # E-flag changed

    # ----- graceful restart (RFC 3623)

    def _inactivity_expired(self, ifname: str, nbr_id: IPv4Address) -> None:
        """Dead timer fired — unless we are helping this neighbor restart
        (grace window open), in which case we hold the adjacency
        (reference gr.rs helper mode)."""
        ai = self._iface(ifname)
        if ai is None:
            return
        nbr = ai[1].neighbors.get(nbr_id)
        if nbr is not None and nbr.gr_deadline is not None:
            now = self.loop.clock.now()
            if now < nbr.gr_deadline:
                self._timer(
                    ("inactivity", ifname, nbr_id),
                    lambda: InactivityTimerMsg(ifname, nbr_id),
                ).start(nbr.gr_deadline - now)
                return
            nbr.gr_deadline = None  # grace expired: proceed with the kill
        self._nbr_event(ifname, nbr_id, NsmEvent.INACTIVITY_TIMER)

    def send_grace_lsas(self, grace_period: int = 120, reason: int = 1) -> None:
        """Restarting side: announce intent to restart, one link-local
        Grace-LSA per interface (opaque type 9.3), flooded only on its
        own link.  Exempt from the gr_restarting origination suppression
        (RFC 3623 §2.2 — Grace-LSAs are the one thing a restarting router
        DOES originate)."""
        from holo_tpu.protocols.ospf.packet import (
            LsaOpaque,
            encode_grace_tlvs,
            grace_lsa_lsid,
        )

        self._gr_grace_period = grace_period
        self._gr_reason = reason
        for area in self.areas.values():
            for idx, iface in enumerate(area.interfaces.values()):
                if iface.state == IsmState.DOWN or iface.addr_ip is None:
                    continue
                body = LsaOpaque(
                    encode_grace_tlvs(grace_period, reason, iface.addr_ip)
                )
                self._originate(
                    area,
                    LsaType.OPAQUE_LINK,
                    grace_lsa_lsid(idx),
                    body,
                    allow_in_gr=True,
                    only_iface=iface,
                )
        # Persist the highest Grace-LSA seq-no actually used: the post-
        # restart instance resumes from it when synthesizing the MaxAge
        # flush, so helpers accept the flush no matter how many times
        # grace params were re-announced before the restart.
        if self._nvstore is not None:
            seqs = [
                e.lsa.seq_no
                for area in self.areas.values()
                for key in list(area.lsdb.entries)
                if self._is_own_grace_lsa(key)
                and (e := area.lsdb.get(key)) is not None
            ]
            if seqs:
                self._nvstore.put(self._grace_seqno_key, max(seqs))

    def iface_update(
        self,
        ifname: str,
        hello: int | None = None,
        dead: int | None = None,
        priority: int | None = None,
        passive: bool | None = None,
        mtu: int | None = None,
        mtu_ignore: bool | None = None,
        transmit_delay: int | None = None,
    ) -> None:
        """Live interface reconfiguration beyond cost (reference
        northbound InterfaceUpdate family).

        - hello/dead intervals apply from the NEXT hello (the hello
          timer re-arms with the config value each fire); a mismatch
          with the peer drops its hellos until both sides agree —
          standard OSPF semantics.
        - priority is advertised in the next hello; elections react via
          the peers' NeighborChange processing.
        - passive=True kills the circuit's neighbors (the interface
          stops exchanging hellos); passive=False restarts the hello
          task that the passive gate parked."""
        ai = self._iface(ifname)
        if ai is None:
            return
        area, iface = ai
        cfg = iface.config
        if hello is not None:
            cfg.hello_interval = hello
        if dead is not None:
            cfg.dead_interval = dead
        if priority is not None:
            cfg.priority = priority
        if mtu is not None:
            # The §10.6 DD Interface-MTU check reads this live — a stale
            # creation-time snapshot would wedge jumbo adjacencies.
            cfg.mtu = mtu
        if mtu_ignore is not None:
            cfg.mtu_ignore = mtu_ignore
        if transmit_delay is not None:
            cfg.transmit_delay = transmit_delay
        if passive is not None and cfg.passive != passive:
            cfg.passive = passive
            if iface.state == IsmState.DOWN:
                # A link-down interface has nothing to tear down or
                # revive — and forcing WAITING here would advertise a
                # dead link AND break the next if_up's DOWN check.
                return
            if passive:
                # Same teardown discipline as if_down: the going_down
                # guard suppresses interim DR elections per KILL_NBR
                # (a passive interface must not end up claiming DR).
                iface.going_down = True
                try:
                    for nbr_id in list(iface.neighbors):
                        self._nbr_event(ifname, nbr_id, NsmEvent.KILL_NBR)
                finally:
                    iface.going_down = False
                iface.dr = IPv4Address(0)
                iface.bdr = IPv4Address(0)
                if cfg.if_type == IfType.BROADCAST:
                    self._set_ism_state(iface, IsmState.WAITING)
                for key in ("hello", "wait"):
                    t = self._timers.get((key, ifname))
                    if t:
                        t.cancel()
                self._originate_router_lsa(area)
            elif iface.state != IsmState.DOWN:
                # Revival re-enters the §9.1 Waiting phase on broadcast
                # circuits and restarts the hello task the passive gate
                # parked.
                if cfg.if_type == IfType.BROADCAST:
                    self._set_ism_state(iface, IsmState.WAITING)
                    self._timer(
                        ("wait", ifname), lambda: WaitTimerMsg(ifname)
                    ).start(cfg.dead_interval)
                self._timer(
                    ("hello", ifname), lambda: HelloTimerMsg(ifname)
                ).start(0.0)

    def iface_cost_update(self, ifname: str, cost: int) -> None:
        """Live cost reconfiguration (reference northbound
        InterfaceCostUpdate): the new metric re-originates our
        router-LSA, and neighbors reconverge through normal flooding."""
        ai = self._iface(ifname)
        if ai is None:
            return
        area, iface = ai
        if iface.config.cost == cost:
            return
        iface.config.cost = cost
        self._originate_router_lsa(area)

    def _is_own_grace_lsa(self, key: "LsaKey") -> bool:
        """Self-originated Grace-LSA key (link-local opaque type 3)."""
        return (
            key.type == LsaType.OPAQUE_LINK
            and key.adv_rtr == self.config.router_id
            and (int(key.lsid) >> 24) == 3
        )

    def begin_graceful_restart(self, grace_period: int = 120) -> None:
        """Enter restarting mode with a hard exit deadline (RFC 3623 §2.5):
        if resync hasn't completed when the grace period lapses, resume
        normal operation with whatever adjacencies exist — a vanished
        pre-restart neighbor must not suppress origination forever."""
        self.gr_restarting = True
        self._gr_grace_period = grace_period
        t = self._timers.get(("gr-expire",))
        if t is None:
            t = self.loop.timer(self.name, GrRestartExpireMsg)
            self._timers[("gr-expire",)] = t
        t.start(grace_period)

    def _gr_restart_expired(self) -> None:
        if not self.gr_restarting:
            return
        self.gr_restarting = False
        for a in self.areas.values():
            self._originate_router_lsa(a)
            self._originate_router_info(a)  # hostname/caps changed during GR
        self._flush_grace_lsas()

    def _gr_resync_complete(self) -> bool:
        """All p2p neighbors named in our adopted pre-restart router LSA
        must be FULL again before the restart is considered complete
        (RFC 3623 §2.3; the pre-restart LSA is the surviving record of
        which adjacencies existed)."""
        for area in self.areas.values():
            key = LsaKey(LsaType.ROUTER, self.config.router_id, self.config.router_id)
            e = area.lsdb.get(key)
            expected: set = set()
            if e is not None:
                for link in e.lsa.body.links:
                    if link.link_type == RouterLinkType.POINT_TO_POINT:
                        expected.add(link.id)
            full = {
                n.router_id
                for i in area.interfaces.values()
                for n in i.neighbors.values()
                if n.state == NsmState.FULL
            }
            if expected - full:
                return False
        return True

    def _flush_grace_lsas(self) -> None:
        """Restart complete (§2.4): withdraw our Grace-LSAs on the wire.

        The opaque id encodes the interface's position in the area's
        interface order (assigned identically in send_grace_lsas), so the
        maxage copy floods on exactly its own link.

        A freshly restarted instance usually does NOT hold its own
        pre-restart Grace-LSAs (DD exchange excludes link-local opaques),
        so flushing by LSDB lookup alone would silently do nothing and
        helpers would sit out the whole grace period.  For interfaces with
        no stored copy we synthesize the MaxAge Grace-LSA directly with a
        sequence number strictly newer than any plausible pre-restart
        copy, so helpers accept the flush under RFC 2328 §13.1.
        """
        from holo_tpu.protocols.ospf.packet import (
            LsaOpaque,
            encode_grace_tlvs,
            grace_lsa_lsid,
        )

        # Resume from the persisted pre-restart Grace-LSA seq-no when the
        # NV store has one (send_grace_lsas records it); the +4 guess is
        # only the fallback for instances that never wrote the record.
        synth_seq = next_seq_no(None) + 4
        if self._nvstore is not None:
            persisted = self._nvstore.get(self._grace_seqno_key)
            if persisted is not None:
                synth_seq = max(int(persisted) + 1, synth_seq)
        for area in self.areas.values():
            ifaces = list(area.interfaces.values())
            flushed: set = set()
            for key in list(area.lsdb.entries):
                if self._is_own_grace_lsa(key):
                    idx = int(key.lsid) & 0xFFFFFF
                    only = ifaces[idx] if idx < len(ifaces) else None
                    self._flush_self_lsa(area, key, only_iface=only)
                    flushed.add(idx)
            for idx, iface in enumerate(ifaces):
                if idx in flushed:
                    continue
                if iface.state == IsmState.DOWN or iface.addr_ip is None:
                    continue
                lsa = Lsa(
                    age=MAX_AGE,
                    options=Options(0) if area.stub else Options.E,
                    type=LsaType.OPAQUE_LINK,
                    lsid=grace_lsa_lsid(idx),
                    adv_rtr=self.config.router_id,
                    # Strictly newer than any pre-restart copy helpers
                    # hold: the NV store records how far the old instance
                    # got (synth_seq above); the +4-past-initial fallback
                    # covers instances without the record.
                    seq_no=synth_seq,
                    body=LsaOpaque(
                        encode_grace_tlvs(
                            self._gr_grace_period, self._gr_reason,
                            iface.addr_ip,
                        )
                    ),
                )
                lsa.encode()
                self._install_and_flood(area, lsa, only_iface=iface)

    def _maybe_enter_gr_helper(self, area: Area, lsa: Lsa) -> None:
        from holo_tpu.protocols.ospf.packet import decode_grace_tlvs

        if lsa.type != LsaType.OPAQUE_LINK or (int(lsa.lsid) >> 24) != 3:
            return
        if lsa.is_maxage:
            # Flushed Grace-LSA = restart complete: close the window.
            for iface in area.interfaces.values():
                nbr = iface.neighbors.get(lsa.adv_rtr)
                if nbr is not None and nbr.gr_deadline is not None:
                    self.gr_helper_exit(area, iface, nbr, "completed")
            return
        info = decode_grace_tlvs(lsa.body.data)
        period = info.get("grace_period")
        if period is None:
            return
        now = self.loop.clock.now()
        for iface in area.interfaces.values():
            nbr = iface.neighbors.get(lsa.adv_rtr)
            if nbr is not None and nbr.state == NsmState.FULL:
                entering = nbr.gr_deadline is None
                nbr.gr_deadline = now + period
                nbr.gr_reason = info.get("reason", 0)
                if entering:
                    self.gr_helper_enter(area, iface, nbr, period)

    # ----- NSM plumbing

    def _adj_ok(self, iface: OspfInterface, nbr: Neighbor) -> bool:
        """§10.4: should we form/keep an adjacency with this neighbor?"""
        if iface.config.if_type in (
            IfType.POINT_TO_POINT, IfType.VIRTUAL_LINK
        ):
            return True
        return (
            iface.state in (IsmState.DR, IsmState.BACKUP)
            or nbr.src == iface.dr
            or nbr.src == iface.bdr
        )

    def _nbr_event(self, ifname: str, nbr_id: IPv4Address, event: NsmEvent) -> None:
        ai = self._iface(ifname)
        if ai is None:
            return
        area, iface = ai
        nbr = iface.neighbors.get(nbr_id)
        if nbr is None:
            return
        old_state = nbr.state
        res = nsm_transition(nbr, event, adj_ok=self._adj_ok(iface, nbr))
        nbr.state = res.new_state
        if nbr.state != old_state:
            from holo_tpu.protocols.ospf.nb_state import _NSM_NAME

            _OSPF_NBR_TRANSITIONS.labels(
                instance=self.name, to=_NSM_NAME[nbr.state]
            ).inc()
            self._notify(
                "ietf-ospf:nbr-state-change",
                self._notif_iface(iface)
                | {
                    "neighbor-router-id": str(nbr.router_id),
                    "neighbor-ip-addr": str(nbr.src),
                    "state": _NSM_NAME[nbr.state],
                },
            )
        for act in res.actions:
            if act == "start_exstart":
                self._start_exstart(area, iface, nbr)
            elif act == "send_dd_summary":
                self._enter_exchange(area, iface, nbr)
            elif act == "send_ls_request":
                self._send_ls_request(area, iface, nbr)
            elif act == "clear_lists":
                nbr.ls_request.clear()
                nbr.ls_rxmt.clear()
                nbr.dd_summary.clear()
            elif act == "stop_timers":
                for key in ("inactivity", "rxmt"):
                    t = self._timers.get((key, ifname, nbr_id))
                    if t:
                        t.cancel()
            elif act == "full":
                t = self._timers.get(("rxmt", ifname, nbr_id))
                if t:
                    t.cancel()
                # The helper window stays open until the restarting router
                # flushes its Grace-LSA (gr.rs:49-63) — reaching FULL alone
                # does not end it.
                if self.gr_restarting and self._gr_resync_complete():
                    # All pre-restart adjacencies re-established (§2.3):
                    # resume origination and withdraw Grace-LSAs (§2.4).
                    self.gr_restarting = False
                    t = self._timers.get(("gr-expire",))
                    if t:
                        t.cancel()
                    for a in self.areas.values():
                        self._originate_router_lsa(a)
                        self._originate_router_info(a)
                    self._flush_grace_lsas()
        if nbr.state == NsmState.DOWN:
            del iface.neighbors[nbr_id]
            if iface.config.bfd_enabled and self.ibus is not None:
                from holo_tpu.utils.ibus import BfdSessionUnreg

                self.ibus.request(
                    self.bfd_actor,
                    BfdSessionUnreg(sender=self.name, key=(iface.name, nbr.src)),
                    sender=self.name,
                )
        if (old_state >= NsmState.FULL) != (nbr.state >= NsmState.FULL) or (
            nbr.state == NsmState.DOWN
        ):
            # Adjacency formed/lost: re-originate router LSA (+network if DR),
            # and rerun election bookkeeping via NeighborChange where needed.
            self._originate_router_lsa(area)
            self._originate_network_lsa(area, iface)
        if event in (NsmEvent.KILL_NBR, NsmEvent.INACTIVITY_TIMER, NsmEvent.ONE_WAY_RECEIVED):
            if (
                iface.config.if_type == IfType.BROADCAST
                and iface.state >= IsmState.DR_OTHER
                and not getattr(iface, "going_down", False)
            ):
                self._run_dr_election(area, iface)

    # ----- DD exchange

    def _start_exstart(self, area: Area, iface: OspfInterface, nbr: Neighbor) -> None:
        if self.config.deterministic_dd:
            # Interop with the reference's recorded exchanges: its
            # 'deterministic' build seeds the DD sequence number from the
            # neighbor's router-id (holo-ospf/src/neighbor.rs:171-178) and
            # increments before the first DD, so recorded slave echoes only
            # line up if we do the same.
            nbr.dd_seq_no = int(nbr.router_id) + 1
        else:
            self._dd_seq += 1
            nbr.dd_seq_no = self._dd_seq
        nbr.master = True  # assume master until negotiation says otherwise
        dd = DbDesc(
            mtu=iface.config.mtu,
            options=Options.E,
            flags=DbDescFlags.I | DbDescFlags.M | DbDescFlags.MS,
            dd_seq_no=nbr.dd_seq_no,
        )
        nbr.last_sent_dd = dd
        self._send(iface, nbr.src, dd, area)
        self._arm_rxmt(iface, nbr)

    def _dd_summary_chunk(self, nbr: Neighbor) -> list[Lsa]:
        return nbr.dd_summary[:DD_CHUNK]

    def _enter_exchange(self, area: Area, iface: OspfInterface, nbr: Neighbor) -> None:
        """Populate the DD summary list (§10.8 NegotiationDone).  Sending is
        driven by the caller: the master continues processing the packet
        that completed negotiation, the slave replies to it."""
        now = self.loop.clock.now()
        # Link-local (type 9) LSAs are excluded: they must not DD-sync
        # beyond their own link (RFC 5250 §3).
        nbr.dd_summary = [
            e.lsa
            for e in area.lsdb.entries.values()
            if e.current_age(now) < MAX_AGE
            and e.lsa.type != LsaType.OPAQUE_LINK
        ]

    def _send_dd(self, area: Area, iface: OspfInterface, nbr: Neighbor) -> None:
        chunk = self._dd_summary_chunk(nbr)
        more = len(nbr.dd_summary) > len(chunk)
        flags = DbDescFlags(0)
        if nbr.master:
            flags |= DbDescFlags.MS
        if more:
            flags |= DbDescFlags.M
        dd = DbDesc(
            mtu=iface.config.mtu,
            options=Options.E,
            flags=flags,
            dd_seq_no=nbr.dd_seq_no,
            lsa_headers=chunk,
        )
        nbr.last_sent_dd = dd
        self._send(iface, nbr.src, dd, area)
        if nbr.master:
            self._arm_rxmt(iface, nbr)

    def _rx_db_desc(self, area: Area, iface: OspfInterface, src: IPv4Address, pkt: Packet) -> None:
        dd: DbDesc = pkt.body
        nbr = iface.neighbors.get(pkt.router_id)
        if nbr is None:
            return
        if nbr.state == NsmState.INIT:
            # §10.6: a DD in Init proves the neighbor sees us — run
            # 2-WayReceived and, if that starts the adjacency (ExStart),
            # keep processing this same packet.
            self._nbr_event(iface.name, pkt.router_id, NsmEvent.TWO_WAY_RECEIVED)
            nbr = iface.neighbors.get(pkt.router_id)
            if nbr is None:
                return
        if nbr.state < NsmState.EX_START:
            return
        # §10.6: reject a DD whose Interface MTU exceeds what we can
        # receive unfragmented, unless mtu-ignore is set.  Virtual links
        # carry MTU 0 and are exempt (§10.8).
        if (
            dd.mtu > iface.config.mtu
            and not iface.config.mtu_ignore
            and iface.config.if_type != IfType.VIRTUAL_LINK
        ):
            return
        if nbr.state == NsmState.EX_START:
            negotiated = False
            if (
                dd.flags == DbDescFlags.I | DbDescFlags.M | DbDescFlags.MS
                and not dd.lsa_headers
                and int(pkt.router_id) > int(self.config.router_id)
            ):
                # Peer is master; adopt its sequence number.
                nbr.master = False
                nbr.dd_seq_no = dd.dd_seq_no
                negotiated = True
            elif (
                not (dd.flags & DbDescFlags.I)
                and not (dd.flags & DbDescFlags.MS)
                and dd.dd_seq_no == nbr.dd_seq_no
                and int(pkt.router_id) < int(self.config.router_id)
            ):
                nbr.master = True
                negotiated = True
            if not negotiated:
                return
            self._nbr_event(iface.name, pkt.router_id, NsmEvent.NEGOTIATION_DONE)
            nbr = iface.neighbors.get(pkt.router_id)
            if nbr is None or nbr.state != NsmState.EXCHANGE:
                return
            nbr.last_dd = (dd.flags, dd.options, dd.dd_seq_no)
            # Either way the packet completing negotiation must be processed
            # for content (§10.8): the slave's echo may carry LSA headers.
            self._process_dd_headers(area, iface, nbr, dd)
            if nbr.master:
                # The master always sends its first data DD (even with an
                # empty summary): the slave can only conclude the exchange
                # from a master DD with M clear.
                nbr.dd_seq_no += 1
                self._send_dd(area, iface, nbr)
            else:
                self._slave_reply(area, iface, nbr, dd)
            return

        if nbr.state != NsmState.EXCHANGE:
            # §10.6: duplicate handling in Loading/Full — slave re-echoes.
            if (
                nbr.state in (NsmState.LOADING, NsmState.FULL)
                and not nbr.master
                and nbr.last_dd == (dd.flags, dd.options, dd.dd_seq_no)
            ):
                if nbr.last_sent_dd is not None:
                    self._send(iface, nbr.src, nbr.last_sent_dd, area)
                return
            if nbr.state in (NsmState.LOADING, NsmState.FULL):
                self._nbr_event(iface.name, pkt.router_id, NsmEvent.SEQ_NUMBER_MISMATCH)
            return

        dup = nbr.last_dd == (dd.flags, dd.options, dd.dd_seq_no)
        if dup:
            if not nbr.master and nbr.last_sent_dd is not None:
                self._send(iface, nbr.src, nbr.last_sent_dd, area)
            return
        # Master/slave bit must be consistent (exactly one master).
        peer_is_master = bool(dd.flags & DbDescFlags.MS)
        if peer_is_master == nbr.master:
            self._nbr_event(iface.name, pkt.router_id, NsmEvent.SEQ_NUMBER_MISMATCH)
            return
        if dd.flags & DbDescFlags.I:
            self._nbr_event(iface.name, pkt.router_id, NsmEvent.SEQ_NUMBER_MISMATCH)
            return
        if nbr.master:
            if dd.dd_seq_no != nbr.dd_seq_no:
                self._nbr_event(iface.name, pkt.router_id, NsmEvent.SEQ_NUMBER_MISMATCH)
                return
            nbr.last_dd = (dd.flags, dd.options, dd.dd_seq_no)
            self._process_dd_headers(area, iface, nbr, dd)
            nbr.dd_summary = nbr.dd_summary[len(self._dd_summary_chunk(nbr)) :]
            nbr.dd_seq_no += 1
            if not nbr.dd_summary and not (dd.flags & DbDescFlags.M):
                self._nbr_event(iface.name, pkt.router_id, NsmEvent.EXCHANGE_DONE)
            else:
                self._send_dd(area, iface, nbr)
        else:
            if dd.dd_seq_no != nbr.dd_seq_no + 1 and nbr.last_dd is not None:
                self._nbr_event(iface.name, pkt.router_id, NsmEvent.SEQ_NUMBER_MISMATCH)
                return
            nbr.last_dd = (dd.flags, dd.options, dd.dd_seq_no)
            self._process_dd_headers(area, iface, nbr, dd)
            self._slave_reply(area, iface, nbr, dd)

    def _slave_reply(self, area: Area, iface: OspfInterface, nbr: Neighbor, dd: DbDesc) -> None:
        nbr.dd_seq_no = dd.dd_seq_no
        chunk = self._dd_summary_chunk(nbr)
        nbr.dd_summary = nbr.dd_summary[len(chunk) :]
        flags = DbDescFlags(0)
        if nbr.dd_summary:
            flags |= DbDescFlags.M
        reply = DbDesc(
            mtu=iface.config.mtu,
            options=Options.E,
            flags=flags,
            dd_seq_no=nbr.dd_seq_no,
            lsa_headers=chunk,
        )
        nbr.last_sent_dd = reply
        self._send(iface, nbr.src, reply, area)
        if not (dd.flags & DbDescFlags.M) and not (flags & DbDescFlags.M):
            self._nbr_event(iface.name, nbr.router_id, NsmEvent.EXCHANGE_DONE)

    def _process_dd_headers(self, area: Area, iface: OspfInterface, nbr: Neighbor, dd: DbDesc) -> None:
        for hdr in dd.lsa_headers:
            cur = area.lsdb.get(hdr.key)
            if cur is None or hdr.compare(cur.lsa) > 0:
                nbr.ls_request[hdr.key] = hdr

    # ----- LS request / update / ack

    def _send_ls_request(self, area: Area, iface: OspfInterface, nbr: Neighbor) -> None:
        keys = list(nbr.ls_request.keys())[:LSREQ_CHUNK]
        if not keys:
            return
        self._send(iface, nbr.src, LsRequest(keys), area)
        self._arm_rxmt(iface, nbr)

    def _rx_ls_request(self, area: Area, iface: OspfInterface, src: IPv4Address, pkt: Packet) -> None:
        nbr = iface.neighbors.get(pkt.router_id)
        if nbr is None or nbr.state < NsmState.EXCHANGE:
            return
        lsas = []
        for key in pkt.body.entries:
            e = area.lsdb.get(key)
            if e is None:
                self._nbr_event(iface.name, pkt.router_id, NsmEvent.BAD_LS_REQ)
                return
            lsas.append(self._aged_copy(e, iface.config.transmit_delay))
        if lsas:
            self._send(iface, nbr.src, LsUpdate(lsas), area)

    def _aged_copy(self, entry, delay: int = 0) -> Lsa:
        """LSA with age advanced to now plus the outgoing interface's
        InfTransDelay (§13.1/§13.3), capped at MaxAge.  The copy/patch
        step is the shared ``lsa_tx_copy``, expressed as the delta from
        the stored age to (current age + delay)."""
        lsa = entry.lsa
        from holo_tpu.protocols.ospf.packet import lsa_tx_copy

        age = min(entry.current_age(self.loop.clock.now()) + delay, MAX_AGE)
        return lsa_tx_copy(lsa, age - lsa.age)

    @staticmethod
    def _tx_copy(lsa: Lsa, delay: int) -> Lsa:
        """§13.3 InfTransDelay age increment (shared helper)."""
        from holo_tpu.protocols.ospf.packet import lsa_tx_copy

        return lsa_tx_copy(lsa, delay)

    @staticmethod
    def _validate_lsa(lsa: Lsa) -> str | None:
        """LSA sanity checks (reference lsa.rs validate()); returns the
        holo-ospf lsa-validation-error identity or None."""
        from holo_tpu.utils.bytesbuf import fletcher16_verify

        if lsa.age > MAX_AGE:
            return "invalid-age"
        if (lsa.seq_no & 0xFFFFFFFF) == 0x80000000:  # reserved seqno
            return "invalid-seq-num"
        if lsa.raw and len(lsa.raw) >= 20 and not fletcher16_verify(
            lsa.raw[2:]
        ):
            return "invalid-checksum"
        if lsa.type == LsaType.ROUTER and lsa.lsid != lsa.adv_rtr:
            return "ospfv2-router-lsa-id-mismatch"
        return None

    def _rx_ls_update(self, area: Area, iface: OspfInterface, src: IPv4Address, pkt: Packet) -> None:
        nbr = iface.neighbors.get(pkt.router_id)
        if nbr is None or nbr.state < NsmState.EXCHANGE:
            return
        acks: list[Lsa] = []
        now = self.loop.clock.now()
        exchanging = any(
            n.state in (NsmState.EXCHANGE, NsmState.LOADING)
            for a2 in self.areas.values()
            for i2 in a2.interfaces.values()
            for n in i2.neighbors.values()
        )
        for lsa in pkt.body.lsas:
            # (1) Validation beyond the RFC's checksum-only rule
            # (reference lsa.rs:370-386 + events.rs:830-845).
            err = self._validate_lsa(lsa)
            if err is not None:
                self._notify(
                    "holo-ospf:if-rx-bad-lsa",
                    {
                        "routing-protocol-name": self.name,
                        "packet-source": str(src),
                        "error": err,
                    },
                )
                continue
            # Flooding scope (§3.6 / RFC 3101 §2.2): no type-5s into
            # stub or NSSA areas — nor type-4 ASBR-summaries or AS-scope
            # opaques (RFC 2328 errata 3746; reference lsdb.rs:85-99) —
            # and type-7s only inside an NSSA.
            if lsa.type in (
                LsaType.AS_EXTERNAL,
                LsaType.SUMMARY_ROUTER,
                LsaType.OPAQUE_AS,
            ) and area.no_type5:
                continue
            if lsa.type == LsaType.NSSA_EXTERNAL and not area.nssa:
                continue
            cur = area.lsdb.get(lsa.key)
            # §13 (4): a MaxAge LSA with no database copy (and no
            # exchange in progress) is acked directly, never installed —
            # otherwise flushes ping-pong around multi-access links.
            if lsa.is_maxage and cur is None and not exchanging:
                acks.append(lsa)
                continue
            # §13 (5): newer than DB copy (or no copy).
            if cur is None or lsa.compare(cur.lsa) > 0:
                if (
                    cur is not None
                    and now - cur.rcvd_time < self.config.min_ls_arrival
                ):
                    continue
                # Self-originated received from elsewhere (§13.4): flood
                # the newer copy on as usual, then outpace or flush it
                # (the reference does both, in that order — two floods on
                # every adjacency).  Network LSAs are self-identified by
                # the LSA-ID matching one of our interface addresses, NOT
                # only by the advertising router (a pre-restart router-id
                # change leaves stale copies under the old adv-rtr).
                self_net_iface = (
                    self._iface_by_addr(lsa.lsid)
                    if lsa.type == LsaType.NETWORK
                    else None
                )
                if (
                    lsa.adv_rtr == self.config.router_id
                    or self_net_iface is not None
                ) and not lsa.is_maxage:
                    prev_lsa = cur.lsa if cur is not None else None
                    fb = self._install_and_flood(
                        area, lsa, from_iface=iface, from_nbr=nbr
                    )
                    if self._ack_wanted(iface, nbr, fb):
                        acks.append(lsa)
                    self._post_self_orig(area, lsa, prev_lsa, self_net_iface)
                    continue
                fb = self._install_and_flood(
                    area, lsa, from_iface=iface, from_nbr=nbr
                )
                if self._ack_wanted(iface, nbr, fb):
                    acks.append(lsa)
            elif lsa.key in nbr.ls_request:
                # §13 (4)... actually handled via request list below.
                self._nbr_event(iface.name, pkt.router_id, NsmEvent.BAD_LS_REQ)
                return
            elif cur is not None and lsa.compare(cur.lsa) == 0:
                # Duplicate: implied ack if on rxmt list, else direct ack.
                if lsa.key in nbr.ls_rxmt:
                    nbr.ls_rxmt.pop(lsa.key, None)
                else:
                    self._send(iface, nbr.src, LsAck([lsa]), area)
            else:
                # DB copy is newer: send it back directly (§13 (8)).
                self._send(
                    iface,
                    nbr.src,
                    LsUpdate(
                        [self._aged_copy(cur, iface.config.transmit_delay)]
                    ),
                    area,
                )
            # Fulfilled request?
            if lsa.key in nbr.ls_request:
                req = nbr.ls_request[lsa.key]
                if lsa.compare(req) >= 0:
                    del nbr.ls_request[lsa.key]
        if acks:
            # §13.5 delayed-ack destination: AllSPFRouters on p2p and from
            # DR/BDR; AllDRouters (modeled as the DR address) otherwise.
            if (
                iface.config.if_type
                in (IfType.POINT_TO_POINT, IfType.VIRTUAL_LINK)
                or iface.is_dr_or_bdr()
            ):
                ack_dst = ALL_SPF_RTRS_V4
            else:
                ack_dst = iface.dr if int(iface.dr) else nbr.src
            self._send(iface, ack_dst, LsAck(acks), area)
        if nbr.state == NsmState.LOADING and not nbr.ls_request:
            self._nbr_event(iface.name, pkt.router_id, NsmEvent.LOADING_DONE)
        elif nbr.state == NsmState.LOADING:
            self._send_ls_request(area, iface, nbr)

    @staticmethod
    def _ack_wanted(iface: OspfInterface, nbr: Neighbor, flooded_back: bool) -> bool:
        """§13.5 (5.e) delayed-ack condition (events.rs:941-947): no ack
        when the LSA was flooded back out the receiving interface, and a
        Backup DR only acks what arrived from the DR."""
        if flooded_back:
            return False
        return (
            iface.state != IsmState.BACKUP or nbr.src == iface.dr
        )

    def _rx_ls_ack(self, area: Area, iface: OspfInterface, src: IPv4Address, pkt: Packet) -> None:
        nbr = iface.neighbors.get(pkt.router_id)
        if nbr is None or nbr.state < NsmState.EXCHANGE:
            return
        for hdr in pkt.body.lsa_headers:
            cur = nbr.ls_rxmt.get(hdr.key)
            # Same-instance acks only (§13.7) — the reference's exact rule.
            if cur is not None and hdr.compare(cur) == 0:
                del nbr.ls_rxmt[hdr.key]

    # ----- flooding (§13.3)

    def _install_and_flood(
        self, area: Area, lsa: Lsa, from_iface=None, from_nbr=None, only_iface=None
    ) -> bool:
        """Installs and floods; returns the §13.5 flooded-back flag (see
        _flood)."""
        if lsa.type == LsaType.AS_EXTERNAL and area.stub:
            return False  # §3.6: stub areas refuse AS-external LSAs
        now = self.loop.clock.now()
        old = area.lsdb.get(lsa.key)
        _, changed = area.lsdb.install(lsa, now)
        if lsa.type == LsaType.OPAQUE_LINK:
            # Operational state groups type-9s under their link: remember
            # which interface each one belongs to (arrival interface for
            # received copies, the pinned tx interface for our own).
            owner = only_iface or from_iface
            if owner is not None:
                self._link_scope_iface[lsa.key] = owner.name
        # Our OWN summary LSAs never trigger route recalculation — they
        # are derived FROM the routes (reference lsdb.rs:465-469).
        self_orig_summary = (
            lsa.adv_rtr == self.config.router_id
            and lsa.type
            in (LsaType.SUMMARY_NETWORK, LsaType.SUMMARY_ROUTER)
        )
        if changed and not self_orig_summary:
            # Old body rides along: a mask change moves the prefix, and
            # the partial run must reconsider BOTH the old and the new
            # prefix or the withdrawn one keeps a stale route.
            self._schedule_spf(
                trigger=(lsa, old.lsa if old is not None else None)
            )
        if lsa.adv_rtr != self.config.router_id:
            self._maybe_enter_gr_helper(area, lsa)
        # A changed topology-information LSA terminates every open helper
        # window (strict-LSA-checking, reference lsdb.rs:472-482).
        if changed and lsa.type in (
            LsaType.ROUTER,
            LsaType.NETWORK,
            LsaType.SUMMARY_NETWORK,
            LsaType.SUMMARY_ROUTER,
            LsaType.AS_EXTERNAL,
            LsaType.NSSA_EXTERNAL,
        ):
            for a2 in self.areas.values():
                for i2 in a2.interfaces.values():
                    for n2 in i2.neighbors.values():
                        if n2.gr_deadline is not None:
                            self.gr_helper_exit(
                                a2, i2, n2, "topology-changed"
                            )
        if lsa.type == LsaType.AS_EXTERNAL and changed and len(self.areas) > 1:
            self._propagate_external(area, lsa)
        # Link-local opaque LSAs (type 9) never leave their link
        # (RFC 5250 §3): received copies re-flood ONLY on the receiving
        # interface (other neighbors on the same segment still need them —
        # e.g. a Grace-LSA on a broadcast link); self-originated ones go
        # out on the originating interface only.
        if lsa.type == LsaType.OPAQUE_LINK and only_iface is None:
            if from_iface is None:
                return False
            only_iface = from_iface
        # MaxAge copies STAY installed (marked maxage in operational
        # state, invisible to SPF) until the rxmt lists drain — the
        # RFC 2328 §14 removal condition, swept from the age tick.
        return self._flood(area, lsa, from_iface, from_nbr, only_iface=only_iface)

    def _flood(
        self, area: Area, lsa: Lsa, from_iface=None, from_nbr=None, only_iface=None
    ) -> bool:
        """Returns True if the LSA was flooded back out the RECEIVING
        interface — the §13.5 'flooded back' condition that suppresses
        the delayed acknowledgment (reference events.rs:941-947)."""
        flooded_back = False
        for iface in area.interfaces.values():
            if iface.state == IsmState.DOWN:
                continue
            if only_iface is not None and iface is not only_iface:
                continue
            if iface.config.if_type == IfType.VIRTUAL_LINK and lsa.type in (
                LsaType.AS_EXTERNAL,
                LsaType.OPAQUE_AS,
            ):
                # AS-scope LSAs never cross virtual links (reference
                # lsdb.rs:74-83; the transit area's own flooding carries
                # them).
                continue
            flood_it = False
            for nbr in iface.neighbors.values():
                if nbr.state < NsmState.EXCHANGE:
                    continue
                if nbr.state in (NsmState.EXCHANGE, NsmState.LOADING):
                    req = nbr.ls_request.get(lsa.key)
                    if req is not None:
                        c = lsa.compare(req)
                        if c < 0:
                            continue
                        del nbr.ls_request[lsa.key]
                        if c == 0:
                            continue
                if from_nbr is not None and nbr is from_nbr:
                    continue
                nbr.ls_rxmt[lsa.key] = lsa
                flood_it = True
                self._arm_rxmt(iface, nbr)
            if not flood_it:
                continue
            if iface is from_iface and from_nbr is not None:
                # §13.3 (3): received on this iface from DR/BDR → skip send.
                if from_nbr.src in (iface.dr, iface.bdr):
                    continue
                # §13.3 (4): the Backup DR defers to the DR's re-flood.
                if iface.state == IsmState.BACKUP:
                    continue
            if iface is from_iface:
                flooded_back = True
            self._send(
                iface,
                ALL_SPF_RTRS_V4,
                LsUpdate([self._tx_copy(lsa, iface.config.transmit_delay)]),
                area,
            )
        return flooded_back

    def _arm_rxmt(self, iface: OspfInterface, nbr: Neighbor) -> None:
        t = self._timer(
            ("rxmt", iface.name, nbr.router_id),
            lambda: RxmtTimerMsg(iface.name, nbr.router_id),
        )
        if not t.armed:
            t.start(iface.config.rxmt_interval)

    def _rxmt(self, ifname: str, nbr_id: IPv4Address) -> None:
        ai = self._iface(ifname)
        if ai is None:
            return
        area, iface = ai
        nbr = iface.neighbors.get(nbr_id)
        if nbr is None:
            return
        resent = False
        if nbr.state == NsmState.EX_START or (
            nbr.state == NsmState.EXCHANGE and nbr.master
        ):
            if nbr.last_sent_dd is not None:
                self._send(iface, nbr.src, nbr.last_sent_dd, area)
                resent = True
        if nbr.state == NsmState.LOADING and nbr.ls_request:
            self._send_ls_request(area, iface, nbr)
            resent = True
        if resent or nbr.ls_rxmt:
            _OSPF_RETRANSMITS.labels(instance=self.name).inc()
        if nbr.ls_rxmt:
            lsas = [
                self._tx_copy(l, iface.config.transmit_delay)
                for l in list(nbr.ls_rxmt.values())[:20]
            ]
            self._send(iface, nbr.src, LsUpdate(lsas), area)
        if (
            nbr.state in (NsmState.EX_START, NsmState.EXCHANGE, NsmState.LOADING)
            or nbr.ls_rxmt
        ):
            self._arm_rxmt(iface, nbr)

    # ----- origination

    def _originate(
        self,
        area: Area,
        ltype: LsaType,
        lsid: IPv4Address,
        body,
        allow_in_gr: bool = False,
        only_iface=None,
        options: Options | None = None,
        force: bool = False,
    ) -> None:
        if options is None:
            # Area-default LSA options (reference area_options): stub
            # areas clear the E-bit on everything originated into them.
            options = Options(0) if area.stub else Options.E
        if self.gr_restarting and not allow_in_gr:
            return  # RFC 3623 §2.2: no origination until resync completes
        if getattr(self, "_shutting_down", False):
            return  # teardown in progress: nothing new goes out
        key = LsaKey(ltype, lsid, self.config.router_id)
        old = area.lsdb.get(key)
        lsa = Lsa(
            age=0,
            options=options,
            type=ltype,
            lsid=lsid,
            adv_rtr=self.config.router_id,
            seq_no=next_seq_no(old.lsa if old else None),
            body=body,
        )
        lsa.encode()
        if (
            not force
            and old is not None
            and not old.lsa.is_maxage
            and old.lsa.raw[20:] == lsa.raw[20:]
            and old.lsa.options == options
        ):
            # Unchanged content AND header options (the NSSA P-bit lives
            # in the header): no re-origination needed.  A MaxAge copy
            # (mid-flush) never suppresses: wanting the LSA again after a
            # premature age requires a fresh instance (§12.4/14.1).
            return
        self._install_and_flood(area, lsa, only_iface=only_iface)

    def _flush_self_lsa(self, area: Area, key: LsaKey, only_iface=None) -> None:
        e = area.lsdb.get(key)
        if e is None:
            return
        if e.lsa.is_maxage:
            # Already being flushed — never flush the same LSA twice
            # (reference lsdb.rs flush(): early-return on is_maxage).
            return
        import copy

        lsa = copy.copy(e.lsa)
        lsa.age = MAX_AGE
        if lsa.raw:
            raw = bytearray(lsa.raw)
            raw[0:2] = MAX_AGE.to_bytes(2, "big")
            lsa.raw = bytes(raw)
        self._install_and_flood(area, lsa, only_iface=only_iface)

    def refresh_lsa(self, area_id: IPv4Address, key: LsaKey) -> None:
        """LSRefreshTime: re-originate a self LSA with a fresh sequence
        number (also driven by the age machinery in _age_tick)."""
        area = self.areas.get(area_id)
        if area is None:
            return
        e = area.lsdb.get(key)
        if e is None or e.lsa.adv_rtr != self.config.router_id:
            return
        lsa = Lsa(
            age=0,
            options=e.lsa.options,
            type=e.lsa.type,
            lsid=e.lsa.lsid,
            adv_rtr=e.lsa.adv_rtr,
            seq_no=next_seq_no(e.lsa),
            body=e.lsa.body,
        )
        lsa.encode()
        self._install_and_flood(area, lsa)

    def _iface_by_addr(self, addr: IPv4Address):
        for area in self.areas.values():
            for iface in area.interfaces.values():
                if iface.addr_ip == addr:
                    return iface
        return None

    def _post_self_orig(
        self, area: Area, received: Lsa, prev: Lsa | None, net_iface
    ) -> None:
        """§13.4 per-type disposition after flooding the received copy
        (mirrors the reference's process_self_originated_lsa,
        holo-ospf/src/ospfv2/lsdb.rs:975-1035)."""
        if self.gr_restarting:
            return  # adopt the pre-restart copy until resync completes
        t = received.type
        if t == LsaType.ROUTER:
            # Force: the received copy is already installed, so a content
            # comparison would wrongly suppress the outpacing origination.
            self._originate_router_lsa(area, force=True)
        elif t == LsaType.NETWORK:
            # Still DR for the network under the current router-id?
            if (
                net_iface is not None
                and net_iface.is_dr()
                and received.adv_rtr == self.config.router_id
            ):
                self._originate_network_lsa(area, net_iface, force=True)
            else:
                self._flush_self_lsa(area, received.key)
        elif t in (LsaType.SUMMARY_NETWORK, LsaType.SUMMARY_ROUTER):
            pass  # the next SPF run re-originates or flushes summaries
        elif t in (LsaType.AS_EXTERNAL, LsaType.NSSA_EXTERNAL):
            prefix = IPv4Network(
                (int(received.lsid), bin(int(received.body.mask)).count("1")),
                strict=False,
            )
            cur_lsid = self._external_lsids.get(prefix)
            if prefix in self.redistributed:
                self._originate_external(prefix, force=True)
                if cur_lsid is not None and cur_lsid != received.lsid:
                    # Appendix-E drift: the echo came back under a stale
                    # link-state id; the fresh origination used the current
                    # one, so the stale copy must not linger.
                    self._flush_self_lsa(area, received.key)
            else:
                self._flush_self_lsa(area, received.key)
        elif prev is not None:
            # Opaque and friends: outpace with our previous content.
            lsa = Lsa(
                age=0,
                options=prev.options,
                type=prev.type,
                lsid=prev.lsid,
                adv_rtr=prev.adv_rtr,
                seq_no=received.seq_no + 1,
                body=prev.body,
            )
            lsa.encode()
            self._install_and_flood(area, lsa)
        else:
            self._flush_self_lsa(area, received.key)

    def _nbr_counts_full(self, nbr: Neighbor) -> bool:
        """FULL, or in an open graceful-restart helper window — the helper
        keeps advertising the adjacency while the neighbor restarts
        (RFC 3623 §3.1)."""
        if nbr.state == NsmState.FULL:
            return True
        return (
            nbr.gr_deadline is not None
            and self.loop.clock.now() < nbr.gr_deadline
        )

    # -- deferred origination checks (reference lsdb.rs:589-660)

    def _queue_check(self, key: tuple, **kwargs) -> None:
        """Reference semantics (lsdb.rs:589-660): originations are deferred
        originate-check messages processed later by the instance loop.
        Production (external_orig_checks=False) runs them inline; the
        conformance harness defers them to the recorded LsaOrigCheck
        positions via flush_orig_checks — it drives the *cadence* (when
        the reference rebuilt and whether it bumped the sequence number)
        from the recording while the LSA *content* always comes from our
        own state."""
        if self.config.external_orig_checks:
            self._pending_checks[key] = kwargs
        else:
            self._run_check(key, self._build_check(key), **kwargs)

    def flush_orig_checks(
        self,
        kind: str | None = None,
        area_id: IPv4Address | None = None,
        force: bool = False,
    ) -> None:
        """Run deferred origination checks against CURRENT state.

        With ``kind`` (a recorded LsaOrigCheck position, ``area_id`` from
        its recorded lsdb_key): rebuild that LSA class in that area now.
        ``force=True`` replays a position where the reference's recorded
        body changed — the sequence number advances even when our content
        is unchanged, keeping our instance count aligned with the
        recorded ack stream.  Without ``kind`` (end-of-step quiescence):
        drain everything pending normally."""
        if kind is None:
            pending, self._pending_checks = self._pending_checks, {}
            for key, kwargs in pending.items():
                self._run_check(key, self._build_check(key), **kwargs)
            return
        keys = [
            k
            for k in self._pending_checks
            if k[0] == kind and (area_id is None or k[1] == area_id)
        ]
        if not keys:
            # The reference re-originated here from a trigger we never
            # raised: rebuild from current state so the LSDB keeps pace.
            keys = self._fallback_check_keys(kind, area_id)
        for key in keys:
            kwargs = self._pending_checks.pop(key, {})
            if force:
                kwargs = {**kwargs, "force": True}
            self._run_check(key, self._build_check(key), **kwargs)

    def _fallback_check_keys(
        self, kind: str, area_id: IPv4Address | None = None
    ):
        """Plausible check keys when a recorded check has no queued match:
        one per area (router/RI) or per DR interface (network), narrowed
        to the recorded check's area when known.  A named area we don't
        have (yet) yields nothing — widening to every area would
        force-bump unrelated LSAs."""
        if area_id is not None and area_id not in self.areas:
            return []
        aids = [area_id] if area_id is not None else list(self.areas)
        if kind in ("router", "ri"):
            return [(kind, aid) for aid in aids]
        if kind == "network":
            return [
                ("network", aid, iface.name)
                for aid in aids
                for iface in self.areas[aid].interfaces.values()
                if iface.is_dr()
            ]
        return []

    def _build_check(self, key: tuple):
        """Build the LSA body for a queued check from CURRENT state."""
        kind = key[0]
        area = self.areas.get(key[1])
        if area is None:
            return _CHECK_SKIP
        if kind == "router":
            return self._build_router_lsa(area)
        if kind == "network":
            iface = area.interfaces.get(key[2])
            if iface is None:
                return _CHECK_SKIP
            return self._build_network_lsa(area, iface)
        if kind == "ri":
            return self._build_router_info(area)
        return _CHECK_SKIP

    def _run_check(self, key: tuple, body, **kwargs) -> None:
        kind = key[0]
        area = self.areas.get(key[1])
        if area is None or body is _CHECK_SKIP:
            return
        if kind == "router":
            self._originate(
                area, LsaType.ROUTER, self.config.router_id, body, **kwargs
            )
        elif kind == "network":
            iface = area.interfaces.get(key[2])
            if iface is None:
                return
            if body is None:
                lkey = LsaKey(
                    LsaType.NETWORK, iface.addr_ip, self.config.router_id
                )
                if area.lsdb.get(lkey) is not None:
                    self._flush_self_lsa(area, lkey)
            else:
                self._originate(
                    area, LsaType.NETWORK, iface.addr_ip, body, **kwargs
                )
        elif kind == "ri":
            opts = Options(0) if area.no_type5 else Options.E
            self._originate(
                area, LsaType.OPAQUE_AREA, body[0], body[1],
                options=opts, **kwargs
            )

    def _originate_router_lsa(self, area: Area, force: bool = False) -> None:
        self._queue_check(("router", area.area_id), force=force)

    def _originate_network_lsa(
        self, area: Area, iface: OspfInterface, force: bool = False
    ) -> None:
        self._queue_check(("network", area.area_id, iface.name), force=force)

    def _originate_router_info(self, area: Area) -> None:
        self._queue_check(("ri", area.area_id))

    def _build_router_lsa(self, area: Area) -> "LsaRouter":
        links: list[RouterLink] = []
        # RFC 6987 stub-router: transit-traffic links (p2p, transit,
        # vlink) advertise MaxLinkMetric so neighbors route around us;
        # stub links keep their real cost so our own prefixes stay
        # reachable (maintenance mode).
        def transit_cost(cost: int) -> int:
            return MAX_LINK_METRIC if self.config.stub_router else cost

        # Real interfaces first, loopback host routes last (matches the
        # reference's router-LSA build order).
        ifaces = sorted(
            area.interfaces.values(), key=lambda i: i.config.loopback
        )
        for iface in ifaces:
            if iface.config.if_type == IfType.VIRTUAL_LINK:
                # §12.4.1.3: a type-4 link for each FULL virtual-link
                # neighbor, link data = our vlink interface address,
                # metric = the transit area's current path cost.
                if iface.state == IsmState.DOWN:
                    continue
                for nbr in iface.neighbors.values():
                    if self._nbr_counts_full(nbr):
                        links.append(
                            RouterLink(
                                RouterLinkType.VIRTUAL_LINK,
                                nbr.router_id,
                                iface.addr_ip,
                                transit_cost(iface.config.cost),
                            )
                        )
                continue
            if iface.state == IsmState.DOWN or iface.prefix is None:
                continue
            cost = iface.config.cost
            if iface.config.loopback:
                # Host route for the loopback address, zero cost.
                links.append(
                    RouterLink(
                        RouterLinkType.STUB_NETWORK,
                        iface.addr_ip,
                        IPv4Address("255.255.255.255"),
                        0,
                    )
                )
                continue
            if iface.config.if_type == IfType.POINT_TO_POINT:
                for nbr in iface.neighbors.values():
                    if self._nbr_counts_full(nbr):
                        links.append(
                            RouterLink(RouterLinkType.POINT_TO_POINT,
                                       nbr.router_id, iface.addr_ip,
                                       transit_cost(cost))
                        )
                links.append(
                    RouterLink(RouterLinkType.STUB_NETWORK,
                               iface.prefix.network_address,
                               mask_of(iface.prefix), cost)
                )
            else:
                dr_full = any(
                    self._nbr_counts_full(n) and n.src == iface.dr
                    for n in iface.neighbors.values()
                )
                we_are_dr_with_full = iface.is_dr() and any(
                    self._nbr_counts_full(n) for n in iface.neighbors.values()
                )
                if iface.state >= IsmState.DR_OTHER and (dr_full or we_are_dr_with_full):
                    links.append(
                        RouterLink(RouterLinkType.TRANSIT_NETWORK,
                                   iface.dr, iface.addr_ip,
                                   transit_cost(cost))
                    )
                else:
                    links.append(
                        RouterLink(RouterLinkType.STUB_NETWORK,
                                   iface.prefix.network_address,
                                   mask_of(iface.prefix), cost)
                    )
            for extra in iface.secondary:
                links.append(
                    RouterLink(RouterLinkType.STUB_NETWORK,
                               extra.network_address, mask_of(extra), cost)
                )
        flags = RouterFlags(0)
        if self.is_abr:
            flags |= RouterFlags.B
        if self.is_asbr:
            flags |= RouterFlags.E
        # §12.4.1: the V bit marks this area as the transit area of a
        # FULLY ADJACENT virtual link of ours.
        backbone = self.areas.get(IPv4Address(0))
        if backbone is not None:
            for taid, rid in self.config.virtual_links:
                if taid != area.area_id:
                    continue
                vl = backbone.interfaces.get(f"vlink-{taid}-{rid}")
                if vl is not None and any(
                    self._nbr_counts_full(n)
                    for n in vl.neighbors.values()
                ):
                    flags |= RouterFlags.V
                    break
        return LsaRouter(flags=flags, links=links)

    def _build_network_lsa(self, area: Area, iface: OspfInterface):
        """Network-LSA body for the deferred-check queue, or None when the
        LSA should be withdrawn (not DR / no full neighbors)."""
        full = [n.router_id for n in iface.neighbors.values()
                if self._nbr_counts_full(n)]
        if iface.is_dr() and full and iface.prefix is not None:
            return LsaNetwork(
                mask=mask_of(iface.prefix),
                attached=sorted([self.config.router_id] + full, key=int),
            )
        return None

    # ----- aging / refresh

    def _age_tick(self) -> None:
        now = self.loop.clock.now()
        for area in self.areas.values():
            for e in area.lsdb.refresh_due(now, self.config.router_id):
                lsa = Lsa(
                    age=0,
                    options=e.lsa.options,
                    type=e.lsa.type,
                    lsid=e.lsa.lsid,
                    adv_rtr=e.lsa.adv_rtr,
                    seq_no=next_seq_no(e.lsa),
                    body=e.lsa.body,
                )
                lsa.encode()
                self._install_and_flood(area, lsa)
            for key in area.lsdb.maxage_keys(now):
                e = area.lsdb.get(key)
                if not e.lsa.is_maxage:
                    # Newly expired: flood the MaxAge copy once (§14).
                    lsa = self._aged_copy(e)
                    self._install_and_flood(area, lsa)
                elif not self._maxage_referenced(area, key):
                    # §14 removal: no rxmt holds it and no neighbor is
                    # mid-exchange — the MaxAge copy leaves the database.
                    area.lsdb.remove(key)
                    self._link_scope_iface.pop(key, None)
        self._age_timer.start(AGE_TICK)

    def _maxage_referenced(self, area: Area, key: LsaKey) -> bool:
        for iface in area.interfaces.values():
            for nbr in iface.neighbors.values():
                if key in nbr.ls_rxmt or nbr.state in (
                    NsmState.EXCHANGE,
                    NsmState.LOADING,
                ):
                    return True
        return False

    # ----- SPF scheduling (RFC 8405 delay FSM)

    def _schedule_spf(self, trigger=None) -> None:
        """RFC 8405 SPF delay FSM (reference holo-ospf/src/spf.rs:295-484):
        QUIET→SHORT_WAIT on first IGP event (initial_delay); further events
        in SHORT_WAIT use short_delay until time_to_learn expires, then
        LONG_WAIT uses long_delay; HOLDDOWN quiet time returns to QUIET.

        ``trigger`` is the changed LSA when the event is an LSDB install;
        a trigger-less call (config/interface/clear events) marks the next
        run as unconditionally full (spf.rs:511-516 force_full_run)."""
        if trigger is None:
            self._spf_force_full = True
        else:
            self._spf_triggers.append(trigger)
        # Convergence observatory: stamp the causal event at its origin
        # (an LSA install or a trigger-less config/interface event) —
        # or inherit the already-active ids when this schedule is part
        # of a larger causal chain.  Pending ids drain at the SPF run
        # the delay FSM coalesces them into (shared contract:
        # convergence.pend_schedule / convergence.spf_run).
        convergence.pend_schedule(
            self._conv_pending,
            convergence.TRIGGER_LSA
            if trigger is not None
            else convergence.TRIGGER_IFCONFIG,
            instance=self.name,
        )
        cfg = self.config.spf
        now = self.loop.clock.now()
        self._spf_trigger_count += 1
        if self._spf_scheduled_at is None:
            self._spf_scheduled_at = now
        if self._spf_timer is None:
            self._spf_timer = self.loop.timer(self.name, SpfDelayTimerMsg)
        if self._hold_timer is None:
            self._hold_timer = self.loop.timer(self.name, SpfHoldDownMsg)
        self._hold_timer.start(cfg.hold_down)  # reset on every IGP event
        if self.spf_state == SpfFsmState.QUIET:
            self._learn_deadline = now + cfg.time_to_learn
            self.spf_state = SpfFsmState.SHORT_WAIT
            self._spf_timer.start(cfg.initial_delay)
        elif self.spf_state == SpfFsmState.SHORT_WAIT:
            if now >= (self._learn_deadline or 0):
                self.spf_state = SpfFsmState.LONG_WAIT
                self._spf_timer.start(cfg.long_delay)
            elif not self._spf_timer.armed:
                self._spf_timer.start(cfg.short_delay)
        elif self.spf_state == SpfFsmState.LONG_WAIT:
            if not self._spf_timer.armed:
                self._spf_timer.start(cfg.long_delay)

    def _spf_timer_fired(self) -> None:
        self.run_spf()

    def _spf_holddown_fired(self) -> None:
        self.spf_state = SpfFsmState.QUIET
        self._learn_deadline = None

    # ----- SPF execution + route programming

    @property
    def is_abr(self) -> bool:
        """Area border router: interfaces in more than one active area."""
        active = [
            a
            for a in self.areas.values()
            if any(i.state != IsmState.DOWN for i in a.interfaces.values())
        ]
        return len(active) > 1

    def _classify_spf(self, triggers: list) -> dict | None:
        """Full-vs-partial trigger classification (reference
        holo-ospf/src/ospfv2/spf.rs:99-171).  Returns None when a full
        SPF is required (topology changed), else the partial sets.

        Router/Network-LSA changes are topological; Opaque changes
        (RI/SR ext-prefix/ext-link) also force full because SR label
        derivation depends on them (the reference makes the same
        simplification).  Link-local opaques (Grace) never affect
        routes.  Summaries and externals are prefix-scoped."""
        from holo_tpu.utils.ip import apply_mask

        inter_network: set = set()
        inter_router: set = set()
        external: set = set()
        for new, old in triggers:
            t = new.type
            if t in (
                LsaType.ROUTER,
                LsaType.NETWORK,
                LsaType.OPAQUE_AREA,
                LsaType.OPAQUE_AS,
            ):
                return None
            if t == LsaType.OPAQUE_LINK:
                continue  # Grace-LSAs carry no routing information
            # Both versions contribute prefixes: a mask change moves the
            # prefix and the OLD one must drop its route too.
            if t == LsaType.SUMMARY_NETWORK:
                for lsa in (new, old):
                    if lsa is not None:
                        inter_network.add(apply_mask(lsa.lsid, lsa.body.mask))
            elif t == LsaType.SUMMARY_ROUTER:
                inter_router.add(new.lsid)
            elif t in (LsaType.AS_EXTERNAL, LsaType.NSSA_EXTERNAL):
                for lsa in (new, old):
                    if lsa is not None:
                        external.add(apply_mask(lsa.lsid, lsa.body.mask))
            else:
                return None  # unknown type: be safe, run full
        return {
            "inter_network": inter_network,
            "inter_router": inter_router,
            "external": external,
        }

    def run_spf(self) -> None:
        # Pending causal ids drain into an active context: route
        # publishes to the RIB (ibus requests / marshalled route_cb)
        # capture them, so the event rides through to the FIB commit.
        with convergence.spf_run(self._conv_pending, self.name):
            with telemetry.span("ospf.spf", instance=self.name):
                self._run_spf_traced()

    def _run_spf_traced(self) -> None:
        now = self.loop.clock.now()
        self.spf_run_count += 1
        start_time = now
        scheduled_at = self._spf_scheduled_at
        triggers = self._spf_trigger_count
        self._spf_scheduled_at = None
        self._spf_trigger_count = 0
        trigger_lsas = self._spf_triggers
        self._spf_triggers = []
        force_full = self._spf_force_full
        self._spf_force_full = False
        partial = None if force_full else self._classify_spf(trigger_lsas)
        if partial is not None and self._spf_cache is not None:
            _OSPF_SPF_RUNS.labels(instance=self.name, type="partial").inc()
            self._run_spf_partial(partial, scheduled_at, triggers, start_time)
            return
        _OSPF_SPF_RUNS.labels(instance=self.name, type="full").inc()
        all_routes = {}
        area_intra: dict[IPv4Address, dict] = {}
        area_results: dict[IPv4Address, tuple] = {}
        # Backbone last: its SPF consumes transit-area results for virtual
        # links (§16.1 — vlink next hops come from the transit area).
        # The vlink sync sits between the two passes: it may CREATE the
        # backbone area (a router whose only area-0 attachment is the
        # vlink itself) before the backbone pass runs.
        ordered_areas = sorted(
            self.areas.values(), key=lambda a: int(a.area_id) == 0
        )
        if self.config.virtual_links:
            ordered_areas = [
                a for a in ordered_areas if int(a.area_id) != 0
            ] + ["_vlink_sync"]
        for area in ordered_areas:
            if area == "_vlink_sync":
                self._sync_virtual_links(area_results, now)
                # Backbone pass — the sync may have just created area 0.
                ordered_areas += [
                    a for a in self.areas.values() if int(a.area_id) == 0
                ]
                continue
            iface_by_addr = {
                i.addr_ip: i.name for i in area.interfaces.values() if i.addr_ip
            }
            iface_by_nbr = {}
            p2p_nbr_addr = {}
            for i in area.interfaces.values():
                for nbr in i.neighbors.values():
                    if nbr.state == NsmState.FULL:
                        iface_by_nbr[nbr.router_id] = (i.name, nbr.src)
                        p2p_nbr_addr[(i.name, nbr.router_id)] = nbr.src
            iface_by_ifindex = {
                i.ifindex: i.name
                for i in area.interfaces.values()
                if i.ifindex
            }
            vlink_nexthops = None
            if int(area.area_id) == 0:
                vlink_nexthops = self._vlink_nexthops(area, area_results, now)
            # Interface fast-reroute SRLG config -> Topology.edge_srlg
            # (the FRR srlg_disjoint policy input; ROADMAP carry-over).
            from holo_tpu.protocols.ospf.spf_run import srlg_bits

            iface_srlg = {
                i.name: srlg_bits(i.config.srlg)
                for i in area.interfaces.values()
                if i.config.srlg
            }
            st = build_topology(
                area.lsdb, self.config.router_id, now, iface_by_addr,
                iface_by_nbr, p2p_nbr_addr, iface_by_ifindex,
                vlink_nexthops, iface_srlg=iface_srlg,
                partition_of=self.spf_partition_of,
            )
            if st is None:
                self._spf_delta_bases.pop(area.area_id, None)
                continue
            # DeltaPath seam: diff against the previous run's marshaled
            # topology so the backend can update the device-resident
            # graph in place instead of re-marshaling the area LSDB.
            link_spf_delta(self._spf_delta_bases.get(area.area_id), st)
            self._spf_delta_bases[area.area_id] = st
            res = self.backend.compute(
                st.topo, multipath_k=self._multipath_k()
            )
            area_results[area.area_id] = (st, res)
            # Reachable routers per area WITH their flags as of this SPF
            # run: operational state serves abr-count/asbr-count from the
            # SPF products (reference area.rs:164-182 counts
            # area.state.routers, whose flags were captured at route
            # computation — NOT the live LSDB, which may have changed
            # since, e.g. right after a clear-database RPC).
            from holo_tpu.ops.graph import INF as _INF

            flags_now = {}
            for key, e in area.lsdb.entries.items():
                if key.type == LsaType.ROUTER and not e.lsa.is_maxage:
                    flags_now[key.adv_rtr] = e.lsa.body.flags
            self._area_reachable_routers[area.area_id] = {
                rid: flags_now.get(rid, RouterFlags(0))
                for rid, v in st.router_index.items()
                if res.dist[v] < _INF
            }
            intra = derive_routes(
                st, res, area.lsdb, now, area.area_id,
                max_paths=self.config.max_paths,
            )
            area_intra[area.area_id] = intra
            for prefix, route in intra.items():
                cur = all_routes.get(prefix)
                if cur is None or route.dist < cur.dist or (
                    route.dist == cur.dist and int(route.area_id) < int(cur.area_id)
                ):
                    all_routes[prefix] = route

        # IP-FRR: one batched backup-table dispatch per area right after
        # the primary SPF (the reference hangs TI-LFA off the same
        # moment) — all-roots distance matrix + per-link post-convergence
        # planes + vectorized LFA/rLFA/TI-LFA selection.
        engine = self._frr_engine_for()
        if engine is not None:
            self.frr_tables = {
                aid: engine.compute(st.topo)
                for aid, (st, _res) in area_results.items()
            }
        else:
            self.frr_tables = {}

        # Advisory what-if batches ride the async pipeline (PR 9
        # follow-up); enqueue-only — nothing here waits on them.
        self._enqueue_whatif_advisory(area_results)

        # Inter-area routes (RFC 2328 §16.2): shared consumption stage
        # (also used by the partial run with a prefix scope).
        intra_prefixes = set(all_routes.keys())
        inter_routes: dict = {}
        self._derive_inter_area(
            area_results, all_routes, inter_routes, intra_prefixes
        )

        # ABR: (re-)originate Summary LSAs — each area's intra routes are
        # advertised into every other attached area (loop-free: summaries
        # are never derived from summaries).
        # AS-external routes (lowest preference — only for unknown prefixes).
        for prefix, route in self._external_routes(
            area_results, set(all_routes.keys())
        ).items():
            all_routes[prefix] = route

        self._nssa_translate(area_results)
        if self.is_abr:
            self._originate_summaries(area_intra, inter_routes)
            self._originate_asbr_summaries(area_results)
        else:
            # No longer (or never) an ABR: flush any self-originated
            # summaries or neighbors would route into a dead hierarchy
            # forever (refresh would keep them alive otherwise).
            for area in self.areas.values():
                for key in list(area.lsdb.entries):
                    if (
                        key.type == LsaType.SUMMARY_NETWORK
                        and key.adv_rtr == self.config.router_id
                        and not area.lsdb.entries[key].lsa.is_maxage
                    ):
                        self._flush_self_lsa(area, key)

        # SPF log ring (32 entries, reference spf.rs:770-804).
        self.spf_log.append(
            {
                "run": self.spf_run_count,
                "type": "full",
                "backend": self.backend.name,
                "scheduled-at": scheduled_at,
                "start-time": start_time,
                "end-time": self.loop.clock.now(),
                "trigger-count": triggers,
                "route-count": len(all_routes),
            }
        )
        del self.spf_log[:-32]

        # Cache this run's products: a later summary/external-only change
        # reuses the per-area SPTs and rewrites only the affected table
        # entries (reference route.rs:200-333 update_rib_partial).
        self._spf_cache = {
            "area_results": area_results,
            "area_intra": area_intra,
            "routes": all_routes,
            "inter_routes": inter_routes,
        }

        self._finish_spf(all_routes)

    def _derive_inter_area(
        self,
        area_results: dict,
        routes: dict,
        inter_routes: dict,
        intra_prefixes: set,
        only: set | None = None,
    ) -> bool:
        """Summary-LSA consumption (RFC 2328 §16.2): distance to the
        advertising ABR from the cached/current SPT plus the advertised
        metric; intra-area always preferred, inter-area displaces
        externals (path-type preference, §11).  Shared by the full and
        partial runs — ``only`` scopes a partial run to the changed
        prefixes.  Returns whether anything changed."""
        from holo_tpu.protocols.ospf.spf_run import IntraRoute, _atoms_of
        from holo_tpu.utils.ip import apply_mask

        now = self.loop.clock.now()
        changed = False
        for area in self.areas.values():
            sr = area_results.get(area.area_id)
            if sr is None:
                continue
            st, res = sr
            for e in area.lsdb.all():
                lsa = e.lsa
                if (
                    lsa.type != LsaType.SUMMARY_NETWORK
                    or lsa.adv_rtr == self.config.router_id
                    or e.current_age(now) >= MAX_AGE
                ):
                    continue
                if self.is_abr and int(area.area_id) != 0:
                    # §16.2: ABRs examine backbone summaries only — transit
                    # through non-backbone areas would break the hierarchy.
                    continue
                prefix = apply_mask(lsa.lsid, lsa.body.mask)
                if only is not None and prefix not in only:
                    continue  # partial run: out-of-scope prefix
                if prefix in intra_prefixes:
                    continue  # intra-area preferred
                abr_v = st.router_index.get(lsa.adv_rtr)
                if abr_v is None or res.dist[abr_v] >= 0x40000000:
                    continue
                dist = int(res.dist[abr_v]) + lsa.body.metric
                nhs = _atoms_of(res.nexthop_words[abr_v], st.atoms)
                cur = routes.get(prefix)
                if cur is not None and cur.rtype not in ("intra", "inter"):
                    # Path-type preference, not distance: inter-area
                    # always displaces an external entry (§11).  Only
                    # reachable in partial runs — the full run computes
                    # externals after this stage.
                    cur = None
                if cur is None or dist < cur.dist:
                    # vertex = the advertising ABR: FRR protects the
                    # path toward the area-exit router (the repair
                    # covers the intra-area leg, like the reference).
                    route = IntraRoute(
                        prefix, dist, nhs, area.area_id, "inter", vertex=abr_v
                    )
                    routes[prefix] = route
                    inter_routes[prefix] = route
                    changed = True
                elif dist == cur.dist and cur.rtype == "inter":
                    # Equal-cost inter-area paths union their next hops.
                    # (area_id, vertex) is the FRR consumption key and
                    # must stay a consistent pair — keep the first
                    # contributing area's, like the v3 merge.
                    route = IntraRoute(
                        prefix, dist, cur.nexthops | nhs, cur.area_id,
                        "inter", vertex=cur.vertex,
                    )
                    routes[prefix] = route
                    inter_routes[prefix] = route
                    changed = True
        return changed

    def _run_spf_partial(
        self, partial: dict, scheduled_at, triggers: int, start_time: float
    ) -> None:
        """Prefix-scoped route recomputation over the cached SPTs —
        no Dijkstra runs (reference route.rs:200-333).

        In OSPFv2 intra-area information lives in Router/Network-LSAs,
        which always force a full run, so only the inter-area and
        external stages apply (ospfv2/spf.rs:124-126)."""
        cache = self._spf_cache
        area_results = cache["area_results"]
        area_intra = cache["area_intra"]
        routes = dict(cache["routes"])
        inter_routes = dict(cache["inter_routes"])
        now = self.loop.clock.now()
        inter_network = set(partial["inter_network"])
        inter_router = set(partial["inter_router"])
        external = set(partial["external"])

        inter_changed = False
        if inter_network:
            # Remove affected inter-area routes, then re-derive them for
            # exactly those prefixes from the cached per-area SPTs.
            removed: set = set()
            for prefix in inter_network:
                r = routes.get(prefix)
                if r is not None and r.rtype == "inter":
                    del routes[prefix]
                    inter_routes.pop(prefix, None)
                    removed.add(prefix)
            intra_prefixes = {
                p for p, r in routes.items() if r.rtype == "intra"
            }
            inter_changed = self._derive_inter_area(
                area_results, routes, inter_routes, intra_prefixes,
                only=inter_network,
            )
            # Destinations now newly unreachable fall through to the
            # external stage for alternate paths (route.rs:234-237).
            external |= {p for p in removed if p not in routes}
            inter_changed = inter_changed or bool(removed)

        if inter_router or external:
            # A type-4 change alters ASBR reachability, which can affect
            # ANY external route — re-evaluate them all (route.rs:302-306);
            # otherwise only the changed prefixes.
            reevaluate_all = bool(inter_router)
            ext_types = ("external-1", "external-2", "nssa-1", "nssa-2")
            for prefix in list(routes):
                r = routes[prefix]
                if r.rtype in ext_types and (
                    reevaluate_all or prefix in external
                ):
                    del routes[prefix]
            known = set(routes.keys())
            new_ext = self._external_routes(
                area_results,
                known,
                only=None if reevaluate_all else external,
            )
            routes.update(new_ext)
            # Type-7 changes can shift the NSSA translator's output set.
            if external and any(a.nssa for a in self.areas.values()):
                self._nssa_translate(area_results)

        # ABR summary re-origination: inter routes feed non-backbone
        # summaries, so a changed inter table re-runs origination over
        # the cached intra inputs.
        if inter_changed and self.is_abr:
            self._originate_summaries(area_intra, inter_routes)

        log_type = "inter" if inter_network else "external"
        self.spf_log.append(
            {
                "run": self.spf_run_count,
                "type": log_type,
                "backend": self.backend.name,
                "scheduled-at": scheduled_at,
                "start-time": start_time,
                "end-time": self.loop.clock.now(),
                "trigger-count": triggers,
                "route-count": len(routes),
            }
        )
        del self.spf_log[:-32]

        cache["routes"] = routes
        cache["inter_routes"] = inter_routes
        self._finish_spf(routes)

    def reoriginate_summaries(self) -> None:
        """Config-triggered summary refresh (ranges / totally-stubby /
        default-cost changed): re-run origination over the LAST SPF's
        routing inputs without recomputing routes."""
        if getattr(self, "_last_summary_inputs", None) is not None:
            self._originate_summaries(*self._last_summary_inputs)

    def _originate_summaries(self, area_intra: dict, inter_routes: dict) -> None:
        """ABR summary generation: intra-area routes of each area go into
        every other attached area; inter-area routes learned via the
        BACKBONE are re-summarized into non-backbone areas (the standard
        loop-free hierarchy, RFC 2328 §12.4.3)."""
        from holo_tpu.utils.ip import mask_of

        self._last_summary_inputs = (area_intra, inter_routes)
        backbone = IPv4Address(0)
        wanted: dict[IPv4Address, dict] = {aid: {} for aid in self.areas}

        area_ifnames = {
            aid: frozenset(a.interfaces) for aid, a in self.areas.items()
        }

        def _nexthops_in_area(route, dst_aid) -> bool:
            # area.rs:628-630 split horizon: never summarize a route
            # into the area its next hops already exit through (the
            # vlink-transit case).
            names = area_ifnames.get(dst_aid, frozenset())
            return any(
                nh.ifname in names
                for nh in getattr(route, "nexthops", ())
                if nh.ifname is not None
            )
        for src_aid, routes in area_intra.items():
            if src_aid not in self.areas:
                continue  # area deleted since that SPF ran
            # Area address ranges (§12.4.3 / Appendix C.2): components of
            # an active advertised range aggregate into the range prefix
            # at the max component distance (or its configured cost);
            # advertise=false ranges black-hole their components.
            src_ranges = self.areas[src_aid].ranges
            eff: dict = {}
            range_max: dict = {}
            # Areas a range's COMPONENT routes exit through: the split
            # horizon below must also cover range aggregates.
            range_nh_areas: dict = {}
            for prefix, route in routes.items():
                matches = [
                    r for r in src_ranges if prefix.subnet_of(r["prefix"])
                ]
                # Most-specific range wins (Appendix C.2 semantics).
                rng = max(
                    matches,
                    key=lambda r: r["prefix"].prefixlen,
                    default=None,
                )
                if rng is None:
                    eff[prefix] = route.dist
                elif rng.get("advertise", True):
                    cur = range_max.get(rng["prefix"], -1)
                    range_max[rng["prefix"]] = max(cur, route.dist)
                    acc = range_nh_areas.setdefault(
                        rng["prefix"], set()
                    )
                    for aid2 in self.areas:
                        if _nexthops_in_area(route, aid2):
                            acc.add(aid2)
            for r in src_ranges:
                if r["prefix"] in range_max:
                    eff[r["prefix"]] = (
                        r["cost"]
                        if r.get("cost") is not None
                        else range_max[r["prefix"]]
                    )
            for prefix, dist in eff.items():
                for dst_aid in self.areas:
                    if dst_aid == src_aid:
                        continue
                    r = routes.get(prefix)
                    if r is not None and _nexthops_in_area(r, dst_aid):
                        continue
                    if dst_aid in range_nh_areas.get(prefix, ()):
                        continue  # aggregate: component split horizon
                    cur = wanted[dst_aid].get(prefix)
                    if cur is None or dist < cur:
                        wanted[dst_aid][prefix] = dist
        for prefix, route in inter_routes.items():
            if route.area_id != backbone:
                continue
            for dst_aid in self.areas:
                if dst_aid == backbone:
                    continue
                if _nexthops_in_area(route, dst_aid):
                    continue
                cur = wanted[dst_aid].get(prefix)
                if cur is None or route.dist < cur:
                    wanted[dst_aid][prefix] = route.dist
        # Stub areas get a default summary instead of type-5s (§12.4.3.1);
        # NSSAs get a default type-7 (RFC 3101 §2.4, P=0 so it is never
        # translated back out).
        default = IPv4Network("0.0.0.0/0")
        for aid, area in self.areas.items():
            if (area.stub or area.nssa) and not area.summary:
                # Totally stubby: the default is the only summary.
                wanted[aid].clear()
            if area.stub:
                wanted[aid][default] = area.stub_default_cost
            elif area.nssa and default not in self.redistributed:
                # Injected ABR default (skipped when the operator
                # redistributes 0.0.0.0/0 — that type-7 owns the lsid).
                from holo_tpu.protocols.ospf.packet import LsaAsExternal

                self._originate(
                    area,
                    LsaType.NSSA_EXTERNAL,
                    IPv4Address(0),
                    LsaAsExternal(
                        mask=IPv4Address(0), e_bit=True,
                        metric=area.stub_default_cost,
                        fwd_addr=IPv4Address(0), tag=0,
                    ),
                    options=Options(0),
                )
        for aid, prefixes in wanted.items():
            area = self.areas[aid]
            # Link-state-ID assignment with the RFC 2328 Appendix E rule:
            # prefixes sharing a network address get host bits set on the
            # more specific ones so their LSA keys stay distinct.
            by_net: dict[IPv4Address, list] = {}
            for p in prefixes:
                by_net.setdefault(p.network_address, []).append(p)
            lsid_of = {}
            for net, group in by_net.items():
                group.sort(key=lambda p: p.prefixlen)
                lsid_of[group[0]] = net
                for p in group[1:]:
                    lsid_of[p] = IPv4Address(
                        int(net) | (~int(mask_of(p)) & 0xFFFFFFFF)
                    )
            wanted_lsids = set(lsid_of.values())
            # Flush summaries we no longer want in this area.
            for key in list(area.lsdb.entries):
                if (
                    key.type == LsaType.SUMMARY_NETWORK
                    and key.adv_rtr == self.config.router_id
                    and key.lsid not in wanted_lsids
                ):
                    if not area.lsdb.entries[key].lsa.is_maxage:
                        self._flush_self_lsa(area, key)
            for prefix, dist in prefixes.items():
                from holo_tpu.protocols.ospf.packet import LsaSummary

                self._originate(
                    area,
                    LsaType.SUMMARY_NETWORK,
                    lsid_of[prefix],
                    LsaSummary(mask_of(prefix), dist),
                    # Stub/NSSA areas clear the E option (no external
                    # routing capability inside, RFC 2328 §12.1.2).
                    options=Options(0) if area.no_type5 else Options.E,
                )

    def add_virtual_link(
        self, transit_area_id: IPv4Address, peer_rid: IPv4Address
    ) -> None:
        """Configure a §15 virtual link; it comes up when the peer is
        reachable through the transit area (next SPF run)."""
        entry = (transit_area_id, peer_rid)
        if entry not in self.config.virtual_links:
            self.config.virtual_links = self.config.virtual_links + (entry,)
        self._schedule_spf()

    def _vlink_endpoint_addr(
        self, transit: Area, peer_rid: IPv4Address, now: float
    ) -> IPv4Address | None:
        """The peer's transit-area interface address (§15.1: learned from
        its router-LSA in the transit area) — the vlink's unicast dst."""
        e = transit.lsdb.get(
            LsaKey(LsaType.ROUTER, peer_rid, peer_rid)
        )
        if e is None or e.current_age(now) >= MAX_AGE:
            return None
        # First p2p/transit link's data, exactly like the reference
        # (ospfv2/area.rs:75-95 vlink_neighbor_addr) — in deployment the
        # unicast is routed to the peer regardless of which of its
        # transit-area addresses is picked.
        return next(
            (
                link.data
                for link in e.lsa.body.links
                if link.link_type
                in (
                    RouterLinkType.POINT_TO_POINT,
                    RouterLinkType.TRANSIT_NETWORK,
                )
            ),
            None,
        )

    def _sync_virtual_links(self, area_results: dict, now: float) -> None:
        """Bring configured virtual links up/down from transit-area SPF
        reachability (reference interface.rs:50,84,135-148): a reachable
        endpoint materializes an unnumbered point-to-point interface in
        the BACKBONE whose packets ride the transit area's shortest path;
        an unreachable one tears the interface (and adjacency) down."""
        from holo_tpu.ops.graph import INF as _INF
        from holo_tpu.protocols.ospf.spf_run import _atoms_of

        wanted: dict[str, tuple] = {}
        # Virtual links only activate on ABRs (reference area.rs:304-306).
        vlinks = self.config.virtual_links if self.is_abr else ()
        for taid, rid in vlinks:
            transit = self.areas.get(taid)
            got = area_results.get(taid)
            if transit is None or transit.stub or transit.nssa or got is None:
                continue
            st, res = got
            v = st.router_index.get(rid)
            # §15.1: a path cost at or above LSInfinity means the
            # endpoint is unusable — the vlink stays down rather than
            # advertising a wrapped 16-bit metric.
            if v is None or res.dist[v] >= min(_INF, 0xFFFF):
                continue
            # The endpoint must itself be an ABR (reference area.rs:314).
            pe = transit.lsdb.get(LsaKey(LsaType.ROUTER, rid, rid))
            if pe is None or not (pe.lsa.body.flags & RouterFlags.B):
                continue
            nhs = _atoms_of(res.nexthop_words[v], st.atoms)
            # Deterministic egress for the unnumbered link-data: the
            # lowest-addressed transit interface among the ECMP set.
            cands = sorted(
                (
                    n
                    for n in (
                        nh.ifname for nh in nhs if nh.ifname is not None
                    )
                    if n in transit.interfaces
                    and transit.interfaces[n].addr_ip is not None
                ),
                key=lambda n: int(transit.interfaces[n].addr_ip),
            )
            out_if = cands[0] if cands else None
            dst = self._vlink_endpoint_addr(transit, rid, now)
            if out_if is None or dst is None:
                continue
            phys = transit.interfaces.get(out_if)
            if phys is None or phys.addr_ip is None:
                continue
            wanted[f"vlink-{taid}-{rid}"] = (
                taid, rid, dst, out_if, phys.addr_ip, int(res.dist[v]),
                phys.config.auth,
            )
        backbone = self.areas.get(IPv4Address(0))
        if backbone is None:
            if not wanted:
                return
            # A vlink IS the router's backbone attachment (§15): area 0
            # springs into existence with the first RESOLVED vlink, with
            # the same new-area hooks add_interface runs.
            backbone = self.areas[IPv4Address(0)] = Area(IPv4Address(0))
            for prefix in list(self.redistributed):
                self._originate_external(prefix)
            self._originate_router_info(backbone)
        # Tear down vlinks that lost their transit path.
        for name in [
            n
            for n, i in backbone.interfaces.items()
            if i.config.if_type == IfType.VIRTUAL_LINK and n not in wanted
        ]:
            self.if_down(name)
            del backbone.interfaces[name]
            self._if_area.pop(name, None)
        # Bring up / refresh the rest.
        changed = False
        for name, (taid, rid, dst, out_if, src, cost, auth) in wanted.items():
            iface = backbone.interfaces.get(name)
            if iface is None:
                iface = OspfInterface(
                    name=name,
                    config=IfConfig(
                        area_id=backbone.area_id,
                        if_type=IfType.VIRTUAL_LINK,
                        cost=cost,
                        hello_interval=self.config.vlink_hello_interval,
                        dead_interval=self.config.vlink_dead_interval,
                        # Vlink packets arrive on (and are decoded with)
                        # the transit interface — send with its auth.
                        auth=auth,
                    ),
                    addr_ip=src,
                    vlink_peer=rid,
                    vlink_transit=taid,
                    vlink_dst=dst,
                    vlink_out_ifname=out_if,
                )
                backbone.interfaces[name] = iface
                self._if_area[name] = backbone.area_id
                self._set_ism_state(iface, IsmState.POINT_TO_POINT)
                self._timer(
                    ("hello", name), lambda n=name: HelloTimerMsg(n)
                ).start(0.0)
                changed = True
            else:
                # Any dynamic-parameter change re-originates the backbone
                # router-LSA (reference area.rs:339-371: nbr_addr /
                # src_addr / cost changes all resync advertisement).
                if (
                    iface.vlink_dst,
                    iface.vlink_out_ifname,
                    iface.addr_ip,
                    iface.config.cost,
                ) != (dst, out_if, src, cost):
                    iface.vlink_dst = dst
                    iface.vlink_out_ifname = out_if
                    iface.addr_ip = src
                    iface.config.cost = cost
                    iface.config.auth = auth
                    changed = True
        if changed:
            self._originate_router_lsa(backbone)

    def _vlink_nexthops(self, backbone: Area, area_results: dict, now) -> dict:
        """{vlink neighbor rid: frozenset[RouteNexthop]} — the transit
        area's next hops toward each virtual-link neighbor named in our
        backbone router LSA."""
        from holo_tpu.protocols.ospf.spf_run import _atoms_of

        key = LsaKey(
            LsaType.ROUTER, self.config.router_id, self.config.router_id
        )
        e = backbone.lsdb.get(key)
        if e is None:
            return {}
        from holo_tpu.ops.graph import INF

        # The transit area is the one actually carrying the vlink
        # (§16.1): shortest intra-area path to the endpoint; equal-cost
        # paths through DIFFERENT transit areas union their next hops
        # (parallel virtual links, reference topo3-3).
        best: dict = {}  # rid -> (dist, area id of first best, nhs)
        for link in e.lsa.body.links:
            if link.link_type != RouterLinkType.VIRTUAL_LINK:
                continue
            for aid, (st, res) in area_results.items():
                v = st.router_index.get(link.id)
                if v is None or res.dist[v] >= INF:
                    continue
                nhs = _atoms_of(res.nexthop_words[v], st.atoms)
                if not nhs:
                    continue
                cand = (int(res.dist[v]), int(aid))
                cur = best.get(link.id)
                if cur is None or cand[0] < cur[0]:
                    best[link.id] = (*cand, nhs)
                elif cand[0] == cur[0]:
                    # Parallel virtual links through different transit
                    # areas at equal cost: ECMP union (reference
                    # topo3-3 shape).
                    best[link.id] = (cur[0], cur[1], cur[2] | nhs)
        return {rid: nhs for rid, (_d, _a, nhs) in best.items()}

    def _originate_asbr_summaries(self, area_results: dict) -> None:
        """ABR: type-4 ASBR-summary LSAs (§12.4.3) so other areas can
        resolve ASBRs they cannot see in their own SPF."""
        from holo_tpu.protocols.ospf.packet import LsaSummary

        now = self.loop.clock.now()
        # ASBRs reachable per area: routers whose router-LSA carries E.
        asbr_dist: dict[IPv4Address, tuple[IPv4Address, int]] = {}
        for aid, (st, res) in area_results.items():
            area = self.areas[aid]
            for e in area.lsdb.all():
                lsa = e.lsa
                if (
                    lsa.type != LsaType.ROUTER
                    or not (lsa.body.flags & RouterFlags.E)
                    or lsa.adv_rtr == self.config.router_id
                    or e.current_age(now) >= MAX_AGE
                ):
                    continue
                v = st.router_index.get(lsa.adv_rtr)
                if v is None or res.dist[v] >= 0x40000000:
                    continue
                d = int(res.dist[v])
                cur = asbr_dist.get(lsa.adv_rtr)
                if cur is None or d < cur[1]:
                    asbr_dist[lsa.adv_rtr] = (aid, d)
        wanted_per_area: dict[IPv4Address, dict] = {
            aid: {} for aid in self.areas
        }
        for asbr, (src_aid, d) in asbr_dist.items():
            for dst_aid, dst_area in self.areas.items():
                if dst_aid != src_aid and not dst_area.stub:
                    # §12.4.3.1: no type-4s into stub areas (no type-5s
                    # there to resolve).
                    wanted_per_area[dst_aid][asbr] = d
        zero_mask = IPv4Address(0)
        for aid, wanted in wanted_per_area.items():
            area = self.areas[aid]
            for key in list(area.lsdb.entries):
                if (
                    key.type == LsaType.SUMMARY_ROUTER
                    and key.adv_rtr == self.config.router_id
                    and key.lsid not in wanted
                    and not area.lsdb.entries[key].lsa.is_maxage
                ):
                    self._flush_self_lsa(area, key)
            for asbr, d in wanted.items():
                self._originate(
                    area, LsaType.SUMMARY_ROUTER, asbr,
                    LsaSummary(zero_mask, d),
                )

    # ----- segment routing (RFC 8665 prefix-SIDs over RFC 7684 LSAs)

    def _originate_prefix_sids(self) -> None:
        sr = self.config.sr
        if sr is None or not sr.enabled:
            return
        from holo_tpu.protocols.ospf.packet import (
            LsaOpaque,
            encode_ext_prefix_sid,
            ext_prefix_lsid,
        )

        # Stable opaque-id per prefix (never reused) so removals can be
        # flushed and reorderings can't cross LSAs.
        for prefix in sr.prefix_sids:
            self._alloc_ext_prefix_opaque_id(("sr", prefix))
        for key, opaque_id in list(self._ext_prefix_opaque_ids.items()):
            if key[0] != "sr":
                continue
            prefix = key[1]
            psid = sr.prefix_sids.get(prefix)
            lsid = ext_prefix_lsid(opaque_id)
            if psid is None:
                key = LsaKey(LsaType.OPAQUE_AREA, lsid, self.config.router_id)
                for area in self.areas.values():
                    self._flush_self_lsa(area, key)
                continue
            flags = 0x40 if psid.no_php else 0
            body = LsaOpaque(
                encode_ext_prefix_sid(psid.prefix, psid.index, flags)
            )
            for area in self.areas.values():
                self._originate(area, LsaType.OPAQUE_AREA, lsid, body)

    def _resolve_sr_labels(self, all_routes: dict) -> dict:
        """prefix → (local label, route) for every prefix-SID heard,
        resolved through the SRGB (reference holo-ospf/src/sr.rs)."""
        sr = self.config.sr
        if sr is None or not sr.enabled:
            return {}
        from holo_tpu.protocols.ospf.packet import decode_ext_prefix_sid

        now = self.loop.clock.now()
        out = {}
        for area in self.areas.values():
            for e in area.lsdb.all():
                lsa = e.lsa
                if (
                    lsa.type != LsaType.OPAQUE_AREA
                    or (int(lsa.lsid) >> 24) != 7
                    or e.current_age(now) >= MAX_AGE
                ):
                    continue
                parsed = decode_ext_prefix_sid(lsa.body.data)
                if parsed is None:
                    continue
                prefix, sid_index, _flags = parsed
                label = sr.srgb.label_of(sid_index)
                route = all_routes.get(prefix)
                if label is not None and route is not None:
                    out[prefix] = (label, route)
        return out

    def _alloc_ext_prefix_opaque_id(self, key: tuple) -> int:
        if key not in self._ext_prefix_opaque_ids:
            self._ext_prefix_opaque_ids[key] = len(
                self._ext_prefix_opaque_ids
            )
        return self._ext_prefix_opaque_ids[key]

    def update_ext_prefix_flags(self) -> None:
        """Originate (or flush) the extended-prefix attribute LSA
        carrying N/AC flags for interface addresses (reference
        ospfv2/lsdb.rs:760-800: lsa-id 7.0.0.0, one TLV per flagged
        address; N for node-flag host addresses, else AC for
        anycast-flag interfaces)."""
        from holo_tpu.protocols.ospf.packet import (
            EXT_PREFIX_FLAG_AC,
            EXT_PREFIX_FLAG_N,
            LsaOpaque,
            encode_ext_prefix_flags,
        )

        lsid = IPv4Address(7 << 24)  # opaque type 7, opaque id 0
        for area in self.areas.values():
            entries = []
            for iface in area.interfaces.values():
                if iface.state == IsmState.DOWN:
                    continue
                addrs = []
                if iface.prefix is not None:
                    addrs.append(iface.prefix)
                addrs.extend(iface.secondary)
                for prefix in addrs:
                    if (
                        iface.config.node_flag
                        and prefix.prefixlen == 32
                    ):
                        entries.append((prefix, EXT_PREFIX_FLAG_N))
                    elif iface.config.anycast_flag:
                        entries.append((prefix, EXT_PREFIX_FLAG_AC))
            if entries:
                body = LsaOpaque(encode_ext_prefix_flags(sorted(
                    entries, key=lambda e: (int(e[0].network_address), e[0].prefixlen)
                )))
                self._originate(area, LsaType.OPAQUE_AREA, lsid, body)
            else:
                key = LsaKey(
                    LsaType.OPAQUE_AREA, lsid, self.config.router_id
                )
                self._flush_self_lsa(area, key)

    # ----- BIER underlay (RFC 9089 over RFC 7684 LSAs)

    def _originate_bier(self) -> None:
        bier = self.config.bier
        if bier is None or not bier.enabled():
            # Withdraw any previously advertised sub-domains.
            from holo_tpu.protocols.ospf.packet import ext_prefix_lsid

            for key, opaque_id in self._ext_prefix_opaque_ids.items():
                if key[0] != "bier":
                    continue
                lsa_key = LsaKey(
                    LsaType.OPAQUE_AREA,
                    ext_prefix_lsid(opaque_id),
                    self.config.router_id,
                )
                for area in self.areas.values():
                    self._flush_self_lsa(area, lsa_key)
            return
        from holo_tpu.protocols.ospf.packet import (
            LsaOpaque,
            encode_ext_prefix_bier,
            ext_prefix_lsid,
        )

        for sd_id, sd in sorted(bier.sub_domains.items()):
            if sd.bfr_prefix is None:
                continue
            self._alloc_ext_prefix_opaque_id(("bier", sd_id))
        for key, opaque_id in list(self._ext_prefix_opaque_ids.items()):
            if key[0] != "bier":
                continue
            sd = bier.sub_domains.get(key[1])
            lsid = ext_prefix_lsid(opaque_id)
            if sd is None or sd.bfr_prefix is None:
                # Sub-domain removed: withdraw the advertisement.
                lsa_key = LsaKey(
                    LsaType.OPAQUE_AREA, lsid, self.config.router_id
                )
                for area in self.areas.values():
                    self._flush_self_lsa(area, lsa_key)
                continue
            body = LsaOpaque(
                encode_ext_prefix_bier(
                    sd.bfr_prefix, key[1], sd.bfr_id, sd.encaps
                )
            )
            for area in self.areas.values():
                self._originate(area, LsaType.OPAQUE_AREA, lsid, body)

    def _resolve_bier(self, all_routes: dict) -> dict:
        """prefix -> (BierInfo, route) for every BFR prefix heard in a
        locally configured sub-domain (reference holo-ospf/src/bier.rs:
        bier_route_add filters on the shared sub-domain config)."""
        bier = self.config.bier
        if bier is None or not bier.enabled():
            return {}
        from holo_tpu.protocols.ospf.packet import decode_ext_prefix_bier
        from holo_tpu.utils.bier import BierInfo

        now = self.loop.clock.now()
        out = {}
        for area in self.areas.values():
            for e in area.lsdb.all():
                lsa = e.lsa
                if (
                    lsa.type != LsaType.OPAQUE_AREA
                    or (int(lsa.lsid) >> 24) != 7
                    or e.current_age(now) >= MAX_AGE
                ):
                    continue
                parsed = decode_ext_prefix_bier(lsa.body.data)
                if parsed is None:
                    continue
                prefix, sd_id, _mt, bfr_id, bsls = parsed
                if sd_id not in bier.sub_domains or not bsls:
                    continue
                route = all_routes.get(prefix)
                if route is not None:
                    out[prefix] = (
                        BierInfo(sd_id=sd_id, bfr_id=bfr_id, bfr_bss=bsls),
                        route,
                    )
        return out

    def _multipath_k(self) -> int:
        """The SPF dispatch's parent-set width: ``max-paths`` when it
        limits ECMP (2..8 → the vectorized multipath kernel with UCMP
        weights), else 1 (the unchanged single-parent program)."""
        m = self.config.max_paths
        return m if (m is not None and m > 1) else 1

    def _enqueue_whatif_advisory(self, area_results: dict) -> None:
        """Protocol-level consumption of ``compute_whatif_async`` (PR 9
        follow-up): after each full SPF, enqueue an advisory batch of
        single-link-failure scenarios per area through the async
        pipeline.  Purely advisory — nothing on the SPF path waits for
        the results; a storm's batches coalesce (newer SPF generation
        supersedes a queued older one) and breaker-open batches are
        skipped, both visible in ``holo_pipeline_coalesced_total`` /
        ``holo_pipeline_breaker_skip_total``."""
        budget = int(self.config.whatif_advisory or 0)
        enqueue = getattr(self.backend, "compute_whatif_async", None)
        if budget <= 0 or enqueue is None:
            return
        import numpy as np

        for aid, (st, _res) in area_results.items():
            topo = st.topo
            if topo.n_edges == 0:
                continue
            pair: dict = {}
            for e in range(topo.n_edges):
                pair.setdefault(
                    (int(topo.edge_src[e]), int(topo.edge_dst[e])), e
                )
            n = min(budget, topo.n_edges)
            masks = np.ones((n, topo.n_edges), bool)
            row = 0
            for e in range(topo.n_edges):
                if row >= n:
                    break
                rev = pair.get(
                    (int(topo.edge_dst[e]), int(topo.edge_src[e]))
                )
                if rev is not None and rev < e:
                    # The reverse direction already produced this
                    # link's scenario: one row per LINK, not per
                    # directed edge, or half the budget is duplicates.
                    continue
                # Mask both directions of the link (§16.1 contract).
                masks[row, e] = False
                if rev is not None:
                    masks[row, rev] = False
                row += 1
            ticket = enqueue(
                topo, masks[:row], generation=self.spf_run_count
            )
            self._whatif_tickets[aid] = ticket
            self._whatif_stats["enqueued"] += 1
            ticket.add_done_callback(self._whatif_done)

    def _whatif_done(self, _ticket) -> None:
        # Worker-thread callback: a plain counter bump only (ints are
        # GIL-atomic; the advisory results themselves stay on the
        # ticket for operational-state readers).
        self._whatif_stats["completed"] += 1

    def _frr_tables_ready(self) -> None:
        """Actor-side completion of a deferred FRR attach: join the
        (now materialized) backup tables onto the current routes and
        republish the prefixes that gained backups."""
        if not self._frr_attach_deferred or self._spf_cache is None:
            return
        self._frr_attach_deferred = False
        import copy as _copy

        routes = self.routes
        before = {p: r.backups for p, r in routes.items()}
        # NOT deferred=True: a newer SPF may have swapped in tables
        # that are THEMSELVES still in flight — the pending check then
        # re-defers (fresh callbacks) instead of forcing them here.
        self._attach_frr_backups(routes)
        if self._frr_attach_deferred:
            return
        old = {}
        for p, r in routes.items():
            if (r.backups or None) != (before.get(p) or None):
                c = _copy.copy(r)
                c.backups = before.get(p)
                old[p] = c
            else:
                old[p] = r
        if self.ibus is not None:
            self._sync_rib(old, routes)

    def _frr_engine_for(self):
        """The instance's FrrEngine when fast reroute is configured."""
        cfg = self.config.frr
        if cfg is None or not cfg.active():
            return None
        from holo_tpu.frr.manager import ensure_engine

        self._frr_engine = ensure_engine(self._frr_engine, cfg)
        return self._frr_engine

    def _attach_frr_backups(self, all_routes: dict, deferred: bool = False) -> None:
        """Join the per-area backup tables onto the route table (runs
        after SR label resolution: remote/TI-LFA repairs tunnel through
        node-SID labels and attach only when the stack resolves).

        When the tables are PIPELINED and still in flight, the attach
        is deferred (ISSUE 10 satellite): a done-callback on the last
        pending ticket posts :class:`FrrTablesReadyMsg` back to this
        actor, and the SPF path proceeds without forcing — the FRR
        device wait moves entirely onto the pipeline worker
        (``holo_pipeline_wait_seconds{kind=frr}`` stays empty)."""
        cfg = self.config.frr
        if (
            cfg is None
            or not cfg.active()
            or not self.frr_tables
            or self._spf_cache is None
        ):
            return
        if not deferred:
            pending = [
                t
                for t in self.frr_tables.values()
                if getattr(t, "pending", None) is not None and t.pending()
            ]
            if pending:
                self._frr_attach_deferred = True
                run = self.spf_run_count
                import threading

                lock = threading.Lock()
                remaining = [len(pending)]

                def _one_done(_ticket, _remaining=remaining, _run=run,
                              _lock=lock):
                    # May fire on the pipeline worker OR inline on this
                    # actor thread (a ticket that completed between the
                    # pending scan and registration): the countdown
                    # must be atomic or a lost decrement strands the
                    # deferred attach forever.  The winner hops back
                    # onto the actor loop (deque append is thread-safe;
                    # the loop drains it on its own thread).
                    with _lock:
                        _remaining[0] -= 1
                        last = _remaining[0] <= 0
                    if last:
                        self.loop.send(self.name, FrrTablesReadyMsg(_run))

                for t in pending:
                    t.on_done(_one_done)
                return
        from holo_tpu.protocols.ospf.spf_run import attach_frr_backups

        # Per-area vertex -> node-SID label maps (vertex ids are area
        # scoped; the SID of a router's host prefix stands for the node).
        vlabels: dict = {}
        for _prefix, (label, route) in self.sr_labels.items():
            v = getattr(route, "vertex", -1)
            if v >= 0:
                vlabels.setdefault(route.area_id, {}).setdefault(v, label)
        for aid, (st, res) in self._spf_cache["area_results"].items():
            table = self.frr_tables.get(aid)
            if table is None:
                continue
            label_of = vlabels.get(aid, {}).get if cfg.ti_lfa or cfg.remote_lfa else None
            attach_frr_backups(
                st, res, all_routes, table, cfg, label_of, area_id=aid
            )

    def _finish_spf(self, all_routes: dict) -> None:
        # max-paths applies to the WHOLE table (full and partial runs):
        # inter-area and external routes inherit raw SPF next-hop sets
        # via their ABR/ASBR vertex and must clamp like intra routes
        # (the v3 instance clamps its merged table the same way).
        # Intra routes were already clamped weight-aware in
        # derive_routes; re-clamping them is a no-op.
        from holo_tpu.protocols.ospf.spf_run import clamp_multipath

        clamp_multipath(all_routes, self.config.max_paths)
        self._originate_prefix_sids()
        self._originate_bier()
        self.bier_routes = self._resolve_bier(all_routes)
        self.sr_labels = self._resolve_sr_labels(all_routes)
        self._attach_frr_backups(all_routes)
        old = self.routes
        self.routes = all_routes
        if self.route_cb is not None:
            self.route_cb(all_routes)
        if self.ibus is not None:
            self._sync_rib(old, all_routes)

    def _sync_rib(self, old: dict, new: dict) -> None:
        """Publish route deltas to the routing provider (ibus route
        install/uninstall — reference route.rs:894-906 → ibus.rs:344-351)."""
        from holo_tpu.utils.southbound import (
            Nexthop,
            Protocol,
            RouteKeyMsg,
            RouteMsg,
            DEFAULT_DISTANCE,
        )

        def installable(route) -> bool:
            # Connected destinations — no next-hops at all, or only
            # address-less (interface-only) ones — are never installed:
            # the RIB's DIRECT entries own them (reference route.rs:96
            # models connected with addr=None and skips the install).
            return any(nh.addr is not None for nh in route.nexthops)

        installed = self._installed_prefixes

        def uninstall(prefix):
            installed.discard(prefix)
            self.ibus.request(
                self.routing_actor,
                RouteKeyMsg(Protocol.OSPFV2, prefix),
                sender=self.name,
            )

        for prefix in old:
            if prefix not in new and prefix in installed:
                uninstall(prefix)
        for prefix, route in new.items():
            prev = old.get(prefix)
            if (
                prev is not None
                and prev.dist == route.dist
                and prev.nexthops == route.nexthops
                and getattr(prev, "backups", None) == getattr(route, "backups", None)
                and getattr(prev, "nh_weights", None)
                == getattr(route, "nh_weights", None)
            ):
                continue
            if not installable(route):
                # A previously-installed route degrading to connected
                # (directly attached again) is left in place — the
                # reference emits nothing on this transition (verified
                # against its recordings: ibus-addr-add3 step 4); the
                # entry is withdrawn when the prefix itself goes away.
                continue
            nhs = frozenset(
                Nexthop(
                    addr=nh.addr,
                    ifname=nh.ifname,
                    ifindex=self._ifindex_of(nh.ifname),
                )
                # An ECMP tie between a directly-attached path and one
                # via a neighbor can mix address-less and addressed
                # next-hops: only the addressed ones are installable.
                for nh in route.nexthops
                if nh.addr is not None
            )
            nh_weights = {}
            for nh, w in (getattr(route, "nh_weights", None) or {}).items():
                if nh.addr is None or nh not in route.nexthops:
                    continue
                nh_weights[
                    Nexthop(
                        addr=nh.addr,
                        ifname=nh.ifname,
                        ifindex=self._ifindex_of(nh.ifname),
                    )
                ] = int(w)
            backups = {}
            for pnh, (bnh, labels) in (getattr(route, "backups", None) or {}).items():
                if pnh.addr is None or bnh.addr is None:
                    continue
                backups[
                    Nexthop(
                        addr=pnh.addr,
                        ifname=pnh.ifname,
                        ifindex=self._ifindex_of(pnh.ifname),
                    )
                ] = Nexthop(
                    addr=bnh.addr,
                    ifname=bnh.ifname,
                    ifindex=self._ifindex_of(bnh.ifname),
                    labels=tuple(labels),
                )
            installed.add(prefix)
            self.ibus.request(
                self.routing_actor,
                RouteMsg(
                    protocol=Protocol.OSPFV2,
                    prefix=prefix,
                    distance=self._route_distance(route),
                    metric=route.dist,
                    nexthops=nhs,
                    backups=backups,
                    nh_weights=nh_weights,
                ),
                sender=self.name,
            )

    def _route_distance(self, route) -> int:
        c = self.config
        rtype = getattr(route, "rtype", "intra")
        if rtype.startswith(("external", "nssa")):
            return c.preference_external if c.preference_external is not None else c.preference
        typed = c.preference_intra if rtype == "intra" else c.preference_inter
        if typed is not None:
            return typed
        if c.preference_internal is not None:
            return c.preference_internal
        return c.preference

    def _ifindex_of(self, ifname: str | None) -> int | None:
        if ifname is None:
            return None
        ai = self._iface(ifname)
        return ai[1].ifindex if ai else None

    def set_preference(self, preference: int | None = None, **typed) -> None:
        """Administrative-distance change: republish every route with the
        new distances (the RIB re-ranks protocols on them).  ``typed``
        accepts intra/inter/internal/external keyword overrides."""
        changed = False
        if preference is not None and preference != self.config.preference:
            self.config.preference = preference
            changed = True
        for kind, val in typed.items():
            attr = f"preference_{kind}"
            if getattr(self.config, attr) != val:
                setattr(self.config, attr, val)
                changed = True
        if changed and self.ibus is not None:
            self._sync_rib({}, self.routes)

    def shutdown_self(self) -> None:
        """Disable path (and router-id change): flush every LSA we
        originated and withdraw all routes (reference: instance teardown
        floods MaxAge self-LSAs and uninstalls its RIB contribution)."""
        # Flush while adjacencies can still flood the MaxAge copies; the
        # shutdown guard stops the FULL->DOWN kill hooks from
        # re-originating live LSAs behind the flush.
        self._shutting_down = True
        try:
            for area in self.areas.values():
                for key in list(area.lsdb.entries):
                    if key.adv_rtr == self.config.router_id:
                        self._flush_self_lsa(area, key)
            # Stop interfaces one by one (reference teardown): each kills
            # its neighbors (nbr down notifications) then transitions the
            # interface itself to Down (if-state-change notification).
            # Loopbacks have no ISM to stop — they stay 'loopback'.
            for area in self.areas.values():
                for iface in area.interfaces.values():
                    for nbr_id in list(iface.neighbors):
                        self._nbr_event(iface.name, nbr_id, NsmEvent.KILL_NBR)
                    if iface.config.loopback:
                        continue
                    self._set_ism_state(iface, IsmState.DOWN)
                    iface.dr = IPv4Address(0)
                    iface.bdr = IPv4Address(0)
                    for key in ("hello", "wait"):
                        t = self._timers.get((key, iface.name))
                        if t:
                            t.cancel()
        finally:
            self._shutting_down = False
        # Teardown discards any re-origination checks its kill hooks queued,
        # and drops ALL instance state — the reference tears the whole
        # Instance<Up> down, so the LSDBs and SPF products vanish with it.
        self._pending_checks.clear()
        for area in self.areas.values():
            area.lsdb.entries.clear()
            area.lsdb.pending.clear()
        self._link_scope_iface.clear()
        self._area_reachable_routers.clear()
        self.spf_state = SpfFsmState.QUIET
        self._learn_deadline = None
        self.enabled = False
        old = self.routes
        self.routes = {}
        if self.route_cb is not None:
            self.route_cb({})
        if self.ibus is not None:
            self._sync_rib(old, {})

    def restart_with_router_id(self, router_id: IPv4Address) -> None:
        """Router-id change requires a restart: flush the old identity's
        LSAs, adopt the new id, bring interfaces back up and let
        adjacencies re-form."""
        if router_id == self.config.router_id:
            return
        was_up = [
            iface.name
            for area in self.areas.values()
            for iface in area.interfaces.values()
            if iface.state != IsmState.DOWN
        ]
        self.shutdown_self()
        self.config.router_id = router_id
        self.enabled = True
        # Instance (re)start: AreaStart re-originates the RI LSAs, then
        # the interfaces come back up under the new identity.
        for area in self.areas.values():
            self._originate_router_info(area)
        for ifname in was_up:
            self.if_up(ifname)

    def clear_neighbors(
        self,
        nbr_id: IPv4Address | None = None,
        ifname: str | None = None,
    ) -> None:
        """ietf-ospf clear-neighbor RPC: tear down adjacencies (they
        re-form from hellos), optionally scoped to one interface/neighbor."""
        for area in self.areas.values():
            for iface in area.interfaces.values():
                if ifname is not None and iface.name != ifname:
                    continue
                for rid in list(iface.neighbors):
                    if nbr_id is None or rid == nbr_id:
                        self._nbr_event(iface.name, rid, NsmEvent.KILL_NBR)

    def clear_database(self) -> None:
        """ietf-ospf clear-database RPC (reference rpc.rs:48-76): drop
        every LSA and kill the neighbors; re-origination happens through
        the kill events' own origination checks (router-LSA), NOT
        explicitly — the RI LSA only returns at area (re)start."""
        for area in self.areas.values():
            for key in list(area.lsdb.entries):
                area.lsdb.remove(key)
            for iface in area.interfaces.values():
                for rid in list(iface.neighbors):
                    self._nbr_event(iface.name, rid, NsmEvent.KILL_NBR)
        self._link_scope_iface.clear()

    # ----- rx/tx plumbing

    def _rx_packet(self, msg: NetRxPacket) -> None:
        ai = self._iface(msg.ifname)
        if ai is None:
            return
        area, iface = ai
        if iface.state == IsmState.DOWN:
            return
        if iface.config.passive:
            # Passive circuits neither send NOR process OSPF packets —
            # a peer's hellos must not recreate phantom neighbors here.
            return
        try:
            pkt = Packet.decode(msg.data, auth=iface.config.auth)
        except Exception:
            # Malformed/unauthenticated: drop + notify (events.rs:132).
            _OSPF_RX_BAD.labels(instance=self.name).inc()
            self._notify(
                "ietf-ospf:if-rx-bad-packet",
                self._notif_iface(iface) | {"packet-source": str(msg.src)},
            )
            return
        _OSPF_PACKETS.labels(instance=self.name, dir="rx").inc()
        # Destination validation (ospfv2/interface.rs:94-126): our own
        # address, AllSPFRouters, or AllDRouters when we are DR/BDR.
        if msg.dst is not None and msg.dst not in (
            iface.addr_ip,
            ALL_SPF_RTRS_V4,
        ):
            if not (msg.dst == ALL_DR_RTRS_V4 and iface.is_dr_or_bdr()):
                return
        # Source validation (:128-146): usable, and on the interface's
        # subnet for non-p2p interfaces.  Virtual-link packets are exempt
        # from the subnet rule — the peer sits several hops away across
        # the transit area (§15), identified by area id 0 in the header.
        if int(msg.src) == 0:
            return
        if (
            iface.config.if_type != IfType.POINT_TO_POINT
            and iface.prefix is not None
            and msg.src not in iface.prefix
            and not (int(pkt.area_id) == 0 and int(area.area_id) != 0)
        ):
            return
        if pkt.router_id == self.config.router_id:
            if pkt.body.TYPE == PacketType.HELLO:
                # Another router is using OUR router-id (hello from a
                # different source): misconfiguration worth flagging.
                self._notify_if_config_error(
                    iface, msg.src, "hello", "duplicate-router-id"
                )
            return  # our own multicast (or a duplicate router-id)
        if pkt.area_id != area.area_id:
            # §15: virtual-link packets carry the BACKBONE area id but
            # arrive over the transit area's physical interface — rebind
            # to the matching vlink interface before processing.
            vl = None
            if int(pkt.area_id) == 0 and int(area.area_id) != 0:
                backbone = self.areas.get(IPv4Address(0))
                if backbone is not None:
                    # The vlink must be configured THROUGH this transit
                    # area and the source must be the resolved endpoint —
                    # otherwise an off-path sender could inject packets
                    # as the vlink neighbor.
                    vl = next(
                        (
                            i
                            for i in backbone.interfaces.values()
                            if i.config.if_type == IfType.VIRTUAL_LINK
                            and i.vlink_peer == pkt.router_id
                            and i.vlink_transit == area.area_id
                            and i.vlink_dst == msg.src
                        ),
                        None,
                    )
            if vl is None:
                self._notify_if_config_error(
                    iface, msg.src, _PKT_TYPE_YANG[pkt.body.TYPE],
                    "area-mismatch",
                )
                return
            area, iface = self.areas[IPv4Address(0)], vl
        if pkt.auth_type == AuthType.CRYPTOGRAPHIC:
            nbr = iface.neighbors.get(pkt.router_id)
            if nbr is not None:
                if pkt.auth_seqno < nbr.crypto_seqno:
                    return  # replay
                nbr.crypto_seqno = pkt.auth_seqno
        t = pkt.body.TYPE
        if t == PacketType.HELLO:
            self._rx_hello(area, iface, msg.src, pkt)
        elif t == PacketType.DB_DESC:
            self._rx_db_desc(area, iface, msg.src, pkt)
        elif t == PacketType.LS_REQUEST:
            self._rx_ls_request(area, iface, msg.src, pkt)
        elif t == PacketType.LS_UPDATE:
            self._rx_ls_update(area, iface, msg.src, pkt)
        elif t == PacketType.LS_ACK:
            self._rx_ls_ack(area, iface, msg.src, pkt)

    def _send(self, iface: OspfInterface, dst, body, area: Area, lls=None) -> None:
        pkt = Packet(
            router_id=self.config.router_id,
            area_id=area.area_id,
            body=body,
            lls=lls,
        )
        auth = iface.config.auth
        if auth is not None and auth.type == AuthType.CRYPTOGRAPHIC:
            self._crypto_seq += 1
            if self._nvstore is not None and self._crypto_seq >= self._crypto_reserved:
                self._reserve_seqnos()
            auth.seqno = self._crypto_seq
        out_ifname = iface.name
        if iface.config.if_type == IfType.VIRTUAL_LINK:
            # §15: vlink packets are unicast to the resolved endpoint and
            # leave through the transit area's physical interface.
            out_ifname = iface.vlink_out_ifname or iface.name
            dst = iface.vlink_dst
            if dst is None:
                return
        _OSPF_PACKETS.labels(instance=self.name, dir="tx").inc()
        self.netio.send(out_ifname, iface.addr_ip, dst, pkt.encode(auth=auth))

"""Reference-grade BGP-4 protocol engine (RFC 4271 + MP-BGP).

Event-driven core mirroring holo-bgp's semantics — the reference's
recorded conformance topologies (10 router snapshots) replay through this
engine via tools/stepwise_bgp.py.  Structure maps 1:1:

- neighbor FSM Idle/Connect/Active/OpenSent/OpenConfirm/Established with
  capability negotiation  (holo-bgp/src/neighbor.rs:129-470,560-780)
- Adj-RIB-In/Out pre/post planes + Loc-RIB with attribute interning
  (holo-bgp/src/rib.rs:37-133)
- decision process: eligibility (AS loop, unresolvable nexthop), the
  RFC 4271 §9.1.2.2 tie-breakers, ECMP multipath, route dissemination
  with distribute filtering  (holo-bgp/src/rib.rs:297-774,
  events.rs:643-848)
- policy offload boundary: import/export/redistribute policy RESULTS are
  inputs (the reference computes them on a worker thread and records
  them; holo-bgp/src/events.rs:441-639)
- nexthop tracking over the ibus  (rib.rs:881-925)
- YANG operational state + established/backward-transition notifications
  (holo-bgp/src/northbound/state.rs)

The daemon-facing transport slice (real TCP sessions, wire codecs) lives
in :mod:`holo_tpu.protocols.bgp`; this engine is the protocol core the
conformance corpus verifies.
"""

from __future__ import annotations

import json

from holo_tpu.protocols.bgp import (
    NO_ADVERTISE,
    NO_EXPORT,
    NO_EXPORT_SUBCONFED,
)
from dataclasses import dataclass, field, replace
from ipaddress import IPv4Address

DFLT_LOCAL_PREF = 100
AS_TRANS = 23456

# FSM states (neighbor.rs:138-145); ordering matters (state >= OpenSent).
IDLE, CONNECT, ACTIVE, OPENSENT, OPENCONFIRM, ESTABLISHED = range(6)
STATE_YANG = {
    IDLE: "idle",
    CONNECT: "connect",
    ACTIVE: "active",
    OPENSENT: "open-sent",
    OPENCONFIRM: "open-confirm",
    ESTABLISHED: "established",
}

ORIGIN_ORDER = {"Igp": 0, "Egp": 1, "Incomplete": 2}


# ===== attributes =====


@dataclass(frozen=True)
class AsSegment:
    seg_type: str  # "Sequence" | "Set"
    members: tuple = ()


@dataclass(frozen=True)
class BaseAttrs:
    """packet/attribute.rs BaseAttrs (subset exercised by the corpus +
    med/ll_nexthop for MP-BGP parity)."""

    origin: str = "Incomplete"  # "Igp"/"Egp"/"Incomplete"
    as_path: tuple = ()  # of AsSegment
    nexthop: str | None = None
    ll_nexthop: str | None = None
    med: int | None = None
    local_pref: int | None = None
    # Aggregation + route reflection (attribute.rs BaseAttrs:57-61).
    aggregator: tuple | None = None  # (asn, identifier)
    atomic_aggregate: bool = False
    originator_id: str | None = None
    cluster_list: tuple = ()
    # Community families (attribute.rs Attrs:39-42; the reference interns
    # each list separately in the RIB — rib.rs:106-119 — our engine keys
    # the whole attrs object, which subsumes that sharing).
    comm: tuple = ()  # of u32
    ext_comm: tuple = ()  # of 8-byte values (hex strings in JSON)
    extv6_comm: tuple = ()  # of 20-byte values (hex strings in JSON)
    large_comm: tuple = ()  # of (global, local1, local2)

    def path_length(self) -> int:
        # as_path.path_length(): sets count as 1 (attribute.rs).
        total = 0
        for seg in self.as_path:
            total += len(seg.members) if seg.seg_type == "Sequence" else 1
        return total

    def first_as(self):
        for seg in self.as_path:
            if seg.seg_type == "Sequence" and seg.members:
                return seg.members[0]
            if seg.seg_type == "Set":
                return None
        return None

    def as_path_contains(self, asn: int) -> bool:
        return any(asn in seg.members for seg in self.as_path)

    def as_path_prepend(self, asn: int) -> "BaseAttrs":
        segs = list(self.as_path)
        if segs and segs[0].seg_type == "Sequence":
            segs[0] = AsSegment(
                "Sequence", (asn,) + tuple(segs[0].members)
            )
        else:
            segs.insert(0, AsSegment("Sequence", (asn,)))
        return replace(self, as_path=tuple(segs))


@dataclass(frozen=True)
class RouteOrigin:
    """rib.rs:91-101."""

    protocol: str | None = None  # local/redistributed origin
    identifier: str | None = None  # neighbor origin
    remote_addr: str | None = None

    def is_local(self) -> bool:
        return self.protocol is not None


@dataclass
class Route:
    origin: RouteOrigin
    attrs: BaseAttrs
    route_type: str  # "Internal" | "External"
    igp_cost: int | None = None
    ineligible_reason: str | None = None
    reject_reason: str | None = None

    def is_eligible(self) -> bool:
        return self.ineligible_reason is None


@dataclass
class AdjRib:
    in_pre: Route | None = None
    in_post: Route | None = None
    out_pre: Route | None = None
    out_post: Route | None = None


@dataclass
class Destination:
    local: Route | None = None
    local_nexthops: frozenset | None = None
    adj_rib: dict = field(default_factory=dict)  # addr(str) -> AdjRib
    redistribute: Route | None = None


@dataclass
class NhtEntry:
    metric: int | None = None
    prefixes: dict = field(default_factory=dict)  # prefix -> refcount


@dataclass
class Table:
    prefixes: dict = field(default_factory=dict)  # prefix(str) -> Destination
    queued: set = field(default_factory=set)
    nht: dict = field(default_factory=dict)  # addr -> NhtEntry


# ===== capabilities (packet/message.rs:120-140) =====


def cap_mp(afi: str, safi: str) -> tuple:
    return ("MultiProtocol", afi, safi)


def cap_asn32(asn: int) -> tuple:
    return ("FourOctetAsNumber", asn)


CAP_RR = ("RouteRefresh",)

# Rust enum Ord: variant declaration order then fields.
_CAP_ORDER = {
    "MultiProtocol": 0,
    "FourOctetAsNumber": 1,
    "AddPath": 2,
    "RouteRefresh": 3,
    "EnhancedRouteRefresh": 4,
}
_CAP_CODE = {
    "MultiProtocol": 1,
    "RouteRefresh": 2,
    "FourOctetAsNumber": 65,
    "AddPath": 69,
    "EnhancedRouteRefresh": 70,
}
_CAP_YANG = {
    "MultiProtocol": "iana-bgp-types:mp-bgp",
    "RouteRefresh": "iana-bgp-types:route-refresh",
    "FourOctetAsNumber": "iana-bgp-types:asn32",
    "AddPath": "iana-bgp-types:add-paths",
    "EnhancedRouteRefresh": "iana-bgp-types:enhanced-route-refresh",
}


def _cap_sort_key(cap: tuple):
    return (_CAP_ORDER[cap[0]],) + cap[1:]


def cap_negotiated(cap: tuple) -> tuple:
    """message.rs:678-691 — strip negotiation-irrelevant data."""
    if cap[0] == "FourOctetAsNumber":
        return ("FourOctetAsNumber",)
    return cap


# ===== neighbor =====


@dataclass
class AfiSafiCfg:
    enabled: bool = False
    default_import_policy: str = "reject-route"
    default_export_policy: str = "reject-route"


@dataclass
class NeighborCfg:
    peer_as: int = 0
    enabled: bool = True
    holdtime: int = 90
    passive_mode: bool = False
    local_address: str | None = None
    afi_safi: dict = field(default_factory=dict)  # "ipv4-unicast" -> AfiSafiCfg


@dataclass
class Neighbor:
    remote_addr: str
    peer_type: str  # "internal" | "external"
    config: NeighborCfg
    state: int = IDLE
    conn_info: dict | None = None
    identifier: str | None = None
    holdtime_nego: int | None = None
    capabilities_adv: list = field(default_factory=list)  # sorted
    capabilities_rcvd: list = field(default_factory=list)
    capabilities_nego: list = field(default_factory=list)
    connecting: bool = False
    connect_retry_active: bool = False
    autostart_active: bool = False
    # update tx queues per afi-safi: {afi_safi: ({attrs: set(prefix)}, set)}
    reach_queue: dict = field(default_factory=dict)
    unreach_queue: dict = field(default_factory=dict)

    def is_af_enabled(self, afi: str, safi: str) -> bool:
        """neighbor.rs:1106-1125."""
        if cap_mp(afi, safi) in self.capabilities_nego:
            return True
        if not self.capabilities_nego and afi == "Ipv4" and safi == "Unicast":
            return True
        return False


AFI_SAFIS = ("ipv4-unicast", "ipv6-unicast")
_AF_TUPLE = {
    "ipv4-unicast": ("Ipv4", "Unicast"),
    "ipv6-unicast": ("Ipv6", "Unicast"),
}


class BgpEngine:
    """One BGP speaker (holo-bgp Instance + InstanceState combined)."""

    def __init__(
        self,
        name: str,
        send_cb=None,
        ibus_cb=None,
        notif_cb=None,
        table_backend=None,
    ):
        self.name = name
        self.send_cb = send_cb or (lambda kind, payload: None)
        self.ibus_cb = ibus_cb or (lambda kind, payload: None)
        self.notif_cb = notif_cb or (lambda data: None)
        # Decision-process dispatch seam (ISSUE 16): None keeps the
        # scalar walk below byte-for-byte; a BgpTableBackend (see
        # holo_tpu/ops/bgp_table.py) moves best-path/multipath onto
        # device planes with this scalar path as its oracle + fallback.
        self.table_backend = table_backend

        # config
        self.asn = 0
        self.cfg_identifier: str | None = None
        self.afi_safi_enabled: set = set()  # {"ipv4-unicast", ...}
        self.redistribution: dict = {}  # afi_safi -> set(protocol)
        self.multipath: dict = {}  # afi_safi -> {"enabled","ebgp_max","ibgp_max","allow_multiple_as"}
        self.distance_external = 20
        self.distance_internal = 200
        self.neighbor_cfg: dict = {}  # addr -> NeighborCfg

        # system / state
        self.sys_router_id: str | None = None
        self.active = False
        self.router_id: str | None = None
        self.neighbors: dict[str, Neighbor] = {}
        self.tables: dict[str, Table] = {
            afs: Table() for afs in AFI_SAFIS
        }

    # ---- lifecycle (instance.rs update/start)

    def get_router_id(self):
        return self.cfg_identifier or self.sys_router_id

    def _instantiate_neighbor(self, addr: str) -> None:
        cfg = self.neighbor_cfg[addr]
        peer_type = "internal" if cfg.peer_as == self.asn else "external"
        nbr = Neighbor(remote_addr=addr, peer_type=peer_type, config=cfg)
        self.neighbors[addr] = nbr
        # Enabled neighbors enter via the auto-start timer
        # (neighbor.rs autostart_start; fires Timer::AutoStart).
        nbr.autostart_active = cfg.enabled

    def _neighbor_shutdown(self, nbr: Neighbor) -> None:
        """Cease/administrative-shutdown close (neighbor.rs fsm Stop arm)."""
        if nbr.state != IDLE:
            self._session_close(nbr, notif=_notif_msg(6, 2))  # Cease/AdminShutdown
            nbr.autostart_active = False
            self._fsm_state_change(nbr, IDLE)

    def update(self) -> None:
        """instance.rs update(): start when ready, stop when unconfigured,
        and reconcile the neighbor set against config while active."""
        router_id = self.get_router_id()
        ready = self.asn != 0 and router_id is not None
        if ready and not self.active:
            self.active = True
            self.router_id = router_id
            self.ibus_cb("RouterIdSub", {})
            for afs, protos in sorted(self.redistribution.items()):
                for proto in sorted(protos):
                    self.ibus_cb(
                        "RouteRedistributeSub",
                        {
                            "protocol": proto,
                            "af": _AF_TUPLE[afs][0],
                        },
                    )
            for addr in sorted(self.neighbor_cfg, key=_addr_key):
                self._instantiate_neighbor(addr)
        elif not ready and self.active:
            # Instance stop (instance.rs stop path): close every session,
            # drop neighbor state, clear the tables.
            for addr in sorted(self.neighbors, key=_addr_key):
                self._neighbor_shutdown(self.neighbors[addr])
            self.neighbors.clear()
            self.tables = {afs: Table() for afs in AFI_SAFIS}
            self.active = False
            self.router_id = None
        elif ready and self.active:
            self.router_id = router_id
            for addr in sorted(
                set(self.neighbor_cfg) - set(self.neighbors), key=_addr_key
            ):
                self._instantiate_neighbor(addr)
            for addr in sorted(
                set(self.neighbors) - set(self.neighbor_cfg), key=_addr_key
            ):
                nbr = self.neighbors.pop(addr)
                self._neighbor_shutdown(nbr)

    # ---- FSM (neighbor.rs:221-470)

    def fsm(self, nbr: Neighbor, event: tuple) -> None:
        kind = event[0]
        next_state = None
        if nbr.state == IDLE:
            if kind in ("Start", "AutoStart"):
                nbr.connect_retry_active = True
                if nbr.config.passive_mode:
                    next_state = ACTIVE
                else:
                    nbr.connecting = True
                    next_state = CONNECT
        elif nbr.state in (CONNECT, ACTIVE):
            if kind == "Start":
                pass
            elif kind == "Connected":
                nbr.connect_retry_active = False
                nbr.conn_info = event[1]
                self._open_send(nbr)
                next_state = OPENSENT
            elif kind == "ConnFail":
                self._session_close(nbr)
                next_state = IDLE
            elif kind == "ConnectRetry":
                nbr.connecting = True
                nbr.connect_retry_active = True
                next_state = CONNECT if nbr.state == ACTIVE else None
            elif kind == "AutoStart":
                pass
            else:
                self._session_close(nbr)
                next_state = IDLE
        elif nbr.state == OPENSENT:
            if kind == "Start":
                pass
            elif kind == "ConnFail":
                self._session_close(nbr)
                nbr.connect_retry_active = True
                next_state = ACTIVE
            elif kind == "RcvdOpen":
                next_state = self._open_process(nbr, event[1])
            elif kind == "Hold":
                self._session_close(
                    nbr, notif=_notif_msg(4, 0)  # HoldTimerExpired
                )
                next_state = IDLE
            else:
                self._session_close(nbr, notif=_notif_msg(5, 1))
                next_state = IDLE
        elif nbr.state == OPENCONFIRM:
            if kind == "Start":
                pass
            elif kind in ("ConnFail", "RcvdNotif"):
                self._session_close(nbr)
                next_state = IDLE
            elif kind == "RcvdOpen":
                next_state = IDLE  # collision: not implemented
            elif kind == "RcvdKalive":
                next_state = ESTABLISHED
            elif kind == "Hold":
                self._session_close(nbr, notif=_notif_msg(4, 0))
                next_state = IDLE
            else:
                self._session_close(nbr, notif=_notif_msg(5, 2))
                next_state = IDLE
        elif nbr.state == ESTABLISHED:
            if kind == "Start":
                pass
            elif kind in ("ConnFail", "RcvdNotif"):
                self._session_close(nbr)
                next_state = IDLE
            elif kind in ("RcvdKalive", "RcvdUpdate"):
                pass
            elif kind == "Hold":
                self._session_close(nbr, notif=_notif_msg(4, 0))
                next_state = IDLE
            else:
                self._session_close(nbr, notif=_notif_msg(5, 3))
                next_state = IDLE

        if next_state is not None and nbr.state != next_state:
            nbr.autostart_active = (
                next_state == IDLE and nbr.config.enabled
            )
            self._fsm_state_change(nbr, next_state)

    def _fsm_state_change(self, nbr: Neighbor, next_state: int) -> None:
        if next_state == ESTABLISHED:
            self.notif_cb(self._nb_notif(nbr, "established"))
        elif nbr.state == ESTABLISHED:
            self.notif_cb(self._nb_notif(nbr, "backward-transition"))
        nbr.state = next_state
        if next_state == ESTABLISHED:
            self._session_init(nbr)

    def _nb_notif(self, nbr: Neighbor, kind: str) -> dict:
        return {
            "ietf-routing:routing": {
                "control-plane-protocols": {
                    "control-plane-protocol": [
                        {
                            "type": "ietf-bgp:bgp",
                            "name": self.name,
                            "ietf-bgp:bgp": {
                                "neighbors": {
                                    kind: {
                                        "remote-address": nbr.remote_addr
                                    }
                                }
                            },
                        }
                    ]
                }
            }
        }

    def _session_init(self, nbr: Neighbor) -> None:
        """neighbor.rs:563-587."""
        adv = {cap_negotiated(c) for c in nbr.capabilities_adv}
        rcvd = {cap_negotiated(c) for c in nbr.capabilities_rcvd}
        nbr.capabilities_nego = sorted(adv & rcvd, key=_cap_sort_key)
        self.send_cb(
            "UpdateCapabilities",
            [_cap_to_json(c, nego=True) for c in nbr.capabilities_nego],
        )
        for afs in AFI_SAFIS:
            self._initial_routing_update(nbr, afs)

    def _initial_routing_update(self, nbr: Neighbor, afs: str) -> None:
        afi, safi = _AF_TUPLE[afs]
        if not nbr.is_af_enabled(afi, safi):
            return
        table = self.tables[afs]
        routes = []
        for prefix in sorted(table.prefixes, key=_prefix_key):
            dest = table.prefixes[prefix]
            if dest.local is None:
                continue
            route = Route(
                origin=dest.local.origin,
                attrs=dest.local.attrs,
                route_type=dest.local.route_type,
            )
            if self._distribute_filter(nbr, route):
                routes.append((prefix, route))
        self._advertise_routes(nbr, afs, routes)

    def _session_close(self, nbr: Neighbor, notif: dict | None = None):
        """neighbor.rs:590-625."""
        if nbr.state >= OPENSENT and notif is not None:
            self._message_send(nbr, notif)
        nbr.connect_retry_active = False
        nbr.conn_info = None
        nbr.identifier = None
        nbr.holdtime_nego = None
        nbr.capabilities_adv = []
        nbr.capabilities_rcvd = []
        nbr.capabilities_nego = []
        nbr.connecting = False
        for afs in AFI_SAFIS:
            self._clear_routes(nbr, afs)
        self.trigger_decision_process()

    def _clear_routes(self, nbr: Neighbor, afs: str) -> None:
        table = self.tables[afs]
        for prefix, dest in table.prefixes.items():
            adj = dest.adj_rib.pop(nbr.remote_addr, None)
            if adj is not None and adj.in_post is not None:
                self._nexthop_untrack(table, prefix, adj.in_post)
            table.queued.add(prefix)
            if self.table_backend is not None:
                self.table_backend.note_route_change(afs, prefix)

    # ---- message sending

    def _message_send(self, nbr: Neighbor, msg: dict) -> None:
        self.send_cb(
            "SendMessage", {"nbr_addr": nbr.remote_addr, "msg": msg}
        )

    def _open_send(self, nbr: Neighbor) -> None:
        """neighbor.rs:671-711."""
        caps = [CAP_RR, cap_asn32(self.asn)]
        for afs in AFI_SAFIS:
            cfg = nbr.config.afi_safi.get(afs)
            if cfg is not None and cfg.enabled:
                caps.append(cap_mp(*_AF_TUPLE[afs]))
        nbr.capabilities_adv = sorted(set(caps), key=_cap_sort_key)
        msg = {
            "Open": {
                "version": 4,
                "my_as": self.asn if self.asn <= 0xFFFF else AS_TRANS,
                "holdtime": nbr.config.holdtime,
                "identifier": self.router_id,
                "capabilities": [
                    _cap_to_json(c) for c in nbr.capabilities_adv
                ],
            }
        }
        self._message_send(nbr, msg)

    def _open_process(self, nbr: Neighbor, open_j: dict) -> int:
        """neighbor.rs:714-777."""
        caps = [_cap_from_json(c) for c in open_j.get("capabilities", [])]
        real_as = next(
            (c[1] for c in caps if c[0] == "FourOctetAsNumber"),
            open_j["my_as"],
        )
        if nbr.config.peer_as != real_as:
            self._message_send(nbr, _notif_msg(2, 2))  # BadPeerAs
            self._session_close(nbr)
            return IDLE
        if (
            nbr.peer_type == "internal"
            and open_j["identifier"] == self.router_id
        ):
            self._message_send(nbr, _notif_msg(2, 3))  # BadBgpIdentifier
            self._session_close(nbr)
            return IDLE
        holdtime_nego = min(open_j["holdtime"], nbr.config.holdtime)
        nbr.connect_retry_active = False
        self._message_send(nbr, {"Keepalive": {}})
        nbr.identifier = open_j["identifier"]
        nbr.holdtime_nego = holdtime_nego if holdtime_nego else None
        nbr.capabilities_rcvd = sorted(set(caps), key=_cap_sort_key)
        return OPENCONFIRM

    # ---- events (events.rs)

    def tcp_accept(self, conn_info: dict) -> None:
        nbr = self.neighbors.get(str(conn_info["remote_addr"]))
        if nbr is None or nbr.conn_info is not None:
            return
        self.fsm(nbr, ("Connected", dict(conn_info)))

    def tcp_connect(self, conn_info: dict) -> None:
        nbr = self.neighbors.get(str(conn_info["remote_addr"]))
        if nbr is None:
            return
        nbr.connecting = False
        if nbr.conn_info is not None:
            return
        self.fsm(nbr, ("Connected", dict(conn_info)))

    def nbr_timer(self, nbr_addr: str, timer: str) -> None:
        nbr = self.neighbors.get(nbr_addr)
        if nbr is None:
            return
        self.fsm(nbr, (timer,))

    def nbr_rx(self, nbr_addr: str, msg) -> None:
        """msg: dict (message JSON) | "conn-closed" | ("decode-error", _)."""
        nbr = self.neighbors.get(nbr_addr)
        if nbr is None:
            return
        if msg == "conn-closed":
            self.fsm(nbr, ("ConnFail",))
            return
        if isinstance(msg, tuple) and msg[0] == "decode-error":
            # RcvdError: one notification, one close, Idle
            # (neighbor.rs fsm RcvdError arms).
            if nbr.state != IDLE:
                self._session_close(nbr, notif=msg[1])
                nbr.autostart_active = nbr.config.enabled
                self._fsm_state_change(nbr, IDLE)
            return
        kind, body = next(iter(msg.items()))
        if kind == "Open":
            self.fsm(nbr, ("RcvdOpen", body))
        elif kind == "Update":
            self.fsm(nbr, ("RcvdUpdate",))
            self._process_nbr_update(nbr, body)
        elif kind == "Notification":
            self.fsm(nbr, ("RcvdNotif", body))
        elif kind == "Keepalive":
            self.fsm(nbr, ("RcvdKalive",))
        elif kind == "RouteRefresh":
            pass  # resend handled by clear_session(Soft) path

    def _process_nbr_update(self, nbr: Neighbor, upd: dict) -> None:
        """events.rs:152-270."""
        attrs_j = upd.get("attrs")
        reach = upd.get("reach")
        if reach is not None:
            if attrs_j is not None:
                attrs = _attrs_from_json(attrs_j)
                attrs = replace(attrs, nexthop=str(reach["nexthop"]))
                self._reach_prefixes(
                    nbr, "ipv4-unicast", reach["prefixes"], attrs
                )
            else:
                self._unreach_prefixes(
                    nbr, "ipv4-unicast", reach["prefixes"]
                )
        mp_reach = upd.get("mp_reach")
        if mp_reach is not None:
            fam, body = next(iter(mp_reach.items()))
            afs = "ipv4-unicast" if fam == "Ipv4Unicast" else "ipv6-unicast"
            if attrs_j is not None:
                attrs = _attrs_from_json(attrs_j)
                attrs = replace(attrs, nexthop=str(body["nexthop"]))
                if body.get("ll_nexthop"):
                    attrs = replace(
                        attrs, ll_nexthop=str(body["ll_nexthop"])
                    )
                self._reach_prefixes(nbr, afs, body["prefixes"], attrs)
            else:
                self._unreach_prefixes(nbr, afs, body["prefixes"])
        unreach = upd.get("unreach")
        if unreach is not None:
            self._unreach_prefixes(
                nbr, "ipv4-unicast", unreach["prefixes"]
            )
        mp_unreach = upd.get("mp_unreach")
        if mp_unreach is not None:
            fam, body = next(iter(mp_unreach.items()))
            afs = "ipv4-unicast" if fam == "Ipv4Unicast" else "ipv6-unicast"
            self._unreach_prefixes(nbr, afs, body["prefixes"])
        self.trigger_decision_process()

    def _reach_prefixes(
        self, nbr: Neighbor, afs: str, prefixes, attrs: BaseAttrs
    ) -> None:
        """events.rs:272-341; the import policy application itself runs
        on the worker — its recorded result arrives via
        policy_result_neighbor()."""
        afi, safi = _AF_TUPLE[afs]
        if not nbr.is_af_enabled(afi, safi):
            return
        origin = RouteOrigin(
            identifier=nbr.identifier, remote_addr=nbr.remote_addr
        )
        route_type = (
            "Internal" if nbr.peer_type == "internal" else "External"
        )
        table = self.tables[afs]
        for prefix in prefixes:
            dest = table.prefixes.setdefault(str(prefix), Destination())
            adj = dest.adj_rib.setdefault(nbr.remote_addr, AdjRib())
            adj.in_pre = Route(
                origin=origin, attrs=attrs, route_type=route_type
            )

    def _unreach_prefixes(self, nbr: Neighbor, afs: str, prefixes) -> None:
        afi, safi = _AF_TUPLE[afs]
        if not nbr.is_af_enabled(afi, safi):
            return
        table = self.tables[afs]
        for prefix in prefixes:
            prefix = str(prefix)
            dest = table.prefixes.get(prefix)
            if dest is None:
                continue
            adj = dest.adj_rib.get(nbr.remote_addr)
            if adj is None:
                continue
            adj.in_pre = None
            if adj.in_post is not None:
                self._nexthop_untrack(table, prefix, adj.in_post)
                adj.in_post = None
            table.queued.add(prefix)
            if self.table_backend is not None:
                self.table_backend.note_route_change(afs, prefix)

    # ---- policy results (recorded worker outputs; events.rs:441-639)

    def policy_result_neighbor(
        self, policy_type: str, nbr_addr: str, afs: str, routes
    ) -> None:
        nbr = self.neighbors.get(nbr_addr)
        if nbr is None or nbr.state < ESTABLISHED:
            return
        table = self.tables[afs]
        if policy_type == "Import":
            for prefix, result in routes:
                prefix = str(prefix)
                dest = table.prefixes.setdefault(prefix, Destination())
                adj = dest.adj_rib.setdefault(nbr.remote_addr, AdjRib())
                if result is not None:
                    route = Route(
                        origin=result["origin"],
                        attrs=result["attrs"],
                        route_type=result["route_type"],
                    )
                    if adj.in_post is not None:
                        self._nexthop_untrack(table, prefix, adj.in_post)
                    self._nexthop_track(table, prefix, route)
                    adj.in_post = route
                else:
                    if adj.in_post is not None:
                        self._nexthop_untrack(table, prefix, adj.in_post)
                        adj.in_post = None
                table.queued.add(prefix)
                if self.table_backend is not None:
                    self.table_backend.note_route_change(afs, prefix)
            self.trigger_decision_process()
        else:  # Export
            for prefix, result in routes:
                prefix = str(prefix)
                dest = table.prefixes.setdefault(prefix, Destination())
                adj = dest.adj_rib.setdefault(nbr.remote_addr, AdjRib())
                if result is not None:
                    route = Route(
                        origin=result["origin"],
                        attrs=result["attrs"],
                        route_type=result["route_type"],
                    )
                    update = (
                        adj.out_post is None
                        or adj.out_post.attrs != route.attrs
                        or adj.out_post.origin != route.origin
                    )
                    if update:
                        adj.out_post = route
                        attrs = self._attrs_tx_update(
                            result["attrs"],
                            nbr,
                            result["origin"].is_local(),
                        )
                        self._queue_reach(nbr, afs, prefix, attrs)
                else:
                    if adj.out_post is not None:
                        adj.out_post = None
                        self._queue_unreach(nbr, afs, prefix)
            self._flush_updates(nbr)

    def policy_result_redistribute(self, afs: str, prefix, result) -> None:
        table = self.tables[afs]
        prefix = str(prefix)
        if result is not None:
            dest = table.prefixes.setdefault(prefix, Destination())
            dest.redistribute = Route(
                origin=result["origin"],
                attrs=result["attrs"],
                route_type="Internal",
            )
        else:
            dest = table.prefixes.get(prefix)
            if dest is not None:
                dest.redistribute = None
        table.queued.add(prefix)
        if self.table_backend is not None:
            self.table_backend.note_route_change(afs, prefix)
        self.trigger_decision_process()

    # ---- ibus rx

    def router_id_update(self, router_id) -> None:
        self.sys_router_id = router_id
        self.update()

    def nexthop_update(self, addr: str, metric: int | None) -> None:
        for table in self.tables.values():
            nht = table.nht.get(addr)
            if nht is not None:
                nht.metric = metric
                table.queued.update(nht.prefixes.keys())
        self.trigger_decision_process()

    # ---- nexthop tracking (rib.rs:881-925)

    def _nexthop_track(self, table: Table, prefix: str, route: Route):
        addr = route.attrs.ll_nexthop or route.attrs.nexthop
        nht = table.nht.get(addr)
        if nht is None:
            nht = table.nht[addr] = NhtEntry()
            self.ibus_cb("NexthopTrack", {"addr": addr})
        nht.prefixes[prefix] = nht.prefixes.get(prefix, 0) + 1

    def _nexthop_untrack(self, table: Table, prefix: str, route: Route):
        addr = route.attrs.ll_nexthop or route.attrs.nexthop
        nht = table.nht.get(addr)
        if nht is None or prefix not in nht.prefixes:
            return
        nht.prefixes[prefix] -= 1
        if nht.prefixes[prefix] == 0:
            del nht.prefixes[prefix]
            if not nht.prefixes:
                self.ibus_cb("NexthopUntrack", {"addr": addr})
                del table.nht[addr]

    # ---- decision process (events.rs:643-848, rib.rs:297-774)

    def trigger_decision_process(self) -> None:
        """The reference schedules this over a channel; the stepwise
        harness fires it via the recorded TriggerDecisionProcess events,
        so scheduling here is a no-op."""

    def run_decision_process(self) -> None:
        for afs in AFI_SAFIS:
            self._decision_process(afs)

    def _decision_process(self, afs: str) -> None:
        table = self.tables[afs]
        queued = sorted(table.queued, key=_prefix_key)
        table.queued = set()
        tb = self.table_backend
        if tb is not None:
            # One device batch for the whole queued set: scatter the
            # changed rows, recompute only these prefixes, read the
            # verdicts back once.  Per-prefix results are consumed in
            # best_path below; any miss falls back to the scalar walk.
            tb.begin_batch(self, afs, table, queued)
        reach, unreach = [], []
        for prefix in queued:
            dest = table.prefixes.get(prefix)
            if dest is None:
                continue
            if tb is not None:
                best = tb.best_path(self, afs, table, prefix, dest)
            else:
                best = self._best_path(table, dest)
            self._loc_rib_update(afs, table, prefix, dest, best)
            if best is not None:
                reach.append((prefix, best))
            else:
                unreach.append(prefix)
        for addr in sorted(self.neighbors, key=_addr_key):
            nbr = self.neighbors[addr]
            if nbr.state != ESTABLISHED:
                continue
            if not nbr.is_af_enabled(*_AF_TUPLE[afs]):
                continue
            nbr_unreach = list(unreach)
            nbr_reach = []
            for prefix, route in reach:
                if self._distribute_filter(nbr, route):
                    nbr_reach.append((prefix, route))
                else:
                    nbr_unreach.append(prefix)
            if nbr_unreach:
                self._withdraw_routes(nbr, afs, table, nbr_unreach)
            if nbr_reach:
                self._advertise_routes(nbr, afs, nbr_reach)
        # Prune empty destinations (events.rs:751-768).
        for prefix in queued:
            dest = table.prefixes.get(prefix)
            if (
                dest is not None
                and dest.local is None
                and dest.redistribute is None
                and all(
                    a.in_pre is None
                    and a.in_post is None
                    and a.out_pre is None
                    and a.out_post is None
                    for a in dest.adj_rib.values()
                )
            ):
                del table.prefixes[prefix]

    def _best_path(self, table: Table, dest: Destination) -> Route | None:
        best = None
        candidates = [
            adj.in_post
            for _, adj in sorted(dest.adj_rib.items(), key=lambda kv: _addr_key(kv[0]))
            if adj.in_post is not None
        ]
        if dest.redistribute is not None:
            candidates.append(dest.redistribute)
        for route in candidates:
            route.reject_reason = None
            route.ineligible_reason = None
            if route.attrs.as_path_contains(self.asn):
                route.ineligible_reason = "as-loop"
                continue
            if not route.origin.is_local():
                nexthop = route.attrs.ll_nexthop or route.attrs.nexthop
                nht = table.nht.get(nexthop)
                route.igp_cost = nht.metric if nht else None
                if route.igp_cost is None:
                    route.ineligible_reason = "unresolvable"
                    continue
            if best is None:
                best = route
            else:
                cmp, reason = _route_compare(route, best)
                if cmp > 0:
                    best.reject_reason = reason
                    best = route
                else:
                    route.reject_reason = reason
        if best is None:
            return None
        return Route(
            origin=best.origin,
            attrs=best.attrs,
            route_type=best.route_type,
            igp_cost=best.igp_cost,
        )

    def _compute_nexthops(
        self, afs: str, dest: Destination, best: Route
    ) -> frozenset | None:
        """rib.rs:667-705."""
        if best.origin.is_local():
            return None
        mp = self.multipath.get(afs)
        if not mp or not mp.get("enabled"):
            return frozenset(
                {best.attrs.ll_nexthop or best.attrs.nexthop}
            )
        max_paths = (
            mp.get("ibgp_max", 1)
            if best.route_type == "Internal"
            else mp.get("ebgp_max", 1)
        )
        nexthops = []
        for _, adj in sorted(
            dest.adj_rib.items(), key=lambda kv: _addr_key(kv[0])
        ):
            route = adj.in_post
            if route is None or not route.is_eligible():
                continue
            if not _multipath_equal(route, best, mp):
                continue
            nexthops.append(route.attrs.ll_nexthop or route.attrs.nexthop)
            if len(nexthops) >= max_paths:
                break
        return frozenset(nexthops)

    def _loc_rib_update(
        self, afs, table, prefix, dest: Destination, best: Route | None
    ) -> None:
        """rib.rs:776-847."""
        if best is not None:
            if self.table_backend is not None:
                nexthops = self.table_backend.compute_nexthops(
                    self, afs, prefix, dest, best
                )
            else:
                nexthops = self._compute_nexthops(afs, dest, best)
            if (
                dest.local is not None
                and dest.local.origin == best.origin
                and dest.local.attrs == best.attrs
                and dest.local.route_type == best.route_type
                and dest.local_nexthops == nexthops
            ):
                return
            dest.local = best
            dest.local_nexthops = nexthops
            if not best.origin.is_local():
                self.ibus_cb(
                    "RouteIpAdd",
                    {
                        "protocol": "bgp",
                        "prefix": prefix,
                        "distance": (
                            self.distance_internal
                            if best.route_type == "Internal"
                            else self.distance_external
                        ),
                        "metric": best.attrs.med or 0,
                        "tag": None,
                        "nexthops": [
                            {
                                "Recursive": {
                                    "addr": nh,
                                    "labels": [],
                                    "resolved": [],
                                }
                            }
                            for nh in sorted(nexthops or ())
                        ],
                    },
                )
        elif dest.local is not None:
            local = dest.local
            dest.local = None
            dest.local_nexthops = None
            if not local.origin.is_local():
                self.ibus_cb(
                    "RouteIpDel", {"protocol": "bgp", "prefix": prefix}
                )

    def _distribute_filter(self, nbr: Neighbor, route: Route) -> bool:
        """neighbor.rs:1060-1104."""
        if route.attrs.as_path_contains(nbr.config.peer_as):
            return False
        if (
            route.route_type == "Internal"
            and route.origin.remote_addr == nbr.remote_addr
        ):
            return False
        # Well-known communities (neighbor.rs:1083-1102).
        if route.attrs.comm:
            ebgp = nbr.config.peer_as != self.asn
            if NO_ADVERTISE in route.attrs.comm:
                return False
            if ebgp and (
                NO_EXPORT in route.attrs.comm
                or NO_EXPORT_SUBCONFED in route.attrs.comm
            ):
                return False
        return True

    def _withdraw_routes(self, nbr, afs, table, prefixes) -> None:
        for prefix in prefixes:
            dest = table.prefixes.get(prefix)
            if dest is None:
                continue
            adj = dest.adj_rib.get(nbr.remote_addr)
            if adj is None:
                continue
            adj.out_pre = None
            if adj.out_post is not None:
                adj.out_post = None
                self._queue_unreach(nbr, afs, prefix)
        self._flush_updates(nbr)

    def _advertise_routes(self, nbr, afs, routes) -> None:
        """events.rs:802-848 — out-pre update + export policy enqueue
        (the worker's recorded result continues the flow)."""
        table = self.tables[afs]
        for prefix, route in routes:
            dest = table.prefixes.setdefault(prefix, Destination())
            adj = dest.adj_rib.setdefault(nbr.remote_addr, AdjRib())
            adj.out_pre = route

    def _attrs_tx_update(
        self, attrs: BaseAttrs, nbr: Neighbor, local: bool
    ) -> BaseAttrs:
        """rib.rs:850-879 + af.rs nexthop_tx_change."""
        if nbr.peer_type == "internal":
            if attrs.local_pref is None:
                attrs = replace(attrs, local_pref=DFLT_LOCAL_PREF)
        else:
            attrs = attrs.as_path_prepend(self.asn)
            attrs = replace(attrs, med=None, local_pref=None)
        session_src = (
            str(nbr.conn_info["local_addr"]) if nbr.conn_info else None
        )
        if local:
            attrs = replace(attrs, nexthop=session_src)
        elif nbr.peer_type == "external":
            # shared_subnet is never set in the recorded corpus.
            attrs = replace(attrs, nexthop=session_src)
        return attrs

    def _queue_reach(self, nbr, afs, prefix, attrs: BaseAttrs) -> None:
        q = nbr.reach_queue.setdefault(afs, {})
        q.setdefault(attrs, set()).add(prefix)

    def _queue_unreach(self, nbr, afs, prefix) -> None:
        nbr.unreach_queue.setdefault(afs, set()).add(prefix)

    def _flush_updates(self, nbr: Neighbor) -> None:
        """build_updates (af.rs): one Update per attrs group."""
        msg_list = []
        for afs in AFI_SAFIS:
            reach = nbr.reach_queue.pop(afs, {})
            unreach = nbr.unreach_queue.pop(afs, set())
            v4 = afs == "ipv4-unicast"
            for attrs in sorted(reach, key=_attrs_sort_key):
                prefixes = sorted(reach[attrs], key=_prefix_key)
                if v4:
                    msg_list.append(
                        {
                            "Update": {
                                "reach": {
                                    "prefixes": prefixes,
                                    "nexthop": attrs.nexthop,
                                },
                                "attrs": _attrs_to_json(attrs),
                            }
                        }
                    )
                else:
                    msg_list.append(
                        {
                            "Update": {
                                "mp_reach": {
                                    "Ipv6Unicast": {
                                        "prefixes": prefixes,
                                        "nexthop": attrs.nexthop,
                                        "ll_nexthop": attrs.ll_nexthop,
                                    }
                                },
                                "attrs": _attrs_to_json(attrs),
                            }
                        }
                    )
            if unreach:
                prefixes = sorted(unreach, key=_prefix_key)
                if v4:
                    msg_list.append(
                        {"Update": {"unreach": {"prefixes": prefixes}}}
                    )
                else:
                    msg_list.append(
                        {
                            "Update": {
                                "mp_unreach": {
                                    "Ipv6Unicast": {"prefixes": prefixes}
                                }
                            }
                        }
                    )
        if msg_list:
            self.send_cb(
                "SendMessageList",
                {"nbr_addr": nbr.remote_addr, "msg_list": msg_list},
            )

    # ---- operational state (northbound/state.rs, testing-mode fields)

    def northbound_state(self) -> dict:
        bgp: dict = {}
        if self.active:
            counts = {
                afs: len(self.tables[afs].prefixes) for afs in AFI_SAFIS
            }
            afi_safis = [
                {
                    "name": f"iana-bgp-types:{afs}",
                    "statistics": {"total-prefixes": counts[afs]},
                }
                for afs in AFI_SAFIS
                if afs in self.afi_safi_enabled
            ]
            bgp["global"] = {
                "afi-safis": {"afi-safi": afi_safis},
                "statistics": {
                    "total-prefixes": sum(counts.values())
                },
            }
        nbrs = [
            self._state_neighbor(self.neighbors[a])
            for a in sorted(self.neighbors, key=_addr_key)
        ]
        if nbrs:
            bgp["neighbors"] = {"neighbor": nbrs}
        rib = self._state_rib()
        if rib:
            bgp["rib"] = rib
        return bgp

    def _state_neighbor(self, nbr: Neighbor) -> dict:
        entry: dict = {"remote-address": nbr.remote_addr}
        if nbr.conn_info is not None:
            entry["local-address"] = str(nbr.conn_info["local_addr"])
        entry["peer-type"] = nbr.peer_type
        if nbr.identifier is not None:
            entry["identifier"] = nbr.identifier
        if nbr.holdtime_nego is not None:
            entry["timers"] = {
                "negotiated-hold-time": nbr.holdtime_nego
            }
        af_list = []
        if not nbr.capabilities_nego:
            af_names = ["ipv4-unicast"]
        else:
            af_names = [
                afs
                for afs in AFI_SAFIS
                if cap_mp(*_AF_TUPLE[afs]) in nbr.capabilities_nego
            ]
        for afs in af_names:
            table = self.tables[afs]
            r = s = i = 0
            for dest in table.prefixes.values():
                adj = dest.adj_rib.get(nbr.remote_addr)
                if adj is None:
                    continue
                r += adj.in_pre is not None
                s += adj.out_post is not None
                i += adj.in_post is not None
            af_list.append(
                {
                    "name": f"iana-bgp-types:{afs}",
                    "prefixes": {
                        "received": r,
                        "sent": s,
                        "installed": i,
                    },
                }
            )
        if af_list:
            entry["afi-safis"] = {"afi-safi": af_list}
        entry["session-state"] = STATE_YANG[nbr.state]
        caps: dict = {}
        if nbr.capabilities_adv:
            caps["advertised-capabilities"] = [
                _cap_state(i, c)
                for i, c in enumerate(nbr.capabilities_adv)
            ]
        if nbr.capabilities_rcvd:
            caps["received-capabilities"] = [
                _cap_state(i, c)
                for i, c in enumerate(nbr.capabilities_rcvd)
            ]
        if nbr.capabilities_nego:
            caps["negotiated-capabilities"] = [
                _CAP_YANG[c[0]] for c in nbr.capabilities_nego
            ]
        if caps:
            entry["capabilities"] = caps
        return entry

    def _state_rib(self) -> dict:
        if not self.active:
            return {}
        # Collect attr sets from all live routes (interning view).  The
        # community lists are interned separately, as the reference RIB
        # does (rib.rs:106-119; ietf-bgp rib/communities + the routes'
        # community-index pointer).
        attr_sets: dict[BaseAttrs, str] = {}
        comm_sets: dict[tuple, int] = {}

        def intern(attrs: BaseAttrs) -> str:
            return attr_sets.setdefault(
                attrs, f"attr-{len(attr_sets)}"
            )

        def intern_comm(comm: tuple) -> int:
            return comm_sets.setdefault(comm, len(comm_sets))

        afi_safi_entries = []
        for afs in AFI_SAFIS:
            if afs not in self.afi_safi_enabled:
                continue
            table = self.tables[afs]
            loc_routes = []
            nbr_entries_by_addr: dict = {}
            for prefix in sorted(table.prefixes, key=_prefix_key):
                dest = table.prefixes[prefix]
                if dest.local is not None:
                    loc: dict = {
                        "prefix": prefix,
                        "origin": _origin_yang(dest.local.origin),
                        "path-id": 0,
                        "attr-index": intern(dest.local.attrs),
                    }
                    if dest.local.attrs.comm:
                        loc["community-index"] = intern_comm(
                            dest.local.attrs.comm
                        )
                    loc_routes.append(loc)
                for addr in sorted(dest.adj_rib, key=_addr_key):
                    adj = dest.adj_rib[addr]
                    nbr = self.neighbors.get(addr)
                    if nbr is None or nbr.state != ESTABLISHED:
                        continue
                    ent = nbr_entries_by_addr.setdefault(
                        addr,
                        {
                            "neighbor-address": addr,
                            "adj-rib-in-pre": [],
                            "adj-rib-in-post": [],
                            "adj-rib-out-pre": [],
                            "adj-rib-out-post": [],
                        },
                    )
                    for plane, route in (
                        ("adj-rib-in-pre", adj.in_pre),
                        ("adj-rib-in-post", adj.in_post),
                        ("adj-rib-out-pre", adj.out_pre),
                        ("adj-rib-out-post", adj.out_post),
                    ):
                        if route is None:
                            continue
                        r = {
                            "prefix": prefix,
                            "path-id": 0,
                            "attr-index": intern(route.attrs),
                        }
                        if route.attrs.comm:
                            r["community-index"] = intern_comm(
                                route.attrs.comm
                            )
                        r["eligible-route"] = route.is_eligible()
                        if route.ineligible_reason:
                            # yang.rs:206-210: unresolvable is a
                            # holo-bgp augmentation identity.
                            module = (
                                "holo-bgp:"
                                if route.ineligible_reason
                                == "unresolvable"
                                else "iana-bgp-rib-types:"
                            )
                            r["ineligible-reason"] = (
                                module
                                + "ineligible-"
                                + route.ineligible_reason
                            )
                        if route.reject_reason:
                            r["reject-reason"] = (
                                "iana-bgp-rib-types:"
                                + route.reject_reason
                            )
                        ent[plane].append(r)
            entry: dict = {"name": f"iana-bgp-types:{afs}"}
            fam: dict = {}
            if loc_routes:
                fam["loc-rib"] = {"routes": {"route": loc_routes}}
            nbrs = []
            for addr in sorted(nbr_entries_by_addr, key=_addr_key):
                ent = nbr_entries_by_addr[addr]
                out = {"neighbor-address": ent["neighbor-address"]}
                for plane in (
                    "adj-rib-in-pre",
                    "adj-rib-in-post",
                    "adj-rib-out-pre",
                    "adj-rib-out-post",
                ):
                    if ent[plane]:
                        out[plane] = {
                            "routes": {"route": ent[plane]}
                        }
                nbrs.append(out)
            if nbrs:
                fam["neighbors"] = {"neighbor": nbrs}
            if fam:
                entry[afs] = fam
            afi_safi_entries.append(entry)

        rib: dict = {}
        if attr_sets:
            rib["attr-sets"] = {
                "attr-set": [
                    {
                        "index": idx,
                        "attributes": _attrs_state(attrs),
                    }
                    for attrs, idx in attr_sets.items()
                ]
            }
        if comm_sets:
            rib["communities"] = {
                "community": [
                    {"index": idx, "community": [_comm_yang(c) for c in comm]}
                    for comm, idx in comm_sets.items()
                ]
            }
        if afi_safi_entries:
            rib["afi-safis"] = {"afi-safi": afi_safi_entries}
        return rib


# ===== helpers =====

_WELL_KNOWN_COMMS = {
    NO_EXPORT: "iana-bgp-community-types:no-export",
    NO_ADVERTISE: "iana-bgp-community-types:no-advertise",
    NO_EXPORT_SUBCONFED: "iana-bgp-community-types:no-export-subconfed",
}


def _comm_yang(comm: int) -> str:
    """holo-utils/src/bgp.rs:161-175 Comm::to_yang — well-known identity
    or "global:local"."""
    if comm in _WELL_KNOWN_COMMS:
        return _WELL_KNOWN_COMMS[comm]
    return f"{comm >> 16}:{comm & 0xFFFF}"


def _addr_key(addr: str):
    try:
        return (0, int(IPv4Address(addr)))
    except Exception:  # noqa: BLE001 — v6 sort after v4
        return (1, addr)


def _prefix_key(prefix: str):
    addr, _, plen = prefix.partition("/")
    return (_addr_key(addr), int(plen or 0))


def _attrs_sort_key(attrs: BaseAttrs):
    return json.dumps(_attrs_to_json(attrs), sort_keys=True)


def _notif_msg(code: int, subcode) -> dict:
    return {
        "Notification": {
            "error_code": code,
            "error_subcode": int(subcode),
            "data": [],
        }
    }


def _route_compare(a: Route, b: Route) -> tuple[int, str]:
    """rib.rs Route::compare with default selection config.
    Returns (+1 if a preferred, -1 if b preferred, reason)."""
    av = a.attrs.local_pref if a.attrs.local_pref is not None else DFLT_LOCAL_PREF
    bv = b.attrs.local_pref if b.attrs.local_pref is not None else DFLT_LOCAL_PREF
    if av != bv:
        return (1 if av > bv else -1), "local-pref-lower"
    av, bv = a.attrs.path_length(), b.attrs.path_length()
    if av != bv:
        return (1 if av < bv else -1), "as-path-longer"
    av = ORIGIN_ORDER[a.attrs.origin]
    bv = ORIGIN_ORDER[b.attrs.origin]
    if av != bv:
        return (1 if av < bv else -1), "origin-type-higher"
    if a.attrs.first_as() == b.attrs.first_as():
        av, bv = a.attrs.med or 0, b.attrs.med or 0
        if av != bv:
            return (1 if av < bv else -1), "med-higher"
    order = {"Internal": 0, "External": 1}
    av, bv = order[a.route_type], order[b.route_type]
    if av != bv:
        return (1 if av > bv else -1), "prefer-external"
    if (a.igp_cost is None) != (b.igp_cost is None):
        return (
            1 if a.igp_cost is None else -1
        ), "nexthop-cost-higher"
    if a.igp_cost is not None and a.igp_cost != b.igp_cost:
        return (
            1 if a.igp_cost < b.igp_cost else -1
        ), "nexthop-cost-higher"
    if (
        a.origin.identifier is not None
        and b.origin.identifier is not None
    ):
        av = int(IPv4Address(a.origin.identifier))
        bv = int(IPv4Address(b.origin.identifier))
        if av != bv:
            return (1 if av < bv else -1), "higher-router-id"
    if (
        a.origin.remote_addr is not None
        and b.origin.remote_addr is not None
    ):
        av = _addr_key(a.origin.remote_addr)
        bv = _addr_key(b.origin.remote_addr)
        if av != bv:
            return (
                1 if av < bv else -1
            ), "higher-peer-address"
    return -1, "higher-peer-address"


def _multipath_equal(a: Route, b: Route, mp: dict) -> bool:
    """rib.rs:463-487 — equality prerequisites after full tie chain."""
    a_lp = a.attrs.local_pref if a.attrs.local_pref is not None else DFLT_LOCAL_PREF
    b_lp = b.attrs.local_pref if b.attrs.local_pref is not None else DFLT_LOCAL_PREF
    cmp_fields = (
        a_lp == b_lp
        and a.attrs.path_length() == b.attrs.path_length()
        and a.attrs.origin == b.attrs.origin
        and a.route_type == b.route_type
        and a.igp_cost == b.igp_cost
    )
    if not cmp_fields:
        return False
    if a.attrs.first_as() == b.attrs.first_as():
        if (a.attrs.med or 0) != (b.attrs.med or 0):
            return False
    if a.route_type == "External":
        return mp.get("allow_multiple_as", False) or (
            a.attrs.first_as() == b.attrs.first_as()
        )
    return a.attrs.as_path == b.attrs.as_path


def _attrs_from_json(j: dict) -> BaseAttrs:
    base = j.get("base", {})
    segs = tuple(
        AsSegment(s["seg_type"], tuple(s["members"]))
        for s in base.get("as_path", {}).get("segments", [])
    )
    agg = base.get("aggregator")
    return BaseAttrs(
        origin=base.get("origin", "Incomplete"),
        as_path=segs,
        nexthop=base.get("nexthop"),
        ll_nexthop=base.get("ll_nexthop"),
        med=base.get("med"),
        local_pref=base.get("local_pref"),
        aggregator=(agg["asn"], agg["identifier"]) if agg else None,
        # Option<()> serializes as a null-valued key: presence == Some(()).
        atomic_aggregate="atomic_aggregate" in base,
        originator_id=base.get("originator_id"),
        cluster_list=tuple(base.get("cluster_list", ())),
        comm=tuple(j.get("comm", ())),
        ext_comm=tuple(j.get("ext_comm", ())),
        extv6_comm=tuple(j.get("extv6_comm", ())),
        large_comm=tuple(tuple(c) for c in j.get("large_comm", ())),
    )


def _attrs_to_json(attrs: BaseAttrs) -> dict:
    base: dict = {
        "origin": attrs.origin,
        "as_path": {
            "segments": [
                {"seg_type": s.seg_type, "members": list(s.members)}
                for s in attrs.as_path
            ]
        },
    }
    if attrs.nexthop is not None:
        base["nexthop"] = attrs.nexthop
    if attrs.ll_nexthop is not None:
        base["ll_nexthop"] = attrs.ll_nexthop
    if attrs.med is not None:
        base["med"] = attrs.med
    if attrs.local_pref is not None:
        base["local_pref"] = attrs.local_pref
    if attrs.aggregator is not None:
        base["aggregator"] = {
            "asn": attrs.aggregator[0],
            "identifier": attrs.aggregator[1],
        }
    if attrs.atomic_aggregate:
        base["atomic_aggregate"] = None  # Option<()> serde shape
    if attrs.originator_id is not None:
        base["originator_id"] = attrs.originator_id
    if attrs.cluster_list:
        base["cluster_list"] = list(attrs.cluster_list)
    out = {"base": base}
    if attrs.comm:
        out["comm"] = sorted(attrs.comm)
    if attrs.ext_comm:
        out["ext_comm"] = sorted(attrs.ext_comm)
    if attrs.extv6_comm:
        out["extv6_comm"] = sorted(attrs.extv6_comm)
    if attrs.large_comm:
        out["large_comm"] = sorted(list(c) for c in attrs.large_comm)
    return out


def origin_from_json(j) -> RouteOrigin:
    if isinstance(j, dict):
        if "Neighbor" in j:
            return RouteOrigin(
                identifier=str(j["Neighbor"]["identifier"]),
                remote_addr=str(j["Neighbor"]["remote_addr"]),
            )
        if "Protocol" in j:
            return RouteOrigin(protocol=j["Protocol"])
    raise ValueError(f"origin {j}")


def _origin_yang(origin: RouteOrigin) -> str:
    if origin.protocol is not None:
        return f"ietf-routing:{origin.protocol}"
    return origin.remote_addr


def _cap_to_json(cap: tuple, nego: bool = False):
    if cap[0] == "MultiProtocol":
        return {"MultiProtocol": {"afi": cap[1], "safi": cap[2]}}
    if cap[0] == "FourOctetAsNumber":
        if nego or len(cap) == 1:
            return "FourOctetAsNumber"
        return {"FourOctetAsNumber": {"asn": cap[1]}}
    return cap[0]


def _cap_from_json(j) -> tuple:
    if isinstance(j, str):
        return (j,)
    kind, body = next(iter(j.items()))
    if kind == "MultiProtocol":
        return cap_mp(body["afi"], body["safi"])
    if kind == "FourOctetAsNumber":
        return cap_asn32(body["asn"])
    return (kind,)


def _cap_state(index: int, cap: tuple) -> dict:
    out = {
        "code": _CAP_CODE[cap[0]],
        "index": index,
        "name": _CAP_YANG[cap[0]],
    }
    if cap[0] == "MultiProtocol":
        afi = cap[1].lower()
        safi = "unicast-safi" if cap[2] == "Unicast" else cap[2].lower()
        name = f"iana-bgp-types:{cap[1].lower()}-{cap[2].lower()}"
        out["value"] = {
            "mpbgp": {"afi": afi, "safi": safi, "name": name}
        }
    elif cap[0] == "FourOctetAsNumber":
        out["value"] = {"asn32": {"as": cap[1]}}
    return out


def _attrs_state(attrs: BaseAttrs) -> dict:
    out: dict = {"origin": attrs.origin.lower()}
    if attrs.as_path:
        out["as-path"] = {
            "segment": [
                {
                    "type": (
                        "iana-bgp-types:as-sequence"
                        if s.seg_type == "Sequence"
                        else "iana-bgp-types:as-set"
                    ),
                    "member": list(s.members),
                }
                for s in attrs.as_path
            ]
        }
    if attrs.nexthop is not None:
        out["next-hop"] = attrs.nexthop
    if attrs.ll_nexthop is not None:
        out["link-local-next-hop"] = attrs.ll_nexthop
    if attrs.med is not None:
        out["med"] = attrs.med
    if attrs.local_pref is not None:
        out["local-pref"] = attrs.local_pref
    return out

"""IGMPv1/v2 querier (RFC 2236): group membership tracking.

Reference: holo-igmp (SURVEY.md §2.3) — querier election (lowest address),
per-group membership state with expiry, last-member query on leave.
Kernel multicast VIF registration mirrors the reference's per-interface
start_vif (holo-igmp/src/interface.rs:106): pass a
:class:`holo_tpu.routing.mroute.MulticastRouting` as ``mroute`` and each
IGMP interface is added/removed as a VIF on the kernel's multicast
routing socket.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from ipaddress import IPv4Address

from holo_tpu.utils.bytesbuf import DecodeError, Reader, Writer, ip_checksum
from holo_tpu.utils.netio import NetIo, NetRxPacket
from holo_tpu.utils.runtime import Actor

ALL_SYSTEMS = IPv4Address("224.0.0.1")
ALL_ROUTERS = IPv4Address("224.0.0.2")


class IgmpType(enum.IntEnum):
    QUERY = 0x11
    REPORT_V1 = 0x12
    REPORT_V2 = 0x16
    LEAVE = 0x17


@dataclass
class IgmpPacket:
    type: IgmpType
    max_resp: int  # tenths of seconds
    group: IPv4Address

    def encode(self) -> bytes:
        w = Writer()
        w.u8(int(self.type)).u8(self.max_resp).u16(0)
        w.ipv4(self.group)
        cks = ip_checksum(bytes(w.buf))
        w.patch_u16(2, cks)
        return w.finish()

    @classmethod
    def decode(cls, data: bytes) -> "IgmpPacket":
        r = Reader(data)
        try:
            t = IgmpType(r.u8())
        except ValueError as e:
            raise DecodeError("unknown IGMP type") from e
        max_resp = r.u8()
        r.u16()
        if ip_checksum(data[:8]) != 0:
            raise DecodeError("IGMP checksum mismatch")
        return cls(t, max_resp, r.ipv4())


@dataclass
class QueryTimerMsg:
    ifname: str


@dataclass
class GroupExpiryMsg:
    ifname: str
    group: IPv4Address


@dataclass
class OtherQuerierMsg:
    ifname: str


@dataclass
class IgmpIfConfig:
    query_interval: float = 125.0
    query_response_interval: float = 10.0
    robustness: int = 2
    version: int = 2


@dataclass
class Group:
    addr: IPv4Address
    reporters: set = field(default_factory=set)


class IgmpInterface:
    def __init__(self, name: str, cfg: IgmpIfConfig, addr: IPv4Address):
        self.name = name
        self.config = cfg
        self.addr = addr
        self.querier = True  # assume querier until a lower address queries
        self.groups: dict[IPv4Address, Group] = {}


class IgmpInstance(Actor):
    name = "igmp"

    def __init__(self, name: str, netio: NetIo, group_cb=None, mroute=None):
        self.name = name
        self.netio = netio
        self.group_cb = group_cb  # callable(ifname, groups) membership hook
        self.mroute = mroute  # MulticastRouting: kernel VIF programming
        self.interfaces: dict[str, IgmpInterface] = {}

    def add_interface(
        self,
        ifname: str,
        cfg: IgmpIfConfig,
        addr: IPv4Address,
        ifindex: int | None = None,
    ):
        iface = IgmpInterface(ifname, cfg, addr)
        self.interfaces[ifname] = iface
        if self.mroute is not None and ifindex is not None:
            # Register the interface as a kernel multicast VIF
            # (reference interface.rs:106 start_vif).
            self.mroute.add_vif(ifname, ifindex)
        t = self.loop.timer(self.name, lambda: QueryTimerMsg(ifname))
        iface._query_timer = t
        t.start(0.1)

    def remove_interface(self, ifname: str) -> None:
        iface = self.interfaces.pop(ifname, None)
        if iface is None:
            return
        for attr in ("_query_timer", "_other_querier_timer"):
            t = getattr(iface, attr, None)
            if t is not None:
                t.cancel()
        for g in iface.groups.values():
            t = getattr(g, "_expiry", None)
            if t is not None:
                t.cancel()
        if self.mroute is not None:
            self.mroute.del_vif(ifname)

    def handle(self, msg):
        if isinstance(msg, NetRxPacket):
            self._rx(msg)
        elif isinstance(msg, QueryTimerMsg):
            self._send_query(msg.ifname)
        elif isinstance(msg, GroupExpiryMsg):
            self._expire_group(msg.ifname, msg.group)
        elif isinstance(msg, OtherQuerierMsg):
            iface = self.interfaces.get(msg.ifname)
            if iface is not None:
                iface.querier = True  # other querier present timer expired
                iface._query_timer.start(0.1)

    # -- querier

    def _send_query(self, ifname: str, group: IPv4Address = IPv4Address(0)) -> None:
        iface = self.interfaces.get(ifname)
        if iface is None or not iface.querier:
            return
        pkt = IgmpPacket(
            IgmpType.QUERY,
            int(iface.config.query_response_interval * 10),
            group,
        )
        self.netio.send(ifname, iface.addr, ALL_SYSTEMS, pkt.encode())
        iface._query_timer.start(iface.config.query_interval)

    def _rx(self, msg: NetRxPacket) -> None:
        iface = self.interfaces.get(msg.ifname)
        if iface is None:
            return
        try:
            pkt = IgmpPacket.decode(msg.data)
        except DecodeError:
            return
        if pkt.type == IgmpType.QUERY:
            # Querier election: lowest address wins (RFC 2236 §3).
            if msg.src is not None and int(msg.src) < int(iface.addr):
                iface.querier = False
                t = getattr(iface, "_other_querier_timer", None)
                if t is None:
                    t = self.loop.timer(
                        self.name, lambda: OtherQuerierMsg(iface.name)
                    )
                    iface._other_querier_timer = t
                t.start(
                    iface.config.robustness * iface.config.query_interval
                    + iface.config.query_response_interval / 2
                )
        elif pkt.type in (IgmpType.REPORT_V1, IgmpType.REPORT_V2):
            if not pkt.group.is_multicast:
                return
            g = iface.groups.get(pkt.group)
            if g is None:
                g = Group(pkt.group)
                iface.groups[pkt.group] = g
                self._notify(iface)
            if msg.src is not None:
                g.reporters.add(msg.src)
            t = getattr(g, "_expiry", None)
            if t is None:
                t = self.loop.timer(
                    self.name,
                    lambda grp=pkt.group: GroupExpiryMsg(iface.name, grp),
                )
                g._expiry = t
            t.start(
                iface.config.robustness * iface.config.query_interval
                + iface.config.query_response_interval
            )
        elif pkt.type == IgmpType.LEAVE:
            g = iface.groups.get(pkt.group)
            if g is not None and iface.querier:
                # Last-member query: short expiry unless a report arrives.
                self._send_group_query(iface, pkt.group)
                g._expiry.start(2.0)

    def _send_group_query(self, iface: IgmpInterface, group: IPv4Address) -> None:
        pkt = IgmpPacket(IgmpType.QUERY, 10, group)
        self.netio.send(iface.name, iface.addr, group, pkt.encode())

    def _expire_group(self, ifname: str, group: IPv4Address) -> None:
        iface = self.interfaces.get(ifname)
        if iface is None:
            return
        if iface.groups.pop(group, None) is not None:
            self._notify(iface)

    def _notify(self, iface: IgmpInterface) -> None:
        if self.group_cb is not None:
            self.group_cb(iface.name, set(iface.groups.keys()))

"""BGP policy evaluation worker: the CPU-offload actor pattern.

Reference: holo-bgp offloads policy application to a dedicated blocking
worker fed over crossbeam channels (holo-bgp/src/tasks.rs:457-520,
SURVEY.md §2.4.6) so heavy policy runs never stall the instance's event
loop.  This is the same boundary the TPU SPF backend generalizes: ship a
batch out, results return as input messages.

``PolicyWorker`` is an actor (separate OS thread in production via the
native MsgRing; same loop in deterministic tests) evaluating batches of
(prefix, attrs) through the policy engine and replying to the BGP
instance, which applies results only if the peer generation still
matches (a peer flap between request and reply discards stale results).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from holo_tpu.utils.policy import PolicyEngine
from holo_tpu.utils.runtime import Actor


@dataclass
class EvalBatchRequest:
    reply_to: str
    peer: Any  # peer address
    peer_generation: int
    policy_name: str
    entries: list  # [(prefix, PathAttrs)]
    token: int = 0


@dataclass
class EvalBatchResult:
    peer: Any
    peer_generation: int
    entries: list  # [(prefix, PathAttrs | None)]  None = rejected
    token: int = 0


class PolicyWorker(Actor):
    """Evaluates policy batches; CPU-bound work isolated from protocol
    actors (swap in a thread + MsgRing for true parallelism in prod)."""

    name = "bgp-policy-worker"

    def __init__(self, engine: PolicyEngine):
        self.engine = engine
        self.batches_processed = 0

    def handle(self, msg):
        if not isinstance(msg, EvalBatchRequest):
            return
        # Reuse the engine's canonical per-route hook so the sync and async
        # paths can never diverge; the batch's peer scopes neighbor-set
        # conditions.
        hook = self.engine.bgp_import_hook(msg.policy_name, neighbor=msg.peer)
        out = [(prefix, hook(prefix, attrs)) for prefix, attrs in msg.entries]
        self.batches_processed += 1
        self.loop.send(
            msg.reply_to,
            EvalBatchResult(msg.peer, msg.peer_generation, out, msg.token),
        )

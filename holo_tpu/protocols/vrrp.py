"""VRRP v2/v3 (RFC 3768 / RFC 5798): virtual router redundancy.

Reference: holo-vrrp (SURVEY.md §2.3) — master election FSM per virtual
router instance on an interface; the master answers for the virtual IPs
(macvlan programming in the daemon; recorded on the mock kernel in tests).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from ipaddress import IPv4Address

from holo_tpu.utils.bytesbuf import DecodeError, Reader, Writer, ip_checksum
from holo_tpu.utils.ip import VRRP_GROUP_V4, VRRP_GROUP_V6
from holo_tpu.utils.netio import NetIo, NetRxPacket
from holo_tpu.utils.runtime import Actor


class VrrpState(enum.Enum):
    INITIALIZE = "initialize"
    BACKUP = "backup"
    MASTER = "master"


@dataclass
class VrrpPacket:
    """VRRPv3 (RFC 5798 §5.2); v2 differs in advert-int units + auth.
    ``af`` selects the address family the virtual addresses encode in
    (v6 checksums ride the kernel's pseudo-header offload: 0 on tx)."""

    version: int
    vrid: int
    priority: int
    max_advert_int: int  # centiseconds (v3) / seconds (v2)
    addresses: list = field(default_factory=list)
    af: int = 4

    def encode(self) -> bytes:
        w = Writer()
        w.u8((self.version << 4) | 1)  # type 1 = advertisement
        w.u8(self.vrid)
        w.u8(self.priority)
        w.u8(len(self.addresses))
        if self.version == 3:
            w.u16(self.max_advert_int & 0xFFF)
        else:
            w.u8(0).u8(self.max_advert_int & 0xFF)  # auth type 0, advert int
        w.u16(0)  # checksum
        for a in self.addresses:
            if self.af == 4:
                w.ipv4(a)
            else:
                w.ipv6(a)
        if self.version == 2:
            w.u64(0)  # empty auth data
        if self.af == 4:
            cks = ip_checksum(bytes(w.buf))
            w.patch_u16(6, cks)
        return w.finish()

    @classmethod
    def decode(cls, data: bytes, af: int = 4) -> "VrrpPacket":
        r = Reader(data)
        vt = r.u8()
        version, ptype = vt >> 4, vt & 0xF
        if version not in (2, 3) or ptype != 1:
            raise DecodeError("bad VRRP version/type")
        vrid = r.u8()
        prio = r.u8()
        count = r.u8()
        if version == 3:
            advert = r.u16() & 0xFFF
        else:
            r.u8()
            advert = r.u8()
        r.u16()  # checksum (validated below; v6 uses the pseudo-header
        # and is checked by the kernel before delivery)
        if af == 4 and ip_checksum(data) != 0:
            raise DecodeError("VRRP checksum mismatch")
        addrs = [r.ipv4() if af == 4 else r.ipv6() for _ in range(count)]
        return cls(version, vrid, prio, advert, addrs, af)


@dataclass
class AdvertTimerMsg:
    vrid: int


@dataclass
class MasterDownTimerMsg:
    vrid: int


@dataclass
class VrrpConfig:
    vrid: int
    ifname: str
    version: int = 3
    af: int = 4
    priority: int = 100
    advert_interval: float = 1.0  # seconds
    addresses: list = field(default_factory=list)
    preempt: bool = True
    accept: bool = False


class VrrpInstance(Actor):
    """One virtual router (per (interface, vrid) like the reference's
    per-interface ProtocolInstance, holo-vrrp/src/interface.rs:36)."""

    name = "vrrp"

    def __init__(self, name: str, config: VrrpConfig, iface_addr: IPv4Address,
                 netio: NetIo, on_state=None, garp_cb=None, notif_cb=None):
        self.name = name
        self.config = config
        self.iface_addr = iface_addr
        self.netio = netio
        self.on_state = on_state  # callable(state) for macvlan programming
        self.notif_cb = notif_cb  # YANG notifications (vrrp-new-master-event)
        # callable(addr) fired per virtual address on master transition:
        # gratuitous ARP (v4) / unsolicited neighbor advert (v6).
        self.garp_cb = garp_cb
        self.state = VrrpState.INITIALIZE
        self.master_adver_int = config.advert_interval
        self.owner = iface_addr in config.addresses
        # True while we are deliberately letting master-down expire to
        # preempt a live lower-priority master (event reason plumbing).
        self._preempting = False

    def attach(self, loop_):
        super().attach(loop_)
        self._advert_timer = self.loop.timer(
            self.name, lambda: AdvertTimerMsg(self.config.vrid)
        )
        self._mdown_timer = self.loop.timer(
            self.name, lambda: MasterDownTimerMsg(self.config.vrid)
        )

    # -- FSM entry points

    def startup(self) -> None:
        if self.owner or self.config.priority == 255:
            self._become_master("priority")
        else:
            self._become_backup()

    def shutdown(self) -> None:
        if self.state == VrrpState.MASTER:
            self._send_advert(priority=0)
        self._advert_timer.cancel()
        self._mdown_timer.cancel()
        self._set_state(VrrpState.INITIALIZE)

    # -- timers

    def _skew_time(self) -> float:
        return ((256 - self.config.priority) / 256.0) * self.master_adver_int

    def _master_down_interval(self) -> float:
        return 3 * self.master_adver_int + self._skew_time()

    def _become_master(self, reason: str = "no-response") -> None:
        became = self.state != VrrpState.MASTER
        self._preempting = False
        self._set_state(VrrpState.MASTER)
        if became and self.notif_cb is not None:
            # Reference holo-vrrp northbound/notification.rs:21-29.
            self.notif_cb({
                "ietf-vrrp:vrrp-new-master-event": {
                    "master-ip-address": str(self.iface_addr),
                    "new-master-reason": reason,
                }
            })
        self._send_advert()
        if self.garp_cb is not None:
            for addr in self.config.addresses:
                self.garp_cb(addr)
        self._advert_timer.start(self.config.advert_interval)
        self._mdown_timer.cancel()

    def _become_backup(self) -> None:
        self._set_state(VrrpState.BACKUP)
        self._advert_timer.cancel()
        self._mdown_timer.start(self._master_down_interval())

    def _set_state(self, new: VrrpState) -> None:
        if new != self.state:
            self.state = new
            if self.on_state is not None:
                self.on_state(new)

    # -- actor

    def handle(self, msg):
        if isinstance(msg, NetRxPacket):
            self._rx(msg)
        elif isinstance(msg, AdvertTimerMsg):
            if self.state == VrrpState.MASTER:
                self._send_advert()
                self._advert_timer.start(self.config.advert_interval)
        elif isinstance(msg, MasterDownTimerMsg):
            if self.state == VrrpState.BACKUP:
                self._become_master(
                    "preempted" if self._preempting else "no-response"
                )

    def _rx(self, msg: NetRxPacket) -> None:
        try:
            pkt = VrrpPacket.decode(msg.data, af=self.config.af)
        except DecodeError:
            return
        self.rx_packet(msg.src, pkt)

    def rx_packet(self, src, pkt: VrrpPacket) -> None:
        """Process a decoded advertisement (the conformance replay feeds
        decoded objects, like the reference's testing stub)."""
        if pkt.vrid != self.config.vrid:
            return
        if pkt.version == 3:
            advert = pkt.max_advert_int / 100.0
        else:
            advert = float(pkt.max_advert_int)
        if self.state == VrrpState.BACKUP:
            if pkt.priority == 0:
                self._mdown_timer.start(self._skew_time())
            elif (
                not self.config.preempt
                or pkt.priority >= self.config.priority
            ):
                self._preempting = False
                self.master_adver_int = advert
                self._mdown_timer.start(self._master_down_interval())
            else:
                # We preempt by letting master-down expire.
                self._preempting = True
        elif self.state == VrrpState.MASTER:
            if pkt.priority == 0:
                self._send_advert()
                self._advert_timer.start(self.config.advert_interval)
            elif pkt.priority > self.config.priority or (
                pkt.priority == self.config.priority
                and int(src) > int(self.iface_addr)
            ):
                self.master_adver_int = advert
                self._become_backup()
            else:
                # Lower-priority challenger: assert mastership at once.
                self._send_advert()
                self._advert_timer.start(self.config.advert_interval)

    def _send_advert(self, priority: int | None = None) -> None:
        cfg = self.config
        adv = (
            int(cfg.advert_interval * 100)
            if cfg.version == 3
            else int(cfg.advert_interval)
        )
        pkt = VrrpPacket(
            version=cfg.version,
            vrid=cfg.vrid,
            priority=cfg.priority if priority is None else priority,
            max_advert_int=adv,
            addresses=list(cfg.addresses),
            af=cfg.af,
        )
        group = VRRP_GROUP_V4 if cfg.af == 4 else VRRP_GROUP_V6
        self.netio.send(cfg.ifname, self.iface_addr, group, pkt.encode())

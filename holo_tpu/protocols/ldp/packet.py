"""LDP wire codec (RFC 5036 + RFC 5561/5918/5919 capabilities).

Full PDU/message/TLV encode-decode for the reference-grade LDP engine
(reference: holo-ldp/src/packet/{pdu,message,tlv}.rs and
packet/messages/*.rs).  Messages are dataclasses whose fields mirror the
reference's serde shapes so the conformance harness can map the recorded
JSON corpus onto them 1:1 (holo-ldp/tests/conformance).

Layout summary:
- PDU header: version(2) pdu-len(2) lsr-id(4) label-space(2); pdu-len
  covers lsr-id onward (pdu.rs:19-33).
- Message: U|type(2) len(2) msg-id(4) TLVs... (message.rs:23-45).
- TLV: U|F|type(2) len(2) value (tlv.rs:17-34).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from ipaddress import (
    IPv4Address,
    IPv4Network,
    IPv6Address,
    IPv6Network,
    ip_network,
)

from holo_tpu.utils.bytesbuf import DecodeError as _BufDecodeError
from holo_tpu.utils.bytesbuf import Reader, Writer

LDP_VERSION = 1
PDU_HDR_SIZE = 10
PDU_HDR_MIN_LEN = 6  # lsr-id + label-space
PDU_HDR_DEAD_LEN = 4  # version + pdu-length fields
PDU_DFLT_MAX_LEN = 4096

TLV_HDR_SIZE = 4
TLV_UNKNOWN_FLAG = 0x8000
TLV_FORWARD_FLAG = 0x4000
TLV_TYPE_MASK = 0x3FFF

MSG_UNKNOWN_FLAG = 0x8000
MSG_TYPE_MASK = 0x7FFF

INFINITE_HOLDTIME = 0xFFFF

# Hello flags (hello.rs:74-81)
HELLO_TARGETED = 0x8000
HELLO_REQ_TARGETED = 0x4000
HELLO_GTSM = 0x2000

# Init flags (initialization.rs:85-91)
INIT_ADV_DISCIPLINE = 0x80
INIT_LOOP_DETECTION = 0x40

# Capability S-bit (capability.rs:62)
TLV_CAP_S_BIT = 0x80

# FEC element types (label.rs:163-176)
FEC_ELEMENT_WILDCARD = 0x01
FEC_ELEMENT_PREFIX = 0x02
FEC_ELEMENT_TYPED_WILDCARD = 0x05

AF_IPV4 = 1
AF_IPV6 = 2


class MsgType(enum.IntEnum):
    """message.rs:58-77 (IANA LDP message types)."""

    NOTIFICATION = 0x0001
    HELLO = 0x0100
    INITIALIZATION = 0x0200
    KEEPALIVE = 0x0201
    CAPABILITY = 0x0202
    ADDRESS = 0x0300
    ADDRESS_WITHDRAW = 0x0301
    LABEL_MAPPING = 0x0400
    LABEL_REQUEST = 0x0401
    LABEL_WITHDRAW = 0x0402
    LABEL_RELEASE = 0x0403
    LABEL_ABORT_REQ = 0x0404


class TlvType(enum.IntEnum):
    """tlv.rs:40-75 (IANA LDP TLV types)."""

    FEC = 0x0100
    ADDR_LIST = 0x0101
    HOP_COUNT = 0x0103
    PATH_VECTOR = 0x0104
    GENERIC_LABEL = 0x0200
    STATUS = 0x0300
    EXT_STATUS = 0x0301
    RETURNED_PDU = 0x0302
    RETURNED_MSG = 0x0303
    RETURNED_TLVS = 0x0304
    COMMON_HELLO_PARAMS = 0x0400
    IPV4_TRANS_ADDR = 0x0401
    CONFIG_SEQNO = 0x0402
    IPV6_TRANS_ADDR = 0x0403
    COMMON_SESS_PARAMS = 0x0500
    CAP_DYNAMIC = 0x0506
    CAP_TWCARD_FEC = 0x050B
    LABEL_REQUEST_ID = 0x0600
    CAP_UNREC_NOTIF = 0x0603
    DUAL_STACK = 0x0701


class StatusCode(enum.IntEnum):
    """notification.rs:100-141 (IANA LDP status codes)."""

    SUCCESS = 0x0000_0000
    BAD_LDP_ID = 0x0000_0001
    BAD_PROTO_VERS = 0x0000_0002
    BAD_PDU_LEN = 0x0000_0003
    UNKNOWN_MSG_TYPE = 0x0000_0004
    BAD_MSG_LEN = 0x0000_0005
    UNKNOWN_TLV = 0x0000_0006
    BAD_TLV_LEN = 0x0000_0007
    MALFORMED_TLV_VALUE = 0x0000_0008
    HOLD_TIMER_EXP = 0x0000_0009
    SHUTDOWN = 0x0000_000A
    LOOP_DETECTED = 0x0000_000B
    UNKNOWN_FEC = 0x0000_000C
    NO_ROUTE = 0x0000_000D
    NO_LABEL_RES = 0x0000_000E
    LABEL_RES_AVAILABLE = 0x0000_000F
    SESS_REJ_NO_HELLO = 0x0000_0010
    SESS_REJ_ADV_MODE = 0x0000_0011
    SESS_REJ_MAX_PDU_LEN = 0x0000_0012
    SESS_REJ_LABEL_RANGE = 0x0000_0013
    KEEPALIVE_EXP = 0x0000_0014
    LABEL_REQ_ABRT = 0x0000_0015
    MISSING_MSG_PARAMS = 0x0000_0016
    UNSUPPORTED_AF = 0x0000_0017
    SESS_REJ_KEEPALIVE = 0x0000_0018
    INTERNAL_ERROR = 0x0000_0019
    UNSUPPORTED_CAP = 0x0000_002E  # RFC 5561
    END_OF_LIB = 0x0000_002F  # RFC 5919
    TRANSPORT_MISMATCH = 0x0000_0032  # RFC 7552
    DS_NONCOMPLIANCE = 0x0000_0033

    # Fatal-error E bit / forward F bit (notification.rs:143-145).
    E_FLAG = 0x8000_0000
    F_FLAG = 0x4000_0000

    def encode_status(self, fwd: bool = False) -> int:
        """Status code word with the E bit set for fatal errors
        (notification.rs StatusCode::encode)."""
        code = int(self)
        if self in _FATAL_CODES:
            code |= StatusCode.E_FLAG
        if fwd:
            code |= StatusCode.F_FLAG
        return code


# Codes the reference raises as session-fatal (E-bit set when sent):
# everything that tears the session down per RFC 5036 §3.5.1.1.
_FATAL_CODES = frozenset(
    {
        StatusCode.BAD_LDP_ID,
        StatusCode.BAD_PROTO_VERS,
        StatusCode.BAD_PDU_LEN,
        StatusCode.BAD_MSG_LEN,
        StatusCode.BAD_TLV_LEN,
        StatusCode.MALFORMED_TLV_VALUE,
        StatusCode.HOLD_TIMER_EXP,
        StatusCode.SHUTDOWN,
        StatusCode.SESS_REJ_NO_HELLO,
        StatusCode.SESS_REJ_ADV_MODE,
        StatusCode.SESS_REJ_MAX_PDU_LEN,
        StatusCode.SESS_REJ_LABEL_RANGE,
        StatusCode.KEEPALIVE_EXP,
        StatusCode.SESS_REJ_KEEPALIVE,
        StatusCode.INTERNAL_ERROR,
    }
)


def status_is_fatal(status_code_word: int) -> bool:
    return bool(status_code_word & StatusCode.E_FLAG)


class DecodeError(Exception):
    """Decode failure; `kind` mirrors the reference DecodeError variant
    names (packet/error.rs:19-45) so recorded Err inputs map onto it."""

    def __init__(self, kind: str, *args):
        super().__init__(f"{kind}{args if args else ''}")
        self.kind = kind
        self.args_ = args

    def status_code(self) -> StatusCode:
        """notification.rs:459-477 — decode error -> LDP status."""
        return {
            "InvalidPduLength": StatusCode.BAD_PDU_LEN,
            "InvalidVersion": StatusCode.BAD_PROTO_VERS,
            "InvalidLsrId": StatusCode.BAD_LDP_ID,
            "InvalidLabelSpace": StatusCode.BAD_LDP_ID,
            "InvalidMessageLength": StatusCode.BAD_MSG_LEN,
            "UnknownMessage": StatusCode.UNKNOWN_MSG_TYPE,
            "MissingMsgParams": StatusCode.MISSING_MSG_PARAMS,
            "InvalidTlvLength": StatusCode.BAD_TLV_LEN,
            "UnknownTlv": StatusCode.UNKNOWN_TLV,
            "InvalidTlvValue": StatusCode.MALFORMED_TLV_VALUE,
            "UnsupportedAf": StatusCode.UNSUPPORTED_AF,
            "UnknownFec": StatusCode.UNKNOWN_FEC,
            "BadKeepaliveTime": StatusCode.SESS_REJ_KEEPALIVE,
        }.get(self.kind, StatusCode.INTERNAL_ERROR)


# ===== FEC elements =====


@dataclass(frozen=True)
class FecPrefix:
    prefix: IPv4Network | IPv6Network

    def encode(self, w: Writer) -> None:
        af = AF_IPV4 if self.prefix.version == 4 else AF_IPV6
        plen = self.prefix.prefixlen
        nbytes = (plen + 7) // 8
        w.u8(FEC_ELEMENT_PREFIX).u16(af).u8(plen)
        w.bytes(self.prefix.network_address.packed[:nbytes])


@dataclass(frozen=True)
class FecWildcard:
    """The full wildcard (element 0x01) or a typed wildcard (0x05,
    RFC 5918) constrained to prefix FECs of one address family."""

    typed_af: int | None = None  # None = "All"; AF_IPV4/AF_IPV6 = typed

    def encode(self, w: Writer) -> None:
        if self.typed_af is None:
            w.u8(FEC_ELEMENT_WILDCARD)
        else:
            # label.rs:519-536: typed wildcard for Prefix FECs.
            w.u8(FEC_ELEMENT_TYPED_WILDCARD)
            w.u8(FEC_ELEMENT_PREFIX).u8(2).u16(self.typed_af)


FecElem = FecPrefix | FecWildcard


def _decode_fec_elems(r: Reader) -> list[FecElem]:
    out: list[FecElem] = []
    while r.remaining() > 0:
        elem = r.u8()
        if elem == FEC_ELEMENT_WILDCARD:
            out.append(FecWildcard())
        elif elem == FEC_ELEMENT_PREFIX:
            if r.remaining() < 3:
                raise DecodeError("InvalidTlvLength", r.remaining())
            af = r.u16()
            plen = r.u8()
            if af not in (AF_IPV4, AF_IPV6):
                raise DecodeError("UnsupportedAf", af)
            maxlen = 32 if af == AF_IPV4 else 128
            if plen > maxlen:
                raise DecodeError("InvalidTlvValue")
            nbytes = (plen + 7) // 8
            if r.remaining() < nbytes:
                raise DecodeError("InvalidTlvLength", r.remaining())
            raw = r.bytes(nbytes)
            width = 4 if af == AF_IPV4 else 16
            raw = raw + bytes(width - nbytes)
            out.append(
                FecPrefix(ip_network((raw, plen), strict=False))
            )
        elif elem == FEC_ELEMENT_TYPED_WILDCARD:
            if r.remaining() < 4:
                raise DecodeError("InvalidTlvLength", r.remaining())
            inner = r.u8()
            r.u8()  # len of FEC type info
            af = r.u16()
            if inner != FEC_ELEMENT_PREFIX:
                raise DecodeError("UnknownFec", inner)
            if af not in (AF_IPV4, AF_IPV6):
                raise DecodeError("UnsupportedAf", af)
            out.append(FecWildcard(typed_af=af))
        else:
            raise DecodeError("UnknownFec", elem)
    return out


# ===== Messages =====


@dataclass
class HelloMsg:
    msg_id: int = 0
    holdtime: int = 15
    flags: int = 0  # HELLO_* bits
    ipv4_addr: IPv4Address | None = None  # transport address TLV
    ipv6_addr: IPv6Address | None = None
    cfg_seqno: int | None = None
    dual_stack: int | None = None  # transport preference (RFC 7552)

    msg_type = MsgType.HELLO

    def encode_body(self, w: Writer) -> None:
        w.u16(TlvType.COMMON_HELLO_PARAMS).u16(4)
        w.u16(self.holdtime).u16(self.flags)
        if self.ipv4_addr is not None:
            w.u16(TlvType.IPV4_TRANS_ADDR).u16(4).ipv4(self.ipv4_addr)
        if self.ipv6_addr is not None:
            w.u16(TlvType.IPV6_TRANS_ADDR).u16(16).ipv6(self.ipv6_addr)
        if self.cfg_seqno is not None:
            w.u16(TlvType.CONFIG_SEQNO).u16(4).u32(self.cfg_seqno)
        if self.dual_stack is not None:
            w.u16(TLV_UNKNOWN_FLAG | TlvType.DUAL_STACK).u16(4)
            w.u16(self.dual_stack << 12).u16(0)


@dataclass
class InitMsg:
    msg_id: int = 0
    keepalive_time: int = 180
    flags: int = 0  # INIT_* bits
    pvlim: int = 0
    max_pdu_len: int = 0
    lsr_id: IPv4Address = IPv4Address(0)  # receiver LSR-ID
    lspace_id: int = 0
    cap_dynamic: bool = False
    cap_twcard_fec: bool | None = None  # value = S bit
    cap_unrec_notif: bool | None = None

    msg_type = MsgType.INITIALIZATION

    def encode_body(self, w: Writer) -> None:
        w.u16(TlvType.COMMON_SESS_PARAMS).u16(14)
        w.u16(LDP_VERSION).u16(self.keepalive_time)
        w.u8(self.flags).u8(self.pvlim).u16(self.max_pdu_len)
        w.ipv4(self.lsr_id).u16(self.lspace_id)
        if self.cap_dynamic:
            w.u16(TLV_UNKNOWN_FLAG | TlvType.CAP_DYNAMIC).u16(1)
            w.u8(TLV_CAP_S_BIT)
        if self.cap_twcard_fec is not None:
            w.u16(TLV_UNKNOWN_FLAG | TlvType.CAP_TWCARD_FEC).u16(1)
            w.u8(TLV_CAP_S_BIT if self.cap_twcard_fec else 0)
        if self.cap_unrec_notif is not None:
            w.u16(TLV_UNKNOWN_FLAG | TlvType.CAP_UNREC_NOTIF).u16(1)
            w.u8(TLV_CAP_S_BIT if self.cap_unrec_notif else 0)


@dataclass
class KeepaliveMsg:
    msg_id: int = 0

    msg_type = MsgType.KEEPALIVE

    def encode_body(self, w: Writer) -> None:
        pass


@dataclass
class AddressMsg:
    msg_id: int = 0
    withdraw: bool = False
    addr_list: list[IPv4Address | IPv6Address] = field(default_factory=list)

    @property
    def msg_type(self) -> MsgType:
        return MsgType.ADDRESS_WITHDRAW if self.withdraw else MsgType.ADDRESS

    def encode_body(self, w: Writer) -> None:
        # The Address-List TLV is single-family (address.rs
        # TlvAddressList enum): a mixed list cannot be encoded.
        versions = {a.version for a in self.addr_list}
        if len(versions) > 1:
            raise ValueError("mixed v4/v6 address list")
        v6 = versions == {6}
        width = 16 if v6 else 4
        w.u16(TlvType.ADDR_LIST).u16(2 + width * len(self.addr_list))
        w.u16(AF_IPV6 if v6 else AF_IPV4)
        for a in self.addr_list:
            w.bytes(a.packed)


@dataclass
class LabelMsg:
    msg_id: int = 0
    msg_type: MsgType = MsgType.LABEL_MAPPING
    fec: list[FecElem] = field(default_factory=list)
    label: int | None = None
    request_id: int | None = None

    def encode_body(self, w: Writer) -> None:
        pos = len(w)
        w.u16(TlvType.FEC).u16(0)
        start = len(w)
        for elem in self.fec:
            elem.encode(w)
        w.patch_u16(pos + 2, len(w) - start)
        if self.label is not None:
            w.u16(TlvType.GENERIC_LABEL).u16(4).u32(self.label)
        if self.request_id is not None:
            w.u16(TlvType.LABEL_REQUEST_ID).u16(4).u32(self.request_id)


@dataclass
class NotifMsg:
    msg_id: int = 0
    status_code: int = 0  # full word incl. E/F bits
    status_msg_id: int = 0
    status_msg_type: int = 0
    ext_status: int | None = None
    fec: list[FecElem] | None = None

    msg_type = MsgType.NOTIFICATION

    def is_fatal(self) -> bool:
        return status_is_fatal(self.status_code)

    def encode_body(self, w: Writer) -> None:
        # The status TLV's U/F bits mirror the status code's E/F bits
        # (notification.rs TlvStatus::encode_hdr override).
        ttype = int(TlvType.STATUS)
        if status_is_fatal(self.status_code):
            ttype |= TLV_UNKNOWN_FLAG
        if self.status_code & StatusCode.F_FLAG:
            ttype |= TLV_FORWARD_FLAG
        w.u16(ttype).u16(10)
        w.u32(self.status_code).u32(self.status_msg_id)
        w.u16(self.status_msg_type)
        if self.ext_status is not None:
            w.u16(TlvType.EXT_STATUS).u16(4).u32(self.ext_status)
        if self.fec is not None:
            pos = len(w)
            w.u16(TlvType.FEC).u16(0)
            start = len(w)
            for elem in self.fec:
                elem.encode(w)
            w.patch_u16(pos + 2, len(w) - start)


@dataclass
class CapabilityMsg:
    """RFC 5561 dynamic capability announcement (capability.rs)."""

    msg_id: int = 0
    twcard_fec: bool | None = None  # value = S bit
    unrec_notif: bool | None = None

    msg_type = MsgType.CAPABILITY

    def encode_body(self, w: Writer) -> None:
        if self.twcard_fec is not None:
            w.u16(TLV_UNKNOWN_FLAG | TlvType.CAP_TWCARD_FEC).u16(1)
            w.u8(TLV_CAP_S_BIT if self.twcard_fec else 0)
        if self.unrec_notif is not None:
            w.u16(TLV_UNKNOWN_FLAG | TlvType.CAP_UNREC_NOTIF).u16(1)
            w.u8(TLV_CAP_S_BIT if self.unrec_notif else 0)


Message = (
    HelloMsg
    | InitMsg
    | KeepaliveMsg
    | AddressMsg
    | LabelMsg
    | NotifMsg
    | CapabilityMsg
)

_LABEL_TYPES = {
    MsgType.LABEL_MAPPING,
    MsgType.LABEL_REQUEST,
    MsgType.LABEL_WITHDRAW,
    MsgType.LABEL_RELEASE,
    MsgType.LABEL_ABORT_REQ,
}


def _encode_message(msg: Message, w: Writer) -> None:
    mtype = int(msg.msg_type)
    # U-bit messages: capability is U per RFC 5561 (capability.rs U_BIT).
    if isinstance(msg, CapabilityMsg):
        mtype |= MSG_UNKNOWN_FLAG
    w.u16(mtype)
    len_pos = len(w)
    w.u16(0)
    body_start = len(w)
    w.u32(msg.msg_id)
    msg.encode_body(w)
    w.patch_u16(len_pos, len(w) - body_start)


@dataclass
class Pdu:
    lsr_id: IPv4Address
    lspace_id: int = 0
    messages: list[Message] = field(default_factory=list)
    version: int = LDP_VERSION

    def encode(self, max_pdu_len: int = PDU_DFLT_MAX_LEN) -> bytes:
        """One or more wire PDUs (splits when max_pdu_len is exceeded,
        pdu.rs:80-135)."""
        out = bytearray()
        w = self._new_hdr()
        for msg in self.messages:
            before = len(w)
            _encode_message(msg, w)
            if len(w) > max_pdu_len and before > PDU_HDR_SIZE:
                full = w.finish()
                head, tail = full[:before], full[before:]
                out += self._finish_pdu(head)
                w = self._new_hdr()
                w.bytes(tail)
        out += self._finish_pdu(w.finish())
        return bytes(out)

    def _new_hdr(self) -> Writer:
        w = Writer()
        w.u16(self.version).u16(0)
        w.ipv4(self.lsr_id).u16(self.lspace_id)
        return w

    @staticmethod
    def _finish_pdu(buf: bytes) -> bytes:
        ln = len(buf) - PDU_HDR_DEAD_LEN
        return buf[:2] + ln.to_bytes(2, "big") + buf[4:]

    @classmethod
    def decode(cls, data: bytes, multicast: bool | None = None) -> "Pdu":
        """Decode one PDU (pdu.rs decode + per-message decode_body).

        ``multicast`` enables the hello link/targeted cross-checks
        (hello.rs:266-280) when the transport is known.
        """
        r = Reader(data)
        if r.remaining() < PDU_HDR_SIZE:
            raise DecodeError("IncompletePdu")
        version = r.u16()
        pdu_len = r.u16()
        if version != LDP_VERSION:
            raise DecodeError("InvalidVersion", version)
        if (
            pdu_len < PDU_HDR_MIN_LEN
            or pdu_len + PDU_HDR_DEAD_LEN > len(data)
        ):
            raise DecodeError("InvalidPduLength", pdu_len)
        lsr_id = r.ipv4()
        if lsr_id == IPv4Address(0):
            raise DecodeError("InvalidLsrId", str(lsr_id))
        lspace_id = r.u16()
        if lspace_id != 0:
            raise DecodeError("InvalidLabelSpace", lspace_id)
        end = PDU_HDR_DEAD_LEN + pdu_len
        body = Reader(data, start=PDU_HDR_SIZE, end=end)
        messages: list[Message] = []
        try:
            while body.remaining() >= 8:
                msg = _decode_message(body, multicast)
                if msg is not None:
                    messages.append(msg)
        except _BufDecodeError as e:
            # Truncated value inside a TLV/message body: surface as an
            # LDP decode error so callers' status mapping applies.
            raise DecodeError("ReadOutOfBounds") from e
        return cls(lsr_id, lspace_id, messages, version)


def _decode_message(r: Reader, multicast: bool | None) -> Message | None:
    mtype_raw = r.u16()
    mlen = r.u16()
    if mlen < 4 or mlen - 4 > r.remaining() - 4:
        raise DecodeError("InvalidMessageLength", mlen)
    msg_id = r.u32()
    body = r.sub(mlen - 4)
    mtype = mtype_raw & MSG_TYPE_MASK
    try:
        mt = MsgType(mtype)
    except ValueError as e:
        if mtype_raw & MSG_UNKNOWN_FLAG:
            # U bit set: silently skip the unknown message
            # (message.rs:363 returns None).
            return None
        raise DecodeError("UnknownMessage", mtype) from e

    decoder = {
        MsgType.HELLO: _decode_hello,
        MsgType.INITIALIZATION: _decode_init,
        MsgType.KEEPALIVE: lambda b, i, m: KeepaliveMsg(msg_id=i),
        MsgType.ADDRESS: _decode_address,
        MsgType.ADDRESS_WITHDRAW: _decode_address,
        MsgType.NOTIFICATION: _decode_notification,
        MsgType.CAPABILITY: _decode_capability,
    }
    if mt in _LABEL_TYPES:
        return _decode_label(body, msg_id, mt)
    return decoder[mt](body, msg_id, mt if mt != MsgType.HELLO else multicast)


def _tlvs(r: Reader):
    while r.remaining() >= TLV_HDR_SIZE:
        ttype_raw = r.u16()
        tlen = r.u16()
        if tlen > r.remaining():
            raise DecodeError("InvalidTlvLength", tlen)
        body = r.sub(tlen)
        yield ttype_raw, tlen, body


def _unknown_tlv(ttype_raw: int) -> None:
    if not (ttype_raw & TLV_UNKNOWN_FLAG):
        raise DecodeError("UnknownTlv", ttype_raw & TLV_TYPE_MASK)


def _decode_hello(r: Reader, msg_id: int, multicast) -> HelloMsg:
    msg = HelloMsg(msg_id=msg_id)
    seen_params = False
    for ttype_raw, tlen, body in _tlvs(r):
        ttype = ttype_raw & TLV_TYPE_MASK
        if ttype == TlvType.COMMON_HELLO_PARAMS:
            if tlen != 4:
                raise DecodeError("InvalidTlvLength", tlen)
            msg.holdtime = body.u16()
            msg.flags = body.u16() & 0xE000
            seen_params = True
            # Link/targeted vs transport cross-checks (hello.rs:266-280).
            if multicast is True and msg.flags & HELLO_TARGETED:
                raise DecodeError("McastTHello")
            if multicast is False and not (msg.flags & HELLO_TARGETED):
                raise DecodeError("UcastLHello")
        elif ttype == TlvType.IPV4_TRANS_ADDR:
            if tlen != 4:
                raise DecodeError("InvalidTlvLength", tlen)
            msg.ipv4_addr = body.ipv4()
        elif ttype == TlvType.IPV6_TRANS_ADDR:
            if tlen != 16:
                raise DecodeError("InvalidTlvLength", tlen)
            msg.ipv6_addr = body.ipv6()
        elif ttype == TlvType.CONFIG_SEQNO:
            if tlen != 4:
                raise DecodeError("InvalidTlvLength", tlen)
            msg.cfg_seqno = body.u32()
        elif ttype == TlvType.DUAL_STACK:
            msg.dual_stack = body.u16() >> 12
        else:
            _unknown_tlv(ttype_raw)
    if not seen_params:
        raise DecodeError(
            "MissingMsgParams", TlvType.COMMON_HELLO_PARAMS
        )
    return msg


def _decode_init(r: Reader, msg_id: int, _mt) -> InitMsg:
    msg = InitMsg(msg_id=msg_id)
    seen_params = False
    for ttype_raw, tlen, body in _tlvs(r):
        ttype = ttype_raw & TLV_TYPE_MASK
        if ttype == TlvType.COMMON_SESS_PARAMS:
            if tlen != 14:
                raise DecodeError("InvalidTlvLength", tlen)
            version = body.u16()
            if version != LDP_VERSION:
                raise DecodeError("InvalidVersion", version)
            msg.keepalive_time = body.u16()
            if msg.keepalive_time == 0:
                raise DecodeError("BadKeepaliveTime", 0)
            msg.flags = body.u8()
            msg.pvlim = body.u8()
            msg.max_pdu_len = body.u16()
            msg.lsr_id = body.ipv4()
            msg.lspace_id = body.u16()
            seen_params = True
        elif ttype == TlvType.CAP_DYNAMIC:
            msg.cap_dynamic = True
        elif ttype == TlvType.CAP_TWCARD_FEC:
            msg.cap_twcard_fec = bool(body.u8() & TLV_CAP_S_BIT)
        elif ttype == TlvType.CAP_UNREC_NOTIF:
            msg.cap_unrec_notif = bool(body.u8() & TLV_CAP_S_BIT)
        else:
            _unknown_tlv(ttype_raw)
    if not seen_params:
        raise DecodeError(
            "MissingMsgParams", TlvType.COMMON_SESS_PARAMS
        )
    return msg


def _decode_address(r: Reader, msg_id: int, mt: MsgType) -> AddressMsg:
    msg = AddressMsg(
        msg_id=msg_id, withdraw=(mt == MsgType.ADDRESS_WITHDRAW)
    )
    seen = False
    for ttype_raw, tlen, body in _tlvs(r):
        ttype = ttype_raw & TLV_TYPE_MASK
        if ttype == TlvType.ADDR_LIST:
            af = body.u16()
            if af == AF_IPV4:
                while body.remaining() >= 4:
                    msg.addr_list.append(body.ipv4())
            elif af == AF_IPV6:
                while body.remaining() >= 16:
                    msg.addr_list.append(body.ipv6())
            else:
                raise DecodeError("UnsupportedAf", af)
            seen = True
        else:
            _unknown_tlv(ttype_raw)
    if not seen:
        raise DecodeError("MissingMsgParams", TlvType.ADDR_LIST)
    return msg


def _decode_label(r: Reader, msg_id: int, mt: MsgType) -> LabelMsg:
    msg = LabelMsg(msg_id=msg_id, msg_type=mt)
    seen_fec = False
    for ttype_raw, tlen, body in _tlvs(r):
        ttype = ttype_raw & TLV_TYPE_MASK
        if ttype == TlvType.FEC:
            msg.fec = _decode_fec_elems(body)
            seen_fec = True
        elif ttype == TlvType.GENERIC_LABEL:
            if tlen != 4:
                raise DecodeError("InvalidTlvLength", tlen)
            msg.label = body.u32() & 0xFFFFF
        elif ttype == TlvType.LABEL_REQUEST_ID:
            if tlen != 4:
                raise DecodeError("InvalidTlvLength", tlen)
            msg.request_id = body.u32()
        else:
            _unknown_tlv(ttype_raw)
    if not seen_fec:
        raise DecodeError("MissingMsgParams", TlvType.FEC)
    if mt == MsgType.LABEL_MAPPING and msg.label is None:
        raise DecodeError("MissingMsgParams", TlvType.GENERIC_LABEL)
    return msg


def _decode_notification(r: Reader, msg_id: int, _mt) -> NotifMsg:
    msg = NotifMsg(msg_id=msg_id)
    seen = False
    for ttype_raw, tlen, body in _tlvs(r):
        ttype = ttype_raw & TLV_TYPE_MASK
        if ttype == TlvType.STATUS:
            if tlen != 10:
                raise DecodeError("InvalidTlvLength", tlen)
            msg.status_code = body.u32()
            msg.status_msg_id = body.u32()
            msg.status_msg_type = body.u16()
            seen = True
        elif ttype == TlvType.EXT_STATUS:
            msg.ext_status = body.u32()
        elif ttype == TlvType.FEC:
            msg.fec = _decode_fec_elems(body)
        elif ttype in (
            TlvType.RETURNED_PDU,
            TlvType.RETURNED_MSG,
            TlvType.RETURNED_TLVS,
        ):
            pass  # opaque returned data: accepted, not retained
        else:
            _unknown_tlv(ttype_raw)
    if not seen:
        raise DecodeError("MissingMsgParams", TlvType.STATUS)
    return msg


def _decode_capability(r: Reader, msg_id: int, _mt) -> CapabilityMsg:
    msg = CapabilityMsg(msg_id=msg_id)
    for ttype_raw, tlen, body in _tlvs(r):
        ttype = ttype_raw & TLV_TYPE_MASK
        if ttype == TlvType.CAP_TWCARD_FEC:
            msg.twcard_fec = bool(body.u8() & TLV_CAP_S_BIT)
        elif ttype == TlvType.CAP_UNREC_NOTIF:
            msg.unrec_notif = bool(body.u8() & TLV_CAP_S_BIT)
        else:
            _unknown_tlv(ttype_raw)
    return msg

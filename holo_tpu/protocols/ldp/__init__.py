"""LDP (RFC 5036): label distribution for MPLS.

Reference: holo-ldp (SURVEY.md §2.3) — UDP hello discovery, TCP session
with init/keepalive, downstream-unsolicited label distribution with
liberal retention, FEC table driven by RIB routes.

Package layout:
- :mod:`.packet` — full RFC 5036 wire codec (all messages/TLVs, status
  codes, decode-error -> status mapping);
- :mod:`.engine` — the reference-grade protocol core (session FSM,
  LMp/LRq/LWd/LRl label procedures, targeted discovery, YANG state),
  verified against all 70 recorded holo-ldp conformance cases + both
  topology snapshots (tools/stepwise_ldp.py);
- this module — the daemon-facing transport slice (fabric/netns
  hellos + sessions, LIB feed to the RIB manager).  Its
  :class:`LdpMsg` is a convenience view over one single-message PDU;
  all wire encoding/decoding goes through :mod:`.packet` (one codec
  for the protocol).  New protocol behavior belongs in :mod:`.engine`.

Transport on the fabric: hellos are multicast frames, session messages
unicast frames (the daemon binds real UDP 646 + TCP 646).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from ipaddress import IPv4Address, IPv4Network

from holo_tpu.protocols.ldp import packet as wire
from holo_tpu.utils.bytesbuf import DecodeError
from holo_tpu.utils.mpls import IMPLICIT_NULL, LabelManager
from holo_tpu.utils.netio import NetIo, NetRxPacket
from holo_tpu.utils.runtime import Actor


class _McastAll(str):
    is_multicast = True


ALL_ROUTERS_LDP = _McastAll("224.0.0.2:646")


class LdpMsgType(enum.IntEnum):
    HELLO = 0x0100
    INIT = 0x0200
    KEEPALIVE = 0x0201
    LABEL_MAPPING = 0x0400
    LABEL_WITHDRAW = 0x0402
    LABEL_RELEASE = 0x0403


@dataclass
class LdpMsg:
    type: LdpMsgType
    lsr_id: IPv4Address
    # message payload fields (superset; relevant per type):
    hold_time: int = 15
    keepalive_time: int = 30
    fec: IPv4Network | None = None
    label: int | None = None

    def encode(self) -> bytes:
        """One single-message PDU through the :mod:`.packet` codec."""
        msg: wire.Message
        if self.type == LdpMsgType.HELLO:
            msg = wire.HelloMsg(holdtime=self.hold_time)
        elif self.type == LdpMsgType.INIT:
            msg = wire.InitMsg(
                keepalive_time=self.keepalive_time, lsr_id=self.lsr_id
            )
        elif self.type == LdpMsgType.KEEPALIVE:
            msg = wire.KeepaliveMsg()
        else:
            label = self.label
            if label is None and self.type != LdpMsgType.LABEL_RELEASE:
                label = 0  # mapping/withdraw always carry a label TLV
            msg = wire.LabelMsg(
                msg_type=wire.MsgType(int(self.type)),
                fec=[wire.FecPrefix(self.fec)],
                label=label,
            )
        return wire.Pdu(self.lsr_id, messages=[msg]).encode()

    @classmethod
    def decode(cls, data: bytes) -> "LdpMsg":
        """First message of a PDU, folded back into the flat view."""
        try:
            pdu = wire.Pdu.decode(data)
        except wire.DecodeError as e:
            raise DecodeError(f"LDP: {e}") from e
        if not pdu.messages:
            raise DecodeError("LDP: empty PDU")
        msg = pdu.messages[0]
        try:
            mtype = LdpMsgType(int(msg.msg_type))
        except ValueError as e:
            raise DecodeError("unknown LDP message") from e
        out = cls(mtype, pdu.lsr_id)
        if isinstance(msg, wire.HelloMsg):
            out.hold_time = msg.holdtime
        elif isinstance(msg, wire.InitMsg):
            out.keepalive_time = msg.keepalive_time
        elif isinstance(msg, wire.LabelMsg):
            for elem in msg.fec:
                if isinstance(elem, wire.FecPrefix) and elem.prefix.version == 4:
                    out.fec = elem.prefix
                    break
            out.label = msg.label
        return out


class NbrState(enum.Enum):
    DISCOVERED = "discovered"
    INIT_SENT = "init-sent"
    OPERATIONAL = "operational"


@dataclass
class LdpNeighbor:
    lsr_id: IPv4Address
    addr: IPv4Address
    ifname: str
    state: NbrState = NbrState.DISCOVERED
    hold_time: int = 15
    # label bindings learned from this peer: fec -> label
    bindings: dict[IPv4Network, int] = field(default_factory=dict)


@dataclass
class HelloTimerMsg:
    pass


@dataclass
class NbrTimeoutMsg:
    lsr_id: IPv4Address


class LdpInstance(Actor):
    """One LDP LSR: discovery + sessions + DU label distribution."""

    name = "ldp"

    def __init__(
        self,
        name: str,
        lsr_id: IPv4Address,
        netio: NetIo,
        label_manager: LabelManager | None = None,
        lib_cb=None,
        notif_cb=None,
        control_mode: str = "independent",
    ):
        assert control_mode in ("independent", "ordered")
        self.name = name
        self.lsr_id = lsr_id
        self.netio = netio
        self.labels = label_manager or LabelManager()
        self.lib_cb = lib_cb  # callable(lib) on label-table change
        self.notif_cb = notif_cb  # YANG notifications (mpls-ldp events)
        # RFC 5036 §2.6: independent control advertises local bindings
        # immediately; ordered control (§2.6.1) only once the FEC's next
        # hop has advertised its own mapping (or we are the egress).
        self.control_mode = control_mode
        self.interfaces: dict[str, IPv4Address] = {}  # ifname -> our addr
        self.neighbors: dict[IPv4Address, LdpNeighbor] = {}
        # Our FECs: prefix -> (local label, is_egress)
        self.fec_table: dict[IPv4Network, tuple[int, bool]] = {}
        # Ordered mode: FEC -> next-hop LSR id (fed from the RIB) and the
        # set of FECs currently advertised upstream.
        self.nexthop_lsr: dict[IPv4Network, IPv4Address] = {}
        self.advertised: set[IPv4Network] = set()

    def attach(self, loop_):
        super().attach(loop_)
        self._hello_timer = self.loop.timer(self.name, HelloTimerMsg)
        self._hello_timer.start(0.1)

    def add_interface(self, ifname: str, addr: IPv4Address) -> None:
        self.interfaces[ifname] = addr

    def remove_interface(self, ifname: str, fec: IPv4Network | None = None) -> None:
        """Stop discovery on an interface; drop its connected FEC (and
        any neighbors discovered over it)."""
        if self.interfaces.pop(ifname, None) is None:
            return
        if fec is not None:
            self.remove_fec(fec)
        for lsr_id, nbr in list(self.neighbors.items()):
            if nbr.ifname == ifname:
                del self.neighbors[lsr_id]
        self._lib_changed()

    def add_fec(self, prefix: IPv4Network, egress: bool) -> int:
        """Create a local binding (egress FECs bind implicit-null)."""
        if prefix in self.fec_table:
            return self.fec_table[prefix][0]
        label = IMPLICIT_NULL if egress else self.labels.allocate()
        self.fec_table[prefix] = (label, egress)
        if self._may_advertise(prefix):
            self.advertised.add(prefix)
            for nbr in self.neighbors.values():
                if nbr.state == NbrState.OPERATIONAL:
                    self._send_mapping(nbr, prefix, label)
        self._lib_changed()
        return label

    def set_nexthops(self, nexthop_lsr: dict) -> None:
        """Ordered mode: the RIB feeds each FEC's downstream LSR id so
        eligibility (§2.6.1: egress, or mapping received from the next
        hop) can be evaluated."""
        self.nexthop_lsr = dict(nexthop_lsr)
        self._reeval_ordered()

    def _may_advertise(self, prefix: IPv4Network) -> bool:
        if self.control_mode == "independent":
            return True
        label, egress = self.fec_table[prefix]
        if egress:
            return True
        nh = self.nexthop_lsr.get(prefix)
        if nh is None:
            return False
        nbr = self.neighbors.get(nh)
        return nbr is not None and prefix in nbr.bindings

    def _reeval_ordered(self) -> None:
        """Advertise newly-eligible FECs upstream; withdraw ones whose
        downstream mapping disappeared (ordered-control propagation)."""
        if self.control_mode != "ordered":
            return
        ops = [
            n for n in self.neighbors.values()
            if n.state == NbrState.OPERATIONAL
        ]
        changed = False
        for prefix in self.fec_table:
            eligible = self._may_advertise(prefix)
            if eligible and prefix not in self.advertised:
                self.advertised.add(prefix)
                for nbr in ops:
                    self._send_mapping(nbr, prefix, self.fec_table[prefix][0])
                changed = True
            elif not eligible and prefix in self.advertised:
                self.advertised.discard(prefix)
                for nbr in ops:
                    self._send(
                        nbr.ifname, nbr.addr,
                        LdpMsg(LdpMsgType.LABEL_WITHDRAW, self.lsr_id,
                               fec=prefix, label=self.fec_table[prefix][0]),
                    )
                changed = True
        if changed:
            self._lib_changed()

    def remove_fec(self, prefix: IPv4Network) -> None:
        entry = self.fec_table.pop(prefix, None)
        if entry is None:
            return
        label, egress = entry
        was_advertised = prefix in self.advertised
        self.advertised.discard(prefix)
        if not egress:
            self.labels.release(label)
        if not was_advertised and self.control_mode == "ordered":
            self._lib_changed()
            return  # never advertised: nothing to withdraw upstream
        for nbr in self.neighbors.values():
            if nbr.state == NbrState.OPERATIONAL:
                self._send(
                    nbr.ifname,
                    nbr.addr,
                    LdpMsg(LdpMsgType.LABEL_WITHDRAW, self.lsr_id,
                           fec=prefix, label=label),
                )
        self._lib_changed()

    # -- actor

    def handle(self, msg):
        if isinstance(msg, NetRxPacket):
            self._rx(msg)
        elif isinstance(msg, HelloTimerMsg):
            for ifname, addr in self.interfaces.items():
                hello = LdpMsg(LdpMsgType.HELLO, self.lsr_id, hold_time=15)
                self.netio.send(ifname, addr, ALL_ROUTERS_LDP, hello.encode())
            self._hello_timer.start(5.0)
        elif isinstance(msg, NbrTimeoutMsg):
            nbr = self.neighbors.pop(msg.lsr_id, None)
            if nbr is not None:
                self._notify("mpls-ldp-hello-adjacency-event", {
                    "event-type": "down",
                    "interface": nbr.ifname,
                    "adjacent-address": str(nbr.addr),
                })
                if nbr.state == NbrState.OPERATIONAL:
                    self._notify("mpls-ldp-peer-event", {
                        "event-type": "down",
                        "peer": {"lsr-id": str(nbr.lsr_id)},
                    })
                self._reeval_ordered()  # lost downstream: withdraw
                self._lib_changed()

    def _notify(self, kind: str, data: dict) -> None:
        """Reference holo-ldp northbound/notification.rs: peer and
        hello-adjacency lifecycle events under ietf-mpls-ldp."""
        if self.notif_cb is not None:
            self.notif_cb({f"ietf-mpls-ldp:{kind}": data})

    def _rx(self, msg: NetRxPacket) -> None:
        try:
            pdu = LdpMsg.decode(msg.data)
        except DecodeError:
            return
        if pdu.lsr_id == self.lsr_id:
            return
        if pdu.type == LdpMsgType.HELLO:
            self._rx_hello(msg, pdu)
            return
        nbr = self.neighbors.get(pdu.lsr_id)
        if nbr is None:
            return
        if pdu.type == LdpMsgType.INIT:
            if nbr.state == NbrState.DISCOVERED:
                self._send_init(nbr)
            self._send(nbr.ifname, nbr.addr,
                       LdpMsg(LdpMsgType.KEEPALIVE, self.lsr_id))
        elif pdu.type == LdpMsgType.KEEPALIVE:
            if nbr.state != NbrState.OPERATIONAL:
                nbr.state = NbrState.OPERATIONAL
                self._notify("mpls-ldp-peer-event", {
                    "event-type": "up",
                    "peer": {"lsr-id": str(nbr.lsr_id)},
                })
                # Advertise eligible local bindings (DU; ordered mode
                # holds back FECs still waiting on their next hop).
                for prefix, (label, _e) in self.fec_table.items():
                    if self._may_advertise(prefix):
                        self.advertised.add(prefix)
                        self._send_mapping(nbr, prefix, label)
            self._touch(nbr)
        elif pdu.type == LdpMsgType.LABEL_MAPPING and pdu.fec is not None:
            nbr.bindings[pdu.fec] = pdu.label
            self._reeval_ordered()  # downstream arrived: maybe advertise
            self._lib_changed()
        elif pdu.type == LdpMsgType.LABEL_WITHDRAW and pdu.fec is not None:
            nbr.bindings.pop(pdu.fec, None)
            self._send(nbr.ifname, nbr.addr,
                       LdpMsg(LdpMsgType.LABEL_RELEASE, self.lsr_id,
                              fec=pdu.fec, label=pdu.label))
            self._reeval_ordered()  # downstream gone: withdraw upstream
            self._lib_changed()

    def _rx_hello(self, msg: NetRxPacket, pdu: LdpMsg) -> None:
        nbr = self.neighbors.get(pdu.lsr_id)
        if nbr is None:
            nbr = LdpNeighbor(pdu.lsr_id, msg.src, msg.ifname,
                              hold_time=pdu.hold_time)
            self.neighbors[pdu.lsr_id] = nbr
            self._notify("mpls-ldp-hello-adjacency-event", {
                "event-type": "up",
                "interface": msg.ifname,
                "adjacent-address": str(msg.src),
            })
            # Active side: higher LSR id initiates the session (RFC 5036
            # §2.5.2 transport connection roles).
            if int(self.lsr_id) > int(pdu.lsr_id):
                self._send_init(nbr)
        self._touch(nbr)

    def _touch(self, nbr: LdpNeighbor) -> None:
        t = getattr(nbr, "_timeout", None)
        if t is None:
            t = self.loop.timer(
                self.name, lambda l=nbr.lsr_id: NbrTimeoutMsg(l)
            )
            nbr._timeout = t
        t.start(nbr.hold_time * 3)

    def _send(self, ifname: str, dst, pdu: LdpMsg) -> None:
        self.netio.send(ifname, self.interfaces.get(ifname), dst, pdu.encode())

    def _send_init(self, nbr: LdpNeighbor) -> None:
        nbr.state = NbrState.INIT_SENT
        self._send(nbr.ifname, nbr.addr,
                   LdpMsg(LdpMsgType.INIT, self.lsr_id))

    def _send_mapping(self, nbr: LdpNeighbor, prefix: IPv4Network, label: int) -> None:
        self._send(nbr.ifname, nbr.addr,
                   LdpMsg(LdpMsgType.LABEL_MAPPING, self.lsr_id,
                          fec=prefix, label=label))

    # -- LIB (label information base) view

    def lib(self) -> dict:
        """fec -> {local, remote: {lsr_id: label}} — the MPLS LIB the
        routing provider merges with RIB next hops to build LFIB entries
        (reference rib.rs:152-212)."""
        out = {}
        for prefix, (label, egress) in self.fec_table.items():
            out[prefix] = {
                "local": label,
                "egress": egress,
                "remote": {
                    str(n.lsr_id): n.bindings[prefix]
                    for n in self.neighbors.values()
                    if prefix in n.bindings
                },
            }
        return out

    def _lib_changed(self) -> None:
        if self.lib_cb is not None:
            self.lib_cb(self.lib())

"""Reference-grade LDP protocol engine (RFC 5036 + RFC 5561/5918/5919).

Event-driven core mirroring holo-ldp's semantics exactly — the reference's
recorded conformance corpus (70 step cases + 2 topologies) replays through
this engine via tools/stepwise_ldp.py.  Structure maps 1:1:

- discovery/adjacencies + targeted neighbors  (holo-ldp/src/discovery.rs)
- session FSM NonExistent/Initialized/OpenRec/OpenSent/Operational
  (holo-ldp/src/neighbor.rs:137-318)
- label distribution procedures LMp/LRq/LWd/LRl/SL with liberal retention
  and independent control  (holo-ldp/src/events.rs:479-1268)
- FECs fed by RIB redistribution; label install/uninstall to the FIB
  (holo-ldp/src/ibus/{rx,tx}.rs)
- YANG operational state + notifications
  (holo-ldp/src/northbound/{state,notification}.rs)

Transport is injected: `send_cb(nbr_id, msg, flush)` for session messages
(the reference's NbrTxPdu plane), `ibus_cb(kind, payload)` for southbound
label routes, `notif_cb(name, data)` for YANG notifications.  Timer state
is tracked but never self-fires — timeouts arrive as events (`adj_timeout`,
`nbr_ka_timeout`, `nbr_backoff_timeout`), exactly like the reference's
testing mode where timer tasks are no-ops.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from ipaddress import IPv4Address, IPv4Network, IPv6Network, ip_network

from holo_tpu.protocols.ldp.packet import (
    AddressMsg,
    CapabilityMsg,
    DecodeError,
    FecPrefix,
    FecWildcard,
    HelloMsg,
    InitMsg,
    KeepaliveMsg,
    LabelMsg,
    Message,
    MsgType,
    NotifMsg,
    Pdu,
    StatusCode,
    status_is_fatal,
    AF_IPV4,
    AF_IPV6,
    HELLO_GTSM,
    HELLO_REQ_TARGETED,
    HELLO_TARGETED,
    INIT_ADV_DISCIPLINE,
    INFINITE_HOLDTIME,
    PDU_DFLT_MAX_LEN,
)

from holo_tpu.utils.mpls import IMPLICIT_NULL


def _is_reserved(label: int) -> bool:
    return label < 16


class BumpLabelAllocator:
    """holo-utils/src/mpls.rs:186-201 — monotonic dynamic allocator
    starting at 16; release is a no-op (labels are never reused)."""

    def __init__(self) -> None:
        self.next_dynamic = 15

    def label_request(self) -> int:
        self.next_dynamic += 1
        return self.next_dynamic

    def label_release(self, label: int) -> None:
        pass


# ===== configuration (northbound/configuration.rs:55-101,565-640) =====


@dataclass
class TargetedNbrCfg:
    enabled: bool = True  # YANG default "true" (ietf-mpls-ldp target list)
    hello_holdtime: int = 45
    hello_interval: int = 10


@dataclass
class InterfaceCfg:
    hello_holdtime: int = 15
    hello_interval: int = 5
    ipv4_enabled: bool | None = None  # None = no ipv4 container


@dataclass
class InstanceCfg:
    router_id: IPv4Address | None = None
    session_ka_holdtime: int = 180
    session_ka_interval: int = 60
    password: str | None = None
    interface_hello_holdtime: int = 15
    interface_hello_interval: int = 5
    targeted_hello_holdtime: int = 45
    targeted_hello_interval: int = 10
    targeted_hello_accept: bool = False
    ipv4_enabled: bool | None = None  # None = no ipv4 container
    neighbor_passwords: dict = field(default_factory=dict)


# ===== runtime objects =====


@dataclass
class AdjSource:
    ifname: str | None  # None for targeted adjacencies
    addr: IPv4Address

    def key(self):
        return (self.ifname, self.addr)


@dataclass
class Adjacency:
    id: int
    source: AdjSource
    local_addr: IPv4Address
    trans_addr: IPv4Address
    lsr_id: IPv4Address
    holdtime_adjacent: int
    holdtime_negotiated: int
    hello_rcvd: int = 1
    hello_dropped: int = 0
    timeout_active: bool = False


FSM_NON_EXISTENT = "non-existent"
FSM_INITIALIZED = "initialized"
FSM_OPENREC = "openrec"
FSM_OPENSENT = "opensent"
FSM_OPERATIONAL = "operational"


@dataclass
class MsgCounters:
    address: int = 0
    address_withdraw: int = 0
    initialization: int = 0
    keepalive: int = 0
    label_abort_request: int = 0
    label_mapping: int = 0
    label_release: int = 0
    label_request: int = 0
    label_withdraw: int = 0
    notification: int = 0
    total: int = 0

    def update(self, msg: Message) -> None:
        self.total += 1
        mt = msg.msg_type
        attr = {
            MsgType.NOTIFICATION: "notification",
            MsgType.INITIALIZATION: "initialization",
            MsgType.KEEPALIVE: "keepalive",
            MsgType.ADDRESS: "address",
            MsgType.ADDRESS_WITHDRAW: "address_withdraw",
            MsgType.LABEL_MAPPING: "label_mapping",
            MsgType.LABEL_REQUEST: "label_request",
            MsgType.LABEL_WITHDRAW: "label_withdraw",
            MsgType.LABEL_RELEASE: "label_release",
            MsgType.LABEL_ABORT_REQ: "label_abort_request",
        }.get(mt)
        if attr:
            setattr(self, attr, getattr(self, attr) + 1)


@dataclass
class Neighbor:
    id: int
    lsr_id: IPv4Address
    trans_addr: IPv4Address
    kalive_interval: int
    state: str = FSM_NON_EXISTENT
    cfg_seqno: int = 0
    conn_info: dict | None = None  # {local_addr, local_port, remote_addr, remote_port}
    max_pdu_len: int = PDU_DFLT_MAX_LEN
    kalive_holdtime_rcvd: int | None = None
    kalive_holdtime_negotiated: int | None = None
    rcvd_label_adv_mode: str | None = None  # "downstream-unsolicited"/"downstream-on-demand"
    addr_list: set = field(default_factory=set)
    rcvd_mappings: dict = field(default_factory=dict)  # prefix -> label
    sent_mappings: dict = field(default_factory=dict)
    rcvd_requests: dict = field(default_factory=dict)  # prefix -> request msg id
    sent_requests: dict = field(default_factory=dict)
    sent_withdraws: dict = field(default_factory=dict)  # prefix -> label
    flags: set = field(default_factory=set)  # GTSM/CAP_DYNAMIC/CAP_TYPED_WCARD/CAP_UNREC_NOTIF
    msgs_rcvd: MsgCounters = field(default_factory=MsgCounters)
    msgs_sent: MsgCounters = field(default_factory=MsgCounters)
    connecting: bool = False  # active-role TCP connect in flight
    backoff_active: bool = False
    kalive_timeout_active: bool = False
    session_up: bool = False  # uptime surrogate

    def is_operational(self) -> bool:
        return self.state == FSM_OPERATIONAL

    def is_session_active_role(self, local_trans_addr: IPv4Address) -> bool:
        return int(local_trans_addr) > int(self.trans_addr)

    def close_session(self) -> None:
        """neighbor.rs:508-523."""
        self.conn_info = None
        self.kalive_holdtime_rcvd = None
        self.kalive_holdtime_negotiated = None
        self.rcvd_label_adv_mode = None
        self.addr_list.clear()
        self.rcvd_mappings.clear()
        self.sent_mappings.clear()
        self.rcvd_requests.clear()
        self.sent_requests.clear()
        self.sent_withdraws.clear()
        self.msgs_rcvd = MsgCounters()
        self.msgs_sent = MsgCounters()
        self.connecting = False
        self.kalive_timeout_active = False
        self.session_up = False


@dataclass
class Nexthop:
    addr: IPv4Address
    ifindex: int | None
    label: int | None = None


@dataclass
class Fec:
    prefix: IPv4Network | IPv6Network
    downstream: dict = field(default_factory=dict)  # lsr_id -> label
    upstream: dict = field(default_factory=dict)
    local_label: int | None = None
    protocol: str | None = None
    nexthops: dict = field(default_factory=dict)  # addr -> Nexthop

    def is_operational(self) -> bool:
        """RFC 9070 §7: up iff ≥1 NHLFE has an outgoing label
        (fec.rs:95-103)."""
        return any(nh.label is not None for nh in self.nexthops.values())

    def is_nbr_nexthop(self, nbr: Neighbor) -> bool:
        return any(nh.addr in nbr.addr_list for nh in self.nexthops.values())


@dataclass
class TargetedNbr:
    addr: IPv4Address
    config: TargetedNbrCfg = field(default_factory=TargetedNbrCfg)
    configured: bool = False
    dynamic: bool = False
    active: bool = False  # hello interval task running

    def is_ready(self) -> bool:
        return self.dynamic or (self.configured and self.config.enabled)

    def remove_check(self) -> bool:
        return not self.dynamic and not self.configured

    def calculate_adj_holdtime(self, hello_holdtime: int) -> int:
        if hello_holdtime == 0:
            hello_holdtime = 45
        return min(self.config.hello_holdtime, hello_holdtime)


@dataclass
class Interface:
    name: str
    config: InterfaceCfg = field(default_factory=InterfaceCfg)
    operative: bool = False
    ifindex: int | None = None
    ipv4_addr_list: set = field(default_factory=set)  # of IPv4Network (interface form)
    active: bool = False

    def is_ready(self) -> bool:
        return (
            self.config.ipv4_enabled is True
            and self.operative
            and self.ifindex is not None
            and bool(self.ipv4_addr_list)
        )

    def local_ipv4_addr(self) -> IPv4Address:
        return min(self.ipv4_addr_list, key=lambda p: int(p.ip)).ip

    def contains_addr(self, addr: IPv4Address) -> bool:
        return any(addr in p.network for p in self.ipv4_addr_list)

    def calculate_adj_holdtime(self, hello_holdtime: int) -> int:
        if hello_holdtime == 0:
            hello_holdtime = 15
        return min(self.config.hello_holdtime, hello_holdtime)


def _prefix_sort_key(prefix):
    return (prefix.version, int(prefix.network_address), prefix.prefixlen)


class LdpEngine:
    """One LDP LSR: the reference Instance + InstanceState combined.

    Cites: holo-ldp/src/instance.rs:38-263 (lifecycle), events.rs (all
    event handlers), neighbor.rs (FSM + senders).
    """

    def __init__(
        self,
        name: str,
        send_cb=None,
        ibus_cb=None,
        notif_cb=None,
        label_allocator: BumpLabelAllocator | None = None,
    ):
        self.name = name
        self.send_cb = send_cb or (lambda nbr_id, msg, flush: None)
        self.ibus_cb = ibus_cb or (lambda kind, payload: None)
        self.notif_cb = notif_cb or (lambda name, data: None)
        self.labels = label_allocator or BumpLabelAllocator()

        self.config = InstanceCfg()
        # system data (instance.rs:58-63)
        self.sys_router_id: IPv4Address | None = None
        self.ipv4_addr_list: set = set()  # of IPv4Network interface-form prefixes
        self.interfaces: dict[str, Interface] = {}
        self.tneighbors: dict[IPv4Address, TargetedNbr] = {}

        # state (None when inactive; instance.rs:65-100)
        self.active = False
        self.msg_id = 0
        self.cfg_seqno = 0
        self.router_id: IPv4Address | None = None
        self.trans_addr: IPv4Address | None = None
        self.neighbors: dict[int, Neighbor] = {}  # id -> Neighbor
        self.fecs: dict = {}  # prefix -> Fec
        self.adjacencies: dict[int, Adjacency] = {}  # id -> Adjacency
        self._next_nbr_id = 0
        self._next_adj_id = 0

    # ---- id & msg-id helpers (collections.rs next_id; instance.rs:427-429)

    def next_msg_id(self) -> int:
        v = self.msg_id
        self.msg_id += 1
        return v

    def _next_neighbor_id(self) -> int:
        self._next_nbr_id += 1
        return self._next_nbr_id

    def _next_adjacency_id(self) -> int:
        self._next_adj_id += 1
        return self._next_adj_id

    # ---- lookups

    def nbr_by_lsr_id(self, lsr_id) -> Neighbor | None:
        for nbr in self.neighbors.values():
            if nbr.lsr_id == lsr_id:
                return nbr
        return None

    def nbr_by_trans_addr(self, addr) -> Neighbor | None:
        for nbr in self.neighbors.values():
            if nbr.trans_addr == addr:
                return nbr
        return None

    def nbr_by_adv_addr(self, addr) -> Neighbor | None:
        for nbr in self.neighbors.values():
            if addr in nbr.addr_list:
                return nbr
        return None

    def adj_by_source(self, source: AdjSource) -> Adjacency | None:
        for adj in self.adjacencies.values():
            if adj.source.key() == source.key():
                return adj
        return None

    def _nbrs_sorted(self):
        return sorted(self.neighbors.values(), key=lambda n: int(n.lsr_id))

    def _fecs_sorted(self):
        return [
            self.fecs[p] for p in sorted(self.fecs, key=_prefix_sort_key)
        ]

    # ---- instance lifecycle (instance.rs:149-262)

    def get_router_id(self) -> IPv4Address | None:
        return self.config.router_id or self.sys_router_id

    def update(self) -> None:
        router_id = self.get_router_id()
        ready = self.config.ipv4_enabled is True and router_id is not None
        if ready and not self.active:
            self._start(router_id)
        elif not ready and self.active:
            self._stop()

    def _start(self, router_id: IPv4Address) -> None:
        self.active = True
        self.msg_id = 0
        self.cfg_seqno = 0
        self.router_id = router_id
        self.trans_addr = router_id
        self.neighbors = {}
        self.fecs = {}
        self.adjacencies = {}
        self._next_nbr_id = 0
        self._next_adj_id = 0
        for iface in self.interfaces.values():
            self.iface_check(iface)
        for tnbr in list(self.tneighbors.values()):
            self.tnbr_update(tnbr)

    def _stop(self) -> None:
        for iface in self.interfaces.values():
            if iface.active:
                self.iface_stop(iface)
        for tnbr in list(self.tneighbors.values()):
            if tnbr.active:
                self.tnbr_stop(tnbr, delete_adjacency=True)
        self.active = False
        self.neighbors = {}
        self.fecs = {}
        self.adjacencies = {}

    # ---- interface lifecycle (interface.rs:120-177)

    def iface_check(self, iface: Interface) -> None:
        if iface.is_ready() and not iface.active and self.active:
            iface.active = True
        elif iface.active and (not iface.is_ready() or not self.active):
            self.iface_stop(iface)

    def iface_stop(self, iface: Interface) -> None:
        iface.active = False
        for adj in [
            a
            for a in self.adjacencies.values()
            if a.source.ifname == iface.name
        ]:
            self.adjacency_delete(adj, StatusCode.SHUTDOWN)

    # ---- targeted neighbor lifecycle (discovery.rs:196-246)

    def tnbr_update(self, tnbr: TargetedNbr) -> None:
        is_ready = tnbr.is_ready() and self.active
        remove = tnbr.remove_check()
        if not tnbr.active and is_ready:
            tnbr.active = True
        elif tnbr.active and not is_ready:
            self.tnbr_stop(tnbr, delete_adjacency=True)
        if remove:
            self.tneighbors.pop(tnbr.addr, None)

    def tnbr_stop(self, tnbr: TargetedNbr, delete_adjacency: bool) -> None:
        tnbr.active = False
        if delete_adjacency:
            adj = self.adj_by_source(AdjSource(None, tnbr.addr))
            if adj is not None:
                self.adjacency_delete(adj, StatusCode.SHUTDOWN)

    # ---- outbound plane (neighbor.rs:540-766)

    def _send(self, nbr: Neighbor, msg: Message, flush: bool) -> None:
        nbr.msgs_sent.update(msg)
        self.send_cb(nbr.id, msg, flush)

    def send_init(self, nbr: Neighbor) -> None:
        msg = InitMsg(
            msg_id=self.next_msg_id(),
            keepalive_time=self.config.session_ka_holdtime,
            lsr_id=nbr.lsr_id,
            lspace_id=0,
            cap_dynamic=True,
            cap_twcard_fec=True,
            cap_unrec_notif=True,
        )
        self._send(nbr, msg, True)

    def send_keepalive(self, nbr: Neighbor) -> None:
        self._send(nbr, KeepaliveMsg(msg_id=self.next_msg_id()), True)

    def send_notification(
        self,
        nbr: Neighbor,
        status: StatusCode,
        peer_msg: Message | None = None,
        wcard_af: int | None = None,
    ) -> None:
        peer_msg_id = peer_msg.msg_id if peer_msg is not None else 0
        peer_msg_type = (
            int(peer_msg.msg_type) if peer_msg is not None else 0
        )
        msg = NotifMsg(
            msg_id=self.next_msg_id(),
            status_code=status.encode_status(),
            status_msg_id=peer_msg_id,
            status_msg_type=peer_msg_type,
            fec=(
                [FecWildcard(typed_af=wcard_af)]
                if wcard_af is not None
                else None
            ),
        )
        self._send(nbr, msg, True)

    def send_shutdown(self, nbr: Neighbor, peer_msg=None) -> None:
        self.send_notification(nbr, StatusCode.SHUTDOWN, peer_msg)

    def send_end_of_lib(self, nbr: Neighbor, wcard_af: int) -> None:
        self.send_notification(
            nbr, StatusCode.END_OF_LIB, None, wcard_af
        )

    def send_address(
        self, nbr: Neighbor, withdraw: bool, addrs
    ) -> None:
        msg = AddressMsg(
            msg_id=self.next_msg_id(),
            withdraw=withdraw,
            addr_list=sorted(addrs, key=int),
        )
        self._send(nbr, msg, False)

    def send_label_mapping(self, nbr: Neighbor, fec: Fec) -> None:
        """SL.4-7 (neighbor.rs:688-727)."""
        if fec.local_label is None:
            return
        prefix = fec.prefix
        request_id = nbr.rcvd_requests.pop(prefix, None)
        msg = LabelMsg(
            msg_id=self.next_msg_id(),
            msg_type=MsgType.LABEL_MAPPING,
            fec=[FecPrefix(prefix)],
            label=fec.local_label,
            request_id=request_id,
        )
        self._send(nbr, msg, False)
        fec.upstream[nbr.lsr_id] = fec.local_label
        nbr.sent_mappings[prefix] = fec.local_label

    def send_label_withdraw(self, nbr: Neighbor, fec: Fec) -> None:
        """SWd.1-2 (neighbor.rs:729-751)."""
        if fec.local_label is None:
            return
        msg = LabelMsg(
            msg_id=self.next_msg_id(),
            msg_type=MsgType.LABEL_WITHDRAW,
            fec=[FecPrefix(fec.prefix)],
            label=fec.local_label,
        )
        self._send(nbr, msg, False)
        nbr.sent_withdraws[fec.prefix] = fec.local_label

    def send_label_release(
        self, nbr: Neighbor, fec_elem, label: int | None
    ) -> None:
        msg = LabelMsg(
            msg_id=self.next_msg_id(),
            msg_type=MsgType.LABEL_RELEASE,
            fec=[fec_elem],
            label=label,
        )
        self._send(nbr, msg, False)

    # ---- label install/uninstall to the FIB (ibus/tx.rs:28-95)

    def _label_install(self, fec: Fec, nh: Nexthop) -> None:
        if fec.local_label is None or _is_reserved(fec.local_label):
            return
        if nh.label is None:
            return
        self.ibus_cb(
            "RouteMplsAdd",
            {
                "protocol": "ldp",
                "label": fec.local_label,
                "nexthops": [
                    {
                        "Address": {
                            "ifindex": nh.ifindex or 0,
                            "addr": str(nh.addr),
                            "labels": [nh.label],
                        }
                    }
                ],
                "route": [fec.protocol, str(fec.prefix)],
                "replace": False,
            },
        )

    def _label_uninstall(self, fec: Fec, nh: Nexthop) -> None:
        if fec.local_label is None or _is_reserved(fec.local_label):
            return
        if nh.label is None:
            return
        self.ibus_cb(
            "RouteMplsDel",
            {
                "protocol": "ldp",
                "label": fec.local_label,
                "nexthops": [
                    {
                        "Address": {
                            "ifindex": nh.ifindex or 0,
                            "addr": str(nh.addr),
                            "labels": [nh.label],
                        }
                    }
                ],
                "route": [fec.protocol, str(fec.prefix)],
            },
        )

    # ---- notifications (northbound/notification.rs)

    def _notif_peer_event(self, nbr: Neighbor) -> None:
        self.notif_cb(
            "ietf-mpls-ldp:mpls-ldp-peer-event",
            {
                "event-type": "up" if nbr.is_operational() else "down",
                "peer": {
                    "protocol-name": self.name,
                    "lsr-id": str(nbr.lsr_id),
                },
            },
        )

    def _notif_adjacency_event(
        self, ifname: str | None, addr, created: bool
    ) -> None:
        data = {
            "protocol-name": self.name,
            "event-type": "up" if created else "down",
        }
        if ifname is None:
            data["targeted"] = {"target-address": str(addr)}
        else:
            data["link"] = {
                "next-hop-interface": ifname,
                "next-hop-address": str(addr),
            }
        self.notif_cb(
            "ietf-mpls-ldp:mpls-ldp-hello-adjacency-event", data
        )

    def _notif_fec_event(self, fec: Fec) -> None:
        self.notif_cb(
            "ietf-mpls-ldp:mpls-ldp-fec-event",
            {
                "event-type": "up" if fec.is_operational() else "down",
                "protocol-name": self.name,
                "fec": str(fec.prefix),
            },
        )

    # ---- FSM (neighbor.rs:219-434)

    def fsm(self, nbr: Neighbor, event: str) -> None:
        st = nbr.state
        new_state = action = None
        if st == FSM_NON_EXISTENT and event == "matched-adjacency":
            new_state = FSM_INITIALIZED
        elif st == FSM_NON_EXISTENT and event == "connection-up":
            new_state, action = FSM_INITIALIZED, "send-init"
        elif st == FSM_INITIALIZED and event == "init-rcvd":
            new_state, action = FSM_OPENREC, "send-init-and-keepalive"
        elif st == FSM_INITIALIZED and event == "init-sent":
            new_state = FSM_OPENSENT
        elif st == FSM_OPENREC and event == "keepalive-rcvd":
            new_state, action = FSM_OPERATIONAL, "start-session"
        elif st == FSM_OPENSENT and event == "init-rcvd":
            new_state, action = FSM_OPENREC, "send-keepalive"
        elif st in (
            FSM_INITIALIZED,
            FSM_OPENREC,
            FSM_OPENSENT,
            FSM_OPERATIONAL,
        ) and event in ("connection-down", "error-rcvd", "error-sent"):
            new_state, action = FSM_NON_EXISTENT, "close-session"
        else:
            return  # unexpected event: logged and ignored (fsm_event Err)

        old_state = nbr.state
        nbr.state = new_state
        if FSM_OPERATIONAL in (new_state, old_state):
            self._notif_peer_event(nbr)
        if action is not None:
            self._fsm_action(nbr, action)

    def _fsm_action(self, nbr: Neighbor, action: str) -> None:
        if action == "send-init-and-keepalive":
            self.send_init(nbr)
            self.send_keepalive(nbr)
            nbr.kalive_timeout_active = True
        elif action == "send-init":
            self.send_init(nbr)
            self.fsm(nbr, "init-sent")
        elif action == "send-keepalive":
            self.send_keepalive(nbr)
            nbr.kalive_timeout_active = True
        elif action == "start-session":
            nbr.kalive_timeout_active = True
            nbr.session_up = True
            self.send_address(
                nbr,
                False,
                [p.ip for p in self.ipv4_addr_list],
            )
            for fec in self._fecs_sorted():
                if fec.local_label is None:
                    continue
                self.send_label_mapping(nbr, fec)
            if "CAP_UNREC_NOTIF" in nbr.flags:
                self.send_end_of_lib(nbr, AF_IPV4)
        elif action == "close-session":
            for fec in self._fecs_sorted():
                old_status = fec.is_operational()
                for nh in fec.nexthops.values():
                    if nh.addr in nbr.addr_list:
                        self._label_uninstall(fec, nh)
                        nh.label = None
                if old_status != fec.is_operational():
                    self._notif_fec_event(fec)
                fec.downstream.pop(nbr.lsr_id, None)
                fec.upstream.pop(nbr.lsr_id, None)
            nbr.close_session()
            # New id so stale events can't leak into a new session
            # (neighbor.rs:428-431).
            del self.neighbors[nbr.id]
            nbr.id = self._next_neighbor_id()
            self.neighbors[nbr.id] = nbr

    # ---- UDP discovery events (events.rs:43-317)

    def udp_rx_pdu(
        self, src_addr, multicast: bool, pdu: Pdu | DecodeError
    ) -> None:
        if not self.active:
            return
        if multicast:
            self._udp_rx_multicast(src_addr, pdu)
        else:
            self._udp_rx_unicast(src_addr, pdu)

    def _iface_by_addr(self, addr) -> Interface | None:
        for iface in self.interfaces.values():
            if iface.active and iface.contains_addr(addr):
                return iface
        return None

    def _udp_rx_multicast(self, src_addr, pdu) -> None:
        iface = self._iface_by_addr(src_addr)
        if iface is None:
            return
        source = AdjSource(iface.name, src_addr)
        if isinstance(pdu, DecodeError):
            self._udp_rx_error(source)
            return
        hello = next(
            (m for m in pdu.messages if isinstance(m, HelloMsg)), None
        )
        if hello is None or hello.flags & HELLO_TARGETED:
            return
        local_addr = iface.local_ipv4_addr()
        holdtime_neg = iface.calculate_adj_holdtime(hello.holdtime)
        self._process_hello(
            local_addr, source, pdu.lsr_id, hello, hello.holdtime,
            holdtime_neg,
        )

    def _udp_rx_unicast(self, src_addr, pdu) -> None:
        source = AdjSource(None, src_addr)
        if isinstance(pdu, DecodeError):
            self._udp_rx_error(source)
            return
        hello = next(
            (m for m in pdu.messages if isinstance(m, HelloMsg)), None
        )
        if hello is None or not (hello.flags & HELLO_TARGETED):
            return
        tnbr = self.tneighbors.get(src_addr)
        if tnbr is None:
            if (
                not (hello.flags & HELLO_REQ_TARGETED)
                or not self.config.targeted_hello_accept
            ):
                return
            tnbr = TargetedNbr(addr=src_addr)
            self.tneighbors[src_addr] = tnbr
        tnbr.dynamic = bool(
            hello.flags & HELLO_REQ_TARGETED
        ) and self.config.targeted_hello_accept
        self.tnbr_update(tnbr)
        tnbr = self.tneighbors.get(src_addr)
        if tnbr is None or not tnbr.active:
            return
        holdtime_neg = tnbr.calculate_adj_holdtime(hello.holdtime)
        self._process_hello(
            self.trans_addr, source, pdu.lsr_id, hello,
            hello.holdtime, holdtime_neg,
        )

    def _udp_rx_error(self, source: AdjSource) -> None:
        adj = self.adj_by_source(source)
        if adj is not None:
            adj.hello_dropped += 1

    def _process_hello(
        self,
        local_addr,
        source: AdjSource,
        lsr_id,
        hello: HelloMsg,
        holdtime_adjacent: int,
        holdtime_negotiated: int,
    ) -> None:
        """events.rs:187-317."""
        trans_addr = (
            hello.ipv4_addr if hello.ipv4_addr is not None else source.addr
        )
        adj = self.adj_by_source(source)
        if adj is not None:
            if adj.lsr_id != lsr_id:
                return
            shutdown_nbr = adj.trans_addr != trans_addr
            adj.local_addr = local_addr
            adj.trans_addr = trans_addr
            adj.holdtime_adjacent = holdtime_adjacent
            adj.holdtime_negotiated = holdtime_negotiated
            adj.hello_rcvd += 1
            adj.timeout_active = (
                holdtime_negotiated != INFINITE_HOLDTIME
            )
            if shutdown_nbr:
                nbr = self.nbr_by_lsr_id(lsr_id)
                if nbr is not None and nbr.is_operational():
                    self.send_shutdown(nbr)
                    self.fsm(nbr, "error-sent")
        else:
            adj = Adjacency(
                id=self._next_adjacency_id(),
                source=source,
                local_addr=local_addr,
                trans_addr=trans_addr,
                lsr_id=lsr_id,
                holdtime_adjacent=holdtime_adjacent,
                holdtime_negotiated=holdtime_negotiated,
            )
            adj.timeout_active = holdtime_negotiated != INFINITE_HOLDTIME
            self._notif_adjacency_event(
                source.ifname, source.addr, True
            )
            self.adjacencies[adj.id] = adj

        nbr = self.nbr_by_lsr_id(lsr_id)
        if nbr is None:
            nbr = Neighbor(
                id=self._next_neighbor_id(),
                lsr_id=lsr_id,
                trans_addr=trans_addr,
                kalive_interval=self.config.session_ka_interval,
            )
            self.neighbors[nbr.id] = nbr

        # Dynamic GTSM negotiation (events.rs:286-293).
        if not (hello.flags & HELLO_TARGETED) and (
            hello.flags & HELLO_GTSM
        ):
            nbr.flags.add("GTSM")
        else:
            nbr.flags.discard("GTSM")

        if hello.cfg_seqno is not None:
            if hello.cfg_seqno > nbr.cfg_seqno:
                nbr.backoff_active = False
            nbr.cfg_seqno = hello.cfg_seqno

        # Active role starts the TCP connection (events.rs:303-316).
        if (
            nbr.state == FSM_NON_EXISTENT
            and nbr.is_session_active_role(self.trans_addr)
            and not nbr.connecting
            and not nbr.backoff_active
        ):
            nbr.connecting = True

    # ---- adjacency timeout (events.rs:321-344)

    def adj_timeout(self, adj_id: int) -> None:
        adj = self.adjacencies.get(adj_id)
        if adj is None:
            return
        if adj.source.ifname is None:
            tnbr = self.tneighbors.get(adj.source.addr)
            if tnbr is not None:
                tnbr.dynamic = False
                self.tnbr_update(tnbr)
        self.adjacency_delete(adj, StatusCode.HOLD_TIMER_EXP)

    def adjacency_delete(
        self, adj: Adjacency, status: StatusCode
    ) -> None:
        """discovery.rs:338-358."""
        del self.adjacencies[adj.id]
        self._notif_adjacency_event(
            adj.source.ifname, adj.source.addr, False
        )
        self._nbr_delete_check(adj.lsr_id, status)

    def _nbr_delete_check(self, lsr_id, status: StatusCode) -> None:
        """collections.rs:626-667 — delete the neighbor when its last
        adjacency goes."""
        if any(a.lsr_id == lsr_id for a in self.adjacencies.values()):
            return
        nbr = self.nbr_by_lsr_id(lsr_id)
        if nbr is None:
            return
        if nbr.is_operational():
            self.send_notification(nbr, status)
            self.fsm(nbr, "error-sent")
        nbr = self.nbr_by_lsr_id(lsr_id)
        if nbr is not None:
            del self.neighbors[nbr.id]

    # ---- TCP events (events.rs:348-420)

    def tcp_accept(self, conn_info: dict) -> None:
        if not self.active:
            return
        source = IPv4Address(conn_info["remote_addr"])
        nbr = self.nbr_by_trans_addr(source)
        if nbr is None:
            return
        if nbr.is_session_active_role(self.trans_addr):
            return
        if nbr.state != FSM_NON_EXISTENT:
            return
        nbr.conn_info = dict(conn_info)
        nbr.session_up = True
        self.fsm(nbr, "matched-adjacency")

    def tcp_connect(self, nbr_id: int, conn_info: dict) -> None:
        nbr = self.neighbors.get(nbr_id)
        if nbr is None:
            return
        nbr.connecting = False
        nbr.conn_info = dict(conn_info)
        nbr.session_up = True
        self.fsm(nbr, "connection-up")

    # ---- neighbor PDU receipt (events.rs:424-509)

    def nbr_rx_pdu(self, nbr_id: int, pdu) -> None:
        """``pdu``: Pdu | ("decode-error", DecodeError) | "conn-closed"."""
        nbr = self.neighbors.get(nbr_id)
        if nbr is None:
            return
        if pdu == "conn-closed":
            self.fsm(nbr, "connection-down")
            return
        if isinstance(pdu, tuple) and pdu[0] == "decode-error":
            error: DecodeError = pdu[1]
            status = error.status_code()
            self.send_notification(nbr, status)
            if status in (
                StatusCode.SHUTDOWN,
            ) or status.encode_status() & 0x80000000:
                self.fsm(nbr, "error-sent")
            return
        fatal = None
        for msg in pdu.messages:
            fatal = self._process_nbr_msg(nbr, msg)
            if fatal is not None:
                self.fsm(nbr, fatal)
                break
        nbr = self.nbr_by_lsr_id(nbr.lsr_id)
        if nbr is not None and nbr.state == FSM_OPERATIONAL:
            nbr.kalive_timeout_active = True  # reset on any PDU

    def _process_nbr_msg(self, nbr: Neighbor, msg: Message):
        """Returns the fatal FSM event name, or None (events.rs:511-543)."""
        nbr.msgs_rcvd.update(msg)
        if isinstance(msg, NotifMsg):
            return self._nbr_msg_notification(nbr, msg)
        if isinstance(msg, InitMsg):
            return self._nbr_msg_init(nbr, msg)
        if isinstance(msg, KeepaliveMsg):
            return self._nbr_msg_keepalive(nbr, msg)
        if isinstance(msg, AddressMsg):
            return self._nbr_msg_address(nbr, msg)
        if isinstance(msg, LabelMsg):
            return self._nbr_msg_label(nbr, msg)
        if isinstance(msg, CapabilityMsg):
            return self._nbr_msg_capability(nbr, msg)
        return None  # unexpected Hello: ignored

    def _nbr_msg_notification(self, nbr: Neighbor, msg: NotifMsg):
        """events.rs:545-576."""
        if not msg.is_fatal():
            return None
        if nbr.state == FSM_OPENSENT:
            nbr.backoff_active = True
        code = msg.status_code & ~(0xC0000000)
        if not nbr.is_operational() and code == StatusCode.SHUTDOWN:
            self.send_shutdown(nbr, msg)
        return "error-rcvd"

    def _nbr_msg_init(self, nbr: Neighbor, msg: InitMsg):
        """events.rs:578-648."""
        if nbr.state not in (FSM_INITIALIZED, FSM_OPENSENT):
            self.send_shutdown(nbr, msg)
            return "error-sent"
        if msg.lsr_id != self.router_id or msg.lspace_id != 0:
            self.send_notification(
                nbr, StatusCode.SESS_REJ_NO_HELLO, msg
            )
            return "error-sent"
        nbr.kalive_holdtime_rcvd = msg.keepalive_time
        nbr.kalive_holdtime_negotiated = min(
            self.config.session_ka_holdtime, msg.keepalive_time
        )
        nbr.rcvd_label_adv_mode = (
            "downstream-on-demand"
            if msg.flags & INIT_ADV_DISCIPLINE
            else "downstream-unsolicited"
        )
        max_pdu_len = msg.max_pdu_len
        if max_pdu_len <= 255:
            max_pdu_len = PDU_DFLT_MAX_LEN
        nbr.max_pdu_len = min(max_pdu_len, PDU_DFLT_MAX_LEN)
        if msg.cap_dynamic:
            nbr.flags.add("CAP_DYNAMIC")
        if msg.cap_twcard_fec is not None:
            nbr.flags.add("CAP_TYPED_WCARD")
        if msg.cap_unrec_notif is not None:
            nbr.flags.add("CAP_UNREC_NOTIF")
        self.fsm(nbr, "init-rcvd")
        return None

    def _nbr_msg_keepalive(self, nbr: Neighbor, msg: KeepaliveMsg):
        """events.rs:650-673."""
        if nbr.state == FSM_OPENREC:
            self.fsm(nbr, "keepalive-rcvd")
            return None
        if nbr.state == FSM_OPERATIONAL:
            return None
        self.send_shutdown(nbr, msg)
        return "error-sent"

    def _nbr_msg_address(self, nbr: Neighbor, msg: AddressMsg):
        """events.rs:675-753."""
        if not nbr.is_operational():
            self.send_shutdown(nbr, msg)
            return "error-sent"
        addr_list = list(msg.addr_list)
        for prefix, label in nbr.rcvd_mappings.items():
            fec = self.fecs[prefix]
            old_status = fec.is_operational()
            for nh in fec.nexthops.values():
                if nh.addr not in addr_list:
                    continue
                if not msg.withdraw:
                    nh.label = label
                    self._label_install(fec, nh)
                else:
                    self._label_uninstall(fec, nh)
                    nh.label = None
            if old_status != fec.is_operational():
                self._notif_fec_event(fec)
        if not msg.withdraw:
            nbr.addr_list.update(addr_list)
        else:
            nbr.addr_list.difference_update(addr_list)
        return None

    def _nbr_msg_label(self, nbr: Neighbor, msg: LabelMsg):
        """events.rs:755-801."""
        if not nbr.is_operational():
            self.send_shutdown(nbr, msg)
            return "error-sent"
        for fec_elem in msg.fec:
            mt = msg.msg_type
            if mt == MsgType.LABEL_MAPPING:
                self._label_mapping_rx(nbr, msg.label, fec_elem)
            elif mt == MsgType.LABEL_REQUEST:
                self._label_request_rx(nbr, msg, fec_elem)
            elif mt == MsgType.LABEL_WITHDRAW:
                self._label_withdraw_rx(nbr, msg, fec_elem)
            elif mt == MsgType.LABEL_RELEASE:
                self._label_release_rx(nbr, msg, fec_elem)
            # LabelAbortReq: nothing to do with independent control
            # (events.rs:1226-1236).
        return None

    def _label_mapping_rx(self, nbr: Neighbor, label, fec_elem) -> None:
        """LMp.1-16 (events.rs:803-894)."""
        prefix = fec_elem.prefix
        fec = self.fecs.setdefault(prefix, Fec(prefix=prefix))
        old_status = fec.is_operational()
        req_response = prefix in nbr.sent_requests
        nbr.sent_requests.pop(prefix, None)
        if prefix in nbr.rcvd_mappings:
            old_label = nbr.rcvd_mappings[prefix]
            if old_label != label and not req_response:
                for nh in fec.nexthops.values():
                    if nh.addr not in nbr.addr_list:
                        continue
                    self._label_uninstall(fec, nh)
                    nh.label = None
                self.send_label_release(
                    nbr, FecPrefix(prefix), old_label
                )
        for nh in fec.nexthops.values():
            if nh.addr not in nbr.addr_list:
                continue
            if nh.label == label:
                continue
            nh.label = label
            if fec.local_label is not None:
                self._label_install(fec, nh)
        if old_status != fec.is_operational():
            self._notif_fec_event(fec)
        fec.downstream[nbr.lsr_id] = label
        nbr.rcvd_mappings[prefix] = label

    def _label_request_rx(self, nbr: Neighbor, msg, fec_elem) -> None:
        """LRq.1-9 (events.rs:896-1016)."""
        if isinstance(fec_elem, FecWildcard):
            if fec_elem.typed_af is None:
                return  # All-wildcard requests are invalid (unreachable)
            af = fec_elem.typed_af
            for fec in self._fecs_sorted():
                if (
                    AF_IPV4 if fec.prefix.version == 4 else AF_IPV6
                ) != af:
                    continue
                if not fec.nexthops:
                    continue
                if fec.prefix in nbr.rcvd_requests:
                    continue
                nbr.rcvd_requests[fec.prefix] = msg.msg_id
                self.send_label_mapping(nbr, fec)
            if "CAP_UNREC_NOTIF" in nbr.flags:
                self.send_end_of_lib(nbr, af)
            return
        prefix = fec_elem.prefix
        fec = self.fecs.get(prefix)
        if fec is None or not fec.nexthops:
            self.send_notification(nbr, StatusCode.NO_ROUTE, msg)
            return
        for nh in fec.nexthops.values():
            if nh.addr in nbr.addr_list:
                self.send_notification(
                    nbr, StatusCode.LOOP_DETECTED, msg
                )
                return
        if prefix in nbr.rcvd_requests:
            return  # LRq.7 duplicate
        nbr.rcvd_requests[prefix] = msg.msg_id
        self.send_label_mapping(nbr, fec)

    def _label_withdraw_rx(self, nbr: Neighbor, msg, fec_elem) -> None:
        """LWd.1-4 (events.rs:1019-1138)."""
        if isinstance(fec_elem, FecWildcard):
            self.send_label_release(nbr, fec_elem, msg.label)
            for fec in self._fecs_sorted():
                if fec_elem.typed_af is not None and (
                    AF_IPV4 if fec.prefix.version == 4 else AF_IPV6
                ) != fec_elem.typed_af:
                    continue
                self._withdraw_one(nbr, msg, fec)
            return
        prefix = fec_elem.prefix
        fec = self.fecs.setdefault(prefix, Fec(prefix=prefix))
        self._withdraw_one(nbr, msg, fec, send_release=True)

    def _withdraw_one(
        self, nbr: Neighbor, msg, fec: Fec, send_release: bool = False
    ) -> None:
        old_status = fec.is_operational()
        for nh in fec.nexthops.values():
            if nh.addr not in nbr.addr_list:
                continue
            if msg.label is not None and msg.label != nh.label:
                continue
            self._label_uninstall(fec, nh)
            nh.label = None
        if old_status != fec.is_operational():
            self._notif_fec_event(fec)
        if send_release:
            self.send_label_release(
                nbr, FecPrefix(fec.prefix), msg.label
            )
        if fec.prefix in nbr.rcvd_mappings:
            mapping = nbr.rcvd_mappings[fec.prefix]
            if msg.label is None or msg.label == mapping:
                del nbr.rcvd_mappings[fec.prefix]
                fec.downstream.pop(nbr.lsr_id, None)

    def _label_release_rx(self, nbr: Neighbor, msg, fec_elem) -> None:
        """LRl.1-6 (events.rs:1140-1224)."""
        if isinstance(fec_elem, FecWildcard):
            for fec in self._fecs_sorted():
                if fec_elem.typed_af is not None and (
                    AF_IPV4 if fec.prefix.version == 4 else AF_IPV6
                ) != fec_elem.typed_af:
                    continue
                self._release_one(nbr, msg, fec)
            return
        fec = self.fecs.get(fec_elem.prefix)
        if fec is None:
            return
        self._release_one(nbr, msg, fec)

    def _release_one(self, nbr: Neighbor, msg, fec: Fec) -> None:
        prefix = fec.prefix
        if prefix in nbr.sent_mappings:
            mapping = nbr.sent_mappings[prefix]
            if msg.label is None or msg.label == mapping:
                del nbr.sent_mappings[prefix]
                fec.upstream.pop(nbr.lsr_id, None)
        if prefix in nbr.sent_withdraws:
            if msg.label is None or msg.label == nbr.sent_withdraws[prefix]:
                del nbr.sent_withdraws[prefix]

    def _nbr_msg_capability(self, nbr: Neighbor, msg: CapabilityMsg):
        """events.rs:1238-1268."""
        if not nbr.is_operational():
            self.send_shutdown(nbr, msg)
            return "error-sent"
        if msg.twcard_fec is not None:
            if msg.twcard_fec:
                nbr.flags.add("CAP_TYPED_WCARD")
            else:
                nbr.flags.discard("CAP_TYPED_WCARD")
        if msg.unrec_notif is not None:
            if msg.unrec_notif:
                nbr.flags.add("CAP_UNREC_NOTIF")
            else:
                nbr.flags.discard("CAP_UNREC_NOTIF")
        return None

    # ---- timeouts (events.rs:1272-1312)

    def nbr_ka_timeout(self, nbr_id: int) -> None:
        nbr = self.neighbors.get(nbr_id)
        if nbr is None:
            return
        self.send_notification(nbr, StatusCode.KEEPALIVE_EXP)
        self.fsm(nbr, "error-sent")

    def nbr_backoff_timeout(self, lsr_id) -> None:
        nbr = self.nbr_by_lsr_id(lsr_id)
        if nbr is None:
            return
        nbr.backoff_active = False
        nbr.connecting = True

    # ---- ibus rx (ibus/rx.rs)

    def router_id_update(self, router_id) -> None:
        self.sys_router_id = router_id
        self.update()

    def iface_update(self, ifname: str, ifindex, operative: bool) -> None:
        # System data is tracked regardless of instance state (the
        # reference keeps it outside the instance, ibus/rx.rs) — only the
        # protocol side effects are gated on self.active.
        iface = self.interfaces.get(ifname)
        if iface is None:
            return
        iface.ifindex = ifindex
        iface.operative = operative
        if self.active:
            self.iface_check(iface)

    def addr_add(
        self, ifname: str, prefix, unnumbered: bool = False
    ) -> None:
        if prefix.version == 4:
            if not unnumbered and prefix not in self.ipv4_addr_list:
                self.ipv4_addr_list.add(prefix)
                if self.active:
                    for nbr in self._nbrs_sorted():
                        if nbr.is_operational():
                            self.send_address(nbr, False, [prefix.ip])
        iface = self.interfaces.get(ifname)
        if iface is not None and prefix.version == 4:
            if prefix not in iface.ipv4_addr_list:
                iface.ipv4_addr_list.add(prefix)
                if self.active:
                    self.iface_check(iface)

    def addr_del(
        self, ifname: str, prefix, unnumbered: bool = False
    ) -> None:
        if prefix.version == 4:
            if not unnumbered and prefix in self.ipv4_addr_list:
                self.ipv4_addr_list.discard(prefix)
                if self.active:
                    for nbr in self._nbrs_sorted():
                        if nbr.is_operational():
                            self.send_address(nbr, True, [prefix.ip])
        iface = self.interfaces.get(ifname)
        if iface is not None and prefix.version == 4:
            if prefix in iface.ipv4_addr_list:
                iface.ipv4_addr_list.discard(prefix)
                if self.active:
                    self.iface_check(iface)

    def route_add(self, prefix, protocol: str, nexthops) -> None:
        """ibus/rx.rs process_route_add; nexthops: [(ifindex, addr)]."""
        if not self.active:
            return
        fec = self.fecs.setdefault(prefix, Fec(prefix=prefix))
        old_status = fec.is_operational()
        fec.protocol = protocol
        new_addrs = {addr for _, addr in nexthops}
        for addr in list(fec.nexthops):
            if addr not in new_addrs:
                nh = fec.nexthops[addr]
                self._label_uninstall(fec, nh)
                del fec.nexthops[addr]
        if old_status != fec.is_operational():
            self._notif_fec_event(fec)
        for ifindex, addr in nexthops:
            if addr not in fec.nexthops:
                fec.nexthops[addr] = Nexthop(addr=addr, ifindex=ifindex)
        self._local_label_update(fec)
        self._process_new_fec(fec)

    def route_del(self, prefix) -> None:
        if not self.active:
            return
        fec = self.fecs.get(prefix)
        if fec is None:
            return
        old_status = fec.is_operational()
        for nbr in self._nbrs_sorted():
            if nbr.is_operational():
                self.send_label_withdraw(nbr, fec)
        for nh in fec.nexthops.values():
            self._label_uninstall(fec, nh)
        if fec.local_label is not None:
            self.labels.label_release(fec.local_label)
        fec.nexthops.clear()
        if old_status != fec.is_operational():
            self._notif_fec_event(fec)

    def _local_label_update(self, fec: Fec) -> None:
        """ibus/rx.rs:36-59."""
        if fec.local_label is not None:
            return
        if fec.protocol == "direct":
            fec.local_label = IMPLICIT_NULL
        else:
            fec.local_label = self.labels.label_request()

    def _process_new_fec(self, fec: Fec) -> None:
        """FEC.1-5 (ibus/rx.rs:61-91)."""
        for nbr in self._nbrs_sorted():
            if nbr.is_operational():
                self.send_label_mapping(nbr, fec)
        for addr in list(fec.nexthops):
            nbr = self.nbr_by_adv_addr(addr)
            if nbr is not None and fec.prefix in nbr.rcvd_mappings:
                self._label_mapping_rx(
                    nbr,
                    nbr.rcvd_mappings[fec.prefix],
                    FecPrefix(fec.prefix),
                )

    # ---- RPCs (northbound/rpc.rs)

    def clear_peer(self, lsr_id=None) -> None:
        for nbr in list(self._nbrs_sorted()):
            if nbr.state == FSM_NON_EXISTENT:
                continue
            if lsr_id is not None and nbr.lsr_id != lsr_id:
                continue
            self.send_shutdown(nbr)
            self.fsm(nbr, "error-sent")

    def clear_hello_adjacency(
        self,
        targeted: bool | None = None,
        target_address=None,
        next_hop_interface=None,
        next_hop_address=None,
    ) -> None:
        for adj in list(self.adjacencies.values()):
            if adj.id not in self.adjacencies:
                continue
            if targeted is True and adj.source.ifname is not None:
                continue
            if targeted is False and adj.source.ifname is None:
                continue
            if (
                target_address is not None
                and adj.source.addr != target_address
            ):
                continue
            if (
                next_hop_interface is not None
                and adj.source.ifname != next_hop_interface
            ):
                continue
            if (
                next_hop_address is not None
                and adj.source.addr != next_hop_address
            ):
                continue
            self.adjacency_delete(adj, StatusCode.SHUTDOWN)

    def clear_peer_statistics(self, lsr_id=None) -> None:
        for nbr in self.neighbors.values():
            if lsr_id is not None and nbr.lsr_id != lsr_id:
                continue
            nbr.msgs_rcvd = MsgCounters()
            nbr.msgs_sent = MsgCounters()

    # ---- operational state (northbound/state.rs, testing-mode fields)

    def northbound_state(self) -> dict:
        mpls_ldp: dict = {}
        ipv4: dict = {
            "label-distribution-control-mode": "independent",
        }
        bindings = self._state_bindings()
        if bindings:
            ipv4["bindings"] = bindings
        mpls_ldp["global"] = {"address-families": {"ipv4": ipv4}}
        disc = self._state_discovery()
        if disc:
            mpls_ldp["discovery"] = disc
        peers = self._state_peers()
        if peers:
            mpls_ldp["peers"] = {"peer": peers}
        return mpls_ldp

    def _state_bindings(self) -> dict:
        if not self.active:
            return {}
        out: dict = {}
        # address bindings: skip entirely unless some nbr is operational
        # (state.rs:81-101).
        if any(n.is_operational() for n in self._nbrs_sorted()):
            addrs = []
            for p in sorted(self.ipv4_addr_list, key=lambda p: int(p.ip)):
                addrs.append(
                    {
                        "address": str(p.ip),
                        "advertisement-type": "advertised",
                    }
                )
            for nbr in self._nbrs_sorted():
                for addr in sorted(nbr.addr_list, key=int):
                    if addr.version != 4:
                        continue
                    addrs.append(
                        {
                            "address": str(addr),
                            "advertisement-type": "received",
                            "peer": {
                                "lsr-id": str(nbr.lsr_id),
                                "label-space-id": 0,
                            },
                        }
                    )
            if addrs:
                out["address"] = addrs
        fec_labels = []
        for fec in self._fecs_sorted():
            if fec.prefix.version != 4:
                continue
            if not fec.upstream and not fec.downstream:
                continue
            peers = []
            for lsr_id in sorted(fec.upstream, key=int):
                peers.append(
                    {
                        "lsr-id": str(lsr_id),
                        "label-space-id": 0,
                        "advertisement-type": "advertised",
                        "label": _label_yang(fec.upstream[lsr_id]),
                        "used-in-forwarding": True,
                    }
                )
            for lsr_id in sorted(fec.downstream, key=int):
                nbr = self.nbr_by_lsr_id(lsr_id)
                if nbr is None:
                    continue
                peers.append(
                    {
                        "lsr-id": str(lsr_id),
                        "label-space-id": 0,
                        "advertisement-type": "received",
                        "label": _label_yang(fec.downstream[lsr_id]),
                        "used-in-forwarding": fec.is_nbr_nexthop(nbr),
                    }
                )
            fec_labels.append({"fec": str(fec.prefix), "peer": peers})
        if fec_labels:
            out["fec-label"] = fec_labels
        return out

    def _state_discovery(self) -> dict:
        out: dict = {}
        ifaces = []
        if self.active:
            for name in sorted(self.interfaces):
                iface = self.interfaces[name]
                if not iface.active:
                    continue
                adjs = [
                    a
                    for a in self.adjacencies.values()
                    if a.source.ifname == name
                ]
                entry: dict = {"name": name}
                if adjs:
                    entry["address-families"] = {
                        "ipv4": {
                            "hello-adjacencies": {
                                "hello-adjacency": [
                                    self._state_adj(a, local=False)
                                    for a in sorted(
                                        adjs,
                                        key=lambda a: int(a.source.addr),
                                    )
                                ]
                            }
                        }
                    }
                ifaces.append(entry)
        if ifaces:
            out["interfaces"] = {"interface": ifaces}
        tadjs = [
            a
            for a in self.adjacencies.values()
            if a.source.ifname is None
        ]
        if tadjs:
            out["targeted"] = {
                "address-families": {
                    "ipv4": {
                        "hello-adjacencies": {
                            "hello-adjacency": [
                                self._state_adj(a, local=True)
                                for a in sorted(
                                    tadjs,
                                    key=lambda a: int(a.source.addr),
                                )
                            ]
                        }
                    }
                }
            }
        return out

    def _state_adj(self, adj: Adjacency, local: bool) -> dict:
        entry: dict = {}
        if local:
            entry["local-address"] = str(adj.local_addr)
        entry["adjacent-address"] = str(adj.source.addr)
        entry["hello-holdtime"] = {
            "adjacent": adj.holdtime_adjacent,
            "negotiated": adj.holdtime_negotiated,
        }
        entry["peer"] = {
            "lsr-id": str(adj.lsr_id),
            "label-space-id": 0,
        }
        return entry

    def _state_peers(self) -> list:
        peers = []
        for nbr in self._nbrs_sorted():
            entry: dict = {
                "lsr-id": str(nbr.lsr_id),
                "label-space-id": 0,
            }
            adjs = [
                a
                for a in self.adjacencies.values()
                if a.lsr_id == nbr.lsr_id
            ]
            if adjs:
                entry["address-families"] = {
                    "ipv4": {
                        "hello-adjacencies": {
                            "hello-adjacency": [
                                {
                                    "local-address": str(a.local_addr),
                                    "adjacent-address": str(
                                        a.source.addr
                                    ),
                                    "hello-holdtime": {
                                        "adjacent": a.holdtime_adjacent,
                                        "negotiated": (
                                            a.holdtime_negotiated
                                        ),
                                    },
                                }
                                for a in sorted(
                                    adjs,
                                    key=lambda a: int(a.source.addr),
                                )
                            ]
                        }
                    }
                }
            lam: dict = {}
            if nbr.is_operational():
                lam["local"] = "downstream-unsolicited"
            if nbr.rcvd_label_adv_mode is not None:
                lam["peer"] = nbr.rcvd_label_adv_mode
            if nbr.is_operational():
                lam["negotiated"] = "downstream-unsolicited"
            if lam:
                entry["label-advertisement-mode"] = lam
            entry["received-peer-state"] = {
                "capability": {
                    "end-of-lib": {
                        "enabled": "CAP_UNREC_NOTIF" in nbr.flags
                    },
                    "typed-wildcard-fec": {
                        "enabled": "CAP_TYPED_WCARD" in nbr.flags
                    },
                }
            }
            sh: dict = {}
            if nbr.kalive_holdtime_rcvd is not None:
                sh["peer"] = nbr.kalive_holdtime_rcvd
            if nbr.kalive_holdtime_negotiated is not None:
                sh["negotiated"] = nbr.kalive_holdtime_negotiated
            if sh:
                entry["session-holdtime"] = sh
            entry["session-state"] = nbr.state
            if nbr.conn_info is not None:
                entry["tcp-connection"] = {
                    "local-address": str(nbr.conn_info["local_addr"]),
                    "remote-address": str(nbr.conn_info["remote_addr"]),
                }
            total_fec_bindings = sum(
                1
                for prefix in nbr.rcvd_mappings
                if prefix in self.fecs
                and self.fecs[prefix].is_nbr_nexthop(nbr)
            )
            entry["statistics"] = {
                "total-addresses": len(nbr.addr_list),
                "total-labels": len(nbr.rcvd_mappings),
                "total-fec-label-bindings": total_fec_bindings,
            }
            peers.append(entry)
        return peers


def _label_yang(label: int) -> int | str:
    """holo-yang label rendering: reserved labels use identities."""
    return {
        0: "ietf-routing-types:ipv4-explicit-null-label",
        2: "ietf-routing-types:ipv6-explicit-null-label",
        3: "ietf-routing-types:implicit-null-label",
    }.get(label, label)

"""BFD (RFC 5880/5881/5883): asynchronous-mode session FSM.

Reference: holo-bfd (SURVEY.md §2.3) — session table keyed by peer,
clients (OSPF/IS-IS/BGP) register over the ibus and receive state-change
notifications to kill adjacencies fast (§3.5 of SURVEY.md).

Scope parity with the reference plus extras:
- single-hop (RFC 5881) and multihop (RFC 5883) sessions — key tuples
  ``(ifname, dst)`` and ``("mh", src, dst)`` mirror the reference's
  SessionKey::IpSingleHop/IpMultihop (holo-utils/src/bfd.rs:29-31);
- the authentication section (RFC 5880 §4.2-4.4): the reference only
  parses and length-validates it (holo-bfd/src/packet.rs:188-231); here
  simple-password comparison and keyed MD5/SHA1 digest computation +
  verification with sequence-number windows are implemented as well;
- the echo function (RFC 5880 §6.4): echo packets loop back through the
  peer's forwarding plane; a missed echo window drops the session with
  diagnostic EchoFailed.
"""

from __future__ import annotations

import enum
import hashlib
import hmac
from dataclasses import dataclass, field
from ipaddress import IPv4Address

from holo_tpu import telemetry
from holo_tpu.utils.bytesbuf import DecodeError, Reader, Writer
from holo_tpu.utils.ibus import TOPIC_BFD_STATE, BfdSessionReg, BfdSessionUnreg, BfdStateUpd, Ibus, IbusMsg
from holo_tpu.utils.netio import NetIo, NetRxPacket
from holo_tpu.utils.runtime import Actor

# Session FSM + wire observability.  A "flap" is the monitored failure
# event (UP -> DOWN): it is what triggers the RIB's FRR local repair,
# so its count joins directly against holo_rib_backup_flips_total.
_BFD_TRANSITIONS = telemetry.counter(
    "holo_bfd_transitions_total", "BFD session state transitions", ("to",)
)
_BFD_FLAPS = telemetry.counter(
    "holo_bfd_flaps_total", "BFD sessions dropping from UP to DOWN"
)
_BFD_PACKETS = telemetry.counter(
    "holo_bfd_packets_total", "BFD control packets", ("dir",)
)


class BfdState(enum.IntEnum):
    ADMIN_DOWN = 0
    DOWN = 1
    INIT = 2
    UP = 3


class BfdDiag(enum.IntEnum):
    NONE = 0
    TIME_EXPIRED = 1
    ECHO_FAILED = 2
    NEIGHBOR_DOWN = 3
    FWD_PLANE_RESET = 4
    PATH_DOWN = 5
    CONCAT_DOWN = 6
    ADMIN_DOWN = 7
    REVERSE_CONCAT_DOWN = 8


class BfdAuthType(enum.IntEnum):
    """RFC 5880 §4.1 Auth Type (holo-bfd/src/packet.rs:74-82)."""

    SIMPLE_PASSWORD = 1
    KEYED_MD5 = 2
    METICULOUS_KEYED_MD5 = 3
    KEYED_SHA1 = 4
    METICULOUS_KEYED_SHA1 = 5


_AUTH_DIGEST_LEN = {
    BfdAuthType.KEYED_MD5: (24, 16, "md5"),
    BfdAuthType.METICULOUS_KEYED_MD5: (24, 16, "md5"),
    BfdAuthType.KEYED_SHA1: (28, 20, "sha1"),
    BfdAuthType.METICULOUS_KEYED_SHA1: (28, 20, "sha1"),
}


@dataclass
class BfdAuth:
    """Authentication section (RFC 5880 §4.2-4.4)."""

    auth_type: BfdAuthType
    key_id: int = 0
    password: bytes = b""  # simple-password payload
    seq: int = 0  # keyed types: sequence number
    digest: bytes = b""  # keyed types: as decoded from the wire


@dataclass
class BfdPacket:
    """RFC 5880 §4.1 mandatory section + optional auth section."""

    state: BfdState
    diag: BfdDiag = BfdDiag.NONE
    poll: bool = False
    final: bool = False
    detect_mult: int = 3
    my_discr: int = 0
    your_discr: int = 0
    desired_min_tx: int = 1_000_000  # microseconds
    required_min_rx: int = 1_000_000
    required_min_echo_rx: int = 0
    auth: BfdAuth | None = None

    def encode(self, auth_key: bytes | None = None) -> bytes:
        w = Writer()
        w.u8((1 << 5) | int(self.diag))  # version 1
        flags = (int(self.state) << 6) | (0x20 if self.poll else 0) | (
            0x10 if self.final else 0
        )
        if self.auth is not None:
            flags |= 0x04  # A bit
        w.u8(flags)
        w.u8(self.detect_mult)
        len_pos = len(w)
        w.u8(24)  # patched below when an auth section follows
        w.u32(self.my_discr).u32(self.your_discr)
        w.u32(self.desired_min_tx).u32(self.required_min_rx)
        w.u32(self.required_min_echo_rx)
        if self.auth is not None:
            a = self.auth
            if a.auth_type == BfdAuthType.SIMPLE_PASSWORD:
                pw = a.password or (auth_key or b"")
                if not 1 <= len(pw) <= 16:
                    raise ValueError(
                        "BFD simple password must be 1-16 bytes"
                    )
                w.u8(a.auth_type).u8(3 + len(pw)).u8(a.key_id)
                w.bytes(pw)
            else:
                auth_len, dlen, algo = _AUTH_DIGEST_LEN[a.auth_type]
                w.u8(a.auth_type).u8(auth_len).u8(a.key_id).u8(0)
                w.u32(a.seq)
                digest_pos = len(w)
                w.zeros(dlen)
                buf = bytearray(w.finish())
                buf[len_pos] = 24 + auth_len
                # Digest over the whole packet with the key in place of
                # the digest field (RFC 5880 §6.7.3/6.7.4).
                key = (auth_key or b"")[:dlen].ljust(dlen, b"\x00")
                buf[digest_pos : digest_pos + dlen] = key
                digest = hashlib.new(algo, bytes(buf)).digest()
                buf[digest_pos : digest_pos + dlen] = digest
                return bytes(buf)
            buf = bytearray(w.finish())
            buf[len_pos] = len(buf)
            return bytes(buf)
        return w.finish()

    @classmethod
    def decode(cls, data: bytes) -> "BfdPacket":
        r = Reader(data)
        vd = r.u8()
        if vd >> 5 != 1:
            raise DecodeError("bad BFD version")
        flags = r.u8()
        mult = r.u8()
        length = r.u8()
        if length < 24 or length > len(data):
            raise DecodeError("bad BFD length")
        my, your = r.u32(), r.u32()
        tx, rx, erx = r.u32(), r.u32(), r.u32()
        if mult == 0 or my == 0:
            raise DecodeError("invalid BFD fields")
        auth = None
        if flags & 0x04:
            # Auth section present; length checks mirror the reference
            # (holo-bfd/src/packet.rs:188-231).
            if r.remaining() < 2:
                raise DecodeError("truncated BFD auth section")
            atype_raw = r.u8()
            alen = r.u8()
            if alen + 24 > length:
                raise DecodeError("bad BFD auth length")
            try:
                atype = BfdAuthType(atype_raw)
            except ValueError as e:
                raise DecodeError("bad BFD auth type") from e
            if atype == BfdAuthType.SIMPLE_PASSWORD:
                if alen < 4 or alen > 19:
                    raise DecodeError("bad BFD auth length")
                key_id = r.u8()
                auth = BfdAuth(
                    atype, key_id=key_id, password=r.bytes(alen - 3)
                )
            else:
                want_len, dlen, _algo = _AUTH_DIGEST_LEN[atype]
                if alen != want_len:
                    raise DecodeError("bad BFD auth length")
                key_id = r.u8()
                r.u8()  # reserved
                seq = r.u32()
                auth = BfdAuth(
                    atype, key_id=key_id, seq=seq, digest=r.bytes(dlen)
                )
        try:
            diag = BfdDiag(vd & 0x1F)
        except ValueError:
            diag = BfdDiag.NONE  # reserved diag codes: accept, ignore diag
        return cls(
            state=BfdState((flags >> 6) & 0x3),
            diag=diag,
            poll=bool(flags & 0x20),
            final=bool(flags & 0x10),
            detect_mult=mult,
            my_discr=my,
            your_discr=your,
            desired_min_tx=tx,
            required_min_rx=rx,
            required_min_echo_rx=erx,
            auth=auth,
        )

    def verify_auth(self, raw: bytes, key: bytes) -> bool:
        """Verify the packet's auth section against ``key`` (RFC 5880
        §6.7; digest verification goes beyond the reference's
        parse-only handling)."""
        a = self.auth
        if a is None:
            return False
        if a.auth_type == BfdAuthType.SIMPLE_PASSWORD:
            return hmac.compare_digest(a.password or b"", key)
        _len, dlen, algo = _AUTH_DIGEST_LEN[a.auth_type]
        # The digest sits at (declared length - dlen): derive it from the
        # packet's own length field (byte 3), not the datagram size —
        # trailing bytes in the datagram must not shift the digest window.
        declared = raw[3] if len(raw) > 3 else len(raw)
        if declared < 24 + 8 + dlen or declared > len(raw):
            return False
        buf = bytearray(raw[:declared])
        digest_pos = declared - dlen
        buf[digest_pos:] = key[:dlen].ljust(dlen, b"\x00")
        return hmac.compare_digest(
            hashlib.new(algo, bytes(buf)).digest(), a.digest
        )


@dataclass
class TxTimerMsg:
    key: tuple


@dataclass
class DetectTimerMsg:
    key: tuple


@dataclass
class EchoTxTimerMsg:
    key: tuple


@dataclass
class EchoDetectTimerMsg:
    key: tuple


# Echo packet format is sender-local per RFC 5880 §6.4; ours is a magic
# marker + the session's local discriminator.
ECHO_MAGIC = b"\xbf\xdeECHO"


@dataclass
class Session:
    key: tuple  # (ifname, dst) single-hop | ("mh", src, dst) multihop
    local_discr: int
    state: BfdState = BfdState.DOWN
    remote_discr: int = 0
    remote_min_rx: int = 1_000_000
    remote_min_tx: int = 1_000_000
    remote_detect_mult: int = 3
    remote_state: BfdState = BfdState.DOWN
    remote_min_echo_rx: int = 0
    desired_min_tx: int = 1_000_000
    required_min_rx: int = 1_000_000
    required_min_echo_rx: int = 0
    detect_mult: int = 3
    diag: BfdDiag = BfdDiag.NONE
    clients: set = field(default_factory=set)
    # Authentication (RFC 5880 §6.7); None = no auth on this session.
    auth_type: BfdAuthType | None = None
    auth_key: bytes = b""
    auth_key_id: int = 0
    _tx_seq: int = 0
    _last_rx_seq: int | None = None
    # Echo function (RFC 5880 §6.4).
    echo_interval: float | None = None  # seconds; None = echo disabled

    def is_multihop(self) -> bool:
        return self.key[0] == "mh"

    def peer_addr(self):
        return self.key[2] if self.is_multihop() else self.key[1]


class BfdInstance(Actor):
    """BFD master actor: one session table for all interfaces/peers.

    Spawned at daemon startup inside the routing provider, like the
    reference (holo-routing/src/lib.rs:261-281).
    """

    name = "bfd"

    def __init__(self, netio: NetIo, ibus: Ibus | None = None, slow_tx: float = 1.0,
                 notif_cb=None):
        self.netio = netio
        self.ibus = ibus
        self.notif_cb = notif_cb  # YANG notifications (ietf-bfd-ip-sh/mh)
        self.sessions: dict[tuple, Session] = {}
        self._next_discr = 1
        self.slow_tx = slow_tx  # tx interval until session is UP (seconds)

    # -- lifecycle

    def session_key(self, ifname: str, peer: IPv4Address) -> tuple:
        """Single-hop key (reference SessionKey::IpSingleHop)."""
        return (ifname, peer)

    @staticmethod
    def session_key_mh(src: IPv4Address, dst: IPv4Address) -> tuple:
        """Multihop key, RFC 5883 (reference SessionKey::IpMultihop)."""
        return ("mh", src, dst)

    def configure_auth(
        self,
        key: tuple,
        auth_type: BfdAuthType,
        auth_key: bytes,
        key_id: int = 1,
    ) -> None:
        s = self.sessions.get(key)
        if s is None:
            raise KeyError(f"no BFD session for {key}")
        s.auth_type = auth_type
        s.auth_key = auth_key
        s.auth_key_id = key_id

    def enable_echo(self, key: tuple, interval: float = 0.05) -> None:
        """Start the echo function on an up session (RFC 5880 §6.4);
        echo packets are only sent while the peer advertises a nonzero
        Required Min Echo RX."""
        s = self.sessions.get(key)
        if s is None or s.is_multihop():
            return  # echo is single-hop only (RFC 5883 §5)
        s.echo_interval = interval
        s.required_min_echo_rx = int(interval * 1e6)
        self._arm_echo_tx(s)

    def register(self, key: tuple, client: str, local: IPv4Address) -> Session:
        s = self.sessions.get(key)
        if s is None:
            s = Session(key=key, local_discr=self._next_discr)
            self._next_discr += 1
            s.local = local
            self.sessions[key] = s
            self._arm_tx(s, self.slow_tx)
        elif local is not None:
            s.local = local
        s.clients.add(client)
        return s

    def unregister(self, key: tuple, client: str) -> None:
        s = self.sessions.get(key)
        if s is None:
            return
        s.clients.discard(client)
        if not s.clients:
            for attr in ("_tx_timer", "_detect_timer", "_echo_tx_timer",
                         "_echo_detect_timer"):
                t = getattr(s, attr, None)
                if t is not None:
                    t.cancel()
            del self.sessions[key]

    # -- actor

    def handle(self, msg):
        if isinstance(msg, NetRxPacket):
            self._rx(msg)
        elif isinstance(msg, TxTimerMsg):
            s = self.sessions.get(msg.key)
            if s is not None:
                self._send(s)
                self._arm_tx(s, self._tx_interval(s))
        elif isinstance(msg, DetectTimerMsg):
            s = self.sessions.get(msg.key)
            if s is not None and s.state in (BfdState.INIT, BfdState.UP):
                self._transition(s, BfdState.DOWN, BfdDiag.TIME_EXPIRED)
        elif isinstance(msg, EchoTxTimerMsg):
            s = self.sessions.get(msg.key)
            if s is not None and s.echo_interval is not None:
                if s.state == BfdState.UP and s.remote_min_echo_rx:
                    self._send_echo(s)
                self._arm_echo_tx(s)
        elif isinstance(msg, EchoDetectTimerMsg):
            s = self.sessions.get(msg.key)
            if s is not None and s.state == BfdState.UP:
                self._transition(s, BfdState.DOWN, BfdDiag.ECHO_FAILED)
        elif isinstance(msg, IbusMsg):
            p = msg.payload
            if isinstance(p, BfdSessionReg):
                s = self.register(p.key, msg.sender, p.local)
                # Honor the client's requested timing parameters (take the
                # fastest/safest across clients).
                s.required_min_rx = min(s.required_min_rx, p.min_rx)
                s.desired_min_tx = min(s.desired_min_tx, p.min_tx)
                s.detect_mult = p.multiplier
            elif isinstance(p, BfdSessionUnreg):
                self.unregister(p.key, msg.sender)

    # -- FSM (RFC 5880 §6.8.6)

    def _rx(self, msg: NetRxPacket) -> None:
        if msg.data.startswith(ECHO_MAGIC):
            self._rx_echo(msg)
            return
        _BFD_PACKETS.labels(dir="rx").inc()
        try:
            pkt = BfdPacket.decode(msg.data)
        except DecodeError:
            return
        s = self.sessions.get(self.session_key(msg.ifname, msg.src))
        if s is None:
            # Multihop lookup: keyed by (local, remote) address pair.
            s = self.sessions.get(
                self.session_key_mh(msg.dst, msg.src)
            ) or next(
                (
                    t
                    for t in self.sessions.values()
                    if t.is_multihop()
                    and t.key[2] == msg.src
                    and (msg.dst is None or t.key[1] == msg.dst)
                ),
                None,
            )
        if s is None:
            return
        if pkt.your_discr != 0 and pkt.your_discr != s.local_discr:
            return
        # Authentication (RFC 5880 §6.7): sessions with auth configured
        # drop unauthenticated or badly-keyed packets; sessions without
        # drop authenticated ones (§6.7.1 bfd.AuthSeqKnown discipline).
        if s.auth_type is not None:
            if pkt.auth is None or pkt.auth.auth_type != s.auth_type:
                return
            if pkt.auth.key_id != s.auth_key_id:
                return
            if not pkt.verify_auth(msg.data, s.auth_key):
                return
            if pkt.auth.auth_type != BfdAuthType.SIMPLE_PASSWORD:
                meticulous = pkt.auth.auth_type in (
                    BfdAuthType.METICULOUS_KEYED_MD5,
                    BfdAuthType.METICULOUS_KEYED_SHA1,
                )
                last = s._last_rx_seq
                if last is not None:
                    window = 3 * s.remote_detect_mult
                    delta = (pkt.auth.seq - last) & 0xFFFFFFFF
                    if meticulous and (delta == 0 or delta > window):
                        return
                    if not meticulous and delta > window:
                        return
                s._last_rx_seq = pkt.auth.seq
        elif pkt.auth is not None:
            return
        s.remote_discr = pkt.my_discr
        s.remote_state = pkt.state
        s.remote_min_rx = pkt.required_min_rx
        s.remote_min_tx = pkt.desired_min_tx
        s.remote_detect_mult = pkt.detect_mult
        s.remote_min_echo_rx = pkt.required_min_echo_rx

        if pkt.state == BfdState.ADMIN_DOWN:
            if s.state in (BfdState.INIT, BfdState.UP):
                self._transition(s, BfdState.DOWN, BfdDiag.NEIGHBOR_DOWN)
        elif s.state == BfdState.DOWN:
            if pkt.state == BfdState.DOWN:
                self._transition(s, BfdState.INIT)
            elif pkt.state == BfdState.INIT:
                self._transition(s, BfdState.UP)
        elif s.state == BfdState.INIT:
            if pkt.state in (BfdState.INIT, BfdState.UP):
                self._transition(s, BfdState.UP)
        elif s.state == BfdState.UP:
            if pkt.state == BfdState.DOWN:
                self._transition(s, BfdState.DOWN, BfdDiag.NEIGHBOR_DOWN)
        self._arm_detect(s)

    def _transition(self, s: Session, new: BfdState, diag: BfdDiag = BfdDiag.NONE) -> None:
        if s.state == new:
            return
        _BFD_TRANSITIONS.labels(to=new.name.lower()).inc()
        if s.state == BfdState.UP and new == BfdState.DOWN:
            _BFD_FLAPS.inc()
        s.state = new
        s.diag = diag
        if new == BfdState.DOWN:
            # RFC 5880 §6.8.1: bfd.AuthSeqKnown is cleared when the
            # detection timer expires so a recovered peer's sequence
            # numbers are accepted afresh.
            s._last_rx_seq = None
        if self.notif_cb is not None:
            # Reference holo-bfd northbound/notification.rs:18-33: the
            # notification module matches the session key flavor.
            body = {
                "local-discr": s.local_discr,
                "remote-discr": s.remote_discr,
                "new-state": {
                    BfdState.UP: "up",
                    BfdState.DOWN: "down",
                    BfdState.INIT: "init",
                    BfdState.ADMIN_DOWN: "admin-down",
                }[new],
            }
            if s.key and s.key[0] == "mh":
                body["source-addr"] = str(s.key[1])
                body["dest-addr"] = str(s.key[2])
                self.notif_cb(
                    {"ietf-bfd-multihop:multihop-notification": body}
                )
            else:
                body["interface"] = s.key[0]
                body["dest-addr"] = str(s.key[1])
                self.notif_cb(
                    {"ietf-bfd-ip-sh:singlehop-notification": body}
                )
        if self.ibus is not None:
            label = {
                BfdState.UP: "up",
                BfdState.DOWN: "down",
                BfdState.INIT: "init",
                BfdState.ADMIN_DOWN: "admin-down",
            }[new]
            # Causal origin stamp: a BFD state change IS a topology
            # event — the id rides the publish into the RIB's O(1)
            # local-repair flip and any subscribed protocol's SPF.
            from holo_tpu.telemetry import convergence

            eid = convergence.begin(
                convergence.TRIGGER_BFD, state=label, key=str(s.key)
            )
            with convergence.activation(eid):
                self.ibus.publish(TOPIC_BFD_STATE, BfdStateUpd(s.key, label))
        # Faster tx once the session leaves Down.
        self._arm_tx(s, self._tx_interval(s))

    def _tx_interval(self, s: Session) -> float:
        if s.state == BfdState.UP:
            return max(s.desired_min_tx, s.remote_min_rx) / 1e6
        return self.slow_tx

    def _detect_time(self, s: Session) -> float:
        """RFC 5880 §6.8.4: remote detect-mult × max(our RequiredMinRx,
        remote DesiredMinTx) — the peer may legitimately transmit slower
        than we are willing to receive."""
        return (
            s.remote_detect_mult
            * max(s.required_min_rx, s.remote_min_tx, 1)
            / 1e6
        )

    def _arm_tx(self, s: Session, delay: float) -> None:
        t = getattr(s, "_tx_timer", None)
        if t is None:
            t = self.loop.timer(self.name, lambda key=s.key: TxTimerMsg(key))
            s._tx_timer = t
        t.start(delay)

    def _arm_detect(self, s: Session) -> None:
        t = getattr(s, "_detect_timer", None)
        if t is None:
            t = self.loop.timer(self.name, lambda key=s.key: DetectTimerMsg(key))
            s._detect_timer = t
        t.start(self._detect_time(s))

    def _send(self, s: Session) -> None:
        auth = None
        if s.auth_type is not None:
            if s.auth_type != BfdAuthType.SIMPLE_PASSWORD:
                # Meticulous types increment on every packet, plain
                # keyed types occasionally (we bump per packet too —
                # permitted by §6.7.3).
                s._tx_seq = (s._tx_seq + 1) & 0xFFFFFFFF
            auth = BfdAuth(
                s.auth_type, key_id=s.auth_key_id, seq=s._tx_seq
            )
        pkt = BfdPacket(
            state=s.state,
            diag=s.diag,
            detect_mult=s.detect_mult,
            my_discr=s.local_discr,
            your_discr=s.remote_discr,
            desired_min_tx=s.desired_min_tx,
            required_min_rx=s.required_min_rx,
            required_min_echo_rx=s.required_min_echo_rx,
            auth=auth,
        )
        wire = pkt.encode(auth_key=s.auth_key or None)
        _BFD_PACKETS.labels(dir="tx").inc()
        if s.is_multihop():
            _, src, dst = s.key
            self.netio.send(None, src, dst, wire)
        else:
            ifname, peer = s.key
            self.netio.send(
                ifname, getattr(s, "local", None), peer, wire
            )

    # -- echo function (RFC 5880 §6.4)

    def _send_echo(self, s: Session) -> None:
        local = getattr(s, "local", None)
        tag = local.packed if local is not None else b"\x00" * 4
        data = ECHO_MAGIC + s.local_discr.to_bytes(4, "big") + tag
        ifname, peer = s.key
        self.netio.send(ifname, local, peer, data)
        self._arm_echo_detect(s)

    def _rx_echo(self, msg: NetRxPacket) -> None:
        body = msg.data[len(ECHO_MAGIC) :]
        discr = int.from_bytes(body[:4], "big")
        tag = body[4:8]
        mine = next(
            (
                s
                for s in self.sessions.values()
                if s.local_discr == discr
                and s.echo_interval is not None
                and getattr(s, "local", None) is not None
                and s.local.packed == tag
            ),
            None,
        )
        if mine is not None:
            # Our echo came back: the forwarding path is alive.
            t = getattr(mine, "_echo_detect_timer", None)
            if t is not None:
                t.cancel()
            return
        # Not ours: play the forwarding plane and loop it to the sender
        # (real kernels U-turn BFD echo at the IP layer).
        self.netio.send(msg.ifname, msg.dst, msg.src, msg.data)

    def _arm_echo_tx(self, s: Session) -> None:
        t = getattr(s, "_echo_tx_timer", None)
        if t is None:
            t = self.loop.timer(
                self.name, lambda key=s.key: EchoTxTimerMsg(key)
            )
            s._echo_tx_timer = t
        t.start(s.echo_interval)

    def _arm_echo_detect(self, s: Session) -> None:
        t = getattr(s, "_echo_detect_timer", None)
        if t is None:
            t = self.loop.timer(
                self.name, lambda key=s.key: EchoDetectTimerMsg(key)
            )
            s._echo_detect_timer = t
        # Only arm when idle: each returning echo cancels the timer, and
        # the next send opens a fresh window.  Re-arming on every send
        # would push the deadline forever while echoes are lost.
        if not t.armed:
            t.start(s.echo_interval * s.detect_mult)

"""BFD (RFC 5880/5881): asynchronous-mode session FSM.

Reference: holo-bfd (SURVEY.md §2.3) — session table keyed by peer,
clients (OSPF/IS-IS/BGP) register over the ibus and receive state-change
notifications to kill adjacencies fast (§3.5 of SURVEY.md).

Wire format (RFC 5880 §4.1) is implemented for real interop; the fabric
delivers packets like any other protocol.  Echo mode and authentication
are later-round items.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from ipaddress import IPv4Address

from holo_tpu.utils.bytesbuf import DecodeError, Reader, Writer
from holo_tpu.utils.ibus import TOPIC_BFD_STATE, BfdSessionReg, BfdSessionUnreg, BfdStateUpd, Ibus, IbusMsg
from holo_tpu.utils.netio import NetIo, NetRxPacket
from holo_tpu.utils.runtime import Actor


class BfdState(enum.IntEnum):
    ADMIN_DOWN = 0
    DOWN = 1
    INIT = 2
    UP = 3


class BfdDiag(enum.IntEnum):
    NONE = 0
    TIME_EXPIRED = 1
    ECHO_FAILED = 2
    NEIGHBOR_DOWN = 3
    FWD_PLANE_RESET = 4
    PATH_DOWN = 5
    CONCAT_DOWN = 6
    ADMIN_DOWN = 7
    REVERSE_CONCAT_DOWN = 8


@dataclass
class BfdPacket:
    """RFC 5880 §4.1 mandatory section."""

    state: BfdState
    diag: BfdDiag = BfdDiag.NONE
    poll: bool = False
    final: bool = False
    detect_mult: int = 3
    my_discr: int = 0
    your_discr: int = 0
    desired_min_tx: int = 1_000_000  # microseconds
    required_min_rx: int = 1_000_000
    required_min_echo_rx: int = 0

    def encode(self) -> bytes:
        w = Writer()
        w.u8((1 << 5) | int(self.diag))  # version 1
        flags = (int(self.state) << 6) | (0x20 if self.poll else 0) | (
            0x10 if self.final else 0
        )
        w.u8(flags)
        w.u8(self.detect_mult)
        w.u8(24)  # length
        w.u32(self.my_discr).u32(self.your_discr)
        w.u32(self.desired_min_tx).u32(self.required_min_rx)
        w.u32(self.required_min_echo_rx)
        return w.finish()

    @classmethod
    def decode(cls, data: bytes) -> "BfdPacket":
        r = Reader(data)
        vd = r.u8()
        if vd >> 5 != 1:
            raise DecodeError("bad BFD version")
        flags = r.u8()
        mult = r.u8()
        length = r.u8()
        if length < 24 or length > len(data):
            raise DecodeError("bad BFD length")
        my, your = r.u32(), r.u32()
        tx, rx, erx = r.u32(), r.u32(), r.u32()
        if mult == 0 or my == 0:
            raise DecodeError("invalid BFD fields")
        try:
            diag = BfdDiag(vd & 0x1F)
        except ValueError:
            diag = BfdDiag.NONE  # reserved diag codes: accept, ignore diag
        return cls(
            state=BfdState((flags >> 6) & 0x3),
            diag=diag,
            poll=bool(flags & 0x20),
            final=bool(flags & 0x10),
            detect_mult=mult,
            my_discr=my,
            your_discr=your,
            desired_min_tx=tx,
            required_min_rx=rx,
            required_min_echo_rx=erx,
        )


@dataclass
class TxTimerMsg:
    key: tuple


@dataclass
class DetectTimerMsg:
    key: tuple


@dataclass
class Session:
    key: tuple  # (ifname, peer_addr)
    local_discr: int
    state: BfdState = BfdState.DOWN
    remote_discr: int = 0
    remote_min_rx: int = 1_000_000
    remote_min_tx: int = 1_000_000
    remote_detect_mult: int = 3
    remote_state: BfdState = BfdState.DOWN
    desired_min_tx: int = 1_000_000
    required_min_rx: int = 1_000_000
    detect_mult: int = 3
    diag: BfdDiag = BfdDiag.NONE
    clients: set = field(default_factory=set)


class BfdInstance(Actor):
    """BFD master actor: one session table for all interfaces/peers.

    Spawned at daemon startup inside the routing provider, like the
    reference (holo-routing/src/lib.rs:261-281).
    """

    name = "bfd"

    def __init__(self, netio: NetIo, ibus: Ibus | None = None, slow_tx: float = 1.0):
        self.netio = netio
        self.ibus = ibus
        self.sessions: dict[tuple, Session] = {}
        self._next_discr = 1
        self.slow_tx = slow_tx  # tx interval until session is UP (seconds)

    # -- lifecycle

    def session_key(self, ifname: str, peer: IPv4Address) -> tuple:
        return (ifname, peer)

    def register(self, key: tuple, client: str, local: IPv4Address) -> Session:
        s = self.sessions.get(key)
        if s is None:
            s = Session(key=key, local_discr=self._next_discr)
            self._next_discr += 1
            s.local = local
            self.sessions[key] = s
            self._arm_tx(s, self.slow_tx)
        elif local is not None:
            s.local = local
        s.clients.add(client)
        return s

    def unregister(self, key: tuple, client: str) -> None:
        s = self.sessions.get(key)
        if s is None:
            return
        s.clients.discard(client)
        if not s.clients:
            for attr in ("_tx_timer", "_detect_timer"):
                t = getattr(s, attr, None)
                if t is not None:
                    t.cancel()
            del self.sessions[key]

    # -- actor

    def handle(self, msg):
        if isinstance(msg, NetRxPacket):
            self._rx(msg)
        elif isinstance(msg, TxTimerMsg):
            s = self.sessions.get(msg.key)
            if s is not None:
                self._send(s)
                self._arm_tx(s, self._tx_interval(s))
        elif isinstance(msg, DetectTimerMsg):
            s = self.sessions.get(msg.key)
            if s is not None and s.state in (BfdState.INIT, BfdState.UP):
                self._transition(s, BfdState.DOWN, BfdDiag.TIME_EXPIRED)
        elif isinstance(msg, IbusMsg):
            p = msg.payload
            if isinstance(p, BfdSessionReg):
                s = self.register(p.key, msg.sender, p.local)
                # Honor the client's requested timing parameters (take the
                # fastest/safest across clients).
                s.required_min_rx = min(s.required_min_rx, p.min_rx)
                s.desired_min_tx = min(s.desired_min_tx, p.min_tx)
                s.detect_mult = p.multiplier
            elif isinstance(p, BfdSessionUnreg):
                self.unregister(p.key, msg.sender)

    # -- FSM (RFC 5880 §6.8.6)

    def _rx(self, msg: NetRxPacket) -> None:
        try:
            pkt = BfdPacket.decode(msg.data)
        except DecodeError:
            return
        key = self.session_key(msg.ifname, msg.src)
        s = self.sessions.get(key)
        if s is None:
            return
        if pkt.your_discr != 0 and pkt.your_discr != s.local_discr:
            return
        s.remote_discr = pkt.my_discr
        s.remote_state = pkt.state
        s.remote_min_rx = pkt.required_min_rx
        s.remote_min_tx = pkt.desired_min_tx
        s.remote_detect_mult = pkt.detect_mult

        if pkt.state == BfdState.ADMIN_DOWN:
            if s.state in (BfdState.INIT, BfdState.UP):
                self._transition(s, BfdState.DOWN, BfdDiag.NEIGHBOR_DOWN)
        elif s.state == BfdState.DOWN:
            if pkt.state == BfdState.DOWN:
                self._transition(s, BfdState.INIT)
            elif pkt.state == BfdState.INIT:
                self._transition(s, BfdState.UP)
        elif s.state == BfdState.INIT:
            if pkt.state in (BfdState.INIT, BfdState.UP):
                self._transition(s, BfdState.UP)
        elif s.state == BfdState.UP:
            if pkt.state == BfdState.DOWN:
                self._transition(s, BfdState.DOWN, BfdDiag.NEIGHBOR_DOWN)
        self._arm_detect(s)

    def _transition(self, s: Session, new: BfdState, diag: BfdDiag = BfdDiag.NONE) -> None:
        if s.state == new:
            return
        s.state = new
        s.diag = diag
        if self.ibus is not None:
            label = {
                BfdState.UP: "up",
                BfdState.DOWN: "down",
                BfdState.INIT: "init",
                BfdState.ADMIN_DOWN: "admin-down",
            }[new]
            self.ibus.publish(TOPIC_BFD_STATE, BfdStateUpd(s.key, label))
        # Faster tx once the session leaves Down.
        self._arm_tx(s, self._tx_interval(s))

    def _tx_interval(self, s: Session) -> float:
        if s.state == BfdState.UP:
            return max(s.desired_min_tx, s.remote_min_rx) / 1e6
        return self.slow_tx

    def _detect_time(self, s: Session) -> float:
        """RFC 5880 §6.8.4: remote detect-mult × max(our RequiredMinRx,
        remote DesiredMinTx) — the peer may legitimately transmit slower
        than we are willing to receive."""
        return (
            s.remote_detect_mult
            * max(s.required_min_rx, s.remote_min_tx, 1)
            / 1e6
        )

    def _arm_tx(self, s: Session, delay: float) -> None:
        t = getattr(s, "_tx_timer", None)
        if t is None:
            t = self.loop.timer(self.name, lambda key=s.key: TxTimerMsg(key))
            s._tx_timer = t
        t.start(delay)

    def _arm_detect(self, s: Session) -> None:
        t = getattr(s, "_detect_timer", None)
        if t is None:
            t = self.loop.timer(self.name, lambda key=s.key: DetectTimerMsg(key))
            s._detect_timer = t
        t.start(self._detect_time(s))

    def _send(self, s: Session) -> None:
        pkt = BfdPacket(
            state=s.state,
            diag=s.diag,
            detect_mult=s.detect_mult,
            my_discr=s.local_discr,
            your_discr=s.remote_discr,
            desired_min_tx=s.desired_min_tx,
            required_min_rx=s.required_min_rx,
        )
        ifname, peer = s.key
        self.netio.send(ifname, getattr(s, "local", None), peer, pkt.encode())
